"""Benchmark suite: flagship GPT + ResNet-50 + LeNet + PP-YOLOE on the local chip.

Driver contract: prints JSON lines of the form
{"metric", "value", "unit", "vs_baseline", ...extras}.
The flagship GPT line is printed and FLUSHED the moment the GPT bench
finishes, so a driver that kills the suite mid-run still captures the
primary number (round 4's bench exceeded the driver budget and recorded
rc=124 with no output — never again). The final line repeats the primary
metric with all extras merged; both lines are valid driver output.

Budget discipline:
- whole-suite hard wall clock (BENCH_BUDGET_S, default 1140 s)
- per-bench subprocess timeout bounded by remaining budget
- inside each child, the sweep checks the deadline before each batch and
  stops early, so the child always prints what it measured
- one attempt per batch size; no retry sleeps. Errors are carried in the
  "errors" field of the output rather than swallowed.
- a BACKEND PROBE runs first (r04/r05 lesson: every bench timing out at
  its full budget is the dead-accelerator-tunnel hang signature, not slow
  compute — the gpt train bench reported 0.0 two rounds straight): a tiny
  jit in a subprocess must finish inside BENCH_PROBE_S, else children are
  pinned to JAX_PLATFORMS=cpu where the small configs always fit the
  budget. PADDLE_TPU_BENCH_FAST=1 (set automatically when the probe is
  slow) additionally shrinks sweeps/iteration counts in every bench.

vs_baseline: the reference publishes no numbers (BASELINE.md) — 1.0 = recorded
placeholder until an A100 anchor measurement exists.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_T0 = time.monotonic()
_BUDGET_S = float(os.environ.get("BENCH_BUDGET_S", "1140"))
# set by main() when the backend probe fails: benches that then produce no
# result report status "tpu_unreachable" instead of "bench_failed"
_TPU_UNREACHABLE = False
# the backend-probe outcome, stamped onto EVERY emitted JSON line so a
# silent cpu/fast-tier fallback is visible in the BENCH_*.json trajectory
# itself, not only in stderr (the r04/r05 lesson: two rounds recorded 0
# tok/s before anyone saw the platform-init hang)
_PROBE = {"backend": None, "fell_back": False, "reason": None}


def _status(result, errors):
    """Machine-readable per-line status (VERDICT item 10: a failed round
    must be distinguishable from a zero-throughput framework):
    ``ok`` — result landed, no errors; ``partial`` — result landed but
    something (deadline cut, sub-bench failure, probe fallback) is in the
    errors field; ``tpu_unreachable`` — no result AND the accelerator
    probe failed with only environment-shaped errors (timeouts/skips)
    since; ``bench_failed`` — no result for any other reason, including a
    real exception AFTER the CPU fallback kicked in (that is a code bug,
    not infra — it must not hide behind the infra label)."""
    if result is None:
        env_shaped = all(
            "timed out" in e or "timeout" in e or "skipped" in e
            or e.startswith("probe:")
            for e in errors
        ) if errors else True
        return ("tpu_unreachable" if _TPU_UNREACHABLE and env_shaped
                else "bench_failed")
    return "partial" if errors else "ok"


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T0)


def _fast():
    """FAST tier: smaller sweeps/iteration counts everywhere. Set
    explicitly (PADDLE_TPU_BENCH_FAST=1) or auto-enabled by the probe."""
    return os.environ.get("PADDLE_TPU_BENCH_FAST", "") not in ("", "0")


def _probe_backend(timeout_s=None):
    """Prove the default backend can init + compile + run ONE tiny program
    before committing the budget to it. Returns an error note (and pins
    children to CPU / FAST tier via the environment) when it can't."""
    import subprocess

    timeout_s = float(os.environ.get("BENCH_PROBE_S", "120")
                      if timeout_s is None else timeout_s)
    code = ("import jax, jax.numpy as jnp; "
            "v = jax.jit(lambda x: x + 1)(jnp.zeros(8)).sum(); "
            "print(float(v), jax.default_backend())")
    t0 = time.monotonic()
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        ok = proc.returncode == 0
    except Exception:  # noqa: BLE001 — timeout or spawn failure
        proc, ok = None, False
    dt = time.monotonic() - t0
    if not ok:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.setdefault("PADDLE_TPU_BENCH_FAST", "1")
        reason = (f"backend probe failed/hung after {dt:.0f}s; "
                  "forcing JAX_PLATFORMS=cpu + FAST tier for all benches")
        _PROBE.update(backend="cpu", fell_back=True, reason=reason)
        return reason
    # "1.0 tpu" -> the backend the children will actually run on
    _PROBE["backend"] = (proc.stdout.split() or ["?"])[-1]
    _log(f"backend probe ok in {dt:.0f}s: {proc.stdout.strip()}")
    if dt > 60.0:
        os.environ.setdefault("PADDLE_TPU_BENCH_FAST", "1")
        reason = f"slow backend probe ({dt:.0f}s); FAST tier enabled"
        _PROBE["reason"] = reason
        return reason
    return None


# MFU accounting lives in paddle_tpu.profiler.flops now (lifted from here
# in the observability PR so any run can compute it, not just benches);
# these thin wrappers keep the bench call sites and import laziness — the
# parent process must never import jax/paddle_tpu before the probe runs.

def _peak_flops(device) -> float:
    from paddle_tpu.profiler.flops import peak_flops

    return peak_flops(device)


def _train_flops_per_token(cfg) -> float:
    from paddle_tpu.profiler.flops import gpt_train_flops_per_token

    return gpt_train_flops_per_token(cfg)


def _log(msg):
    print(f"[bench +{time.monotonic() - _T0:.0f}s] {msg}", file=sys.stderr, flush=True)


def _sweep(run, batches, iters, errors, deadline_s, name=""):
    """Run `run(batch, iters)` once per batch. OOM short-circuits (a larger
    batch will OOM too); the deadline stops the sweep so the child always
    gets to print. All failures land in `errors` — nothing is retried or
    silently dropped (a batch that fails shows up in the output)."""
    sweep = {}
    for b in batches:
        if time.monotonic() > deadline_s:
            errors.append(f"{name}: deadline before batch={b}; partial sweep")
            break
        t0 = time.monotonic()
        try:
            sweep[b] = run(b, iters)
            _log(f"{name} batch={b}: {sweep[b]:.1f} in {time.monotonic() - t0:.0f}s")
        except Exception as e:  # noqa: BLE001 — a red bench gate helps no one
            msg = f"{type(e).__name__}: {e}"
            errors.append(f"{name} batch={b}: {msg[:300]}")
            _log(f"{name} batch={b}: FAILED after {time.monotonic() - t0:.0f}s")
            if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg:
                break
    return sweep


# ---------------------------------------------------------------------------
# GPT (primary metric)
# ---------------------------------------------------------------------------

def bench_gpt(on_tpu, errors, deadline_s):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.models.gpt import GPT, GPTConfig

    paddle.seed(0)
    seq = 1024 if on_tpu else 128
    if on_tpu:
        # num_heads=8 -> head_dim 128: fills the MXU's 128 contraction lanes
        # in the flash kernels (head_dim 64 runs them at half utilization —
        # measured +20% step throughput at identical model FLOPs)
        cfg = GPTConfig(
            vocab_size=32768, hidden_size=1024, num_layers=12, num_heads=8,
            max_seq_len=seq, attn_impl="flash", dtype="bfloat16",
        )
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=seq, attn_impl="xla")
    model = GPT(cfg)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    params, buffers = state_dict_arrays(model)
    opt_state = opt.init_state_arrays(params)

    def step(params, buffers, opt_state, lr, key, ids, labels):
        def loss_fn(p):
            # fused chunked CE head: loss computed without materializing
            # [b, s, vocab] logits (models/gpt.py forward labels= path)
            loss, new_buf = functional_call(
                model, p, buffers, args=(ids,), kwargs={"labels": labels},
                rng_key=key, training=True,
            )
            return loss, new_buf

        (loss, new_buf), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_arrays(params, grads, opt_state, lr)
        return loss, new_params, new_buf, new_opt

    jstep = jax.jit(step, donate_argnums=(0, 2))
    lr = jnp.asarray(1e-4, jnp.float32)
    rs = np.random.RandomState(0)

    # host snapshot: donation invalidates device buffers, so a fresh batch
    # size must re-materialize state from host copies
    snap = jax.tree_util.tree_map(np.asarray, (params, buffers, opt_state))

    def run(batch, iters):
        params, buffers, opt_state = jax.tree_util.tree_map(jnp.asarray, snap)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
        labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
        loss, params, buffers, opt_state = jstep(
            params, buffers, opt_state, lr, rng.next_key(), ids, labels
        )
        float(np.asarray(loss))  # compile + sync
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, params, buffers, opt_state = jstep(
                params, buffers, opt_state, lr, rng.next_key(), ids, labels
            )
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        return batch * seq * iters / dt

    # r4 sweep: batch 16 won (98.5k), 8 close, 32 regressed, 64 OOM'd.
    # Known-best FIRST: a deadline-cut sweep still reports the best config.
    # FAST tier: the known-best batch only, fewer timed steps — a slow
    # tunnel still lands a nonzero primary metric inside the budget.
    if _fast():
        batches = (16,) if on_tpu else (2,)
        iters = 8 if on_tpu else 2
    else:
        batches = (16, 8, 32) if on_tpu else (2,)
        iters = 20 if on_tpu else 3
    # per-chip optimizer-state bytes of the state the sweep runs on —
    # measured BEFORE the sweep donates it (the explicit-ZeRO train wave
    # reports the dp-sharded counterpart; the trajectory compares them)
    from paddle_tpu.parallel.spmd import per_chip_opt_state_bytes

    opt_bytes = per_chip_opt_state_bytes(opt_state)
    sweep = _sweep(run, batches, iters, errors, deadline_s, name="gpt")
    if not sweep:
        return None
    best_batch = max(sweep, key=sweep.get)
    tokens_per_sec = sweep[best_batch]
    flops_per_token = _train_flops_per_token(cfg)
    peak = _peak_flops(jax.devices()[0])
    return {
        "value": round(tokens_per_sec, 1),
        "mfu": round(tokens_per_sec * flops_per_token / peak, 4),
        "batch": best_batch,
        "sweep": {str(k): round(v, 1) for k, v in sweep.items()},
        # train-side drift fields (PR 19): the single-chip flagship runs
        # the unsharded step — zero_stage 0, no quantized grads, share
        # measured from a short xplane capture (~0 with no collectives);
        # bench_gpt_train_zero carries the dp-sharded numbers
        "zero_stage": 0,
        "quant_grads": False,
        "per_chip_opt_state_bytes": int(opt_bytes),
        "collective_time_share": _capture_collective_share(
            lambda: run(best_batch, 2), errors, deadline_s, name="gpt"),
    }


def _capture_collective_share(run_steps, errors, deadline_s, name=""):
    """Fraction of device busy time spent in collective ops over an
    xplane capture of `run_steps()` — `profiler.flops.collective_time`
    aggregated across device planes (EQuARX's motivating measurement:
    is the step compute-bound or interconnect-bound). None when the
    capture can't run (deadline, profiler unavailable) — recorded in
    `errors`, never fatal to the bench that asked."""
    import shutil
    import tempfile

    if time.monotonic() > deadline_s:
        errors.append(f"{name}: deadline before collective_time capture")
        return None
    try:
        import jax

        from paddle_tpu.profiler.flops import collective_time

        td = tempfile.mkdtemp(prefix="bench_xplane_")
        try:
            with jax.profiler.trace(td):
                run_steps()
            planes = collective_time(td)
            coll = sum(p["collective_ms"] for p in planes.values())
            total = sum(p["total_ms"] for p in planes.values())
            return round(coll / total, 4) if total else 0.0
        finally:
            shutil.rmtree(td, ignore_errors=True)
    except Exception as e:  # noqa: BLE001 — attribution is best-effort
        errors.append(f"{name}: collective_time capture: "
                      f"{type(e).__name__}: {str(e)[:200]}")
        return None


# ---------------------------------------------------------------------------
# GPT explicit-ZeRO train wave (parallel/spmd.py explicit weight update)
# ---------------------------------------------------------------------------

def bench_gpt_train_zero(on_tpu, errors, deadline_s):
    """Explicit ZeRO weight-update train wave on the 8-fake-device CPU
    mesh: the SAME dp=4 batch trained through stage 0 (GSPMD reference),
    stage 2 (explicit reduce-scatter + shard-local update + gather of
    updated shards, arXiv:2004.13336), and stage 2 with int8 quantized
    gradient reduce-scatter (EQuARX wire format). ALWAYS runs on the fake
    CPU host platform, even with a TPU reachable — like the multichip
    serve wave it certifies the sharded program's correctness, layout,
    and collective shape, not accelerator speed. One JSON line reports
    per-stage tok/s, `per_chip_opt_state_bytes` (the ~dp-fold drop
    IR004 locks), lowered collective counts (the train-side sibling of
    serving's `collectives` object — IR001 drift visible in the BENCH
    trajectory itself), `collective_time_share` from an xplane capture
    of the stage-2 step, a `loss_parity: ok|mismatch` verdict (stage-2
    losses must track stage 0 within f32 reduction-order noise — the
    BIT-identity gate lives in tier-1 on the deterministic tiny config,
    tests/test_zero_explicit.py; at this size 1-ulp grad-reduction
    differences surface after the first update), and the int8 drift."""
    del on_tpu  # forced to the fake CPU mesh by _child
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn
    from paddle_tpu.parallel.spmd import (make_sharded_train_step,
                                          per_chip_opt_state_bytes)

    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                    num_heads=8, max_seq_len=128, attn_impl="xla")
    dp, batch, seq = 4, 8, 128
    mesh = init_mesh({"dp": dp})
    rs = np.random.RandomState(0)
    ids = rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    labels = rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32)
    iters = 4 if _fast() else 10

    def wave(zero_stage, quant=False, capture=False):
        paddle.seed(0)
        model = GPT(cfg)
        opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                     parameters=model.parameters())
        step = make_sharded_train_step(model, gpt_loss_fn, opt, mesh,
                                       zero_stage=zero_stage,
                                       quant_grads=quant)
        params, buffers, opt_state = step.init_state()
        opt_bytes = per_chip_opt_state_bytes(opt_state)
        b = step.shard_batch(ids, labels)
        lr, key = jnp.float32(1e-4), jax.random.PRNGKey(0)
        loss, params, buffers, opt_state = step(
            params, buffers, opt_state, lr, key, *b)      # compile
        losses = [float(np.asarray(loss))]
        t0 = time.perf_counter()
        for _ in range(iters):
            # the per-step host sync is deliberate: the loss trajectory
            # IS the parity verdict this wave exists to record
            loss, params, buffers, opt_state = step(
                params, buffers, opt_state, lr, key, *b)
            losses.append(float(np.asarray(loss)))
        dt = time.perf_counter() - t0
        out = {
            "tok_s": round(batch * seq * iters / dt, 1) if dt else 0.0,
            "zero_stage": zero_stage,
            "quant_grads": quant,
            "explicit_update": step.explicit_update,
            "per_chip_opt_state_bytes": int(opt_bytes),
        }
        if time.monotonic() < deadline_s:
            # lowered collective counts of THE program just measured —
            # the hlolint train/* artifacts lock these tier-1; the bench
            # line records them so the trajectory sees drift too
            from paddle_tpu.analysis.ir import (collective_counts,
                                                parse_hlo_ops)

            lowered, _ = step.lower_step(
                *[jax.ShapeDtypeStruct(x.shape, x.dtype) for x in b])
            counts = collective_counts(
                parse_hlo_ops(lowered.compile().as_text()))
            out["collectives"] = {k: n for k, n in counts.items() if n}
        if capture:
            def more_steps(params=params, buffers=buffers,
                           opt_state=opt_state):
                lo, p, bu, o = step(params, buffers, opt_state, lr, key, *b)
                lo, p, bu, o = step(p, bu, o, lr, key, *b)
                float(np.asarray(lo))
            out["collective_time_share"] = _capture_collective_share(
                more_steps, errors, deadline_s, name="gpt_train_zero")
        return out, losses

    zs0, l0 = wave(0)
    if time.monotonic() > deadline_s:
        errors.append("gpt_train_zero: deadline before stage-2 wave")
        return None
    zs2, l2 = wave(2, capture=True)
    drift = max(abs(a - b) for a, b in zip(l2, l0))
    parity = "ok" if drift < 1e-4 else "mismatch"
    if parity != "ok":
        errors.append("gpt_train_zero: stage-2 losses diverged from the "
                      f"stage-0 reference beyond reduction-order noise "
                      f"(drift {drift}): {l2} vs {l0}")
    out = {
        "value": zs2["tok_s"],
        "dp": dp, "batch": batch, "seq": seq, "iters": iters,
        "n_devices": len(jax.devices()),
        "zs0": zs0, "zs2": zs2,
        "loss_parity": parity,
        "loss_drift": round(drift, 7),
        "opt_state_shrink": round(
            zs0["per_chip_opt_state_bytes"]
            / zs2["per_chip_opt_state_bytes"], 2)
        if zs2["per_chip_opt_state_bytes"] else 0.0,
        # the primary fields mirror the measured stage-2 config
        "zero_stage": 2,
        "quant_grads": False,
        "per_chip_opt_state_bytes": zs2["per_chip_opt_state_bytes"],
        "collective_time_share": zs2.get("collective_time_share"),
    }
    if out["opt_state_shrink"] < dp - 1:
        errors.append(f"gpt_train_zero: opt-state shrink "
                      f"{out['opt_state_shrink']} below ~dp-fold (dp={dp})")
    if time.monotonic() <= deadline_s:
        try:
            q8, lq = wave(2, quant=True)
        except Exception as e:  # noqa: BLE001 — f32 waves already landed
            errors.append(f"gpt_train_zero: int8 wave: "
                          f"{type(e).__name__}: {str(e)[:200]}")
        else:
            q8["int8_loss_drift"] = round(
                max(abs(a - b) for a, b in zip(lq, l0)), 5)
            out["zs2_q8"] = q8
    _log(f"train zero: zs2 {zs2['tok_s']} tok/s parity {parity} "
         f"opt-state shrink {out['opt_state_shrink']}x "
         f"collectives {zs2.get('collectives')}")
    return out


# ---------------------------------------------------------------------------
# GPT serving throughput (paddle_tpu.serving continuous batching)
# ---------------------------------------------------------------------------

def bench_gpt_serve(on_tpu, errors, deadline_s):
    """Continuous-batching decode throughput: overlapping requests with
    mixed prompt lengths through LLMEngine's paged KV cache and chunked
    prefill. Reports generated tokens/sec across the whole serve, TTFT
    percentiles, the mixed/decode step split, decode-step p50/p95 and
    `host_syncs_per_step` (the unified ragged program makes exactly ONE
    device->host transfer per step — this line catches a reintroduced
    sync, not just throughput drift), and the jit trace count — the
    whole serve compiles one program per ragged width bucket (two on
    this spec-off engine), which `jit_traces_measured == 0` makes
    checkable from the BENCH json.

    A second, shared-system-prompt wave measures AUTOMATIC PREFIX CACHING
    (production traffic's dominant shape): identical workloads served with
    caching on vs. off (`PADDLE_TPU_PREFIX_CACHE=0` also disables the
    cached engine), reporting `prefix_cache_hit_rate` and the tokens/sec of
    each — the hot-prefix case must beat the no-cache baseline.

    A third, repetitive-suffix wave measures SPECULATIVE DECODING
    (prompt-lookup drafting + batched verify, serving/spec.py): the same
    workload spec-on vs spec-off, reporting both tok/s plus
    `spec_acceptance_rate` and tokens/step — the repetitive case must beat
    the one-token-per-step baseline.

    A fourth wave measures the INT8 KV ARENA (`kv_dtype="int8"`): the
    same `kv_hbm_bytes` budget spent on int8 vs weight-dtype blocks,
    over capacity for the baseline — reporting blocks bought, preemption
    counts, tok/s, and the greedy parity rate between the two engines.
    The main line also carries `kv_dtype`/`kv_bytes_per_block` so the
    trajectory can see which arena priced the serve."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=32768, hidden_size=1024, num_layers=12, num_heads=8,
            max_seq_len=1024, attn_impl="xla", dtype="bfloat16",
        )
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=256, attn_impl="xla")
    model = GPT(cfg)
    model.to(dtype="bfloat16")
    max_batch = 8 if on_tpu else 4
    # slo=True: the ledger's lifecycle hooks are per-request (never per
    # step/token), so the measured tok/s still reflects the serving hot
    # path — and the line gains tail-latency fields (tpot p50/p95,
    # deadline attainment) so the trajectory catches tail drift too
    engine = LLMEngine(model, block_size=16, max_batch=max_batch, slo=True)
    rs = np.random.RandomState(0)

    # warmup: one multi-chunk request compiles BOTH programs — the mixed
    # prefill+decode step and the pure-decode step (max_new_tokens=2 forces
    # at least one decode step; a 1-token request finishes at its last
    # prefill chunk and never compiles decode)
    lens = (24, 60, 100, 40, 80, 30, 120, 50)[: 2 * max_batch]
    list(engine.generate(
        [rs.randint(0, cfg.vocab_size, (max(lens),))], max_new_tokens=2
    ))
    warm_tokens = engine.metrics.counters["generated_tokens"]
    warm_traces = engine.metrics.counters["jit_traces"]
    warm_syncs = engine.metrics.counters.get("host_syncs", 0)
    warm_steps = (engine.metrics.counters.get("mixed_steps", 0)
                  + engine.metrics.counters.get("decode_steps", 0)
                  + engine.metrics.counters.get("verify_steps", 0))
    # drop warmup step timings (they include the jit traces/compiles) so the
    # reported engine_utilization/TTFT/TPOT describe the measured wave only
    engine.metrics.reset_schedule()
    engine.slo.reset()

    max_new = 64 if on_tpu else 16
    if _fast():
        max_new //= 2
    # a generous accounting deadline (nothing enforces it on the bare
    # engine): attainment on the bench line is 1.0 unless the tail
    # regresses pathologically — exactly the drift alarm we want.
    # NOT the harness `deadline_s` param — that is an absolute monotonic
    # timestamp bounding the whole bench child.
    slo_deadline_s = 120.0
    for ln in lens:
        engine.add_request(
            rs.randint(0, cfg.vocab_size, (ln,)), max_new_tokens=max_new,
            deadline_s=slo_deadline_s,
        )
    t0 = time.perf_counter()
    while engine.has_unfinished():
        if time.monotonic() > deadline_s:
            errors.append("gpt_serve: deadline mid-serve; partial throughput")
            break
        engine.step()
    dt = time.perf_counter() - t0
    generated = engine.metrics.counters["generated_tokens"] - warm_tokens
    if not generated:
        return None
    # with PADDLE_TPU_TRACE set the engine recorded a lifecycle/step trace
    # of the whole measured wave — dump it Perfetto-loadable next to the
    # BENCH json (the per-phase step breakdown perf PRs report against)
    trace_info = {}
    if engine.tracer is not None:
        trace_path = os.environ.get("PADDLE_TPU_TRACE_PATH",
                                    "bench_serve_trace.json")
        try:
            trace_info = {
                "trace_path": trace_path,
                "trace_events": engine.tracer.dump(trace_path),
            }
        except OSError as e:
            errors.append(f"gpt_serve: trace dump failed: {e}")
    shared = _serve_shared_prefix(model, cfg, max_batch, rs, errors,
                                  deadline_s, on_tpu)
    spec = _serve_spec_wave(model, cfg, max_batch, rs, errors, deadline_s,
                            on_tpu)
    int8cmp = _serve_int8_overcap(model, cfg, rs, errors, deadline_s)
    view = engine.metrics.schedule_view()
    sched = view.get("serving-engine", {})
    lat = engine.metrics.latency_summary()
    ttft = lat.get("ttft", {})
    counters = engine.metrics.counters
    slo_total = engine.slo.rollup()["total"]
    tpot = slo_total["tpot_ms"]
    steps = (counters.get("mixed_steps", 0) + counters.get("decode_steps", 0)
             + counters.get("verify_steps", 0) - warm_steps)
    syncs = counters.get("host_syncs", 0) - warm_syncs
    dec = lat.get("decode_step", {})
    return {
        "value": round(generated / dt, 1),
        "requests": len(lens),
        "max_batch": max_batch,
        "max_new_tokens": max_new,
        "prefill_chunk": engine.prefill_chunk,
        "kv_dtype": engine.pool_stats()["kv_dtype"],
        "kv_bytes_per_block": engine.pool_stats()["kv_bytes_per_block"],
        "ttft_p50_ms": round(ttft.get("p50_ms", 0.0), 2),
        "ttft_p95_ms": round(ttft.get("p95_ms", 0.0), 2),
        "tpot_p50_ms": round(tpot["p50"] or 0.0, 3),
        "tpot_p95_ms": round(tpot["p95"] or 0.0, 3),
        "deadline_attainment": slo_total["deadline"]["attainment"],
        "mixed_steps": int(counters["mixed_steps"]),
        "decode_steps": int(counters["decode_steps"]),
        "mixed_step_mean_ms": round(
            lat.get("mixed_step", {}).get("mean_ms", 0.0), 3),
        "decode_step_mean_ms": round(dec.get("mean_ms", 0.0), 3),
        "decode_step_p50_ms": round(dec.get("p50_ms", 0.0), 3),
        "decode_step_p95_ms": round(dec.get("p95_ms", 0.0), 3),
        # exactly ONE device->host transfer per step (trace sync phase);
        # a regression here is a reintroduced per-step host round-trip
        "host_syncs_per_step": round(syncs / steps, 3) if steps else None,
        "preemptions": int(counters["preemptions"]),
        "jit_traces": int(counters["jit_traces"]),
        "jit_traces_measured": int(counters["jit_traces"] - warm_traces),
        "engine_utilization": round(sched.get("utilization", 0.0), 4),
        **trace_info,
        **(shared or {}),
        **(spec or {}),
        **({"int8_overcap": int8cmp} if int8cmp else {}),
    }


def bench_gpt_serve_multichip(on_tpu, errors, deadline_s):
    """Sharded multi-chip serve wave (serving/sharded.py) on the
    8-fake-device CPU mesh: tp=2 and tp=4 tensor-parallel engines serve
    the same mixed wave as a single-chip reference, reporting tok/s per
    degree plus a ``sharded_parity: ok|mismatch`` verdict — greedy sharded
    output must be token-for-token identical to single-chip (the parity
    guarantee tests/test_serving_sharded.py locks in tier-1). ALWAYS runs
    on the fake CPU host platform, even with a TPU reachable: this wave
    certifies the sharded engine's correctness and topology plumbing, not
    accelerator speed (`_child` forces the platform via
    `_cpu_mesh.force_host_cpu_devices` before any jax backend init, the
    same trick as the MULTICHIP dryrun). A final tp=2 A/B re-serves the
    wave through the int8 KV arena with the EQuARX quantized all-reduce,
    reporting decode-step p50/p95 beside the f32 fields plus a greedy
    parity rate; its collective counts ride the same `collectives` dict
    so the quantized program's shape is trajectory-locked too."""
    del on_tpu  # forced to the fake CPU mesh by _child
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=256, attn_impl="xla")
    model = GPT(cfg)
    model.eval()
    rs = np.random.RandomState(0)
    lens = (24, 60, 100, 40)
    prompts = [rs.randint(0, cfg.vocab_size, (n,)).tolist() for n in lens]
    max_new = 8 if _fast() else 16

    def wave(mesh, **kw):
        eng = LLMEngine(model, block_size=16, max_batch=4, mesh=mesh, **kw)
        # warm: compiles the touched width-bucket programs outside the
        # timing, then reset step timings so decode p50/p95 describe the
        # measured wave only
        eng.generate([prompts[0]], max_new_tokens=2, temperature=0.0)
        eng.metrics.reset_schedule()
        t0_tok = eng.metrics.counters["generated_tokens"]
        t0_syncs = eng.metrics.counters.get("host_syncs", 0)
        t0_steps = sum(eng.metrics.counters.get(k, 0) for k in
                       ("mixed_steps", "decode_steps", "verify_steps"))
        t0 = time.perf_counter()
        outs = eng.generate(prompts, max_new_tokens=max_new,
                            temperature=0.0)
        dt = time.perf_counter() - t0
        toks = eng.metrics.counters["generated_tokens"] - t0_tok
        steps = sum(eng.metrics.counters.get(k, 0) for k in
                    ("mixed_steps", "decode_steps", "verify_steps")) - t0_steps
        syncs = eng.metrics.counters.get("host_syncs", 0) - t0_syncs
        dec = eng.metrics.latency_summary().get("decode_step", {})
        st = eng.pool_stats()
        facts = {
            "decode_step_p50_ms": round(dec.get("p50_ms", 0.0), 3),
            "decode_step_p95_ms": round(dec.get("p95_ms", 0.0), 3),
            "host_syncs_per_step": (round(syncs / steps, 3) if steps
                                    else None),
            "kv_dtype": st["kv_dtype"],
            "kv_bytes_per_block": st["kv_bytes_per_block"],
        }
        return outs, (toks / dt if dt > 0 else 0.0), eng, facts

    def program_collectives(eng):
        """hlolint collective counts per program kind — the bench line
        records them so the trajectory catches collective-count drift
        (an accidental per-layer re-gather), not just tok/s drift.
        Lowering recompiles the programs, so past the deadline the
        counts are skipped rather than overshooting the budget."""
        if time.monotonic() > deadline_s:
            return {}
        from paddle_tpu.analysis.ir import engine_collective_counts

        return {
            kind: {op: n for op, n in counts.items() if n}
            for kind, counts in engine_collective_counts(eng).items()
        }

    # mesh=1 is the EXPLICIT single-chip request: a PADDLE_TPU_TP env
    # left set must not shard the reference and make parity vacuous
    ref_outs, ref_tok_s, ref_eng, ref_facts = wave(1)
    out = {"n_devices": len(jax.devices()),
           "max_new_tokens": max_new,
           "requests": len(lens),
           "tok_s_single": round(ref_tok_s, 1)}
    out.update({f"tp1_{k}": v for k, v in ref_facts.items()})
    engines = {"tp1": ref_eng}
    parity_all = "ok"
    for tp in (2, 4):
        if time.monotonic() > deadline_s:
            errors.append(f"gpt_serve_multichip: deadline before tp={tp}")
            break
        outs, tok_s, eng, facts = wave(tp)
        parity = "ok" if outs == ref_outs else "mismatch"
        if parity != "ok":
            parity_all = "mismatch"
            errors.append(f"gpt_serve_multichip: tp={tp} greedy output "
                          "diverged from single-chip")
        out[f"tp{tp}_tok_s"] = round(tok_s, 1)
        out.update({f"tp{tp}_{k}": v for k, v in facts.items()})
        out[f"tp{tp}_sharded_parity"] = parity
        out[f"tp{tp}_mesh"] = eng.mesh_info()
        engines[f"tp{tp}"] = eng
        _log(f"multichip serve tp={tp}: {tok_s:.1f} tok/s "
             f"sharded_parity: {parity}")
    if "tp2_tok_s" not in out:
        return None
    # sharded-decode step-time A/B: the SAME tp=2 wave through the int8
    # KV arena + EQuARX quantized RowParallel all-reduce. Decode-step
    # p50/p95 land next to the f32 fields above (the ratio is THE metric
    # — a quantized step that got slower means the dequant left VMEM or
    # the quantized collective regressed), plus tok/s, bytes/block, and
    # the greedy per-request parity rate vs the single-chip f32 reference
    # (recorded, not errored: tests/test_int8_kv.py owns the rate gate).
    if time.monotonic() <= deadline_s:
        try:
            outs, tok_s, eng, facts = wave(2, kv_dtype="int8",
                                           quant_allreduce=True)
        except Exception as e:  # noqa: BLE001 — f32 waves already landed
            errors.append(f"gpt_serve_multichip: int8 tp=2 wave: "
                          f"{type(e).__name__}: {str(e)[:200]}")
        else:
            out["tp2_int8_tok_s"] = round(tok_s, 1)
            out.update({f"tp2_int8_{k}": v for k, v in facts.items()})
            out["tp2_int8_parity_rate"] = round(
                sum(a == b for a, b in zip(outs, ref_outs)) / len(ref_outs),
                3) if ref_outs else 0.0
            out["tp2_int8_quant_collectives"] = sorted(
                eng.quant_collectives)
            p50_f32 = out.get("tp2_decode_step_p50_ms") or 0.0
            out["tp2_int8_decode_p50_ratio"] = round(
                facts["decode_step_p50_ms"] / p50_f32, 3) if p50_f32 else None
            engines["tp2_int8"] = eng
            _log(f"multichip serve tp=2 int8: {tok_s:.1f} tok/s "
                 f"decode p50 ratio {out['tp2_int8_decode_p50_ratio']} "
                 f"parity rate {out['tp2_int8_parity_rate']}")
    # collective counts come LAST: the drift metric is order-independent,
    # and its lowering+compiling must never eat deadline budget the tp
    # waves (the primary tok/s + parity measurement) still need
    out["collectives"] = {name: program_collectives(eng)
                          for name, eng in engines.items()}
    _log(f"multichip serve collectives: {out['collectives']}")
    out["value"] = out["tp2_tok_s"]
    out["sharded_parity"] = parity_all
    return out


def bench_gpt_serve_router(on_tpu, errors, deadline_s):
    """Replica-fleet router wave (serving/router.py): a mixed-tenant
    workload — `chat` (shared system prompt, short tails), `batch`
    (unique prompts, long generations), `long` (shared long-context
    prefix) — served through 2 replicas twice: prefix-AFFINITY routing
    vs the no-affinity (least-loaded) router. One JSON line reports
    per-class p95 TTFT, deadline attainment, tokens/s, and the prefix-
    cache hit rate per mode; the affinity router must keep the shared-
    prefix classes' hit rate ABOVE the no-affinity spread (PR 4's cache
    win surviving fan-out — the ROADMAP item-1 acceptance)."""
    import asyncio

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.serving import (AsyncLLMEngine, LLMEngine,
                                    ReplicaRouter, SLOLedger)

    del on_tpu  # a routing-policy wave: CPU-sized model either way
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=256, attn_impl="xla")
    model = GPT(cfg)
    model.eval()
    rs = np.random.RandomState(0)
    gen = 8 if _fast() else 16
    chat_prefix = rs.randint(0, cfg.vocab_size, (64,)).tolist()
    long_prefix = rs.randint(0, cfg.vocab_size, (128,)).tolist()
    reqs = []   # (class, prompt, max_new)
    for _ in range(8):
        reqs.append(("chat", chat_prefix
                     + rs.randint(0, cfg.vocab_size, (8,)).tolist(), gen))
    for _ in range(4):
        reqs.append(("batch",
                     rs.randint(0, cfg.vocab_size, (32,)).tolist(), 2 * gen))
    for _ in range(4):
        reqs.append(("long", long_prefix
                     + rs.randint(0, cfg.vocab_size, (16,)).tolist(), gen))

    async def wave(affinity):
        engines = [AsyncLLMEngine(LLMEngine(
            model, block_size=16, max_batch=4, slo=True)) for _ in range(2)]
        router = ReplicaRouter(engines, affinity=affinity,
                               sweep_interval_s=0.05)
        await router.start()
        # warm each replica directly (compile outside the timing; the
        # warm prompt shares no prefix with the wave)
        for e in engines:
            await e.submit(rs.randint(0, cfg.vocab_size, (8,)).tolist(),
                           max_new_tokens=2, temperature=0.0).collect()
        for e in engines:
            e.engine.metrics.reset_schedule()
            e.engine.slo.reset()
        t0 = time.perf_counter()
        streams = []
        for cls, p, n in reqs:
            streams.append(await router.submit(
                p, max_new_tokens=n, temperature=0.0,
                tenant=cls, deadline_s=120.0))
            # small inter-arrival gap: a zero-gap burst admits every
            # shared-prefix request before the first can publish its
            # blocks, zeroing the hit rate in BOTH modes — real traffic
            # arrives over time
            await asyncio.sleep(0.02)
        outs = [await s.collect() for s in streams]
        dt = time.perf_counter() - t0
        generated = sum(len(t) for t, _ in outs)
        # per-class hit rate: matched prefix tokens / full-block prompt
        # tokens, off each routed request's own record
        per_class = {}
        bs = 16
        for (cls, p, _n), s in zip(reqs, streams):
            hit, lookup = per_class.setdefault(cls, [0, 0])
            per_class[cls] = [hit + (s.req.prefix_hit_tokens or 0),
                              lookup + (len(p) // bs) * bs]
        rates = {cls: round(h / lu, 4) if lu else 0.0
                 for cls, (h, lu) in per_class.items()}
        merged = SLOLedger.merged_rollup(
            [e.engine.slo for e in engines])
        classes = {c["tenant"]: c for c in merged["classes"]}
        out = {
            "tok_s": round(generated / dt, 1),
            "hit_rate_by_class": rates,
            "deadline_attainment": merged["total"]["deadline"]["attainment"],
            "ttft_p95_ms_by_class": {
                cls: classes[cls]["ttft_ms"]["p95"] for cls in rates
                if cls in classes},
            "failed": sum(1 for _, r in outs if r not in ("length", "stop")),
        }
        await router.shutdown()
        return out

    async def both():
        a = await wave(True)
        if time.monotonic() > deadline_s:
            errors.append("gpt_serve_router: deadline before no-affinity "
                          "wave; comparison dropped")
            return a, None
        b = await wave(False)
        return a, b

    aff, noaff = asyncio.run(both())
    out = {"value": aff["tok_s"], "requests": len(reqs), "replicas": 2,
           "affinity": aff}
    if aff["failed"]:
        errors.append(f"gpt_serve_router: {aff['failed']} affinity-wave "
                      "requests failed")
    if noaff is not None:
        out["no_affinity"] = noaff
        # the acceptance signal: shared-prefix classes keep their cache
        # win only when routed by affinity
        for cls in ("chat", "long"):
            a, b = (aff["hit_rate_by_class"].get(cls, 0.0),
                    noaff["hit_rate_by_class"].get(cls, 0.0))
            out[f"{cls}_affinity_hit_gain"] = round(a - b, 4)
            if a <= b:
                errors.append(f"gpt_serve_router: affinity hit rate {a} "
                              f"not above no-affinity {b} on {cls!r}")
        out["affinity_preserves_cache_win"] = all(
            out[f"{c}_affinity_hit_gain"] > 0 for c in ("chat", "long"))
        _log(f"router serve: affinity {aff['tok_s']} tok/s "
             f"(hit {aff['hit_rate_by_class']}) vs no-affinity "
             f"{noaff['tok_s']} tok/s (hit {noaff['hit_rate_by_class']})")
    # host-tier measurements ride the same JSON line: the over-capacity
    # distinct-prefix wave (host hit rate must beat device-only at
    # neutral step latency) and the zero-rewarm rolling drain (post-drain
    # hit rate with vs without migration, zero failed requests)
    oc = _kvtier_overcap_wave(model, cfg, rs, errors, deadline_s)
    if oc:
        out["kvtier_overcap"] = oc
    dr = _kvtier_drain_wave(model, cfg, rs, errors, deadline_s)
    if dr:
        out["kvtier_drain"] = dr
    return out


def _serve_adapter_wave(model, cfg, rs, errors, deadline_s):
    """N-adapter LoRA wave: the same workload round-robined across the
    base model and N loaded adapters on ONE engine, vs the identical
    workload on a plain (lora_slots=0) engine. Reports tok/s for both,
    the overhead ratio, and `jit_traces_measured` — which adapters a
    step mixes must never key a program (the zero-retrace claim)."""
    from paddle_tpu.models import lora as lora_mod
    from paddle_tpu.serving import LLMEngine

    if time.monotonic() > deadline_s:
        errors.append("gpt_serve_fairness: deadline before adapter wave")
        return None
    n_adapters = 4
    gen = 8 if _fast() else 16
    names = [f"adapter-{i}" for i in range(n_adapters)]
    prompts = [rs.randint(0, cfg.vocab_size, (24,)).tolist()
               for _ in range(3 * (n_adapters + 1))]

    def wave(lora_slots):
        eng = LLMEngine(model, block_size=16, max_batch=4, slo=True,
                        lora_slots=lora_slots, lora_rank=8)
        if lora_slots:
            for i, nm in enumerate(names):
                eng.load_adapter(nm, lora_mod.random_adapter(
                    cfg, 8, lora_mod.LORA_TARGETS, seed=i + 1,
                    scale=0.05))
        # warm both programs outside the timing
        list(eng.generate([rs.randint(0, cfg.vocab_size, (8,))],
                          max_new_tokens=2))
        warm_tokens = eng.metrics.counters["generated_tokens"]
        warm_traces = eng.metrics.counters["jit_traces"]
        eng.metrics.reset_schedule()
        # base + every adapter in one continuous batch
        cycle = [None] + (names if lora_slots else [None] * n_adapters)
        for i, p in enumerate(prompts):
            eng.add_request(p, max_new_tokens=gen,
                            adapter=cycle[i % len(cycle)])
        t0 = time.perf_counter()
        while eng.has_unfinished():
            if time.monotonic() > deadline_s:
                errors.append("gpt_serve_fairness: deadline mid-adapter-"
                              "wave; partial throughput")
                break
            eng.step()
        dt = time.perf_counter() - t0
        c = eng.metrics.counters
        return {
            "tok_s": round((c["generated_tokens"] - warm_tokens) / dt, 1),
            "jit_traces_measured": int(c["jit_traces"] - warm_traces),
        }

    lora = wave(n_adapters)
    base = wave(0)
    out = {
        "n_adapters": n_adapters,
        "requests": len(prompts),
        "tok_s": lora["tok_s"],
        "tok_s_base": base["tok_s"],
        # > 1.0 = the per-row gather + two rank-r matmuls cost; the
        # trajectory catches this creeping, not just absolute tok/s
        "overhead_ratio": (round(base["tok_s"] / lora["tok_s"], 3)
                           if lora["tok_s"] else None),
        "jit_traces_measured": lora["jit_traces_measured"],
    }
    if lora["jit_traces_measured"]:
        errors.append(
            f"gpt_serve_fairness: {lora['jit_traces_measured']} retraces "
            "in the measured adapter wave — adapter mixing keyed a program")
    return out


def bench_gpt_serve_fairness(on_tpu, errors, deadline_s):
    """Multi-tenant scheduling wave (serving/policy.py): a mixed-priority
    overload — interactive / standard / batch tenants all submitted up
    front against a max_batch far below the queue depth — served twice:
    policy ON (strict priority + windowed tenant fairness) vs the FCFS
    engine. One JSON line reports per-priority-class p95 TTFT, deadline
    attainment, and finish counts; the policy must pull interactive's
    p95 TTFT BELOW FCFS's interleaved arrival order, and the starvation
    check asserts the lowest class still finished everything (strict
    priority drains the queue, it never parks it). A second sub-wave
    measures N-adapter LoRA serving on the same line (tok/s vs the
    plain engine + the zero-retrace check)."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.serving import LLMEngine

    del on_tpu  # a scheduling-policy wave: CPU-sized model either way
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=256, attn_impl="xla")
    model = GPT(cfg)
    model.eval()
    rs = np.random.RandomState(0)
    gen = 8 if _fast() else 16
    per_class = 2 if _fast() else 4
    # (priority, tenant): one tenant per class; arrival order interleaves
    # the classes so FCFS serves them round-robin while the policy
    # strictly reorders — the measured gap IS the policy
    classes = (("interactive", "chat"), ("standard", "api"),
               ("batch", "nightly"))
    reqs = [(prio, tenant, rs.randint(0, cfg.vocab_size, (24,)).tolist())
            for _ in range(per_class) for prio, tenant in classes]

    def wave(policy):
        eng = LLMEngine(model, block_size=16, max_batch=2, slo=True,
                        policy=policy)
        list(eng.generate([rs.randint(0, cfg.vocab_size, (8,))],
                          max_new_tokens=2))
        warm_tokens = eng.metrics.counters["generated_tokens"]
        eng.metrics.reset_schedule()
        eng.slo.reset()
        # the overload: every request is waiting before the first step,
        # so admission ORDER (not capacity) decides who goes first; the
        # deadline is accounting-generous — attainment is 1.0 unless the
        # tail regresses pathologically, the same drift-alarm discipline
        # as bench_gpt_serve
        for prio, tenant, p in reqs:
            eng.add_request(p, max_new_tokens=gen, priority=prio,
                            tenant=tenant, deadline_s=120.0)
        t0 = time.perf_counter()
        while eng.has_unfinished():
            if time.monotonic() > deadline_s:
                errors.append("gpt_serve_fairness: deadline mid-wave; "
                              "partial throughput")
                break
            eng.step()
        dt = time.perf_counter() - t0
        generated = eng.metrics.counters["generated_tokens"] - warm_tokens
        roll = eng.slo.rollup()
        by_prio = {c["priority"]: c for c in roll["classes"]}
        return {
            "tok_s": round(generated / dt, 1),
            "by_class": {
                prio: {
                    "ttft_p95_ms": by_prio[prio]["ttft_ms"]["p95"],
                    "deadline_attainment":
                        by_prio[prio]["deadline"]["attainment"],
                    "finished": by_prio[prio]["finished"],
                    "output_tokens": by_prio[prio]["output_tokens"],
                } for prio, _ in classes if prio in by_prio},
        }

    pol = wave(True)
    if time.monotonic() > deadline_s:
        errors.append("gpt_serve_fairness: deadline before FCFS wave; "
                      "comparison dropped")
        fcfs = None
    else:
        fcfs = wave(None)
    out = {"value": pol["tok_s"], "requests": len(reqs),
           "per_class_requests": per_class, "policy": pol}
    # the starvation check: strict priority must DRAIN the queue — the
    # lowest class finishes every request and emitted real tokens
    batch = pol["by_class"].get("batch", {})
    out["starvation_free"] = (batch.get("finished") == per_class
                              and batch.get("output_tokens", 0) > 0)
    if not out["starvation_free"]:
        errors.append(f"gpt_serve_fairness: batch class starved: {batch}")
    for prio, _ in classes:
        att = pol["by_class"].get(prio, {}).get("deadline_attainment")
        if att is not None and att < 1.0:
            errors.append(f"gpt_serve_fairness: {prio} attainment {att} "
                          "< 1.0 under a 120s accounting deadline")
    if fcfs is not None:
        out["fcfs"] = fcfs
        a = pol["by_class"].get("interactive", {}).get("ttft_p95_ms")
        b = fcfs["by_class"].get("interactive", {}).get("ttft_p95_ms")
        if a is not None and b is not None:
            out["interactive_ttft_p95_gain_ms"] = round(b - a, 2)
            if a >= b:
                errors.append(
                    f"gpt_serve_fairness: policy interactive p95 TTFT "
                    f"{a}ms not below FCFS {b}ms")
        _log(f"fairness serve: policy {pol['tok_s']} tok/s vs FCFS "
             f"{fcfs['tok_s']} tok/s; interactive p95 TTFT {a} vs {b}")
    adapters = _serve_adapter_wave(model, cfg, rs, errors, deadline_s)
    if adapters:
        out["lora"] = adapters
    return out


def bench_gpt_serve_autoscale(on_tpu, errors, deadline_s):
    """Elastic-fleet closed loop (serving/autoscale.py): one replica born
    from a streamed sharded checkpoint (skeleton model + warmup wave)
    serves a burst that saturates it; the SLO-driven autoscaler spawns a
    second replica through the same factory path. One JSON line reports
    `time_to_first_token_after_spawn_ms` (decision → first served token
    on the new replica — the bounded-birth measurement), the spawn's
    total wall time, and per-fleet deadline attainment BEFORE (1-replica
    wave) vs AFTER (2-replica wave) the scale-up."""
    import asyncio
    import shutil
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.distributed.checkpoint import save_sharded_model
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.nn.layer import skeleton_init
    from paddle_tpu.serving import (AsyncLLMEngine, AutoScaler, LLMEngine,
                                    ReplicaRouter, SLOLedger)

    del on_tpu  # a control-loop wave: CPU-sized model either way
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=256, attn_impl="xla")
    eager = GPT(cfg)
    eager.eval()
    ckpt = tempfile.mkdtemp(prefix="bench_autoscale_ckpt_")
    save_sharded_model(eager, None, ckpt)
    del eager
    with skeleton_init():
        skel = GPT(cfg)   # shapes only — every replica streams from ckpt
    skel.eval()
    rs = np.random.RandomState(0)
    gen = 8 if _fast() else 16
    n_req = 8 if _fast() else 12

    def factory(_i):
        # the birth path under test: streamed load + warmup wave, so the
        # spawned replica's first served request retraces nothing
        return AsyncLLMEngine(LLMEngine(
            skel, block_size=16, max_batch=2, slo=True,
            checkpoint_path=ckpt, warmup=True))

    async def wave(router, tag):
        for r in router.replicas:
            r.engine.engine.slo.reset()
        t0 = time.perf_counter()
        streams = []
        for _ in range(n_req):
            streams.append(await router.submit(
                rs.randint(0, cfg.vocab_size, (24,)).tolist(),
                max_new_tokens=gen, temperature=0.0, tenant="burst",
                deadline_s=120.0))
            await asyncio.sleep(0.005)
        outs = [await s.collect() for s in streams]
        dt = time.perf_counter() - t0
        failed = sum(1 for _, r in outs if r not in ("length", "stop"))
        if failed:
            errors.append(f"gpt_serve_autoscale: {failed} {tag}-wave "
                          "requests failed")
        merged = SLOLedger.merged_rollup(
            [r.engine.engine.slo for r in router.replicas])
        return {"tok_s": round(sum(len(t) for t, _ in outs) / dt, 1),
                "deadline_attainment":
                    merged["total"]["deadline"]["attainment"]}

    async def run():
        router = ReplicaRouter([factory(0)], factory=factory,
                               sweep_interval_s=0.05)
        await router.start()
        # aggressive knobs so a saturating burst trips the loop within
        # the bench budget: queue pressure alone (predicted wait) scales
        # up; down_streak effectively disables scale-down mid-bench
        scaler = AutoScaler(router, factory=factory, min_replicas=1,
                            max_replicas=2, interval_s=0.05,
                            cooldown_s=0.5, up_streak=1, down_streak=10_000,
                            wait_high_s=0.02, wait_low_s=0.0,
                            min_window_events=2)
        await scaler.start()
        before = await wave(router, "before-scale")
        # the burst should have tripped a spawn; give the factory (stream
        # + compile, off-loop) time to land it, nudging with more traffic
        # if the first wave drained before the loop could observe it
        t_wait = time.monotonic()
        while (len(router.replicas) < 2
               and time.monotonic() - t_wait < 120.0
               and time.monotonic() < deadline_s):
            st = await router.submit(
                rs.randint(0, cfg.vocab_size, (24,)).tolist(),
                max_new_tokens=gen, temperature=0.0, tenant="burst")
            await st.collect()
        up = next((d for d in scaler.decisions if d["action"] == "up"),
                  None)
        out = {"before_scale": before, "replicas_after": len(router.replicas)}
        if up is None or len(router.replicas) < 2:
            errors.append("gpt_serve_autoscale: the burst never tripped a "
                          "scale-up")
        else:
            out["scale_up_reason"] = up["reason"]
            out["spawn_total_s"] = up.get("spawn_s")
            ttft = up.get("spawn_ttft_s")
            if ttft is None:
                errors.append("gpt_serve_autoscale: spawn TTFT probe "
                              "failed on the new replica")
            else:
                out["time_to_first_token_after_spawn_ms"] = round(
                    ttft * 1e3, 1)
            out["after_scale"] = await wave(router, "after-scale")
        await scaler.stop()
        await router.shutdown()
        return out

    try:
        out = asyncio.run(run())
    finally:
        shutil.rmtree(ckpt, ignore_errors=True)
    out["value"] = out.get("time_to_first_token_after_spawn_ms", 0.0)
    out["attainment_before_scale"] = (
        out["before_scale"]["deadline_attainment"])
    if "after_scale" in out:
        out["attainment_after_scale"] = (
            out["after_scale"]["deadline_attainment"])
        _log(f"autoscale serve: spawn ttft {out['value']} ms, attainment "
             f"{out['attainment_before_scale']} -> "
             f"{out['attainment_after_scale']}")
    return out


def _hit_rates(engines):
    """(hit_tokens, lookup_tokens, swap_in_hit_tokens) summed across
    engines — prefix_cache_hit_tokens already includes host-tier
    swap-backs (scheduler._swap_in charges them like device hits)."""
    hit = lookup = swap = 0
    for eng in engines:
        c = eng.metrics.counters
        hit += c.get("prefix_cache_hit_tokens", 0)
        lookup += c.get("prefix_cache_lookup_tokens", 0)
        swap += c.get("swap_in_hit_tokens", 0)
    return hit, lookup, swap


def _kvtier_overcap_wave(model, cfg, rs, errors, deadline_s):
    """Many-distinct-prefixes wave OVER device-cache capacity, served
    with the host tier on vs off through otherwise-identical engines.
    Round 1 publishes every prefix (early ones are LRU-evicted — demoted
    to host when the tier is on); round 2 re-serves them in the same
    order, so the device-only engine recomputes what the tiered engine
    swaps back in. Reports both hit rates (the tiered one must be
    strictly higher) and the p95 step latency ratio (the tier must be
    off the critical path: within +10%)."""
    from paddle_tpu.serving import LLMEngine

    if time.monotonic() > deadline_s:
        errors.append("gpt_serve_router: deadline before kvtier "
                      "over-capacity wave")
        return None
    bs, num_blocks, gen = 16, 40, 8
    n_prefix, plen = 10, 64
    prefixes = [rs.randint(0, cfg.vocab_size, (plen,)).tolist()
                for _ in range(n_prefix)]
    tails = [rs.randint(0, cfg.vocab_size, (8,)).tolist()
             for _ in range(n_prefix)]

    def wave(host_blocks):
        eng = LLMEngine(model, block_size=bs, max_batch=4,
                        num_blocks=num_blocks, host_kv_blocks=host_blocks)
        eng.generate([rs.randint(0, cfg.vocab_size, (8,)).tolist()],
                     max_new_tokens=2, temperature=0.0)       # prime
        for p in prefixes:                                    # round 1
            eng.generate([p], max_new_tokens=2, temperature=0.0)
        base = _hit_rates([eng])
        for p, t in zip(prefixes, tails):                     # round 2
            eng.add_request(p + t, max_new_tokens=gen, temperature=0.0)
        steps, t0 = [], time.perf_counter()
        while eng.has_unfinished():
            if time.monotonic() > deadline_s:
                errors.append("gpt_serve_router: deadline mid kvtier "
                              "over-capacity wave; comparison dropped")
                for rid in list(eng._requests):
                    eng.abort(rid)
                return None
            s0 = time.perf_counter()
            eng.step()
            steps.append(time.perf_counter() - s0)
        dt = time.perf_counter() - t0
        hit, lookup, swap = (a - b for a, b in
                             zip(_hit_rates([eng]), base))
        eng.close()
        return {
            "hit_rate": round(hit / lookup, 4) if lookup else 0.0,
            "swap_in_hit_tokens": swap,
            "p95_step_ms": round(
                sorted(steps)[int(0.95 * (len(steps) - 1))] * 1e3, 2),
            "tok_s": round(n_prefix * gen / dt, 1) if dt else 0.0,
        }

    tiered = wave(host_blocks=128)
    if tiered is None or time.monotonic() > deadline_s:
        return None
    device_only = wave(host_blocks=None)
    if device_only is None:
        return None
    out = {
        "distinct_prefixes": n_prefix,
        "device_blocks": num_blocks - 1,
        "tiered": tiered,
        "device_only": device_only,
        "hit_rate_gain": round(
            tiered["hit_rate"] - device_only["hit_rate"], 4),
        "p95_step_ratio": round(
            tiered["p95_step_ms"] / device_only["p95_step_ms"], 3)
        if device_only["p95_step_ms"] else 0.0,
    }
    if tiered["hit_rate"] <= device_only["hit_rate"]:
        errors.append(
            f"gpt_serve_router: kvtier over-capacity hit rate "
            f"{tiered['hit_rate']} not above device-only "
            f"{device_only['hit_rate']}")
    if out["p95_step_ratio"] > 1.10:
        errors.append(
            f"gpt_serve_router: kvtier p95 step latency ratio "
            f"{out['p95_step_ratio']} exceeds 1.10 — the host tier is "
            "on the decode critical path")
    _log(f"kvtier overcap: hit {tiered['hit_rate']} (tiered) vs "
         f"{device_only['hit_rate']} (device-only), p95 ratio "
         f"{out['p95_step_ratio']}")
    return out


def _kvtier_drain_wave(model, cfg, rs, errors, deadline_s):
    """Zero-rewarm rolling drain: a 2-replica fleet with a restart
    factory serves a warm shared-prefix wave, rolls every replica, and
    re-serves — once WITH cross-replica migration and once WITHOUT. With
    migration the post-drain hit rate must hold at (or above) the
    pre-drain warm rate and no request may fail; without it the fresh
    engines start cache-cold."""
    import asyncio

    from paddle_tpu.serving import AsyncLLMEngine, LLMEngine, ReplicaRouter

    if time.monotonic() > deadline_s:
        errors.append("gpt_serve_router: deadline before kvtier "
                      "drain wave")
        return None
    gen = 4
    shared = [rs.randint(0, cfg.vocab_size, (64,)).tolist()
              for _ in range(3)]
    prompts = [s + rs.randint(0, cfg.vocab_size, (8,)).tolist()
               for s in shared for _ in range(2)]

    def mk(_i=0):
        return AsyncLLMEngine(LLMEngine(model, block_size=16, max_batch=4,
                                        host_kv_blocks=128))

    async def run(migrate):
        router = ReplicaRouter([mk() for _ in range(2)], factory=mk,
                               migrate_on_drain=migrate,
                               sweep_interval_s=0.05)
        await router.start()
        engines = lambda: [r.engine.engine for r in router.replicas]  # noqa: E731

        async def serve():
            base = _hit_rates(engines())
            streams = [await router.submit(p, max_new_tokens=gen,
                                           temperature=0.0)
                       for p in prompts]
            outs = [await s.collect() for s in streams]
            hit, lookup, _ = (a - b for a, b in
                              zip(_hit_rates(engines()), base))
            failed = sum(1 for _, r in outs if r not in ("length", "stop"))
            return (round(hit / lookup, 4) if lookup else 0.0), failed

        await serve()                                  # publish + compile
        warm_rate, _ = await serve()                   # pre-drain warm
        await router.rolling_drain()
        post_rate, failed = await serve()              # post-drain
        migrated = router.metrics.counters.get("router_migrated_blocks", 0)
        await router.shutdown()
        return {"warm_hit_rate": warm_rate, "post_drain_hit_rate": post_rate,
                "failed": failed, "migrated_blocks": migrated}

    try:
        with_mig = asyncio.run(run(True))
        if time.monotonic() > deadline_s:
            errors.append("gpt_serve_router: deadline before no-migration "
                          "drain wave; comparison dropped")
            return {"with_migration": with_mig}
        without = asyncio.run(run(False))
    except Exception as e:  # noqa: BLE001 — the router waves already landed
        errors.append(f"gpt_serve_router kvtier drain: "
                      f"{type(e).__name__}: {str(e)[:200]}")
        return None
    out = {"with_migration": with_mig, "without_migration": without,
           "zero_rewarm": with_mig["post_drain_hit_rate"]
           >= with_mig["warm_hit_rate"]}
    if with_mig["failed"] or without["failed"]:
        errors.append(f"gpt_serve_router: kvtier drain failed requests "
                      f"(with={with_mig['failed']}, "
                      f"without={without['failed']})")
    if with_mig["post_drain_hit_rate"] < with_mig["warm_hit_rate"]:
        errors.append(
            f"gpt_serve_router: post-drain hit rate "
            f"{with_mig['post_drain_hit_rate']} below pre-drain warm "
            f"rate {with_mig['warm_hit_rate']} despite migration")
    if with_mig["post_drain_hit_rate"] <= without["post_drain_hit_rate"]:
        errors.append(
            f"gpt_serve_router: migration post-drain hit rate "
            f"{with_mig['post_drain_hit_rate']} not above no-migration "
            f"{without['post_drain_hit_rate']}")
    _log(f"kvtier drain: post-drain hit {with_mig['post_drain_hit_rate']} "
         f"(migration, {with_mig['migrated_blocks']} blocks) vs "
         f"{without['post_drain_hit_rate']} (cold restart)")
    return out


def _bench_model():
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=2,
                    num_heads=4, max_seq_len=256, attn_impl="xla")
    model = GPT(cfg)
    model.eval()
    return model, cfg


def bench_gpt_serve_longdoc_qa(on_tpu, errors, deadline_s):
    """Long-document QA over a shared corpus (the host-tier headline
    workload): a corpus of document prefixes larger than the device
    cache, each asked several questions with OTHER documents' questions
    interleaved between them — so by the time a document's next question
    arrives, its blocks have been LRU-evicted from the device arena.
    Served tiered vs device-only: the tier turns every re-visit into a
    swap-back instead of a full-document re-prefill."""
    from paddle_tpu.serving import LLMEngine

    del on_tpu
    model, cfg = _bench_model()
    rs = np.random.RandomState(0)
    bs, num_blocks, gen = 16, 28, 8
    n_docs, doc_len, n_q = (6, 96, 2) if _fast() else (8, 96, 3)
    docs = [rs.randint(0, cfg.vocab_size, (doc_len,)).tolist()
            for _ in range(n_docs)]
    # round-robin across documents: consecutive questions about one doc
    # never run back-to-back (the interleaving that defeats device LRU)
    qa = [(d, docs[d] + rs.randint(0, cfg.vocab_size, (8,)).tolist())
          for q in range(n_q) for d in range(n_docs)]

    def wave(host_blocks):
        eng = LLMEngine(model, block_size=bs, max_batch=2,
                        num_blocks=num_blocks, host_kv_blocks=host_blocks)
        eng.generate([docs[0]], max_new_tokens=2, temperature=0.0)  # prime
        base = _hit_rates([eng])
        t0 = time.perf_counter()
        for i in range(0, len(qa), 2):
            if time.monotonic() > deadline_s:
                errors.append("gpt_serve_longdoc_qa: deadline mid wave")
                return None
            eng.generate([p for _, p in qa[i:i + 2]],
                         max_new_tokens=gen, temperature=0.0)
        dt = time.perf_counter() - t0
        hit, lookup, swap = (a - b for a, b in
                             zip(_hit_rates([eng]), base))
        eng.close()
        return {
            "tok_s": round(len(qa) * gen / dt, 1) if dt else 0.0,
            "hit_rate": round(hit / lookup, 4) if lookup else 0.0,
            "swap_in_hit_tokens": swap,
        }

    tiered = wave(host_blocks=192)
    if tiered is None or time.monotonic() > deadline_s:
        return None
    device_only = wave(host_blocks=None)
    if device_only is None:
        return None
    out = {
        "value": tiered["tok_s"],
        "documents": n_docs, "doc_tokens": doc_len,
        "questions_per_doc": n_q,
        "device_blocks": num_blocks - 1,
        "tiered": tiered, "device_only": device_only,
        "hit_rate_gain": round(
            tiered["hit_rate"] - device_only["hit_rate"], 4),
        "speedup": round(tiered["tok_s"] / device_only["tok_s"], 3)
        if device_only["tok_s"] else 0.0,
    }
    if tiered["hit_rate"] <= device_only["hit_rate"]:
        errors.append(
            f"gpt_serve_longdoc_qa: tiered hit rate {tiered['hit_rate']} "
            f"not above device-only {device_only['hit_rate']}")
    _log(f"longdoc qa: {tiered['tok_s']} tok/s hit {tiered['hit_rate']} "
         f"(tiered) vs {device_only['tok_s']} tok/s hit "
         f"{device_only['hit_rate']} (device-only)")
    return out


def bench_gpt_serve_nbest(on_tpu, errors, deadline_s):
    """N-best parallel sampling over a prompt corpus: each round samples
    n completions of ONE prompt (the samples share every prompt block;
    their divergent tails copy-on-write off the shared last block), and
    rounds cycle through more prompts than the device cache holds — the
    host tier keeps every prompt's prefix warm between its rounds.
    Tiered vs device-only tok/s + hit rate, plus the COW copy count
    (the sharing proof)."""
    from paddle_tpu.serving import LLMEngine

    del on_tpu
    model, cfg = _bench_model()
    rs = np.random.RandomState(1)
    bs, num_blocks, gen, n_best = 16, 40, 8, 4
    n_prompts, plen, rounds = (6, 64, 2) if _fast() else (8, 64, 2)
    corpus = [rs.randint(0, cfg.vocab_size, (plen,)).tolist()
              for _ in range(n_prompts)]

    def wave(host_blocks):
        eng = LLMEngine(model, block_size=bs, max_batch=n_best,
                        num_blocks=num_blocks, host_kv_blocks=host_blocks)
        eng.generate([corpus[0]], max_new_tokens=2, temperature=0.0)
        base = _hit_rates([eng])
        cow0 = eng.metrics.counters.get("prefix_cache_cow_copies", 0)
        t0, generated = time.perf_counter(), 0
        for rnd in range(rounds):
            for p in corpus:
                if time.monotonic() > deadline_s:
                    errors.append("gpt_serve_nbest: deadline mid wave")
                    return None
                # n-best: n sampled completions of the same prompt in
                # one batch (seeded engine sampler -> distinct tails)
                outs = eng.generate([p] * n_best, max_new_tokens=gen,
                                    temperature=0.8, top_p=0.95)
                generated += sum(len(o) for o in outs)
        dt = time.perf_counter() - t0
        hit, lookup, swap = (a - b for a, b in
                             zip(_hit_rates([eng]), base))
        cow = eng.metrics.counters.get("prefix_cache_cow_copies", 0) - cow0
        eng.close()
        return {
            "tok_s": round(generated / dt, 1) if dt else 0.0,
            "hit_rate": round(hit / lookup, 4) if lookup else 0.0,
            "swap_in_hit_tokens": swap,
            "cow_copies": cow,
        }

    tiered = wave(host_blocks=192)
    if tiered is None or time.monotonic() > deadline_s:
        return None
    device_only = wave(host_blocks=None)
    if device_only is None:
        return None
    out = {
        "value": tiered["tok_s"],
        "prompts": n_prompts, "n_best": n_best, "rounds": rounds,
        "device_blocks": num_blocks - 1,
        "tiered": tiered, "device_only": device_only,
        "hit_rate_gain": round(
            tiered["hit_rate"] - device_only["hit_rate"], 4),
        "speedup": round(tiered["tok_s"] / device_only["tok_s"], 3)
        if device_only["tok_s"] else 0.0,
    }
    if tiered["hit_rate"] <= device_only["hit_rate"]:
        errors.append(
            f"gpt_serve_nbest: tiered hit rate {tiered['hit_rate']} "
            f"not above device-only {device_only['hit_rate']}")
    _log(f"nbest: {tiered['tok_s']} tok/s hit {tiered['hit_rate']} "
         f"(tiered, {tiered['cow_copies']} cow) vs {device_only['tok_s']} "
         f"tok/s hit {device_only['hit_rate']} (device-only)")
    return out


def _serve_shared_prefix(model, cfg, max_batch, rs, errors, deadline_s,
                         on_tpu):
    """Shared-system-prompt wave: N requests = one long common prefix +
    short unique tails, served twice through fresh engines — prefix cache
    on (engine default, honoring PADDLE_TPU_PREFIX_CACHE) vs. forced off.
    Both engines are primed with one request (compiles their programs AND
    seeds the cached engine's index) before the measured wave."""
    from paddle_tpu.serving import LLMEngine

    if time.monotonic() > deadline_s:
        errors.append("gpt_serve: deadline before shared-prefix wave")
        return None
    prefix_len = 512 if on_tpu else 160
    tail, max_new = (16, 16) if on_tpu else (8, 8)
    n_req = 2 * max_batch if not _fast() else max_batch
    prefix = rs.randint(0, cfg.vocab_size, (prefix_len,)).tolist()
    prompts = [prefix + rs.randint(0, cfg.vocab_size, (tail,)).tolist()
               for _ in range(n_req)]

    def wave(prefix_cache):
        eng = LLMEngine(model, block_size=16, max_batch=max_batch,
                        prefix_cache=prefix_cache)
        # prime: compiles both step programs; on the cached engine this
        # also publishes the shared prefix's blocks into the index
        eng.generate([prefix], max_new_tokens=2)
        eng.metrics.reset_schedule()
        t0_tok = eng.metrics.counters["generated_tokens"]
        for p in prompts:
            eng.add_request(p, max_new_tokens=max_new)
        t0 = time.perf_counter()
        while eng.has_unfinished():
            if time.monotonic() > deadline_s:
                # a truncated wave's rate is ramp-up-dominated: poison the
                # comparison rather than report a bogus speedup
                errors.append("gpt_serve: deadline mid shared-prefix wave; "
                              "comparison dropped")
                for rid in list(eng._requests):
                    eng.abort(rid)
                return 0.0, eng.metrics
            eng.step()
        dt = time.perf_counter() - t0
        toks = eng.metrics.counters["generated_tokens"] - t0_tok
        return (toks / dt if dt > 0 and toks else 0.0), eng.metrics

    try:
        tok_s_cached, m = wave(prefix_cache=None)   # None -> engine default
        if not tok_s_cached or time.monotonic() > deadline_s:
            # don't let the second wave's unmeasured prime (two fresh jit
            # compiles + a prefix serve) overrun an already-blown budget
            return None
        tok_s_off, _ = wave(prefix_cache=False)
    except Exception as e:  # noqa: BLE001 — the main wave already landed
        errors.append(f"gpt_serve shared-prefix: {type(e).__name__}: "
                      f"{str(e)[:200]}")
        return None
    if not tok_s_off:
        return None
    return {
        "shared_prefix_requests": n_req,
        "shared_prefix_len": prefix_len,
        "shared_prefix_tok_s": round(tok_s_cached, 1),
        "shared_prefix_tok_s_nocache": round(tok_s_off, 1),
        "shared_prefix_speedup": round(tok_s_cached / tok_s_off, 3),
        "prefix_cache_hit_rate": round(
            m.gauges.get("prefix_cache_hit_rate", 0.0), 4),
        "prefix_cache_hit_tokens": int(
            m.counters.get("prefix_cache_hit_tokens", 0)),
        "prefix_cache_evictions": int(
            m.counters.get("prefix_cache_evictions", 0)),
    }


def _serve_spec_wave(model, cfg, max_batch, rs, errors, deadline_s, on_tpu):
    """Speculative-decoding wave: a repetitive-suffix workload served with
    spec decoding ON (prompt-lookup drafting + batched verify) vs OFF
    through otherwise-identical engines. Prompts end in a repeated motif
    and the decode runs long — greedy decode of the (random-weight) bench
    model collapses into short token cycles within a few dozen steps, so
    the drafter's n-gram lookups hit exactly the way they do on real
    repetitive traffic (extraction, code edits, quoting). Reports tok/s
    for both engines plus the spec engine's acceptance rate and
    tokens/step; greedy outputs of the two engines are identical by the
    engine's spec parity guarantee (tests/test_spec_decode.py)."""
    from paddle_tpu.serving import LLMEngine

    if time.monotonic() > deadline_s:
        errors.append("gpt_serve: deadline before spec wave")
        return None
    n_req = max_batch if _fast() else 2 * max_batch
    # the long decode tail is where the model's output goes cyclic and
    # acceptance climbs — r06 sweep: max_new 64 broke even on CPU, 128 won
    # 1.31x (acceptance 0.54, min_ngram=2 to skip spurious unigram drafts)
    max_new = 128 if not _fast() else 64
    motif_len, n_motif = 8, 3
    prompts = []
    for _ in range(n_req):
        motif = rs.randint(0, cfg.vocab_size, (motif_len,)).tolist()
        head = rs.randint(0, cfg.vocab_size, (16,)).tolist()
        prompts.append(head + motif * n_motif)

    def wave(spec_on):
        eng = LLMEngine(model, block_size=16, max_batch=max_batch,
                        spec_decoding=spec_on, num_spec_tokens=4,
                        spec_min_ngram=2, prefix_cache=False)
        # prime compiles every program the wave will use: mixed + decode,
        # and on the spec engine the verify step too (a repeated-token
        # prompt guarantees the drafter proposes from the first decode)
        eng.generate([[7] * 24], max_new_tokens=6)
        eng.metrics.reset_schedule()
        # counters are engine-lifetime: snapshot after priming so the wave
        # reports ITS deltas, not the priming request's drafts/steps
        keys = ("generated_tokens", "spec_proposed_tokens",
                "spec_accepted_tokens", "verify_steps", "mixed_steps",
                "decode_steps")
        base = {k: eng.metrics.counters.get(k, 0) for k in keys}
        for p in prompts:
            eng.add_request(p, max_new_tokens=max_new)
        t0 = time.perf_counter()
        while eng.has_unfinished():
            if time.monotonic() > deadline_s:
                errors.append("gpt_serve: deadline mid spec wave; "
                              "comparison dropped")
                for rid in list(eng._requests):
                    eng.abort(rid)
                return 0.0, {}
            eng.step()
        dt = time.perf_counter() - t0
        d = {k: eng.metrics.counters.get(k, 0) - base[k] for k in keys}
        toks = d["generated_tokens"]
        return (toks / dt if dt > 0 and toks else 0.0), d

    try:
        tok_s_spec, d = wave(spec_on=True)
        if not tok_s_spec or time.monotonic() > deadline_s:
            return None
        tok_s_off, _ = wave(spec_on=False)
    except Exception as e:  # noqa: BLE001 — the main wave already landed
        errors.append(f"gpt_serve spec wave: {type(e).__name__}: "
                      f"{str(e)[:200]}")
        return None
    if not tok_s_off:
        return None
    steps = d["verify_steps"] + d["mixed_steps"] + d["decode_steps"]
    return {
        "spec_requests": n_req,
        "spec_max_new_tokens": max_new,
        "spec_tok_s": round(tok_s_spec, 1),
        "spec_tok_s_off": round(tok_s_off, 1),
        "spec_speedup": round(tok_s_spec / tok_s_off, 3),
        "spec_acceptance_rate": round(
            d["spec_accepted_tokens"] / d["spec_proposed_tokens"], 4
        ) if d["spec_proposed_tokens"] else 0.0,
        "spec_tokens_per_step": round(
            d["generated_tokens"] / steps, 3) if steps else 0.0,
        "spec_verify_steps": int(d["verify_steps"]),
        "spec_proposed_tokens": int(d["spec_proposed_tokens"]),
        "spec_accepted_tokens": int(d["spec_accepted_tokens"]),
    }


def _serve_int8_overcap(model, cfg, rs, errors, deadline_s):
    """Int8-vs-weight-dtype KV arena at the SAME per-chip byte budget
    (`kv_hbm_bytes`): the quantized arena's smaller blocks buy ~2x (bf16)
    to ~4x (f32) the capacity, so an over-capacity wave that churns the
    baseline engine through preemptions mostly fits resident on int8.
    Reports blocks bought per dtype, bytes/block, preemptions, tok/s, and
    the greedy token parity rate between the two engines — the tier-1
    quality gate (tests/test_int8_kv.py) locks the rate; the bench line
    records the measured value so the trajectory sees quantization drift
    before the gate trips."""
    from paddle_tpu.serving import LLMEngine

    if time.monotonic() > deadline_s:
        errors.append("gpt_serve: deadline before int8 overcap wave")
        return None
    bs, max_seq, max_new, n_req = 16, 128, 8, 8
    head_dim = cfg.hidden_size // cfg.num_heads
    itemsize = model.wte.weight._array.dtype.itemsize
    per_block = 2 * cfg.num_layers * cfg.num_heads * bs * head_dim * itemsize
    # ~12 baseline blocks: enough for one max_seq sequence (+null) but
    # well under the wave's working set, so the baseline engine churns
    budget = 12 * per_block
    prompts = [rs.randint(0, cfg.vocab_size, (96,)).tolist()
               for _ in range(n_req)]

    def wave(kv_dtype):
        eng = LLMEngine(model, block_size=bs, max_batch=4,
                        max_seq_len=max_seq, kv_hbm_bytes=budget,
                        kv_dtype=kv_dtype)
        eng.generate([prompts[0][:24]], max_new_tokens=2,
                     temperature=0.0)                          # prime
        eng.metrics.reset_schedule()
        t0_tok = eng.metrics.counters["generated_tokens"]
        t0_pre = eng.metrics.counters.get("preemptions", 0)
        t0 = time.perf_counter()
        rids = [eng.add_request(p, max_new_tokens=max_new, temperature=0.0)
                for p in prompts]
        while eng.has_unfinished():
            if time.monotonic() > deadline_s:
                errors.append("gpt_serve: deadline mid int8 overcap "
                              "wave; comparison dropped")
                for rid in list(eng._requests):
                    eng.abort(rid)
                return None, None
            eng.step()
        dt = time.perf_counter() - t0
        outs = [tuple(eng._requests[r].output_ids) for r in rids]
        for r in rids:
            eng.release(r)
        toks = eng.metrics.counters["generated_tokens"] - t0_tok
        st = eng.pool_stats()
        return outs, {
            "kv_dtype": st["kv_dtype"],
            "num_blocks": st["blocks_total"],
            "kv_bytes_per_block": st["kv_bytes_per_block"],
            "preemptions": int(eng.metrics.counters.get("preemptions", 0)
                               - t0_pre),
            "tok_s": round(toks / dt, 1) if dt else 0.0,
        }

    try:
        base_outs, base = wave(None)
        if base is None or time.monotonic() > deadline_s:
            return None
        q_outs, quant = wave("int8")
    except Exception as e:  # noqa: BLE001 — the main wave already landed
        errors.append(f"gpt_serve int8 overcap wave: {type(e).__name__}: "
                      f"{str(e)[:200]}")
        return None
    if quant is None:
        return None
    matched = sum(a == b for a, b in zip(base_outs, q_outs))
    out = {
        "kv_hbm_bytes": budget,
        "requests": n_req,
        "base": base,
        "int8": quant,
        "capacity_ratio": round(quant["num_blocks"] / base["num_blocks"], 2),
        "greedy_parity_rate": round(matched / n_req, 3) if n_req else 0.0,
    }
    _log(f"int8 overcap: {quant['num_blocks']} blocks "
         f"({quant['tok_s']} tok/s, {quant['preemptions']} preempt) vs "
         f"{base['num_blocks']} {base['kv_dtype']} blocks "
         f"({base['tok_s']} tok/s, {base['preemptions']} preempt), "
         f"parity {out['greedy_parity_rate']}")
    return out


# ---------------------------------------------------------------------------
# ResNet-50 (BASELINE config 1) — NHWC, the TPU-native layout
# ---------------------------------------------------------------------------

def bench_resnet50(on_tpu, errors, deadline_s):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    # NHWC: channels-minor makes BN reductions lane-contiguous and feeds the
    # MXU directly (resnet.py module docstring); NCHW was the round-4 number
    # (2,253 img/s MFU 0.14) with conv absent from the top-25 self-time ops.
    model = resnet50(data_format="NHWC")
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.Momentum(
        learning_rate=0.1, momentum=0.9, parameters=model.parameters()
    )
    params, buffers = state_dict_arrays(model)
    opt_state = opt.init_state_arrays(params)

    def step(params, buffers, opt_state, lr, key, images, labels):
        def loss_fn(p):
            logits, new_buf = functional_call(
                model, p, buffers, args=(images,), rng_key=key, training=True
            )
            lg = (logits if not isinstance(logits, (tuple, list)) else logits[0])
            lg = lg.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(
                lg, labels[:, None].astype(jnp.int32), axis=-1
            )[:, 0]
            return jnp.mean(lse - picked), new_buf

        (loss, new_buf), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_arrays(params, grads, opt_state, lr)
        return loss, new_params, new_buf, new_opt

    jstep = jax.jit(step, donate_argnums=(0, 2))
    lr = jnp.asarray(0.1, jnp.float32)
    rs = np.random.RandomState(0)
    snap = jax.tree_util.tree_map(np.asarray, (params, buffers, opt_state))
    side = 224 if on_tpu else 32

    def run(batch, iters):
        params, buffers, opt_state = jax.tree_util.tree_map(jnp.asarray, snap)
        images = jnp.asarray(
            rs.rand(batch, side, side, 3).astype(np.float32), jnp.bfloat16
        )
        labels = jnp.asarray(rs.randint(0, 1000, (batch,), dtype=np.int32))
        loss, params, buffers, opt_state = jstep(
            params, buffers, opt_state, lr, rng.next_key(), images, labels
        )
        float(np.asarray(loss))
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, params, buffers, opt_state = jstep(
                params, buffers, opt_state, lr, rng.next_key(), images, labels
            )
        float(np.asarray(loss))
        return batch * iters / (time.perf_counter() - t0)

    if _fast():
        batches = (256,) if on_tpu else (2,)
        iters = 8 if on_tpu else 2
    else:
        batches = (256, 128) if on_tpu else (2,)
        iters = 20 if on_tpu else 2
    sweep = _sweep(run, batches, iters, errors, deadline_s, name="resnet50")
    if not sweep:
        return None
    best = max(sweep, key=sweep.get)
    from paddle_tpu.profiler.flops import resnet50_train_flops_per_image

    train_flops = resnet50_train_flops_per_image(side)
    peak = _peak_flops(jax.devices()[0])
    return {
        "samples_per_sec": round(sweep[best], 1),
        "mfu": round(sweep[best] * train_flops / peak, 4),
        "batch": best,
        "layout": "NHWC",
        "sweep": {str(k): round(v, 1) for k, v in sweep.items()},
    }


# ---------------------------------------------------------------------------
# PP-YOLOE-s inference latency (BASELINE config 4)
# ---------------------------------------------------------------------------

def bench_ppyoloe(on_tpu, errors, deadline_s):
    """Batch-1 detection latency: PP-YOLOE-s net + decode + matrix NMS as
    ONE compiled program (the predictor's bucket machinery is exercised in
    tests/test_detection.py; here we time the compiled detect step itself)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import autograd
    from paddle_tpu.core.functional import state_dict_arrays, swap_state
    from paddle_tpu.core.tensor import Tensor as _T
    from paddle_tpu.vision.models import ppyoloe_s

    paddle.seed(0)
    side = 640 if on_tpu else 64
    model = ppyoloe_s(num_classes=80)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    params, buffers = state_dict_arrays(model)

    @jax.jit
    def detect(params, images):
        with autograd.trace_mode(), swap_state(model, params, buffers):
            out, nums = model.predict(_T._from_op(images), keep_top_k=100)
        return out._array, nums._array

    rs = np.random.RandomState(0)
    img = rs.rand(1, 3, side, side).astype(np.float32)
    imgs = jnp.asarray(img, jnp.bfloat16 if on_tpu else jnp.float32)
    out = detect(params, imgs)
    jax.block_until_ready(out)
    iters = 30 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = detect(params, imgs)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    return {"latency_ms": round(dt * 1e3, 3), "image_size": side, "batch": 1}


# ---------------------------------------------------------------------------
# LeNet Model.fit step time (BASELINE config 0)
# ---------------------------------------------------------------------------

def bench_lenet(on_tpu, errors, deadline_s):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = paddle.Model(LeNet())
    opt = paddle.optimizer.Adam(
        learning_rate=1e-3, parameters=model.network.parameters()
    )
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.rand(64, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rs.randint(0, 10, (64, 1)))
    model.train_batch([x], [y])  # compile
    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        model.train_batch([x], [y])
    dt = (time.perf_counter() - t0) / iters
    # train_batch syncs the loss to host every step; through the remote-TPU
    # tunnel that round trip dominates tiny models. Record the measured
    # round-trip AND a device-resident number so the framework's own step
    # cost is visible: a lax.scan of 50 training steps inside ONE program
    # has no per-step host sync (what a real input-pipelined run achieves).
    f = jax.jit(lambda a: a + 1.0)
    z = jnp.zeros(8)
    np.asarray(f(z))
    t0 = time.perf_counter()
    for _ in range(10):
        np.asarray(f(z))
    sync_ms = (time.perf_counter() - t0) / 10 * 1e3

    net = LeNet()
    opt2 = paddle.optimizer.Adam(learning_rate=1e-3, parameters=net.parameters())
    params, buffers = state_dict_arrays(net)
    opt_state = opt2.init_state_arrays(params)
    lr = jnp.asarray(1e-3, jnp.float32)
    xs = jnp.asarray(rs.rand(64, 1, 28, 28).astype(np.float32))
    ys = jnp.asarray(rs.randint(0, 10, (64,), dtype=np.int32))

    def one(carry, key):
        params, buffers, opt_state = carry

        def loss_fn(p):
            logits, nb = functional_call(
                net, p, buffers, args=(xs,), rng_key=key, training=True
            )
            lg = logits.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(lg, ys[:, None], axis=-1)[:, 0]
            return jnp.mean(lse - picked), nb

        (loss, nb), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        np_, no_ = opt2.apply_gradients_arrays(params, grads, opt_state, lr)
        return (np_, nb, no_), loss

    @jax.jit
    def scan_steps(carry, keys):
        return jax.lax.scan(one, carry, keys)

    keys = jax.random.split(rng.next_key(), 50)
    carry = (params, buffers, opt_state)
    carry, losses = scan_steps(carry, keys)  # compile
    jax.block_until_ready(losses)
    t0 = time.perf_counter()
    carry, losses = scan_steps(carry, keys)
    jax.block_until_ready(losses)
    device_ms = (time.perf_counter() - t0) / 50 * 1e3
    return {"step_ms": round(dt * 1e3, 3), "batch": 64,
            "host_sync_roundtrip_ms": round(sync_ms, 2),
            "device_resident_step_ms": round(device_ms, 3)}


_BENCHES = {
    "gpt": bench_gpt,
    "gpt_train_zero": bench_gpt_train_zero,
    "gpt_serve": bench_gpt_serve,
    "gpt_serve_multichip": bench_gpt_serve_multichip,
    "gpt_serve_router": bench_gpt_serve_router,
    "gpt_serve_fairness": bench_gpt_serve_fairness,
    "gpt_serve_autoscale": bench_gpt_serve_autoscale,
    "gpt_serve_longdoc_qa": bench_gpt_serve_longdoc_qa,
    "gpt_serve_nbest": bench_gpt_serve_nbest,
    "resnet50": bench_resnet50,
    "lenet": bench_lenet,
    "ppyoloe": bench_ppyoloe,
}


def _child(name, soft_deadline_s):
    """Run ONE benchmark and print its JSON on the last line."""
    if name in ("gpt_serve_multichip", "gpt_train_zero"):
        # the sharded waves ALWAYS run on the 8-fake-device CPU host
        # platform — flip it before any jax backend init (the env var
        # alone is not enough; same trick as tests/conftest.py)
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from _cpu_mesh import force_host_cpu_devices

        force_host_cpu_devices(8)
    import jax

    # (persistent compile cache is enabled by paddle_tpu at import —
    # repeated bench runs reuse the tunnel compiles from ~/.cache)
    on_tpu = jax.default_backend() in ("tpu", "axon")
    deadline = time.monotonic() + soft_deadline_s
    errors = []
    try:
        result = _BENCHES[name](on_tpu, errors, deadline)
    except Exception as e:  # noqa: BLE001
        errors.append(f"{name}: {type(e).__name__}: {str(e)[:300]}")
        result = None
    print(json.dumps({"result": result, "errors": errors}))
    return 0


def _run_isolated(name, timeout_s):
    """Each benchmark gets its own process: device memory fully released
    between benches, and one bench's OOM cannot poison the next (an
    in-process OOM leaves the PjRt allocator poisoned for later benches).
    The child gets a soft deadline 30 s inside the hard kill so it can
    print a partial sweep before the subprocess timeout fires."""
    import subprocess

    if timeout_s < 60:
        return {"result": None,
                "errors": [f"{name}: skipped — {timeout_s:.0f}s left in budget"]}
    try:
        proc = subprocess.run(
            [sys.executable, __file__, name, str(max(30.0, timeout_s - 30.0))],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"result": None,
                "errors": [f"{name}: no output (rc={proc.returncode}) "
                           f"{proc.stderr[-200:]}"]}
    except subprocess.TimeoutExpired as e:
        # the child may have printed its (partial-sweep) JSON just before
        # the hard kill — salvage it rather than reporting 0.0
        out = e.stdout
        if out:
            if isinstance(out, bytes):
                out = out.decode("utf-8", "replace")
            for line in reversed(out.strip().splitlines()):
                line = line.strip()
                if line.startswith("{"):
                    try:
                        r = json.loads(line)
                        r.setdefault("errors", []).append(
                            f"{name}: hard timeout after {timeout_s:.0f}s "
                            "(salvaged last JSON line)"
                        )
                        return r
                    except ValueError:
                        break
        return {"result": None, "errors": [f"{name}: timed out after {timeout_s:.0f}s"]}
    except Exception as e:  # noqa: BLE001
        return {"result": None, "errors": [f"{name}: {type(e).__name__}: {e}"]}


def _emit(gpt, extras, errors):
    out = {
        "metric": "gpt_train_tokens_per_sec_per_chip",
        "value": (gpt or {}).get("value", 0.0),
        "unit": "tokens/sec",
        "vs_baseline": 1.0 if gpt else 0.0,
        "status": _status(gpt, errors),
        "probe": dict(_PROBE),
    }
    if gpt:
        out["mfu"] = gpt["mfu"]
        out["batch"] = gpt["batch"]
        out["sweep"] = gpt["sweep"]
        # train-side drift fields (PR 19) ride the primary line
        for k in ("zero_stage", "quant_grads", "per_chip_opt_state_bytes",
                  "collective_time_share"):
            if k in gpt:
                out[k] = gpt[k]
    out.update(extras)
    if errors:
        out["errors"] = errors
    print(json.dumps(out), flush=True)
    return out


def _emit_model(name, r, unit, metric=None):
    """One flushed JSON line per model, the moment its bench finishes —
    BENCH_r05's lesson: gpt timing out must not make every later model
    invisible. A timeout/error is a RECORD (status + errors on the line),
    never a crash that hides the models that did complete."""
    result = r.get("result")
    errs = r.get("errors") or []
    line = {
        "metric": metric or f"bench_{name}",
        "value": 0.0,
        "unit": unit,
        "vs_baseline": 1.0 if result else 0.0,
        "status": _status(result, errs),
        "probe": dict(_PROBE),
    }
    if result:
        line.update(result)
        for k in ("value", "samples_per_sec", "latency_ms", "step_ms"):
            if k in result:
                line["value"] = result[k]
                break
    if errs:
        line["errors"] = errs
    print(json.dumps(line), flush=True)
    return result


def main():
    if len(sys.argv) > 2:
        return _child(sys.argv[1], float(sys.argv[2]))
    if len(sys.argv) > 1:  # legacy single-arg child invocation
        return _child(sys.argv[1], 600.0)

    errors = []
    extras = {}
    completed = 0

    # Prove the backend is alive before betting the budget on it (r04/r05:
    # a hung accelerator tunnel timed out EVERY bench and zeroed the
    # primary metric; CPU finishes the whole suite in minutes).
    note = _probe_backend()
    if note:
        global _TPU_UNREACHABLE
        _TPU_UNREACHABLE = "forcing JAX_PLATFORMS=cpu" in note
        _log(note)
        errors.append(f"probe: {note}")

    # GPT first: the primary metric must land even if the driver kills us.
    r = _run_isolated("gpt", min(540.0, _remaining()))
    errors.extend(r.get("errors") or [])
    gpt = r.get("result")
    completed += bool(gpt)
    _emit(gpt, {}, errors)  # flushed immediately — this line alone is valid

    # explicit-ZeRO train wave: stage 0/2/2+int8 tok/s, opt-state shrink,
    # loss-parity verdict and lowered collective counts on the fake CPU
    # mesh (correctness + collective shape, not accelerator speed)
    r = _run_isolated("gpt_train_zero", min(240.0, _remaining()))
    errors.extend(r.get("errors") or [])
    z = _emit_model("gpt_train_zero", r, "tokens/sec",
                    metric="gpt_train_zero_tokens_per_sec")
    if z:
        completed += 1
        extras["gpt_train_zero"] = z

    # gpt_serve rides the same per-model cap as the secondary benches so a
    # slow serve (BENCH_r05: gpt itself can time out) can't eat the window
    r = _run_isolated("gpt_serve", min(300.0, _remaining()))
    errors.extend(r.get("errors") or [])
    serve = _emit_model("gpt_serve", r, "tokens/sec",
                        metric="gpt_serve_tokens_per_sec")
    if serve:
        completed += 1
        extras["gpt_serve"] = serve

    # sharded serve wave: tp=2/tp=4 tok/s + single-chip parity verdict on
    # the fake CPU mesh (correctness plumbing, not accelerator speed)
    r = _run_isolated("gpt_serve_multichip", min(240.0, _remaining()))
    errors.extend(r.get("errors") or [])
    mc = _emit_model("gpt_serve_multichip", r, "tokens/sec",
                     metric="gpt_serve_multichip_tokens_per_sec")
    if mc:
        completed += 1
        extras["gpt_serve_multichip"] = mc

    # fleet-router wave: mixed tenants over 2 replicas, affinity vs
    # no-affinity, per-class p95 TTFT / attainment / cache hit rate
    r = _run_isolated("gpt_serve_router", min(300.0, _remaining()))
    errors.extend(r.get("errors") or [])
    rt = _emit_model("gpt_serve_router", r, "tokens/sec",
                     metric="gpt_serve_router_tokens_per_sec")
    if rt:
        completed += 1
        extras["gpt_serve_router"] = rt

    # multi-tenant policy wave: mixed-priority overload, policy vs FCFS
    # per-class TTFT/attainment + starvation check, and the N-adapter
    # LoRA tok/s + zero-retrace sub-wave
    r = _run_isolated("gpt_serve_fairness", min(240.0, _remaining()))
    errors.extend(r.get("errors") or [])
    fa = _emit_model("gpt_serve_fairness", r, "tokens/sec",
                     metric="gpt_serve_fairness_tokens_per_sec")
    if fa:
        completed += 1
        extras["gpt_serve_fairness"] = fa

    # host-tier workload scenarios: long-document QA over a shared
    # corpus, and n-best parallel sampling — both over device capacity,
    # tiered vs device-only
    for name in ("gpt_serve_longdoc_qa", "gpt_serve_nbest"):
        r = _run_isolated(name, min(180.0, _remaining()))
        errors.extend(r.get("errors") or [])
        result = _emit_model(name, r, "tokens/sec",
                             metric=f"{name}_tokens_per_sec")
        if result:
            completed += 1
            extras[name] = result

    units = {"resnet50": "samples/sec", "ppyoloe": "ms", "lenet": "ms"}
    for name in ("resnet50", "ppyoloe", "lenet"):
        r = _run_isolated(name, min(300.0, _remaining()))
        errors.extend(r.get("errors") or [])
        result = _emit_model(name, r, units[name])
        if result:
            completed += 1
            extras[name] = result

    # Final line: primary metric + everything that completed in budget.
    _emit(gpt, extras, errors)
    # one completed model is a usable bench run; rc=1 only for a total wash
    return 0 if completed else 1


if __name__ == "__main__":
    sys.exit(main())
