"""Benchmark suite: flagship GPT + ResNet-50 + LeNet on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
Primary metric stays the flagship GPT train throughput; `extras` carries the
rest of the BASELINE matrix (BASELINE.json configs): resnet50 samples/sec
(config 1), LeNet step time (config 0). vs_baseline: the reference publishes
no numbers (BASELINE.md) — 1.0 = recorded placeholder until an A100 anchor
measurement exists.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# bf16 peak FLOP/s by TPU generation (public spec sheets)
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5": 459e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in sorted(_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return 197e12  # conservative default (v5e-class)


def _train_flops_per_token(cfg) -> float:
    """6*N for the matmuls (fwd+bwd) + causal attention score/value FLOPs.

    Counts USEFUL model FLOPs only — the fused CE head's backward logit
    recompute (ops/fused_ce.py) is extra hardware work that buys HBM, so it
    raises throughput but is excluded here; MFU stays honest."""
    H, L, S, V = cfg.hidden_size, cfg.num_layers, cfg.max_seq_len, cfg.vocab_size
    Ff = cfg.intermediate_size
    n_matmul = L * (4 * H * H + 2 * H * Ff) + V * H  # qkv+proj + mlp + unembed
    # causal attention: 2 matmuls of S*H per token fwd, x3 for train, /2 causal
    attn = L * 2 * S * H * 3
    return 6.0 * n_matmul + attn


def _retrying_sweep(run, batches, iters, errors, name=""):
    """Run `run(batch, iters)` per batch with OOM short-circuit + transient
    retry (remote-compile transport resets); returns {batch: value}."""
    sweep = {}
    oom = False
    for b in batches:
        for attempt in range(3):
            try:
                sweep[b] = run(b, iters)
                break
            except Exception as e:  # noqa: BLE001 — a red bench gate helps no one
                msg = f"{type(e).__name__}: {e}"
                errors.append(f"{name} batch={b} attempt={attempt + 1}: {msg[:300]}")
                if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg:
                    oom = True
                    break  # OOM is deterministic — larger batches will too
                if "tpu_compile_helper" in msg:
                    break
                time.sleep(5.0 * (attempt + 1))
        if oom:
            break
    return sweep


# ---------------------------------------------------------------------------
# GPT (primary metric)
# ---------------------------------------------------------------------------

def bench_gpt(on_tpu, errors):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.models.gpt import GPT, GPTConfig

    paddle.seed(0)
    seq = 1024 if on_tpu else 128
    if on_tpu:
        # num_heads=8 -> head_dim 128: fills the MXU's 128 contraction lanes
        # in the flash kernels (head_dim 64 runs them at half utilization —
        # measured +20% step throughput at identical model FLOPs)
        cfg = GPTConfig(
            vocab_size=32768, hidden_size=1024, num_layers=12, num_heads=8,
            max_seq_len=seq, attn_impl="flash", dtype="bfloat16",
        )
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=seq, attn_impl="xla")
    model = GPT(cfg)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    params, buffers = state_dict_arrays(model)
    opt_state = opt.init_state_arrays(params)

    def step(params, buffers, opt_state, lr, key, ids, labels):
        def loss_fn(p):
            # fused chunked CE head: loss computed without materializing
            # [b, s, vocab] logits (models/gpt.py forward labels= path)
            loss, new_buf = functional_call(
                model, p, buffers, args=(ids,), kwargs={"labels": labels},
                rng_key=key, training=True,
            )
            return loss, new_buf

        (loss, new_buf), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_arrays(params, grads, opt_state, lr)
        return loss, new_params, new_buf, new_opt

    jstep = jax.jit(step, donate_argnums=(0, 2))
    lr = jnp.asarray(1e-4, jnp.float32)
    rs = np.random.RandomState(0)

    # host snapshot: donation invalidates device buffers, so any retry after
    # a mid-step failure must re-materialize state from host copies
    snap = jax.tree_util.tree_map(np.asarray, (params, buffers, opt_state))

    def run(batch, iters):
        params, buffers, opt_state = jax.tree_util.tree_map(jnp.asarray, snap)
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
        labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
        loss, params, buffers, opt_state = jstep(
            params, buffers, opt_state, lr, rng.next_key(), ids, labels
        )
        float(np.asarray(loss))  # compile + sync
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, params, buffers, opt_state = jstep(
                params, buffers, opt_state, lr, rng.next_key(), ids, labels
            )
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        return batch * seq * iters / dt

    batches = (8, 16, 32, 64) if on_tpu else (2,)
    iters = 20 if on_tpu else 3
    sweep = _retrying_sweep(run, batches, iters, errors, name="gpt")
    if not sweep:
        return None
    best_batch = max(sweep, key=sweep.get)
    tokens_per_sec = sweep[best_batch]
    flops_per_token = _train_flops_per_token(cfg)
    peak = _peak_flops(jax.devices()[0])
    return {
        "value": round(tokens_per_sec, 1),
        "mfu": round(tokens_per_sec * flops_per_token / peak, 4),
        "batch": best_batch,
        "sweep": {str(k): round(v, 1) for k, v in sweep.items()},
    }


# ---------------------------------------------------------------------------
# ResNet-50 (BASELINE config 1)
# ---------------------------------------------------------------------------

def bench_resnet50(on_tpu, errors):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.vision.models import resnet50

    paddle.seed(0)
    model = resnet50()
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.Momentum(
        learning_rate=0.1, momentum=0.9, parameters=model.parameters()
    )
    params, buffers = state_dict_arrays(model)
    opt_state = opt.init_state_arrays(params)

    def step(params, buffers, opt_state, lr, key, images, labels):
        def loss_fn(p):
            logits, new_buf = functional_call(
                model, p, buffers, args=(images,), rng_key=key, training=True
            )
            lg = (logits if not isinstance(logits, (tuple, list)) else logits[0])
            lg = lg.astype(jnp.float32)
            lse = jax.scipy.special.logsumexp(lg, axis=-1)
            picked = jnp.take_along_axis(
                lg, labels[:, None].astype(jnp.int32), axis=-1
            )[:, 0]
            return jnp.mean(lse - picked), new_buf

        (loss, new_buf), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_arrays(params, grads, opt_state, lr)
        return loss, new_params, new_buf, new_opt

    jstep = jax.jit(step, donate_argnums=(0, 2))
    lr = jnp.asarray(0.1, jnp.float32)
    rs = np.random.RandomState(0)
    snap = jax.tree_util.tree_map(np.asarray, (params, buffers, opt_state))
    side = 224 if on_tpu else 32

    def run(batch, iters):
        params, buffers, opt_state = jax.tree_util.tree_map(jnp.asarray, snap)
        images = jnp.asarray(
            rs.rand(batch, 3, side, side).astype(np.float32), jnp.bfloat16
        )
        labels = jnp.asarray(rs.randint(0, 1000, (batch,), dtype=np.int32))
        loss, params, buffers, opt_state = jstep(
            params, buffers, opt_state, lr, rng.next_key(), images, labels
        )
        float(np.asarray(loss))
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, params, buffers, opt_state = jstep(
                params, buffers, opt_state, lr, rng.next_key(), images, labels
            )
        float(np.asarray(loss))
        return batch * iters / (time.perf_counter() - t0)

    batches = (64, 128, 256) if on_tpu else (2,)
    iters = 20 if on_tpu else 2
    sweep = _retrying_sweep(run, batches, iters, errors, name="resnet50")
    if not sweep:
        return None
    best = max(sweep, key=sweep.get)
    # ResNet-50 @224: ~4.1e9 fwd FLOPs/image (published op count), train ~3x
    train_flops = 3 * 4.1e9 if on_tpu else 3 * 4.1e9 * (side / 224) ** 2
    peak = _peak_flops(jax.devices()[0])
    return {
        "samples_per_sec": round(sweep[best], 1),
        "mfu": round(sweep[best] * train_flops / peak, 4),
        "batch": best,
        "sweep": {str(k): round(v, 1) for k, v in sweep.items()},
    }


# ---------------------------------------------------------------------------
# PP-YOLOE-s inference latency (BASELINE config 4)
# ---------------------------------------------------------------------------

def bench_ppyoloe(on_tpu, errors):
    """Batch-1 detection latency: PP-YOLOE-s net + decode + matrix NMS as
    ONE compiled program (the predictor's bucket machinery is exercised in
    tests/test_detection.py; here we time the compiled detect step itself)."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import autograd
    from paddle_tpu.core.functional import state_dict_arrays, swap_state
    from paddle_tpu.core.tensor import Tensor as _T
    from paddle_tpu.vision.models import ppyoloe_s

    paddle.seed(0)
    side = 640 if on_tpu else 64
    model = ppyoloe_s(num_classes=80)
    if on_tpu:
        model.to(dtype="bfloat16")
    model.eval()
    params, buffers = state_dict_arrays(model)

    @jax.jit
    def detect(params, images):
        with autograd.trace_mode(), swap_state(model, params, buffers):
            out, nums = model.predict(_T._from_op(images), keep_top_k=100)
        return out._array, nums._array

    rs = np.random.RandomState(0)
    img = rs.rand(1, 3, side, side).astype(np.float32)
    imgs = jnp.asarray(img, jnp.bfloat16 if on_tpu else jnp.float32)
    out = detect(params, imgs)
    jax.block_until_ready(out)
    iters = 30 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        out = detect(params, imgs)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    return {"latency_ms": round(dt * 1e3, 3), "image_size": side, "batch": 1}


# ---------------------------------------------------------------------------
# LeNet Model.fit step time (BASELINE config 0)
# ---------------------------------------------------------------------------

def bench_lenet(on_tpu, errors):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    model = paddle.Model(LeNet())
    opt = paddle.optimizer.Adam(
        learning_rate=1e-3, parameters=model.network.parameters()
    )
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.rand(64, 1, 28, 28).astype(np.float32))
    y = paddle.to_tensor(rs.randint(0, 10, (64, 1)))
    model.train_batch([x], [y])  # compile
    iters = 50
    t0 = time.perf_counter()
    for _ in range(iters):
        model.train_batch([x], [y])
    dt = (time.perf_counter() - t0) / iters
    # train_batch syncs the loss to host every step; through the remote-TPU
    # tunnel that round trip dominates tiny models. Record it so step_ms is
    # interpretable: compute time ~= step_ms - sync overhead.
    f = jax.jit(lambda a: a + 1.0)
    z = jnp.zeros(8)
    np.asarray(f(z))
    t0 = time.perf_counter()
    for _ in range(10):
        np.asarray(f(z))
    sync_ms = (time.perf_counter() - t0) / 10 * 1e3
    return {"step_ms": round(dt * 1e3, 3), "batch": 64,
            "host_sync_roundtrip_ms": round(sync_ms, 2)}


_BENCHES = {
    "gpt": lambda on_tpu, errors: bench_gpt(on_tpu, errors),
    "resnet50": lambda on_tpu, errors: bench_resnet50(on_tpu, errors),
    "lenet": lambda on_tpu, errors: bench_lenet(on_tpu, errors),
    "ppyoloe": lambda on_tpu, errors: bench_ppyoloe(on_tpu, errors),
}


def _child(name):
    """Run ONE benchmark and print its JSON on the last line."""
    import jax

    on_tpu = jax.default_backend() in ("tpu", "axon")
    errors = []
    try:
        result = _BENCHES[name](on_tpu, errors)
    except Exception as e:  # noqa: BLE001
        errors.append(f"{name}: {type(e).__name__}: {str(e)[:300]}")
        result = None
    print(json.dumps({"result": result, "errors": errors}))
    return 0


def _run_isolated(name, timeout_s=2400):
    """Each benchmark gets its own process: device memory fully released
    between benches, and one bench's OOM cannot poison the next (an
    in-process OOM leaves the PjRt allocator poisoned for later benches)."""
    import subprocess

    try:
        proc = subprocess.run(
            [sys.executable, __file__, name],
            capture_output=True, text=True, timeout=timeout_s,
        )
        for line in reversed(proc.stdout.strip().splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        return {"result": None,
                "errors": [f"{name}: no output (rc={proc.returncode}) "
                           f"{proc.stderr[-200:]}"]}
    except subprocess.TimeoutExpired:
        return {"result": None, "errors": [f"{name}: timed out after {timeout_s}s"]}
    except Exception as e:  # noqa: BLE001
        return {"result": None, "errors": [f"{name}: {type(e).__name__}: {e}"]}


def main():
    if len(sys.argv) > 1:
        return _child(sys.argv[1])

    errors = []
    extras = {}
    gpt = None
    for name in ("gpt", "resnet50", "lenet", "ppyoloe"):
        r = _run_isolated(name)
        errors.extend(r.get("errors") or [])
        if name == "gpt":
            gpt = r.get("result")
        elif r.get("result"):
            extras[name] = r["result"]

    if gpt is None:
        print(json.dumps({
            "metric": "gpt_train_tokens_per_sec_per_chip",
            "value": 0.0, "unit": "tokens/sec", "vs_baseline": 0.0,
            "errors": errors, **extras,
        }))
        return 1
    out = {
        "metric": "gpt_train_tokens_per_sec_per_chip",
        "value": gpt["value"],
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "mfu": gpt["mfu"],
        "batch": gpt["batch"],
        "sweep": gpt["sweep"],
        **extras,
    }
    if errors:
        out["errors"] = errors
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
