"""Benchmark: flagship GPT compiled train-step throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.
vs_baseline: the reference publishes no numbers (BASELINE.md); 1.0 = the
recorded target placeholder until an A100 reference measurement exists.
Extras: mfu (model flops utilization vs the chip's bf16 peak), best batch
size from the sweep, and per-batch throughput.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np

# bf16 peak FLOP/s by TPU generation (public spec sheets)
_PEAK_FLOPS = {
    "v4": 275e12,
    "v5 lite": 197e12,
    "v5e": 197e12,
    "v5": 459e12,
    "v5p": 459e12,
    "v6 lite": 918e12,
    "v6e": 918e12,
}


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower()
    for key, val in sorted(_PEAK_FLOPS.items(), key=lambda kv: -len(kv[0])):
        if key in kind:
            return val
    return 197e12  # conservative default (v5e-class)


def _train_flops_per_token(cfg) -> float:
    """6*N for the matmuls (fwd+bwd) + causal attention score/value FLOPs."""
    H, L, S, V = cfg.hidden_size, cfg.num_layers, cfg.max_seq_len, cfg.vocab_size
    Ff = cfg.intermediate_size
    n_matmul = L * (4 * H * H + 2 * H * Ff) + V * H  # qkv+proj + mlp + unembed
    # causal attention: 2 matmuls of S*H per token fwd, x3 for train, /2 causal
    attn = L * 2 * S * H * 3
    return 6.0 * n_matmul + attn


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn

    on_tpu = jax.default_backend() in ("tpu", "axon")
    paddle.seed(0)
    seq = 1024 if on_tpu else 128
    if on_tpu:
        cfg = GPTConfig(
            vocab_size=32768,
            hidden_size=1024,
            num_layers=12,
            num_heads=16,
            max_seq_len=seq,
            attn_impl="flash",
            dtype="bfloat16",
        )
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=seq, attn_impl="xla")
    model = GPT(cfg)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    params, buffers = state_dict_arrays(model)
    opt_state = opt.init_state_arrays(params)

    def step(params, buffers, opt_state, lr, key, ids, labels):
        def loss_fn(p):
            out, new_buf = functional_call(
                model, p, buffers, args=(ids,), rng_key=key, training=True
            )
            return gpt_loss_fn(out, labels), new_buf

        (loss, new_buf), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_arrays(params, grads, opt_state, lr)
        return loss, new_params, new_buf, new_opt

    jstep = jax.jit(step, donate_argnums=(0, 2))
    lr = jnp.asarray(1e-4, jnp.float32)
    rs = np.random.RandomState(0)

    # host snapshot: donation invalidates device buffers, so any retry after
    # a mid-step failure must re-materialize state from host copies
    snap = jax.tree_util.tree_map(np.asarray, (params, buffers, opt_state))

    def restore_state():
        nonlocal params, buffers, opt_state
        params, buffers, opt_state = jax.tree_util.tree_map(jnp.asarray, snap)

    def run(batch, iters):
        nonlocal params, buffers, opt_state
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
        labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
        loss, params, buffers, opt_state = jstep(
            params, buffers, opt_state, lr, rng.next_key(), ids, labels
        )
        float(np.asarray(loss))  # compile + sync
        t0 = time.perf_counter()
        for _ in range(iters):
            loss, params, buffers, opt_state = jstep(
                params, buffers, opt_state, lr, rng.next_key(), ids, labels
            )
        float(np.asarray(loss))
        dt = time.perf_counter() - t0
        return batch * seq * iters / dt

    sweep = {}
    errors = []
    batches = (8, 16, 32) if on_tpu else (2,)
    iters = 20 if on_tpu else 3
    max_attempts = 3
    oom = False
    for b in batches:
        for attempt in range(max_attempts):
            try:
                sweep[b] = run(b, iters)
                break
            except Exception as e:  # noqa: BLE001 — a red bench gate helps no one
                msg = f"{type(e).__name__}: {e}"
                errors.append(f"batch={b} attempt={attempt + 1}: {msg[:300]}")
                restore_state()
                if "RESOURCE_EXHAUSTED" in msg or "Out of memory" in msg:
                    oom = True
                    break  # OOM is deterministic — larger batches will too
                if "tpu_compile_helper" in msg:
                    break  # compile-helper failures are deterministic too
                # transient (remote-compile transport, tunnel resets): back
                # off and retry; the compile cache makes retries cheap
                time.sleep(5.0 * (attempt + 1))
        if oom:
            break

    if not sweep:
        print(
            json.dumps(
                {
                    "metric": "gpt_train_tokens_per_sec_per_chip",
                    "value": 0.0,
                    "unit": "tokens/sec",
                    "vs_baseline": 0.0,
                    "errors": errors,
                }
            )
        )
        return 1
    best_batch = max(sweep, key=sweep.get)
    tokens_per_sec = sweep[best_batch]

    flops_per_token = _train_flops_per_token(cfg)
    peak = _peak_flops(jax.devices()[0])
    mfu = tokens_per_sec * flops_per_token / peak

    out = {
        "metric": "gpt_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/sec",
        "vs_baseline": 1.0,
        "mfu": round(mfu, 4),
        "batch": best_batch,
        "sweep": {str(k): round(v, 1) for k, v in sweep.items()},
    }
    if errors:
        out["errors"] = errors
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
