"""Benchmark: flagship GPT compiled train-step throughput on the local chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline: the reference publishes no numbers (BASELINE.md); 1.0 = the
recorded target placeholder until an A100 reference measurement exists.
"""
from __future__ import annotations

import json
import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.core import rng
    from paddle_tpu.core.functional import state_dict_arrays
    from paddle_tpu.models.gpt import GPT, GPTConfig, gpt_loss_fn

    on_tpu = jax.default_backend() in ("tpu", "axon")
    paddle.seed(0)
    # GPT-small-ish sized to fit one chip comfortably in bf16
    cfg = GPTConfig(
        vocab_size=32768,
        hidden_size=1024,
        num_layers=12,
        num_heads=16,
        max_seq_len=1024,
        attn_impl="flash" if on_tpu else "xla",
        dtype="bfloat16",
    )
    batch, seq = (8, 1024) if on_tpu else (2, 128)
    if not on_tpu:
        cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                        num_heads=8, max_seq_len=seq, attn_impl="xla")
    model = GPT(cfg)
    model.to(dtype="bfloat16")
    opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=model.parameters())

    params, buffers = state_dict_arrays(model)
    opt_state = opt.init_state_arrays(params)

    from paddle_tpu.core.functional import functional_call

    def step(params, buffers, opt_state, lr, key, ids, labels):
        def loss_fn(p):
            out, new_buf = functional_call(
                model, p, buffers, args=(ids,), rng_key=key, training=True
            )
            return gpt_loss_fn(out, labels), new_buf

        (loss, new_buf), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        new_params, new_opt = opt.apply_gradients_arrays(params, grads, opt_state, lr)
        return loss, new_params, new_buf, new_opt

    jstep = jax.jit(step, donate_argnums=(0, 2))

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
    labels = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, seq), dtype=np.int32))
    lr = jnp.asarray(1e-4, jnp.float32)

    # warmup / compile
    loss, params, buffers, opt_state = jstep(params, buffers, opt_state, lr, rng.next_key(), ids, labels)
    float(np.asarray(loss))

    iters = 20 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        loss, params, buffers, opt_state = jstep(
            params, buffers, opt_state, lr, rng.next_key(), ids, labels
        )
    float(np.asarray(loss))  # sync
    dt = time.perf_counter() - t0

    tokens_per_sec = batch * seq * iters / dt
    print(
        json.dumps(
            {
                "metric": "gpt_train_tokens_per_sec_per_chip",
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/sec",
                "vs_baseline": 1.0,
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
