// Package paddle is the Go client for the paddle_tpu serving C ABI
// (reference parity: /root/reference/paddle/fluid/inference/goapi/ —
// config.go / predictor.go wrap the C API via cgo; this file wraps
// csrc/predictor_capi.cc's PD_* surface the same way).
//
// Build: the shared library comes from
//
//	python -c "import paddle_tpu.inference.capi as c; print(c.build_capi())"
//
// then
//
//	CGO_CFLAGS="-I${REPO}/goapi" CGO_LDFLAGS="-L${LIBDIR} -lpd_capi" go build
//
// Thread-safety matches the C ABI: calls on one Predictor serialize on its
// handle; distinct Predictors run concurrently.
package paddle

/*
#cgo LDFLAGS: -lpd_capi
#include <stdint.h>
#include <stdlib.h>

extern const char* PD_GetLastError();
extern void* PD_PredictorCreate(const char* model_path);
extern int PD_PredictorRun(void* handle, const float* data,
                           const int64_t* shape, int ndim);
extern int PD_GetOutputNumDims(void* handle, int idx);
extern int PD_GetOutputShape(void* handle, int idx, int64_t* shape_out);
extern int64_t PD_GetOutputNumel(void* handle, int idx);
extern int PD_GetOutputData(void* handle, int idx, float* out);
extern void PD_PredictorDestroy(void* handle);
*/
import "C"

import (
	"errors"
	"fmt"
	"runtime"
	"unsafe"
)

// Predictor executes a jit.save'd paddle_tpu artifact.
type Predictor struct {
	handle unsafe.Pointer
}

func lastError() error {
	return errors.New(C.GoString(C.PD_GetLastError()))
}

// NewPredictor loads the artifact at modelPath (the path passed to
// paddle_tpu.jit.save, without extension).
//
// PD_GetLastError is thread-local in the C ABI, so the failing call and the
// error fetch must run on the same OS thread: every wrapper pins its
// goroutine with runtime.LockOSThread for the call + error read.
func NewPredictor(modelPath string) (*Predictor, error) {
	runtime.LockOSThread()
	defer runtime.UnlockOSThread()
	cPath := C.CString(modelPath)
	defer C.free(unsafe.Pointer(cPath))
	h := C.PD_PredictorCreate(cPath)
	if h == nil {
		return nil, lastError()
	}
	p := &Predictor{handle: h}
	runtime.SetFinalizer(p, func(p *Predictor) { p.Destroy() })
	return p, nil
}

// Run feeds one float32 tensor of the given shape and returns every output
// as (data, shape) pairs.
func (p *Predictor) Run(data []float32, shape []int64) ([][]float32, [][]int64, error) {
	if p.handle == nil {
		return nil, nil, errors.New("predictor destroyed")
	}
	if len(data) == 0 || len(shape) == 0 {
		return nil, nil, errors.New("empty input data or shape")
	}
	numel := int64(1)
	for _, d := range shape {
		numel *= d
	}
	// the C side reads shape-product floats from &data[0]
	if numel != int64(len(data)) {
		return nil, nil, fmt.Errorf(
			"data length %d does not match shape product %d", len(data), numel)
	}
	// the finalizer set in NewPredictor may otherwise destroy the handle
	// mid-call once p's last Go reference (the p.handle read above) is gone
	defer runtime.KeepAlive(p)
	runtime.LockOSThread()
	defer runtime.UnlockOSThread()
	nOut := C.PD_PredictorRun(
		p.handle,
		(*C.float)(unsafe.Pointer(&data[0])),
		(*C.int64_t)(unsafe.Pointer(&shape[0])),
		C.int(len(shape)),
	)
	if nOut < 0 {
		return nil, nil, lastError()
	}
	outs := make([][]float32, int(nOut))
	shapes := make([][]int64, int(nOut))
	for i := 0; i < int(nOut); i++ {
		nd := C.PD_GetOutputNumDims(p.handle, C.int(i))
		if nd < 0 {
			return nil, nil, lastError()
		}
		shp := make([]int64, int(nd))
		if nd > 0 {
			if C.PD_GetOutputShape(p.handle, C.int(i),
				(*C.int64_t)(unsafe.Pointer(&shp[0]))) < 0 {
				return nil, nil, lastError()
			}
		}
		numel := C.PD_GetOutputNumel(p.handle, C.int(i))
		if numel < 0 { // e.g. handle destroyed by a concurrent goroutine
			return nil, nil, lastError()
		}
		buf := make([]float32, int64(numel))
		if numel > 0 {
			if C.PD_GetOutputData(p.handle, C.int(i),
				(*C.float)(unsafe.Pointer(&buf[0]))) != 0 {
				return nil, nil, lastError()
			}
		}
		outs[i] = buf
		shapes[i] = shp
	}
	return outs, shapes, nil
}

// Destroy releases the native handle (also registered as a finalizer).
func (p *Predictor) Destroy() {
	if p.handle != nil {
		C.PD_PredictorDestroy(p.handle)
		p.handle = nil
	}
}
