"""Lifecycle tracing + engine step timeline (serving/trace.py).

Acceptance criteria from the observability issue:

- the exported JSON is valid Chrome/Perfetto trace-event format and a
  known scenario produces the expected span names (the schema canary —
  drift fails CI, not a user's Perfetto import);
- spans nest and close for every interleaving of preempt/abort/COW
  (churn harness reused from tests/test_prefix_cache.py): every traced
  request that terminates gets exactly ONE closing ``request`` span,
  phase children sit inside their ``step`` parent;
- the ring buffer never grows past its bound;
- tracing disabled is byte-identical output to the untraced path (and
  `engine.tracer` is None — the hook sites are pointer tests, nothing
  else);
- TTFT/queue-wait spans agree with ServingMetrics quantiles;
- satellites: the per-request JSON summary log line, and the Prometheus
  exposition's `# HELP`/`_count`/`_sum` contract.
"""
import json
import logging

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import LLMEngine
from paddle_tpu.serving.trace import (PID_ENGINE, PID_REQUESTS, TID_STEPS,
                                      EngineTracer)

_PH = {"X", "i", "M"}


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(model, **kw)


def _events(engine, name=None, ph=None):
    evs = engine.tracer.chrome_trace()["traceEvents"]
    if name is not None:
        evs = [e for e in evs if e["name"] == name]
    if ph is not None:
        evs = [e for e in evs if e["ph"] == ph]
    return evs


def _validate_trace_event_json(trace):
    """Every structural property a Perfetto import depends on."""
    json.loads(json.dumps(trace))  # JSON-serializable end to end
    assert isinstance(trace["traceEvents"], list)
    for ev in trace["traceEvents"]:
        assert ev["ph"] in _PH, ev
        assert isinstance(ev["name"], str) and ev["name"], ev
        assert isinstance(ev["pid"], int) and isinstance(ev["tid"], int), ev
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0, ev
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0, ev
        if ev["ph"] == "M":
            assert ev["name"] in ("process_name", "thread_name"), ev
            assert ev["args"]["name"], ev


# -- schema canary (CI gate against trace-format drift) ---------------------

def test_trace_schema_canary(model):
    """A known scenario (two requests, one multi-chunk prefill, greedy
    decode) must export valid trace-event JSON containing exactly the
    span vocabulary the docs and the Perfetto workflow rely on."""
    engine = _engine(model, prefill_chunk=8, trace=1.0)
    engine.generate(_prompts((20, 7), seed=1), max_new_tokens=4)
    trace = engine.tracer.chrome_trace()
    _validate_trace_event_json(trace)

    names = {e["name"] for e in trace["traceEvents"]}
    # engine step timeline: step spans + all five phase children
    assert {"step[mixed]", "step[decode]"} <= names
    assert {"plan", "build", "dispatch", "sync", "emit"} <= names
    # request lifecycle span tree
    assert {"enqueue", "queued", "prefill_chunk", "decode", "ttft",
            "request"} <= names
    # track metadata survives export
    assert {"process_name", "thread_name"} <= names

    # the lifecycle spans live on request lanes, the timeline on engine 0
    for e in trace["traceEvents"]:
        if e["name"] in ("queued", "request", "ttft", "decode",
                         "prefill_chunk", "enqueue"):
            assert e["pid"] == PID_REQUESTS
            assert e["args"]["request_id"] is not None
        if e["name"].startswith("step[") or e["name"] in (
                "plan", "build", "dispatch", "sync", "emit"):
            assert e["pid"] == PID_ENGINE and e["tid"] == TID_STEPS
    # step spans carry the batch composition the issue asks for
    step = next(e for e in trace["traceEvents"]
                if e["name"] == "step[mixed]")
    for key in ("step", "kind", "decode_rows", "prefill_rows",
                "spec_lanes", "fed_tokens", "emitted_tokens"):
        assert key in step["args"], step["args"]
    assert trace["otherData"]["dropped_events"] == 0


def test_phases_nest_inside_their_step(model):
    engine = _engine(model, prefill_chunk=8, trace=1.0)
    engine.generate(_prompts((20, 9), seed=2), max_new_tokens=4)
    steps = {e["args"]["step"]: e for e in _events(engine, ph="X")
             if e["name"].startswith("step[")}
    phases = [e for e in _events(engine, ph="X")
              if e["name"] in ("plan", "build", "dispatch", "sync", "emit")]
    assert steps and phases
    eps = 1e-3  # ts/dur are rounded to 3 decimals (ns resolution)
    for ph in phases:
        parent = steps[ph["args"]["step"]]
        assert ph["ts"] >= parent["ts"] - eps, (ph, parent)
        assert (ph["ts"] + ph["dur"]
                <= parent["ts"] + parent["dur"] + eps), (ph, parent)


# -- spans close under churn (preempt/abort/COW interleavings) --------------

def test_spans_close_under_churn(model):
    """The prefix-cache churn harness with tracing on: shared prefixes
    through a tiny pool force hits, COW, preemptions, and aborts; every
    traced request must still close with exactly one ``request`` span
    whose reason matches how it terminated."""
    rs = np.random.RandomState(0)
    engine = LLMEngine(model, block_size=4, num_blocks=10, max_batch=3,
                       max_seq_len=64, prefill_chunk=8, trace=1.0)
    prefixes = [rs.randint(0, 128, (8,)).tolist() for _ in range(3)]
    all_rids, aborted = [], set()
    for rnd in range(4):
        reqs = []
        for _ in range(rs.randint(2, 5)):
            p = (prefixes[rs.randint(len(prefixes))]
                 + rs.randint(0, 128, (rs.randint(0, 9),)).tolist())
            reqs.append(engine.add_request(
                p, max_new_tokens=int(rs.randint(2, 8))))
        doomed = set(rs.choice(reqs, size=len(reqs) // 3, replace=False)
                     .tolist()) if len(reqs) >= 3 else set()
        steps = 0
        while engine.has_unfinished():
            engine.step()
            steps += 1
            if steps == 2:
                for rid in doomed:
                    if engine.abort(rid):   # may already have finished
                        aborted.add(rid)
        all_rids.extend(reqs)
        for rid in reqs:
            if rid not in aborted:
                engine.release(rid)

    closes = {}
    for e in _events(engine, name="request"):
        rid = e["args"]["request_id"]
        assert rid not in closes, f"request {rid} closed twice"
        closes[rid] = e
    assert set(closes) == set(all_rids)  # every request closed exactly once
    for rid, e in closes.items():
        want = "aborted" if rid in aborted else "finished"
        assert e["args"]["reason"] == want, (rid, e["args"])
        # the span tree is consistent: outputs in the summary match reality
        assert e["args"]["output_tokens"] >= (0 if rid in aborted else 1)
    # the churn actually exercised the mechanisms it claims to
    names = {e["name"] for e in _events(engine)}
    assert "cow" in names, "no COW instant recorded"
    c = engine.metrics.counters
    assert c.get("requests_aborted", 0) > 0
    # preemptions happened iff preempt instants were recorded
    assert ("preempt" in names) == (c.get("preemptions", 0) > 0)
    _validate_trace_event_json(engine.tracer.chrome_trace())


def test_ring_buffer_never_grows_past_bound(model):
    engine = _engine(model, trace=1.0, trace_buffer=64)
    for wave in range(3):
        engine.generate(_prompts((12, 9, 7), seed=wave), max_new_tokens=8)
    tr = engine.tracer
    assert len(tr.events) == 64          # full, not past capacity
    assert tr.dropped > 0                # the ring actually wrapped
    assert tr.capacity == 64
    # export still valid after wrap (metadata lives outside the ring)
    trace = tr.chrome_trace()
    _validate_trace_event_json(trace)
    assert any(e["ph"] == "M" for e in trace["traceEvents"])


# -- disabled tracing is free ----------------------------------------------

def test_disabled_tracing_is_byte_identical_and_absent(model, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_TRACE", raising=False)
    prompts = _prompts((13, 6, 20), seed=3)
    off = _engine(model, prefill_chunk=8)
    assert off.tracer is None            # default: no tracer object at all
    out_off = off.generate(prompts, max_new_tokens=6)
    on = _engine(model, prefill_chunk=8, trace=1.0)
    out_on = on.generate(prompts, max_new_tokens=6)
    assert out_on == out_off             # tracing never changes tokens
    assert len(on.tracer.events) > 0


def test_trace_env_knob(model, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
    assert _engine(model).tracer is not None
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0.25")
    eng = _engine(model)
    assert eng.tracer is not None and eng.tracer.sample == 0.25
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
    assert _engine(model).tracer is None
    monkeypatch.setenv("PADDLE_TPU_TRACE_BUF", "32")
    monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
    assert _engine(model).tracer.capacity == 32


def test_sampling_fraction_and_per_request_override(model):
    engine = _engine(model, trace=0.25)
    prompts = _prompts((5,) * 8, seed=4)
    rids = [engine.add_request(p, max_new_tokens=2) for p in prompts]
    traced = [r for r in rids if engine.get_request(r).traced]
    assert len(traced) == 2              # deterministic: every 4th request
    while engine.has_unfinished():
        engine.step()
    # per-request override beats the sampler in both directions
    forced = engine.add_request(_prompts((5,), seed=5)[0],
                                max_new_tokens=2, trace=True)
    assert engine.get_request(forced).traced
    denied_ids = [engine.add_request(p, max_new_tokens=2, trace=False)
                  for p in _prompts((4,) * 8, seed=6)]
    assert not any(engine.get_request(r).traced for r in denied_ids)
    while engine.has_unfinished():
        engine.step()
    closed = {e["args"]["request_id"] for e in _events(engine,
                                                       name="request")}
    assert forced in closed
    assert closed.isdisjoint(denied_ids)


# -- agreement with ServingMetrics -----------------------------------------

def test_ttft_and_queue_wait_spans_agree_with_metrics(model):
    """The acceptance criterion: the trace's TTFT spans are the SAME
    measurements ServingMetrics aggregates into its quantiles — same
    clock, same anchors — so span durations must reproduce the metric
    summary to float precision, and queue waits must be consistent with
    admission (inside the request span, before its first token)."""
    engine = _engine(model, trace=1.0, max_batch=2)
    engine.generate(_prompts((9, 14, 6, 11), seed=7), max_new_tokens=5)
    ttft_spans = sorted(e["dur"] / 1e6 for e in _events(engine, name="ttft"))
    lat = engine.metrics.latency_summary()["ttft"]
    assert len(ttft_spans) == lat["count"] == 4
    assert ttft_spans[-1] == pytest.approx(lat["max_ms"] / 1e3, abs=2e-6)
    assert sum(ttft_spans) == pytest.approx(
        lat["total_ms"] / 1e3, abs=1e-5)
    p95 = lat["p95_ms"] / 1e3
    assert any(abs(s - p95) < 2e-6 for s in ttft_spans)
    # queue-wait spans: start at arrival (request span start), end before
    # the request's first token lands
    reqs = {e["args"]["request_id"]: e for e in _events(engine,
                                                        name="request")}
    ttfts = {e["args"]["request_id"]: e for e in _events(engine,
                                                         name="ttft")}
    queued = [e for e in _events(engine, name="queued")]
    assert len(queued) == 4
    for q in queued:
        rid = q["args"]["request_id"]
        assert q["ts"] == pytest.approx(reqs[rid]["ts"], abs=1e-3)
        assert q["ts"] + q["dur"] <= ttfts[rid]["ts"] + ttfts[rid]["dur"] \
            + 1e-3


# -- satellite: per-request summary log ------------------------------------

def test_request_log_lines(model, caplog):
    engine = _engine(model, request_log=True, prefill_chunk=8)
    with caplog.at_level(logging.INFO, logger="paddle_tpu.serving.request"):
        rids = [engine.add_request(p, max_new_tokens=3)
                for p in _prompts((18, 5), seed=8)]
        victim = engine.add_request(_prompts((6,), seed=9)[0],
                                    max_new_tokens=3)
        engine.step()
        engine.abort(victim)
        while engine.has_unfinished():
            engine.step()
    recs = [json.loads(r.message) for r in caplog.records
            if r.name == "paddle_tpu.serving.request"]
    assert len(recs) == 3                # one line per finish/abort, ever
    by_id = {r["request_id"]: r for r in recs}
    for rid in rids:
        r = by_id[str(rid)]
        assert r["reason"] == "finished"
        assert r["output_tokens"] == 3
        assert r["ttft_ms"] > 0 and r["queue_wait_ms"] >= 0
        assert r["ttft_ms"] <= r["total_ms"]
    assert by_id[str(victim)]["reason"] == "aborted"
    from paddle_tpu.serving import slo as slo_mod

    phase_keys = {f"phase_{p}_ms" for p in slo_mod.PHASES}
    for r in recs:                       # the full greppable schema
        assert {"event", "request_id", "reason", "prompt_tokens",
                "output_tokens", "prefix_hit_tokens",
                "spec_accepted_tokens", "preemptions", "queue_wait_ms",
                "ttft_ms", "tpot_ms", "total_ms", "tenant", "priority",
                "deadline_s", "deadline"} <= set(r)
        # the line's phase fields are derived from the ledger's phase
        # vocabulary (slo.PHASES) — line and ledger cannot drift — and
        # the decomposition sums to the line's own total_ms
        assert phase_keys <= set(r)
        assert sum(r[k] for k in phase_keys) == pytest.approx(
            r["total_ms"], abs=0.05)


def test_request_log_off_by_default(model, caplog, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_REQUEST_LOG", raising=False)
    engine = _engine(model)
    with caplog.at_level(logging.INFO, logger="paddle_tpu.serving.request"):
        engine.generate(_prompts((5,), seed=10), max_new_tokens=2)
    assert not [r for r in caplog.records
                if r.name == "paddle_tpu.serving.request"]


# -- satellite: Prometheus exposition contract ------------------------------

def test_prometheus_help_type_and_count_sum(model):
    engine = _engine(model)
    engine.generate(_prompts((9, 5), seed=11), max_new_tokens=4)
    text = engine.metrics.prometheus_text()
    lines = text.splitlines()
    # every TYPE line is preceded by its HELP line, for every family
    for i, ln in enumerate(lines):
        if ln.startswith("# TYPE "):
            metric = ln.split()[2]
            assert lines[i - 1].startswith(f"# HELP {metric} "), ln
    # latency families expose _count/_sum so scrapers can build true rates
    for fam in ("ttft_seconds", "decode_step_seconds"):
        assert f"# HELP paddle_tpu_serving_{fam} " in text
        assert f"paddle_tpu_serving_{fam}_count " in text
        assert f"paddle_tpu_serving_{fam}_sum " in text
    # the bounded-window caveat is documented in the exposition itself
    assert "most recent 4096 observations" in text
    # counters keep their HELP too
    assert "# HELP paddle_tpu_serving_generated_tokens_total " in text


# -- tracer unit: lanes recycle, ids stay attributable ----------------------

def test_request_lanes_recycle_bounded_metadata():
    tracer = EngineTracer(capacity=1 << 14, sample=1.0)

    class _Req:
        def __init__(self, rid):
            self.request_id = rid
            self.prompt_ids = [1]
            self.max_new_tokens = 1
            self.output_ids = []
            self.arrival_time = tracer.epoch
            self.prefix_hit_tokens = 0
            self.preemptions = 0
            self.spec_accepted = 0

    for i in range(600):                 # > the 256-lane pool
        r = _Req(f"r{i}")
        tracer.begin_request(r)
        tracer.end_request(r, "finished")
    assert not tracer._lane_of           # every lane returned
    meta = [e for e in tracer.chrome_trace()["traceEvents"]
            if e["ph"] == "M"]
    assert len(meta) <= 256 + 8          # O(lanes), not O(requests)
    spans = [e for e in tracer.chrome_trace()["traceEvents"]
             if e["name"] == "request"]
    assert {e["args"]["request_id"] for e in spans} >= {"r599"}
