"""MFU/goodput accounting (profiler/flops.py), lifted from bench.py.

The acceptance criterion: bench's gpt-train MFU is UNCHANGED after the
lift — the pre-lift formulas are restated here verbatim as plain
arithmetic and the module must reproduce them (to well past the 4
decimal places the BENCH json rounds to), for both bench GPT configs and
every peak-flops registry entry.
"""
import json
import os
import tempfile

import numpy as np
import pytest

from paddle_tpu.profiler import flops


class _Dev:
    def __init__(self, kind):
        self.device_kind = kind


def _pre_lift_flops_per_token(H, L, S, V, Ff):
    """bench.py's _train_flops_per_token as it stood before the lift."""
    n_matmul = L * (4 * H * H + 2 * H * Ff) + V * H
    attn = L * 2 * S * H * 3
    return 6.0 * n_matmul + attn


def test_gpt_train_flops_matches_pre_lift_formula():
    from paddle_tpu.models.gpt import GPTConfig

    # both bench_gpt configs: the TPU flagship and the CPU fallback
    cfgs = [
        GPTConfig(vocab_size=32768, hidden_size=1024, num_layers=12,
                  num_heads=8, max_seq_len=1024),
        GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                  num_heads=8, max_seq_len=128),
    ]
    for cfg in cfgs:
        want = _pre_lift_flops_per_token(
            cfg.hidden_size, cfg.num_layers, cfg.max_seq_len,
            cfg.vocab_size, cfg.intermediate_size)
        assert flops.gpt_train_flops_per_token(cfg) == want


def test_bench_mfu_unchanged_to_4_decimals():
    """End to end: round(tok/s * flops/token / peak, 4) — the exact MFU
    arithmetic bench.py emits — through the lifted module, at the r03
    throughput on the flagship config."""
    from paddle_tpu.models.gpt import GPTConfig

    cfg = GPTConfig(vocab_size=32768, hidden_size=1024, num_layers=12,
                    num_heads=8, max_seq_len=1024)
    tokens_per_sec = 82400.0  # the r03 number
    fpt = _pre_lift_flops_per_token(1024, 12, 1024, 32768,
                                    cfg.intermediate_size)
    for kind, peak in (("TPU v5e", 197e12), ("TPU v4", 275e12),
                       ("unknown accelerator", 197e12)):
        want = round(tokens_per_sec * fpt / peak, 4)
        got = round(flops.mfu(tokens_per_sec,
                              flops.gpt_train_flops_per_token(cfg),
                              device=_Dev(kind)), 4)
        assert got == want


def test_peak_flops_registry_matches_pre_lift():
    pre_lift = {
        "TPU v4": 275e12,
        "TPU v5 lite": 197e12,
        "TPU v5e": 197e12,
        "TPU v5p": 459e12,        # longest-key-wins: v5p beats v5
        "TPU v6e": 918e12,
        "TPU v6 lite": 918e12,
        "anything else": 197e12,  # conservative default
    }
    for kind, want in pre_lift.items():
        assert flops.peak_flops(_Dev(kind)) == want
        assert flops.peak_flops(kind) == want      # plain strings work too


def test_resnet50_flops_matches_pre_lift():
    assert flops.resnet50_train_flops_per_image(224) == 3 * 4.1e9
    assert flops.resnet50_train_flops_per_image(32) == \
        3 * 4.1e9 * (32 / 224) ** 2


def test_bench_delegates_to_flops_module():
    """bench.py is a CONSUMER now: its wrappers must return exactly what
    the module does (the lift left no second copy of the math)."""
    import importlib.util

    from paddle_tpu.models.gpt import GPTConfig

    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(os.path.dirname(__file__), "..", "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    cfg = GPTConfig(vocab_size=1024, hidden_size=256, num_layers=4,
                    num_heads=8, max_seq_len=128)
    assert bench._train_flops_per_token(cfg) == \
        flops.gpt_train_flops_per_token(cfg)
    assert bench._peak_flops(_Dev("TPU v5p")) == flops.peak_flops("v5p")


# -- goodput over recorded train_step spans ---------------------------------

def _trace(durs_ms, gap_ms=1.0):
    evs, t = [], 0.0
    for i, d in enumerate(durs_ms):
        evs.append({"name": "train_step", "ph": "X", "pid": 1, "tid": 0,
                    "ts": t * 1e3, "dur": d * 1e3, "args": {"step": i}})
        t += d + gap_ms
    return {"traceEvents": evs}


def test_goodput_summary_math():
    tr = _trace([10.0] * 9 + [30.0], gap_ms=0.0)   # 9x10ms + 1x30ms back-to-back
    g = flops.goodput_summary(tr, tokens_per_step=1000,
                              flops_per_token=1e9, peak=1e12)
    assert g["steps"] == 10
    assert g["span_s"] == pytest.approx(0.120)
    assert g["step_p50_ms"] == pytest.approx(10.0)
    assert g["step_p95_ms"] == pytest.approx(30.0)   # nearest-rank: 10th of 10
    assert g["step_max_ms"] == pytest.approx(30.0)
    assert g["step_mean_ms"] == pytest.approx(12.0)
    assert g["tokens_per_sec"] == pytest.approx(10 * 1000 / 0.120)
    assert g["mfu"] == pytest.approx(g["tokens_per_sec"] * 1e9 / 1e12)


def test_goodput_summary_empty_and_path_roundtrip(tmp_path):
    assert flops.goodput_summary({"traceEvents": []})["steps"] == 0
    p = tmp_path / "t.json"
    p.write_text(json.dumps(_trace([5.0, 5.0])))
    assert flops.goodput_summary(str(p))["steps"] == 2


# -- time-in-collectives from xplane categories -----------------------------

def test_collective_time_from_capture(tmp_path):
    from paddle_tpu.profiler._xplane import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    ops = (("fusion.1", 0, 10), ("all-reduce.2", 10, 4),
           ("reduce-scatter.3", 14, 2), ("matmul.4", 16, 4))
    line = plane.lines.add()
    line.name = "XLA Ops"
    line.timestamp_ns = 0
    for mid, (name, off_ms, dur_ms) in enumerate(ops, start=1):
        plane.event_metadata[mid].id = mid
        plane.event_metadata[mid].name = name
        ev = line.events.add()
        ev.metadata_id = mid
        ev.offset_ps = int(off_ms * 1e9)
        ev.duration_ps = int(dur_ms * 1e9)
    path = str(tmp_path / "cap.xplane.pb")
    with open(path, "wb") as f:
        f.write(xs.SerializeToString())

    ct = flops.collective_time(path)
    st = ct["/device:TPU:0"]
    assert st["total_ms"] == pytest.approx(20.0)
    assert st["collective_ms"] == pytest.approx(6.0)
    assert st["fraction"] == pytest.approx(0.3)
    names = [n for n, _ in st["by_category"]]
    assert "all-reduce" in names and "reduce-scatter" in names
    assert "fusion" not in names and "matmul" not in names
