"""Int8 end-to-end serving: quantized KV arena + AdaRound weights +
EQuARX quantized all-reduce, behind QUALITY GATES.

The contract this file enforces (README "Quantization"): int8 is only
shippable because these gates pass — a greedy serve on the quantized
arena must be near-token-identical to the f32 serve on the SAME mixed
wave (chunked prefill + decode + speculative drafts + prefix-cache
hits), single-chip and tp=2 with the quantized collectives on; AdaRound
weight quantization must hold the held-out NLL delta; and the capacity
claim (same ``kv_hbm_bytes`` admits ~4x the f32 blocks) must be real.
Around the anchor: churn-sweep scale-sidecar invariants, the tier's
scale-carrying export/import, the /healthz+/metrics kv_dtype surfaces,
and the PR 16 async-vs-sync drive race guard.
"""
import asyncio

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import AsyncLLMEngine, LLMEngine, kv_capacity_blocks

VOCAB = 128

# quality gates, deliberately stated once: at least this fraction of
# greedy tokens must match f32 exactly (int8 KV rounds logits ~0.1%, so
# runs match until a near-tie flips — on the tiny config they match
# token-for-token, but the gate is what we promise, not bitwiseness)
PARITY_RATE = 0.9
# AdaRound held-out mean-NLL may exceed f32 by at most this (nats/token)
NLL_DELTA = 0.05


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=VOCAB, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=96, attn_impl="xla",
                    dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _no_env_knobs(monkeypatch):
    """Developer env must not flip dtypes/meshes under the gates."""
    for var in ("PADDLE_TPU_TP", "PADDLE_TPU_KV_DTYPE",
                "PADDLE_TPU_QUANT_ALLREDUCE", "PADDLE_TPU_HOST_KV_BLOCKS"):
        monkeypatch.delenv(var, raising=False)


def _wave_prompts(seed=0):
    """The acceptance mixed wave: two prompts sharing a cached prefix,
    one longer than the prefill chunk, one with drafter fodder."""
    rs = np.random.RandomState(seed)
    shared = rs.randint(0, VOCAB, (24,)).tolist()
    motif = [7, 11, 13]
    return shared, [
        shared + rs.randint(0, VOCAB, (4,)).tolist(),
        shared + rs.randint(0, VOCAB, (6,)).tolist(),
        rs.randint(0, VOCAB, (40,)).tolist(),              # > prefill_chunk
        rs.randint(0, VOCAB, (5,)).tolist() + motif * 4,   # drafter fodder
    ]


def _serve_wave(model, **kw):
    """Warm the prefix cache, then serve the wave with spec decoding on;
    returns (engine, outputs)."""
    shared, prompts = _wave_prompts()
    kw.setdefault("mesh", 1)
    eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8, spec_decoding=True, num_spec_tokens=3,
                    **kw)
    eng.generate([shared], max_new_tokens=2, temperature=0.0)
    outs = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
    return eng, outs


@pytest.fixture(scope="module")
def ref_wave(model):
    """The f32 single-chip reference serve every gate compares against."""
    eng, outs = _serve_wave(model)
    return eng, outs


def _parity_rate(outs, ref):
    toks = [t for row in outs for t in row]
    want = [t for row in ref for t in row]
    assert len(toks) == len(want)
    return np.mean([a == b for a, b in zip(toks, want)])


# -- the tentpole gates: greedy parity on the mixed wave ----------------------


def test_int8_kv_greedy_parity_mixed_wave(model, ref_wave):
    _, ref = ref_wave
    eng, outs = _serve_wave(model, kv_dtype="int8")
    rate = _parity_rate(outs, ref)
    assert rate >= PARITY_RATE, (rate, outs, ref)
    # the dtype switch is visible on every observability surface
    assert eng.pool.kv_dtype == "int8"
    assert eng.pool_stats()["kv_dtype"] == "int8"
    assert eng.mesh_info()["kv_dtype"] == "int8"
    assert eng.metrics.infos["kv"] == {"dtype": "int8"}
    # one program per width bucket still holds — quantization must not
    # fork the program table
    assert eng.metrics.counters["jit_traces"] <= eng.expected_program_count()


def test_int8_kv_tp2_parity_with_quantized_allreduce(model, ref_wave):
    """tp=2 with BOTH int8 stories on: quantized arena + EQuARX
    RowParallel all-reduces. The gate is against the single-chip f32
    reference, so the collective quantization is inside the gate too."""
    _, ref = ref_wave
    eng, outs = _serve_wave(model, mesh=2, kv_dtype="int8",
                            quant_allreduce=True)
    rate = _parity_rate(outs, ref)
    assert rate >= PARITY_RATE, (rate, outs, ref)
    assert eng.quant_collectives == {"attn_proj", "ffn_fc2"}
    assert eng.mesh_info()["tp_degree"] == 2


def test_int8_kv_spec_and_prefix_determinism(model):
    """Speculative accept/rollback and prefix-cache hits must be
    requantization-safe: the same wave served twice (second run all
    prefix hits) is token-identical — rollback leaves accepted tokens'
    scales intact, and a cached block's payload is never re-scattered."""
    eng, first = _serve_wave(model, kv_dtype="int8")
    shared, prompts = _wave_prompts()
    again = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
    assert first == again
    assert eng.metrics.counters.get("prefix_cache_hit_tokens", 0) > 0


# -- capacity: the reason to ship int8 ----------------------------------------


def test_int8_capacity_vs_f32_at_same_budget(model):
    """Same kv_hbm_bytes must admit ~4x the f32 blocks (minus the scale
    sidecar overhead) — checked both on the sizing formula and on live
    engines, whose bytes-per-block gauge must agree with the formula."""
    cfg = model.cfg
    budget = 1 << 20
    kw = dict(block_size=8, max_batch=4, max_seq_len=96,
              kv_hbm_bytes=budget)
    eng_f = LLMEngine(model, **kw)
    eng_q = LLMEngine(model, kv_dtype="int8", **kw)
    assert eng_q.pool.num_blocks >= 2 * eng_f.pool.num_blocks
    assert eng_q.pool.bytes_per_block() < eng_f.pool.bytes_per_block() / 2
    # formula twin (serving/sharded.py): scales cost 2*L*H*4 per block
    blocks = kv_capacity_blocks(budget, cfg.num_layers, cfg.num_heads, 8,
                                cfg.hidden_size // cfg.num_heads, 1,
                                scale_itemsize=4)
    assert eng_q.pool.num_blocks == blocks
    # and the arena really is int8 + f32 sidecars
    assert eng_q.pool.k.dtype == np.int8
    assert eng_q.pool.k_scale.shape == eng_q.pool.k.shape[:3]
    assert eng_q.pool.k_scale.dtype == np.float32


def test_int8_overcapacity_wave_parity(model):
    """An over-capacity wave (device pool smaller than the wave's block
    need, preempt-by-recompute churn) must still pass the parity gate:
    freed-and-reallocated blocks restart their scales via the fresh-
    write reset, so churn cannot ratchet scales upward forever."""
    shared, prompts = _wave_prompts()
    outs, engs = [], []
    for kv_dtype in (None, "int8"):
        eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                        prefill_chunk=8, num_blocks=18, mesh=1,
                        kv_dtype=kv_dtype)
        outs.append(eng.generate(prompts, max_new_tokens=8,
                                 temperature=0.0))
        engs.append(eng)
    rate = _parity_rate(outs[1], outs[0])
    assert rate >= PARITY_RATE, (rate, outs)
    # pool drained back to idle in both dtypes
    for eng in engs:
        assert eng.pool._refcount == {}


# -- churn sweep: scale-sidecar invariants ------------------------------------


def test_churn_sweep_scale_sidecar_invariants(model):
    """Distinct-prefix over-capacity churn with the host tier on: after
    every round the sidecars hold finite non-negative scales, blocks the
    pool currently owns have strictly positive scales on both K and V,
    and a fresh serve still passes the parity gate (requantize-on-grow
    plus fresh-reset keep old payloads decodable)."""
    eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8, num_blocks=18, host_kv_blocks=16,
                    mesh=1, kv_dtype="int8")
    ref = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8, num_blocks=18, mesh=1)
    rs = np.random.RandomState(11)
    for r in range(3):
        prompts = [rs.randint(0, VOCAB, (n,)).tolist()
                   for n in (17, 25, 19)]
        got = eng.generate(prompts, max_new_tokens=4, temperature=0.0)
        want = ref.generate(prompts, max_new_tokens=4, temperature=0.0)
        assert _parity_rate(got, want) >= PARITY_RATE, (r, got, want)
        for sc in (np.asarray(eng.pool.k_scale),
                   np.asarray(eng.pool.v_scale)):
            assert np.isfinite(sc).all()
            assert (sc >= 0.0).all()
        owned = [b for b in range(1, eng.pool.num_blocks)
                 if eng.pool.refcount(b) > 0]
        for b in owned:
            assert (np.asarray(eng.pool.k_scale)[:, :, b] > 0).all()
            assert (np.asarray(eng.pool.v_scale)[:, :, b] > 0).all()
    eng.close()


# -- tier: scales ride swap + migration ---------------------------------------


def test_tier_export_import_carries_scales(model):
    """A drained int8 replica's export carries (hash, k, v, k_scale,
    v_scale) entries; an importing int8 replica serves the wave host-warm
    and token-identical to its own cold serve. An f32 replica must REJECT
    the int8 payload (dtype is part of the tier geometry)."""
    src, cold = _serve_wave(model, kv_dtype="int8", host_kv_blocks=24)
    payload = src.export_kv_tier(demote=True)
    assert payload["dtype"] == "int8"
    entry = payload["entries"][0]
    assert len(entry) == 5
    L, H = model.cfg.num_layers, model.cfg.num_heads
    assert entry[3].shape == (L, H) and entry[3].dtype == np.float32
    assert entry[1].dtype == np.int8

    dst = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8, spec_decoding=True, num_spec_tokens=3,
                    mesh=1, kv_dtype="int8", host_kv_blocks=24)
    assert dst.import_kv_tier(payload) > 0
    _, prompts = _wave_prompts()
    warm = dst.generate(prompts, max_new_tokens=10, temperature=0.0)
    assert warm == cold
    assert dst.metrics.counters.get("swap_ins", 0) > 0

    f32 = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    mesh=1, host_kv_blocks=24)
    with pytest.raises(ValueError, match="geometry"):
        f32.import_kv_tier(payload)
    for e in (src, dst, f32):
        e.close()


# -- AdaRound weights: the perplexity gate ------------------------------------


def _mean_nll(model, seqs):
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.tensor import Tensor

    tot, n = 0.0, 0
    for seq in seqs:
        ids = np.asarray(seq, np.int32)[None, :]
        logits = model(Tensor(jnp.asarray(ids)))._array[0]  # [s, vocab]
        lse = jax.nn.logsumexp(logits[:-1].astype(jnp.float32), axis=-1)
        ll = logits[np.arange(len(seq) - 1), ids[0, 1:]] - lse
        tot += float(-ll.sum())
        n += len(seq) - 1
    return tot / n


@pytest.mark.slow  # tier-1 headroom (PR 19): heaviest always-on case; tier-2 covers it
def test_adaround_nll_gate_and_grid(model):
    """`LLMEngine(quantize="int8", ...)` rewrites block linears in place
    on an int8 grid; the held-out mean NLL may exceed f32 by at most
    NLL_DELTA, norms/embeddings stay f32 (bit-identical), and the serve
    still passes the greedy parity gate."""
    rs = np.random.RandomState(3)
    calib = [rs.randint(0, VOCAB, (24,)).tolist() for _ in range(4)]
    held = [rs.randint(0, VOCAB, (32,)).tolist() for _ in range(4)]

    paddle.seed(0)
    m2 = GPT(model.cfg)
    m2.eval()
    for (_, p1), (_, p2) in zip(model.named_parameters(),
                                m2.named_parameters()):
        p2._array = p1._array
    base_nll = _mean_nll(model, held)
    wte_before = np.asarray(m2.wte.weight._array).copy()
    ln_before = np.asarray(m2.blocks[0].ln1.weight._array).copy()

    _, ref = _serve_wave(model)
    eng, outs = _serve_wave(m2, quantize="int8", calib_prompts=calib,
                            quantize_iters=40)
    q_nll = _mean_nll(m2, held)
    assert q_nll - base_nll <= NLL_DELTA, (q_nll, base_nll)
    assert _parity_rate(outs, ref) >= PARITY_RATE, (outs, ref)
    # f32 tensors really untouched; quantized weights really on the grid
    assert np.array_equal(np.asarray(m2.wte.weight._array), wte_before)
    assert np.array_equal(np.asarray(m2.blocks[0].ln1.weight._array),
                          ln_before)
    w = np.asarray(m2.blocks[0].fc1.weight._array, np.float32)
    scales = np.abs(w).max(axis=0, keepdims=True) / 127.0
    grid = w / np.maximum(scales, 1e-12)
    assert np.allclose(grid, np.round(grid), atol=1e-3)
    assert eng.quantize == "int8"


def test_adaround_rejects_sharded_engine(model):
    with pytest.raises(ValueError, match="quantize first"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=96,
                  mesh=2, quantize="int8")


def test_bad_knobs_raise(model):
    with pytest.raises(ValueError, match="kv_dtype"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=96,
                  mesh=1, kv_dtype="int4")
    with pytest.raises(ValueError, match="quant_allreduce"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=96,
                  mesh=2, quant_allreduce=["attn_out"])


# -- the PR 16 race guard -----------------------------------------------------


def test_sync_drive_rejected_while_async_loop_owns_engine(model):
    """`engine.generate()` (and step/stream) from a foreign thread while
    an AsyncLLMEngine background loop owns the engine raises a pointed
    RuntimeError instead of interleaving two schedulers over one pool —
    and the engine is drivable again after stop()."""
    eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=96,
                    mesh=1)

    async def main():
        fe = await AsyncLLMEngine(eng).start()
        try:
            with pytest.raises(RuntimeError, match="AsyncLLMEngine"):
                eng.generate([[1, 2, 3]], max_new_tokens=2)
            with pytest.raises(RuntimeError, match="AsyncLLMEngine"):
                eng.step()
            with pytest.raises(RuntimeError, match="AsyncLLMEngine"):
                next(eng.stream([1, 2, 3], max_new_tokens=2))
            # the async surface itself serves fine through the guard
            toks, reason = await fe.submit(
                [5, 6, 7], max_new_tokens=3, temperature=0.0).collect()
            assert len(toks) == 3 and reason == "length"
        finally:
            await fe.shutdown(drain=True)

    asyncio.run(main())
    # owner thread gone: the synchronous surface works again
    outs = eng.generate([[1, 2, 3]], max_new_tokens=2, temperature=0.0)
    assert len(outs[0]) == 2
