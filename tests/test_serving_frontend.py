"""AsyncLLMEngine: the asyncio serving frontend over the engine thread.

Acceptance criteria from the frontend issue, at the Python API level (the
HTTP surface is tests/test_serving_server.py): streamed greedy tokens are
identical to `LLMEngine.generate`'s; cancellations and deadlines abort
in-flight work and return every KV block to the pool; admission is bounded
(EngineOverloadedError, never an unbounded queue); a consumer that never
reads cannot stall the step loop (bounded queues flip to lossless
catch-up); shutdown drains with no hung tasks.
"""
import asyncio
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    EngineClosedError,
    EngineOverloadedError,
    LLMEngine,
)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def _idle(engine):
    return engine.pool.num_free == engine.pool.num_blocks - 1


async def _wait_for(cond, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while not cond():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for {msg}")
        await asyncio.sleep(0.01)


def test_streamed_greedy_matches_generate(model):
    """Concurrent async streams produce token-for-token the engine's
    sequential greedy output; the pool returns to idle after drain."""
    prompts = _prompts((5, 9, 13), seed=0)
    refs = [_reference(model, p, 6) for p in prompts]
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        streams = [fe.submit(p, max_new_tokens=6, temperature=0.0)
                   for p in prompts]
        results = await asyncio.gather(*(s.collect() for s in streams))
        await fe.shutdown(drain=True)
        return results, fe

    results, fe = asyncio.run(main())
    for (toks, reason), ref in zip(results, refs):
        assert toks == ref
        assert reason == "length"
    assert _idle(engine)
    assert engine._requests == {}
    assert not fe._thread.is_alive()


def test_slow_consumer_backpressure_is_lossless(model):
    """A consumer that reads NOTHING until generation completes: the step
    loop never blocks (the request finishes anyway), the bounded queue
    overflows into catch-up mode, and the late reader still gets the exact
    token sequence."""
    (p,) = _prompts((8,), seed=4)
    ref = _reference(model, p, 10)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)

    async def main():
        fe = await AsyncLLMEngine(engine, stream_queue_size=2).start()
        st = fe.submit(p, max_new_tokens=10, temperature=0.0)
        # do not consume a single token until the engine says it's done —
        # if a full queue could block the scheduler thread, this would hang
        await asyncio.wait_for(st.done.wait(), 60.0)
        assert st.overflow
        toks, reason = await st.collect()
        await fe.shutdown()
        return toks, reason

    toks, reason = asyncio.run(main())
    assert toks == ref and reason == "length"
    assert engine.metrics.counters["backpressure_drops"] >= 1
    assert _idle(engine)


def test_cancellation_midstream_frees_blocks(model):
    """abort() mid-decode: the stream ends with finish_reason 'cancelled',
    the other stream is unaffected (token-exact), and every KV block is
    back in the pool."""
    p_kill, p_keep = _prompts((9, 7), seed=3)
    ref_keep = _reference(model, p_keep, 12)
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)

    async def main():
        fe = await AsyncLLMEngine(engine).start()
        st_kill = fe.submit(p_kill, max_new_tokens=30, temperature=0.0)
        st_keep = fe.submit(p_keep, max_new_tokens=12, temperature=0.0)
        got = []
        async for tok in st_kill:
            got.append(tok)
            if len(got) == 2:
                fe.abort(st_kill.request_id)
        keep = await st_keep.collect()
        await fe.shutdown(drain=True)
        return st_kill, got, keep

    st_kill, got, keep = asyncio.run(main())
    assert st_kill.finish_reason == "cancelled"
    assert 2 <= len(got) < 30  # ended early, after the abort landed
    assert keep == (ref_keep, "length")
    assert engine.metrics.counters["requests_cancelled"] == 1
    assert _idle(engine)


def test_deadline_aborts_inflight_work(model):
    """A per-request timeout fires from the engine thread mid-generation:
    finish_reason 'timeout', partial output, pool back to idle."""
    (p,) = _prompts((6,), seed=5)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)

    async def main():
        fe = await AsyncLLMEngine(engine).start()
        st = fe.submit(p, max_new_tokens=56, temperature=0.0, timeout_s=0.15)
        toks, reason = await st.collect()
        await fe.shutdown()
        return toks, reason

    toks, reason = asyncio.run(main())
    assert reason == "timeout"
    assert len(toks) < 56
    assert engine.metrics.counters["requests_timeout"] == 1
    assert _idle(engine)


def test_admission_bounded_wait_queue(model):
    """Past max_batch + max_waiting in-flight requests, submit raises
    EngineOverloadedError — requests are rejected, never queued without
    bound."""
    prompts = _prompts((4, 4, 4), seed=6)
    engine = LLMEngine(model, block_size=8, max_batch=1, max_seq_len=64)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=1).start()
        s1 = fe.submit(prompts[0], max_new_tokens=20, temperature=0.0)
        s2 = fe.submit(prompts[1], max_new_tokens=20, temperature=0.0)
        with pytest.raises(EngineOverloadedError):
            fe.submit(prompts[2], max_new_tokens=20, temperature=0.0)
        await asyncio.gather(s1.collect(), s2.collect())
        # capacity freed: admission works again
        s4 = fe.submit(prompts[2], max_new_tokens=2, temperature=0.0)
        await s4.collect()
        await fe.shutdown()

    asyncio.run(main())
    assert engine.metrics.counters["requests_rejected"] == 1
    assert _idle(engine)


def test_graceful_drain_and_closed_rejection(model):
    """shutdown(drain=True) right after submitting: in-flight requests
    run to completion (token-exact), new submits raise EngineClosedError,
    the engine thread exits with no hung tasks."""
    prompts = _prompts((5, 11), seed=7)
    refs = [_reference(model, p, 8) for p in prompts]
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)

    async def main():
        fe = await AsyncLLMEngine(engine).start()
        streams = [fe.submit(p, max_new_tokens=8, temperature=0.0)
                   for p in prompts]
        drain = asyncio.ensure_future(fe.shutdown(drain=True))
        results = await asyncio.gather(*(s.collect() for s in streams))
        await drain
        with pytest.raises(EngineClosedError):
            fe.submit(prompts[0], max_new_tokens=2)
        return results, fe

    results, fe = asyncio.run(main())
    assert results == [(r, "length") for r in refs]
    assert not fe._thread.is_alive()
    assert _idle(engine)


def test_hard_shutdown_cancels_inflight(model):
    """shutdown(drain=False) aborts everything immediately; streams finish
    'cancelled' and the pool still returns to idle."""
    (p,) = _prompts((6,), seed=8)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)

    async def main():
        fe = await AsyncLLMEngine(engine).start()
        st = fe.submit(p, max_new_tokens=56, temperature=0.0)
        await _wait_for(lambda: len(st.req.output_ids) >= 1,
                        msg="first token")
        await fe.shutdown(drain=False)
        toks, reason = await st.collect()
        return toks, reason, fe

    toks, reason, fe = asyncio.run(main())
    assert reason == "cancelled"
    assert len(toks) < 56
    assert not fe._thread.is_alive()
    assert _idle(engine)
