"""Real text dataset ingestion (VERDICT r4 item 9 / Missing #5).

Mirrors tests/test_datasets_real.py's codec strategy: build standard-format
archive fixtures in tmp_path, parse them with the REAL loaders, and check
the reference's documented semantics (vocab cutoff ordering, <unk> last,
pos=0/neg=1 labels, n-gram windows, SEQ shifted pairs).
"""
import io
import tarfile

import numpy as np
import pytest

from paddle_tpu.text import Imdb, Imikolov


def _add_text(tf, name, text):
    data = text.encode("utf-8")
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))


@pytest.fixture()
def imdb_tar(tmp_path):
    """aclImdb layout: train/test x pos/neg .txt reviews."""
    path = tmp_path / "aclImdb_v1.tar.gz"
    # 'great' appears often enough to clear cutoff; 'terrible' too
    train_pos = ["great movie great fun!", "great great great acting."]
    train_neg = ["terrible movie, terrible.", "terrible terrible plot"]
    test_pos = ["great film"]
    test_neg = ["terrible film"]
    with tarfile.open(path, "w:gz") as tf:
        for i, doc in enumerate(train_pos):
            _add_text(tf, f"aclImdb/train/pos/{i}_10.txt", doc)
        for i, doc in enumerate(train_neg):
            _add_text(tf, f"aclImdb/train/neg/{i}_1.txt", doc)
        for i, doc in enumerate(test_pos):
            _add_text(tf, f"aclImdb/test/pos/{i}_9.txt", doc)
        for i, doc in enumerate(test_neg):
            _add_text(tf, f"aclImdb/test/neg/{i}_2.txt", doc)
    return str(path)


def test_imdb_real_parse(imdb_tar):
    ds = Imdb(data_file=imdb_tar, mode="train", cutoff=2)
    assert ds.real
    # vocab: freq('great')=8, freq('terrible')=7 -> indices 0, 1; <unk> last
    assert ds.word_idx["great"] == 0
    assert ds.word_idx["terrible"] == 1
    assert ds.word_idx["<unk>"] == len(ds.word_idx) - 1
    assert len(ds) == 4  # 2 pos + 2 neg train docs
    # pos docs first with label 0 (reference imdb.py:139), then neg label 1
    x0, y0 = ds[0]
    assert y0[0] == 0
    # "great movie great fun" -> great=0, movie/fun -> <unk>
    unk = ds.word_idx["<unk>"]
    np.testing.assert_array_equal(x0, [0, unk, 0, unk])
    x2, y2 = ds[2]
    assert y2[0] == 1 and x2[0] == ds.word_idx["terrible"]
    # punctuation removed, lowercase applied
    assert all(unk == t or t < len(ds.word_idx) for t in x0)


def test_imdb_test_split(imdb_tar):
    ds = Imdb(data_file=imdb_tar, mode="test", cutoff=2)
    assert len(ds) == 2
    (xp, yp), (xn, yn) = ds[0], ds[1]
    assert yp[0] == 0 and yn[0] == 1
    assert xp[0] == ds.word_idx["great"]
    assert xn[0] == ds.word_idx["terrible"]


def test_imdb_synthetic_fallback_is_loud():
    with pytest.warns(UserWarning, match="SYNTHETIC"):
        ds = Imdb(mode="train")
    assert not ds.real and len(ds) > 0


@pytest.fixture()
def ptb_tgz(tmp_path):
    path = tmp_path / "simple-examples.tgz"
    train = "the cat sat\nthe dog sat\nthe cat ran\n"
    valid = "the cat sat\n"
    test = "the dog ran\n"
    with tarfile.open(path, "w:gz") as tf:
        _add_text(tf, "./simple-examples/data/ptb.train.txt", train)
        _add_text(tf, "./simple-examples/data/ptb.valid.txt", valid)
        _add_text(tf, "./simple-examples/data/ptb.test.txt", test)
    return str(path)


def test_imikolov_ngram(ptb_tgz):
    ds = Imikolov(data_file=ptb_tgz, data_type="NGRAM", window_size=3,
                  mode="train", min_word_freq=1)
    assert ds.real
    # freqs over train+valid: the=4, <s>=4, <e>=4, cat=3, sat=3 > 1;
    # dog=1, ran=1 cut -> <unk>
    wi = ds.word_idx
    assert wi["<unk>"] == len(wi) - 1
    assert "the" in wi and "cat" in wi and "sat" in wi
    assert "dog" not in wi and "ran" not in wi
    # line 1: <s> the cat sat <e> -> windows of 3: 3 windows
    # 3 lines x 3 windows (all lines are 3 words) = 9
    assert len(ds) == 9
    first = ds[0]
    assert len(first) == 3
    np.testing.assert_array_equal(
        np.array([first[0], first[1], first[2]]).ravel(),
        [wi["<s>"], wi["the"], wi["cat"]],
    )


def test_imikolov_seq(ptb_tgz):
    ds = Imikolov(data_file=ptb_tgz, data_type="SEQ", mode="test",
                  min_word_freq=1)
    assert len(ds) == 1
    src, trg = ds[0]
    wi = ds.word_idx
    unk = wi["<unk>"]
    # "the dog ran": dog/ran below cutoff -> unk; src starts <s>, trg ends <e>
    np.testing.assert_array_equal(src, [wi["<s>"], wi["the"], unk, unk])
    np.testing.assert_array_equal(trg, [wi["the"], unk, unk, wi["<e>"]])


def test_imikolov_synthetic_fallback_is_loud():
    with pytest.warns(UserWarning, match="SYNTHETIC"):
        ds = Imikolov(data_type="NGRAM", window_size=5)
    assert not ds.real
    item = ds[0]
    assert len(item) == 5


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


@pytest.fixture()
def ml1m_zip(tmp_path):
    import zipfile

    path = tmp_path / "ml-1m.zip"
    movies = (
        "1::Toy Story (1995)::Animation|Children's|Comedy\n"
        "2::Jumanji (1995)::Adventure|Children's|Fantasy\n"
    )
    users = (
        "1::F::1::10::48067\n"
        "2::M::56::16::70072\n"
    )
    # many ratings so both splits are non-empty under the seeded split
    ratings = "".join(
        f"{(i % 2) + 1}::{(i % 2) + 1}::{(i % 5) + 1}::97830{i:04d}\n"
        for i in range(80)
    )
    with zipfile.ZipFile(path, "w") as z:
        z.writestr("ml-1m/movies.dat", movies)
        z.writestr("ml-1m/users.dat", users)
        z.writestr("ml-1m/ratings.dat", ratings)
    return str(path)


def test_movielens_real_parse(ml1m_zip):
    from paddle_tpu.text import Movielens

    tr = Movielens(data_file=ml1m_zip, mode="train")
    te = Movielens(data_file=ml1m_zip, mode="test")
    assert tr.real and te.real
    assert len(tr) + len(te) == 80
    assert len(te) > 0  # seeded 10% split captured some rows
    item = tr[0]
    # reference item tuple: uid, gender, age_idx, job, mid, cats, title, rating
    assert len(item) == 8
    uid, gender, age_idx, job, mid, cats, words, rating = item
    assert uid[0] in (1, 2) and gender[0] in (0, 1)
    assert age_idx[0] in (0, 6)  # ages 1 and 56 -> table indices 0 and 6
    assert len(cats) == 3  # both fixture movies carry 3 categories
    assert rating[0] in {2 * r - 5.0 for r in (1, 2, 3, 4, 5)}


def test_movielens_synthetic_fallback_is_loud():
    from paddle_tpu.text import Movielens

    with pytest.warns(UserWarning, match="SYNTHETIC"):
        ds = Movielens()
    assert not ds.real and len(ds[0]) == 8
