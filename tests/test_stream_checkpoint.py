"""Streaming sharded checkpoint load (distributed/checkpoint.py
`stream_load_state` + `LLMEngine(checkpoint_path=...)`).

The acceptance bar is the MEMORY BOUND, proven, not asserted by
docstring: streaming places every leaf shard-by-shard straight onto its
owning device, so peak host staging stays one shard slice and each chip
holds only its own shards — the full tree is never materialized on any
host buffer or chip. The regression lock: under the same per-chip
`param_hbm_bytes` budget, the eager placement path (caller holds a full
replica) FAILS engine construction while the streamed skeleton path
succeeds — and the streamed tp=4 serve stays greedy token-identical to
a single-chip reference built from the same checkpoint.
"""
import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu.distributed.checkpoint import (
    load_state,
    save_sharded_model,
    save_state,
    stream_load_state,
)
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.nn.layer import is_skeleton, skeleton_init
from paddle_tpu.serving import LLMEngine

CFG = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
           max_seq_len=64, attn_impl="xla", dropout=0.0)


@pytest.fixture(scope="module")
def ckpt(tmp_path_factory):
    """(path, eager model): one tiny GPT saved as a sharded checkpoint."""
    paddle.seed(0)
    m = GPT(GPTConfig(**CFG))
    m.eval()
    path = tmp_path_factory.mktemp("stream_ckpt") / "gpt"
    save_sharded_model(m, None, str(path))
    return str(path), m


def _skeleton():
    with skeleton_init():
        m = GPT(GPTConfig(**CFG))
    m.eval()
    return m


# -- the loader ---------------------------------------------------------------


def test_stream_load_matches_eager_load(ckpt):
    path, _ = ckpt
    eager = load_state(path)
    tree, report = stream_load_state(path)
    assert sorted(tree) == sorted(eager)
    for group in tree:
        assert sorted(tree[group]) == sorted(eager[group])
        for k, arr in tree[group].items():
            assert isinstance(arr, jax.Array)
            np.testing.assert_array_equal(np.asarray(arr),
                                          np.asarray(eager[group][k]))
    # the host bound: staging peaks at ONE leaf slice, never the tree
    assert 0 < report.peak_host_bytes < report.total_bytes
    assert report.arrays == sum(len(v) for v in eager.values())
    assert report.summary()["total_bytes"] == report.total_bytes


def test_load_state_stream_flag_is_equivalent(ckpt):
    path, _ = ckpt
    a, b = load_state(path), load_state(path, stream=True)
    for group in a:
        for k in a[group]:
            np.testing.assert_array_equal(np.asarray(a[group][k]),
                                          np.asarray(b[group][k]))


def test_stream_load_reshards_onto_mesh(tmp_path):
    """A leaf saved single-device streams back sharded: each device gets
    exactly its slice and per-chip bytes come out 1/tp of the leaf."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    w = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    save_state({"params": {"w": jax.numpy.asarray(w)}}, str(tmp_path / "c"))
    mesh = Mesh(np.array(jax.devices()[:4]), ("tp",))
    sh = NamedSharding(mesh, P("tp", None))
    tree, report = stream_load_state(str(tmp_path / "c"),
                                     shardings={"params/w": sh})
    got = tree["params"]["w"]
    assert got.sharding.is_equivalent_to(sh, got.ndim)
    np.testing.assert_array_equal(np.asarray(got), w)
    assert report.max_chip_bytes == w.nbytes // 4
    assert report.peak_host_bytes == w.nbytes // 4


# -- skeleton construction ----------------------------------------------------


def test_skeleton_model_has_shapes_not_arrays():
    skel = _skeleton()
    assert is_skeleton(skel)
    for _, p in skel.named_parameters_dict().items():
        assert isinstance(p._array, jax.ShapeDtypeStruct)
    paddle.seed(0)
    assert not is_skeleton(GPT(GPTConfig(**CFG)))


def test_skeleton_engine_requires_checkpoint():
    with pytest.raises(ValueError, match="checkpoint_path"):
        LLMEngine(_skeleton(), block_size=8, max_batch=2, max_seq_len=64)


def test_checkpoint_and_quantize_are_exclusive(ckpt):
    path, model = ckpt
    with pytest.raises(ValueError, match="mutually exclusive"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                  quantize="int8", checkpoint_path=path)


# -- the engine path: bound + parity ------------------------------------------


@pytest.fixture(scope="module")
def streamed_tp4(ckpt):
    path, _ = ckpt
    return LLMEngine(_skeleton(), block_size=8, max_batch=2, max_seq_len=64,
                     mesh=4, checkpoint_path=path)


def test_streamed_engine_reports_the_bound(streamed_tp4):
    rep = streamed_tp4.load_report
    assert rep is not None
    # per-chip: each device holds its own shards, NOT the full tree
    # (small replicated leaves ride along, so the bound is strict but
    # not 1/tp exact)
    assert 0 < rep.max_chip_bytes < rep.total_bytes
    assert len(rep.chip_bytes) == 4
    # host: peak staging is one shard slice, never the full tree
    assert 0 < rep.peak_host_bytes < rep.total_bytes
    assert streamed_tp4.metrics.gauges["ckpt_stream_max_chip_bytes"] == (
        rep.max_chip_bytes)


def test_too_big_for_eager_serves_streamed(ckpt, streamed_tp4):
    """THE regression: the same per-chip parameter budget that the
    streamed path provably meets fails the eager full-materialize path
    at construction (its source copy of the full tree is charged to the
    device holding it)."""
    path, model = ckpt
    budget = max(streamed_tp4.param_bytes_by_device().values())
    # streamed: constructs under the budget
    eng = LLMEngine(_skeleton(), block_size=8, max_batch=2, max_seq_len=64,
                    mesh=4, checkpoint_path=path, param_hbm_bytes=budget)
    assert max(eng.param_bytes_by_device().values()) <= budget
    # eager: the caller-held full replica busts the same budget
    with pytest.raises(ValueError, match="param_hbm_bytes"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                  mesh=4, param_hbm_bytes=budget)


def test_streamed_tp4_greedy_parity(ckpt, streamed_tp4):
    """Greedy serve off the streamed tp=4 engine is token-identical to a
    single-chip engine built by streaming the SAME checkpoint."""
    path, _ = ckpt
    ref = LLMEngine(_skeleton(), block_size=8, max_batch=2, max_seq_len=64,
                    checkpoint_path=path)
    rs = np.random.RandomState(0)
    prompts = [rs.randint(0, 128, (n,)).tolist() for n in (5, 11, 8)]
    outs = []
    for eng in (ref, streamed_tp4):
        rids = [eng.add_request(p, max_new_tokens=6, temperature=0.0)
                for p in prompts]
        while eng.has_unfinished():
            eng.step()
        outs.append([eng.get_request(r).output_ids for r in rids])
    assert outs[0] == outs[1]
