"""dy2static fallback (VERDICT r4 item 6 / Missing #2).

Reference: /root/reference/python/paddle/jit/dy2static/ifelse_transformer.py:56
and loop_transformer.py. The trace-based to_static now (1) raises a NAMED,
actionable error when Python control flow branches on a traced tensor, and
(2) auto-converts assignment-style if/while bodies to
static.nn.cond/while_loop and retries.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.jit.dy2static import Dy2StaticControlFlowError


def test_named_actionable_error_for_unconvertible():
    """return-inside-branch is not convertible: the user gets ONE clear
    error naming static.nn.cond, not a jax tracer stack."""

    @jit.to_static
    def f(x):
        if x.sum() > 0:  # data-dependent, returns from the branch
            return x * 2
        return x - 1

    with pytest.raises(Dy2StaticControlFlowError) as ei:
        f(paddle.to_tensor(np.ones(4, np.float32)))
    assert "static.nn.cond" in str(ei.value) or "could not auto-convert" in str(
        ei.value
    )


def test_eager_bool_still_works():
    t = paddle.to_tensor(np.array(1.0, np.float32))
    assert bool(t > 0)


def test_converted_if_end_to_end():
    """Assignment-style data-dependent `if` converts and matches eager."""

    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y + 1.0

    sf = jit.to_static(f)
    pos = paddle.to_tensor(np.ones(4, np.float32))
    neg = paddle.to_tensor(-np.ones(4, np.float32))
    np.testing.assert_allclose(np.asarray(sf(pos)._array), np.ones(4) * 3)
    np.testing.assert_allclose(np.asarray(sf(neg)._array), -np.ones(4))


def test_converted_if_reads_prior_value():
    """Branch bodies that READ the pre-branch value of a reassigned var."""

    def f(x):
        y = x + 1.0
        if x.mean() > 0:
            y = y * 10.0
        return y

    sf = jit.to_static(f)
    pos = paddle.to_tensor(np.ones(3, np.float32))
    neg = paddle.to_tensor(-np.ones(3, np.float32))
    np.testing.assert_allclose(np.asarray(sf(pos)._array), np.ones(3) * 20)
    np.testing.assert_allclose(np.asarray(sf(neg)._array), np.zeros(3))


def test_converted_while_end_to_end():
    """Data-dependent `while` converts to ONE lax.while_loop."""

    def f(x):
        s = x
        while s.sum() < 100.0:
            s = s * 2.0
        return s

    sf = jit.to_static(f)
    out = np.asarray(sf(paddle.to_tensor(np.ones(4, np.float32)))._array)
    # 4 -> 8 -> ... -> 128 >= 100
    np.testing.assert_allclose(out, np.ones(4) * 32)


def test_concrete_condition_keeps_python_semantics():
    """The converted dispatch runs plain Python when the condition is
    concrete (outside tracing)."""

    def f(x, flag):
        if flag:
            y = x + 1.0
        else:
            y = x - 1.0
        return y

    sf = jit.to_static(f)
    # flag is a plain bool (non-tensor arg -> part of the jit cache key)
    a = np.asarray(sf(paddle.to_tensor(np.zeros(2, np.float32)), True)._array)
    b = np.asarray(sf(paddle.to_tensor(np.zeros(2, np.float32)), False)._array)
    np.testing.assert_allclose(a, np.ones(2))
    np.testing.assert_allclose(b, -np.ones(2))


class GatedNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 0:  # data-dependent gate on a Layer forward
            out = h * 2.0
        else:
            out = h * 0.5
        return out


def test_layer_forward_with_data_dependent_if():
    paddle.seed(0)
    net = GatedNet()
    sfnet = jit.to_static(net)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    out = sfnet(x)
    # eager reference (same params, plain python branch)
    h = net.fc(x)
    expected = (h * 2.0 if float(h.mean()._array) > 0 else h * 0.5)._array
    np.testing.assert_allclose(
        np.asarray(out._array), np.asarray(expected), rtol=1e-6
    )


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


class DecoratedGatedNet(nn.Layer):
    """forward decorated @jit.to_static at class level (the reference's
    idiom) — the descriptor must hand back ONE bound wrapper per instance
    so the dy2static conversion survives re-access."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    @jit.to_static
    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 0:
            out = h * 2.0
        else:
            out = h * 0.5
        return out


def test_decorated_layer_method_converts():
    paddle.seed(0)
    net = DecoratedGatedNet()
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    out = net(x)
    h = net.fc(x)
    expected = (h * 2.0 if float(h.mean()._array) > 0 else h * 0.5)._array
    np.testing.assert_allclose(
        np.asarray(out._array), np.asarray(expected), rtol=1e-6
    )


def test_to_static_kwargs_in_cache_key():
    """Changed kwargs must recompile, not replay the first call's baked
    kwargs (review finding: the cache key ignored kwargs)."""

    def f(x, scale=1.0):
        return x * scale

    sf = jit.to_static(f)
    x = paddle.to_tensor(np.ones(3, np.float32))
    a = np.asarray(sf(x, scale=2.0)._array)
    b = np.asarray(sf(x, scale=5.0)._array)
    np.testing.assert_allclose(a, 2.0 * np.ones(3))
    np.testing.assert_allclose(b, 5.0 * np.ones(3))


def test_converted_function_with_concrete_inner_while():
    """A traced `if` triggers whole-function conversion; an unrelated
    concrete while with a body-local temporary must still run (review
    finding: the _UNDEF guard fired before the Python fallback)."""

    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        n = 3
        while n > 0:
            t = y + 1.0
            y = t
            n = n - 1
        return y

    sf = jit.to_static(f)
    out = np.asarray(sf(paddle.to_tensor(np.ones(3, np.float32)))._array)
    np.testing.assert_allclose(out, np.ones(3) * 5.0)  # 2 + 3
