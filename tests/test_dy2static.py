"""dy2static fallback (VERDICT r4 item 6 / Missing #2).

Reference: /root/reference/python/paddle/jit/dy2static/ifelse_transformer.py:56
and loop_transformer.py. The trace-based to_static now (1) raises a NAMED,
actionable error when Python control flow branches on a traced tensor, and
(2) auto-converts assignment-style if/while bodies to
static.nn.cond/while_loop and retries.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.jit.dy2static import Dy2StaticControlFlowError


def test_named_actionable_error_for_unconvertible():
    """return-inside-branch is not convertible: the user gets ONE clear
    error naming static.nn.cond, not a jax tracer stack."""

    @jit.to_static
    def f(x):
        if x.sum() > 0:  # data-dependent, returns from the branch
            return x * 2
        return x - 1

    with pytest.raises(Dy2StaticControlFlowError) as ei:
        f(paddle.to_tensor(np.ones(4, np.float32)))
    assert "static.nn.cond" in str(ei.value) or "could not auto-convert" in str(
        ei.value
    )


def test_eager_bool_still_works():
    t = paddle.to_tensor(np.array(1.0, np.float32))
    assert bool(t > 0)


def test_converted_if_end_to_end():
    """Assignment-style data-dependent `if` converts and matches eager."""

    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        return y + 1.0

    sf = jit.to_static(f)
    pos = paddle.to_tensor(np.ones(4, np.float32))
    neg = paddle.to_tensor(-np.ones(4, np.float32))
    np.testing.assert_allclose(np.asarray(sf(pos)._array), np.ones(4) * 3)
    np.testing.assert_allclose(np.asarray(sf(neg)._array), -np.ones(4))


def test_converted_if_reads_prior_value():
    """Branch bodies that READ the pre-branch value of a reassigned var."""

    def f(x):
        y = x + 1.0
        if x.mean() > 0:
            y = y * 10.0
        return y

    sf = jit.to_static(f)
    pos = paddle.to_tensor(np.ones(3, np.float32))
    neg = paddle.to_tensor(-np.ones(3, np.float32))
    np.testing.assert_allclose(np.asarray(sf(pos)._array), np.ones(3) * 20)
    np.testing.assert_allclose(np.asarray(sf(neg)._array), np.zeros(3))


def test_converted_while_end_to_end():
    """Data-dependent `while` converts to ONE lax.while_loop."""

    def f(x):
        s = x
        while s.sum() < 100.0:
            s = s * 2.0
        return s

    sf = jit.to_static(f)
    out = np.asarray(sf(paddle.to_tensor(np.ones(4, np.float32)))._array)
    # 4 -> 8 -> ... -> 128 >= 100
    np.testing.assert_allclose(out, np.ones(4) * 32)


def test_converted_while_with_body_local_temporary():
    """A traced-cond `while` whose body uses a temporary assigned before
    read must still convert: the temp's _UNDEF init is unobservable, so it
    can't be rejected by the XLA carry check (dy2static review fix)."""

    def f(x):
        s = x
        while s.sum() < 100.0:
            doubled = s * 2.0  # body-local: assigned before read
            s = doubled
        return s

    sf = jit.to_static(f)
    out = np.asarray(sf(paddle.to_tensor(np.ones(4, np.float32)))._array)
    np.testing.assert_allclose(out, np.ones(4) * 32)


def test_converted_while_temporary_read_after_loop_stays_loud():
    """A body 'temporary' that is read AFTER the loop is not a temporary:
    a zero-trip loop would leak the zero-seeded carry where plain Python
    raises NameError, so the traced path must keep the loud conversion
    error instead of silently returning zeros."""

    def f(x):
        s = x
        while s.sum() < 1.0:  # False on entry for ones(4): zero trips
            d = s * 2.0
            s = d
        return d  # noqa: F821 — undefined when the loop never ran

    sf = jit.to_static(f)
    with pytest.raises(TypeError, match="read before assignment|undefined"):
        sf(paddle.to_tensor(np.ones(4, np.float32)))


def test_converted_while_still_rejects_read_before_assignment():
    """A loop variable genuinely read before assignment keeps the
    actionable error on the traced path."""

    def f(x):
        s = x
        while s.sum() < 100.0:
            s = s + acc  # noqa: F821 — read before ANY assignment
            acc = s * 0.0
        return s

    sf = jit.to_static(f)
    with pytest.raises(TypeError, match="read before assignment|undefined"):
        sf(paddle.to_tensor(np.ones(4, np.float32)))


def test_concrete_condition_keeps_python_semantics():
    """The converted dispatch runs plain Python when the condition is
    concrete (outside tracing)."""

    def f(x, flag):
        if flag:
            y = x + 1.0
        else:
            y = x - 1.0
        return y

    sf = jit.to_static(f)
    # flag is a plain bool (non-tensor arg -> part of the jit cache key)
    a = np.asarray(sf(paddle.to_tensor(np.zeros(2, np.float32)), True)._array)
    b = np.asarray(sf(paddle.to_tensor(np.zeros(2, np.float32)), False)._array)
    np.testing.assert_allclose(a, np.ones(2))
    np.testing.assert_allclose(b, -np.ones(2))


class GatedNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 0:  # data-dependent gate on a Layer forward
            out = h * 2.0
        else:
            out = h * 0.5
        return out


def test_layer_forward_with_data_dependent_if():
    paddle.seed(0)
    net = GatedNet()
    sfnet = jit.to_static(net)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    out = sfnet(x)
    # eager reference (same params, plain python branch)
    h = net.fc(x)
    expected = (h * 2.0 if float(h.mean()._array) > 0 else h * 0.5)._array
    np.testing.assert_allclose(
        np.asarray(out._array), np.asarray(expected), rtol=1e-6
    )


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


class DecoratedGatedNet(nn.Layer):
    """forward decorated @jit.to_static at class level (the reference's
    idiom) — the descriptor must hand back ONE bound wrapper per instance
    so the dy2static conversion survives re-access."""

    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 4)

    @jit.to_static
    def forward(self, x):
        h = self.fc(x)
        if h.mean() > 0:
            out = h * 2.0
        else:
            out = h * 0.5
        return out


def test_decorated_layer_method_converts():
    paddle.seed(0)
    net = DecoratedGatedNet()
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    out = net(x)
    h = net.fc(x)
    expected = (h * 2.0 if float(h.mean()._array) > 0 else h * 0.5)._array
    np.testing.assert_allclose(
        np.asarray(out._array), np.asarray(expected), rtol=1e-6
    )


def test_to_static_kwargs_in_cache_key():
    """Changed kwargs must recompile, not replay the first call's baked
    kwargs (review finding: the cache key ignored kwargs)."""

    def f(x, scale=1.0):
        return x * scale

    sf = jit.to_static(f)
    x = paddle.to_tensor(np.ones(3, np.float32))
    a = np.asarray(sf(x, scale=2.0)._array)
    b = np.asarray(sf(x, scale=5.0)._array)
    np.testing.assert_allclose(a, 2.0 * np.ones(3))
    np.testing.assert_allclose(b, 5.0 * np.ones(3))


def test_to_static_tensor_kwargs_are_runtime_values():
    """Two same-shape Tensor kwargs hit the same compiled entry but must
    use their OWN values (ADVICE medium: the kwarg's concrete array was
    baked into the traced closure, silently replaying the first mask)."""

    def f(x, mask=None):
        return x * mask

    sf = jit.to_static(f)
    x = paddle.to_tensor(np.ones(4, np.float32))
    m1 = paddle.to_tensor(np.array([1, 0, 1, 0], np.float32))
    m2 = paddle.to_tensor(np.array([0, 1, 0, 1], np.float32))  # same shape
    np.testing.assert_allclose(np.asarray(sf(x, mask=m1)._array), m1.numpy())
    np.testing.assert_allclose(np.asarray(sf(x, mask=m2)._array), m2.numpy())
    assert len(sf._cache) == 1  # same program, different runtime kwarg


def test_to_static_layer_tensor_kwargs_are_runtime_values():
    """Same regression through the Layer path (functional_call kwargs)."""

    class Masked(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x, mask=None):
            return self.fc(x) * mask

    paddle.seed(0)
    net = Masked()
    sfnet = jit.to_static(net)
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    m1 = paddle.to_tensor(np.ones((2, 4), np.float32))
    m2 = paddle.to_tensor(np.zeros((2, 4), np.float32))
    out1 = np.asarray(sfnet(x, mask=m1)._array)
    out2 = np.asarray(sfnet(x, mask=m2)._array)
    ref = np.asarray(net.fc(x)._array)
    np.testing.assert_allclose(out1, ref, rtol=1e-6)
    np.testing.assert_allclose(out2, np.zeros((2, 4)), rtol=1e-6)


def test_to_static_ndarray_kwargs_are_runtime_values():
    """Raw np.ndarray kwargs take the Tensor-kwarg path: keyed by
    (shape, dtype), value passed at runtime — repr() truncates large arrays,
    so keying by repr collided different arrays onto one baked constant."""

    def f(x, mask=None):
        return x * mask

    sf = jit.to_static(f)
    x = paddle.to_tensor(np.ones(2000, np.float32))
    m1 = np.ones(2000, np.float32)
    m2 = np.ones(2000, np.float32)
    m2[1000] = 5.0  # identical truncated repr, different value
    np.testing.assert_allclose(np.asarray(sf(x, mask=m1)._array), m1)
    np.testing.assert_allclose(np.asarray(sf(x, mask=m2)._array), m2)
    assert len(sf._cache) == 1


def test_to_static_jax_array_kwargs_are_runtime_values():
    """Raw jax.Array kwargs (flagged in the serving-frontend issue): they
    fell through to the repr() cache key and were baked into the traced
    closure as constants, silently replaying the first call's values for
    every later same-shape kwarg. Now keyed by (shape, dtype) and passed
    as runtime arrays, through both the plain-function and Layer paths."""
    import jax.numpy as jnp

    def f(x, mask=None):
        return x * mask

    sf = jit.to_static(f)
    x = paddle.to_tensor(np.ones(4, np.float32))
    m1 = jnp.asarray(np.array([1, 0, 1, 0], np.float32))
    m2 = jnp.asarray(np.array([0, 1, 0, 1], np.float32))  # same shape/dtype
    np.testing.assert_allclose(np.asarray(sf(x, mask=m1)._array),
                               np.asarray(m1))
    np.testing.assert_allclose(np.asarray(sf(x, mask=m2)._array),
                               np.asarray(m2))
    assert len(sf._cache) == 1  # same program, different runtime kwarg

    class Masked(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(4, 4)

        def forward(self, x, mask=None):
            return self.fc(x) * mask

    paddle.seed(0)
    net = Masked()
    sfnet = jit.to_static(net)
    xb = paddle.to_tensor(np.ones((2, 4), np.float32))
    ones = jnp.asarray(np.ones((2, 4), np.float32))
    zeros = jnp.asarray(np.zeros((2, 4), np.float32))
    ref = np.asarray(net.fc(xb)._array)
    np.testing.assert_allclose(np.asarray(sfnet(xb, mask=ones)._array), ref,
                               rtol=1e-6)
    np.testing.assert_allclose(np.asarray(sfnet(xb, mask=zeros)._array),
                               np.zeros((2, 4)), rtol=1e-6)


def test_to_static_rejects_tensor_in_container_kwarg():
    """A Tensor inside a container kwarg would be baked as a constant (and
    numpy's truncated repr would collide cache keys for large arrays) —
    rejected loudly instead."""

    def f(x, masks=None):
        return x * masks[0]

    sf = jit.to_static(f)
    x = paddle.to_tensor(np.ones(4, np.float32))
    m = paddle.to_tensor(np.ones(4, np.float32))
    with pytest.raises(TypeError, match="container"):
        sf(x, masks=[m])


def test_converted_function_with_concrete_inner_while():
    """A traced `if` triggers whole-function conversion; an unrelated
    concrete while with a body-local temporary must still run (review
    finding: the _UNDEF guard fired before the Python fallback)."""

    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = x - 1.0
        n = 3
        while n > 0:
            t = y + 1.0
            y = t
            n = n - 1
        return y

    sf = jit.to_static(f)
    out = np.asarray(sf(paddle.to_tensor(np.ones(3, np.float32)))._array)
    np.testing.assert_allclose(out, np.ones(3) * 5.0)  # 2 + 3
