"""Elastic manager + auto-checkpoint (VERDICT round-2 item 7; reference
fleet/elastic/manager.py:126, incubate/checkpoint/auto_checkpoint.py:72)."""
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.elastic import (
    AutoCheckpoint,
    ElasticManager,
    ElasticStatus,
)


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


class TestElasticManager:
    def _mk(self, nnodes=2, timeout=1.0):
        port = _free_port()
        m0 = ElasticManager("job1", 0, nnodes, host="127.0.0.1", port=port,
                            timeout=timeout, endpoint="127.0.0.1:1000",
                            heartbeat_interval=0.1)
        m1 = ElasticManager("job1", 1, nnodes, store=None, host="127.0.0.1",
                            port=port, timeout=timeout, endpoint="127.0.0.1:1001",
                            heartbeat_interval=0.1)
        return m0, m1

    def test_register_heartbeat_watch(self):
        m0, m1 = self._mk()
        try:
            m0.register()
            m1.register()
            time.sleep(0.1)
            assert m0.all_alive()
            assert m0.watch_once() == ElasticStatus.HOLD
            assert m0.endpoints() == {0: "127.0.0.1:1000", 1: "127.0.0.1:1001"}
        finally:
            m0.exit()
            m1.exit()

    def test_stale_node_detected_and_restart_signal(self):
        m0, m1 = self._mk(timeout=0.5)
        try:
            m0.register()
            m1.register()
            time.sleep(0.1)
            m1.exit()  # node 1 stops heartbeating (simulated failure)
            time.sleep(1.0)
            assert m0.dead_nodes() == [1]
            assert m0.watch_once() == ElasticStatus.RESTART
        finally:
            m0.exit()

    def test_endpoint_rewrite_and_generation(self):
        m0, m1 = self._mk()
        try:
            m0.register()
            m1.register()
            assert m0.generation() == 0
            m0.rewrite_endpoints({1: "10.0.0.9:1001"})
            assert m0.generation() == 1
            # the survivor (and any replacement) reads the new table
            env = m1.export_env({})
            assert env["PADDLE_TRAINER_ENDPOINTS"] == "127.0.0.1:1000,10.0.0.9:1001"
            assert env["PADDLE_ELASTIC_GENERATION"] == "1"
        finally:
            m0.exit()
            m1.exit()


class TestAutoCheckpoint:
    def test_epoch_skip_and_state_restore(self, tmp_path):
        paddle.seed(0)
        net = nn.Linear(4, 4)
        opt = paddle.optimizer.Adam(parameters=net.parameters())
        ck = AutoCheckpoint("jobA", str(tmp_path), net, opt)

        ran = []
        for epoch in ck.train_epoch_range(3):
            ran.append(epoch)
            out = net(paddle.to_tensor(np.ones((2, 4), np.float32)))
            out.sum().backward()
            opt.step()
            opt.clear_grad()
            if epoch == 1:
                break  # simulated crash AFTER epoch 0 snapshot, mid-epoch 1
        assert ran == [0, 1]
        w_after_e0 = None

        # "restarted" process: fresh model/opt, same job id + dir
        paddle.seed(9)
        net2 = nn.Linear(4, 4)
        opt2 = paddle.optimizer.Adam(parameters=net2.parameters())
        ck2 = AutoCheckpoint("jobA", str(tmp_path), net2, opt2)
        ran2 = list(ck2.train_epoch_range(3))
        assert ran2 == [1, 2]  # epoch 0 skipped — resumed from the snapshot
        # weights restored from the epoch-0 snapshot, not fresh init
        sd2 = opt2.state_dict()
        assert any("moment1" in k for k in sd2)

    def test_fresh_job_starts_at_zero(self, tmp_path):
        ck = AutoCheckpoint("jobB", str(tmp_path))
        assert list(ck.train_epoch_range(2)) == [0, 1]


def test_launcher_elastic_restart_loop(tmp_path):
    """End-to-end: the launcher relaunches a crashing script and the second
    incarnation resumes via AutoCheckpoint (epoch skip)."""
    script = tmp_path / "train.py"
    script.write_text(
        f"""
import os, sys
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.fleet.elastic import AutoCheckpoint

net = nn.Linear(2, 2)
opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
ck = AutoCheckpoint("launcher_job", {str(tmp_path)!r}, net, opt)
for epoch in ck.train_epoch_range(3):
    out = net(paddle.to_tensor(np.ones((1, 2), np.float32)))
    out.sum().backward(); opt.step(); opt.clear_grad()
    print("EPOCH", epoch, flush=True)
    if epoch == 1 and not os.path.exists({str(tmp_path / "crashed")!r}):
        open({str(tmp_path / "crashed")!r}, "w").write("1")
        sys.exit(17)  # crash during epoch 1 — epoch 0 is already snapshotted
print("DONE", flush=True)
"""
    )
    from paddle_tpu.distributed.launch.main import launch_main

    log_dir = str(tmp_path / "logs")
    rc = launch_main([
        "--max_restarts", "2", "--log_dir", log_dir, str(script)
    ])
    assert rc == 0
    log = open(os.path.join(log_dir, "workerlog.0")).read()
    assert "DONE" in log
    # first run: epochs 0,1 then crash; second run resumes at epoch 1 —
    # epoch 0 is NOT re-run (the snapshot skip)
    assert log.count("EPOCH 0") == 1, log
    assert log.count("EPOCH 1") == 2, log
    assert log.count("EPOCH 2") == 1, log
