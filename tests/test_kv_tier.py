"""Tiered KV cache (serving/kv_tier.py): host-memory block tier with
swap-back, cross-replica migration, and zero-rewarm drains.

The acceptance bar is TOKEN parity: a greedy serve whose prefix lands as
host-tier hits must be token-for-token identical to a cold serve and to a
device-warm serve — single-chip, tp=2 (per-shard slabs), and with
speculative decoding on. Around that anchor: churn-sweep accounting
(refcounts drain, host slots balance, pool returns to idle across
swap-in/swap-out/COW/preempt/abort interleavings), the /debug/kvtier
surfaces, the /healthz-vs-/metrics pool agreement with the new host-tier
gauges, the rolling-drain migration handoff (zero failed requests,
post-drain host hits), and a witnessed churn serve covering
``KVTier._lock`` (acyclic, JL009-covered).
"""
import asyncio
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    LLMEngine,
    ReplicaRouter,
    RouterServer,
    ServingServer,
)

from test_serving_router import _parse_prom


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def ref_engine(model):
    """One shared no-tier engine for reference outputs (the chaos-file
    discipline: fresh step programs per reference run would dominate
    this file's wall time)."""
    return LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)


@pytest.fixture(autouse=True)
def _no_env_knobs(monkeypatch):
    """Developer env must not shard the single-chip engines or resize the
    host tier out from under the capacity-pressure tests."""
    monkeypatch.delenv("PADDLE_TPU_TP", raising=False)
    monkeypatch.delenv("PADDLE_TPU_HOST_KV_BLOCKS", raising=False)


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    kw.setdefault("host_kv_blocks", 24)
    return LLMEngine(model, **kw)


def _idle(engine):
    assert engine.pool._refcount == {}
    assert engine.pool.num_free == engine.pool.num_blocks - 1


def _tier_consistent(tier):
    """Host-slot conservation: every slot is exactly one of free or
    indexed, and nothing is pending after a settle."""
    tier.settle()
    with tier._lock:
        assert tier._pending == {}
        assert tier._save_buf == []
        used = set(tier._index.values())
        assert len(used) == len(tier._index)          # no slot aliasing
        assert used.isdisjoint(tier._free_slots)
        assert len(used) + len(tier._free_slots) == tier.host_blocks


def _churn(engine, rounds=3, seed=5):
    """Over-capacity distinct-prefix traffic: fills the device pool and
    forces LRU evictions (host-tier demotions) every round."""
    for r in range(rounds):
        engine.generate(_prompts((17, 25, 19), seed=seed + 7 * r),
                        max_new_tokens=4, temperature=0.0)


# -- token parity: host-warm == cold == device-warm ---------------------------


def test_host_warm_matches_cold_and_device_warm(model, ref_engine):
    """THE tier acceptance criterion, single-chip: a document prompt is
    served cold, churned out of the device cache (demoted to host), then
    re-served — the re-serve must swap blocks BACK in (swap_ins > 0) and
    emit tokens identical to the cold serve. A back-to-back device-warm
    re-serve (no churn) stays identical too and never touches the tier."""
    doc = _prompts((24,), seed=1)[0]                   # three full blocks
    tails = _prompts((3, 5), seed=2)
    prompts = [doc + t for t in tails]
    refs = ref_engine.generate(prompts, max_new_tokens=6, temperature=0.0)

    engine = _engine(model, num_blocks=12)             # 11 usable: tight
    cold = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    assert cold == refs

    warm = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    assert warm == refs                                # device-warm
    ins_before = engine.tier.swap_ins

    _churn(engine)                                     # demote doc blocks
    engine.tier.settle()
    assert engine.tier.swap_outs > 0
    hostwarm = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    assert hostwarm == refs                            # host-warm parity
    assert engine.tier.swap_ins > ins_before           # came from host
    assert engine.tier.swap_in_hit_tokens >= \
        (engine.tier.swap_ins - ins_before) * engine.pool.block_size
    _idle(engine)
    _tier_consistent(engine.tier)
    engine.close()


def test_tp2_per_shard_slabs_and_spec_on_parity(model, ref_engine):
    """tp=2 + speculative decoding: the tier keeps one slab per head
    range (no cross-chip gather on save), and a host-warm serve stays
    token-identical to the single-chip cold reference."""
    doc = _prompts((24,), seed=1)[0]
    tails = _prompts((3, 5), seed=2)
    prompts = [doc + t for t in tails]
    refs = ref_engine.generate(prompts, max_new_tokens=6, temperature=0.0)

    engine = _engine(model, num_blocks=12, mesh=2, spec_decoding=True,
                     num_spec_tokens=3)
    tier = engine.tier
    assert [(h0, h1) for h0, h1, _, _ in tier._slabs] == [(0, 1), (1, 2)]
    assert engine.generate(prompts, max_new_tokens=6,
                           temperature=0.0) == refs
    _churn(engine)
    tier.settle()
    assert tier.swap_outs > 0
    assert engine.generate(prompts, max_new_tokens=6,
                           temperature=0.0) == refs   # host-warm parity
    assert tier.swap_ins > 0
    _idle(engine)
    _tier_consistent(tier)
    engine.close()


def test_cross_topology_migration_parity(model, ref_engine):
    """Migration is topology-portable: a tp=2 engine's export (payloads
    are full-logical [L, H, bs, D]) imports into a single-chip engine
    and serves host-warm tokens identical to the cold reference."""
    doc = _prompts((24,), seed=1)[0]
    prompts = [doc + t for t in _prompts((3,), seed=2)]
    refs = ref_engine.generate(prompts, max_new_tokens=6, temperature=0.0)

    src = _engine(model, num_blocks=12, mesh=2)
    src.generate(prompts, max_new_tokens=6, temperature=0.0)
    payload = src.export_kv_tier(demote=True)          # quiescent: demote
    assert payload is not None and payload["entries"]

    dst = _engine(model, num_blocks=12)
    n = dst.import_kv_tier(payload)
    assert n == len(payload["entries"])
    assert dst.tier.migrated_blocks_in == n
    assert dst.generate(prompts, max_new_tokens=6, temperature=0.0) == refs
    assert dst.tier.swap_ins > 0                       # served FROM import
    # geometry mismatches refuse loudly instead of serving foreign KV
    bad = dict(payload, block_size=payload["block_size"] + 1)
    with pytest.raises(ValueError, match="geometry mismatch"):
        dst.import_kv_tier(bad)
    src.close()
    dst.close()


# -- churn sweep: accounting across interleavings -----------------------------


def test_churn_sweep_interleavings_leave_pool_and_tier_idle(model):
    """Randomized rounds of shared-prefix traffic over a pool too small
    to hold it — swap-outs, swap-back hits, COW on shared tails,
    preemption, and mid-flight aborts all interleave — and EVERY round
    ends with refcounts drained, the pool's free count restored, and the
    host tier's slot accounting balanced."""
    rs = np.random.RandomState(11)
    engine = _engine(model, num_blocks=10, block_size=4, host_kv_blocks=16,
                     host_swap_chunk=2)
    prefixes = [rs.randint(0, 128, (12,)).tolist() for _ in range(3)]
    idle_free = engine.pool.num_free
    for rnd in range(4):
        reqs = []
        for _ in range(int(rs.randint(3, 6))):
            p = (prefixes[rs.randint(len(prefixes))]
                 + rs.randint(0, 128, (rs.randint(0, 7),)).tolist())
            reqs.append(engine.add_request(
                p, max_new_tokens=int(rs.randint(2, 7)), temperature=0.0))
        doomed = set(rs.choice(reqs, size=len(reqs) // 3,
                               replace=False).tolist())
        steps = 0
        while engine.has_unfinished():
            engine.step()
            steps += 1
            if steps == 2:
                for rid in doomed:
                    engine.abort(rid)
        for rid in reqs:
            if rid not in doomed:
                engine.release(rid)
        assert engine.pool._refcount == {}, f"round {rnd}"
        assert engine.pool.num_free == idle_free, f"round {rnd}"
        _tier_consistent(engine.tier)
    assert engine.tier.swap_outs > 0          # the sweep exercised demotion
    assert engine.tier.swap_ins > 0           # ... and swap-back
    assert engine.metrics.counters.get("preemptions", 0) > 0
    assert engine.metrics.counters.get("prefix_cache_cow_copies", 0) > 0
    engine.close()


def test_tier_lru_eviction_keeps_newest(model):
    """Host capacity pressure: with a tier smaller than the churn, the
    OLDEST host entries are evicted and the slot accounting still
    balances (no leak, no aliasing)."""
    engine = _engine(model, num_blocks=10, host_kv_blocks=4)
    _churn(engine, rounds=4)
    tier = engine.tier
    _tier_consistent(tier)
    with tier._lock:
        assert len(tier._index) == tier.host_blocks       # full, not over
    assert tier.swap_outs > tier.host_blocks              # evicted + reused
    engine.close()


# -- observability: /debug/kvtier + pool agreement ----------------------------


async def _http(port, method, path, obj=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    data = json.dumps(obj).encode() if obj is not None else b""
    writer.write(
        (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
         f"Content-Type: application/json\r\n"
         f"Content-Length: {len(data)}\r\n\r\n").encode() + data)
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), body


def test_debug_kvtier_endpoint_and_pool_agreement(model):
    """/debug/kvtier 404s with a hint when the tier is off, dumps the
    snapshot when on; the /healthz pool dict (now carrying host-tier
    stats) agrees number-for-number with the /metrics pool_* gauges, and
    every new family is HELP'd and TYPE'd (the exposition lock)."""
    doc = _prompts((24,), seed=1)[0]

    async def main():
        eng_off = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
        off = ServingServer(eng_off, host="127.0.0.1", port=0)
        await off.start()
        off_status, off_body = await _http(off.port, "GET", "/debug/kvtier")
        await off.shutdown()

        eng = _engine(model, num_blocks=12)
        server = ServingServer(eng, host="127.0.0.1", port=0)
        await server.start()
        await server.engine.submit(
            doc, max_new_tokens=4, temperature=0.0).collect()
        # churn THROUGH the frontend (the engine thread owns step
        # dispatch — a direct generate would race arena donation)
        for r in range(2):
            for p in _prompts((17, 25, 19), seed=5 + 7 * r):
                await server.engine.submit(
                    p, max_new_tokens=4, temperature=0.0).collect()
        await asyncio.to_thread(eng.tier.settle)
        # re-serve the doc: its churned-out blocks swap back in, so the
        # swap_ins counter families render on the scrape below
        await server.engine.submit(
            doc, max_new_tokens=4, temperature=0.0).collect()
        dbg = await _http(server.port, "GET", "/debug/kvtier")
        met = await _http(server.port, "GET", "/metrics")
        hz = await _http(server.port, "GET", "/healthz")
        await server.shutdown()
        return off_status, off_body, dbg, met, hz

    off_status, off_body, dbg, met, hz = asyncio.run(main())
    assert off_status == 404
    assert b"host_kv_blocks" in off_body                 # the hint

    assert dbg[0] == 200
    snap = json.loads(dbg[1])
    assert snap["host_blocks_total"] == 24
    assert snap["swap_outs"] > 0
    assert snap["host_blocks_used"] == len(snap["resident"])
    assert snap["shards"] == [[0, 2]]                    # single-chip slab
    assert snap["block_shape"][3] * snap["block_shape"][1] == 32  # H*D

    text = met[1].decode()
    types, samples = _parse_prom(text)                   # every line parses
    pre = "paddle_tpu_serving_"
    gauges = {n: v for n, lab, v in samples if n.startswith(pre + "pool_")}
    health = json.loads(hz[1])
    want = {f"{pre}pool_{k}": float(v) for k, v in health["pool"].items()
            if not isinstance(v, str)}                   # kv_dtype: info fam
    assert gauges == want                                # same live numbers
    assert gauges[pre + "pool_host_blocks_total"] == 24
    assert gauges[pre + "pool_swap_outs"] > 0
    for fam in ("pool_host_blocks_total", "pool_host_blocks_used",
                "pool_swap_ins", "pool_swap_outs", "pool_swap_in_hit_tokens",
                "pool_migrated_blocks_out", "pool_migrated_blocks_in"):
        assert types[pre + fam] == "gauge", fam
        assert f"# HELP {pre}{fam} " in text, fam
    # the tier's own counters are first-class families too
    for fam in ("swap_ins_total", "swap_outs_total",
                "swap_in_hit_tokens_total"):
        assert pre + fam in {n for n, _, _ in samples}, fam


def test_router_debug_kvtier_merges_replicas(model):
    """The fleet view: RouterServer /debug/kvtier returns one snapshot
    per replica keyed by name (404 with a hint when no replica runs the
    tier)."""
    async def main():
        bare = ReplicaRouter(
            [AsyncLLMEngine(LLMEngine(model, block_size=8, max_batch=4,
                                      max_seq_len=64)) for _ in range(2)],
            sweep_interval_s=0.05)
        off = RouterServer(bare, port=0)
        await off.start()
        off_resp = await _http(off.port, "GET", "/debug/kvtier")
        await off.shutdown()

        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model)) for _ in range(2)],
            sweep_interval_s=0.05)
        server = RouterServer(router, port=0)
        await server.start()
        resp = await _http(server.port, "GET", "/debug/kvtier")
        await server.shutdown()
        return off_resp, resp

    off_resp, resp = asyncio.run(main())
    assert off_resp[0] == 404 and b"host_kv_blocks" in off_resp[1]
    assert resp[0] == 200
    snaps = json.loads(resp[1])
    assert set(snaps) == {"r0", "r1"}
    assert all(s["host_blocks_total"] == 24 for s in snaps.values())


# -- zero-rewarm drains -------------------------------------------------------


def test_rolling_drain_migrates_and_serves_host_warm(model, ref_engine):
    """THE drain acceptance criterion: a rolling drain with a factory
    restarts every replica, the old home's cache rides along through the
    host tier (router_migrations fires), zero requests fail, and a
    post-drain re-serve of the warmed prefixes hits the NEW engines'
    host tier (swap_ins > 0) with token-identical output."""
    shared = _prompts((16,), seed=3)[0]
    prompts = [shared + t for t in _prompts((3, 5, 4), seed=4)]
    refs = ref_engine.generate(prompts, max_new_tokens=6, temperature=0.0)

    def factory(i):
        return AsyncLLMEngine(_engine(model, num_blocks=12))

    async def main():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model, num_blocks=12))
             for _ in range(2)],
            factory=factory, sweep_interval_s=0.05)
        await router.start()
        warm = [await (await router.submit(
            p, max_new_tokens=6, temperature=0.0)).collect()
            for p in prompts]
        # live traffic THROUGH the drain: nothing may fail
        streams = [await router.submit(p, max_new_tokens=6, temperature=0.0)
                   for p in prompts]
        drained = await router.rolling_drain()
        mid = [await s.collect() for s in streams]
        post = [await (await router.submit(
            p, max_new_tokens=6, temperature=0.0)).collect()
            for p in prompts]
        swap_ins = sum(r.engine.engine.tier.swap_ins
                       for r in router.replicas)
        c = dict(router.metrics.counters)
        await router.shutdown()
        return drained, warm, mid, post, swap_ins, c

    drained, warm, mid, post, swap_ins, c = asyncio.run(main())
    assert drained == ["r0", "r1"]
    assert c["router_restarts"] == 2
    assert c["router_migrations"] >= 1
    assert c["router_migrated_blocks"] > 0
    assert c.get("router_requests_failed", 0) == 0       # zero-rewarm AND
    for got, ref in zip(warm + mid + post, refs * 3):    # zero-failure
        toks, reason = got
        assert reason == "length" and toks == ref
    # the post-drain serve was warmed from the MIGRATED host blocks, not
    # recompute: the fresh engines swapped prefix blocks back in
    assert swap_ins > 0


def test_ejection_salvages_host_tier_to_live_replicas(model):
    """The live-export path (demote=False): salvaging an ejected
    replica's SETTLED host blocks into its peers touches only slabs —
    safe on a non-quiescent engine — and the peers adopt them."""
    async def main():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model, num_blocks=10))
             for _ in range(2)],
            sweep_interval_s=0.05)
        await router.start()
        victim = router.replicas[0]
        # force demotions on the victim so its host tier holds blocks
        # (through its frontend — the engine thread owns step dispatch)
        eng = victim.engine.engine
        for r in range(2):
            for p in _prompts((17, 25, 19), seed=5 + 7 * r):
                await victim.engine.submit(
                    p, max_new_tokens=4, temperature=0.0).collect()
        await asyncio.to_thread(eng.tier.settle)
        await router._migrate_from(victim)
        peer = router.replicas[1].engine.engine
        c = dict(router.metrics.counters)
        got = peer.tier.migrated_blocks_in
        await router.shutdown()
        return c, got

    c, got = asyncio.run(main())
    assert c["router_migrations"] == 1
    assert got > 0 and c["router_migrated_blocks"] == got


# -- concurrency: the witness covers KVTier._lock -----------------------------


def test_witnessed_tier_churn_acyclic_and_covered(model):
    """A witnessed churn serve with the tier on: the drain thread's slab
    writes, the engine thread's flush/restore, and a loop-thread debug
    snapshot all take ``KVTier._lock`` concurrently — the observed graph
    must be acyclic, must contain the tier's lock, and every observed
    edge must be covered by the static JL009 model (gaps == [])."""
    from paddle_tpu.analysis import witness

    w = witness.install()
    try:
        engine = _engine(model, num_blocks=10, slo=True)
        _churn(engine, rounds=2)
        engine.tier.debug_snapshot()        # scrape-thread acquisition
        engine.tier.settle()
        engine.slo.rollup()
        _idle(engine)
        engine.close()
        w.check_acyclic()
        g = w.observed_graph()
        assert any("kv_tier.py" in n["ctor"] for n in g["nodes"]), g["nodes"]
        gaps = witness.cross_check(w)
        assert gaps == [], "\n".join(gaps)
    finally:
        witness.uninstall()
