"""User custom C++ op with autograd (reference
framework/custom_operator.cc:746 + cpp_extension load flow).

A real C++ kernel is JIT-built and registered; the op must work on the tape
(correct user-supplied gradient), inside a Layer training step, and under
static capture.
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.utils.custom_op import REGISTRY, load_custom_op

CPP = r"""
#include <cstdint>
#include <cmath>

// y = x^3 + 2x   ;   dy/dx = 3x^2 + 2
extern "C" void cube2_forward(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] * x[i] * x[i] + 2.0f * x[i];
}

extern "C" void cube2_backward(const float* x, const float* gy, float* gx,
                               int64_t n) {
  for (int64_t i = 0; i < n; ++i) gx[i] = (3.0f * x[i] * x[i] + 2.0f) * gy[i];
}

// forward-only op (no backward symbol)
extern "C" void stepfn_forward(const float* x, float* y, int64_t n) {
  for (int64_t i = 0; i < n; ++i) y[i] = x[i] > 0.0f ? 1.0f : 0.0f;
}
"""


@pytest.fixture(scope="module")
def cpp_source(tmp_path_factory):
    p = tmp_path_factory.mktemp("customop") / "ops.cc"
    p.write_text(CPP)
    return str(p)


@pytest.fixture(scope="module")
def cube2(cpp_source):
    return load_custom_op("cube2", [cpp_source])


def test_forward_matches_cpp(cube2):
    x = paddle.to_tensor(np.array([0.5, -1.0, 2.0], np.float32))
    y = cube2(x)
    np.testing.assert_allclose(
        np.asarray(y._array), np.array([1.125, -3.0, 12.0]), rtol=1e-6
    )
    assert REGISTRY["cube2"] is cube2


def test_backward_uses_cpp_kernel(cube2):
    xv = np.array([0.5, -1.0, 2.0], np.float32)
    x = paddle.to_tensor(xv)
    x.stop_gradient = False
    cube2(x).sum().backward()
    np.testing.assert_allclose(
        np.asarray(x.grad._array), 3 * xv**2 + 2, rtol=1e-6
    )


def test_custom_op_inside_layer_training(cube2):
    """The op composes with built-in layers on the tape: a Linear upstream
    of the custom op receives gradients THROUGH the C++ backward."""
    paddle.seed(0)
    lin = nn.Linear(4, 4)
    # small lr: the op is cubic, large steps blow up the objective
    opt = paddle.optimizer.SGD(learning_rate=0.005, parameters=lin.parameters())
    x = paddle.to_tensor(np.random.RandomState(0).rand(8, 4).astype(np.float32))
    losses = []
    for _ in range(5):
        loss = (cube2(lin(x)) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(np.asarray(loss._array)))
    assert losses[-1] < losses[0]


def test_custom_op_in_static_program(cube2):
    from paddle_tpu import static

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [3], "float32")
        y = cube2(x)
    exe = static.Executor()
    out = exe.run(prog, feed={"x": np.array([1.0, 2.0, 3.0], np.float32)},
                  fetch_list=[y])
    np.testing.assert_allclose(out[0], [3.0, 12.0, 33.0], rtol=1e-6)


def test_traced_host_callback_warns_once(cube2):
    """VERDICT item 7: tracing a host-callback custom op into a compiled
    program warns ONCE, naming the per-call host round trip — eager use
    (including eager autograd) stays silent."""
    import warnings

    from paddle_tpu.utils import custom_op as co

    co._TRACE_WARNED.discard("cube2")
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning fails the test
        cube2(x)                        # eager forward: silent
        xg = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        xg.stop_gradient = False
        cube2(xg).sum().backward()      # eager autograd: silent
    assert "cube2" not in co._TRACE_WARNED

    from paddle_tpu import static

    with pytest.warns(UserWarning, match="host.*round trip") as rec:
        prog = static.Program()
        with static.program_guard(prog):
            y = cube2(static.data("x", [2], "float32"))
        static.Executor().run(
            prog, feed={"x": np.array([1.0, 2.0], np.float32)},
            fetch_list=[y],
        )
    assert len([w for w in rec if "cube2" in str(w.message)]) == 1
    # once per op: a second compiled program does not warn again
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        prog2 = static.Program()
        with static.program_guard(prog2):
            y2 = cube2(static.data("x", [2], "float32"))
        static.Executor().run(
            prog2, feed={"x": np.array([3.0, 4.0], np.float32)},
            fetch_list=[y2],
        )


def test_forward_only_op_refuses_grad(cpp_source):
    stepfn = load_custom_op("stepfn", [cpp_source])
    x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
    y = stepfn(x)
    np.testing.assert_allclose(np.asarray(y._array), [0.0, 1.0])
    assert y.stop_gradient


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
