"""Systematic op parity sweep: numpy forward reference + numeric-vs-autodiff
gradient checks over the op library (VERDICT round-2 item 5; reference
unittests/op_test.py:326). One OpCase per enrolled op; exemptions in
op_test_whitelist.py."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.ops import (
    activation as A,
    common_nn as CN,
    conv_pool as CP,
    creation as CR,
    linalg as L,
    logic as LG,
    loss_ops as LO,
    manipulation as MA,
    math as M,
    norm_ops as NO,
    search as S,
)

from op_test import OpCase, check_grad, check_output
from op_test_whitelist import FWD_RTOL, GRAD_TOL, NO_GRAD_CHECK

try:
    from scipy import special as sps
except Exception:  # pragma: no cover
    sps = None


# ---- input makers -----------------------------------------------------------

def n(*shape, lo=-1.0, hi=1.0, dtype=np.float32):
    def make(rs):
        return (rs.uniform(lo, hi, shape).astype(dtype),)
    return make


def n2(*shape, lo=-1.0, hi=1.0):
    def make(rs):
        return (
            rs.uniform(lo, hi, shape).astype(np.float32),
            rs.uniform(lo, hi, shape).astype(np.float32),
        )
    return make


def pos(*shape, lo=0.2, hi=2.0):
    return n(*shape, lo=lo, hi=hi)


def unit(*shape):  # open interval (0, 1) away from endpoints
    return n(*shape, lo=0.05, hi=0.95)


def ints(*shape, lo=0, hi=8):
    def make(rs):
        return (rs.randint(lo, hi, shape).astype(np.int32),)
    return make


def _softmax_np(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def _spd(rs, k):
    a = rs.uniform(-1, 1, (k, k)).astype(np.float32)
    return (a @ a.T + k * np.eye(k, dtype=np.float32),)


# ---- the enrolment table ----------------------------------------------------

CASES = [
    # math: elementwise binary
    OpCase("add", M.add, n2(3, 4), np.add),
    OpCase("subtract", M.subtract, n2(3, 4), np.subtract),
    OpCase("multiply", M.multiply, n2(3, 4), np.multiply),
    OpCase("divide", M.divide, lambda rs: (rs.uniform(-1, 1, (3, 4)).astype(np.float32), rs.uniform(0.5, 2, (3, 4)).astype(np.float32)), np.divide),
    OpCase("pow", M.pow, lambda rs: (rs.uniform(0.5, 2, (3, 4)).astype(np.float32), np.float32(2.3)), lambda a, b: a ** b),
    OpCase("maximum", M.maximum, n2(3, 4), np.maximum),
    OpCase("minimum", M.minimum, n2(3, 4), np.minimum),
    OpCase("fmax", M.fmax, n2(3, 4), np.fmax),
    OpCase("fmin", M.fmin, n2(3, 4), np.fmin),
    OpCase("mod", M.mod, lambda rs: (rs.uniform(0, 4, (6,)).astype(np.float32), rs.uniform(1, 3, (6,)).astype(np.float32)), np.mod),
    OpCase("floor_divide", M.floor_divide, lambda rs: (rs.uniform(1, 9, (6,)).astype(np.float32), rs.uniform(1, 3, (6,)).astype(np.float32)), np.floor_divide, grad=False),
    OpCase("atan2", M.atan2, n2(3, 4), np.arctan2),
    OpCase("copysign", M.copysign, n2(3, 4), np.copysign, grad=False),
    OpCase("hypot", M.hypot, lambda rs: (rs.uniform(0.5, 2, (5,)).astype(np.float32), rs.uniform(0.5, 2, (5,)).astype(np.float32)), np.hypot),
    OpCase("logaddexp", M.logaddexp, n2(3, 4), np.logaddexp),
    OpCase("heaviside", M.heaviside, n2(3, 4), np.heaviside),
    OpCase("nextafter", M.nextafter, n2(4,), np.nextafter, grad=False),
    OpCase("lerp", M.lerp, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(3, 4).astype(np.float32), np.float32(0.3)), lambda a, b, w: a + w * (b - a)),
    OpCase("gcd", M.gcd, lambda rs: (rs.randint(1, 40, (6,)), rs.randint(1, 40, (6,))), np.gcd, grad=False),
    OpCase("lcm", M.lcm, lambda rs: (rs.randint(1, 12, (6,)), rs.randint(1, 12, (6,))), np.lcm, grad=False),
    # math: elementwise unary
    OpCase("abs", M.abs, n(3, 4, lo=0.2, hi=1.0), np.abs),
    OpCase("neg", M.neg, n(3, 4), np.negative),
    OpCase("exp", M.exp, n(3, 4), np.exp),
    OpCase("expm1", M.expm1, n(3, 4), np.expm1),
    OpCase("log", M.log, pos(3, 4), np.log),
    OpCase("log2", M.log2, pos(3, 4), np.log2),
    OpCase("log10", M.log10, pos(3, 4), np.log10),
    OpCase("log1p", M.log1p, pos(3, 4), np.log1p),
    OpCase("sqrt", M.sqrt, pos(3, 4), np.sqrt),
    OpCase("rsqrt", M.rsqrt, pos(3, 4), lambda a: 1.0 / np.sqrt(a)),
    OpCase("square", M.square, n(3, 4), np.square),
    OpCase("reciprocal", M.reciprocal, pos(3, 4), np.reciprocal),
    OpCase("sin", M.sin, n(3, 4), np.sin),
    OpCase("cos", M.cos, n(3, 4), np.cos),
    OpCase("tan", M.tan, n(3, 4), np.tan),
    OpCase("asin", M.asin, n(3, 4, lo=-0.8, hi=0.8), np.arcsin),
    OpCase("acos", M.acos, n(3, 4, lo=-0.8, hi=0.8), np.arccos),
    OpCase("atan", M.atan, n(3, 4), np.arctan),
    OpCase("sinh", M.sinh, n(3, 4), np.sinh),
    OpCase("cosh", M.cosh, n(3, 4), np.cosh),
    OpCase("tanh", M.tanh, n(3, 4), np.tanh),
    OpCase("asinh", M.asinh, n(3, 4), np.arcsinh),
    OpCase("acosh", M.acosh, n(3, 4, lo=1.5, hi=3.0), np.arccosh),
    OpCase("atanh", M.atanh, n(3, 4, lo=-0.7, hi=0.7), np.arctanh),
    OpCase("floor", M.floor, n(3, 4, lo=-3, hi=3), np.floor),
    OpCase("ceil", M.ceil, n(3, 4, lo=-3, hi=3), np.ceil),
    OpCase("round", M.round, n(3, 4, lo=-3, hi=3), np.round),
    OpCase("trunc", M.trunc, n(3, 4, lo=-3, hi=3), np.trunc),
    OpCase("frac", M.frac, n(3, 4, lo=-3, hi=3), lambda a: a - np.trunc(a)),
    OpCase("sign", M.sign, n(3, 4), np.sign),
    OpCase("sigmoid", M.sigmoid, n(3, 4), lambda a: 1 / (1 + np.exp(-a))),
    OpCase("erf", M.erf, n(3, 4), (lambda a: sps.erf(a)) if sps else None),
    OpCase("erfinv", M.erfinv, n(3, 4, lo=-0.7, hi=0.7), (lambda a: sps.erfinv(a)) if sps else None),
    OpCase("lgamma", M.lgamma, pos(3, 4, lo=0.5, hi=3.0), (lambda a: sps.gammaln(a)) if sps else None),
    OpCase("digamma", M.digamma, pos(3, 4, lo=0.5, hi=3.0), (lambda a: sps.digamma(a)) if sps else None),
    OpCase("i0", M.i0, n(3, 4), (lambda a: sps.i0(a)) if sps else None),
    OpCase("i1", M.i1, n(3, 4), (lambda a: sps.i1(a)) if sps else None),
    OpCase("logit", M.logit, unit(3, 4), (lambda a: sps.logit(a)) if sps else None),
    OpCase("deg2rad", M.deg2rad, n(5,), np.deg2rad),
    OpCase("rad2deg", M.rad2deg, n(5,), np.rad2deg),
    OpCase("isnan", M.isnan, lambda rs: (np.array([1.0, np.nan, np.inf], np.float32),), np.isnan, grad=False),
    OpCase("isinf", M.isinf, lambda rs: (np.array([1.0, np.nan, np.inf], np.float32),), np.isinf, grad=False),
    OpCase("isfinite", M.isfinite, lambda rs: (np.array([1.0, np.nan, np.inf], np.float32),), np.isfinite, grad=False),
    OpCase("nan_to_num", M.nan_to_num, lambda rs: (np.array([1.0, np.nan, np.inf, -np.inf], np.float32),), np.nan_to_num, grad=False),
    # math: reductions
    OpCase("sum", M.sum, n(3, 4), np.sum, kwargs={"axis": 1}, ref_kwargs=True),
    OpCase("mean", M.mean, n(3, 4), np.mean, kwargs={"axis": 0}, ref_kwargs=True),
    OpCase("max", M.max, n(3, 4), lambda a: np.max(a, axis=1), kwargs={"axis": 1}),
    OpCase("min", M.min, n(3, 4), lambda a: np.min(a, axis=1), kwargs={"axis": 1}),
    OpCase("amax", M.amax, n(3, 4), lambda a: np.max(a, axis=1), kwargs={"axis": 1}),
    OpCase("amin", M.amin, n(3, 4), lambda a: np.min(a, axis=1), kwargs={"axis": 1}),
    OpCase("prod", M.prod, pos(2, 3), lambda a: np.prod(a, axis=1), kwargs={"axis": 1}),
    OpCase("std", M.std, n(3, 4), lambda a: np.std(a, ddof=1)),
    OpCase("var", M.var, n(3, 4), lambda a: np.var(a, ddof=1), gtol=1e-2),
    OpCase("median", M.median, n(3, 5), np.median),
    OpCase("nanmean", M.nanmean, lambda rs: (np.where(rs.rand(3, 4) < 0.2, np.nan, rs.rand(3, 4)).astype(np.float32),), np.nanmean, grad=False),
    OpCase("nansum", M.nansum, lambda rs: (np.where(rs.rand(3, 4) < 0.2, np.nan, rs.rand(3, 4)).astype(np.float32),), np.nansum, grad=False),
    OpCase("logsumexp", M.logsumexp, n(3, 4), lambda a: np.log(np.sum(np.exp(a)))),
    OpCase("count_nonzero", M.count_nonzero, lambda rs: (rs.randint(0, 2, (3, 4)).astype(np.float32),), np.count_nonzero, grad=False),
    OpCase("all", M.all, lambda rs: (rs.randint(0, 2, (3, 4)).astype(bool),), np.all, grad=False),
    OpCase("any", M.any, lambda rs: (rs.randint(0, 2, (3, 4)).astype(bool),), np.any, grad=False),
    # math: scans & misc
    OpCase("cumsum", M.cumsum, n(3, 4), lambda a: np.cumsum(a, axis=1), kwargs={"axis": 1}),
    OpCase("cumprod", M.cumprod, pos(2, 3), lambda a: np.cumprod(a, axis=1), kwargs={"dim": 1}),
    OpCase("clip", M.clip, n(3, 4, lo=-2, hi=2), lambda a: np.clip(a, -0.5, 0.5), kwargs={"min": -0.5, "max": 0.5}),
    OpCase("diff", M.diff, n(2, 5), lambda a: np.diff(a, axis=-1)),
    OpCase("kron", M.kron, lambda rs: (rs.rand(2, 2).astype(np.float32), rs.rand(2, 3).astype(np.float32)), np.kron),
    OpCase("trace", M.trace, n(4, 4), np.trace),
    OpCase("diagonal", M.diagonal, n(3, 4), lambda a: np.diagonal(a)),
    OpCase("outer", M.outer, lambda rs: (rs.rand(3).astype(np.float32), rs.rand(4).astype(np.float32)), np.outer),
    OpCase("inner", M.inner, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(2, 4).astype(np.float32)), np.inner),
    OpCase("scale", M.scale, n(3, 4), lambda a: a * 2.5 + 1.0, kwargs={"scale": 2.5, "bias": 1.0}),
    OpCase("remainder", M.remainder, lambda rs: (rs.uniform(0, 4, (6,)).astype(np.float32), rs.uniform(1, 3, (6,)).astype(np.float32)), np.remainder),
    OpCase("real", M.real, lambda rs: ((rs.rand(3, 2) + 1j * rs.rand(3, 2)).astype(np.complex64),), np.real, grad=False),
    OpCase("imag", M.imag, lambda rs: ((rs.rand(3, 2) + 1j * rs.rand(3, 2)).astype(np.complex64),), np.imag, grad=False),
    OpCase("conj", M.conj, lambda rs: ((rs.rand(3, 2) + 1j * rs.rand(3, 2)).astype(np.complex64),), np.conj, grad=False),
    OpCase("angle", M.angle, lambda rs: ((rs.rand(3, 2) + 0.5 + 1j * rs.rand(3, 2)).astype(np.complex64),), np.angle, grad=False),
    # activation
    OpCase("relu", A.relu, n(3, 4, lo=0.1, hi=1.0), lambda a: np.maximum(a, 0)),
    OpCase("leaky_relu", A.leaky_relu, n(3, 4, lo=0.1), lambda a: np.where(a >= 0, a, 0.01 * a)),
    OpCase("gelu", A.gelu, n(3, 4), (lambda a: a * sps.ndtr(a)) if sps else None),
    OpCase("silu", A.silu, n(3, 4), lambda a: a / (1 + np.exp(-a))),
    OpCase("swish", A.swish, n(3, 4), lambda a: a / (1 + np.exp(-a))),
    OpCase("elu", A.elu, n(3, 4), lambda a: np.where(a > 0, a, np.exp(a) - 1)),
    OpCase("celu", A.celu, n(3, 4), lambda a: np.maximum(a, 0) + np.minimum(0, np.exp(a) - 1)),
    OpCase("selu", A.selu, n(3, 4), lambda a: 1.0507009873554805 * np.where(a > 0, a, 1.6732632423543772 * (np.exp(a) - 1))),
    OpCase("relu6", A.relu6, n(3, 4, lo=-1, hi=7), lambda a: np.minimum(np.maximum(a, 0), 6)),
    OpCase("softplus", A.softplus, n(3, 4), lambda a: np.log1p(np.exp(a))),
    OpCase("softsign", A.softsign, n(3, 4), lambda a: a / (1 + np.abs(a))),
    OpCase("tanhshrink", A.tanhshrink, n(3, 4), lambda a: a - np.tanh(a)),
    OpCase("hardtanh", A.hardtanh, n(3, 4, lo=-2, hi=2), lambda a: np.clip(a, -1, 1)),
    OpCase("hardshrink", A.hardshrink, n(3, 4, lo=-2, hi=2), lambda a: np.where(np.abs(a) > 0.5, a, 0)),
    OpCase("softshrink", A.softshrink, n(3, 4, lo=-2, hi=2), lambda a: np.where(a > 0.5, a - 0.5, np.where(a < -0.5, a + 0.5, 0))),
    OpCase("hardsigmoid", A.hardsigmoid, n(3, 4, lo=-4, hi=4), lambda a: np.clip(a / 6 + 0.5, 0, 1)),
    OpCase("hardswish", A.hardswish, n(3, 4, lo=-4, hi=4), lambda a: a * np.clip(a / 6 + 0.5, 0, 1)),
    OpCase("mish", A.mish, n(3, 4), lambda a: a * np.tanh(np.log1p(np.exp(a)))),
    OpCase("log_sigmoid", A.log_sigmoid, n(3, 4), lambda a: -np.log1p(np.exp(-a))),
    OpCase("softmax", A.softmax, n(3, 4), lambda a: _softmax_np(a), kwargs={"axis": -1}),
    OpCase("log_softmax", A.log_softmax, n(3, 4), lambda a: np.log(_softmax_np(a)), kwargs={"axis": -1}),
    OpCase("stanh", M.stanh, n(3, 4), lambda a: 1.7159 * np.tanh(0.67 * a)),
    OpCase("thresholded_relu", A.thresholded_relu, n(3, 4, lo=-2, hi=3), lambda a: np.where(a > 1.0, a, 0)),
    OpCase("glu", A.glu, n(3, 4), lambda a: a[:, :2] * (1 / (1 + np.exp(-a[:, 2:]))), gtol=1e-2),
    # linalg
    OpCase("matmul", L.matmul, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(4, 5).astype(np.float32)), np.matmul),
    OpCase("bmm", L.bmm, lambda rs: (rs.rand(2, 3, 4).astype(np.float32), rs.rand(2, 4, 5).astype(np.float32)), np.matmul),
    OpCase("mm", L.mm, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(4, 5).astype(np.float32)), np.matmul),
    OpCase("mv", L.mv, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(4).astype(np.float32)), np.matmul),
    OpCase("dot", L.dot, lambda rs: (rs.rand(5).astype(np.float32), rs.rand(5).astype(np.float32)), np.dot),
    OpCase("cross", L.cross, lambda rs: (rs.rand(4, 3).astype(np.float32), rs.rand(4, 3).astype(np.float32)), lambda a, b: np.cross(a, b)),
    OpCase("det", L.det, lambda rs: _spd(rs, 3), np.linalg.det),
    OpCase("slogdet", L.slogdet, lambda rs: _spd(rs, 3), lambda a: np.stack(np.linalg.slogdet(a)), grad=False),
    OpCase("inv", L.inv, lambda rs: _spd(rs, 3), np.linalg.inv),
    OpCase("matrix_power", L.matrix_power, lambda rs: _spd(rs, 3), lambda a: np.linalg.matrix_power(a, 2), kwargs={"n": 2}),
    OpCase("cholesky", L.cholesky, lambda rs: _spd(rs, 3), np.linalg.cholesky),
    OpCase("solve", L.solve, lambda rs: _spd(rs, 3) + (rs.rand(3, 2).astype(np.float32),), np.linalg.solve),
    OpCase("norm", L.norm, n(3, 4), np.linalg.norm, gtol=1e-2),
    OpCase("vector_norm", L.vector_norm, n(6,), np.linalg.norm, gtol=1e-2),
    OpCase("multi_dot", lambda a, b, c: L.multi_dot([a, b, c]), lambda rs: (rs.rand(2, 3).astype(np.float32), rs.rand(3, 4).astype(np.float32), rs.rand(4, 2).astype(np.float32)), lambda a, b, c: a @ b @ c),
    OpCase("einsum", lambda a, b: L.einsum("ij,jk->ik", a, b), lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(4, 2).astype(np.float32)), np.matmul),
    OpCase("pinv", L.pinv, lambda rs: (rs.rand(4, 3).astype(np.float32),), np.linalg.pinv, grad=False, rtol=1e-4, atol=1e-5),
    OpCase("qr", L.qr, lambda rs: (rs.rand(4, 3).astype(np.float32),), lambda a: list(np.linalg.qr(a)), grad=False, rtol=1e-4, atol=1e-4),
    OpCase("svd", L.svd, lambda rs: (rs.rand(3, 3).astype(np.float32) + 2 * np.eye(3, dtype=np.float32),), None, grad=False),
    OpCase("eigvalsh", L.eigvalsh, lambda rs: _spd(rs, 3), np.linalg.eigvalsh, grad=False, rtol=1e-4, atol=1e-4),
    OpCase("cov", L.cov, n(3, 6), lambda a: np.cov(a), gtol=1e-2),
    OpCase("corrcoef", L.corrcoef, n(3, 6), lambda a: np.corrcoef(a), grad=False, rtol=1e-4, atol=1e-5),
    OpCase("dist", L.dist, n2(3, 4), lambda a, b: np.linalg.norm((a - b).ravel())),
    # manipulation
    OpCase("reshape", MA.reshape, n(3, 4), lambda a: a.reshape(2, 6), kwargs={"shape": [2, 6]}),
    OpCase("transpose", MA.transpose, n(2, 3, 4), lambda a: a.transpose(2, 0, 1), kwargs={"perm": [2, 0, 1]}),
    OpCase("t", MA.t, n(3, 4), lambda a: a.T),
    OpCase("concat", lambda a, b: MA.concat([a, b], axis=1), n2(3, 4), lambda a, b: np.concatenate([a, b], 1)),
    OpCase("stack", lambda a, b: MA.stack([a, b], axis=0), n2(3, 4), lambda a, b: np.stack([a, b], 0)),
    OpCase("split", MA.split, n(4, 6), lambda a: list(np.split(a, 2, 1)), kwargs={"num_or_sections": 2, "axis": 1}),
    OpCase("chunk", MA.chunk, n(4, 6), lambda a: list(np.split(a, 2, 0)), kwargs={"chunks": 2, "axis": 0}),
    OpCase("squeeze", MA.squeeze, n(3, 1, 4), lambda a: a.squeeze(1), kwargs={"axis": 1}),
    OpCase("unsqueeze", MA.unsqueeze, n(3, 4), lambda a: a[:, None], kwargs={"axis": 1}),
    OpCase("flatten", MA.flatten, n(2, 3, 4), lambda a: a.reshape(2, 12), kwargs={"start_axis": 1, "stop_axis": 2}),
    OpCase("tile", MA.tile, n(2, 3), lambda a: np.tile(a, (2, 2)), kwargs={"repeat_times": [2, 2]}),
    OpCase("expand", MA.expand, n(1, 3), lambda a: np.broadcast_to(a, (4, 3)), kwargs={"shape": [4, 3]}),
    OpCase("broadcast_to", MA.broadcast_to, n(1, 3), lambda a: np.broadcast_to(a, (4, 3)), kwargs={"shape": [4, 3]}),
    OpCase("roll", MA.roll, n(3, 4), lambda a: np.roll(a, 2), kwargs={"shifts": 2}),
    OpCase("flip", MA.flip, n(3, 4), lambda a: np.flip(a, 1), kwargs={"axis": 1}),
    OpCase("rot90", MA.rot90, n(3, 4), lambda a: np.rot90(a)),
    OpCase("moveaxis", MA.moveaxis, n(2, 3, 4), lambda a: np.moveaxis(a, 0, 2), kwargs={"source": 0, "destination": 2}),
    OpCase("swapaxes", MA.swapaxes, n(2, 3, 4), lambda a: np.swapaxes(a, 0, 2), kwargs={"axis0": 0, "axis1": 2}),
    OpCase("pad_manip", MA.pad, n(2, 3), lambda a: np.pad(a, ((1, 1), (2, 2))), kwargs={"pad": [1, 1, 2, 2]}),
    OpCase("gather", MA.gather, lambda rs: (rs.rand(5, 3).astype(np.float32), np.array([0, 2, 4])), lambda a, i: a[i]),
    OpCase("index_select", MA.index_select, lambda rs: (rs.rand(5, 3).astype(np.float32), np.array([0, 2])), lambda a, i: a[i], kwargs={"axis": 0}),
    OpCase("take", MA.take, lambda rs: (rs.rand(3, 4).astype(np.float32), np.array([0, 5, 11])), lambda a, i: np.take(a, i)),
    OpCase("take_along_axis", MA.take_along_axis, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.randint(0, 4, (3, 2))), lambda a, i: np.take_along_axis(a, i, 1), kwargs={"axis": 1}),
    OpCase("gather_nd", MA.gather_nd, lambda rs: (rs.rand(3, 4).astype(np.float32), np.array([[0, 1], [2, 3]])), lambda a, i: a[tuple(i.T)]),
    OpCase("repeat_interleave", MA.repeat_interleave, n(2, 3), lambda a: np.repeat(a, 2, 1), kwargs={"repeats": 2, "axis": 1}),
    OpCase("unbind", MA.unbind, n(3, 4), lambda a: list(a), kwargs={"axis": 0}),
    OpCase("unstack", MA.unbind, n(3, 4), lambda a: list(a)),
    OpCase("slice", MA.slice, n(4, 5), lambda a: a[1:3], kwargs={"axes": [0], "starts": [1], "ends": [3]}),
    OpCase("strided_slice", MA.strided_slice, n(4, 6), lambda a: a[:, 1:6:2], kwargs={"axes": [1], "starts": [1], "ends": [6], "strides": [2]}),
    OpCase("crop", MA.crop, n(4, 5), lambda a: a[1:3, 2:5], kwargs={"shape": [2, 3], "offsets": [1, 2]}),
    OpCase("where_op", MA.where, lambda rs: (rs.rand(3, 4) > 0.5, rs.rand(3, 4).astype(np.float32), rs.rand(3, 4).astype(np.float32)), np.where),
    OpCase("masked_fill", MA.masked_fill, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(3, 4) > 0.5, np.float32(9.0)), lambda a, m, v: np.where(m, v, a), grad_idx=[0]),
    OpCase("index_sample", MA.index_sample, lambda rs: (rs.rand(3, 5).astype(np.float32), rs.randint(0, 5, (3, 2))), lambda a, i: np.take_along_axis(a, i, 1)),
    OpCase("tensordot", MA.tensordot, lambda rs: (rs.rand(2, 3, 4).astype(np.float32), rs.rand(4, 3, 5).astype(np.float32)), lambda a, b: np.tensordot(a, b, axes=1), kwargs={"axes": 1}),
    OpCase("as_strided_cast", MA.cast, n(3, 4), lambda a: a.astype(np.float64), kwargs={"dtype": "float64"}, grad=False),
    OpCase("nonzero", MA.nonzero, lambda rs: (np.array([[1.0, 0.0], [0.0, 2.0]], np.float32),), lambda a: np.stack(np.nonzero(a), 1), grad=False),
    OpCase("unique", MA.unique, lambda rs: (np.array([3, 1, 2, 1, 3], np.int64),), np.unique, grad=False),
    OpCase("scatter_nd_add", MA.scatter_nd_add, lambda rs: (rs.rand(5, 3).astype(np.float32), np.array([[1], [3]]), rs.rand(2, 3).astype(np.float32)), None, grad=False),
    # creation (forward-only where random or trivial)
    OpCase("zeros", lambda: CR.zeros([2, 3]), lambda rs: (), lambda: np.zeros((2, 3), np.float32), grad=False),
    OpCase("ones", lambda: CR.ones([2, 3]), lambda rs: (), lambda: np.ones((2, 3), np.float32), grad=False),
    OpCase("full", lambda: CR.full([2, 2], 7.0), lambda rs: (), lambda: np.full((2, 2), 7.0, np.float32), grad=False),
    OpCase("arange", lambda: CR.arange(0, 10, 2), lambda rs: (), lambda: np.arange(0, 10, 2), grad=False),
    OpCase("linspace", lambda: CR.linspace(0.0, 1.0, 5), lambda rs: (), lambda: np.linspace(0, 1, 5, dtype=np.float32), grad=False),
    OpCase("logspace", lambda: CR.logspace(0.0, 2.0, 3), lambda rs: (), lambda: np.logspace(0, 2, 3, dtype=np.float32), grad=False, rtol=1e-4),
    OpCase("eye", lambda: CR.eye(3, 4), lambda rs: (), lambda: np.eye(3, 4, dtype=np.float32), grad=False),
    OpCase("tril", CR.tril, n(4, 4), np.tril),
    OpCase("triu", CR.triu, n(4, 4), np.triu),
    OpCase("diag", CR.diag, n(4,), np.diag, grad=False),
    OpCase("diagflat", CR.diagflat, n(4,), np.diagflat, grad=False),
    OpCase("zeros_like", CR.zeros_like, n(2, 3), np.zeros_like, grad=False),
    OpCase("ones_like", CR.ones_like, n(2, 3), np.ones_like, grad=False),
    OpCase("full_like", CR.full_like, n(2, 3), lambda a: np.full_like(a, 5.0), kwargs={"fill_value": 5.0}, grad=False),
    OpCase("numel", CR.numel, n(2, 3), lambda a: np.int64(a.size), grad=False),
    OpCase("meshgrid", lambda a, b: CR.meshgrid(a, b), lambda rs: (rs.rand(3).astype(np.float32), rs.rand(2).astype(np.float32)), lambda a, b: list(np.meshgrid(a, b, indexing="ij")), grad=False),
    OpCase("as_complex", CR.as_complex, n(3, 2), lambda a: (a[..., 0] + 1j * a[..., 1]).astype(np.complex64), grad=False),
    OpCase("as_real", CR.as_real, lambda rs: ((rs.rand(3) + 1j * rs.rand(3)).astype(np.complex64),), lambda a: np.stack([a.real, a.imag], -1), grad=False),
    # logic
    OpCase("equal", LG.equal, lambda rs: (np.array([1, 2, 3], np.int64), np.array([1, 0, 3], np.int64)), np.equal, grad=False),
    OpCase("not_equal", LG.not_equal, lambda rs: (np.array([1, 2], np.int64), np.array([1, 3], np.int64)), np.not_equal, grad=False),
    OpCase("greater_than", LG.greater_than, n2(3, 4), np.greater, grad=False),
    OpCase("greater_equal", LG.greater_equal, n2(3, 4), np.greater_equal, grad=False),
    OpCase("less_than", LG.less_than, n2(3, 4), np.less, grad=False),
    OpCase("less_equal", LG.less_equal, n2(3, 4), np.less_equal, grad=False),
    OpCase("logical_and", LG.logical_and, lambda rs: (rs.rand(4) > 0.5, rs.rand(4) > 0.5), np.logical_and, grad=False),
    OpCase("logical_or", LG.logical_or, lambda rs: (rs.rand(4) > 0.5, rs.rand(4) > 0.5), np.logical_or, grad=False),
    OpCase("logical_not", LG.logical_not, lambda rs: (rs.rand(4) > 0.5,), np.logical_not, grad=False),
    OpCase("logical_xor", LG.logical_xor, lambda rs: (rs.rand(4) > 0.5, rs.rand(4) > 0.5), np.logical_xor, grad=False),
    OpCase("bitwise_and", LG.bitwise_and, lambda rs: (rs.randint(0, 16, (5,)), rs.randint(0, 16, (5,))), np.bitwise_and, grad=False),
    OpCase("bitwise_or", LG.bitwise_or, lambda rs: (rs.randint(0, 16, (5,)), rs.randint(0, 16, (5,))), np.bitwise_or, grad=False),
    OpCase("bitwise_xor", LG.bitwise_xor, lambda rs: (rs.randint(0, 16, (5,)), rs.randint(0, 16, (5,))), np.bitwise_xor, grad=False),
    OpCase("bitwise_not", LG.bitwise_not, lambda rs: (rs.randint(0, 16, (5,)),), np.bitwise_not, grad=False),
    OpCase("isclose", LG.isclose, lambda rs: (np.array([1.0, 2.0], np.float32), np.array([1.0, 2.1], np.float32)), np.isclose, grad=False),
    # search
    OpCase("argmax", S.argmax, n(3, 4), lambda a: np.argmax(a, 1), kwargs={"axis": 1}, grad=False),
    OpCase("argmin", S.argmin, n(3, 4), lambda a: np.argmin(a, 1), kwargs={"axis": 1}, grad=False),
    OpCase("argsort", S.argsort, n(3, 4), lambda a: np.argsort(a, 1, kind="stable"), kwargs={"axis": 1}, grad=False),
    OpCase("sort", S.sort, n(3, 4), lambda a: np.sort(a, 1), kwargs={"axis": 1}),
    OpCase("topk", S.topk, n(3, 5), lambda a: [np.sort(a, 1)[:, ::-1][:, :2], np.argsort(-a, 1, kind="stable")[:, :2]], kwargs={"k": 2}, grad=False),
    OpCase("kthvalue", S.kthvalue, n(3, 5), lambda a: [np.sort(a, 1)[:, 1], np.argsort(a, 1, kind="stable")[:, 1]], kwargs={"k": 2}, grad=False),
    OpCase("searchsorted", S.searchsorted, lambda rs: (np.array([1.0, 3.0, 5.0, 7.0], np.float32), np.array([2.0, 6.0], np.float32)), np.searchsorted, grad=False),
    OpCase("bucketize", S.bucketize, lambda rs: (np.array([2.0, 6.0], np.float32), np.array([1.0, 3.0, 5.0, 7.0], np.float32)), lambda x, e: np.searchsorted(e, x), grad=False),
    OpCase("bincount", S.bincount, lambda rs: (np.array([0, 1, 1, 3], np.int64),), np.bincount, grad=False),
    OpCase("histogram", S.histogram, lambda rs: (rs.rand(20).astype(np.float32),), lambda a: np.histogram(a, bins=4, range=(0, 1))[0], kwargs={"bins": 4, "min": 0, "max": 1}, grad=False),
    OpCase("mode", S.mode, lambda rs: (np.array([[1.0, 1.0, 2.0], [3.0, 3.0, 1.0]], np.float32),), lambda a: [np.array([1.0, 3.0], np.float32), np.array([1, 1])], grad=False),
    # common_nn / norm / conv / pool
    OpCase("linear", CN.linear, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(4, 2).astype(np.float32), rs.rand(2).astype(np.float32)), lambda x, w, b: x @ w + b),
    OpCase("one_hot", CN.one_hot, lambda rs: (np.array([0, 2, 1], np.int64),), lambda a: np.eye(4, dtype=np.float32)[a], kwargs={"num_classes": 4}, grad=False),
    OpCase("embedding", CN.embedding, lambda rs: (np.array([[0, 2], [1, 1]], np.int64), rs.rand(4, 3).astype(np.float32)), lambda i, w: w[i], grad_idx=[1]),
    OpCase("label_smooth", CN.label_smooth, lambda rs: (np.eye(3, dtype=np.float32)[np.array([0, 2])],), lambda a: a * 0.9 + 0.1 / 3, kwargs={"epsilon": 0.1}),
    OpCase("cosine_similarity", LO.cosine_similarity, n2(3, 4, lo=0.2, hi=1.0), lambda a, b: np.sum(a * b, 1) / (np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1))),
    OpCase("normalize", NO.normalize, n(3, 4, lo=0.2, hi=1.0), lambda a: a / np.linalg.norm(a, axis=1, keepdims=True)),
    OpCase("layer_norm", lambda x, w, b: NO.layer_norm(x, [6], w, b), lambda rs: (rs.rand(2, 6).astype(np.float32), rs.rand(6).astype(np.float32), rs.rand(6).astype(np.float32)), lambda x, w, b: (x - x.mean(-1, keepdims=True)) / np.sqrt(x.var(-1, keepdims=True) + 1e-5) * w + b),
    OpCase("rms_norm", NO.rms_norm, lambda rs: (rs.rand(2, 6).astype(np.float32), rs.rand(6).astype(np.float32)), lambda x, w: x / np.sqrt((x ** 2).mean(-1, keepdims=True) + 1e-6) * w, kwargs={"epsilon": 1e-6}),
    OpCase("conv2d", CP.conv2d, lambda rs: (rs.rand(1, 2, 4, 4).astype(np.float32), rs.rand(3, 2, 3, 3).astype(np.float32)), None, gtol=1e-2),
    OpCase("conv2d_transpose", CP.conv2d_transpose, lambda rs: (rs.rand(1, 2, 3, 3).astype(np.float32), rs.rand(2, 3, 2, 2).astype(np.float32)), None, gtol=1e-2),
    OpCase("max_pool2d", CP.max_pool2d, lambda rs: (rs.rand(1, 2, 4, 4).astype(np.float32),), None, kwargs={"kernel_size": 2}, grad=False),
    OpCase("avg_pool2d", CP.avg_pool2d, lambda rs: (rs.rand(1, 2, 4, 4).astype(np.float32),), None, kwargs={"kernel_size": 2}),
    OpCase("adaptive_avg_pool2d", CP.adaptive_avg_pool2d, lambda rs: (rs.rand(1, 2, 4, 4).astype(np.float32),), None, kwargs={"output_size": 2}),
    OpCase("pixel_shuffle", CP.pixel_shuffle, lambda rs: (rs.rand(1, 4, 2, 2).astype(np.float32),), None, kwargs={"upscale_factor": 2}),
    # losses
    OpCase("mse_loss", LO.mse_loss, n2(3, 4), lambda a, b: np.mean((a - b) ** 2)),
    OpCase("l1_loss", LO.l1_loss, n2(3, 4), lambda a, b: np.mean(np.abs(a - b)), gtol=1e-2),
    OpCase("smooth_l1_loss", LO.smooth_l1_loss, n2(3, 4), None, gtol=1e-2),
    OpCase("huber_loss", LO.huber_loss, n2(3, 4), None, gtol=1e-2),
    OpCase("square_error_cost", LO.square_error_cost, n2(3, 4), lambda a, b: (a - b) ** 2),
    OpCase("log_loss", LO.log_loss, lambda rs: (rs.uniform(0.1, 0.9, (4, 1)).astype(np.float32), rs.randint(0, 2, (4, 1)).astype(np.float32)), lambda p, y: -y * np.log(p + 1e-4) - (1 - y) * np.log(1 - p + 1e-4), grad_idx=[0]),
    OpCase("binary_cross_entropy", LO.binary_cross_entropy, lambda rs: (rs.uniform(0.1, 0.9, (4,)).astype(np.float32), rs.randint(0, 2, (4,)).astype(np.float32)), lambda p, y: float(np.mean(-y * np.log(p) - (1 - y) * np.log(1 - p))), grad_idx=[0]),
    OpCase("bce_with_logits", LO.binary_cross_entropy_with_logits, lambda rs: (rs.uniform(-2, 2, (4,)).astype(np.float32), rs.randint(0, 2, (4,)).astype(np.float32)), lambda x, y: float(np.mean(np.maximum(x, 0) - x * y + np.log1p(np.exp(-np.abs(x))))), grad_idx=[0], gtol=1e-2),
    OpCase("kl_div", LO.kl_div, lambda rs: (np.log(_softmax_np(rs.rand(3, 4).astype(np.float32))), _softmax_np(rs.rand(3, 4).astype(np.float32))), None, grad_idx=[0]),
    OpCase("nll_loss", LO.nll_loss, lambda rs: (np.log(_softmax_np(rs.rand(3, 4).astype(np.float32))), np.array([0, 2, 1], np.int64)), lambda lp, t: float(-np.mean(lp[np.arange(3), t])), grad_idx=[0], gtol=1e-2),
    OpCase("cross_entropy", LO.cross_entropy, lambda rs: (rs.rand(3, 4).astype(np.float32), np.array([0, 2, 1], np.int64)), lambda x, t: float(-np.mean(np.log(_softmax_np(x))[np.arange(3), t])), grad_idx=[0]),
    OpCase("softmax_with_cross_entropy", LO.softmax_with_cross_entropy, lambda rs: (rs.rand(3, 4).astype(np.float32), np.array([[0], [2], [1]], np.int64)), None, grad_idx=[0], gtol=1e-2),
    OpCase("margin_ranking_loss", LO.margin_ranking_loss, lambda rs: (rs.rand(4).astype(np.float32), rs.rand(4).astype(np.float32), np.sign(rs.rand(4) - 0.5).astype(np.float32)), None, grad=False),
    OpCase("hinge_embedding_loss", LO.hinge_embedding_loss, lambda rs: (rs.rand(4).astype(np.float32), np.sign(rs.rand(4) - 0.5).astype(np.float32)), None, grad=False),
    OpCase("sigmoid_focal_loss", LO.sigmoid_focal_loss, lambda rs: (rs.uniform(-2, 2, (4, 1)).astype(np.float32), rs.randint(0, 2, (4, 1)).astype(np.float32)), None, grad_idx=[0]),
    OpCase("triplet_margin_loss", LO.triplet_margin_loss, lambda rs: (rs.rand(3, 4).astype(np.float32), rs.rand(3, 4).astype(np.float32), rs.rand(3, 4).astype(np.float32)), None, grad=False),
]

# ---- fft / signal enrolment -------------------------------------------------
from paddle_tpu import fft as FF  # noqa: E402
from paddle_tpu import signal as SG  # noqa: E402

CASES += [
    OpCase("fft", FF.fft, n(2, 8), np.fft.fft, grad=False, rtol=1e-4, atol=1e-4),
    OpCase("ifft", FF.ifft, lambda rs: ((rs.rand(2, 8) + 1j * rs.rand(2, 8)).astype(np.complex64),), np.fft.ifft, grad=False, rtol=1e-4, atol=1e-4),
    OpCase("fft2", FF.fft2, n(4, 4), np.fft.fft2, grad=False, rtol=1e-4, atol=1e-4),
    OpCase("ifft2", FF.ifft2, lambda rs: ((rs.rand(4, 4) + 1j * rs.rand(4, 4)).astype(np.complex64),), np.fft.ifft2, grad=False, rtol=1e-4, atol=1e-4),
    OpCase("fftn", FF.fftn, n(2, 3, 4), np.fft.fftn, grad=False, rtol=1e-4, atol=1e-4),
    OpCase("rfft", FF.rfft, n(2, 8), np.fft.rfft, grad=False, rtol=1e-4, atol=1e-4),
    OpCase("irfft", FF.irfft, lambda rs: ((rs.rand(2, 5) + 1j * rs.rand(2, 5)).astype(np.complex64),), lambda a: np.fft.irfft(a), grad=False, rtol=1e-4, atol=1e-4),
    OpCase("hfft", FF.hfft, lambda rs: ((rs.rand(2, 5) + 1j * rs.rand(2, 5)).astype(np.complex64),), lambda a: np.fft.hfft(a), grad=False, rtol=1e-4, atol=1e-4),
    OpCase("fftfreq", lambda: FF.fftfreq(8, 0.5), lambda rs: (), lambda: np.fft.fftfreq(8, 0.5).astype(np.float32), grad=False),
    OpCase("rfftfreq", lambda: FF.rfftfreq(8, 0.5), lambda rs: (), lambda: np.fft.rfftfreq(8, 0.5).astype(np.float32), grad=False),
    OpCase("fftshift", FF.fftshift, n(2, 8), np.fft.fftshift, grad=False),
    OpCase("ifftshift", FF.ifftshift, n(2, 8), np.fft.ifftshift, grad=False),
    OpCase(
        "signal_frame",
        lambda x: SG.frame(x, frame_length=4, hop_length=2),
        n(16,),
        None,  # shape/grad only (layout is axis-convention specific)
        gtol=1e-2,
    ),
]

# ---- more conv / pool variants ----------------------------------------------
CASES += [
    OpCase("conv1d", CP.conv1d, lambda rs: (rs.rand(1, 2, 8).astype(np.float32), rs.rand(3, 2, 3).astype(np.float32)), None, gtol=1e-2),
    OpCase("conv3d", CP.conv3d, lambda rs: (rs.rand(1, 1, 3, 4, 4).astype(np.float32), rs.rand(2, 1, 2, 2, 2).astype(np.float32)), None, grad=False),
    OpCase("max_pool1d", CP.max_pool1d, lambda rs: (rs.rand(1, 2, 8).astype(np.float32),), None, kwargs={"kernel_size": 2}, grad=False),
    OpCase("avg_pool1d", CP.avg_pool1d, lambda rs: (rs.rand(1, 2, 8).astype(np.float32),), None, kwargs={"kernel_size": 2}, gtol=1e-2),
    OpCase("adaptive_max_pool2d", CP.adaptive_max_pool2d, lambda rs: (rs.rand(1, 2, 4, 4).astype(np.float32),), None, kwargs={"output_size": 2}, grad=False),
    OpCase("pixel_unshuffle", CP.pixel_unshuffle, lambda rs: (rs.rand(1, 1, 4, 4).astype(np.float32),), None, kwargs={"downscale_factor": 2}),
    OpCase("conv1d_transpose", CP.conv1d_transpose, lambda rs: (rs.rand(1, 2, 5).astype(np.float32), rs.rand(2, 3, 2).astype(np.float32)), None, gtol=1e-2),
    OpCase("zeropad2d", CN.zeropad2d, lambda rs: (rs.rand(1, 1, 3, 3).astype(np.float32),), lambda a: np.pad(a, ((0, 0), (0, 0), (1, 1), (2, 2))), kwargs={"padding": [2, 2, 1, 1]}),
    OpCase("unfold", CP.unfold, lambda rs: (rs.rand(1, 2, 4, 4).astype(np.float32),), None, kwargs={"kernel_sizes": 2}, gtol=1e-2),
]

# ---- numpy references for the formerly shape/grad-only cases ---------------
# (r3 verdict weak #9: burn the skip list down). Each implements the
# documented paddle semantics independently in numpy — loops over tiny
# shapes, not a translation of the jnp code.

def _np_conv(x, w, stride=1):
    """Cross-correlation, VALID padding. x [N,C,*sp], w [O,C,*k]."""
    N, C = x.shape[:2]
    O = w.shape[0]
    sp, k = x.shape[2:], w.shape[2:]
    nd = len(sp)
    out_sp = tuple((s - kk) // stride + 1 for s, kk in zip(sp, k))
    out = np.zeros((N, O) + out_sp, np.float32)
    for idx in np.ndindex(*out_sp):
        sl = (slice(None), slice(None)) + tuple(
            slice(i * stride, i * stride + kk) for i, kk in zip(idx, k)
        )
        patch = x[sl]  # [N, C, *k]
        axes = list(range(1, nd + 2))
        out[(slice(None), slice(None)) + idx] = np.tensordot(patch, w, (axes, axes))
    return out


def _np_conv_transpose(x, w, stride=1):
    """x [N,I,*sp], w [I,O,*k] (paddle transpose-conv weight layout)."""
    N, I = x.shape[:2]
    O = w.shape[1]
    sp, k = x.shape[2:], w.shape[2:]
    out_sp = tuple((s - 1) * stride + kk for s, kk in zip(sp, k))
    out = np.zeros((N, O) + out_sp, np.float32)
    for n in range(N):
        for idx in np.ndindex(*sp):
            vec = x[(n, slice(None)) + idx]  # [I]
            for o in range(O):
                region = tuple(
                    slice(i * stride, i * stride + kk) for i, kk in zip(idx, k)
                )
                out[(n, o) + region] += np.tensordot(vec, w[:, o], (0, 0))
    return out


def _np_pool2d(x, k, mode):
    N, C, H, W = x.shape
    out = np.zeros((N, C, H // k, W // k), np.float32)
    red = np.max if mode == "max" else np.mean
    for i in range(H // k):
        for j in range(W // k):
            out[:, :, i, j] = red(
                x[:, :, i * k:(i + 1) * k, j * k:(j + 1) * k], axis=(2, 3)
            )
    return out


def _np_pool1d(x, k, mode):
    N, C, L = x.shape
    red = np.max if mode == "max" else np.mean
    return np.stack(
        [red(x[:, :, i * k:(i + 1) * k], axis=2) for i in range(L // k)], axis=2
    )


def _np_pixel_shuffle(a, r):
    n, c, h, w = a.shape
    a = a.reshape(n, c // (r * r), r, r, h, w)
    return a.transpose(0, 1, 4, 2, 5, 3).reshape(n, c // (r * r), h * r, w * r)


def _np_pixel_unshuffle(a, r):
    n, c, h, w = a.shape
    a = a.reshape(n, c, h // r, r, w // r, r)
    return a.transpose(0, 1, 3, 5, 2, 4).reshape(n, c * r * r, h // r, w // r)


def _np_unfold(x, k):
    """im2col: [N, C*k*k, L], channel-major columns, row-major positions."""
    N, C, H, W = x.shape
    cols = []
    for i in range(H - k + 1):
        for j in range(W - k + 1):
            cols.append(x[:, :, i:i + k, j:j + k].reshape(N, C * k * k))
    return np.stack(cols, axis=2)


def _np_frame(x, fl, hop):
    """signal.frame: out[..., l, f] = x[..., f*hop + l]."""
    n_frames = (x.shape[-1] - fl) // hop + 1
    return np.stack([x[..., f * hop: f * hop + fl] for f in range(n_frames)], -1)


_NEW_REFS = {
    "scatter_nd_add": lambda x, idx, upd: (
        lambda o: (np.add.at(o, idx.reshape(-1), upd), o)[1]
    )(x.copy()),
    "conv2d": _np_conv,
    "conv1d": _np_conv,
    "conv3d": _np_conv,
    "conv2d_transpose": _np_conv_transpose,
    "conv1d_transpose": _np_conv_transpose,
    "max_pool2d": lambda x: _np_pool2d(x, 2, "max"),
    "avg_pool2d": lambda x: _np_pool2d(x, 2, "avg"),
    "adaptive_avg_pool2d": lambda x: _np_pool2d(x, 2, "avg"),  # 4->2 = k2
    "adaptive_max_pool2d": lambda x: _np_pool2d(x, 2, "max"),
    "max_pool1d": lambda x: _np_pool1d(x, 2, "max"),
    "avg_pool1d": lambda x: _np_pool1d(x, 2, "avg"),
    "pixel_shuffle": lambda x: _np_pixel_shuffle(x, 2),
    "pixel_unshuffle": lambda x: _np_pixel_unshuffle(x, 2),
    "unfold": lambda x: _np_unfold(x, 2),
    "signal_frame": lambda x: _np_frame(x, 4, 2),
    "smooth_l1_loss": lambda a, b: float(np.mean(np.where(
        np.abs(a - b) < 1.0, 0.5 * (a - b) ** 2, np.abs(a - b) - 0.5))),
    "huber_loss": lambda a, b: float(np.mean(np.where(
        np.abs(a - b) < 1.0, 0.5 * (a - b) ** 2, np.abs(a - b) - 0.5))),
    "kl_div": lambda lp, y: float(np.mean(y * (np.log(np.maximum(y, 1e-30)) - lp))),
    "softmax_with_cross_entropy": lambda x, t: (
        -np.log(_softmax_np(x))[np.arange(x.shape[0]), t[:, 0]][:, None]
    ),
    "margin_ranking_loss": lambda a, b, y: float(np.mean(np.maximum(0.0, -y * (a - b)))),
    "hinge_embedding_loss": lambda a, y: float(np.mean(np.where(y == 1, a, np.maximum(0.0, 1.0 - a)))),
    "sigmoid_focal_loss": lambda x, y: float(np.sum(
        (0.25 * y + 0.75 * (1 - y))
        * (1 - (1 / (1 + np.exp(-x)) * y + (1 - 1 / (1 + np.exp(-x))) * (1 - y))) ** 2
        * (np.maximum(x, 0) - x * y + np.log1p(np.exp(-np.abs(x)))))),
    "triplet_margin_loss": lambda a, p_, n_: float(np.mean(np.maximum(
        np.sqrt(np.sum((np.abs(a - p_) + 1e-6) ** 2, -1))
        - np.sqrt(np.sum((np.abs(a - n_) + 1e-6) ** 2, -1)) + 1.0, 0.0))),
}
for c in CASES:
    if c.ref is None and c.name in _NEW_REFS:
        c.ref = _NEW_REFS[c.name]

# apply whitelist relaxations / removals
for c in CASES:
    if c.name in FWD_RTOL:
        c.rtol = max(c.rtol, FWD_RTOL[c.name])
        c.atol = max(c.atol, FWD_RTOL[c.name])
    if c.name in GRAD_TOL:
        c.gtol = max(c.gtol, GRAD_TOL[c.name])
    if c.name in NO_GRAD_CHECK:
        c.grad = False

_IDS = [c.name for c in CASES]
assert len(set(_IDS)) == len(_IDS), "duplicate OpCase names"


def test_enrollment_count():
    """The sweep must cover at least 100 ops (VERDICT item 5 bar)."""
    assert len(CASES) >= 100, len(CASES)


@pytest.mark.parametrize("case", CASES, ids=_IDS)
def test_forward(case):
    if case.ref is None:
        if case.name == "svd":
            # reconstruction check instead of a value reference
            rs = np.random.RandomState(0)
            (a,) = case.make_inputs(rs)
            u, s, vh = [np.asarray(t.numpy()) for t in case.op(paddle.to_tensor(a))]
            np.testing.assert_allclose(u @ np.diag(s) @ vh, a, atol=1e-4)
            return
        pytest.skip("no independent numpy reference (shape/grad-only op)")
    check_output(case)


@pytest.mark.parametrize(
    "case", [c for c in CASES if c.grad], ids=[c.name for c in CASES if c.grad]
)
def test_grad(case):
    check_grad(case)
