"""Worker for the 2-process eager-collective test (run via subprocess).

Mirrors the reference's per-rank test program pattern
(test_collective_api_base.py: each rank runs the collective then the parent
verifies) but verification happens in-rank against numpy and the parent only
checks exit codes + OK markers.

Usage: python _collective_worker.py <rank> <nranks> <port>
"""
import os
import sys

RANK = int(sys.argv[1])
NRANKS = int(sys.argv[2])
PORT = sys.argv[3]

os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["MASTER_ADDR"] = "127.0.0.1"
os.environ["MASTER_PORT"] = PORT
os.environ["PADDLE_TRAINERS_NUM"] = str(NRANKS)
os.environ["PADDLE_TRAINER_ID"] = str(RANK)

import jax

jax.config.update("jax_platforms", "cpu")
# must run before anything touches the XLA backend (paddle_tpu import does)
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{PORT}", num_processes=NRANKS, process_id=RANK
)

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import paddle_tpu as paddle
import paddle_tpu.distributed as dist

env = dist.init_parallel_env()
assert env.rank == RANK and env.world_size == NRANKS, (env.rank, env.world_size)
assert jax.process_count() == NRANKS

ranks = list(range(NRANKS))


def rank_val(r, base=0):
    return np.arange(4, dtype=np.float32) + 10.0 * r + base


# all_reduce (sum / max / prod) on a paddle Tensor, in place
t = paddle.to_tensor(rank_val(RANK))
dist.all_reduce(t)
np.testing.assert_allclose(t.numpy(), sum(rank_val(r) for r in ranks))

t = paddle.to_tensor(rank_val(RANK))
dist.all_reduce(t, op=dist.ReduceOp.MAX)
np.testing.assert_allclose(t.numpy(), rank_val(NRANKS - 1))

# all_gather in rank order
gathered = []
dist.all_gather(gathered, paddle.to_tensor(rank_val(RANK)))
assert len(gathered) == NRANKS
for r in ranks:
    np.testing.assert_allclose(gathered[r].numpy(), rank_val(r))

# broadcast from src=1
t = paddle.to_tensor(rank_val(RANK))
dist.broadcast(t, src=1)
np.testing.assert_allclose(t.numpy(), rank_val(1))

# reduce to dst=1: only dst holds the sum
t = paddle.to_tensor(rank_val(RANK))
dist.reduce(t, dst=1)
expect = sum(rank_val(r) for r in ranks) if RANK == 1 else rank_val(RANK)
np.testing.assert_allclose(t.numpy(), expect)

# reduce_scatter: rank r gets sum_p in_list[p][r]
in_list = [paddle.to_tensor(rank_val(RANK, base=100.0 * j)) for j in range(NRANKS)]
out = paddle.to_tensor(np.zeros(4, dtype=np.float32))
dist.reduce_scatter(out, in_list)
np.testing.assert_allclose(out.numpy(), sum(rank_val(r, base=100.0 * RANK) for r in ranks))

# scatter from src=0
src_list = [paddle.to_tensor(rank_val(j, base=7.0)) for j in range(NRANKS)]
out = paddle.to_tensor(np.zeros(4, dtype=np.float32))
dist.scatter(out, src_list if RANK == 0 else None, src=0)
np.testing.assert_allclose(out.numpy(), rank_val(RANK, base=7.0))

# alltoall: rank r receives in_list[r] from each rank p, in p order
in_list = [paddle.to_tensor(rank_val(RANK, base=1000.0 * j)) for j in range(NRANKS)]
out_list = []
dist.alltoall(in_list, out_list)
for p in ranks:
    np.testing.assert_allclose(out_list[p].numpy(), rank_val(p, base=1000.0 * RANK))

# send / recv pair (blocking, both sides call)
if NRANKS >= 2:
    if RANK == 0:
        dist.send(paddle.to_tensor(rank_val(0, base=5.0)), dst=1)
    elif RANK == 1:
        buf = paddle.to_tensor(np.zeros(4, dtype=np.float32))
        dist.recv(buf, src=0)
        np.testing.assert_allclose(buf.numpy(), rank_val(0, base=5.0))

# symmetric exchange: both ranks send first, then recv — must not deadlock
if NRANKS == 2:
    peer = 1 - RANK
    dist.send(paddle.to_tensor(rank_val(RANK, base=9.0)), dst=peer)
    buf = paddle.to_tensor(np.zeros(4, dtype=np.float32))
    dist.recv(buf, src=peer)
    np.testing.assert_allclose(buf.numpy(), rank_val(peer, base=9.0))

# mismatched send/recv buffers: the metadata handshake must raise a clear
# error on the receiver, not corrupt or crash inside array stacking
if NRANKS == 2:
    if RANK == 0:
        dist.send(paddle.to_tensor(np.ones((2, 3), np.float32)), dst=1)
    else:
        buf = paddle.to_tensor(np.zeros(4, dtype=np.float32))  # wrong shape
        try:
            dist.recv(buf, src=0)
            raise AssertionError("recv of mismatched shape did not raise")
        except RuntimeError as e:
            assert "mismatch" in str(e), e

    # same-size different-dtype mismatch, reversed direction (the first
    # block already exercised the padded unequal-byte-size exchange)
    if RANK == 1:
        dist.send(paddle.to_tensor(np.arange(4, dtype=np.int32)), dst=0)
    else:
        buf = paddle.to_tensor(np.zeros(4, dtype=np.float32))
        try:
            dist.recv(buf, src=1)
            raise AssertionError("recv of mismatched dtype did not raise")
        except RuntimeError as e:
            assert "mismatch" in str(e), e

    # after the failed matches the pair stream stays usable
    dist.send(paddle.to_tensor(rank_val(RANK, base=21.0)), dst=peer)
    buf = paddle.to_tensor(np.zeros(4, dtype=np.float32))
    dist.recv(buf, src=peer)
    np.testing.assert_allclose(buf.numpy(), rank_val(peer, base=21.0))

# batch_isend_irecv: mixed directions in one batch, DIFFERENT op orders on
# each side (the global pair ordering + FIFO matching must line them up)
if NRANKS == 2:
    peer = 1 - RANK
    out1 = paddle.to_tensor(rank_val(RANK, base=31.0))
    out2 = paddle.to_tensor(rank_val(RANK, base=32.0))
    in1 = paddle.to_tensor(np.zeros(4, dtype=np.float32))
    in2 = paddle.to_tensor(np.zeros(4, dtype=np.float32))
    if RANK == 0:
        # recv-first on BOTH sides: the batch must reorder sends ahead
        ops = [dist.P2POp(dist.irecv, in1, peer),
               dist.P2POp(dist.isend, out1, peer),
               dist.P2POp(dist.irecv, in2, peer),
               dist.P2POp(dist.isend, out2, peer)]
    else:
        ops = [dist.P2POp(dist.irecv, in1, peer),
               dist.P2POp(dist.irecv, in2, peer),
               dist.P2POp(dist.isend, out1, peer),
               dist.P2POp(dist.isend, out2, peer)]
    for t in dist.batch_isend_irecv(ops):
        t.wait()
    np.testing.assert_allclose(in1.numpy(), rank_val(peer, base=31.0))
    np.testing.assert_allclose(in2.numpy(), rank_val(peer, base=32.0))

# subgroup: new_group([0]) — rank 1 is not a member, collective is a no-op
g0 = dist.new_group([0])
t = paddle.to_tensor(rank_val(RANK))
dist.all_reduce(t, group=g0)
np.testing.assert_allclose(t.numpy(), rank_val(RANK))  # 1-rank / non-member

# object collectives
objs = []
dist.all_gather_object(objs, {"rank": RANK, "payload": [RANK] * (RANK + 1)})
assert objs == [{"rank": r, "payload": [r] * (r + 1)} for r in ranks], objs

olist = [{"from": RANK}] if RANK == 0 else [None]
dist.broadcast_object_list(olist, src=0)
assert olist == [{"from": 0}], olist

dist.barrier()
print(f"COLLECTIVE_OK rank={RANK}", flush=True)

# recv timeout path (VERDICT r4 / advice): BOTH ranks dive into recv with no
# matching send — each must raise the wall-clock timeout error naming the
# pair's completed sequences, and both-sides-polling must be detected
if NRANKS == 2:
    paddle.flags.set_flags({"FLAGS_p2p_timeout_s": 3.0,
                            "FLAGS_p2p_poll_interval_s": 0.01})
    buf = paddle.to_tensor(np.zeros(4, dtype=np.float32))
    try:
        dist.recv(buf, src=peer)
        raise AssertionError("deadlocked recv did not time out")
    except RuntimeError as e:
        msg = str(e)
        assert ("deadline" in msg or "timeout" in msg), msg
        assert "sends" in msg and "recvs" in msg, msg
        assert "BOTH sides" in msg, msg
    paddle.flags.set_flags({"FLAGS_p2p_timeout_s": 300.0})
    # the pair stream survives a timeout: a normal exchange still works
    dist.send(paddle.to_tensor(rank_val(RANK, base=41.0)), dst=peer)
    buf = paddle.to_tensor(np.zeros(4, dtype=np.float32))
    dist.recv(buf, src=peer)
    np.testing.assert_allclose(buf.numpy(), rank_val(peer, base=41.0))

dist.barrier()
print(f"P2P_TIMEOUT_OK rank={RANK}", flush=True)
