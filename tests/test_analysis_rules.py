"""jaxlint rule fixtures: >=2 violating + >=1 clean snippet per rule,
suppression-comment handling, the JSON schema canary, and a self-check
that the analyzer parses the whole paddle_tpu tree without crashing."""
import json
import os
import textwrap

import pytest

from paddle_tpu.analysis import all_rules, lint_paths, lint_source

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu")


def run(src, select=None):
    rep = lint_source(textwrap.dedent(src), path="fixture.py", select=select)
    assert not rep.errors, rep.errors
    return rep


def rule_ids(rep):
    return [f.rule for f in rep.unsuppressed]


def test_registry_has_all_eleven_rules():
    assert [r.id for r in all_rules()] == [
        "JL001", "JL002", "JL003", "JL004", "JL005", "JL006", "JL007",
        "JL008", "JL009", "JL010", "JL011"]
    for r in all_rules():
        assert r.incident, f"{r.id} must name its historical incident"


# ---------------------------------------------------------------------------
# JL001 donation-aliasing


def test_jl001_flags_asarray_into_self_state():
    rep = run("""
        import jax.numpy as jnp
        class Tensor:
            def set_value(self, value):
                self._array = jnp.asarray(value)
    """)
    assert rule_ids(rep) == ["JL001"]


def test_jl001_flags_conditional_branch_and_set_method_return():
    rep = run("""
        import jax.numpy as jnp
        class Tensor:
            def __init__(self, value):
                self._array = value._array if hasattr(value, "_array") else jnp.asarray(value)
            def set_weights(self, w):
                return jnp.asarray(w)
    """)
    assert rule_ids(rep) == ["JL001", "JL001"]


def test_jl001_clean_copying_array_and_argument_position():
    # copying jnp.array is the fix; jnp.asarray of a fresh index list
    # passed INTO a call is not an ownership transfer
    rep = run("""
        import numpy as np
        import jax.numpy as jnp
        class Tensor:
            def set_value(self, value):
                self._array = jnp.array(np.asarray(value))
            def copy_blocks(self, src, dst):
                self.k, self.v = self._copy_fn(
                    self.k, self.v, jnp.asarray(src, jnp.int32),
                    jnp.asarray(dst, jnp.int32))
    """)
    assert rule_ids(rep) == []


# ---------------------------------------------------------------------------
# JL002 repr-keyed-cache


def test_jl002_flags_repr_append_to_key_accumulator():
    rep = run("""
        import jax
        def make_key(args):
            key = []
            for a in args:
                key.append(repr(a))
            return tuple(key)
    """)
    assert rule_ids(rep) == ["JL002"]


def test_jl002_flags_fstring_cache_subscript():
    rep = run("""
        import jax
        class StaticFn:
            def __call__(self, x):
                self._cache[f"{x}"] = jax.jit(lambda v: v)
    """)
    assert rule_ids(rep) == ["JL002"]


def test_jl002_clean_shape_dtype_keys_and_jaxless_modules():
    # canonicalizing calls (str(np.dtype(...))) are deliberate keys
    rep = run("""
        import jax
        import numpy as np
        def make_key(tensors):
            key = []
            for t in tensors:
                key.append((tuple(t.shape), str(np.dtype(t.dtype))))
            return tuple(key)
    """)
    assert rule_ids(rep) == []
    # without jax there is nothing to constant-bake: string registry keys
    # in host-side modules are fine
    rep = run("""
        def endpoint(job_id, r):
            key = f"elastic/{job_id}/endpoint/{r}"
            return key
    """)
    assert rule_ids(rep) == []


# ---------------------------------------------------------------------------
# JL003 host-callback-in-jit


def test_jl003_flags_item_in_decorated_jit():
    rep = run("""
        import jax
        @jax.jit
        def step(x):
            s = x.sum().item()
            return x * s
    """)
    assert rule_ids(rep) == ["JL003"]


def test_jl003_flags_transitive_host_call_through_helper():
    rep = run("""
        import jax
        import time
        def helper(x):
            t = time.time()
            return x + t
        def step(x):
            return helper(x) * 2
        compiled = jax.jit(step)
    """)
    assert rule_ids(rep) == ["JL003"]


def test_jl003_flags_print_and_float_sync():
    rep = run("""
        import jax
        @jax.jit
        def step(x):
            print(x)
            return x * float(x[0])
    """)
    assert sorted(rule_ids(rep)) == ["JL003", "JL003"]


def test_jl003_clean_outside_jit_and_device_ops_inside():
    rep = run("""
        import jax
        import jax.numpy as jnp
        import time
        @jax.jit
        def step(x):
            return jnp.asarray(x) * 2   # device op, not numpy.asarray
        def host_loop(x):
            t = time.time()             # not reachable from any jit
            print(t)
            return float(x)
    """)
    assert rule_ids(rep) == []


# ---------------------------------------------------------------------------
# JL004 ungated-donation


def test_jl004_flags_direct_donate_argnums_and_argnames():
    rep = run("""
        import jax
        def build(f):
            a = jax.jit(f, donate_argnums=(0, 1))
            b = jax.jit(f, donate_argnames=("params",))
            return a, b
    """)
    assert rule_ids(rep) == ["JL004", "JL004"]


def test_jl004_clean_through_gate():
    rep = run("""
        import jax
        from paddle_tpu.parallel.spmd import mesh_donate_argnums
        def build(f):
            return jax.jit(f, donate_argnums=mesh_donate_argnums((0, 2)))
    """)
    assert rule_ids(rep) == []


# ---------------------------------------------------------------------------
# JL005 lock-discipline


_LOCKED_CLASS = """
    import threading
    class Ring:
        def __init__(self):
            self.events = []
            self.dropped = 0
            self._lock = threading.Lock()
        def push(self, ev):
            with self._lock:
                self.events.append(ev)
                self.dropped += 1
"""


def test_jl005_flags_iteration_outside_lock():
    rep = run(_LOCKED_CLASS + """
        def export(self):
            return list(self.events)
    """)
    assert rule_ids(rep) == ["JL005"]


def test_jl005_flags_mutation_outside_lock():
    rep = run(_LOCKED_CLASS + """
        def clear(self):
            self.events.clear()
    """)
    assert rule_ids(rep) == ["JL005"]


def test_jl005_clean_under_lock_and_lock_held_helpers():
    # private helpers called only from under the lock inherit it
    rep = run(_LOCKED_CLASS + """
        def export(self):
            with self._lock:
                return list(self.events)
        def drain(self):
            with self._lock:
                self._evict()
        def _evict(self):
            while self.events:
                self.events.pop()
    """)
    assert rule_ids(rep) == []


def test_jl005_public_method_does_not_inherit_lock():
    # a PUBLIC method reachable from outside must take the lock itself,
    # even if some internal caller holds it
    rep = run(_LOCKED_CLASS + """
        def drain(self):
            with self._lock:
                self.evict()
        def evict(self):
            self.events.pop()
    """)
    assert rule_ids(rep) == ["JL005"]


# ---------------------------------------------------------------------------
# JL006 retrace-hazard


def test_jl006_flags_jit_in_loop_and_immediate_call():
    rep = run("""
        import jax
        def sweep(fs, x):
            outs = []
            for f in fs:
                outs.append(jax.jit(f)(x))
            return outs, jax.jit(fs[0])(x)
    """)
    assert rule_ids(rep) == ["JL006", "JL006"]


def test_jl006_flags_uncached_per_call_rebuild():
    rep = run("""
        import jax
        class Runner:
            def run(self, x):
                def step(v):
                    return v * 2
                jstep = jax.jit(step)
                return jstep(x)
    """)
    assert rule_ids(rep) == ["JL006"]


def test_jl006_flags_unhashable_static_arg():
    rep = run("""
        import jax
        def build(f, x):
            g = jax.jit(f, static_argnums=(0,))
            return g([1, 2, 3], x)
    """)
    assert rule_ids(rep) == ["JL006"]


def test_jl006_clean_cached_returned_export_and_pallas():
    rep = run("""
        import jax
        from jax.experimental import pallas as pl
        class Engine:
            def _get_fn(self, f, sig):
                fn = jax.jit(f)
                self._cache[sig] = fn
                return fn
        def build(f):
            return jax.jit(f)
        def export_artifact(f, avals):
            return jax.export.export(jax.jit(f))(*avals)
        def kernel_call(kern, x, shape):
            return pl.pallas_call(kern, out_shape=shape)(x)
        def make_step(f):
            jf = jax.jit(f)
            def step(x):
                return jf(x)      # closure capture IS the cache
            return step
    """)
    assert rule_ids(rep) == []


# ---------------------------------------------------------------------------
# JL007 async-hygiene


def test_jl007_flags_time_sleep_in_async_def():
    rep = run("""
        import time
        async def handler(req):
            time.sleep(0.1)
            return req
    """)
    assert rule_ids(rep) == ["JL007"]


def test_jl007_flags_typed_blocking_attrs():
    rep = run("""
        import queue
        import threading
        class Frontend:
            def __init__(self):
                self._cmds = queue.Queue(8)
                self._thread = threading.Thread(target=self._loop)
            async def shutdown(self):
                self._cmds.get()
                self._thread.join(timeout=5.0)
    """)
    assert rule_ids(rep) == ["JL007", "JL007"]


def test_jl007_clean_asyncio_types_unbounded_put_and_sync_defs():
    rep = run("""
        import asyncio
        import queue
        import time
        class Frontend:
            def __init__(self):
                self._cmds = queue.Queue()      # unbounded: put never blocks
                self.queue = asyncio.Queue(8)   # loop-native
                self.wake = asyncio.Event()
            async def stream(self):
                self._cmds.put("cmd")
                item = await self.queue.get()
                await self.wake.wait()
                await asyncio.sleep(0.1)
                return item
            def engine_loop(self):
                time.sleep(0.1)                 # worker thread: fine
                return self._cmds.get(timeout=1.0)
    """)
    assert rule_ids(rep) == []


def test_jl005_tuple_unpacking_write_reports_each_attr_exactly_once():
    # regression: _attr_writes must expand tuple targets on a local
    # stack — extending the AST node's own list duplicated findings on
    # the next walk (guarded-by inference walks before the hits pass)
    rep = run("""
        import threading
        class Pair:
            def __init__(self):
                self.a = 0
                self.b = 0
                self._lock = threading.Lock()
            def set_locked(self, x, y):
                with self._lock:
                    self.a, self.b = x, y
            def set_racy(self, x, y):
                self.a, self.b = x, y
    """)
    assert rule_ids(rep) == ["JL005", "JL005"]


def test_jl007_literal_zero_maxsize_is_unbounded():
    rep = run("""
        import queue
        class F:
            def __init__(self):
                self.q = queue.Queue(maxsize=0)   # stdlib: unbounded
            async def push(self, x):
                self.q.put(x)
    """)
    assert rule_ids(rep) == []
    rep = run("""
        import queue
        class F:
            def __init__(self):
                self.q = queue.Queue(8)
            async def push(self, x):
                self.q.put(x)
    """)
    assert rule_ids(rep) == ["JL007"]


# ---------------------------------------------------------------------------
# JL009 lock-order-cycle


def test_jl009_flags_ab_ba_inversion_in_one_class():
    rep = run("""
        import threading
        class Pools:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
            def one(self):
                with self._a:
                    with self._b:
                        pass
            def two(self):
                with self._b:
                    with self._a:
                        pass
    """)
    assert rule_ids(rep) == ["JL009"]
    (f,) = rep.unsuppressed
    # both acquisition paths are named in the one cycle finding
    assert "Pools.one" in f.message and "Pools.two" in f.message


def test_jl009_flags_cycle_through_call_graph_and_global_locks():
    # the A->B edge only exists through a call: `one` holds _A and calls
    # a helper that takes _B
    rep = run("""
        import threading
        _A = threading.Lock()
        _B = threading.Lock()
        def locked_b():
            with _B:
                pass
        def one():
            with _A:
                locked_b()
        def two():
            with _B:
                with _A:
                    pass
    """)
    assert rule_ids(rep) == ["JL009"]
    assert "one -> " in rep.unsuppressed[0].message


def test_jl009_flags_nonreentrant_self_deadlock_via_helper():
    rep = run("""
        import threading
        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
            def outer(self):
                with self._lock:
                    self._inner()
            def _inner(self):
                with self._lock:
                    pass
    """)
    assert rule_ids(rep) == ["JL009"]
    assert "reacquired" in rep.unsuppressed[0].message


def test_jl009_clean_consistent_order_and_rlock_reentry():
    # one global order (A then B) from two paths is fine; RLock
    # reacquisition through a helper is legal
    rep = run("""
        import threading
        class Pools:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()
                self._r = threading.RLock()
            def one(self):
                with self._a:
                    with self._b:
                        pass
            def two(self):
                with self._a:
                    with self._b:
                        pass
            def outer(self):
                with self._r:
                    self._inner()
            def _inner(self):
                with self._r:
                    pass
    """)
    assert rule_ids(rep) == []


def test_threadgraph_mutual_recursion_closure_not_truncated():
    """Regression: all_locks() must not memoize a partial closure
    computed under the recursion cut — querying f first used to cache
    g's mid-traversal result {_B}, permanently losing _A and with it
    the caller-holds-_C -> _A edge."""
    from paddle_tpu.analysis.core import Module
    from paddle_tpu.analysis.threadgraph import Program

    src = textwrap.dedent("""
        import threading
        _A = threading.Lock()
        _B = threading.Lock()
        _C = threading.Lock()
        def f():
            with _A:
                g()
        def g():
            with _B:
                f()
        def caller():
            with _C:
                g()
    """)
    prog = Program([Module("m.py", src)])
    f = next(fi for fi in prog.funcs if fi.name == "f")
    g = next(fi for fi in prog.funcs if fi.name == "g")
    # query order is the regression: f's traversal computes g partially
    assert set(prog.all_locks(f)) == {"m._A", "m._B"}
    assert set(prog.all_locks(g)) == {"m._A", "m._B"}
    assert ("m._C", "m._A") in prog.lock_edges()


# ---------------------------------------------------------------------------
# JL010 cross-thread-shared-state


def test_jl010_flags_thread_target_vs_caller_write():
    rep = run("""
        import threading
        class Layer:
            def __init__(self):
                self._array = None
                self._thread = threading.Thread(target=self._trace_loop)
            def _trace_loop(self):
                saved = self._array
                self._array = saved
            def swap(self, arr):
                prev = self._array
                self._array = arr
                return prev
    """)
    assert rule_ids(rep) == ["JL010"]
    assert "Layer._array" in rep.unsuppressed[0].message


def test_jl010_flags_executor_root_and_mutator_write():
    # run_in_executor roots a method; a .append() outside any common
    # guard races the locked reader
    rep = run("""
        import threading
        class Feed:
            def __init__(self, loop):
                self.rows = []
                self._lock = threading.Lock()
                loop.run_in_executor(None, self._produce)
            def _produce(self):
                self.rows.append(1)
            def snapshot_rows(self):
                with self._lock:
                    return list(self.rows)
    """)
    assert rule_ids(rep) == ["JL010"]


def test_jl010_clean_common_lock_everywhere():
    rep = run("""
        import threading
        class Feed:
            def __init__(self, loop):
                self.rows = []
                self._lock = threading.Lock()
                loop.run_in_executor(None, self._produce)
            def _produce(self):
                with self._lock:
                    self.rows.append(1)
            def snapshot_rows(self):
                with self._lock:
                    return list(self.rows)
    """)
    assert rule_ids(rep) == []


def test_jl010_clean_threadsafe_types_and_init_only_writes():
    # queue.Queue attrs are thread-safe by construction; a field written
    # only in __init__ and read everywhere is configuration, not a race
    rep = run("""
        import queue
        import threading
        class Pump:
            def __init__(self):
                self.cmds = queue.Queue()
                self.limit = 8
                self._thread = threading.Thread(target=self._loop)
            def _loop(self):
                while True:
                    item = self.cmds.get()
                    if item > self.limit:
                        return
            def push(self, item):
                self.cmds.put(item)
    """)
    assert rule_ids(rep) == []


def test_jl010_stored_callback_roots_cross_class():
    """The supervisor/watchdog shape: a method reference passed into
    another class's callback slot runs on THAT class's thread — writes
    it makes race the owning class's caller-thread readers."""
    rep = run("""
        import threading
        class Watchdog:
            def __init__(self, on_trip):
                self.on_trip = on_trip
                self._thread = threading.Thread(target=self._run)
            def _run(self):
                self.on_trip(1.0)
        class Engine:
            def __init__(self):
                self.tripped_at = None
                self._dog = Watchdog(on_trip=self._on_trip)
            def _on_trip(self, t):
                self.tripped_at = t
            def status(self):
                return self.tripped_at
    """)
    assert rule_ids(rep) == ["JL010"]
    assert "Engine.tripped_at" in rep.unsuppressed[0].message


# ---------------------------------------------------------------------------
# JL011 event-loop-blocking (reachability; direct calls are JL007)


def test_jl011_flags_blocking_call_one_frame_below_async():
    rep = run("""
        import time
        def helper(x):
            time.sleep(0.1)
            return x
        async def handler(req):
            return helper(req)
    """)
    assert rule_ids(rep) == ["JL011"]
    assert "handler' -> helper" in rep.unsuppressed[0].message


def test_jl011_flags_typed_blocking_attr_in_sync_method_chain():
    rep = run("""
        import queue
        class Frontend:
            def __init__(self):
                self._cmds = queue.Queue(8)
            def _drain(self):
                return self._cmds.get(timeout=1.0)
            def _tick(self):
                return self._drain()
            async def poll(self):
                return self._tick()
    """)
    assert rule_ids(rep) == ["JL011"]


def test_jl011_clean_offloaded_and_sync_only_helpers():
    # handing the helper to to_thread/run_in_executor moves it OFF the
    # loop; a blocking helper never called from async code is fine; an
    # async callee is its own rule's problem (no double report)
    rep = run("""
        import asyncio
        import time
        def helper():
            time.sleep(0.1)
        async def offloaded(loop):
            await asyncio.to_thread(helper)
            await loop.run_in_executor(None, helper)
        def sync_caller():
            return helper()
    """)
    assert rule_ids(rep) == []


# ---------------------------------------------------------------------------
# suppressions


_VIOLATION = """
    import jax.numpy as jnp
    class T:
        def set_value(self, v):
            self._a = jnp.asarray(v){trailing}
"""


def test_suppression_trailing_comment():
    rep = run(_VIOLATION.format(
        trailing="  # jaxlint: disable=JL001 -- caller guarantees a copy"))
    assert rule_ids(rep) == []
    assert [f.rule for f in rep.suppressed] == ["JL001"]
    assert rep.suppressed[0].justification == "caller guarantees a copy"


def test_suppression_standalone_applies_to_next_line():
    rep = run("""
        import jax.numpy as jnp
        class T:
            def set_value(self, v):
                # jaxlint: disable=JL001 -- reviewed: v is always freshly allocated
                self._a = jnp.asarray(v)
    """)
    assert rule_ids(rep) == []
    assert len(rep.suppressed) == 1


def test_suppression_standalone_carries_over_decorator_lines():
    # JL006's decorated-def findings anchor at the `def` line; a comment
    # placed above the decorator must still reach it
    rep = run("""
        import jax
        def learn(x):
            # jaxlint: disable=JL006 -- one compile per call is intended
            @jax.jit
            def step(v):
                return v * 2
            for _ in range(3):
                x = step(x)
            return x
    """)
    assert rule_ids(rep) == []
    assert [f.rule for f in rep.suppressed] == ["JL006"]


def test_suppression_wrong_id_does_not_apply():
    rep = run(_VIOLATION.format(trailing="  # jaxlint: disable=JL004"))
    assert rule_ids(rep) == ["JL001"]
    assert rep.suppressed == []


def test_suppression_all_and_file_level():
    rep = run(_VIOLATION.format(trailing="  # jaxlint: disable=all"))
    assert rule_ids(rep) == []
    rep = run("# jaxlint: disable-file=JL001 -- fixture corpus\n"
              + textwrap.dedent(_VIOLATION.format(trailing="")))
    assert rule_ids(rep) == []
    assert rep.suppressed[0].justification == "fixture corpus"


def test_suppression_marker_inside_string_is_inert():
    rep = run(_VIOLATION.format(trailing="") + """
        MARKER = "# jaxlint: disable-file=JL001"
    """)
    assert rule_ids(rep) == ["JL001"]


# ---------------------------------------------------------------------------
# JL008 eager-materialize-then-place


def test_jl008_flags_device_put_of_eager_factory():
    rep = run("""
        import jax
        import jax.numpy as jnp
        def build(shape, sharding):
            arena = jax.device_put(jnp.zeros(shape, jnp.float32), sharding)
            accum = jax.device_put(jnp.full(shape, 0.0), device=sharding)
            return arena, accum
    """)
    assert rule_ids(rep) == ["JL008", "JL008"]


def test_jl008_flags_ones_like_and_from_import():
    rep = run("""
        from jax import device_put
        import jax.numpy as jnp
        def build(template, sharding):
            return device_put(jnp.ones_like(template), sharding)
    """)
    assert rule_ids(rep) == ["JL008"]


def test_jl008_clean_placement_of_existing_arrays_and_builders():
    # placing an EXISTING array is the normal checkpoint/batch path, a
    # bare one-arg device_put places nothing, and the fix — the cached
    # jit-with-out_shardings builder — must not flag itself
    rep = run("""
        import functools
        import jax
        import jax.numpy as jnp
        def place(params, shardings):
            return {k: jax.device_put(v, shardings[k])
                    for k, v in params.items()}
        def noop(v):
            return jax.device_put(jnp.zeros((2,)))
        @functools.lru_cache(maxsize=None)
        def _sharded_zeros_fn(shape, dtype_name, sharding):
            return jax.jit(lambda: jnp.zeros(shape, dtype_name),
                           out_shardings=sharding)
    """)
    assert rule_ids(rep) == []


# ---------------------------------------------------------------------------
# JSON schema canary + self-checks


def test_json_report_schema_canary():
    rep = run(_VIOLATION.format(trailing=""))
    doc = json.loads(json.dumps(rep.to_json()))  # must be JSON-serializable
    assert doc["version"] == 1
    assert doc["tool"] == "jaxlint"
    assert set(doc["summary"]) == {
        "files", "findings", "suppressed", "errors", "duration_s"}
    assert doc["summary"]["findings"] == 1
    (f,) = doc["findings"]
    assert set(f) == {"rule", "name", "path", "line", "col", "message",
                      "suppressed", "justification"}
    assert f["rule"] == "JL001"
    assert f["name"] == "donation-aliasing"
    assert f["path"] == "fixture.py"
    assert f["line"] > 0 and f["col"] >= 0
    assert f["suppressed"] is False


def test_syntax_error_becomes_report_error_not_crash():
    rep = lint_source("def broken(:\n", path="bad.py")
    assert rep.findings == []
    assert len(rep.errors) == 1
    assert "parse error" in rep.errors[0][1]
    assert not rep.ok


def test_analyzer_parses_entire_package_without_crashing():
    rep = lint_paths([PKG_DIR])
    assert rep.files > 150
    assert rep.errors == [], rep.errors


def test_cli_exit_codes_and_list_rules(tmp_path, capsys):
    from paddle_tpu.analysis.cli import main

    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("JL001", "JL007"):
        assert rid in out
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "def b(f):\n"
                   "    return jax.jit(f, donate_argnums=(0,))\n")
    good = tmp_path / "good.py"
    good.write_text("x = 1\n")
    assert main([str(good)]) == 0
    assert main([str(bad)]) == 1
    capsys.readouterr()
    assert main(["--json", str(bad)]) == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["summary"]["findings"] == 1
    assert main([str(tmp_path / "missing.py")]) == 2


def test_select_and_ignore_filters():
    src = _VIOLATION.format(trailing="")
    assert rule_ids(run(src, select=["JL004"])) == []
    assert rule_ids(run(src, select=["JL001"])) == ["JL001"]
    rep = lint_source(textwrap.dedent(src), ignore=["JL001"])
    assert rule_ids(rep) == []
