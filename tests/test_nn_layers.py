"""nn layer tests: shapes, numerics vs manual computation, state_dict."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_linear_forward():
    layer = nn.Linear(4, 3)
    x = paddle.randn([2, 4])
    y = layer(x)
    assert y.shape == [2, 3]
    ref = x.numpy() @ layer.weight.numpy() + layer.bias.numpy()
    assert np.allclose(y.numpy(), ref, atol=1e-5)


def test_conv2d_shapes():
    layer = nn.Conv2D(3, 8, 3, stride=2, padding=1)
    x = paddle.randn([2, 3, 16, 16])
    y = layer(x)
    assert y.shape == [2, 8, 8, 8]


def test_conv2d_matches_reference_math():
    import jax

    w = np.random.rand(2, 1, 3, 3).astype(np.float32)
    x = np.random.rand(1, 1, 5, 5).astype(np.float32)
    out = F.conv2d(paddle.to_tensor(x), paddle.to_tensor(w), padding=0)
    # direct correlation
    ref = np.zeros((1, 2, 3, 3), np.float32)
    for o in range(2):
        for i in range(3):
            for j in range(3):
                ref[0, o, i, j] = (x[0, 0, i : i + 3, j : j + 3] * w[o, 0]).sum()
    assert np.allclose(out.numpy(), ref, atol=1e-4)


def test_conv_grad_flows():
    layer = nn.Conv2D(1, 2, 3)
    x = paddle.randn([1, 1, 8, 8])
    y = layer(x).sum()
    y.backward()
    assert layer.weight.grad is not None
    assert layer.bias.grad is not None


def test_conv2d_transpose_shape():
    layer = nn.Conv2DTranspose(4, 2, 3, stride=2, padding=1, output_padding=1)
    x = paddle.randn([1, 4, 8, 8])
    assert layer(x).shape == [1, 2, 16, 16]


def test_batchnorm_train_eval():
    bn = nn.BatchNorm2D(3)
    x = paddle.randn([4, 3, 5, 5])
    bn.train()
    y = bn(x)
    m = y.numpy().mean(axis=(0, 2, 3))
    assert np.allclose(m, 0, atol=1e-4)
    # running stats moved toward batch stats
    assert not np.allclose(bn._mean.numpy(), 0)
    bn.eval()
    y2 = bn(x)
    assert y2.shape == x.shape


def test_layernorm():
    ln = nn.LayerNorm(8)
    x = paddle.randn([2, 4, 8])
    y = ln(x)
    assert np.allclose(y.numpy().mean(-1), 0, atol=1e-4)
    assert np.allclose(y.numpy().std(-1), 1, atol=1e-2)


def test_groupnorm_instancenorm():
    gn = nn.GroupNorm(2, 4)
    x = paddle.randn([2, 4, 6, 6])
    assert gn(x).shape == [2, 4, 6, 6]
    inn = nn.InstanceNorm2D(4)
    assert inn(x).shape == [2, 4, 6, 6]


def test_pooling():
    x = paddle.randn([1, 2, 8, 8])
    assert nn.MaxPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AvgPool2D(2, 2)(x).shape == [1, 2, 4, 4]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [1, 2, 1, 1]
    a = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    mp = F.max_pool2d(paddle.to_tensor(a), 2, 2).numpy()
    assert np.allclose(mp[0, 0], [[5, 7], [13, 15]])


def test_embedding():
    emb = nn.Embedding(10, 4)
    idx = paddle.to_tensor(np.array([[1, 2], [3, 4]]))
    out = emb(idx)
    assert out.shape == [2, 2, 4]
    assert np.allclose(out.numpy()[0, 0], emb.weight.numpy()[1])


def test_dropout_modes():
    d = nn.Dropout(0.5)
    x = paddle.ones([1000])
    d.train()
    y = d(x)
    frac = (y.numpy() == 0).mean()
    assert 0.3 < frac < 0.7
    assert abs(y.numpy().mean() - 1.0) < 0.2  # upscale_in_train
    d.eval()
    assert np.allclose(d(x).numpy(), 1.0)


def test_activations():
    x = paddle.to_tensor(np.array([-2.0, 0.0, 2.0], np.float32))
    assert np.allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
    assert np.allclose(nn.Sigmoid()(x).numpy(), 1 / (1 + np.exp([2.0, 0, -2.0])), atol=1e-5)
    assert nn.GELU()(x).shape == [3]
    s = nn.Softmax()(x).numpy()
    assert abs(s.sum() - 1) < 1e-5


def test_losses():
    logits = paddle.to_tensor(np.array([[2.0, 1.0, 0.1]], np.float32))
    label = paddle.to_tensor(np.array([0]))
    loss = nn.CrossEntropyLoss()(logits, label)
    p = np.exp([2.0, 1.0, 0.1])
    ref = -np.log(p[0] / p.sum())
    assert abs(loss.item() - ref) < 1e-5

    a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    b = paddle.to_tensor(np.array([1.5, 2.5], np.float32))
    assert abs(nn.MSELoss()(a, b).item() - 0.25) < 1e-6
    assert abs(nn.L1Loss()(a, b).item() - 0.5) < 1e-6


def test_cross_entropy_ignore_index():
    logits = paddle.randn([4, 5])
    label = paddle.to_tensor(np.array([0, 1, -100, 2]))
    loss = nn.CrossEntropyLoss(ignore_index=-100)(logits, label)
    assert np.isfinite(loss.item())


def test_sequential_and_containers():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    assert seq(x).shape == [3, 2]
    assert len(seq) == 3
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    assert len(ll) == 3
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4


def test_state_dict_roundtrip():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    sd = net.state_dict()
    assert len(sd) == 4
    net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net2.set_state_dict(sd)
    for (k1, v1), (k2, v2) in zip(net.state_dict().items(), net2.state_dict().items()):
        assert np.allclose(v1.numpy(), v2.numpy())


def test_named_parameters_unique():
    net = nn.Sequential(nn.Linear(4, 4), nn.Linear(4, 4))
    names = [n for n, _ in net.named_parameters()]
    assert len(names) == len(set(names)) == 4


def test_rnn_lstm_gru():
    x = paddle.randn([2, 5, 4])  # [batch, time, feat]
    for cls in (nn.SimpleRNN, nn.LSTM, nn.GRU):
        rnn = cls(4, 8)
        out, state = rnn(x)
        assert out.shape == [2, 5, 8]


def test_lstm_grad():
    rnn = nn.LSTM(4, 8)
    x = paddle.randn([2, 5, 4])
    out, _ = rnn(x)
    out.sum().backward()
    cell = rnn.layers[0].cell
    assert cell.weight_ih.grad is not None


def test_multihead_attention():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.randn([2, 6, 16])
    y = mha(x, x, x)
    assert y.shape == [2, 6, 16]


def test_transformer_encoder():
    layer = nn.TransformerEncoderLayer(16, 4, 32)
    enc = nn.TransformerEncoder(layer, 2)
    x = paddle.randn([2, 6, 16])
    assert enc(x).shape == [2, 6, 16]


def test_clip_grad_global_norm():
    p = paddle.Parameter(np.ones(4, np.float32) * 3)
    g = paddle.to_tensor(np.ones(4, np.float32) * 10)
    clip = nn.ClipGradByGlobalNorm(1.0)
    out = clip([(p, g)])
    norm = np.linalg.norm(out[0][1].numpy())
    assert abs(norm - 1.0) < 1e-4


def test_initializers():
    from paddle_tpu.nn import initializer as I

    w = I.XavierUniform()([64, 64])
    assert abs(np.asarray(w).std() - np.sqrt(2.0 / 128)) < 0.02
    k = I.KaimingNormal()([100, 100])
    assert abs(np.asarray(k).std() - np.sqrt(2.0 / 100)) < 0.02
    c = I.Constant(3.0)([5])
    assert np.allclose(np.asarray(c), 3.0)
    o = np.asarray(I.Orthogonal()([8, 8]))
    assert np.allclose(o @ o.T, np.eye(8), atol=1e-4)


def test_ctc_loss_matches_brute_force():
    """CTC forward recursion vs exhaustive alignment enumeration
    (reference warpctc kernel semantics)."""
    import itertools

    from paddle_tpu.ops.loss_ops import ctc_loss

    rs = np.random.RandomState(0)
    T_, N, C = 4, 2, 3
    logits = rs.randn(T_, N, C).astype(np.float32)
    lp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    labels = np.array([[1, 2], [2, 0]], np.int64)
    in_len = np.array([4, 3], np.int64)
    lab_len = np.array([2, 1], np.int64)

    def brute(lp_n, lab, T_n):
        total = 0.0
        for path in itertools.product(range(C), repeat=T_n):
            col, prev = [], None
            for ch in path:
                if ch != prev:
                    col.append(ch)
                prev = ch
            col = [ch for ch in col if ch != 0]
            if col == list(lab):
                total += np.exp(sum(lp_n[t, ch] for t, ch in enumerate(path)))
        return -np.log(total)

    ref = [brute(lp[:, 0], [1, 2], 4), brute(lp[:, 1], [2], 3)]
    # the op takes RAW logits (softmax integrated, warpctc contract)
    out = ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                   paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
                   reduction="none")
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-4)
    mean = ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                    paddle.to_tensor(in_len), paddle.to_tensor(lab_len))
    np.testing.assert_allclose(float(mean.numpy()),
                               np.mean([ref[0] / 2, ref[1] / 1]), atol=1e-4)
    lpt = paddle.to_tensor(logits)
    lpt.stop_gradient = False
    ctc_loss(lpt, paddle.to_tensor(labels), paddle.to_tensor(in_len),
             paddle.to_tensor(lab_len)).backward()
    assert lpt.grad is not None and np.isfinite(lpt.grad.numpy()).all()

    # norm_by_times: forward values UNCHANGED, gradients scaled by 1/T
    out_nbt = ctc_loss(paddle.to_tensor(logits), paddle.to_tensor(labels),
                       paddle.to_tensor(in_len), paddle.to_tensor(lab_len),
                       reduction="none", norm_by_times=True)
    np.testing.assert_allclose(out_nbt.numpy(), ref, atol=1e-4)
    g1 = paddle.to_tensor(logits); g1.stop_gradient = False
    ctc_loss(g1, paddle.to_tensor(labels), paddle.to_tensor(in_len),
             paddle.to_tensor(lab_len), reduction="sum").backward()
    g2 = paddle.to_tensor(logits); g2.stop_gradient = False
    ctc_loss(g2, paddle.to_tensor(labels), paddle.to_tensor(in_len),
             paddle.to_tensor(lab_len), reduction="sum",
             norm_by_times=True).backward()
    # sample 0 grads scale by 1/4, sample 1 by 1/3
    np.testing.assert_allclose(
        g2.grad.numpy()[:, 0], g1.grad.numpy()[:, 0] / 4.0, atol=1e-5)
    np.testing.assert_allclose(
        g2.grad.numpy()[:, 1], g1.grad.numpy()[:, 1] / 3.0, atol=1e-5)


def test_fold_inverts_unfold():
    from paddle_tpu.ops.common_nn import fold
    from paddle_tpu.ops.conv_pool import unfold

    x = paddle.to_tensor(np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4))
    u = unfold(x, kernel_sizes=2, strides=2)
    back = fold(u, output_sizes=[4, 4], kernel_sizes=2, strides=2)
    np.testing.assert_allclose(back.numpy(), x.numpy())
    # overlapping patches scatter-add with patch multiplicity
    # 4-element paddings follow the reference [top, left, bottom, right]
    up = unfold(x, kernel_sizes=2, strides=1, paddings=[1, 0, 0, 0])
    bp = fold(up, output_sizes=[4, 4], kernel_sizes=2, strides=1,
              paddings=[1, 0, 0, 0])
    assert bp.shape == [1, 2, 4, 4]

    u2 = unfold(x, kernel_sizes=2, strides=1)
    b2 = fold(u2, output_sizes=[4, 4], kernel_sizes=2, strides=1)
    ones = fold(
        unfold(paddle.ones([1, 2, 4, 4]), kernel_sizes=2, strides=1),
        output_sizes=[4, 4], kernel_sizes=2, strides=1,
    )
    np.testing.assert_allclose(b2.numpy() / ones.numpy(), x.numpy(), atol=1e-5)


def test_spectral_norm():
    from paddle_tpu.nn import SpectralNorm

    rs = np.random.RandomState(0)
    w = rs.randn(6, 4).astype(np.float32)
    sn = SpectralNorm(w.shape, dim=0, power_iters=30)
    wn = sn(paddle.to_tensor(w))
    sv = np.linalg.svd(wn.numpy(), compute_uv=False)
    assert abs(sv[0] - 1.0) < 1e-3  # leading singular value normalized to 1
    wt = paddle.to_tensor(w)
    wt.stop_gradient = False
    sn2 = SpectralNorm(w.shape, power_iters=5)
    sn2(wt).sum().backward()
    assert wt.grad is not None
    assert "weight_u" in dict(sn2.named_buffers())  # persists power-iter state
