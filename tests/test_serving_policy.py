"""Multi-tenant scheduling policy (serving/policy.py + the scheduler /
engine wiring): priority classes with strict ordering, windowed per-tenant
token-rate fairness, and deadline-aware early rejection.

Contract pinned here:

- an engine built WITHOUT a policy is byte-identical to the FCFS engine
  (greedy tokens match, program count unchanged) — and a policy engine
  under no contention produces the same tokens too (the policy only
  reorders under pressure);
- priority is strict: under an overload wave, higher classes' TTFT is
  monotone better, class by class;
- fairness is windowed served-token accounting: a flooding tenant's
  later requests queue behind a light tenant's younger requests at equal
  priority, and a dry pool preempts the heaviest tenant's sequence — no
  tenant starves;
- a request whose predicted completion already overshoots its remaining
  deadline is rejected at admission (``policy_reject:deadline_unattainable``
  on the step_faults channel), before it occupies a lane;
- observability: policy_* labeled counters/gauges on /metrics, a policy
  dict in pool_stats(), per-class queue depth + served share.
"""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import LLMEngine, Request, SchedulingPolicy, as_policy
from paddle_tpu.serving.policy import EARLY_REJECT_REASON, OTHER


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _req(tenant=None, priority=None, deadline_s=None, prompt=8,
         max_new_tokens=8):
    return Request(list(range(1, prompt + 1)), max_new_tokens=max_new_tokens,
                   tenant=tenant, priority=priority, deadline_s=deadline_s)


def _drain(eng, max_steps=400):
    toks = {}
    for _ in range(max_steps):
        for o in eng.step():
            toks.setdefault(o.request_id, []).append(o.token)
        if not eng.scheduler.running and not eng.scheduler.waiting:
            break
    assert not eng.scheduler.running and not eng.scheduler.waiting
    return toks


# -- pure policy unit behavior (no engine) ---------------------------------


def test_rank_and_precedence_order():
    p = SchedulingPolicy(priorities=("interactive", "standard", "batch"))
    hi = _req(priority="interactive")
    mid = _req(priority="standard")
    lo = _req(priority="batch")
    unk = _req(priority="bulk-unknown")
    none = _req()
    assert p.rank(hi) < p.rank(mid) < p.rank(lo)
    # unknown/None rank below every named class, and equal to each other
    assert p.rank(unk) == p.rank(none) == len(p.priorities)
    # precedence: class first, arrival within class (hi is OLDER than the
    # others yet a younger hi still beats them; within a class FCFS holds)
    assert p.precedence(hi) < p.precedence(mid) < p.precedence(lo)
    later_hi = _req(priority="interactive")
    assert p.precedence(hi) < p.precedence(later_hi) < p.precedence(mid)


def test_admission_key_prefers_starved_tenant():
    p = SchedulingPolicy()
    heavy = _req(tenant="heavy", priority="batch")
    light = _req(tenant="light", priority="batch")   # younger arrival
    now = time.monotonic()
    p.note_served(heavy, 500, now=now)
    # equal class: the tenant with less windowed consumption wins even
    # though its request arrived later
    assert p.admission_key(light, now) < p.admission_key(heavy, now)
    # priority still dominates fairness
    hi = _req(tenant="heavy", priority="interactive")
    assert p.admission_key(hi, now) < p.admission_key(light, now)


def test_served_window_expires_and_shares_normalize():
    p = SchedulingPolicy(fairness_window_s=10.0)
    a, b = _req(tenant="a"), _req(tenant="b")
    t0 = 1000.0
    p.note_served(a, 300, now=t0)
    p.note_served(b, 100, now=t0)
    shares = p.served_shares(now=t0 + 1)
    assert shares["a"] == pytest.approx(0.75)
    assert shares["b"] == pytest.approx(0.25)
    assert sum(shares.values()) == pytest.approx(1.0)
    # outside the window everything expires
    assert p.served_tokens("a", now=t0 + 11) == 0
    assert p.served_shares(now=t0 + 11) == {}


def test_tenant_cardinality_folds_to_other():
    p = SchedulingPolicy(max_tenants=2)
    t0 = 1000.0
    p.note_served(_req(tenant="t0"), 10, now=t0)
    p.note_served(_req(tenant="t1"), 10, now=t0)
    p.note_served(_req(tenant="t2"), 10, now=t0)   # over the cap: folds
    p.note_served(_req(tenant="t3"), 10, now=t0)
    assert set(p.served_shares(now=t0)) == {"t0", "t1", OTHER}
    assert p.served_tokens("t2", now=t0) == 20      # reads the fold bucket
    assert p.class_labels(_req(tenant="t9", priority="batch")) == {
        "tenant": OTHER, "priority": "batch"}
    # under the cap the anonymous tenant reads "-" (the SLO convention);
    # at the cap it folds like any other tenant
    assert p.class_labels(_req()) == {"tenant": OTHER, "priority": "-"}
    assert SchedulingPolicy().class_labels(_req()) == {
        "tenant": "-", "priority": "-"}


def test_select_victim_edges():
    p = SchedulingPolicy()
    now = time.monotonic()
    peer = _req(tenant="b", priority="interactive")   # OLDER than hi
    hi = _req(tenant="a", priority="interactive")
    lo_heavy = _req(tenant="heavy", priority="batch")
    lo_light = _req(tenant="light", priority="batch")
    for r in (hi, peer, lo_heavy, lo_light):
        r.blocks = [1]
    p.note_served(lo_heavy, 900, now=now)
    p.note_served(lo_light, 10, now=now)
    # never an equal-or-stronger precedence (peer is same class but
    # OLDER): only the batch-class holders are eligible, and the
    # heaviest tenant among them is the victim
    assert p.select_victim([peer, lo_heavy, lo_light], hi) is lo_heavy
    # blockless sequences are not eligible
    lo_heavy.blocks = []
    assert p.select_victim([peer, lo_heavy, lo_light], hi) is lo_light
    # nothing strictly weaker -> None (the caller defers, never preempts up)
    assert p.select_victim([peer], hi) is None
    assert p.select_victim([hi, peer], lo_light) is None
    # a same-class YOUNGER request is strictly weaker — FCFS within class
    young_peer = _req(tenant="c", priority="interactive")
    young_peer.blocks = [3]
    assert p.select_victim([young_peer], hi) is young_peer
    # tie on consumption breaks arrival-youngest (the FCFS victim rule)
    young = _req(tenant="light2", priority="batch")
    young.blocks = [2]
    p.note_served(young, 10, now=now)
    assert p.select_victim([lo_light, young], hi) is young


def test_early_reject_abstains_cold_fires_warm():
    cold = SchedulingPolicy()
    doomed = _req(deadline_s=0.01, max_new_tokens=32)
    # no step samples yet: the predictor abstains
    assert cold.predicted_serve_s(doomed, prefill_chunk=8) is None
    assert cold.early_reject(doomed, prefill_chunk=8) is None
    warm = SchedulingPolicy(assumed_step_s=1.0)
    # prediction: ceil((pending-1)/chunk) prefill steps + one per token
    assert warm.predicted_serve_s(doomed, prefill_chunk=8) == pytest.approx(
        (1 + 32) * 1.0)
    assert warm.early_reject(doomed, prefill_chunk=8) == EARLY_REJECT_REASON
    assert warm.early_rejections == 1
    # deadline-less requests never reject; neither does a generous deadline
    assert warm.early_reject(_req(), prefill_chunk=8) is None
    assert warm.early_reject(_req(deadline_s=3600.0), prefill_chunk=8) is None
    # the knob turns the mechanism off wholesale
    off = SchedulingPolicy(assumed_step_s=1.0, deadline_early_reject=False)
    assert off.early_reject(doomed, prefill_chunk=8) is None


def test_observe_step_ewma_warms_the_predictor():
    p = SchedulingPolicy(min_samples=3, ewma_alpha=0.5)
    doomed = _req(deadline_s=0.001, max_new_tokens=16)
    for _ in range(2):
        p.observe_step(0.1)
    assert p.early_reject(doomed, prefill_chunk=8) is None   # still cold
    p.observe_step(0.1)
    assert p.early_reject(doomed, prefill_chunk=8) == EARLY_REJECT_REASON
    assert p._step_ewma == pytest.approx(0.1)


def test_as_policy_coercions():
    assert as_policy(None) is None
    assert as_policy(False) is None
    assert isinstance(as_policy(True), SchedulingPolicy)
    p = as_policy({"priorities": ("gold", "bronze"), "max_tenants": 4})
    assert p.priorities == ("gold", "bronze")
    assert as_policy(p) is p
    with pytest.raises(ValueError, match="policy"):
        as_policy("fcfs")
    with pytest.raises(ValueError, match="fairness_window_s"):
        SchedulingPolicy(fairness_window_s=0)


def test_snapshot_shape():
    p = SchedulingPolicy(assumed_step_s=0.05)
    w = [_req(tenant="a", priority="batch"), _req(tenant="a",
                                                  priority="batch")]
    snap = p.snapshot(waiting=w, running=[_req()], now=1000.0)
    assert snap["queue_depth"] == {"a/batch": 2}
    assert snap["running"] == 1
    assert snap["step_ewma_ms"] == pytest.approx(50.0)
    assert snap["priorities"] == ["interactive", "standard", "batch"]


# -- engine integration ----------------------------------------------------


def test_policy_engine_token_identical_to_fcfs(model):
    """No contention, no deadlines: the policy engine emits exactly the
    FCFS engine's greedy tokens with the same compiled-program count."""
    def run(policy):
        eng = LLMEngine(model, block_size=8, num_blocks=48, max_batch=4,
                        policy=policy, spec_decoding=True)
        rids = [eng.add_request(list(range(1, 10 + i)), max_new_tokens=6,
                                tenant=f"t{i % 2}", priority="standard")
                for i in range(6)]
        toks = _drain(eng)
        assert len(eng._step_fns) <= eng.expected_program_count()
        return [toks[r] for r in rids], eng.expected_program_count()
    base, n0 = run(None)
    got, n1 = run(True)
    assert got == base
    assert n0 == n1


def test_priority_ttft_monotone_under_overload(model):
    """3-class overload wave: every class's WORST TTFT is strictly better
    than the next class's best — strict priority, not a statistical
    accident at this scale."""
    eng = LLMEngine(model, block_size=8, num_blocks=48, max_batch=2,
                    policy=True)
    classes = ("interactive", "standard", "batch")
    rids = {c: [] for c in classes}
    # submitted worst-first so FCFS would invert the order
    for i in range(3):
        for c in reversed(classes):
            rids[c].append(eng.add_request(list(range(1, 9)),
                                           max_new_tokens=4, tenant=c,
                                           priority=c))
    _drain(eng)
    ttft = {c: [eng.get_request(r).first_token_time
                - eng.get_request(r).arrival_time for r in rs]
            for c, rs in rids.items()}
    assert max(ttft["interactive"]) < min(ttft["standard"])
    assert max(ttft["standard"]) < min(ttft["batch"])


def test_fairness_flood_does_not_starve_light_tenant(model):
    """A 6-request flood arrives BEFORE a light tenant's 2 requests; at
    equal priority fairness admits the light tenant into the next free
    lanes ahead of the flood's tail."""
    eng = LLMEngine(model, block_size=8, num_blocks=48, max_batch=2,
                    policy=True)
    flood = [eng.add_request(list(range(1, 9)), max_new_tokens=4,
                             tenant="flood", priority="standard")
             for _ in range(6)]
    light = [eng.add_request(list(range(20, 28)), max_new_tokens=4,
                             tenant="light", priority="standard")
             for _ in range(2)]
    _drain(eng)
    admit = lambda r: eng.get_request(r).admit_time   # noqa: E731
    # first two lanes went to the flood (nothing served yet, FCFS tie);
    # every later flood admission happened AFTER both light requests
    for r in light:
        assert all(admit(r) < admit(f) for f in flood[2:])
    shares = eng.pool_stats()["policy"]["served_share"]
    assert shares.get("light", 0) > 0
    # no starvation: everything finished (asserted by _drain) and the
    # flood still got the majority of the window
    assert shares["flood"] > shares["light"]


def test_policy_preemption_picks_weaker_class_and_counts(model):
    """Dry pool: an interactive request reclaims blocks from the batch
    holder (policy victim selection), never the reverse, and the labeled
    policy_preemptions counter records the victim's class."""
    # 10 usable blocks, each request needs up to 6 — concurrent growth
    # must reclaim from somebody
    eng = LLMEngine(model, block_size=4, num_blocks=11, max_batch=2,
                    policy=True, prefix_cache=False)
    lo = eng.add_request(list(range(1, 17)), max_new_tokens=8,
                         tenant="bulk", priority="batch")
    for _ in range(3):
        eng.step()    # let the batch request take most of the pool
    hi = eng.add_request(list(range(30, 46)), max_new_tokens=8,
                         tenant="gold", priority="interactive")
    toks = _drain(eng)
    assert set(toks) == {lo, hi}           # both finish — preempt, not starve
    assert eng.get_request(lo).preemptions >= 1
    assert eng.get_request(hi).preemptions == 0
    assert eng.policy.policy_preemptions >= 1
    labeled = eng.metrics.snapshot()["labeled"]
    rows = labeled.get("policy_preemptions", [])
    assert any(r["labels"] == {"tenant": "bulk", "priority": "batch"}
               and r["value"] >= 1 for r in rows)


def test_deadline_early_reject_fires_before_lane_occupancy(model):
    eng = LLMEngine(model, block_size=8, num_blocks=48, max_batch=2,
                    policy={"assumed_step_s": 30.0})
    ok = eng.add_request(list(range(1, 9)), max_new_tokens=2, tenant="a")
    doomed = eng.add_request(list(range(10, 18)), max_new_tokens=8,
                             tenant="b", priority="interactive",
                             deadline_s=0.5)
    doomed_req = eng._requests[doomed]
    outs = eng.step()
    # the doomed request never occupied a lane: rejected at admission,
    # reported on the step_faults channel, aborted with the structured
    # reason (terminally removed from the engine's live set); the viable
    # request's step proceeded normally
    assert (doomed, EARLY_REJECT_REASON) in eng.step_faults
    assert all(o.request_id == ok for o in outs)
    assert doomed not in eng._requests
    assert doomed_req.aborted
    assert not doomed_req.blocks
    assert doomed_req.admit_time is None
    assert eng.metrics.counters["policy_early_rejections"] == 1
    rows = eng.metrics.snapshot()["labeled"]["policy_early_rejections"]
    assert any(r["labels"]["tenant"] == "b" for r in rows)
    assert eng.pool_stats()["policy"]["early_rejections"] == 1
    _drain(eng)


def test_no_deadline_no_warm_predictor_never_rejects(model):
    """Cold predictor + deadline-less requests: zero rejections even
    under a policy engine with deadlines present but attainable."""
    eng = LLMEngine(model, block_size=8, num_blocks=48, max_batch=2,
                    policy=True)
    rids = [eng.add_request(list(range(1, 9)), max_new_tokens=2,
                            deadline_s=3600.0) for _ in range(3)]
    toks = _drain(eng)
    assert set(toks) == set(rids)
    assert eng.metrics.counters.get("policy_early_rejections", 0) == 0


def test_policy_observability_surfaces(model):
    eng = LLMEngine(model, block_size=8, num_blocks=48, max_batch=2,
                    policy=True)
    for i in range(5):
        eng.add_request(list(range(1, 9)), max_new_tokens=3,
                        tenant=f"t{i % 2}", priority="standard")
    eng.step()
    snap = eng.metrics.snapshot()
    depth = snap["labeled_gauges"]["policy_queue_depth"]
    assert depth and all(set(r["labels"]) == {"tenant", "priority"}
                         for r in depth)
    text = eng.metrics.prometheus_text()
    assert 'policy_queue_depth{' in text
    assert "# TYPE paddle_tpu_serving_policy_queue_depth gauge" in text
    pol = eng.pool_stats()["policy"]
    assert sum(pol["queue_depth"].values()) == len(eng.scheduler.waiting)
    _drain(eng)
    share = eng.metrics.snapshot()["labeled_gauges"]["policy_served_share"]
    assert {r["labels"]["tenant"] for r in share} == {"t0", "t1"}
    assert sum(r["value"] for r in share) == pytest.approx(1.0)
    # drained queues drop off the scrape entirely (whole-family replace)
    assert eng.metrics.snapshot()["labeled_gauges"]["policy_queue_depth"] == []


def test_pool_returns_to_idle_after_policy_churn(model):
    """Preemption + rejection churn leaks no blocks or refcounts."""
    eng = LLMEngine(model, block_size=4, num_blocks=13, max_batch=2,
                    policy={"assumed_step_s": 30.0}, prefix_cache=False)
    eng.add_request(list(range(1, 17)), max_new_tokens=6, priority="batch")
    for _ in range(2):
        eng.step()
    eng.add_request(list(range(30, 46)), max_new_tokens=6,
                    priority="interactive")
    eng.add_request(list(range(50, 58)), max_new_tokens=8,
                    deadline_s=0.2)      # doomed under the assumed step
    _drain(eng)
    assert eng.pool.num_free == eng.pool.num_blocks - 1
    assert eng.pool._refcount == {}
