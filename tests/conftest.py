"""Test configuration: force an 8-device CPU mesh so sharding/collective tests
run deterministically without TPU hardware (SURVEY.md §4 fake-backend testing
strategy — XLA's host platform is the fake_cpu_device.h equivalent).

Set PADDLE_TPU_TEST_ON_TPU=1 to run the suite on the real chip instead.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if not os.environ.get("PADDLE_TPU_TEST_ON_TPU"):
    from _cpu_mesh import force_host_cpu_devices

    force_host_cpu_devices(8)
    # inherited by every subprocess tests spawn (launch children, worker
    # scripts): paddle_tpu._apply_platform_override() flips them to CPU
    # before any jax backend use, so a dead/absent TPU tunnel can never
    # hang a spawned child
    os.environ["PADDLE_TPU_PLATFORM"] = "cpu"
