"""Test configuration: force an 8-device CPU mesh so sharding/collective tests
run deterministically without TPU hardware (SURVEY.md §4 fake-backend testing
strategy — XLA's host platform is the fake_cpu_device.h equivalent).

Note: the axon TPU plugin's sitecustomize sets jax_platforms programmatically,
so the env var alone is not enough — we update jax.config before any backend
initialization. Set PADDLE_TPU_TEST_ON_TPU=1 to run the suite on the real
chip instead.
"""
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

if not os.environ.get("PADDLE_TPU_TEST_ON_TPU"):
    import jax

    jax.config.update("jax_platforms", "cpu")
