"""Replica-fleet router (serving/router.py): routing policy + HTTP surface.

Routing correctness on healthy fleets: prefix-affinity (shared prefixes
co-locate and the aggregate cache hit rate matches a single-replica warm
serve, strictly above the no-affinity router), retry-elsewhere on
draining/overloaded replicas, deadline-aware early rejection, rolling
drain without a factory, the fleet-merged SLO rollup, and the
RouterServer endpoints — including a full Prometheus exposition
conformance parse of the router's /metrics (the PR 12 lock applied to
the new series). Fault-driven chaos is tests/test_serving_router_chaos.py.
"""
import asyncio
import json
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    EngineOverloadedError,
    LLMEngine,
    ReplicaRouter,
    RouterServer,
    SLOLedger,
)
from paddle_tpu.serving.scheduler import Request


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(scope="module")
def ref_engine(model):
    """One shared no-fault engine for reference outputs (the
    test_serving_chaos.py discipline: compiling fresh step programs per
    reference run would dominate this file's wall time)."""
    return LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(model, **kw)


def _fleet_idle(router):
    for r in router.replicas:
        eng = r.engine.engine
        assert eng.pool._refcount == {}
        assert eng.pool.num_free == eng.pool.num_blocks - 1


def _homed_prompt(router, home, length=12, seed0=1000):
    """A fresh random prompt whose affinity key rendezvous-routes to
    `home` (distinct every call — seeds advance globally)."""
    seed = seed0
    while True:
        seed += 1
        p = np.random.RandomState(seed).randint(0, 128, (length,)).tolist()
        if router.home_replica(p) == home:
            return p


# -- routing policy -----------------------------------------------------------


def test_affinity_routes_shared_prefixes_to_one_home(model, ref_engine):
    """Requests sharing a full-block prefix share an affinity key and all
    land on ONE replica; every output is token-identical to an unrouted
    serve; home_replica is deterministic and matches where requests go."""
    shared = _prompts((16,), seed=1)[0]      # two full blocks of 8
    suffixes = _prompts((3, 5, 7, 4), seed=2)
    prompts = [shared + s for s in suffixes]
    refs = ref_engine.generate(prompts, max_new_tokens=6, temperature=0.0)

    async def main():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model)) for _ in range(2)],
            sweep_interval_s=0.02)
        await router.start()
        home = router.home_replica(prompts[0])
        streams = [await router.submit(p, max_new_tokens=6, temperature=0.0)
                   for p in prompts]
        outs = [await s.collect() for s in streams]
        # distinct-prefix traffic is NOT all pinned to one replica: some
        # fresh key must rendezvous to the other replica
        other = [r.name for r in router.replicas if r.name != home][0]
        spread = _homed_prompt(router, other)
        assert router.home_replica(spread) == other
        snap = router.snapshot()
        await router.shutdown()
        return home, streams, outs, snap

    home, streams, outs, snap = asyncio.run(main())
    assert all(s.replica == home for s in streams)          # co-located
    assert all(s.terminal_events == 1 for s in streams)
    for (toks, reason), ref in zip(outs, refs):
        assert reason == "length" and toks == ref
    assert {r["state"] for r in snap["replicas"]} == {"active"}


def test_affinity_hit_rate_matches_single_replica_warm_serve(model):
    """THE affinity acceptance criterion: a shared-prefix wave through 2
    affinity-routed replicas reaches the same aggregate prefix-cache hit
    rate as a single-replica warm serve, and strictly beats the
    no-affinity (least-loaded) router on the same wave."""
    shared = _prompts((24,), seed=3)[0]      # three full blocks
    suffixes = _prompts((3, 4, 5, 6, 3, 4, 5, 6), seed=4)
    prompts = [shared + s for s in suffixes]

    def hit_rate(engines):
        hit = lookup = 0.0
        for e in engines:
            c = e.engine.metrics.counters
            hit += c.get("prefix_cache_hit_tokens", 0)
            lookup += c.get("prefix_cache_lookup_tokens", 0)
        return hit / lookup if lookup else 0.0

    async def wave(n_replicas, affinity):
        engines = [AsyncLLMEngine(_engine(model)) for _ in range(n_replicas)]
        router = ReplicaRouter(engines, affinity=affinity,
                               sweep_interval_s=0.05)
        await router.start()
        streams = [await router.submit(p, max_new_tokens=4, temperature=0.0)
                   for p in prompts]
        outs = [await s.collect() for s in streams]
        assert all(r == "length" for _, r in outs)
        rate = hit_rate(engines)
        _fleet_idle(router)
        await router.shutdown()
        return rate

    async def main():
        single = await wave(1, True)
        affin = await wave(2, True)
        spread = await wave(2, False)
        return single, affin, spread

    single, affin, spread = asyncio.run(main())
    assert single > 0.3                       # the wave is genuinely warm
    # affinity preserves the single-replica hit rate under fan-out...
    assert affin == pytest.approx(single, abs=0.02)
    # ...and strictly beats spreading the shared prefix over both caches
    assert affin > spread


def test_retry_elsewhere_on_draining_replica(model, ref_engine):
    """A request homed to a draining replica is admitted on the other
    replica in the same submit call (no backoff round needed), token
    identical; the router observes the replica-side drain state."""
    async def main():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model)) for _ in range(2)],
            sweep_interval_s=0.02)
        await router.start()
        victim = router.replicas[0]
        p = _homed_prompt(router, victim.name)
        victim.engine.stop_admitting()       # replica-side drain
        st = await router.submit(p, max_new_tokens=5, temperature=0.0)
        toks, reason = await st.collect()
        c = dict(router.metrics.counters)
        state = victim.state
        await router.shutdown()
        return st, toks, reason, c, state, p

    st, toks, reason, c, state, p = asyncio.run(main())
    assert reason == "length"
    assert st.replica == "r1"                # rerouted off the drain
    assert toks == ref_engine.generate([p], max_new_tokens=5,
                                       temperature=0.0)[0]
    assert c.get("router_retries", 0) == 0   # same-round failover, no sleep
    assert state == "draining"               # observed, not ejected


def test_overload_retry_budget_exhausts_to_429(model):
    """With every replica's wait queue full, the router burns its backoff
    budget honoring Retry-After and surfaces the replica's 429."""
    async def main():
        # 1 lane, no wait queue: the second submit to a replica is a 429
        engines = [AsyncLLMEngine(_engine(model, max_batch=1), max_waiting=0)
                   for _ in range(2)]
        router = ReplicaRouter(engines, retry_budget=1,
                               backoff_base_s=0.01, sweep_interval_s=0.05)
        await router.start()
        occupy = [await router.submit(p, max_new_tokens=40, temperature=0.0)
                  for p in _prompts((4, 5), seed=5)]
        assert {s.replica for s in occupy} == {"r0", "r1"}  # both lanes busy
        with pytest.raises(EngineOverloadedError) as ei:
            await router.submit(_prompts((6,), seed=6)[0], max_new_tokens=2)
        err = ei.value
        for s in occupy:
            await s.collect()
        c = dict(router.metrics.counters)
        await router.shutdown()
        return err, c

    err, c = asyncio.run(main())
    assert err.reason == "queue_full"
    assert c["router_admission_rejects"] >= 2     # tried both replicas
    assert c["router_retries"] >= 1               # then backed off


def test_deadline_aware_early_rejection(model):
    """Reject-early beats miss-SLO: when the predicted queue wait on the
    best replica already blows the remaining deadline, submission fails
     429 deadline_unattainable instead of queueing doomed work — and a
    deadline-less request is never early-rejected."""
    async def main():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model, max_batch=1), max_waiting=8)],
            service_time_init_s=10.0, sweep_interval_s=0.05)
        await router.start()
        long = await router.submit(_prompts((4,), seed=7)[0],
                                   max_new_tokens=40, temperature=0.0)
        # inflight 1 == max_batch -> predicted wait 10s >> 0.2s deadline
        with pytest.raises(EngineOverloadedError) as ei:
            await router.submit(_prompts((5,), seed=8)[0],
                                max_new_tokens=2, deadline_s=0.2)
        # no deadline -> no prediction gate; it queues and completes
        ok = await router.submit(_prompts((5,), seed=8)[0],
                                 max_new_tokens=2, temperature=0.0)
        await long.collect()
        toks, reason = await ok.collect()
        c = dict(router.metrics.counters)
        await router.shutdown()
        return ei.value, reason, c

    err, reason, c = asyncio.run(main())
    assert err.reason == "deadline_unattainable"
    assert err.retry_after_s is not None and err.retry_after_s > 0.2
    assert reason == "length"
    assert c["router_early_rejections"] == 1


def test_rolling_drain_without_factory_reopens_admission(model, ref_engine):
    """Restartless rolling drain: one replica at a time closes admission,
    drains to zero in-flight, reopens (`resume_admitting`), re-enters
    rotation — zero failed requests while a wave is live."""
    prompts = _prompts((6, 9, 12, 7, 10, 8), seed=9)
    refs = ref_engine.generate(prompts, max_new_tokens=8, temperature=0.0)

    async def main():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model)) for _ in range(2)],
            sweep_interval_s=0.02)
        await router.start()
        streams = [await router.submit(p, max_new_tokens=8, temperature=0.0)
                   for p in prompts]
        drained = await router.rolling_drain()
        outs = [await s.collect() for s in streams]
        # both replicas admit again after the drain
        post = [await router.generate(p, max_new_tokens=3, temperature=0.0)
                for p in prompts[:2]]
        c = dict(router.metrics.counters)
        states = [r.state for r in router.replicas]
        _fleet_idle(router)
        await router.shutdown()
        return drained, outs, post, c, states

    drained, outs, post, c, states = asyncio.run(main())
    assert drained == ["r0", "r1"]
    assert c["router_drains"] == 2
    assert states == ["active", "active"]
    for (toks, reason), ref in zip(outs, refs):
        assert reason == "length" and toks == ref    # zero failures
    assert all(r == "length" for _, r in post)
    assert c.get("router_requests_failed", 0) == 0


# -- fleet SLO rollup ---------------------------------------------------------


def test_merged_rollup_sums_replica_ledgers():
    """SLOLedger.merged_rollup: per-class counters sum, percentile
    windows pool, and the shape matches a single ledger's rollup."""
    import time as _time

    ledgers = [SLOLedger(), SLOLedger()]
    for i, led in enumerate(ledgers):
        for j in range(3):
            req = Request([1, 2, 3], tenant="acme", priority="hi",
                          deadline_s=30.0)
            led.begin(req)
            req.output_ids = [1, 2]
            req.first_token_time = _time.monotonic()
            led.finalize(req, "finished")
        req = Request([1, 2, 3], tenant=f"solo{i}")
        led.begin(req)
        led.finalize(req, "aborted")
    merged = SLOLedger.merged_rollup(ledgers)
    assert merged["total"]["requests"] == 8
    by_class = {(c["tenant"], c["priority"]): c for c in merged["classes"]}
    acme = by_class[("acme", "hi")]
    assert acme["requests"] == 6 and acme["finished"] == 6
    assert acme["e2e_ms"]["count"] == 6          # pooled windows
    assert acme["deadline"]["met"] == 6
    assert by_class[("solo0", "-")]["aborted"] == 1
    assert by_class[("solo1", "-")]["aborted"] == 1
    assert merged.keys() == ledgers[0].rollup().keys()


# -- RouterServer HTTP surface + exposition conformance -----------------------

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _parse_prom(text):
    """Exposition conformance (the PR 12 lock, applied to the router's
    scrape): every non-comment line must parse and every label body must
    be fully consumed by valid pairs."""
    types, samples = {}, []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m is not None, f"unparseable sample line: {line!r}"
        labels = {}
        if m.group(2):
            body = m.group(2)[1:-1]
            rebuilt = ",".join(f'{k}="{v}"'
                               for k, v in _LABEL_RE.findall(body))
            assert rebuilt == body, f"bad label body: {body!r}"
            labels = dict(_LABEL_RE.findall(body))
        samples.append((m.group(1), labels, float(m.group(3))))
    return types, samples


async def _http(port, method, path, obj=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    data = json.dumps(obj).encode() if obj is not None else b""
    writer.write(
        (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
         f"Content-Type: application/json\r\n"
         f"Content-Length: {len(data)}\r\n\r\n").encode() + data
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), body


def test_router_server_endpoints_and_metrics_conformance(model, ref_engine):
    """The fleet HTTP surface: /v1/completions (SSE + full) routes and
    serves token-identical output, /healthz reports every replica's
    state machine, /debug/router dumps the table, /debug/slo merges the
    replica ledgers, and the router /metrics scrape passes the
    exposition-conformance parse with the new router families present
    and HELP'd."""
    prompts = _prompts((9, 13, 11), seed=10)
    refs = ref_engine.generate(prompts, max_new_tokens=5, temperature=0.0)

    async def main():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model, slo=True)) for _ in range(2)],
            sweep_interval_s=0.02)
        server = RouterServer(router, port=0)
        await server.start()
        full = await _http(server.port, "POST", "/v1/completions",
                           {"prompt": prompts[0], "max_tokens": 5,
                            "tenant": "acme", "timeout_s": 30.0})
        sse = await _http(server.port, "POST", "/v1/completions",
                          {"prompt": prompts[1], "max_tokens": 5,
                           "stream": True, "tenant": "free"})
        bad = await _http(server.port, "POST", "/v1/completions",
                          {"prompt": "nope"})
        await _http(server.port, "POST", "/v1/completions",
                    {"prompt": prompts[2], "max_tokens": 5})
        health = await _http(server.port, "GET", "/healthz")
        table = await _http(server.port, "GET", "/debug/router")
        slo = await _http(server.port, "GET", "/debug/slo")
        metrics = await _http(server.port, "GET", "/metrics")
        await server.shutdown()
        return full, sse, bad, health, table, slo, metrics

    full, sse, bad, health, table, slo, metrics = asyncio.run(main())
    assert full[0] == 200
    assert json.loads(full[1])["choices"][0]["token_ids"] == refs[0]
    assert sse[0] == 200 and b"[DONE]" in sse[1]
    sse_toks = []
    for line in sse[1].decode().splitlines():
        if line.startswith("data: ") and line != "data: [DONE]":
            sse_toks.extend(json.loads(line[6:])["choices"][0]["token_ids"])
    assert sse_toks == refs[1]
    assert bad[0] == 400

    assert health[0] == 200
    h = json.loads(health[1])
    assert h["status"] == "ok" and h["replicas_active"] == 2
    assert {r["name"] for r in h["replicas"]} == {"r0", "r1"}
    assert all(r["state"] == "active" and r["healthz"] == "ok"
               for r in h["replicas"])

    assert table[0] == 200
    snap = json.loads(table[1])
    assert snap["affinity"] is True and len(snap["replicas"]) == 2

    assert slo[0] == 200
    roll = json.loads(slo[1])
    assert roll["total"]["requests"] == 3      # fleet-merged, all 3 classes
    tenants = {c["tenant"] for c in roll["classes"]}
    assert {"acme", "free", "-"} <= tenants

    assert metrics[0] == 200
    text = metrics[1].decode()
    types, samples = _parse_prom(text)         # every line parses
    pre = "paddle_tpu_serving_"
    names = {n for n, _, _ in samples}
    for fam, kind in (("router_requests_total", "counter"),
                      ("router_replica_requests_total", "counter"),
                      ("router_replicas_active", "gauge"),
                      ("router_inflight", "gauge"),
                      ("router_prefix_cache_hit_rate", "gauge")):
        assert pre + fam in names, fam
        base = pre + fam
        assert types[base] == kind
        assert f"# HELP {base} " in text
    # per-replica labeled family carries both routing decisions' labels
    replica_labels = {tuple(sorted(lab.items()))
                      for n, lab, _ in samples
                      if n == pre + "router_replica_requests_total"}
    assert all(dict(lt).get("replica") in ("r0", "r1")
               for lt in replica_labels)


def test_router_healthz_poison_field_on_single_server(model):
    """Satellite lock: the single-replica /healthz now carries the
    supervisor's sliding-window poison stats (the router's sick-chip
    signal), zeroed on a healthy replica."""
    from paddle_tpu.serving import ServingServer

    async def main():
        server = ServingServer(_engine(model), port=0)
        await server.start()
        status, body = await _http(server.port, "GET", "/healthz")
        mstatus, mbody = await _http(server.port, "GET", "/metrics")
        await server.shutdown()
        return status, json.loads(body), mstatus, mbody.decode()

    status, health, mstatus, metrics = asyncio.run(main())
    assert status == 200
    assert health["poison"] == {"window_s": 60.0, "isolated_in_window": 0,
                                "distinct_sources": 0}
    assert mstatus == 200
    assert "paddle_tpu_serving_poison_distinct_sources 0" in metrics
