"""Many-adapter LoRA serving (models/lora.py + the engine registry):
N per-request adapters over ONE shared base model, one compiled program
per ragged width bucket.

Contract pinned here:

- TOKEN IDENTITY: a request on adapter X emits exactly what a dedicated
  engine whose base weights have X merged in (``W + A@B``) emits — with
  chunked prefill, speculative drafts, and prefix caching live — while
  base requests on the SAME engine match a plain engine exactly;
- ZERO retraces: which adapters a step mixes never keys a program —
  ``jit_traces <= expected_program_count()`` and the count formula is
  unchanged by ``lora_slots``;
- bounded slots: load past capacity LRU-evicts only IDLE adapters,
  unload refuses while requests are in flight, every slot transition
  shows on /metrics (`lora_adapters_loaded`, `lora_adapter_evictions`);
- KV is adapter-dependent: the prefix cache never shares blocks across
  adapters (the chain-hash salt), and the router's affinity key is
  ``(adapter, prefix)``;
- the full stack threads ``adapter=``: engine, async frontend, the HTTP
  body parser, and the fleet router.
"""
import asyncio

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import lora as lora_mod
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import AsyncLLMEngine, LLMEngine
from paddle_tpu.serving.block_pool import chain_block_hashes
from paddle_tpu.serving.router import ReplicaRouter
from paddle_tpu.serving.server import _parse_completion_spec

CFG = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
           max_seq_len=64, attn_impl="xla", dropout=0.0)
# spec decoding + prefix caching ON: adapter identity must survive the
# full decode machinery, not just plain greedy steps
ENG = dict(block_size=8, num_blocks=48, max_batch=4, spec_decoding=True,
           prefix_cache=True)
PROMPT = list(range(1, 11))


def make_model():
    """A fresh, bit-identical base model (merge_adapter_into mutates
    weights in place, so reference engines each need their own copy)."""
    paddle.seed(0)
    return GPT(GPTConfig(**CFG)).eval()


def _adapter(cfg, seed, rank=4, scale=0.5):
    return lora_mod.random_adapter(cfg, rank, lora_mod.LORA_TARGETS,
                                   seed=seed, scale=scale)


def _drain(eng, max_steps=400):
    toks = {}
    for _ in range(max_steps):
        for o in eng.step():
            toks.setdefault(o.request_id, []).append(o.token)
        if not eng.scheduler.running and not eng.scheduler.waiting:
            break
    assert not eng.scheduler.running and not eng.scheduler.waiting
    return toks


def _serve_one(eng, prompt=PROMPT, n=12, adapter=None):
    rid = eng.add_request(prompt, max_new_tokens=n, adapter=adapter)
    _drain(eng)
    return eng.get_request(rid).output_ids


# -- table/pack unit behavior ----------------------------------------------


def test_adapter_tables_layout():
    cfg = make_model().cfg
    tables = lora_mod.init_adapter_tables(cfg, 3, 4)
    assert set(tables) == set(lora_mod.LORA_TARGETS)
    a, b = tables["attn_qkv"]
    assert a.shape == (3, cfg.num_layers, cfg.hidden_size, 4)
    assert b.shape == (3, cfg.num_layers, 4, 3 * cfg.hidden_size)
    assert not np.asarray(a).any() and not np.asarray(b).any()

    w = _adapter(cfg, seed=1, rank=2)     # narrower than the table rank
    packed = lora_mod.pack_adapter(cfg, w, 4, lora_mod.LORA_TARGETS,
                                   alpha=8)
    pa, pb = packed["attn_qkv"]
    # zero-padded up to rank 4; alpha/r' folded into B (8 / 2 == 4x)
    assert pa.shape[-1] == 4 and pb.shape[1] == 4
    assert not pa[..., 2:].any() and not pb[:, 2:].any()
    np.testing.assert_allclose(pb[:, :2], w["attn_qkv"][1] * 4.0,
                               rtol=1e-6)

    tables = lora_mod.write_slot(tables, 1, packed)
    a1 = np.asarray(tables["attn_qkv"][0][1])
    assert a1.any()
    # slot 0 (base) stays zero; zero_slot scrubs slot 1 again
    assert not np.asarray(tables["attn_qkv"][0][0]).any()
    tables = lora_mod.zero_slot(tables, 1)
    assert not np.asarray(tables["attn_qkv"][0][1]).any()


def test_pack_adapter_validation():
    cfg = make_model().cfg
    good = _adapter(cfg, seed=1)
    targets = lora_mod.LORA_TARGETS
    with pytest.raises(ValueError, match="not enabled"):
        lora_mod.pack_adapter(cfg, {"attn_proj": good["attn_qkv"]}, 4,
                              targets)
    bad_a = {"attn_qkv": (good["attn_qkv"][0][:, :-1], good["attn_qkv"][1])}
    with pytest.raises(ValueError, match="A shape"):
        lora_mod.pack_adapter(cfg, bad_a, 4, targets)
    with pytest.raises(ValueError, match="exceeds"):
        lora_mod.pack_adapter(cfg, _adapter(cfg, seed=1, rank=8), 4,
                              targets)
    with pytest.raises(ValueError, match="no target weights"):
        lora_mod.pack_adapter(cfg, {}, 4, targets)


# -- token identity ---------------------------------------------------------


def test_adapters_token_identical_to_merged_engines():
    """THE acceptance test: three classes of traffic interleaved on one
    multi-adapter engine — base, adapter alpha (rank 4), adapter beta
    (rank 2, zero-padded) — each stream token-identical to its dedicated
    reference engine, with 0 retraces beyond the program-count
    contract."""
    base = make_model()
    w_a = _adapter(base.cfg, seed=7, rank=4)
    w_b = _adapter(base.cfg, seed=11, rank=2)

    eng = LLMEngine(base, lora_slots=3, lora_rank=4, **ENG)
    eng.load_adapter("alpha", w_a, alpha=8)
    eng.load_adapter("beta", w_b, alpha=4)

    plain = LLMEngine(make_model(), **ENG)
    ref_a = LLMEngine(lora_mod.merge_adapter_into(make_model(), w_a,
                                                  alpha=8), **ENG)
    ref_b = LLMEngine(lora_mod.merge_adapter_into(make_model(), w_b,
                                                  alpha=4), **ENG)
    # adapter-enabled engines keep the exact program-count formula
    assert eng.expected_program_count() == plain.expected_program_count()

    # one mixed wave: every kind shares steps with every other kind
    rids = {}
    for i, ad in enumerate([None, "alpha", "beta", None, "beta", "alpha"]):
        prompt = PROMPT + [20 + i]
        rids[(ad, i)] = (eng.add_request(prompt, max_new_tokens=10,
                                         adapter=ad), prompt)
    _drain(eng)
    refs = {None: plain, "alpha": ref_a, "beta": ref_b}
    for (ad, _i), (rid, prompt) in rids.items():
        got = eng.get_request(rid).output_ids
        want = _serve_one(refs[ad], prompt=prompt, n=10)
        assert got == want, f"adapter {ad}: {got} != {want}"

    # adapters actually steer decoding (the test would pass vacuously on
    # a model whose argmax never moves)
    (r_base, p0) = rids[(None, 0)]
    (r_alpha, _) = rids[("alpha", 1)]
    assert (eng.get_request(r_base).output_ids
            != eng.get_request(r_alpha).output_ids)

    assert (eng.metrics.counters.get("jit_traces")
            <= eng.expected_program_count())
    # registry surfaces
    stats = eng.pool_stats()["lora"]
    assert stats["slots"] == 3 and stats["rank"] == 4
    assert stats["loaded"] == ["alpha", "beta"]
    assert stats["inflight"] == {}     # all drained
    assert eng.metrics.counters.get("lora_requests") == 4.0


def test_lora_off_engine_is_untouched():
    eng = LLMEngine(make_model(), **ENG)
    assert eng._lora_tables == {} and eng.lora_targets == ()
    with pytest.raises(ValueError, match="lora_slots=0"):
        eng.add_request(PROMPT, adapter="alpha")
    with pytest.raises(RuntimeError, match="lora_slots=0"):
        eng.load_adapter("alpha", {})


# -- registry lifecycle -----------------------------------------------------


def test_unknown_adapter_rejected_at_admission():
    eng = LLMEngine(make_model(), lora_slots=2, lora_rank=4, **ENG)
    with pytest.raises(ValueError, match="unknown adapter"):
        eng.add_request(PROMPT, adapter="nope")
    assert not eng.scheduler.waiting     # nothing half-admitted


def test_lru_eviction_and_slot_reuse():
    base = make_model()
    eng = LLMEngine(base, lora_slots=2, lora_rank=4, **ENG)
    s_a = eng.load_adapter("a", _adapter(base.cfg, seed=1))
    s_b = eng.load_adapter("b", _adapter(base.cfg, seed=2))
    assert {s_a, s_b} == {1, 2}
    assert eng.metrics.gauges.get("lora_adapters_loaded") == 2.0

    # serving on "a" makes it most-recently-used, so a third load evicts
    # the idle "b" and reuses ITS slot
    _serve_one(eng, adapter="a")
    s_c = eng.load_adapter("c", _adapter(base.cfg, seed=3))
    assert s_c == s_b
    stats = eng.pool_stats()["lora"]
    assert stats["loaded"] == ["a", "c"]
    assert eng.metrics.counters.get("lora_adapter_evictions") == 1.0
    # reloading a live name overwrites in place — no eviction, same slot
    assert eng.load_adapter("a", _adapter(base.cfg, seed=4)) == s_a
    assert eng.metrics.counters.get("lora_adapter_evictions") == 1.0


def test_unload_refuses_while_inflight():
    base = make_model()
    eng = LLMEngine(base, lora_slots=1, lora_rank=4, **ENG)
    eng.load_adapter("a", _adapter(base.cfg, seed=1))
    rid = eng.add_request(PROMPT, max_new_tokens=16, adapter="a")
    eng.step()
    assert not eng.get_request(rid).finished
    with pytest.raises(RuntimeError, match="in flight"):
        eng.unload_adapter("a")
    # the single slot is also pinned against eviction-by-load
    with pytest.raises(RuntimeError, match="slots hold adapters"):
        eng.load_adapter("b", _adapter(base.cfg, seed=2))
    _drain(eng)
    eng.unload_adapter("a")
    assert eng.metrics.gauges.get("lora_adapters_loaded") == 0.0
    # freed slot is scrubbed — no stale weights for a future tenant
    assert not np.asarray(eng._lora_tables["attn_qkv"][0][1]).any()
    with pytest.raises(ValueError, match="unknown adapter"):
        eng.unload_adapter("a")


def test_abort_releases_adapter_pin():
    base = make_model()
    eng = LLMEngine(base, lora_slots=1, lora_rank=4, **ENG)
    eng.load_adapter("a", _adapter(base.cfg, seed=1))
    rid = eng.add_request(PROMPT, max_new_tokens=16, adapter="a")
    eng.step()
    eng.abort(rid)
    eng.unload_adapter("a")      # no longer pinned


# -- KV/prefix-cache isolation ---------------------------------------------


def test_prefix_cache_never_shared_across_adapters():
    """Same prompt, different adapter => different chained block hashes,
    so the warm base-model prefix is NOT reused for an adapter request
    (its KV was computed through different weights) — but the same
    adapter's own re-serve hits."""
    assert (chain_block_hashes(PROMPT, 8)
            != chain_block_hashes(PROMPT, 8, salt="a"))
    assert (chain_block_hashes(PROMPT, 8, salt="a")
            != chain_block_hashes(PROMPT, 8, salt="b"))

    base = make_model()
    eng = LLMEngine(base, lora_slots=1, lora_rank=4, **ENG)
    eng.load_adapter("a", _adapter(base.cfg, seed=7))
    prompt = list(range(1, 17))          # two full cacheable blocks

    _serve_one(eng, prompt=prompt, n=4)              # warm: base
    hits0 = eng.metrics.counters.get("prefix_cache_hit_tokens", 0)
    _serve_one(eng, prompt=prompt, n=4, adapter="a")  # cold: adapter
    assert eng.metrics.counters.get("prefix_cache_hit_tokens", 0) == hits0
    _serve_one(eng, prompt=prompt, n=4, adapter="a")  # warm: same adapter
    assert eng.metrics.counters.get("prefix_cache_hit_tokens", 0) > hits0


# -- stack threading: parser, frontend, router ------------------------------


def test_completion_parser_accepts_adapter():
    kw, _stream = _parse_completion_spec(
        b'{"prompt": [1, 2, 3], "adapter": "alpha"}')
    assert kw["adapter"] == "alpha"
    kw, _stream = _parse_completion_spec(b'{"prompt": [1, 2, 3]}')
    assert kw["adapter"] is None


def test_async_frontend_threads_adapter():
    base = make_model()
    eng = LLMEngine(base, lora_slots=1, lora_rank=4, **ENG)
    w = _adapter(base.cfg, seed=7)
    eng.load_adapter("a", w, alpha=8)
    want = _serve_one(LLMEngine(lora_mod.merge_adapter_into(
        make_model(), w, alpha=8), **ENG), n=8)

    async def main():
        fe = await AsyncLLMEngine(eng).start()
        toks, reason = await fe.generate(PROMPT, max_new_tokens=8,
                                         adapter="a")
        # unknown adapters bounce at submit, BEFORE the engine thread
        with pytest.raises(ValueError, match="unknown adapter"):
            fe.submit(PROMPT, adapter="nope")
        await fe.shutdown()
        return toks, reason

    toks, reason = asyncio.run(main())
    assert reason == "length" and toks == want


def test_router_affinity_keys_on_adapter():
    """The router homes (adapter, prefix) pairs: the same prompt under
    different adapters may land on different replicas, and adapter
    requests route end to end token-identically."""
    base = make_model()
    w = _adapter(base.cfg, seed=7)
    want = _serve_one(LLMEngine(lora_mod.merge_adapter_into(
        make_model(), w, alpha=8), **ENG), n=6)

    def engine():
        e = LLMEngine(make_model(), lora_slots=1, lora_rank=4, **ENG)
        e.load_adapter("a", w, alpha=8)
        return e

    async def main():
        router = ReplicaRouter([AsyncLLMEngine(engine()) for _ in range(2)],
                               sweep_interval_s=0.02)
        await router.start()
        assert (router.affinity_key(PROMPT)
                != router.affinity_key(PROMPT, "a"))
        rs = await router.submit(PROMPT, max_new_tokens=6, adapter="a")
        toks, reason = await rs.collect()
        await router.shutdown()
        return toks, reason

    toks, reason = asyncio.run(main())
    assert reason == "length" and toks == want
