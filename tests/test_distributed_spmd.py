"""Distributed/SPMD tests on the virtual 8-device CPU mesh.

Modeled on the reference's no-GPU distributed test strategy (SURVEY.md §4:
test_dist_base.py gloo path) — here the 'fake backend' is the forced
8-device host platform; shardings and collectives are real XLA SPMD.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def _mesh(**degrees):
    from paddle_tpu.distributed.mesh import init_mesh

    return init_mesh(degrees)


def teardown_module():
    from paddle_tpu.distributed.mesh import set_mesh

    set_mesh(None)


def test_build_mesh_axes():
    mesh = _mesh(dp=2, mp=2, sp=2)
    assert mesh.shape["dp"] == 2 and mesh.shape["mp"] == 2
    assert mesh.shape["pp"] == 1


def test_topology_coords():
    from paddle_tpu.distributed import CommunicateTopology

    topo = CommunicateTopology(dims=(2, 2, 1, 2))
    assert topo.world_size() == 8
    assert topo.get_rank(data=1, pipe=0, sharding=0, model=1) == 5
    assert topo.get_coord(5) == (1, 0, 0, 1)
    comm = topo.get_comm_list("model")
    assert all(len(g) == 2 for g in comm)


def test_hybrid_communicate_group():
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy

    strategy = DistributedStrategy()
    strategy.hybrid_configs.update(dict(dp_degree=2, mp_degree=2, pp_degree=1, sharding_degree=2))
    fleet.init(is_collective=True, strategy=strategy)
    hcg = fleet.fleet.get_hybrid_communicate_group()
    assert hcg.get_data_parallel_world_size() == 2
    assert hcg.get_model_parallel_world_size() == 2
    assert hcg.get_sharding_parallel_world_size() == 2
    assert hcg.get_parallel_mode() == "sharding_parallel"


def test_tp_layers_match_dense():
    """Column/Row parallel layers must equal dense math (degree-1 path)."""
    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear,
        RowParallelLinear,
        VocabParallelEmbedding,
    )
    from paddle_tpu.distributed.mesh import set_mesh

    set_mesh(None)
    paddle.seed(0)
    col = ColumnParallelLinear(8, 16, gather_output=True)
    x = paddle.randn([2, 8])
    ref = x.numpy() @ col.weight.numpy() + col.bias.numpy()
    assert np.allclose(col(x).numpy(), ref, atol=1e-5)

    row = RowParallelLinear(16, 8)
    y = paddle.randn([2, 16])
    ref2 = y.numpy() @ row.weight.numpy() + row.bias.numpy()
    assert np.allclose(row(y).numpy(), ref2, atol=1e-5)

    emb = VocabParallelEmbedding(32, 8)
    ids = paddle.to_tensor(np.array([[1, 5]]))
    assert np.allclose(emb(ids).numpy()[0, 0], emb.weight.numpy()[1])
    assert emb.weight.sharding_axes == ("mp", None)
    assert col.weight.sharding_axes == (None, "mp")


def test_sharded_train_step_dp_matches_single():
    """DP over the mesh must produce the same loss trajectory as 1 device."""
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.mesh import init_mesh, set_mesh
    from paddle_tpu.parallel.spmd import make_sharded_train_step

    def build():
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
        return net, opt

    def loss_fn(out, labels):
        import jax.numpy as jnp

        logits = out if not isinstance(out, (tuple, list)) else out[0]
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None].astype("int32"), -1))

    rs = np.random.RandomState(0)
    x = rs.rand(8, 16).astype(np.float32)
    y = rs.randint(0, 4, (8,))

    losses = {}
    for degrees in ({"dp": 1}, {"dp": 8}):
        mesh = init_mesh(degrees)
        net, opt = build()
        step = make_sharded_train_step(net, loss_fn, opt, mesh, batch_specs=(P("dp"), P("dp")))
        params, buffers, opt_state = step.init_state()
        from paddle_tpu.core import rng

        ls = []
        key = jax.random.PRNGKey(0)
        for _ in range(3):
            xs, ys = step.shard_batch(x, y)
            loss, params, buffers, opt_state = step(
                params, buffers, opt_state, np.float32(0.1), key, xs, ys
            )
            ls.append(float(np.asarray(loss)))
        losses[degrees["dp"]] = ls
    set_mesh(None)
    assert np.allclose(losses[1], losses[8], atol=1e-5), losses


def test_sharded_train_step_tp_zero_matches():
    """TP (mp=2) + ZeRO-1 over sharding=2 matches the single-device loss."""
    import jax
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.fleet.meta_parallel import (
        ColumnParallelLinear,
        RowParallelLinear,
    )
    from paddle_tpu.distributed.mesh import init_mesh, set_mesh
    from paddle_tpu.parallel.spmd import make_sharded_train_step

    class MLP(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = ColumnParallelLinear(16, 32, gather_output=False)
            self.fc2 = RowParallelLinear(32, 4, input_is_parallel=True)
            self.act = nn.ReLU()

        def forward(self, x):
            return self.fc2(self.act(self.fc1(x)))

    def loss_fn(out, labels):
        import jax.numpy as jnp

        logits = out if not isinstance(out, (tuple, list)) else out[0]
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None].astype("int32"), -1))

    rs = np.random.RandomState(1)
    x = rs.rand(4, 16).astype(np.float32)
    y = rs.randint(0, 4, (4,))
    key = jax.random.PRNGKey(0)

    results = {}
    for degrees, zs in (({"dp": 1}, 0), ({"dp": 2, "mp": 2, "sharding": 2}, 1)):
        mesh = init_mesh(degrees)
        paddle.seed(0)
        net = MLP()
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
        step = make_sharded_train_step(
            net, loss_fn, opt, mesh, batch_specs=(P("dp"), P("dp")), zero_stage=zs
        )
        params, buffers, opt_state = step.init_state()
        ls = []
        for _ in range(3):
            xs, ys = step.shard_batch(x, y)
            loss, params, buffers, opt_state = step(
                params, buffers, opt_state, np.float32(0.01), key, xs, ys
            )
            ls.append(float(np.asarray(loss)))
        results[zs] = ls
    set_mesh(None)
    assert np.allclose(results[0], results[1], atol=1e-4), results


@pytest.mark.parametrize("zs", [2, 3])
def test_sharded_train_step_zero23_matches_single(zs):
    """ZeRO-2 (sharded grads+slots) and ZeRO-3 (sharded params) must track
    the single-device loss trajectory exactly; stage-3 params must actually
    live sharded on the mesh (reference group_sharded_stage3.py:59)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.distributed.fleet.meta_parallel.sharding import (
        shard_parameters_over,
    )
    from paddle_tpu.distributed.mesh import init_mesh, set_mesh
    from paddle_tpu.parallel.spmd import make_sharded_train_step

    def loss_fn(out, labels):
        logits = out if not isinstance(out, (tuple, list)) else out[0]
        logp = jax.nn.log_softmax(logits, -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None].astype("int32"), -1))

    rs = np.random.RandomState(2)
    x = rs.rand(8, 16).astype(np.float32)
    y = rs.randint(0, 4, (8,))
    key = jax.random.PRNGKey(0)

    results = {}
    for degrees, stage in (({"dp": 1}, 0), ({"dp": 2, "sharding": 4}, zs)):
        mesh = init_mesh(degrees)
        paddle.seed(0)
        # big enough that the >= degree*128 sharding threshold triggers
        net = nn.Sequential(nn.Linear(16, 512), nn.ReLU(), nn.Linear(512, 4))
        if stage >= 3:
            shard_parameters_over(net, degrees.get("sharding", 1))
        opt = paddle.optimizer.Adam(learning_rate=0.01, parameters=net.parameters())
        step = make_sharded_train_step(
            net, loss_fn, opt, mesh, batch_specs=(P("dp"), P("dp")), zero_stage=stage
        )
        params, buffers, opt_state = step.init_state()
        if stage >= 3:
            sharded = [
                k for k, v in params.items()
                if getattr(v.sharding, "spec", None) and any(v.sharding.spec)
            ]
            assert sharded, "stage-3 params must be mesh-sharded"
        if stage == 2:
            # stage-2's defining property: sharded optimizer slots
            slot_specs = [
                a.sharding.spec
                for slots in opt_state.values()
                for a in slots.values()
                if a.ndim > 0
            ]
            assert any(any(s) for s in slot_specs), "stage-2 slots must be sharded"
        ls = []
        for _ in range(4):
            xs, ys = step.shard_batch(x, y)
            loss, params, buffers, opt_state = step(
                params, buffers, opt_state, np.float32(0.01), key, xs, ys
            )
            ls.append(float(np.asarray(loss)))
        results[stage] = ls
    set_mesh(None)
    assert np.allclose(results[0], results[zs], atol=1e-4), results


def test_group_sharded_offload_rejected():
    """offload=True must fail loudly, not silently drop (advisor r3)."""
    from paddle_tpu.distributed import group_sharded_parallel
    from paddle_tpu.distributed.mesh import init_mesh, set_mesh

    init_mesh({"sharding": 8})
    net = nn.Linear(16, 16)
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    for level in ("os_g", "p_g_os"):
        with pytest.raises(NotImplementedError):
            group_sharded_parallel(net, opt, level, offload=True)
    set_mesh(None)


def test_group_sharded_segment_size_threshold():
    """segment_size maps to a replicate-below threshold for stage 3."""
    from paddle_tpu.distributed import group_sharded_parallel
    from paddle_tpu.distributed.mesh import init_mesh, set_mesh

    init_mesh({"sharding": 8})
    net = nn.Sequential(nn.Linear(16, 32), nn.Linear(32, 8))
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    # every param is far below 1MB -> nothing gets sharded
    group_sharded_parallel(net, opt, "p_g_os", segment_size=2**20)
    sharded = [
        p.sharding_axes for p in net.parameters() if p.sharding_axes and any(p.sharding_axes)
    ]
    assert not sharded
    set_mesh(None)


def test_ring_attention_matches_reference():
    import jax.numpy as jnp

    from paddle_tpu.distributed.mesh import init_mesh, set_mesh
    from paddle_tpu.ops.pallas.flash_attention import _attention_xla
    from paddle_tpu.parallel.ring_attention import ring_attention

    mesh = init_mesh({"sp": 8})
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.rand(2, 64, 2, 16).astype(np.float32))
    k = jnp.asarray(rs.rand(2, 64, 2, 16).astype(np.float32))
    v = jnp.asarray(rs.rand(2, 64, 2, 16).astype(np.float32))
    for causal in (False, True):
        out = ring_attention(q, k, v, mesh=mesh, causal=causal)
        ref = _attention_xla(q, k, v, causal=causal)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-4), causal
    set_mesh(None)


def test_collective_api_single_rank_semantics():
    import paddle_tpu.distributed as dist

    dist.init_parallel_env()
    t = paddle.to_tensor(np.ones(4, np.float32))
    out = dist.all_reduce(t)
    assert np.allclose(out.numpy(), 1.0)
    assert dist.get_world_size() == 1
    assert dist.get_rank() == 0
    dist.barrier()


def test_group_sharded_parallel_api():
    from paddle_tpu.distributed import group_sharded_parallel
    from paddle_tpu.distributed.mesh import init_mesh, set_mesh

    init_mesh({"sharding": 8})
    net = nn.Sequential(nn.Linear(16, 32), nn.Linear(32, 8))
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    model, opt2, _ = group_sharded_parallel(net, opt, "p_g_os")
    sharded = [
        p.sharding_axes for p in net.parameters() if p.sharding_axes and any(p.sharding_axes)
    ]
    assert len(sharded) >= 2  # weights got ZeRO-3 annotations
    set_mesh(None)


def test_pipeline_layer_partitioning():
    from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc, PipelineLayer

    descs = [LayerDesc(nn.Linear, 8, 8) for _ in range(8)]
    pipe = PipelineLayer(descs, num_stages=4, loss_fn=nn.MSELoss())
    assert pipe.get_stage_from_index(0) == 0
    assert pipe.get_stage_from_index(7) == 3
    x = paddle.randn([2, 8])
    out = pipe(x)
    assert out.shape == [2, 8]


def test_data_parallel_wrapper():
    net = nn.Linear(4, 4)
    dp = paddle.DataParallel(net)
    x = paddle.randn([2, 4])
    assert np.allclose(dp(x).numpy(), net(x).numpy())
    with dp.no_sync():
        assert not dp._sync
    assert dp._sync


def test_gpt_tiny_forward_and_loss():
    from paddle_tpu.models.gpt import gpt_tiny

    from paddle_tpu.distributed.mesh import set_mesh

    set_mesh(None)
    paddle.seed(0)
    model = gpt_tiny()
    ids = paddle.to_tensor(np.random.randint(0, 1024, (2, 64)))
    logits = model(ids)
    assert logits.shape == [2, 64, 1024]
    loss = nn.CrossEntropyLoss()(
        logits.reshape([-1, 1024]), ids.reshape([-1])
    )
    loss.backward()
    assert model.wte.weight.grad is not None
    assert np.isfinite(loss.item())
