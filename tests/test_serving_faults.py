"""serving/faults.py: the deterministic fault-injection plan.

Pure host-side units — triggers (at_step / nth_call / probability+seed /
request_id / times), the env-var spec, install/clear semantics, and the
one-pointer-test discipline at every hook site. The faults driving a real
engine are tests/test_serving_supervisor.py and test_serving_chaos.py.
"""
import inspect
import re

import pytest

from paddle_tpu.serving import faults
from paddle_tpu.serving.faults import FaultInjected, FaultPlan, FaultPoint


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process-global plan disarmed (and no thread
    parked in a hang)."""
    yield
    plan = faults.active()
    if plan is not None:
        plan.release_hangs()
    faults.clear()


def test_disabled_by_default():
    assert faults.active() is None
    assert faults._PLAN is None


def test_install_clear_roundtrip():
    plan = faults.install(FaultPlan([{"point": "step_raise"}]))
    assert faults.active() is plan
    faults.clear()
    assert faults.active() is None
    with pytest.raises(TypeError):
        faults.install([{"point": "step_raise"}])


def test_unknown_point_rejected():
    with pytest.raises(ValueError, match="unknown fault point"):
        FaultPoint("step_explode")
    with pytest.raises(ValueError, match="nth_call"):
        FaultPoint("step_raise", nth_call=0)
    with pytest.raises(ValueError, match="probability"):
        FaultPoint("step_raise", probability=1.5)


def test_context_triggers_rejected_on_contextless_points():
    """alloc_fail/thread_die hook sites carry no step counter or batch:
    arming at_step/request_id there would silently never fire, so the
    plan rejects the combination loudly at construction."""
    for point in ("alloc_fail", "thread_die"):
        with pytest.raises(ValueError, match="no step/batch context"):
            FaultPoint(point, at_step=3)
        with pytest.raises(ValueError, match="no step/batch context"):
            FaultPoint(point, request_id="x")
        FaultPoint(point, nth_call=2)       # context-free triggers fine
        FaultPoint(point, probability=0.5)


def test_at_step_trigger_fires_exactly_at_that_step():
    plan = FaultPlan([{"point": "step_raise", "at_step": 3}])
    hits = [s for s in range(1, 8)
            if plan.match("step_raise", step=s) is not None]
    assert hits == [3]
    assert len(plan.fired) == 1
    assert plan.fired[0]["step"] == 3


def test_nth_call_trigger_is_one_based():
    plan = FaultPlan([{"point": "alloc_fail", "nth_call": 2}])
    hits = [i for i in range(1, 6)
            if plan.match("alloc_fail") is not None]
    assert hits == [2]


def test_match_request_trigger_fires_whenever_request_in_batch():
    plan = FaultPlan([{"point": "step_raise", "request_id": "poison"}])
    assert plan.match("step_raise", step=1, request_ids=["a", "b"]) is None
    assert plan.match("step_raise", step=2,
                      request_ids=["a", "poison"]) is not None
    # unlimited by default: re-fires every time the request is present
    assert plan.match("step_raise", step=3,
                      request_ids=["poison"]) is not None
    assert plan.match("step_raise", step=4, request_ids=None) is None


def test_times_caps_total_fires():
    plan = FaultPlan([{"point": "slow_step_ms", "times": 2, "ms": 1}])
    fires = sum(plan.match("slow_step_ms") is not None for _ in range(5))
    assert fires == 2


def test_probability_trigger_is_deterministic_per_seed():
    def draws(seed):
        plan = FaultPlan([{"point": "step_raise", "probability": 0.3,
                           "seed": seed}])
        return [plan.match("step_raise") is not None for _ in range(50)]

    a, b, c = draws(7), draws(7), draws(8)
    assert a == b                      # same seed -> same fault sequence
    assert a != c                      # different seed -> different one
    assert 0 < sum(a) < 50             # actually Bernoulli, not const


def test_conditions_are_anded():
    plan = FaultPlan([{"point": "step_raise", "at_step": 2,
                       "request_id": "x"}])
    assert plan.match("step_raise", step=2, request_ids=["y"]) is None
    assert plan.match("step_raise", step=3, request_ids=["x"]) is None
    assert plan.match("step_raise", step=2, request_ids=["x"]) is not None


def test_point_name_mismatch_never_fires():
    plan = FaultPlan([{"point": "step_hang"}])
    assert plan.match("step_raise", step=1) is None
    assert plan.fired == []


def test_hang_release_is_sticky_and_timeout_bounded():
    plan = FaultPlan([{"point": "step_hang", "timeout_s": 0.01}])
    fp = plan.match("step_hang")
    plan.hang(fp)                      # returns via its own timeout
    plan.release_hangs()
    fp2 = plan.add("step_hang")        # no timeout, but released already
    plan.hang(fp2)                     # passes straight through


def test_plan_from_json_list_and_object_forms():
    p1 = faults.plan_from_json('[{"point": "step_raise", "at_step": 1}]')
    assert len(p1.points) == 1 and p1.points[0].at_step == 1
    p2 = faults.plan_from_json(
        '{"points": [{"point": "alloc_fail"}, '
        '{"point": "slow_step_ms", "ms": 5}]}')
    assert [fp.point for fp in p2.points] == ["alloc_fail", "slow_step_ms"]
    assert p2.points[1].ms == 5.0
    with pytest.raises(ValueError, match="JSON list"):
        faults.plan_from_json('"step_raise"')


def test_env_install_respects_existing_plan(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FAULTS",
                       '[{"point": "thread_die", "nth_call": 9}]')
    installed = faults.maybe_install_from_env()
    assert installed is faults.active()
    assert installed.points[0].point == "thread_die"
    # an explicitly installed plan wins over the env on later calls
    mine = faults.install(FaultPlan())
    assert faults.maybe_install_from_env() is mine
    faults.clear()
    monkeypatch.delenv("PADDLE_TPU_FAULTS")
    assert faults.maybe_install_from_env() is None


def test_fault_injected_carries_point():
    e = FaultInjected("step_raise")
    assert e.point == "step_raise"
    assert "step_raise" in str(e)


def test_hook_sites_are_one_pointer_test():
    """The disabled-path discipline (same as the tracer): every hook site
    in the serving hot paths guards on the single module-attribute test
    ``faults._PLAN is not None`` — no plan construction, env read, or
    method call happens on the no-fault path."""
    from paddle_tpu.serving import block_pool, engine, frontend

    guard = re.compile(r"faults\._PLAN is not None")
    # engine: the step-scoped hook + the unified step's row_ok corruption
    # site (one emission path since the ragged-program unification)
    assert len(guard.findall(inspect.getsource(engine))) >= 2
    # block pool: alloc_fail
    assert len(guard.findall(inspect.getsource(block_pool))) >= 1
    # frontend: thread_die in the engine loop
    assert len(guard.findall(inspect.getsource(frontend))) >= 1
    # and no hook site calls faults.active() (an extra function call on
    # the hot path) — active() is the test/inspection API
    for mod in (engine, block_pool, frontend):
        assert "faults.active()" not in inspect.getsource(mod)
