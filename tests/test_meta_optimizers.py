"""Strategy-activated meta-optimizers: gradient merge, LocalSGD, Lars
(VERDICT r3 item 6 — the DistributedStrategy fields must DRIVE behavior).

Reference: fleet/meta_optimizers/{gradient_merge,localsgd,lars}_optimizer.py.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn


def teardown_module():
    from paddle_tpu.distributed.mesh import set_mesh

    set_mesh(None)


def _fleet_opt(strategy, net, base_opt):
    from paddle_tpu.distributed import fleet

    fleet.init(is_collective=True, strategy=strategy)
    return fleet.fleet.distributed_optimizer(base_opt)


def _train(net, opt, x, y, steps):
    loss_fn = nn.MSELoss()
    losses = []
    for _ in range(steps):
        loss = loss_fn(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    return losses


class TestGradientMergeEager:
    def _strategy(self, gm_k=None):
        from paddle_tpu.distributed.fleet import DistributedStrategy

        s = DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                            "sharding_degree": 1}
        if gm_k:
            s.gradient_merge = True
            s.gradient_merge_configs = {"k_steps": gm_k, "avg": True}
        return s

    def test_k_steps_changes_trajectory_and_matches_big_batch(self):
        rs = np.random.RandomState(0)
        X = rs.randn(8, 6).astype(np.float32)
        Y = rs.randn(8, 3).astype(np.float32)

        def build():
            paddle.seed(0)
            net = nn.Linear(6, 3)
            return net, paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters())

        # merged k=2 over half-batches == plain SGD on the full batch
        net1, base1 = build()
        opt1 = _fleet_opt(self._strategy(gm_k=2), net1, base1)
        loss_fn = nn.MSELoss()
        for half in (slice(0, 4), slice(4, 8)):
            loss = loss_fn(net1(paddle.to_tensor(X[half])), paddle.to_tensor(Y[half]))
            loss.backward()
            opt1.step()
            opt1.clear_grad()
        net2, opt2 = build()
        loss = loss_fn(net2(paddle.to_tensor(X)), paddle.to_tensor(Y))
        loss.backward()
        opt2.step()
        np.testing.assert_allclose(
            net1.weight.numpy(), net2.weight.numpy(), rtol=1e-5, atol=1e-6
        )

        # and it differs from NOT merging (strategy field actually drives)
        net3, base3 = build()
        opt3 = _fleet_opt(self._strategy(None), net3, base3)
        for half in (slice(0, 4), slice(4, 8)):
            loss = loss_fn(net3(paddle.to_tensor(X[half])), paddle.to_tensor(Y[half]))
            loss.backward()
            opt3.step()
            opt3.clear_grad()
        assert not np.allclose(net1.weight.numpy(), net3.weight.numpy())


class TestGradientMergeCompiled:
    def test_compiled_k2_matches_double_batch(self):
        from paddle_tpu.distributed.mesh import init_mesh, set_mesh
        from paddle_tpu.parallel.spmd import make_sharded_train_step

        mesh = init_mesh({"dp": 2})

        def loss_fn(out, labels):
            o = out if not isinstance(out, (tuple, list)) else out[0]
            return jnp.mean((o - labels) ** 2)

        rs = np.random.RandomState(1)
        X = rs.randn(8, 6).astype(np.float32)
        Y = rs.randn(8, 3).astype(np.float32)

        def build(gm_k):
            paddle.seed(0)
            net = nn.Linear(6, 3)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=net.parameters())
            return make_sharded_train_step(
                net, loss_fn, opt, mesh, batch_specs=(P("dp"), P("dp")),
                gradient_merge_k=gm_k,
            )

        key = jax.random.PRNGKey(0)
        # k=2 over the two halves
        step = build(2)
        params, buffers, opt_state = step.init_state()
        for half in (slice(0, 4), slice(4, 8)):
            xs, ys = step.shard_batch(X[half], Y[half])
            loss, params, buffers, opt_state = step(
                params, buffers, opt_state, np.float32(0.1), key, xs, ys
            )
        w_merged = np.asarray(params["weight"])

        # one step on the full batch, no merging
        step2 = build(1)
        params2, buffers2, opt_state2 = step2.init_state()
        xs, ys = step2.shard_batch(X, Y)
        loss, params2, buffers2, opt_state2 = step2(
            params2, buffers2, opt_state2, np.float32(0.1), key, xs, ys
        )
        np.testing.assert_allclose(
            w_merged, np.asarray(params2["weight"]), rtol=1e-5, atol=1e-6
        )
        set_mesh(None)


class TestLocalSGD:
    def test_k1_matches_dp_and_k3_diverges_then_syncs(self):
        from paddle_tpu.distributed.mesh import init_mesh, set_mesh
        from paddle_tpu.parallel.spmd import (
            LocalSGDTrainStep,
            make_sharded_train_step,
        )

        mesh = init_mesh({"dp": 4})

        def loss_fn(out, labels):
            o = out if not isinstance(out, (tuple, list)) else out[0]
            return jnp.mean((o - labels) ** 2)

        rs = np.random.RandomState(2)
        X = rs.randn(8, 6).astype(np.float32)
        Y = rs.randn(8, 3).astype(np.float32)
        key = jax.random.PRNGKey(0)

        def build_net():
            paddle.seed(0)
            net = nn.Linear(6, 3)
            opt = paddle.optimizer.SGD(learning_rate=0.05,
                                       parameters=net.parameters())
            return net, opt

        # k=1 (sync every step) with SGD == grad-averaged DP
        net, opt = build_net()
        ls = LocalSGDTrainStep(net, loss_fn, opt, mesh, k_steps=1)
        params, buffers, opt_state, count = ls.init_state()
        for _ in range(3):
            xs, ys = ls.shard_batch(X, Y)
            loss, params, buffers, opt_state, count = ls(
                params, buffers, opt_state, count, np.float32(0.05), key, xs, ys
            )
        w_local = np.asarray(params["weight"][0])

        net2, opt2 = build_net()
        dp = make_sharded_train_step(net2, loss_fn, opt2, mesh,
                                     batch_specs=(P("dp"), P("dp")))
        p2, b2, o2 = dp.init_state()
        for _ in range(3):
            xs, ys = dp.shard_batch(X, Y)
            loss, p2, b2, o2 = dp(p2, b2, o2, np.float32(0.05), key, xs, ys)
        np.testing.assert_allclose(
            w_local, np.asarray(p2["weight"]), rtol=1e-4, atol=1e-5
        )

        # k=3: after 2 steps replicas have DIVERGED; after the 3rd they agree
        net3, opt3 = build_net()
        ls3 = LocalSGDTrainStep(net3, loss_fn, opt3, mesh, k_steps=3)
        params, buffers, opt_state, count = ls3.init_state()
        for i in range(3):
            xs, ys = ls3.shard_batch(X, Y)
            loss, params, buffers, opt_state, count = ls3(
                params, buffers, opt_state, count, np.float32(0.05), key, xs, ys
            )
            w = np.asarray(params["weight"])
            spread = np.abs(w - w.mean(0, keepdims=True)).max()
            if i < 2:
                assert spread > 1e-6, f"step {i}: replicas did not diverge"
            else:
                assert spread < 1e-6, f"sync step: replicas still differ {spread}"
        # and the local-k3 trajectory differs from the k=1 trajectory
        assert not np.allclose(np.asarray(params["weight"][0]), w_local)
        set_mesh(None)


class TestLars:
    def test_strategy_swaps_momentum_for_lars(self):
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.optimizer import Lars

        rs = np.random.RandomState(3)
        X = rs.randn(8, 6).astype(np.float32)
        Y = rs.randn(8, 3).astype(np.float32)

        def run(lars):
            paddle.seed(0)
            net = nn.Linear(6, 3)
            base = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                             parameters=net.parameters())
            s = DistributedStrategy()
            s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                                "sharding_degree": 1}
            s.lars = lars
            opt = _fleet_opt(s, net, base)
            if lars:
                assert isinstance(opt._inner_opt, Lars)
            _train(net, opt, paddle.to_tensor(X), paddle.to_tensor(Y), 3)
            return net.weight.numpy()

        w_lars = run(True)
        w_mom = run(False)
        assert not np.allclose(w_lars, w_mom)

    def test_lars_optimizer_math(self):
        """One step against the hand-computed LARS update."""
        from paddle_tpu.optimizer import Lars

        w0 = np.array([[3.0, 4.0]], np.float32)  # ||w|| = 5
        g = np.array([[0.6, 0.8]], np.float32)   # ||g|| = 1
        p = paddle.Parameter(w0.copy())
        opt = Lars(learning_rate=1.0, momentum=0.0, lars_coeff=0.01,
                   lars_weight_decay=0.0, parameters=[p])
        from paddle_tpu.core.tensor import Tensor

        p._grad = Tensor(g)
        opt.step()
        local_lr = 1.0 * 0.01 * 5.0 / 1.0
        np.testing.assert_allclose(
            p.numpy(), w0 - local_lr * g, rtol=1e-5
        )


class TestDGC:
    def test_strategy_swaps_momentum_for_dgc_and_trajectory_differs(self):
        from paddle_tpu.optimizer.optimizers import DGCMomentum

        rs = np.random.RandomState(5)
        X = rs.randn(8, 6).astype(np.float32)
        Y = rs.randn(8, 3).astype(np.float32)

        def run(dgc):
            from paddle_tpu.distributed.fleet import DistributedStrategy

            paddle.seed(0)
            net = nn.Linear(6, 3)
            base = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                             parameters=net.parameters())
            s = DistributedStrategy()
            s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                                "sharding_degree": 1}
            s.dgc = dgc
            s.dgc_configs = {"rampup_begin_step": 0, "sparsity": [0.9]}
            opt = _fleet_opt(s, net, base)
            if dgc:
                assert isinstance(opt._inner_opt, DGCMomentum)
            _train(net, opt, paddle.to_tensor(X), paddle.to_tensor(Y), 3)
            return net.weight.numpy()

        w_dgc = run(True)
        w_mom = run(False)
        assert not np.allclose(w_dgc, w_mom)

    def test_error_feedback_conserves_gradient_mass(self):
        """What top-k drops this step must come back via the residual: with
        sparsity s, two steps of constant grad g apply >= the mass of one
        dense step (error feedback never loses gradient)."""
        from paddle_tpu.core.tensor import Tensor
        from paddle_tpu.optimizer.optimizers import DGCMomentum

        w = paddle.Parameter(np.zeros(8, np.float32))
        opt = DGCMomentum(learning_rate=1.0, momentum=0.0, sparsity=0.75,
                          parameters=[w])
        g = np.arange(1, 9, dtype=np.float32)  # top-2 kept per step
        for _ in range(2):
            w._grad = np.asarray(g)
            opt.step()
        # conservation: applied mass + banked residual == total gradient mass
        # (error feedback never loses gradient), and per-step transmission
        # was actually sparse (strictly less than one dense step of mass
        # applied after step 1 would imply)
        applied = -np.asarray(w.numpy())
        residual = np.asarray(opt._accumulators[id(w)]["residual"])
        np.testing.assert_allclose(
            applied.sum() + residual.sum(), 2 * g.sum(), rtol=1e-6
        )
        assert (applied > 0).sum() < g.size  # some entries never transmitted


def test_inmemory_dataset_and_paddle_batch(tmp_path):
    """InMemoryDataset slot-text parsing + native shuffle; QueueDataset
    streaming; paddle.batch reader decorator (reference dataset.py:291,
    batch.py)."""
    import paddle_tpu as paddle

    f = tmp_path / "slots.txt"
    lines = []
    for i in range(10):
        # two slots: dim-2 dense + dim-1 label
        lines.append(f"2 {i}.0 {i + 0.5} 1 {i % 3}")
    f.write_text("\n".join(lines))

    ds = paddle.io.InMemoryDataset()
    ds.init(batch_size=4, thread_num=2)

    class Var:
        def __init__(self, name, shape):
            self.name, self.shape = name, shape

    ds.set_use_var([Var("x", [-1, 2]), Var("y", [-1, 1])])
    ds.set_filelist([str(f)])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    paddle.seed(0)
    ds.local_shuffle()
    batches = list(ds)
    # 2 full batches + the partial tail (drop_last defaults to False)
    assert len(batches) == 3 and batches[0][0].shape == (4, 2)
    assert batches[-1][0].shape == (2, 2)
    seen = sorted(x for b in batches for x in b[0][:, 0].tolist())
    assert len(set(seen)) == 10  # shuffled but all real rows
    ds.set_drop_last(True)
    assert len(list(ds)) == 2

    qd = paddle.io.QueueDataset()
    qd.init(batch_size=5)
    qd.set_use_var([Var("x", [-1, 2]), Var("y", [-1, 1])])
    qd.set_filelist([str(f)])
    stream = list(qd)
    assert len(stream) == 2 and stream[0][0][0, 0] == 0.0  # stream order: 5+5

    def reader():
        yield from range(7)

    out = list(paddle.batch(reader, 3)())
    assert out == [[0, 1, 2], [3, 4, 5], [6]]
    out = list(paddle.batch(reader, 3, drop_last=True)())
    assert out == [[0, 1, 2], [3, 4, 5]]
