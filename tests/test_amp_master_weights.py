"""AMP O2 master weights (multi_precision).

Reference contract: /root/reference/python/paddle/optimizer/adam.py:92,174,209
keeps an fp32 master copy per low-precision param; the update applies to the
master and the working param is a re-cast. The observable difference: with a
per-step update below the bf16 epsilon (2^-8 relative), bf16-only training is
STUCK (every update rounds away) while bf16+master tracks the fp32 run.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


class OneParam(nn.Layer):
    def __init__(self, n=64):
        super().__init__()
        self.w = self.create_parameter(
            [n], default_initializer=paddle.nn.initializer.Constant(1.0)
        )

    def forward(self):
        # constant gradient dw = 1e-4: far below bf16 epsilon at w ~ 1.0
        return (self.w * 1e-4).sum()


STEPS = 300
EXPECTED = 1.0 - STEPS * 1.0 * 1e-4  # SGD lr=1.0: w -= 1e-4 each step


def _run_eager(master_weight):
    paddle.seed(0)
    model = OneParam()
    opt = paddle.optimizer.SGD(learning_rate=1.0, parameters=model.parameters())
    model, opt = paddle.amp.decorate(
        model, opt, level="O2", master_weight=master_weight
    )
    assert str(model.w._array.dtype) == "bfloat16"
    for _ in range(STEPS):
        loss = model()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return model, opt


def test_bf16_only_is_stuck():
    model, _ = _run_eager(master_weight=False)
    w = np.asarray(model.w._array.astype(np.float32))
    # every sub-epsilon update rounded away: the param never moved
    assert np.allclose(w, 1.0), w[:4]


def test_master_weight_tracks_fp32():
    model, opt = _run_eager(master_weight=True)
    w = np.asarray(model.w._array.astype(np.float32))
    # working copy is a bf16 re-cast of the fp32 master -> bf16-level accuracy
    assert np.allclose(w, EXPECTED, atol=4e-3), (w[:4], EXPECTED)
    st = opt._accumulators[id(model.w)]
    master = np.asarray(st["master_weight"])
    assert master.dtype == np.float32
    # the master integrates the (bf16-rounded) gradient in full fp32: the
    # only error left is grad rounding, ~1.4e-7/step — 40x below bf16 eps
    assert np.allclose(master, EXPECTED, atol=1e-4), (master[:4], EXPECTED)


def test_adam_master_weight_matches_fp32_run():
    """bf16+master Adam tracks an fp32 Adam run; bf16-only visibly drifts."""
    import jax
    import jax.numpy as jnp

    rs = np.random.RandomState(0)
    # start from a bf16-representable point so the fp32 reference and the
    # bf16+master run share their initial state exactly (init_state_arrays
    # seeds the master from the params it is given)
    w0 = np.asarray(
        jnp.asarray(rs.rand(128).astype(np.float32) + 0.5, jnp.bfloat16).astype(
            jnp.float32
        )
    )
    # positive-biased gradients: the fp32 trajectory moves ~lr*STEPS = 0.03
    # in one direction (sub-eps per step), while bf16-only cannot move at all
    grads_host = rs.rand(STEPS, 128).astype(np.float32) + 0.5

    def run(dtype, multi_precision):
        o = paddle.optimizer.Adam(learning_rate=1e-4, multi_precision=multi_precision)
        params = {"w": jnp.asarray(w0, dtype)}
        state = o.init_state_arrays(params)

        @jax.jit
        def step(params, state, g):
            return o.apply_gradients_arrays(
                params, {"w": g}, state, jnp.float32(1e-4)
            )

        for i in range(STEPS):
            params, state = step(params, state, jnp.asarray(grads_host[i]))
        return np.asarray(params["w"].astype(jnp.float32)), state

    ref, _ = run(jnp.float32, False)
    got, state = run(jnp.bfloat16, True)
    stuck, _ = run(jnp.bfloat16, False)
    assert "master_weight" in state["w"]
    err_master = np.abs(got - ref).max()
    err_stuck = np.abs(stuck - ref).max()
    # master tracks fp32 to bf16 rounding; bf16-only drifts visibly worse
    assert err_master < 6e-3, err_master
    assert err_stuck > 3 * err_master, (err_stuck, err_master)


def test_master_weight_checkpoint_roundtrip():
    model, opt = _run_eager(master_weight=True)
    sd = opt.state_dict()
    master_keys = [k for k in sd if k.endswith("_master_weight")]
    assert master_keys, list(sd)

    paddle.seed(0)
    model2 = OneParam()
    opt2 = paddle.optimizer.SGD(learning_rate=1.0, parameters=model2.parameters())
    model2, opt2 = paddle.amp.decorate(model2, opt2, level="O2", master_weight=True)
    opt2.set_state_dict(sd)
    st = opt2._accumulators[id(model2.w)]
    np.testing.assert_allclose(
        np.asarray(st["master_weight"]),
        np.asarray(opt._accumulators[id(model.w)]["master_weight"]),
        rtol=0, atol=0,
    )
    # resumed training continues the fp32 trajectory exactly
    for _ in range(10):
        loss = model2()
        loss.backward()
        opt2.step()
        opt2.clear_grad()
    master = np.asarray(opt2._accumulators[id(model2.w)]["master_weight"])
    assert np.allclose(master, EXPECTED - 10 * 1e-4, atol=1e-4)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
