"""jit.save/load program artifact (VERDICT round-2 item 4; reference
jit/translated_layer.py, static/io.py:442 save/load_inference_model)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import jit, nn
from paddle_tpu.static import InputSpec


def _mlp():
    paddle.seed(11)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


def test_save_load_bit_equal(tmp_path):
    net = _mlp()
    net.eval()
    x = paddle.to_tensor(np.random.RandomState(0).rand(3, 4).astype(np.float32))
    ref = net(x).numpy()
    path = str(tmp_path / "m" / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    assert os.path.exists(path + ".pdmodel")
    assert os.path.exists(path + ".pdiparams")

    loaded = jit.load(path)
    out = loaded(x).numpy()
    assert np.array_equal(out, ref)  # bit-equal, same process


def test_polymorphic_batch(tmp_path):
    net = _mlp()
    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    loaded = jit.load(path)
    for b in (1, 5, 16):
        out = loaded(paddle.to_tensor(np.ones((b, 4), np.float32)))
        assert out.numpy().shape == (b, 2)


def test_load_in_fresh_process_without_model_class(tmp_path):
    """The artifact must run where the model's Python class does not exist
    (the deployment contract of the reference's TranslatedLayer)."""
    net = _mlp()
    net.eval()
    x = np.random.RandomState(1).rand(2, 4).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()
    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    np.save(str(tmp_path / "x.npy"), x)
    np.save(str(tmp_path / "ref.npy"), ref)

    script = f"""
import os
os.environ['XLA_FLAGS'] = '--xla_force_host_platform_device_count=1'
import jax; jax.config.update('jax_platforms', 'cpu')
import numpy as np
import sys
sys.path.insert(0, {str(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))!r})
from paddle_tpu import jit
loaded = jit.load({path!r})
x = np.load({str(tmp_path / "x.npy")!r})
out = loaded(x).numpy()
ref = np.load({str(tmp_path / "ref.npy")!r})
assert np.array_equal(out, ref), (out, ref)
print("FRESH_PROCESS_OK")
"""
    r = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True, timeout=180
    )
    assert "FRESH_PROCESS_OK" in r.stdout, (r.stdout, r.stderr)


def test_predictor_accepts_artifact(tmp_path):
    from paddle_tpu.inference import Config, create_predictor

    net = _mlp()
    net.eval()
    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    x = np.random.RandomState(2).rand(3, 4).astype(np.float32)
    ref = net(paddle.to_tensor(x)).numpy()

    cfg = Config(model_path=path)
    pred = create_predictor(cfg)
    out = pred.run([x])
    assert np.allclose(out[0], ref, atol=1e-6)


def test_loaded_artifact_weight_swap(tmp_path):
    net = _mlp()
    net.eval()
    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    loaded = jit.load(path)

    net2 = _mlp()  # same arch, different init
    net2.eval()
    for p in net2.parameters():
        p.set_value(np.asarray(p.numpy()) * 0.5)
    loaded.set_state_dict(net2.state_dict())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    assert np.allclose(loaded(x).numpy(), net2(x).numpy(), atol=1e-6)


def test_artifact_buffer_swap_batchnorm(tmp_path):
    """set_state_dict on a loaded artifact must swap BUFFERS too (BatchNorm
    running stats), not only parameters."""
    paddle.seed(5)
    net = nn.Sequential(nn.Linear(4, 6), nn.BatchNorm1D(6))
    # train a few steps so running stats move away from init
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters())
    for _ in range(3):
        net.train()
        out = net(paddle.to_tensor(np.random.RandomState(0).rand(8, 4).astype(np.float32)))
        out.sum().backward()
        opt.step()
        opt.clear_grad()
    net.eval()
    path = str(tmp_path / "bnmodel")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    loaded = jit.load(path)

    # second model with different running stats
    paddle.seed(9)
    net2 = nn.Sequential(nn.Linear(4, 6), nn.BatchNorm1D(6))
    for _ in range(5):
        net2.train()
        out = net2(paddle.to_tensor(np.random.RandomState(7).rand(8, 4).astype(np.float32) * 3))
        out.sum().backward()
    net2.eval()

    loaded.set_state_dict(net2.state_dict())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    assert np.allclose(loaded(x).numpy(), net2(x).numpy(), atol=1e-5)


def test_conv_model_symbolic_batch(tmp_path):
    """Conv+flatten models (shape math over symbolic dims) export too."""
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    net.eval()
    path = str(tmp_path / "lenet")
    jit.save(net, path, input_spec=[InputSpec([None, 1, 28, 28], "float32")])
    loaded = jit.load(path)
    x = np.random.RandomState(0).rand(4, 1, 28, 28).astype(np.float32)
    assert np.array_equal(loaded(x).numpy(), net(paddle.to_tensor(x)).numpy())


def test_save_requires_input_spec(tmp_path):
    with pytest.raises(ValueError, match="input_spec"):
        jit.save(_mlp(), str(tmp_path / "m"))


def test_loaded_artifact_cannot_train(tmp_path):
    net = _mlp()
    path = str(tmp_path / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 4], "float32")])
    loaded = jit.load(path)
    with pytest.raises(RuntimeError, match="cannot be trained"):
        loaded.train()
