"""Serving C ABI (VERDICT r3 missing #7): a real C program consumes the
predictor through csrc/predictor_capi.cc — no Python in the consumer.

Flow: jit.save a model -> build libpd_capi.so -> compile a C driver with
gcc -> run it as a fresh process (PYTHONPATH points the embedded interpreter
at the repo) -> it prints the output values -> compare against the in-Python
predictor on the same input.
"""
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_C_DRIVER = r"""
#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* (*create_fn)(const char*);
typedef int (*run_fn)(void*, const float*, const int64_t*, int);
typedef int64_t (*numel_fn)(void*, int);
typedef int (*data_fn)(void*, int, float*);
typedef const char* (*err_fn)(void);

int main(int argc, char** argv) {
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
  if (!lib) { fprintf(stderr, "dlopen: %s\n", dlerror()); return 2; }
  create_fn create = (create_fn)dlsym(lib, "PD_PredictorCreate");
  run_fn run = (run_fn)dlsym(lib, "PD_PredictorRun");
  numel_fn numel = (numel_fn)dlsym(lib, "PD_GetOutputNumel");
  data_fn data = (data_fn)dlsym(lib, "PD_GetOutputData");
  err_fn err = (err_fn)dlsym(lib, "PD_GetLastError");
  void* p = create(argv[2]);
  if (!p) { fprintf(stderr, "create: %s\n", err()); return 3; }
  float in[8];
  for (int i = 0; i < 8; ++i) in[i] = 0.25f * (float)(i + 1);
  int64_t shape[2] = {2, 4};
  int n = run(p, in, shape, 2);
  if (n < 1) { fprintf(stderr, "run: %s\n", err()); return 4; }
  int64_t ne = numel(p, 0);
  float* out = (float*)malloc(sizeof(float) * (size_t)ne);
  data(p, 0, out);
  for (int64_t i = 0; i < ne; ++i) printf("%.6f\n", (double)out[i]);
  free(out);
  /* ADVICE r5 regression: an out-of-range output idx must return -1 AND
     set the thread-local error (the early returns used to skip
     g_last_error, so callers printed a stale/empty message). */
  int64_t bad = numel(p, 99);
  const char* msg = err();
  if (bad != -1 || msg == NULL || strstr(msg, "out of range") == NULL) {
    fprintf(stderr, "bad-idx error not set: rc=%lld msg='%s'\n",
            (long long)bad, msg ? msg : "(null)");
    return 5;
  }
  return 0;
}
"""


@pytest.mark.skipif(sys.platform != "linux", reason="dlopen test is linux-only")
def test_c_consumer_matches_python_predictor():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.inference.capi import build_capi
    from paddle_tpu.jit import save as jit_save
    from paddle_tpu.static import InputSpec

    with tempfile.TemporaryDirectory() as td:
        paddle.seed(0)
        net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 3))
        net.eval()
        model_path = os.path.join(td, "m")
        jit_save(net, model_path, input_spec=[InputSpec([None, 4], "float32")])

        x = (0.25 * np.arange(1, 9, dtype=np.float32)).reshape(2, 4)
        cfg = Config(model_path=model_path)
        expected = create_predictor(cfg).run([x])[0]

        so = build_capi()
        c_src = os.path.join(td, "driver.c")
        with open(c_src, "w") as f:
            f.write(_C_DRIVER)
        exe = os.path.join(td, "driver")
        subprocess.run(["gcc", "-O2", c_src, "-o", exe, "-ldl"], check=True)
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}  # no TPU hook in the consumer
        env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
        env["JAX_PLATFORMS"] = "cpu"  # match the artifact's export platform
        proc = subprocess.run(
            [exe, so, model_path], capture_output=True, text=True, timeout=300,
            env=env,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        got = np.asarray([float(l) for l in proc.stdout.split()], np.float32)
        np.testing.assert_allclose(got, expected.reshape(-1), rtol=1e-5, atol=1e-6)


def test_goapi_run_keepalive_and_bounds_guards():
    """ADVICE r5 regression (source contract — the image ships no Go
    toolchain, so the guards are pinned at the source level): `Run` must
    KeepAlive the Predictor past the cgo call (the NewPredictor finalizer
    may otherwise Destroy the handle while a Run is in flight) and must
    reject empty data/shape slices before taking `&data[0]`/`&shape[0]`
    (which would panic)."""
    src = open(os.path.join(REPO, "goapi", "paddle.go")).read()
    # the finalizer that makes KeepAlive necessary is still registered
    assert "runtime.SetFinalizer(p," in src
    run_body = src.split("func (p *Predictor) Run(")[1].split("\nfunc ")[0]
    assert "runtime.KeepAlive(p)" in run_body
    assert "len(data) == 0 || len(shape) == 0" in run_body
    # guards sit BEFORE the element-address-taking cgo call
    guard = run_body.index("len(data) == 0")
    keepalive = run_body.index("runtime.KeepAlive(p)")
    call = run_body.index("C.PD_PredictorRun(")
    assert guard < call and keepalive < call
