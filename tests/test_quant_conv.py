"""Conv quantization (VERDICT r4 item 7 / Missing #4).

Reference: /root/reference/python/paddle/static/quantization/
post_training_quantization.py:117 — conv2d is in the quantizable op set with
per-channel weight scales. Here: QuantedConv2D (fake-quant QAT/calibration)
and Int8Conv2D (emitted int8 x int8 -> int32 conv_general_dilated), so a CNN
can be int8-served end to end.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import (
    PTQ,
    QAT,
    Int8Conv2D,
    Int8Linear,
    QuantConfig,
    QuantedConv2D,
)
from paddle_tpu.vision.models import LeNet


def test_qat_swaps_conv_layers():
    paddle.seed(0)
    net = LeNet()
    q = QAT(QuantConfig())
    q.quantize(net)
    convs = [s for s in net.sublayers() if isinstance(s, QuantedConv2D)]
    assert len(convs) == 2  # LeNet has two Conv2D


def test_quanted_conv_forward_close_to_float():
    paddle.seed(1)
    conv = nn.Conv2D(3, 8, 3, padding=1)
    x = paddle.to_tensor(np.random.RandomState(0).rand(2, 3, 16, 16).astype(np.float32))
    ref = np.asarray(conv(x)._array)
    qconv = QuantedConv2D(conv)
    out = np.asarray(qconv(x)._array)
    # 8-bit fake quant: ~1% relative error on the output scale
    assert np.abs(out - ref).max() < 0.05 * max(np.abs(ref).max(), 1.0)
    assert float(qconv.act_absmax._array) > 0  # calibrated


def test_quanted_conv_gradients_flow():
    """Straight-through estimator: grads reach weight and input."""
    paddle.seed(2)
    conv = nn.Conv2D(1, 4, 3)
    qconv = QuantedConv2D(conv)
    x = paddle.to_tensor(np.ones((1, 1, 8, 8), np.float32))
    x.stop_gradient = False
    loss = qconv(x).mean()
    loss.backward()
    assert conv.weight.grad is not None
    assert float(np.abs(np.asarray(conv.weight.grad._array)).max()) > 0


def _calibrated_int8_lenet(n_cal=8):
    paddle.seed(3)
    net = LeNet()
    rs = np.random.RandomState(0)
    X = rs.rand(64, 1, 28, 28).astype(np.float32)
    ptq = PTQ(QuantConfig())
    ptq.quantize(net)
    for i in range(n_cal):  # calibration pass
        net(paddle.to_tensor(X[i * 8 : (i + 1) * 8]))
    net = ptq.convert(net)
    return net, X


def test_ptq_lenet_emits_int8_convs_and_linears():
    net, _ = _calibrated_int8_lenet()
    kinds = [type(s).__name__ for s in net.sublayers()]
    assert kinds.count("Int8Conv2D") == 2
    assert kinds.count("Int8Linear") == 3
    # weights really are int8
    conv = [s for s in net.sublayers() if isinstance(s, Int8Conv2D)][0]
    assert np.asarray(conv.q_weight._array).dtype == np.int8


def test_ptq_lenet_accuracy_delta():
    """int8 LeNet classifies (argmax) nearly identically to float LeNet —
    the reference's PTQ acceptance criterion is a bounded accuracy delta."""
    net, X = _calibrated_int8_lenet()
    paddle.seed(3)
    ref_net = LeNet()  # same seed -> same float weights
    xb = paddle.to_tensor(X)
    ref_logits = np.asarray(ref_net(xb)._array)
    int8_logits = np.asarray(net(xb)._array)
    ref_top = ref_logits.argmax(1)
    int8_top = int8_logits.argmax(1)
    agreement = (ref_top == int8_top).mean()
    # untrained logits have near-zero argmax margins, so even tiny int8
    # noise flips some; >=85% agreement + bounded logit error is the gate
    assert agreement >= 0.85, agreement
    # logits stay close in scale too
    denom = max(np.abs(ref_logits).max(), 1.0)
    assert np.abs(int8_logits - ref_logits).max() / denom < 0.2


def test_int8_conv_respects_stride_padding_groups():
    paddle.seed(4)
    conv = nn.Conv2D(4, 8, 3, stride=2, padding=1, groups=2)
    x = paddle.to_tensor(np.random.RandomState(1).rand(2, 4, 16, 16).astype(np.float32))
    ref = np.asarray(conv(x)._array)
    qconv = QuantedConv2D(conv)
    qconv(x)  # calibrate
    from paddle_tpu.quantization import _emit_int8

    holder = nn.Sequential(qconv)
    _emit_int8(holder)
    int8_conv = holder[0]
    assert isinstance(int8_conv, Int8Conv2D)
    out = np.asarray(int8_conv(x)._array)
    assert out.shape == ref.shape
    assert np.abs(out - ref).max() < 0.1 * max(np.abs(ref).max(), 1.0)


def test_int8_model_serves_through_predictor(tmp_path):
    """The emitted int8 CNN exports via jit.save and serves through the
    inference predictor (the VERDICT's 'predictor serving it' criterion)."""
    net, X = _calibrated_int8_lenet()
    net.eval()
    from paddle_tpu import inference, jit
    from paddle_tpu.static import InputSpec

    path = str(tmp_path / "int8_lenet" / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 1, 28, 28], "float32")])
    config = inference.Config(model_path=path)
    predictor = inference.create_predictor(config)
    names = predictor.get_input_names()
    h = predictor.get_input_handle(names[0])
    h.copy_from_cpu(X[:4])
    predictor.run()
    out = predictor.get_output_handle(predictor.get_output_names()[0]).copy_to_cpu()
    direct = np.asarray(net(paddle.to_tensor(X[:4]))._array)
    np.testing.assert_allclose(out, direct, rtol=1e-4, atol=1e-5)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
