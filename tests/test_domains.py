"""Domain API tests: fft, signal, sparse, geometric, incubate, quantization,
inference, flags, audio, text, distributions."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_fft_roundtrip():
    x = paddle.randn([4, 16])
    spec = paddle.fft.fft(x)
    back = paddle.fft.ifft(spec)
    assert np.allclose(back.numpy().real, x.numpy(), atol=1e-5)
    r = paddle.fft.rfft(x)
    assert r.shape == [4, 9]
    xb = paddle.fft.irfft(r, n=16)
    assert np.allclose(xb.numpy(), x.numpy(), atol=1e-5)


def test_fft_matches_numpy():
    a = np.random.rand(8, 8).astype(np.float32)
    out = paddle.fft.fft2(paddle.to_tensor(a))
    assert np.allclose(out.numpy(), np.fft.fft2(a), atol=1e-4)


def test_stft_istft_roundtrip():
    sig = np.sin(np.linspace(0, 40 * np.pi, 1024)).astype(np.float32)[None]
    x = paddle.to_tensor(sig)
    spec = paddle.signal.stft(x, n_fft=128, hop_length=32)
    assert spec.shape[1] == 65  # onesided freq bins
    back = paddle.signal.istft(spec, n_fft=128, hop_length=32, length=1024)
    assert np.allclose(back.numpy(), sig, atol=1e-3)


def test_sparse_coo_roundtrip_and_matmul():
    dense = np.zeros((4, 5), np.float32)
    dense[0, 1] = 2.0
    dense[2, 3] = -1.5
    idx = np.array([[0, 2], [1, 3]])
    vals = np.array([2.0, -1.5], np.float32)
    sp = paddle.sparse.sparse_coo_tensor(idx, vals, [4, 5])
    assert np.allclose(sp.to_dense().numpy(), dense)
    y = np.random.rand(5, 3).astype(np.float32)
    out = paddle.sparse.matmul(sp, paddle.to_tensor(y))
    assert np.allclose(out.numpy(), dense @ y, atol=1e-5)


def test_sparse_csr():
    crows = np.array([0, 1, 1, 3])
    cols = np.array([2, 0, 1])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    sp = paddle.sparse.sparse_csr_tensor(crows, cols, vals, [3, 4])
    dense = sp.to_dense().numpy()
    assert dense[0, 2] == 1.0 and dense[2, 0] == 2.0 and dense[2, 1] == 3.0


def test_geometric_send_u_recv():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    src = paddle.to_tensor(np.array([0, 1, 2, 3]))
    dst = paddle.to_tensor(np.array([1, 1, 0, 0]))
    out = paddle.geometric.send_u_recv(x, src, dst, "sum")
    assert np.allclose(out.numpy()[1], x.numpy()[0] + x.numpy()[1])
    assert np.allclose(out.numpy()[0], x.numpy()[2] + x.numpy()[3])
    # gradient flows
    x.stop_gradient = False
    paddle.geometric.send_u_recv(x, src, dst, "sum").sum().backward()
    assert x.grad is not None


def test_geometric_segment_ops():
    data = paddle.to_tensor(np.array([[1.0], [2.0], [3.0], [4.0]], np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
    assert np.allclose(paddle.geometric.segment_sum(data, ids).numpy().ravel(), [3, 7])
    assert np.allclose(paddle.geometric.segment_mean(data, ids).numpy().ravel(), [1.5, 3.5])
    assert np.allclose(paddle.geometric.segment_max(data, ids).numpy().ravel(), [2, 4])


def test_incubate_fused_layers():
    from paddle_tpu.incubate.nn import FusedFeedForward, FusedMultiHeadAttention, FusedMultiTransformer

    x = paddle.randn([2, 6, 16])
    attn = FusedMultiHeadAttention(16, 4, dropout_rate=0.0, attn_dropout_rate=0.0)
    assert attn(x).shape == [2, 6, 16]
    ffn = FusedFeedForward(16, 32, dropout_rate=0.0)
    assert ffn(x).shape == [2, 6, 16]
    stack = FusedMultiTransformer(16, 4, 32, num_layers=2)
    assert stack(x).shape == [2, 6, 16]


def test_incubate_softmax_mask_fuse():
    from paddle_tpu.incubate import softmax_mask_fuse_upper_triangle

    x = paddle.randn([1, 2, 4, 4])
    out = softmax_mask_fuse_upper_triangle(x)
    o = out.numpy()
    assert np.allclose(np.triu(o[0, 0], 1), 0, atol=1e-6)  # causal zeros
    assert np.allclose(o.sum(-1), 1, atol=1e-5)


def test_incubate_lookahead():
    from paddle_tpu.incubate.optimizer import LookAhead

    w = paddle.Parameter(np.array([4.0], np.float32))
    inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    la = LookAhead(inner, alpha=0.5, k=2)
    for _ in range(4):
        (w * w).sum().backward()
        la.step()
        la.clear_grad()
    assert abs(w.numpy()[0]) < 4.0


def test_quantization_qat():
    from paddle_tpu.quantization import QAT, QuantConfig

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    q = QAT(QuantConfig())
    qnet = q.quantize(net)
    x = paddle.randn([2, 8])
    out = qnet(x)
    assert out.shape == [2, 4]
    out.sum().backward()  # straight-through grads reach the fp weights
    from paddle_tpu.quantization import QuantedLinear

    ql = qnet._sub_layers["0"]
    assert isinstance(ql, QuantedLinear)
    assert ql.inner.weight.grad is not None


def test_ptq_convert_emits_int8_model():
    """PTQ calibrate -> convert must emit a real int8 model whose outputs
    track the fp model (reference post_training_quantization.py)."""
    import jax.numpy as jnp

    from paddle_tpu.quantization import Int8Linear, PTQ, QuantConfig

    paddle.seed(3)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    xs = [paddle.randn([4, 8]) for _ in range(8)]
    ref = [net(x).numpy() for x in xs]

    ptq = PTQ(QuantConfig())
    qnet = ptq.quantize(net)
    for x in xs:  # calibration pass
        qnet(x)
    inet = ptq.convert(qnet)

    i0 = inet._sub_layers["0"]
    assert isinstance(i0, Int8Linear)
    assert i0.q_weight.dtype == jnp.int8  # genuinely quantized storage

    for x, r in zip(xs, ref):
        out = inet(x).numpy()
        assert out.shape == r.shape
        # int8 static-activation quant keeps outputs close on tame data
        denom = np.abs(r).max() + 1e-6
        assert np.abs(out - r).max() / denom < 0.1, np.abs(out - r).max()

    # quantized weights/scales must survive a state_dict round trip
    sd = {k: paddle.to_tensor(np.asarray(v.numpy())) for k, v in inet.state_dict().items()}
    assert any("q_weight" in k for k in sd)
    ref_out = inet(xs[0]).numpy()
    i0.q_weight.set_value(np.zeros_like(np.asarray(i0.q_weight.numpy())))
    assert not np.allclose(inet(xs[0]).numpy(), ref_out)  # clobbered
    inet.set_state_dict(sd)  # restore
    assert np.allclose(inet(xs[0]).numpy(), ref_out)


def test_incubate_fused_mha_functional():
    from paddle_tpu.incubate.nn.functional import fused_multi_head_attention

    paddle.seed(0)
    b, s, e, h = 2, 8, 16, 4
    x = paddle.randn([b, s, e])
    qkv_w = paddle.randn([3, h, e // h, e]) * 0.2
    qkv_b = paddle.zeros([3, h, e // h])
    lin_w = paddle.randn([e, e]) * 0.2
    lin_b = paddle.zeros([e])
    ln_s = paddle.ones([e])
    ln_b = paddle.zeros([e])
    out = fused_multi_head_attention(
        x, qkv_w, lin_w, qkv_bias=qkv_b, linear_bias=lin_b,
        ln_scale=ln_s, ln_bias=ln_b, dropout_rate=0.0, attn_dropout_rate=0.0,
        training=False,
    )
    assert out.shape == [b, s, e]
    assert np.isfinite(out.numpy()).all()
    # post-LN output is normalized
    assert abs(out.numpy().mean()) < 0.1


def test_incubate_fused_ec_moe():
    from paddle_tpu.incubate.nn.functional import fused_ec_moe

    paddle.seed(1)
    b, s, d, f, e = 2, 4, 8, 16, 3
    x = paddle.randn([b, s, d])
    gate = paddle.randn([b, s, e])
    w0 = paddle.randn([e, d, f]) * 0.2
    b0 = paddle.zeros([e, 1, f])
    w1 = paddle.randn([e, f, d]) * 0.2
    b1 = paddle.zeros([e, 1, d])
    out = fused_ec_moe(x, gate, w0, b0, w1, b1)
    assert out.shape == [b, s, d]
    # matches the dense numpy mixture
    import jax.nn as jnn
    import jax.numpy as jnp

    hid = np.einsum("bsd,edf->ebsf", x.numpy(), w0.numpy())
    hid = np.asarray(jnn.gelu(jnp.asarray(hid)))
    eo = np.einsum("ebsf,efd->ebsd", hid, w1.numpy())
    wts = np.asarray(jnn.softmax(jnp.asarray(gate.numpy()), axis=-1))
    ref = np.einsum("ebsd,bse->bsd", eo, wts)
    assert np.allclose(out.numpy(), ref, atol=1e-5)


def test_incubate_graph_khop_sampler():
    from paddle_tpu.incubate.operators import graph_khop_sampler

    # graph: 0<-{1,2}, 1<-{2,3}, 2<-{3}, 3<-{}  (CSC: in-neighbors)
    colptr = np.array([0, 2, 4, 5, 5], np.int64)
    row = np.array([1, 2, 2, 3, 3], np.int64)
    src, dst, sample_index, reindex = graph_khop_sampler(
        row, colptr, np.array([0], np.int64), [2, 2]
    )
    nodes = sample_index.numpy()
    assert nodes[0] == 0
    assert set(nodes).issubset({0, 1, 2, 3})
    # every edge endpoint indexes into sample_index
    assert src.numpy().max() < len(nodes)
    assert dst.numpy().max() < len(nodes)
    # dst of hop-1 edges is node 0 (reindexed 0)
    assert 0 in dst.numpy()


def test_incubate_forward_grad():
    from paddle_tpu import static
    from paddle_tpu.incubate.autograd import forward_grad

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [3], "float32")
        y = (x * x).sum() * 2.0
        dy = forward_grad(y, x)
    exe = static.Executor()
    xv = np.array([1.0, 2.0, 3.0], np.float32)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[y, dy])
    assert abs(float(out[0]) - 28.0) < 1e-5
    # d/dx sum(2x^2) . ones = sum(4x) = 24, evaluated at the FED x
    assert abs(float(out[1]) - 24.0) < 1e-5


def test_inference_predictor(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    path = str(tmp_path / "lenet")
    paddle.save(net.state_dict(), path + ".pdparams")

    cfg = Config(path)
    cfg.set_model_factory(LeNet)
    cfg.set_batch_buckets([4, 8])
    pred = create_predictor(cfg)
    x = np.random.rand(3, 1, 28, 28).astype(np.float32)  # pads to bucket 4
    (out,) = pred.run([x])
    assert out.shape == (3, 10)
    ref = net(paddle.to_tensor(x)).numpy()
    assert np.allclose(out, ref, atol=1e-4)
    with pytest.raises(ValueError):
        pred.run([np.random.rand(16, 1, 28, 28).astype(np.float32)])


def test_flags():
    assert paddle.get_flags("FLAGS_use_pallas_attention")["FLAGS_use_pallas_attention"]
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
    paddle.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(ValueError):
        paddle.set_flags({"FLAGS_nonexistent": 1})


def test_audio_features():
    from paddle_tpu.audio import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram

    sig = paddle.to_tensor(np.sin(np.linspace(0, 100, 2048)).astype(np.float32)[None])
    spec = Spectrogram(n_fft=256)(sig)
    assert spec.shape[1] == 129
    mel = MelSpectrogram(sr=16000, n_fft=256, n_mels=32)(sig)
    assert mel.shape[1] == 32
    logmel = LogMelSpectrogram(sr=16000, n_fft=256, n_mels=32)(sig)
    assert np.isfinite(logmel.numpy()).all()
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=256, n_mels=32)(sig)
    assert mfcc.shape[1] == 13


def test_text_datasets():
    from paddle_tpu.text import Imdb, UCIHousing

    ds = Imdb(mode="train")
    x, y = ds[0]
    # reference contract (imdb.py __getitem__): doc id vector + [label]
    assert x.ndim == 1 and y.shape == (1,) and y[0] in (0, 1)
    h = UCIHousing(mode="train")
    x, y = h[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_distributions():
    from paddle_tpu.distribution import Categorical, Normal, kl_divergence

    n = Normal(0.0, 1.0)
    s = n.sample((1000,))
    assert abs(float(s.numpy().mean())) < 0.2
    lp = n.log_prob(paddle.to_tensor(0.0))
    assert abs(lp.item() - (-0.9189)) < 1e-3
    n2 = Normal(1.0, 2.0)
    kl = kl_divergence(n, n2)
    assert kl.item() > 0
    c = Categorical(paddle.to_tensor(np.array([1.0, 1.0, 1.0], np.float32)))
    assert abs(c.entropy().item() - np.log(3)) < 1e-5


def test_onnx_export_writes_onnx_and_stablehlo(tmp_path):
    """export now emits a REAL .onnx ModelProto plus the StableHLO artifact
    XLA serving consumes (full round-trip coverage in test_onnx_export.py)."""
    net = nn.Linear(4, 2)
    from paddle_tpu.static import InputSpec

    out = paddle.onnx.export(net, str(tmp_path / "m"), input_spec=[InputSpec([1, 4])])
    import os

    assert out.endswith(".onnx") and os.path.getsize(out) > 0
    mlir = out + ".stablehlo.mlir"
    assert os.path.exists(mlir)
    text = open(mlir).read()
    assert "stablehlo" in text or "func" in text


def test_deform_conv2d():
    """DCN v1/v2 (reference vision/ops.py deform_conv2d): zero offsets ==
    plain conv, integer offsets == shifted sampling, mask modulates,
    gradients reach x/weight/offset, groups work."""
    from paddle_tpu.ops.conv_pool import conv2d
    from paddle_tpu.vision.ops import deform_conv2d

    rs = np.random.RandomState(0)
    x = paddle.to_tensor(rs.rand(2, 4, 6, 6).astype(np.float32))
    w = paddle.to_tensor(rs.rand(3, 4, 3, 3).astype(np.float32) * 0.2)
    off0 = paddle.to_tensor(np.zeros((2, 18, 4, 4), np.float32))
    ref = conv2d(x, w)
    assert np.allclose(deform_conv2d(x, off0, w).numpy(), ref.numpy(), atol=1e-5)

    off1 = paddle.to_tensor(np.ones((2, 18, 4, 4), np.float32))
    xs = np.zeros_like(x.numpy())
    xs[:, :, :-1, :-1] = x.numpy()[:, :, 1:, 1:]
    ref1 = conv2d(paddle.to_tensor(xs), w)
    assert np.allclose(deform_conv2d(x, off1, w).numpy(), ref1.numpy(), atol=1e-5)

    m = paddle.to_tensor(np.full((2, 9, 4, 4), 0.5, np.float32))
    assert np.allclose(
        deform_conv2d(x, off0, w, mask=m).numpy(), ref.numpy() * 0.5, atol=1e-5
    )

    x.stop_gradient = False
    w.stop_gradient = False
    off_t = paddle.to_tensor(np.full((2, 18, 4, 4), 0.3, np.float32))
    off_t.stop_gradient = False
    deform_conv2d(x, off_t, w).sum().backward()
    assert x.grad is not None and w.grad is not None
    assert np.abs(off_t.grad.numpy()).max() > 0  # offsets are trainable

    xg = paddle.to_tensor(rs.rand(1, 4, 5, 5).astype(np.float32))
    wg = paddle.to_tensor(rs.rand(4, 2, 3, 3).astype(np.float32))
    og = paddle.to_tensor(np.zeros((1, 18, 3, 3), np.float32))
    assert np.allclose(
        deform_conv2d(xg, og, wg, groups=2).numpy(),
        conv2d(xg, wg, groups=2).numpy(), atol=1e-5,
    )


def test_deform_conv2d_layer_registration():
    """DeformConv2D is a real Layer: params visible to parents, distinct
    initialization per instance."""
    from paddle_tpu.vision.ops import DeformConv2D

    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.dc = DeformConv2D(2, 3, 3)

        def forward(self, x, off):
            return self.dc(x, off)

    net = Net()
    names = [k for k, _ in net.named_parameters()]
    assert any("dc" in n and "weight" in n for n in names), names
    assert any("dc" in k for k in net.state_dict())
    d1, d2 = DeformConv2D(2, 3, 3), DeformConv2D(2, 3, 3)
    assert not np.allclose(d1.weight.numpy(), d2.weight.numpy())
    x = paddle.to_tensor(np.random.rand(1, 2, 5, 5).astype(np.float32))
    off = paddle.to_tensor(np.zeros((1, 18, 3, 3), np.float32))
    assert net(x, off).shape == [1, 3, 3, 3]


def test_incubate_asp_2_4_sparsity():
    """ASP: prune to 2:4, train with the decorated optimizer, pattern holds
    (reference incubate/asp prune_model + OptimizerWithSparsity)."""
    from paddle_tpu.incubate import asp

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    masks = asp.prune_model(net, n=2, m=4)
    assert masks  # pruned something
    for lin in (net[0], net[2]):
        w = lin.weight.numpy()
        groups = np.asarray(w).reshape(-1, 4)
        assert ((groups != 0).sum(axis=1) <= 2).all()  # 2:4 pattern
    assert abs(asp.calculate_density(net[0].weight) - 0.5) < 0.1

    opt = asp.decorate(paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()))
    x = paddle.to_tensor(np.random.rand(4, 8).astype(np.float32))
    y = paddle.to_tensor(np.random.rand(4, 4).astype(np.float32))
    for _ in range(3):
        loss = nn.MSELoss()(net(x), y)
        loss.backward()
        opt.step()
        opt.clear_grad()
    for lin in (net[0], net[2]):
        groups = np.asarray(lin.weight.numpy()).reshape(-1, 4)
        assert ((groups != 0).sum(axis=1) <= 2).all()  # masks re-applied


def test_yolo_box_decode():
    """yolo_box decodes grid+anchor offsets into image-space boxes/scores
    (reference vision/ops.py yolo_box, yolo_box_kernel)."""
    from paddle_tpu.vision.ops import yolo_box

    rs = np.random.RandomState(0)
    N, an, cls, H, W = 2, 3, 4, 5, 5
    anchors = [10, 13, 16, 30, 33, 23]
    x = rs.randn(N, an * (5 + cls), H, W).astype(np.float32)
    img = np.array([[320, 320], [416, 416]], np.int32)
    b, s = yolo_box(paddle.to_tensor(x), paddle.to_tensor(img), anchors, cls,
                    conf_thresh=0.01, downsample_ratio=32)
    assert b.shape == [N, an * H * W, 4]
    assert s.shape == [N, an * H * W, cls]

    p = x.reshape(N, an, 5 + cls, H, W)
    sig = lambda v: 1 / (1 + np.exp(-v))
    a_i, gy_i, gx_i = 1, 2, 3
    cx = (sig(p[0, a_i, 0, gy_i, gx_i]) + gx_i) / W
    cy = (sig(p[0, a_i, 1, gy_i, gx_i]) + gy_i) / H
    bw = np.exp(p[0, a_i, 2, gy_i, gx_i]) * anchors[2 * a_i] / (32 * W)
    bh = np.exp(p[0, a_i, 3, gy_i, gx_i]) * anchors[2 * a_i + 1] / (32 * H)
    conf = sig(p[0, a_i, 4, gy_i, gx_i])
    ref = np.array([
        np.clip((cx - bw / 2) * 320, 0, 319), np.clip((cy - bh / 2) * 320, 0, 319),
        np.clip((cx + bw / 2) * 320, 0, 319), np.clip((cy + bh / 2) * 320, 0, 319),
    ]) * (conf >= 0.01)
    idx = a_i * H * W + gy_i * W + gx_i
    np.testing.assert_allclose(b.numpy()[0, idx], ref, atol=1e-3)
    np.testing.assert_allclose(
        s.numpy()[0, idx], sig(p[0, a_i, 5:, gy_i, gx_i]) * conf * (conf >= 0.01),
        atol=1e-5,
    )
    # boxes clipped into the image
    assert (b.numpy()[0] <= 319.0 + 1e-3).all() and (b.numpy() >= 0).all()


def test_audio_datasets():
    """TESS/ESC50 dataset interfaces (reference audio/datasets): raw and
    feature-extracted items, label structure."""
    from paddle_tpu.audio.datasets import ESC50, TESS

    ds = TESS(mode="train")
    wave, label = ds[0]
    assert wave.ndim == 1 and wave.dtype == np.float32
    assert 0 <= int(label) < 7
    assert len(TESS(mode="train")) + len(TESS(mode="dev")) == TESS.N

    mel = TESS(mode="train", feat_type="mfcc", n_mfcc=13)
    feat, _ = mel[0]
    assert feat.ndim == 2 and feat.shape[0] == 13

    e = ESC50(mode="train")
    _, lab = e[1]
    assert 0 <= int(lab) < 50
    assert len(e.label_list) == 50


def test_sparse_add_multiply_stay_sparse():
    """COO+COO and COO*dense keep sparse storage (reference sparse kernels;
    previously these densified)."""
    idx1 = np.array([[0, 2], [1, 3]])
    idx2 = np.array([[0, 1], [1, 0]])
    a = paddle.sparse.sparse_coo_tensor(idx1, np.array([2.0, 3.0], np.float32), [4, 5])
    b = paddle.sparse.sparse_coo_tensor(idx2, np.array([10.0, 5.0], np.float32), [4, 5])
    c = paddle.sparse.add(a, b)
    assert isinstance(c, paddle.sparse.SparseCooTensor)
    dense = c.to_dense().numpy()
    ref = a.to_dense().numpy() + b.to_dense().numpy()
    np.testing.assert_allclose(dense, ref)
    assert c.nnz() == 3  # (0,1) merged

    d = paddle.sparse.subtract(a, b)
    assert isinstance(d, paddle.sparse.SparseCooTensor)
    np.testing.assert_allclose(
        d.to_dense().numpy(), a.to_dense().numpy() - b.to_dense().numpy()
    )

    m = paddle.sparse.multiply(a, 2.5)
    assert isinstance(m, paddle.sparse.SparseCooTensor)
    np.testing.assert_allclose(m.to_dense().numpy(), a.to_dense().numpy() * 2.5)

    y = paddle.to_tensor(np.arange(20, dtype=np.float32).reshape(4, 5))
    mz = paddle.sparse.multiply(a, y)
    assert isinstance(mz, paddle.sparse.SparseCooTensor)
    assert mz.nnz() == 2  # sparsity preserved, no densification
    np.testing.assert_allclose(
        mz.to_dense().numpy(), a.to_dense().numpy() * y.numpy()
    )


@pytest.mark.slow  # tier-1 headroom (PR 19): heaviest always-on case; tier-2 covers it
def test_resnet_nhwc_matches_nchw():
    """data_format="NHWC" (the TPU-optimal channels-minor layout) must be
    numerically identical to NCHW with the same weights, in eval AND train
    (BatchNorm batch-stats) modes."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu.vision.models import resnet18

    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 64, 64).astype(np.float32)
    paddle.seed(0)
    m1 = resnet18(num_classes=7)
    paddle.seed(0)
    m2 = resnet18(num_classes=7, data_format="NHWC")
    xt = paddle.to_tensor(x)
    xt_last = paddle.to_tensor(np.transpose(x, (0, 2, 3, 1)))
    for mode in ("eval", "train"):
        getattr(m1, mode)()
        getattr(m2, mode)()
        o1 = m1(xt).numpy()
        o2 = m2(xt_last).numpy()
        np.testing.assert_allclose(o1, o2, atol=2e-4, err_msg=mode)


def test_quant_calibration_under_jit():
    """Observer state is a buffer: calibration compiles (r3 verdict weak #6)
    and the absmax survives through functional_call's buffer threading."""
    import jax
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.quantization import QAT

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    qnet = QAT().quantize(net)
    params, buffers = state_dict_arrays(qnet)
    assert any("act_absmax" in k for k in buffers), buffers.keys()

    @jax.jit
    def calibrate(params, buffers, x):
        out, new_buf = functional_call(qnet, params, buffers, args=(x,), training=False)
        return out, new_buf

    rs = np.random.RandomState(0)
    x = rs.rand(4, 8).astype(np.float32) * 3.0
    out, buffers = calibrate(params, buffers, x)
    am = [np.asarray(v) for k, v in buffers.items() if "act_absmax" in k]
    assert all(a > 0 for a in am), am
    # absmax is monotone over batches
    x2 = rs.rand(4, 8).astype(np.float32) * 10.0
    _, buffers2 = calibrate(params, buffers, x2)
    am2 = [np.asarray(v) for k, v in buffers2.items() if "act_absmax" in k]
    assert am2[0] >= am[0]
