"""Domain API tests: fft, signal, sparse, geometric, incubate, quantization,
inference, flags, audio, text, distributions."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_fft_roundtrip():
    x = paddle.randn([4, 16])
    spec = paddle.fft.fft(x)
    back = paddle.fft.ifft(spec)
    assert np.allclose(back.numpy().real, x.numpy(), atol=1e-5)
    r = paddle.fft.rfft(x)
    assert r.shape == [4, 9]
    xb = paddle.fft.irfft(r, n=16)
    assert np.allclose(xb.numpy(), x.numpy(), atol=1e-5)


def test_fft_matches_numpy():
    a = np.random.rand(8, 8).astype(np.float32)
    out = paddle.fft.fft2(paddle.to_tensor(a))
    assert np.allclose(out.numpy(), np.fft.fft2(a), atol=1e-4)


def test_stft_istft_roundtrip():
    sig = np.sin(np.linspace(0, 40 * np.pi, 1024)).astype(np.float32)[None]
    x = paddle.to_tensor(sig)
    spec = paddle.signal.stft(x, n_fft=128, hop_length=32)
    assert spec.shape[1] == 65  # onesided freq bins
    back = paddle.signal.istft(spec, n_fft=128, hop_length=32, length=1024)
    assert np.allclose(back.numpy(), sig, atol=1e-3)


def test_sparse_coo_roundtrip_and_matmul():
    dense = np.zeros((4, 5), np.float32)
    dense[0, 1] = 2.0
    dense[2, 3] = -1.5
    idx = np.array([[0, 2], [1, 3]])
    vals = np.array([2.0, -1.5], np.float32)
    sp = paddle.sparse.sparse_coo_tensor(idx, vals, [4, 5])
    assert np.allclose(sp.to_dense().numpy(), dense)
    y = np.random.rand(5, 3).astype(np.float32)
    out = paddle.sparse.matmul(sp, paddle.to_tensor(y))
    assert np.allclose(out.numpy(), dense @ y, atol=1e-5)


def test_sparse_csr():
    crows = np.array([0, 1, 1, 3])
    cols = np.array([2, 0, 1])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    sp = paddle.sparse.sparse_csr_tensor(crows, cols, vals, [3, 4])
    dense = sp.to_dense().numpy()
    assert dense[0, 2] == 1.0 and dense[2, 0] == 2.0 and dense[2, 1] == 3.0


def test_geometric_send_u_recv():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(4, 3))
    src = paddle.to_tensor(np.array([0, 1, 2, 3]))
    dst = paddle.to_tensor(np.array([1, 1, 0, 0]))
    out = paddle.geometric.send_u_recv(x, src, dst, "sum")
    assert np.allclose(out.numpy()[1], x.numpy()[0] + x.numpy()[1])
    assert np.allclose(out.numpy()[0], x.numpy()[2] + x.numpy()[3])
    # gradient flows
    x.stop_gradient = False
    paddle.geometric.send_u_recv(x, src, dst, "sum").sum().backward()
    assert x.grad is not None


def test_geometric_segment_ops():
    data = paddle.to_tensor(np.array([[1.0], [2.0], [3.0], [4.0]], np.float32))
    ids = paddle.to_tensor(np.array([0, 0, 1, 1]))
    assert np.allclose(paddle.geometric.segment_sum(data, ids).numpy().ravel(), [3, 7])
    assert np.allclose(paddle.geometric.segment_mean(data, ids).numpy().ravel(), [1.5, 3.5])
    assert np.allclose(paddle.geometric.segment_max(data, ids).numpy().ravel(), [2, 4])


def test_incubate_fused_layers():
    from paddle_tpu.incubate.nn import FusedFeedForward, FusedMultiHeadAttention, FusedMultiTransformer

    x = paddle.randn([2, 6, 16])
    attn = FusedMultiHeadAttention(16, 4, dropout_rate=0.0, attn_dropout_rate=0.0)
    assert attn(x).shape == [2, 6, 16]
    ffn = FusedFeedForward(16, 32, dropout_rate=0.0)
    assert ffn(x).shape == [2, 6, 16]
    stack = FusedMultiTransformer(16, 4, 32, num_layers=2)
    assert stack(x).shape == [2, 6, 16]


def test_incubate_softmax_mask_fuse():
    from paddle_tpu.incubate import softmax_mask_fuse_upper_triangle

    x = paddle.randn([1, 2, 4, 4])
    out = softmax_mask_fuse_upper_triangle(x)
    o = out.numpy()
    assert np.allclose(np.triu(o[0, 0], 1), 0, atol=1e-6)  # causal zeros
    assert np.allclose(o.sum(-1), 1, atol=1e-5)


def test_incubate_lookahead():
    from paddle_tpu.incubate.optimizer import LookAhead

    w = paddle.Parameter(np.array([4.0], np.float32))
    inner = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    la = LookAhead(inner, alpha=0.5, k=2)
    for _ in range(4):
        (w * w).sum().backward()
        la.step()
        la.clear_grad()
    assert abs(w.numpy()[0]) < 4.0


def test_quantization_qat():
    from paddle_tpu.quantization import QAT, QuantConfig

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    q = QAT(QuantConfig())
    qnet = q.quantize(net)
    x = paddle.randn([2, 8])
    out = qnet(x)
    assert out.shape == [2, 4]
    out.sum().backward()  # straight-through grads reach the fp weights
    from paddle_tpu.quantization import QuantedLinear

    ql = qnet._sub_layers["0"]
    assert isinstance(ql, QuantedLinear)
    assert ql.inner.weight.grad is not None


def test_inference_predictor(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.vision.models import LeNet

    paddle.seed(0)
    net = LeNet()
    path = str(tmp_path / "lenet")
    paddle.save(net.state_dict(), path + ".pdparams")

    cfg = Config(path)
    cfg.set_model_factory(LeNet)
    cfg.set_batch_buckets([4, 8])
    pred = create_predictor(cfg)
    x = np.random.rand(3, 1, 28, 28).astype(np.float32)  # pads to bucket 4
    (out,) = pred.run([x])
    assert out.shape == (3, 10)
    ref = net(paddle.to_tensor(x)).numpy()
    assert np.allclose(out, ref, atol=1e-4)
    with pytest.raises(ValueError):
        pred.run([np.random.rand(16, 1, 28, 28).astype(np.float32)])


def test_flags():
    assert paddle.get_flags("FLAGS_use_pallas_attention")["FLAGS_use_pallas_attention"]
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    assert paddle.get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"]
    paddle.set_flags({"FLAGS_check_nan_inf": False})
    with pytest.raises(ValueError):
        paddle.set_flags({"FLAGS_nonexistent": 1})


def test_audio_features():
    from paddle_tpu.audio import LogMelSpectrogram, MFCC, MelSpectrogram, Spectrogram

    sig = paddle.to_tensor(np.sin(np.linspace(0, 100, 2048)).astype(np.float32)[None])
    spec = Spectrogram(n_fft=256)(sig)
    assert spec.shape[1] == 129
    mel = MelSpectrogram(sr=16000, n_fft=256, n_mels=32)(sig)
    assert mel.shape[1] == 32
    logmel = LogMelSpectrogram(sr=16000, n_fft=256, n_mels=32)(sig)
    assert np.isfinite(logmel.numpy()).all()
    mfcc = MFCC(sr=16000, n_mfcc=13, n_fft=256, n_mels=32)(sig)
    assert mfcc.shape[1] == 13


def test_text_datasets():
    from paddle_tpu.text import Imdb, UCIHousing

    ds = Imdb(mode="train")
    x, y = ds[0]
    assert x.shape == (64,) and y in (0, 1)
    h = UCIHousing(mode="train")
    x, y = h[0]
    assert x.shape == (13,) and y.shape == (1,)


def test_distributions():
    from paddle_tpu.distribution import Categorical, Normal, kl_divergence

    n = Normal(0.0, 1.0)
    s = n.sample((1000,))
    assert abs(float(s.numpy().mean())) < 0.2
    lp = n.log_prob(paddle.to_tensor(0.0))
    assert abs(lp.item() - (-0.9189)) < 1e-3
    n2 = Normal(1.0, 2.0)
    kl = kl_divergence(n, n2)
    assert kl.item() > 0
    c = Categorical(paddle.to_tensor(np.array([1.0, 1.0, 1.0], np.float32)))
    assert abs(c.entropy().item() - np.log(3)) < 1e-5


def test_onnx_export_writes_stablehlo(tmp_path):
    net = nn.Linear(4, 2)
    from paddle_tpu.static import InputSpec

    out = paddle.onnx.export(net, str(tmp_path / "m"), input_spec=[InputSpec([1, 4])])
    import os

    assert os.path.exists(out)
    assert "stablehlo" in open(out).read() or "func" in open(out).read()
