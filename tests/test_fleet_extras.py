"""Fleet extras: TreeIndex (index dataset), LocalFS/HDFSClient, and their
reference query contracts.

Reference: distributed/fleet/dataset/index_dataset.py (TreeIndex),
fleet/utils/fs.py (LocalFS:113, HDFSClient).
"""
import os

import numpy as np
import pytest

import paddle_tpu as paddle


class TestTreeIndex:
    def _tree(self):
        from paddle_tpu.distributed.fleet.index_dataset import TreeIndex

        return TreeIndex("t", branch=2, items=list(range(100, 108)))  # 8 leaves

    def test_shape_queries(self):
        t = self._tree()
        assert t.branch() == 2
        assert t.height() == 4          # 8 leaves -> 4 levels (1,2,4,8)
        assert t.total_node_nums() == 15
        assert len(t.get_all_leafs()) == 8
        assert t.get_layer_codes(0) == [0]
        assert t.get_layer_codes(3) == list(range(7, 15))

    def test_travel_and_ancestors(self):
        t = self._tree()
        travel = t.get_travel_codes(100)  # first leaf -> root
        assert travel[0] == 7 and travel[-1] == 0
        assert len(travel) == 4
        # parent arithmetic consistency
        for child, parent in zip(travel[:-1], travel[1:]):
            assert (child - 1) // 2 == parent
        anc = t.get_ancestor_codes([100, 107], 1)
        assert anc[0] == 1 and anc[1] == 2  # opposite subtrees
        rel = t.get_pi_relation([100], 2)
        assert rel[100] == 3

    def test_nodes_roundtrip_and_save(self, tmp_path):
        from paddle_tpu.distributed.fleet.index_dataset import TreeIndex

        t = self._tree()
        leafs = t.get_all_leafs()
        assert t.get_nodes(leafs) == list(range(100, 108))
        p = str(tmp_path / "tree.npz")
        t.save(p)
        t2 = TreeIndex("t2", path=p)
        assert t2.get_all_leafs() == leafs

    def test_layerwise_sample(self):
        paddle.seed(0)
        t = self._tree()
        t.init_layerwise_sampler([2, 2], start_sample_layer=2)
        rows = t.layerwise_sample([[7], [9]], [100, 107])
        assert rows, "no samples"
        for row in rows:
            user, code, label = row[0], row[1], row[2]
            assert label in (0, 1)
        # each (user, layer) group has exactly one positive
        pos = [r for r in rows if r[2] == 1]
        assert len(pos) == 2 * 2  # 2 users x 2 layers


class TestLocalFS:
    def test_full_surface(self, tmp_path):
        from paddle_tpu.distributed.fleet.utils import LocalFS

        fs = LocalFS()
        d = str(tmp_path / "d")
        fs.mkdirs(d)
        assert fs.is_dir(d) and fs.is_exist(d)
        f = os.path.join(d, "a.txt")
        fs.touch(f)
        assert fs.is_file(f)
        with open(f, "w") as fh:
            fh.write("hello")
        assert fs.cat(f) == "hello"
        dirs, files = fs.ls_dir(d)
        assert files == ["a.txt"] and dirs == []
        f2 = os.path.join(d, "b.txt")
        fs.mv(f, f2)
        assert fs.is_file(f2) and not fs.is_exist(f)
        with pytest.raises(Exception):
            fs.mv(f, f2, test_exists=True)  # src gone
        fs.delete(d)
        assert not fs.is_exist(d)

    def test_hdfs_client_errors_without_hadoop(self):
        from paddle_tpu.distributed.fleet.utils import HDFSClient
        from paddle_tpu.distributed.fleet.utils.fs import ExecuteError

        c = HDFSClient(hadoop_home="/nonexistent")
        with pytest.raises(ExecuteError):
            c.mkdirs("/tmp/x")
