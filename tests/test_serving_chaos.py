"""Chaos suite: injected faults driven through the async/HTTP serving stack.

Every fault point in serving/faults.py is exercised end to end — the full
AsyncLLMEngine / ServingServer path, not the bare engine — and every test
closes on the standing invariants: each request terminates EXACTLY once
with a finish reason, pool refcounts return to zero, num_free returns to
idle capacity, and no consumer future hangs. The exactly-once check uses
the lifecycle tracer where it matters: one closing ``request`` span per
request id, whatever interleaving of faults, drains, and aborts ran.

Fast deterministic triggers run in tier-1; the randomized soak is ``slow``.
The synchronous supervisor mechanics are tests/test_serving_supervisor.py.
"""
import asyncio
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import witness as lock_witness
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    EngineClosedError,
    EngineHealth,
    EngineOverloadedError,
    LLMEngine,
    ServingServer,
    faults,
)
from paddle_tpu.serving.faults import FaultPlan


@pytest.fixture(autouse=True, scope="module")
def _lock_order_witness():
    """PADDLE_TPU_LOCK_WITNESS=1: run this whole chaos module with the
    lock-order witness installed and assert the union acquisition-order
    graph is acyclic at teardown (tests/test_lock_witness.py carries the
    always-on tier-1 variant, so the default run stays unwitnessed and
    byte-identical)."""
    if not lock_witness.enabled_from_env():
        yield None
        return
    w = lock_witness.install()
    try:
        yield w
    finally:
        lock_witness.uninstall()
    w.check_acyclic()
    gaps = lock_witness.cross_check(w)
    assert gaps == [], "\n".join(gaps)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _disarm():
    yield
    plan = faults.active()
    if plan is not None:
        plan.release_hangs()
    faults.clear()


@pytest.fixture(scope="module")
def ref_engine(model):
    """One shared no-fault engine for reference outputs — compiling a
    fresh pair of step programs per reference run is the dominant cost
    of this file (warm-vs-cold parity is PR 4's tested guarantee, so
    reuse cannot change the reference tokens)."""
    return LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _reference(ref_engine, prompts, n=6):
    return ref_engine.generate(prompts, max_new_tokens=n, temperature=0.0)


def _idle(engine):
    assert engine.pool._refcount == {}
    return engine.pool.num_free == engine.pool.num_blocks - 1


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(model, **kw)


def _assert_exactly_once(engine, rids):
    """The single-terminal-event invariant, from the lifecycle trace:
    every traced request closed with exactly ONE ``request`` span."""
    closes = [e["args"]["request_id"]
              for e in engine.tracer.chrome_trace()["traceEvents"]
              if e.get("name") == "request" and e.get("ph") == "X"]
    for rid in rids:
        assert closes.count(rid) == 1, (rid, closes)


async def _http(port, method, path, obj=None):
    """One loopback exchange; returns (status, headers, body_bytes)."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    data = json.dumps(obj).encode() if obj is not None else b""
    writer.write(
        (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
         f"Content-Type: application/json\r\n"
         f"Content-Length: {len(data)}\r\n\r\n").encode() + data
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    lines = head.decode("latin1").split("\r\n")
    headers = {}
    for line in lines[1:]:
        name, sep, value = line.partition(":")
        if sep:
            headers[name.strip().lower()] = value.strip()
    return int(lines[0].split(" ")[1]), headers, body


def _sse(body):
    """SSE body -> (tokens, finish_reason, saw_done)."""
    toks, reason, done = [], None, False
    for line in body.decode().splitlines():
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            done = True
            continue
        choice = json.loads(payload)["choices"][0]
        toks.extend(choice["token_ids"])
        if choice["finish_reason"] is not None:
            reason = choice["finish_reason"]
    return toks, reason, done


# -- poison isolation over HTTP/SSE -----------------------------------------


def test_http_poison_request_isolated_streams(model, ref_engine):
    """A step_raise pinned to one request in a mixed SSE batch: exactly
    that stream finishes with ``error`` while every other stream
    completes token-identical to a no-fault serve; the replica stays
    healthy and the pool drains to idle."""
    prompts = _prompts((5, 9, 13), seed=20)
    refs = _reference(ref_engine, prompts)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison", "exc": "DeviceBoom"},
    ]))
    engine = _engine(model)

    async def main():
        server = await ServingServer(engine, port=0, max_waiting=8).start()

        async def one(i, p):
            rid = "poison" if i == 1 else f"r{i}"
            return await _http(
                server.port, "POST", "/v1/completions",
                {"prompt": p, "max_tokens": 6, "stream": True,
                 "request_id": rid})
        results = await asyncio.gather(
            *(one(i, p) for i, p in enumerate(prompts)))
        hstatus, _, hbody = await _http(server.port, "GET", "/healthz")
        await server.shutdown(drain=True)
        return results, hstatus, json.loads(hbody)

    results, hstatus, health = asyncio.run(main())
    for i, (status, _, body) in enumerate(results):
        assert status == 200
        toks, reason, done = _sse(body)
        assert done
        if i == 1:
            assert reason == "error"
        else:
            assert reason == "length"
            assert toks == refs[i]
    # one poisoned request never unhealthies the replica
    assert hstatus == 200 and health["status"] == "ok"
    assert engine.metrics.counters["poison_requests_isolated"] == 1
    assert _idle(engine)


def test_http_nonfinite_logits_contained(model, ref_engine):
    """step_nonfinite_logits over HTTP: the pinned request's non-stream
    response is a 500 engine_error naming nonfinite_logits; a concurrent
    innocent completes normally."""
    prompts = _prompts((5, 9), seed=21)
    refs = _reference(ref_engine, prompts)
    faults.install(FaultPlan([
        {"point": "step_nonfinite_logits", "request_id": "poison",
         "times": 1},
    ]))
    engine = _engine(model)

    async def main():
        server = await ServingServer(engine, port=0, max_waiting=8).start()
        good, bad = await asyncio.gather(
            _http(server.port, "POST", "/v1/completions",
                  {"prompt": prompts[0], "max_tokens": 6,
                   "request_id": "ok"}),
            _http(server.port, "POST", "/v1/completions",
                  {"prompt": prompts[1], "max_tokens": 6,
                   "request_id": "poison"}),
        )
        await server.shutdown(drain=True)
        return good, bad

    (gs, _, gbody), (bs, _, bbody) = asyncio.run(main())
    assert gs == 200
    assert json.loads(gbody)["choices"][0]["token_ids"] == refs[0]
    assert bs == 500
    err = json.loads(bbody)["error"]
    assert err["type"] == "engine_error"
    assert "nonfinite_logits" in err["message"]
    assert _idle(engine)


# -- stuck step + watchdog ---------------------------------------------------


def test_http_stuck_step_watchdog_flips_healthz(model):
    """THE watchdog acceptance criterion: with a step_hang, /healthz goes
    503 {"reason": "step_stuck"} within watchdog_step_timeout_s + one
    poll interval (plus scheduling slack), every consumer receives a
    terminal event instead of silence, new work is rejected 503
    unhealthy, and after the hang releases the pool drains to idle."""
    prompts = _prompts((5, 7), seed=22)
    plan = faults.install(FaultPlan([
        {"point": "step_hang", "at_step": 1, "timeout_s": 60.0},
    ]))
    engine = _engine(model)
    timeout_s, poll_s = 0.2, 0.05

    async def main():
        server = await ServingServer(
            engine, port=0, max_waiting=8,
            watchdog_step_timeout_s=timeout_s,
        ).start()
        server.engine._watchdog.poll_s = poll_s  # deterministic cadence
        t0 = time.monotonic()
        stream_task = asyncio.ensure_future(_http(
            server.port, "POST", "/v1/completions",
            {"prompt": prompts[0], "max_tokens": 4, "stream": True}))
        full_task = asyncio.ensure_future(_http(
            server.port, "POST", "/v1/completions",
            {"prompt": prompts[1], "max_tokens": 4}))
        flipped_at = None
        while time.monotonic() - t0 < 10.0:
            hs, _, hb = await _http(server.port, "GET", "/healthz")
            if hs == 503:
                flipped_at = time.monotonic()
                health = json.loads(hb)
                break
            await asyncio.sleep(0.02)
        assert flipped_at is not None, "healthz never flipped"
        # both consumers must get terminal events while the step is STILL
        # hung — that is the entire point of the watchdog
        stream_res = await asyncio.wait_for(stream_task, 10.0)
        full_res = await asyncio.wait_for(full_task, 10.0)
        rs, _, rb = await _http(
            server.port, "POST", "/v1/completions",
            {"prompt": prompts[0], "max_tokens": 2})
        plan.release_hangs()
        await server.shutdown(drain=True, timeout_s=10.0)
        return (flipped_at - t0, health, stream_res, full_res, (rs, rb))

    latency, health, stream_res, full_res, rej = asyncio.run(main())
    assert health["status"] == "unhealthy"
    assert health["reason"] == "step_stuck"
    assert health["stuck_for_s"] >= timeout_s
    # detection latency: timeout + one poll interval, plus generous CI
    # scheduling slack (the bound under test is "promptly", not "30s
    # later when the LB gives up")
    assert latency < timeout_s + poll_s + 3.0
    _, sreason, sdone = _sse(stream_res[2])
    assert sdone and sreason == "error"
    assert full_res[0] == 500
    assert "step_stuck" in json.loads(full_res[2])["error"]["message"]
    rs, rb = rej
    assert rs == 503
    assert json.loads(rb)["error"]["reason"] == "unhealthy"
    assert engine.metrics.counters["watchdog_trips"] == 1
    assert engine.metrics.gauges["engine_unhealthy"] == 1.0
    assert _idle(engine)


# -- crash-safe engine-thread exit ------------------------------------------


def test_thread_die_crash_safe_exit(model):
    """An exception escaping the engine LOOP (not a step): every live
    stream gets one terminal error event, KV blocks return to the pool,
    the engine marks unhealthy, and later submits fail fast instead of
    enqueueing into a queue nobody drains."""
    prompts = _prompts((5, 9), seed=23)
    engine = _engine(model, trace=True)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        streams = [fe.submit(p, max_new_tokens=40, temperature=0.0,
                             request_id=f"r{i}")
                   for i, p in enumerate(prompts)]
        await asyncio.sleep(0.05)          # let serving begin
        faults.install(FaultPlan([{"point": "thread_die"}]))
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 10.0)
        # crash epilogue signalled _stopped: shutdown is near-instant
        await asyncio.wait_for(fe.shutdown(drain=False), 10.0)
        with pytest.raises(EngineClosedError) as ei:
            fe.submit(prompts[0], max_new_tokens=2)
        return results, ei.value

    results, closed = asyncio.run(main())
    for _, reason in results:
        assert reason == "error"
    assert not engine.metrics.counters.get("requests_finished")
    assert closed.reason == "unhealthy"
    assert engine.metrics.counters["engine_thread_deaths"] == 1
    _assert_exactly_once(engine, ["r0", "r1"])
    assert _idle(engine)


def test_dead_thread_detected_at_submit(model):
    """White-box: a dead engine thread that somehow left health clean
    (e.g. teardown ordering) is still caught AT submit — reason
    engine_dead, no silent enqueue."""
    engine = _engine(model)
    (p,) = _prompts((5,), seed=24)

    async def main():
        fe = await AsyncLLMEngine(engine).start()
        faults.install(FaultPlan([{"point": "thread_die"}]))
        await fe._stopped.wait()
        faults.clear()
        # simulate the pathological case: health/closed state lost
        fe.health = EngineHealth()
        fe._closed = False
        with pytest.raises(EngineClosedError) as ei:
            fe.submit(p, max_new_tokens=2)
        return ei.value

    err = asyncio.run(main())
    assert err.reason == "engine_dead"


# -- drain-during-fault interleavings ---------------------------------------


def test_drain_racing_poisoned_step(model, ref_engine):
    """begin_drain (stop_admitting) while the supervisor is isolating a
    poisoned request: the poison errors out exactly once, every innocent
    completes, drain finishes, pool idle."""
    prompts = _prompts((5, 9, 13), seed=25)
    refs = _reference(ref_engine, prompts)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison"},
    ]))
    engine = _engine(model, trace=True)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        streams = []
        for i, p in enumerate(prompts):
            rid = "poison" if i == 0 else f"r{i}"
            streams.append(fe.submit(p, max_new_tokens=6, temperature=0.0,
                                     request_id=rid))
        await asyncio.sleep(0.05)          # mid-recovery, with luck
        fe.stop_admitting()                # the LB drain pattern
        with pytest.raises(EngineClosedError):
            fe.submit(prompts[0], max_new_tokens=2)
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 30.0)
        await fe.shutdown(drain=True, timeout_s=10.0)
        return results

    results = asyncio.run(main())
    assert results[0][1] == "error"
    for i in (1, 2):
        toks, reason = results[i]
        assert reason == "length" and toks == refs[i]
    _assert_exactly_once(engine, ["poison", "r1", "r2"])
    assert _idle(engine)


@pytest.mark.slow
def test_abort_racing_bisection(model):
    """Client aborts (the poisoned request AND an innocent) racing the
    supervisor's bisection: every stream sees exactly one terminal
    event, nothing double-frees, pool idle."""
    prompts = _prompts((5, 9, 13, 7), seed=26)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison"},
    ]))
    engine = _engine(model, trace=True)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        streams = []
        for i, p in enumerate(prompts):
            rid = "poison" if i == 2 else f"r{i}"
            streams.append(fe.submit(p, max_new_tokens=8, temperature=0.0,
                                     request_id=rid))
        await asyncio.sleep(0.05)
        fe.abort("poison")                 # may race the isolation verdict
        fe.abort("r0")                     # innocent mid-flight abort
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 30.0)
        await fe.shutdown(drain=True, timeout_s=10.0)
        return results

    results = asyncio.run(main())
    reasons = [r for _, r in results]
    assert reasons[2] in ("error", "cancelled")    # whoever won the race
    assert reasons[0] in ("cancelled", "length")
    for i in (1, 3):
        assert reasons[i] == "length"
    _assert_exactly_once(engine, ["r0", "r1", "poison", "r3"])
    assert _idle(engine)


def test_watchdog_trip_during_drain(model):
    """A step hangs WHILE draining: the watchdog still fires, consumers
    get terminal errors (not a drain that never ends), and once the hang
    releases the drain completes with the pool idle."""
    prompts = _prompts((5, 9), seed=27)
    plan = faults.install(FaultPlan([
        {"point": "step_hang", "at_step": 2, "timeout_s": 60.0},
    ]))
    engine = _engine(model, trace=True)

    async def main():
        fe = await AsyncLLMEngine(
            engine, max_waiting=8,
            watchdog_step_timeout_s=0.2, watchdog_poll_s=0.05,
        ).start()
        streams = [fe.submit(p, max_new_tokens=6, temperature=0.0,
                             request_id=f"r{i}")
                   for i, p in enumerate(prompts)]
        fe.stop_admitting()                # drain begins immediately
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 15.0)
        assert not fe.health.healthy       # tripped during the drain
        plan.release_hangs()
        await fe.shutdown(drain=True, timeout_s=10.0)
        return results

    results = asyncio.run(main())
    for _, reason in results:
        assert reason == "error"
    assert engine.metrics.counters["watchdog_trips"] == 1
    _assert_exactly_once(engine, ["r0", "r1"])
    assert _idle(engine)


def test_emit_path_crash_loses_no_tokens_or_terminals(model, ref_engine):
    """A step that raises from inside the EMISSION loop (a tracer/log
    bug) after appending tokens — the step's StepOutputs are lost. The
    post-recovery reconciliation must still terminate the stream of a
    request that finished inside that step (with its full token list,
    via lossless catch-up) and re-sync partially-emitted streams."""
    prompts = _prompts((5, 9), seed=30)
    refs = _reference(ref_engine, prompts, n=4)
    engine = _engine(model)
    orig_emit = engine._emit
    state = {"armed": True}

    def bomb(req, token):
        out = orig_emit(req, token)
        if state["armed"] and out.finished and req.request_id == "victim":
            state["armed"] = False          # one-shot: recovery is clean
            raise RuntimeError("emit-path bug")
        return out

    engine._emit = bomb

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        streams = [
            fe.submit(prompts[0], max_new_tokens=4, temperature=0.0,
                      request_id="victim"),
            fe.submit(prompts[1], max_new_tokens=4, temperature=0.0,
                      request_id="other"),
        ]
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 30.0)
        await fe.shutdown(drain=True, timeout_s=10.0)
        return results

    results = asyncio.run(main())
    assert results[0] == (refs[0], "length")   # finished in the lost step
    assert results[1] == (refs[1], "length")   # re-synced and completed
    assert _idle(engine)


# -- admission rejections: structured bodies + Retry-After -------------------


def test_reject_bodies_distinguish_reasons(model):
    """429 queue_full and 503 draining carry Retry-After and a
    machine-readable error.reason; kv_capacity is its own 429 reason
    (frontend-level — the gate is opt-in)."""
    (p,) = _prompts((5,), seed=28)
    engine = _engine(model, max_batch=1)

    async def main():
        server = await ServingServer(engine, port=0, max_waiting=0).start()
        hold = asyncio.ensure_future(_http(
            server.port, "POST", "/v1/completions",
            {"prompt": p, "max_tokens": 48, "stream": True}))
        await asyncio.sleep(0.05)          # in flight: queue (0) is full
        full = await _http(server.port, "POST", "/v1/completions",
                           {"prompt": p, "max_tokens": 2})
        server.begin_drain()
        drain = await _http(server.port, "POST", "/v1/completions",
                            {"prompt": p, "max_tokens": 2})
        hstatus, _, _ = await _http(server.port, "GET", "/healthz")
        await hold
        await server.shutdown(drain=True, timeout_s=10.0)
        return full, drain, hstatus

    full, drain, hstatus = asyncio.run(main())
    status, headers, body = full
    assert status == 429
    assert headers.get("retry-after") == "1"
    assert json.loads(body)["error"]["reason"] == "queue_full"
    status, headers, body = drain
    assert status == 503
    assert headers.get("retry-after") == "5"
    err = json.loads(body)["error"]
    assert err["reason"] == "draining" and err["type"] == "draining"
    assert hstatus == 503
    assert _idle(engine)


@pytest.mark.slow
def test_kv_capacity_gate(model):
    """max_kv_commit_blocks: admission rejects with reason kv_capacity
    when the in-flight worst case would oversubscribe, and the
    commitment returns when requests finish."""
    prompts = _prompts((5, 5), seed=29)
    engine = _engine(model)
    need = engine.pool.blocks_for(5 + 8 - 1)

    async def main():
        fe = await AsyncLLMEngine(
            engine, max_waiting=8, max_kv_commit_blocks=need).start()
        st = fe.submit(prompts[0], max_new_tokens=8, temperature=0.0)
        with pytest.raises(EngineOverloadedError) as ei:
            fe.submit(prompts[1], max_new_tokens=8, temperature=0.0)
        assert ei.value.reason == "kv_capacity"
        await st.collect()
        st2 = fe.submit(prompts[1], max_new_tokens=8, temperature=0.0)
        toks, reason = await st2.collect()
        await fe.shutdown(drain=True)
        return reason

    assert asyncio.run(main()) == "length"
    assert _idle(engine)


# -- randomized soak ---------------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_randomized_faults(model):
    """Seeded random faults (raises, phantom alloc failures, non-finite
    rows) over a mixed wave: every stream terminates exactly once, and
    the pool drains to idle whatever interleaving ran."""
    rs = np.random.RandomState(31)
    prompts = [rs.randint(0, 128, (int(n),)).tolist()
               for n in rs.randint(3, 40, size=24)]
    faults.install(FaultPlan([
        {"point": "step_raise", "probability": 0.05, "seed": 1},
        {"point": "alloc_fail", "probability": 0.05, "seed": 2},
        {"point": "step_nonfinite_logits", "probability": 0.01, "seed": 3},
        {"point": "slow_step_ms", "probability": 0.1, "seed": 4, "ms": 2},
    ]))
    engine = _engine(model, trace=True)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=32,
                                  max_step_retries=4).start()
        streams = [fe.submit(p, max_new_tokens=int(rs.randint(1, 12)),
                             temperature=0.0, request_id=f"s{i}")
                   for i, p in enumerate(prompts)]
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 120.0)
        await fe.shutdown(drain=True, timeout_s=30.0)
        return results

    results = asyncio.run(main())
    for toks, reason in results:
        assert reason in ("length", "error")
    _assert_exactly_once(engine, [f"s{i}" for i in range(len(prompts))])
    assert _idle(engine)
