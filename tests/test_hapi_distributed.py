"""Model.fit over a fleet mesh (the BASELINE north star: hapi + Fleet
sharding; reference hapi/model.py auto fleet integration). 8-device CPU
mesh via conftest."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.mesh import init_mesh, set_mesh


@pytest.fixture
def clean_mesh():
    yield
    set_mesh(None)


def _data(n=32, din=8, dout=4, seed=0):
    rs = np.random.RandomState(seed)
    return rs.rand(n, din).astype(np.float32), rs.rand(n, dout).astype(np.float32)


def _fit(mesh_degrees, steps=4, bs=8, mp_annotate=False):
    if mesh_degrees:
        init_mesh(mesh_degrees)
    else:
        set_mesh(None)
    paddle.seed(4)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    if mp_annotate:
        net[0].weight.sharding_axes = (None, "mp")
        net[2].weight.sharding_axes = ("mp", None)
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
    model.prepare(opt, nn.MSELoss())
    xs, ys = _data(steps * bs)
    losses = []
    for i in range(steps):
        out = model.train_batch([xs[i * bs:(i + 1) * bs]], [ys[i * bs:(i + 1) * bs]])
        losses.append(out[0] if isinstance(out, list) else out)
    return [float(l[0]) if isinstance(l, list) else float(l) for l in losses], model


def test_model_fit_dp_sharding_matches_single_device(clean_mesh):
    ref, _ = _fit(None)
    dp, _ = _fit({"dp": 4, "sharding": 2})
    np.testing.assert_allclose(dp, ref, rtol=1e-4, atol=1e-6)


def test_model_fit_dp_mp_matches_single_device(clean_mesh):
    ref, _ = _fit(None, mp_annotate=False)
    mp, _ = _fit({"dp": 2, "mp": 2}, mp_annotate=True)
    np.testing.assert_allclose(mp, ref, rtol=1e-4, atol=1e-6)


def test_model_save_after_distributed_fit(clean_mesh, tmp_path):
    losses, model = _fit({"dp": 2, "sharding": 2}, steps=3)
    assert np.isfinite(losses).all()
    path = str(tmp_path / "dist_hapi" / "ck")
    model.save(path)
    sd = paddle.load(path + ".pdopt")
    assert any("moment1" in k for k in sd)  # real slots from the sharded step


def test_bert_model_fit_sharded(clean_mesh):
    """BERT-tiny via Model.fit on a dp x sharding mesh — the ERNIE-pretrain
    shape of BASELINE config 3 at test scale."""
    from paddle_tpu.models.bert import Bert, BertConfig

    init_mesh({"dp": 2, "sharding": 2, "mp": 2})
    paddle.seed(0)
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                     max_position_embeddings=32, dropout=0.0)
    net = Bert(cfg)

    class MLMLoss(nn.Layer):
        def forward(self, logits, nsp_logits, labels):
            from paddle_tpu.ops.loss_ops import cross_entropy

            return cross_entropy(
                logits.reshape([-1, cfg.vocab_size]), labels.reshape([-1])
            )

    model = paddle.Model(net)
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=net.parameters())
    model.prepare(opt, MLMLoss())
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 128, (8, 16)).astype(np.int64)
    labels = rs.randint(0, 128, (8, 16)).astype(np.int64)
    losses = [
        model.train_batch([ids], [labels])[0] for _ in range(4)
    ]
    losses = [l[0] if isinstance(l, list) else l for l in losses]
    assert losses[-1] < losses[0], losses  # training under dp+zero+mp
    assert np.isfinite(losses).all()


def test_model_fit_ragged_dataset(clean_mesh):
    """fit with a dataset whose tail batch is ragged: auto drop_last under a
    mesh; DataLoader-committed arrays are re-placed on the mesh."""
    init_mesh({"dp": 4, "sharding": 2})
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = paddle.Model(net)
    model.prepare(
        paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters()),
        nn.MSELoss(),
    )
    rs = np.random.RandomState(0)

    class DS(paddle.io.Dataset):
        def __len__(self):
            return 30  # not a multiple of batch 8

        def __getitem__(self, i):
            return rs.rand(8).astype(np.float32), rs.rand(4).astype(np.float32)

    model.fit(DS(), epochs=2, batch_size=8, verbose=0)  # must not raise

    # direct train_batch with an indivisible batch raises a CLEAR error
    import pytest as _pytest

    with _pytest.raises(ValueError, match="divisible"):
        model.train_batch([rs.rand(6, 8).astype(np.float32)],
                          [rs.rand(6, 4).astype(np.float32)])


def test_evaluate_sees_all_samples_under_mesh(clean_mesh):
    """eval/predict are unsharded: a ragged tail must NOT be dropped."""
    init_mesh({"dp": 4})
    paddle.seed(0)
    net = nn.Linear(8, 4)
    model = paddle.Model(net)
    model.prepare(None)
    rs = np.random.RandomState(0)

    class DS(paddle.io.Dataset):
        def __len__(self):
            return 10  # ragged vs batch 4

        def __getitem__(self, i):
            return (rs.rand(8).astype(np.float32),)

    outs = model.predict(DS(), batch_size=4, stack_outputs=True, verbose=0)
    assert outs[0].shape[0] == 10  # every sample predicted
