"""Static mode is no longer frozen (VERDICT r4 item 4 / Weak #4).

Two capture-time freezes are gone:
- RNG ops (dropout) captured into a Program are RNG *slots*: Executor.run
  and the hapi StaticGraphAdapter substitute a fresh per-step key, so masks
  vary across steps (reference: random ops re-execute per Executor.run).
- Buffer mutations (BN running stats) are recorded as state writes: the
  executor fetches the new values each run and writes them back, so
  `enable_static()` training updates BN statistics like the reference's
  in-program state ops (fluid/executor.py:1394 runs the full main program).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static


def _fresh_program():
    return static.Program()


def test_executor_dropout_varies_per_run():
    prog = _fresh_program()
    paddle.seed(7)
    with static.program_guard(prog):
        x = static.data("x", [32, 64], "float32")
        y = nn.functional.dropout(x, p=0.5, training=True)
    exe = static.Executor()
    feed = {"x": np.ones((32, 64), np.float32)}
    a = exe.run(prog, feed=feed, fetch_list=[y])[0]
    b = exe.run(prog, feed=feed, fetch_list=[y])[0]
    # masks actually drop ~half, and DIFFER between runs
    assert 0.3 < (a == 0).mean() < 0.7
    assert not np.array_equal(a, b)


def test_executor_dropout_seeded_reproducibility():
    def run_twice(seed):
        prog = _fresh_program()
        paddle.seed(seed)
        with static.program_guard(prog):
            x = static.data("x", [16, 32], "float32")
            y = nn.functional.dropout(x, p=0.5, training=True)
        exe = static.Executor()
        feed = {"x": np.ones((16, 32), np.float32)}
        return [exe.run(prog, feed=feed, fetch_list=[y])[0] for _ in range(2)]

    r1 = run_twice(3)
    r2 = run_twice(3)
    for a, b in zip(r1, r2):
        np.testing.assert_array_equal(a, b)


def test_executor_bn_stats_update_per_run():
    prog = _fresh_program()
    paddle.seed(0)
    bn = nn.BatchNorm1D(8)
    bn.train()
    rs = np.random.RandomState(0)
    with static.program_guard(prog):
        x = static.data("x", [16, 8], "float32")
        y = bn(x)
    exe = static.Executor()

    mean0 = np.asarray(bn._mean._array).copy()
    x1 = rs.rand(16, 8).astype(np.float32) + 2.0
    exe.run(prog, feed={"x": x1}, fetch_list=[y])
    mean1 = np.asarray(bn._mean._array).copy()
    # EMA moved toward the batch mean (momentum 0.9)
    expected1 = 0.9 * mean0 + 0.1 * x1.mean(0)
    np.testing.assert_allclose(mean1, expected1, rtol=1e-5)

    x2 = rs.rand(16, 8).astype(np.float32) - 1.0
    exe.run(prog, feed={"x": x2}, fetch_list=[y])
    mean2 = np.asarray(bn._mean._array).copy()
    expected2 = 0.9 * mean1 + 0.1 * x2.mean(0)
    np.testing.assert_allclose(mean2, expected2, rtol=1e-5)
    # variance buffer moves too (unbiased batch var)
    assert not np.allclose(np.asarray(bn._variance._array), 1.0)


class DropBNNet(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 32)
        self.bn = nn.BatchNorm1D(32)
        self.drop = nn.Dropout(0.5)
        self.fc2 = nn.Linear(32, 4)

    def forward(self, x):
        return self.fc2(self.drop(self.bn(self.fc1(x))))


def _fit_losses(static_mode, steps=6):
    rs = np.random.RandomState(0)
    X = rs.rand(steps * 16, 16).astype(np.float32)
    Y = rs.randint(0, 4, (steps * 16, 1))
    paddle.seed(11)
    net = DropBNNet()
    model = paddle.Model(net)
    if static_mode:
        paddle.enable_static()
    try:
        model.prepare(
            paddle.optimizer.SGD(learning_rate=0.1, parameters=net.parameters()),
            nn.CrossEntropyLoss(),
        )
        losses = []
        for i in range(steps):
            out = model.train_batch(
                [paddle.to_tensor(X[i * 16 : (i + 1) * 16])],
                [paddle.to_tensor(Y[i * 16 : (i + 1) * 16])],
            )
            loss = out[0] if not isinstance(out, tuple) else out[0][0]
            losses.append(float(np.asarray(loss)))
    finally:
        if static_mode:
            paddle.disable_static()
    return losses, np.asarray(net.bn._mean._array).copy()


def test_hapi_static_dropout_and_bn_match_dynamic():
    """With dropout AND BatchNorm in the model, the static adapter's loss
    trajectory and final BN running stats match dynamic mode: the per-step
    keys and the buffer updates are the same computation."""
    dyn_losses, dyn_mean = _fit_losses(static_mode=False)
    st_losses, st_mean = _fit_losses(static_mode=True)
    np.testing.assert_allclose(st_losses, dyn_losses, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(st_mean, dyn_mean, rtol=1e-4)
    # and the BN stats actually moved off their init (mean starts at 0)
    assert np.abs(st_mean).max() > 1e-3


def test_hapi_static_dropout_masks_vary():
    """Identical consecutive batches yield different losses (masks differ)."""
    rs = np.random.RandomState(1)
    X = rs.rand(16, 16).astype(np.float32)
    Y = rs.randint(0, 4, (16, 1))
    paddle.seed(5)
    net = DropBNNet()
    model = paddle.Model(net)
    paddle.enable_static()
    try:
        # lr=0 isolates the dropout mask as the ONLY source of variation
        model.prepare(
            paddle.optimizer.SGD(learning_rate=0.0, parameters=net.parameters()),
            nn.CrossEntropyLoss(),
        )
        l1 = model.train_batch([paddle.to_tensor(X)], [paddle.to_tensor(Y)])
        l2 = model.train_batch([paddle.to_tensor(X)], [paddle.to_tensor(Y)])
    finally:
        paddle.disable_static()
    v1 = l1[0] if not isinstance(l1, tuple) else l1[0][0]
    v2 = l2[0] if not isinstance(l2, tuple) else l2[0][0]
    assert v1 != v2, (v1, v2)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
