"""Native C++ component tests: TCPStore + data feed (csrc/)."""
import threading

import numpy as np
import pytest


def test_cpp_extension_builds():
    from paddle_tpu.utils.cpp_extension import load_native

    lib = load_native()
    assert lib is not None


def test_tcp_store_set_get_add():
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore(is_master=True)
    client = TCPStore(host="127.0.0.1", port=master.port)
    client.set("hello", b"world")
    assert master.get("hello") == b"world"
    assert master.add("counter", 5) == 5
    assert client.add("counter", 2) == 7
    assert client.check("hello")
    assert not client.check("missing")
    assert client.delete_key("hello")
    assert not client.check("hello")


def test_tcp_store_blocking_get():
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore(is_master=True)
    result = {}

    def waiter():
        c = TCPStore(port=master.port)
        result["v"] = c.get("late_key")  # blocks until set

    t = threading.Thread(target=waiter)
    t.start()
    import time

    time.sleep(0.2)
    assert "v" not in result  # still blocked
    master.set("late_key", b"arrived")
    t.join(timeout=5)
    assert result.get("v") == b"arrived"


def test_tcp_store_barrier():
    from paddle_tpu.distributed.store import TCPStore

    master = TCPStore(is_master=True)
    clients = [TCPStore(port=master.port) for _ in range(3)]
    done = []

    def member(i):
        clients[i].barrier("b0", 3, i)
        done.append(i)

    threads = [threading.Thread(target=member, args=(i,)) for i in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5)
    assert sorted(done) == [0, 1, 2]


def test_native_shuffle_is_permutation():
    from paddle_tpu.io.native_feed import shuffle_indices

    idx = shuffle_indices(1000, seed=42)
    assert sorted(idx.tolist()) == list(range(1000))
    idx2 = shuffle_indices(1000, seed=42)
    assert np.array_equal(idx, idx2)  # deterministic
    idx3 = shuffle_indices(1000, seed=43)
    assert not np.array_equal(idx, idx3)


def test_native_gather_collate():
    from paddle_tpu.io.native_feed import gather_collate

    base = np.random.rand(100, 3, 8, 8).astype(np.float32)
    sel = np.array([5, 17, 3, 99], np.int64)
    out = gather_collate(base, sel)
    assert np.array_equal(out, base[sel])


def test_native_queue_roundtrip():
    from paddle_tpu.io.native_feed import NativeBatchQueue

    q = NativeBatchQueue(capacity=4)
    a = np.random.rand(4, 4).astype(np.float32)
    assert q.push(a)
    out = q.pop((4, 4), np.float32)
    assert np.array_equal(out, a)
    q.close()
    assert q.pop((4, 4), np.float32) is None  # closed + drained


def test_array_data_feed():
    from paddle_tpu.io.native_feed import ArrayDataFeed

    x = np.random.rand(64, 4).astype(np.float32)
    y = np.arange(64, dtype=np.int64)
    feed = ArrayDataFeed([x, y], batch_size=16, shuffle=True, seed=1)
    batches = list(feed)
    assert len(batches) == 4
    all_labels = np.concatenate([b[1] for b in batches])
    assert sorted(all_labels.tolist()) == list(range(64))
    # pairs stay aligned through the shuffle
    for bx, by in batches:
        assert np.allclose(bx, x[by])


# ---- native tokenizer (reference faster_tokenizer_op.cc) --------------------

VOCAB = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "the", "quick", "brown", "fox",
         "jump", "##ed", "##s", "over", "lazy", "dog", ",", "!", "中", "国"]


def test_tokenizer_wordpiece_and_specials():
    from paddle_tpu.text import BertTokenizer

    tok = BertTokenizer(VOCAB)
    assert tok.vocab_size == len(VOCAB)
    ids, types = tok.encode("The quick brown fox jumped!")
    # lowercased, wordpiece jumped -> jump + ##ed, punct split
    assert ids == [2, 4, 5, 6, 7, 8, 9, 15, 3]
    assert types == [0] * len(ids)


def test_tokenizer_unknown_and_cjk():
    from paddle_tpu.text import BertTokenizer

    tok = BertTokenizer(VOCAB)
    assert tok.encode("the zebra")[0] == [2, 4, 1, 3]  # [UNK]
    assert tok.encode("中国")[0] == [2, 16, 17, 3]  # per-codepoint CJK split


def test_tokenizer_pair_and_truncation():
    from paddle_tpu.text import BertTokenizer

    tok = BertTokenizer(VOCAB)
    ids, ty = tok.encode("the fox", "lazy dog")
    assert ids == [2, 4, 7, 3, 12, 13, 3]
    assert ty == [0, 0, 0, 0, 1, 1, 1]
    ids_t, _ = tok.encode("the quick brown fox", max_seq_len=4)
    assert len(ids_t) == 4
    assert ids_t[-1] == 3  # truncation keeps a terminating [SEP]


def test_faster_tokenizer_layer_batch_padding():
    from paddle_tpu.text import FasterTokenizer

    ft = FasterTokenizer(VOCAB)
    ids, types = ft(["the fox", "the quick brown fox"])
    assert ids.shape == [2, 6]
    assert ids.numpy()[0].tolist() == [2, 4, 7, 3, 0, 0]  # [PAD] padded
    assert ids.numpy()[1].tolist() == [2, 4, 5, 6, 7, 3]
    ids2, _ = ft("the dog", pad_to_max_seq_len=True, max_seq_len=8)
    assert ids2.shape == [1, 8]
