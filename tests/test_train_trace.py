"""Training-run observability (profiler/tracing.py + hapi TrainMonitor).

Acceptance criteria from the training-observability issue:

- a traced `Model.fit` exports valid Chrome/Perfetto trace-event JSON
  with exactly the train-step span vocabulary the docs rely on
  (``train_step`` + ``data``/``shard``/``dispatch``/``sync``/``callback``
  phase children) — the schema canary, mirroring
  test_serving_trace.py's;
- tracing OFF is the pre-trace code path: `train_tracer()` is None,
  every hook is one pointer test, and the loss trajectory is identical
  to a traced run (tracing never changes a number);
- `xplane.join_engine_steps` joins training captures by step id exactly
  like serving ones (the dispatch runs under the same
  ``paddle_tpu.step <id>`` annotation);
- `TrainMonitor`: grad global norm in the logs (computed inside the one
  compiled program), non-finite loss detection with an actionable
  message, loss-spike warnings, and the recompile sentinel (warns when
  steady-state training keeps tracing new XLA programs).
"""
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.hapi.callbacks import Callback, TrainMonitor
from paddle_tpu.io import Dataset
from paddle_tpu.profiler import tracing
from paddle_tpu.profiler.tracing import TrainTracer

_PH = {"X", "i", "M"}
_PHASES = {"data", "shard", "dispatch", "sync", "callback"}


@pytest.fixture(autouse=True)
def _fresh_tracing():
    tracing.reset_train_tracing()
    yield
    tracing.reset_train_tracing()


class _Toy(Dataset):
    def __init__(self, n=32, seed=0):
        rs = np.random.RandomState(seed)
        self.x = rs.rand(n, 8).astype(np.float32)
        self.y = rs.randint(0, 4, (n, 1))

    def __getitem__(self, i):
        return self.x[i], self.y[i]

    def __len__(self):
        return len(self.x)


class _Recorder(Callback):
    def __init__(self):
        super().__init__()
        self.logs = []

    def on_train_batch_end(self, step, logs=None):
        self.logs.append(dict(logs or {}))


def _fit(epochs=1, n=32, batch_size=8, callbacks=None, seed=0, lr=1e-3):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=lr, parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    rec = _Recorder()
    model.fit(_Toy(n), epochs=epochs, batch_size=batch_size, verbose=0,
              shuffle=False, callbacks=[rec] + list(callbacks or []))
    return model, rec


def _validate(trace):
    json.loads(json.dumps(trace))
    for ev in trace["traceEvents"]:
        assert ev["ph"] in _PH, ev
        assert isinstance(ev["name"], str) and ev["name"], ev
        assert isinstance(ev["ts"], (int, float)) and ev["ts"] >= 0, ev
        if ev["ph"] == "X":
            assert isinstance(ev["dur"], (int, float)) and ev["dur"] >= 0, ev


# -- schema canary (CI gate against train-trace drift) -----------------------

def test_train_trace_schema_canary():
    tr = tracing.enable_train_tracing()
    _fit(epochs=1)
    trace = tr.chrome_trace()
    _validate(trace)
    names = {e["name"] for e in trace["traceEvents"]}
    assert "train_step" in names
    assert _PHASES <= names, names
    procs = {e["args"]["name"] for e in trace["traceEvents"]
             if e["ph"] == "M" and e["name"] == "process_name"}
    assert "paddle-tpu-train" in procs

    steps = [e for e in trace["traceEvents"] if e["name"] == "train_step"]
    assert len(steps) == 4                       # 32 samples / batch 8
    for ev in steps:
        for key in ("step", "batch", "batch_size", "loss"):
            assert key in ev["args"], ev["args"]
        assert ev["args"]["batch_size"] == 8
    # step ids are consecutive and spans carry monotonically ordered steps
    assert [e["args"]["batch"] for e in steps] == [0, 1, 2, 3]


def test_phases_nest_inside_their_train_step():
    tr = tracing.enable_train_tracing()
    _fit(epochs=1)
    evs = tr.chrome_trace()["traceEvents"]
    steps = {e["args"]["step"]: e for e in evs
             if e.get("ph") == "X" and e["name"] == "train_step"}
    phases = [e for e in evs if e.get("ph") == "X" and e["name"] in _PHASES]
    assert steps and phases
    eps = 1e-3
    for ph in phases:
        parent = steps[ph["args"]["step"]]
        assert ph["ts"] >= parent["ts"] - eps, (ph, parent)
        assert (ph["ts"] + ph["dur"]
                <= parent["ts"] + parent["dur"] + eps), (ph, parent)
    # every step carries all five phases (fit's full instrumentation)
    by_step = {}
    for ph in phases:
        by_step.setdefault(ph["args"]["step"], set()).add(ph["name"])
    assert all(v == _PHASES for v in by_step.values()), by_step


# -- tracing off is the pre-trace path --------------------------------------

def test_trace_off_loss_trajectory_identical(monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_TRACE", raising=False)
    tracing.reset_train_tracing()
    assert tracing.train_tracer() is None       # hook sites see None
    _, rec_off = _fit(epochs=2)
    losses_off = [l["loss"] for l in rec_off.logs]

    tr = tracing.enable_train_tracing()
    _, rec_on = _fit(epochs=2)
    losses_on = [l["loss"] for l in rec_on.logs]
    assert losses_on == losses_off               # tracing never changes math
    assert len(tr.chrome_trace()["traceEvents"]) > 0


def test_env_knobs(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
    monkeypatch.setenv("PADDLE_TPU_TRACE_BUF", "64")
    tracing.reset_train_tracing()
    tr = tracing.train_tracer()
    assert isinstance(tr, TrainTracer) and tr.capacity == 64
    assert tracing.train_tracer() is tr          # stable across calls
    monkeypatch.setenv("PADDLE_TPU_TRACE", "0")
    tracing.reset_train_tracing()
    assert tracing.train_tracer() is None
    # explicit API wins over env
    monkeypatch.setenv("PADDLE_TPU_TRACE", "1")
    tracing.disable_train_tracing()
    assert tracing.train_tracer() is None


def test_standalone_train_batch_records_span():
    """train_batch outside fit closes its own span (no fit loop to do it)."""
    tr = tracing.enable_train_tracing()
    paddle.seed(0)
    net = nn.Linear(8, 4)
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    x = paddle.to_tensor(np.ones((4, 8), np.float32))
    y = paddle.to_tensor(np.zeros((4, 1), np.int64))
    model.train_batch([x], [y])
    spans = [e for e in tr.chrome_trace()["traceEvents"]
             if e["name"] == "train_step"]
    assert len(spans) == 1
    names = {e["name"] for e in tr.chrome_trace()["traceEvents"]}
    # standalone: no loader, no callback list — the three core phases only
    assert {"shard", "dispatch", "sync"} <= names
    assert "data" not in names


def test_train_dispatch_span_unit():
    """The one-phase span ShardedTrainStep/pipeline steps record."""
    tr = TrainTracer(capacity=256)
    with tracing.train_dispatch_span(tr, {"source": "unit"}) as sid:
        pass
    evs = tr.chrome_trace()["traceEvents"]
    span = next(e for e in evs if e["name"] == "train_step")
    assert span["args"]["step"] == sid
    assert span["args"]["source"] == "unit"
    child = next(e for e in evs if e["name"] == "dispatch")
    assert child["args"]["step"] == sid


def test_instrumented_step_delegates_and_traces():
    """The pipeline-step wrapper: records a span per call while tracing,
    stays fully transparent otherwise — jit's AOT surface (.lower) must
    reach the wrapped function (test_pipeline_schedules' memory analysis
    broke on an opaque wrapper once; never again)."""
    import jax

    jfn = jax.jit(lambda x: x * 2)
    step = tracing.InstrumentedStep(jfn, {"source": "unit"})
    assert step.lower(1.0) is not None          # delegation to jit
    tracing.disable_train_tracing()
    assert float(step(2.0)) == 4.0              # transparent when off
    tr = tracing.enable_train_tracing()
    assert float(step(3.0)) == 6.0
    spans = [e for e in tr.chrome_trace()["traceEvents"]
             if e["name"] == "train_step"]
    assert len(spans) == 1 and spans[0]["args"]["source"] == "unit"


# -- xplane join works for training captures --------------------------------

def test_training_capture_joins_by_step_id(tmp_path):
    import jax

    from paddle_tpu.profiler import xplane

    tr = tracing.enable_train_tracing()
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    ds = _Toy()
    # compile outside the capture so it records steady-state steps
    model.fit(ds, epochs=1, batch_size=8, verbose=0, shuffle=False)
    with jax.profiler.trace(str(tmp_path)):
        model.fit(ds, epochs=1, batch_size=8, verbose=0, shuffle=False)
    spans = xplane.engine_step_spans(str(tmp_path))
    assert spans, "no step annotations reached the capture"
    rows = xplane.join_engine_steps(tr.chrome_trace(), str(tmp_path))
    assert rows and all(r["kind"] is None for r in rows)  # training spans
    joined = [r for r in rows if r["capture_dur_us"] is not None]
    assert joined, "no train_step span joined to the capture"
    for r in joined:
        assert r["step"] in spans
        assert r["capture_dur_us"] > 0 and r["host_dur_us"] > 0


# -- TrainMonitor ------------------------------------------------------------

def test_monitor_grad_norm_in_logs():
    _, rec_plain = _fit(epochs=1)
    assert all("grad_norm" not in l for l in rec_plain.logs)  # opt-in only
    mon = TrainMonitor()
    model, rec = _fit(epochs=1, callbacks=[mon])
    assert rec.logs and all("grad_norm" in l for l in rec.logs)
    assert all(np.isfinite(l["grad_norm"]) and l["grad_norm"] > 0
               for l in rec.logs)
    assert not model._monitor_grad_norm        # restored at train end
    assert mon.nan_events == 0 and mon.retrace_warnings == 0
    # steady state: exactly one program, zero retraces
    assert model.jit_retraces == 0


def test_monitor_nonfinite_loss_raises_actionably():
    paddle.seed(0)
    net = nn.Linear(8, 4)
    net.weight.set_value(np.full((8, 4), np.nan, np.float32))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    with pytest.raises(RuntimeError, match="non-finite loss.*check_nan_inf"):
        model.fit(_Toy(), epochs=1, batch_size=8, verbose=0,
                  callbacks=[TrainMonitor()])


def test_monitor_nan_stop_sets_stop_training():
    paddle.seed(0)
    net = nn.Linear(8, 4)
    net.weight.set_value(np.full((8, 4), np.nan, np.float32))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    mon = TrainMonitor(nan_action="stop")
    with pytest.warns(RuntimeWarning, match="non-finite loss"):
        model.fit(_Toy(), epochs=3, batch_size=8, verbose=0,
                  callbacks=[mon])
    assert model.stop_training
    # "stop" stops the EPOCH too — no further batches ran on condemned
    # state (the first NaN batch is the only one)
    assert mon.nan_events == 1


def test_monitor_raise_restores_flags():
    """A raising monitor must not leak its debug switches: the exception
    unwinds past fit, so the restore cannot wait for on_train_end."""
    from paddle_tpu.flags import get_flags

    paddle.seed(0)
    net = nn.Linear(8, 4)
    net.weight.set_value(np.full((8, 4), np.nan, np.float32))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    model.prepare(opt, paddle.nn.CrossEntropyLoss())
    with pytest.raises(RuntimeError, match="non-finite"):
        model.fit(_Toy(), epochs=1, batch_size=8, verbose=0,
                  callbacks=[TrainMonitor(check_nan_inf=True)])
    assert get_flags("FLAGS_check_nan_inf")["FLAGS_check_nan_inf"] is False
    assert model._monitor_grad_norm is False


def test_monitor_loss_spike_warns_unit():
    mon = TrainMonitor(spike_window=16, spike_factor=4.0)
    for i in range(10):
        mon.on_train_batch_end(i, {"loss": 1.0 + 0.01 * i})
    with pytest.warns(RuntimeWarning, match="loss spike"):
        mon.on_train_batch_end(10, {"loss": 50.0})
    assert mon.spike_warnings == 1
    # warnings are bounded — a pathological run cannot spam thousands
    for i in range(20):
        mon.on_train_batch_end(11 + i, {"loss": 50.0 + i})
    assert mon.spike_warnings <= mon.max_warnings
    # ... and the caps are PER KIND: exhausted spike budget must not
    # silence the recompile sentinel

    class _Stub:
        jit_traces = 1
        jit_retraces = 0
        stop_training = False

    stub = _Stub()
    mon.set_model(stub)
    mon.on_epoch_begin(0)
    mon.on_train_batch_end(0, {})        # warmup baseline
    stub.jit_traces = 2
    with pytest.warns(RuntimeWarning, match="recompile sentinel"):
        mon.on_train_batch_end(1, {})
    assert mon.retrace_warnings == 1


def test_stop_training_does_not_truncate_eval():
    """stop_training stops TRAIN epochs only: a stopped fit's eval pass
    (and any later standalone evaluate) must still see every sample."""
    paddle.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=net.parameters())
    from paddle_tpu.metric import Accuracy

    model.prepare(opt, paddle.nn.CrossEntropyLoss(), Accuracy())
    model.stop_training = True           # as a stopped fit leaves it
    seen = []

    class _EvalRec(Callback):
        def on_eval_batch_end(self, step, logs=None):
            seen.append(step)

    model.evaluate(_Toy(32), batch_size=8, verbose=0,
                   callbacks=[_EvalRec()])
    assert seen == [0, 1, 2, 3]          # all 4 batches, not 1


def test_monitor_recompile_sentinel_unit():
    class _Stub:
        jit_traces = 1
        jit_retraces = 0
        stop_training = False

    stub = _Stub()
    mon = TrainMonitor(warmup_steps=1)
    mon.set_model(stub)
    mon.on_epoch_begin(0)
    mon.on_train_batch_end(0, {"loss": 1.0})   # warmup: baseline = 1
    mon.on_train_batch_end(1, {"loss": 1.0})   # steady, no new trace: quiet
    stub.jit_traces = 2
    with pytest.warns(RuntimeWarning, match="recompile sentinel"):
        mon.on_train_batch_end(2, {"loss": 1.0})
    assert mon.retrace_warnings == 1
    # epoch boundary re-baselines (first eval program is not a retrace)
    stub.jit_traces = 3
    mon.on_epoch_begin(1)
    mon.on_train_batch_end(0, {"loss": 1.0})
    assert mon.retrace_warnings == 1


def test_monitor_recompile_sentinel_fires_on_ragged_batches():
    """The real thing: a dataset whose last batch is ragged compiles a
    second program mid-epoch — exactly the per-step compile churn the
    sentinel exists to surface."""
    mon = TrainMonitor(warmup_steps=1)
    with pytest.warns(RuntimeWarning, match="recompile sentinel"):
        _fit(epochs=1, n=20, batch_size=8, callbacks=[mon])  # 8, 8, 4
    assert mon.retrace_warnings == 1
