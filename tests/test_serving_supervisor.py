"""EngineSupervisor + StepWatchdog against a bare LLMEngine (synchronous).

The poison-isolation contract, driven by injected faults
(serving/faults.py): a step_raise pinned to one request aborts exactly
that request while every other in-flight request completes with output
token-identical to a no-fault run; transient faults attribute nobody;
only max_step_retries consecutive unattributable failures abort
everything. Plus non-finite containment, alloc_fail pressure, the
watchdog, and the standing invariants — after ANY injected fault
sequence, every refcount is zero and num_free equals idle capacity.

The async/HTTP layers of the same machinery are
tests/test_serving_chaos.py.
"""
import math
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    EngineSupervisor,
    LLMEngine,
    StepWatchdog,
    faults,
)
from paddle_tpu.serving.faults import FaultPlan


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _disarm():
    yield
    plan = faults.active()
    if plan is not None:
        plan.release_hangs()
    faults.clear()


@pytest.fixture(scope="module")
def ref_engine(model):
    """One shared no-fault engine for reference outputs — compiling a
    fresh pair of step programs per reference run is the dominant cost
    of this file (warm-vs-cold parity is PR 4's tested guarantee, so
    reuse cannot change the reference tokens)."""
    return LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _idle(engine):
    assert engine.pool._refcount == {}
    return engine.pool.num_free == engine.pool.num_blocks - 1


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(model, **kw)


def _run(sup, max_steps=300):
    """Drive the supervised engine to completion; returns (outs, failures)
    accumulated across steps."""
    outs, failures = [], []
    steps = 0
    while sup.engine.has_unfinished():
        o, f = sup.step()
        outs += o
        failures += f
        steps += 1
        assert steps < max_steps, "supervised serve did not converge"
    return outs, failures


def _reference(ref_engine, prompts, n=6):
    return ref_engine.generate(prompts, max_new_tokens=n, temperature=0.0)


def _submit_all(eng, prompts, poison_index=None, n=6):
    """Add every prompt; the poisoned one gets request id 'poison'.
    Returns the request ids in order."""
    rids = []
    for i, p in enumerate(prompts):
        rid = "poison" if i == poison_index else f"r{i}"
        eng.add_request(p, max_new_tokens=n, temperature=0.0, request_id=rid)
        rids.append(rid)
    return rids


def test_poison_step_isolated_others_token_identical(model, ref_engine):
    """THE acceptance criterion: a step_raise pinned to one request in a
    full mixed batch aborts exactly that request with an error carrying
    the exception class; every other request completes token-identical
    to a no-fault run; pool drains to idle."""
    prompts = _prompts((5, 9, 13, 7), seed=0)
    refs = _reference(ref_engine, prompts)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison", "exc": "DeviceBoom"},
    ]))
    rids = _submit_all(eng, prompts, poison_index=2)
    _, failures = _run(sup)
    assert [rid for rid, _ in failures] == ["poison"]
    assert "FaultInjected" in failures[0][1]       # the exception class
    assert "DeviceBoom" in failures[0][1]
    for i, rid in enumerate(rids):
        if rid == "poison":
            assert rid not in eng._requests        # aborted + dropped
            continue
        assert list(eng._requests[rid].output_ids) == refs[i]
    assert eng.metrics.counters["poison_requests_isolated"] == 1
    assert eng.metrics.counters["engine_step_errors"] >= 1
    assert _idle(eng)


def test_bisection_probe_bound_is_logarithmic(model):
    """Isolating one poisoned request out of B costs O(log B) probe
    steps per failed step — never a per-request scan."""
    prompts = _prompts((5, 9, 13, 7), seed=1)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison"},
    ]))
    _submit_all(eng, prompts, poison_index=1)
    _run(sup)
    errors = eng.metrics.counters["engine_step_errors"]
    probes = eng.metrics.counters["engine_step_retries"]
    bound = errors * (math.ceil(math.log2(len(prompts))) + 1)
    assert probes <= bound, f"{probes} probes for {errors} failures"
    assert _idle(eng)


def test_transient_fault_attributes_nobody(model, ref_engine):
    """A fault that does not reproduce under probing (one-shot nth_call)
    aborts NO request: everyone recomputes and completes with the exact
    no-fault outputs."""
    prompts = _prompts((5, 9, 7), seed=2)
    refs = _reference(ref_engine, prompts)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "step_raise", "nth_call": 1},
    ]))
    rids = _submit_all(eng, prompts)
    _, failures = _run(sup)
    assert failures == []
    assert eng.metrics.counters.get("poison_requests_isolated", 0) == 0
    for i, rid in enumerate(rids):
        assert list(eng._requests[rid].output_ids) == refs[i]
    assert _idle(eng)


def test_abort_everything_after_max_consecutive_unattributable(model):
    """Unattributable failures (raise on the main step, clean on every
    probe) fall back to the pre-supervisor abort-everything behavior —
    but only after max_step_retries CONSECUTIVE ones."""
    prompts = _prompts((5,), seed=3)
    eng = _engine(model)
    sup = EngineSupervisor(eng, max_step_retries=3)
    # a single request: main steps and verify probes alternate, so odd
    # match() calls are main steps — three one-shot faults on calls
    # 1/3/5 raise three main steps in a row while every probe is clean
    faults.install(FaultPlan([
        {"point": "step_raise", "nth_call": 1},
        {"point": "step_raise", "nth_call": 3},
        {"point": "step_raise", "nth_call": 5},
    ]))
    eng.add_request(prompts[0], max_new_tokens=6, temperature=0.0,
                    request_id="solo")
    _, failures = _run(sup)
    assert [rid for rid, _ in failures] == ["solo"]
    assert "unattributable" in failures[0][1]
    assert eng.metrics.counters.get("poison_requests_isolated", 0) == 0
    assert _idle(eng)


@pytest.mark.slow
def test_clean_step_resets_unattributable_counter(model, ref_engine):
    """Two unattributable failures separated by a clean step never reach
    a max_step_retries=2 fallback — the counter is consecutive."""
    prompts = _prompts((5,), seed=4)
    refs = _reference(ref_engine, prompts)
    eng = _engine(model)
    sup = EngineSupervisor(eng, max_step_retries=2)
    # calls: 1 = main (raise) / 2 = probe (clean) / 3 = main (clean,
    # resets) / 4 = main (raise) / 5 = probe (clean) -> counter 1 < 2
    faults.install(FaultPlan([
        {"point": "step_raise", "nth_call": 1},
        {"point": "step_raise", "nth_call": 4},
    ]))
    eng.add_request(prompts[0], max_new_tokens=6, temperature=0.0,
                    request_id="solo")
    _, failures = _run(sup)
    assert failures == []
    assert list(eng._requests["solo"].output_ids) == refs[0]
    assert _idle(eng)


def test_nonfinite_fault_aborts_only_that_row(model, ref_engine):
    """step_nonfinite_logits drives the per-row NaN/Inf containment:
    the matched row ends error:nonfinite_logits, everyone else is
    token-identical to the no-fault run."""
    prompts = _prompts((5, 9, 7), seed=5)
    refs = _reference(ref_engine, prompts)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "step_nonfinite_logits", "request_id": "poison",
         "times": 1},
    ]))
    rids = _submit_all(eng, prompts, poison_index=1)
    _, failures = _run(sup)
    assert failures == [("poison", "nonfinite_logits")]
    assert eng.metrics.counters["nonfinite_rows"] == 1
    for i, rid in enumerate(rids):
        if rid != "poison":
            assert list(eng._requests[rid].output_ids) == refs[i]
    assert _idle(eng)


def test_real_nan_forward_is_contained_and_never_cached(model):
    """A genuinely NaN forward (poisoned weights, no fault plan) trips
    the same containment: the row aborts instead of emitting a garbage
    token, and none of its written blocks is published to the prefix
    cache (NaN KV must never serve a later request)."""
    import jax

    (p,) = _prompts((17,), seed=6)
    eng = _engine(model)
    eng._params = jax.tree_util.tree_map(
        lambda x: x * float("nan"), eng._params)
    eng.add_request(p, max_new_tokens=4, temperature=0.0, request_id="bad")
    outs = []
    while eng.has_unfinished():
        outs += eng.step()
    assert outs == []                              # no token ever emitted
    assert eng.step_faults == [("bad", "nonfinite_logits")]
    assert eng.pool._hash_index == {}              # nothing published
    assert _idle(eng)


@pytest.mark.slow
def test_alloc_fail_pressure_is_absorbed(model, ref_engine):
    """Phantom allocation failures defer/preempt exactly like real block
    pressure; the serve completes with the no-fault outputs."""
    prompts = _prompts((5, 9, 13), seed=7)
    refs = _reference(ref_engine, prompts)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "alloc_fail", "nth_call": 2},
        {"point": "alloc_fail", "nth_call": 5},
    ]))
    rids = _submit_all(eng, prompts)
    _, failures = _run(sup)
    assert failures == []
    for i, rid in enumerate(rids):
        assert list(eng._requests[rid].output_ids) == refs[i]
    assert _idle(eng)


def test_watchdog_trips_on_hung_step(model):
    """A step_hang wedges the (here: side) engine thread; the watchdog
    flips health to step_stuck within timeout + one poll interval and
    records the trip; after release the step completes and the pool
    drains."""
    (p,) = _prompts((5,), seed=8)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    plan = faults.install(FaultPlan([
        {"point": "step_hang", "at_step": 1, "timeout_s": 30.0},
    ]))
    eng.add_request(p, max_new_tokens=3, temperature=0.0, request_id="hung")
    wd = StepWatchdog(sup, timeout_s=0.15, poll_s=0.02).start()
    t = threading.Thread(target=_run, args=(sup,), daemon=True)
    t.start()
    deadline = time.monotonic() + 5.0
    while sup.health.healthy and time.monotonic() < deadline:
        time.sleep(0.01)
    assert not sup.health.healthy
    snap = sup.health.snapshot()
    assert snap["reason"] == "step_stuck"
    assert snap["stuck_for_s"] >= 0.15
    assert eng.metrics.counters["watchdog_trips"] == 1
    assert eng.metrics.gauges["engine_unhealthy"] == 1.0
    assert wd.tripped
    plan.release_hangs()
    t.join(10.0)
    assert not t.is_alive()
    assert _idle(eng)
    wd.stop()


@pytest.mark.slow
def test_watchdog_quiet_on_healthy_serve(model):
    """No trip, no health flip, and a clean watchdog stop when steps
    finish inside the timeout."""
    (p,) = _prompts((5,), seed=9)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    wd = StepWatchdog(sup, timeout_s=30.0, poll_s=0.01).start()
    eng.add_request(p, max_new_tokens=4, temperature=0.0)
    _run(sup)
    wd.stop()
    assert not wd.tripped
    assert sup.health.healthy
    assert eng.metrics.counters.get("watchdog_trips", 0) == 0


def test_requeue_semantics(model):
    """requeue: running -> preempted to the waiting queue with blocks
    released; waiting -> True (already queued); unknown/finished ->
    False."""
    prompts = _prompts((5, 9), seed=10)
    eng = _engine(model)
    r0 = eng.add_request(prompts[0], max_new_tokens=4, temperature=0.0)
    r1 = eng.add_request(prompts[1], max_new_tokens=4, temperature=0.0)
    assert eng.requeue(r0) is True                 # waiting: no-op True
    eng.step()                                     # admits + first chunk
    req0 = eng._requests[r0]
    assert req0.state == "running" and req0.blocks
    assert eng.requeue(r0) is True
    assert req0.state == "waiting" and not req0.blocks
    assert eng.requeue("nope") is False
    while eng.has_unfinished():
        eng.step()
    assert eng.requeue(r0) is False                # finished
    assert eng.requeue(r1) is False
    assert _idle(eng)


def test_schedule_only_restricts_planning_and_admission(model):
    """step(only=ids) plans rows ONLY for those requests — everyone else
    holds exactly still (num_cached, outputs, blocks unchanged)."""
    prompts = _prompts((5, 9), seed=11)
    eng = _engine(model)
    ra = eng.add_request(prompts[0], max_new_tokens=4, temperature=0.0)
    rb = eng.add_request(prompts[1], max_new_tokens=4, temperature=0.0)
    outs = eng.step(only={ra})
    assert {o.request_id for o in outs} <= {ra}
    reqb = eng._requests[rb]
    assert reqb.state == "waiting" and reqb.num_cached == 0
    assert not reqb.output_ids
    while eng.has_unfinished():
        eng.step()
    assert len(eng._requests[rb].output_ids) == 4
    assert _idle(eng)


def test_contained_rows_survive_a_same_step_raise(model):
    """A step that poisons row A (non-finite containment) and THEN
    raises while emitting row B must still report A's failure — the
    containment abort already happened engine-side, and dropping it
    would leave A's consumer waiting forever."""
    prompts = _prompts((5, 9), seed=15)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "step_nonfinite_logits", "request_id": "A", "times": 1},
    ]))
    orig_emit = eng._emit
    state = {"armed": True}

    def bomb(req, token):
        out = orig_emit(req, token)
        if state["armed"] and req.request_id == "B":
            state["armed"] = False
            raise RuntimeError("emit-path bug")
        return out

    eng._emit = bomb
    eng.add_request(prompts[0], max_new_tokens=4, temperature=0.0,
                    request_id="A")
    eng.add_request(prompts[1], max_new_tokens=4, temperature=0.0,
                    request_id="B")
    _, failures = _run(sup)
    assert ("A", "nonfinite_logits") in failures
    assert [rid for rid, _ in failures if rid == "B"] == []  # B recovered
    assert len(eng._requests["B"].output_ids) == 4
    assert _idle(eng)


def test_scheduler_raise_never_blames_the_previous_plan(model):
    """schedule() itself raising (here: phantom allocation pressure that
    starves even the oldest request) recovers against an EMPTY plan —
    unattributable, falling back to abort-everything after
    max_step_retries — instead of re-queueing and bisecting whatever the
    previous step happened to plan."""
    (p,) = _prompts((5,), seed=16)
    eng = _engine(model)
    sup = EngineSupervisor(eng, max_step_retries=3)
    faults.install(FaultPlan([{"point": "alloc_fail"}]))  # every allocate
    eng.add_request(p, max_new_tokens=4, temperature=0.0,
                    request_id="solo")
    _, failures = _run(sup)
    assert [rid for rid, _ in failures] == ["solo"]
    assert "unattributable" in failures[0][1]
    assert eng.metrics.counters.get("engine_step_retries", 0) == 0
    assert _idle(eng)


def test_probe_exonerates_only_stepped_ids(model):
    """A clean probe clears exactly the ids the scheduler planned: an id
    it could not step (deferred/unknown) learned nothing and must stay
    suspect — and a probe that stepped nothing is fully inconclusive."""
    (p,) = _prompts((5,), seed=13)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    eng.add_request(p, max_new_tokens=4, temperature=0.0, request_id="r0")
    raised, stepped, outs, step_faults = sup._probe(["ghost"])
    assert raised is False and stepped == []
    assert outs == [] and step_faults == []
    raised, stepped, outs, _ = sup._probe(["ghost", "r0"])
    assert raised is False
    assert stepped == ["r0"]              # the deferred id stays suspect
    assert outs                           # stepped: real chunk progress
    while eng.has_unfinished():
        eng.step()
    assert _idle(eng)


def test_bisect_keeps_unstepped_half_suspect(model):
    """An inconclusive half probe must not eliminate that half: with the
    first half unsteppable, bisection probes the other half instead and
    still attributes the reproducible culprit there; symmetrically, a
    clean other half keeps the unstepped half suspect without ever
    attributing an unprobed request."""
    prompts = _prompts((5, 9), seed=14)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison"},
    ]))
    eng.add_request(prompts[0], max_new_tokens=4, temperature=0.0,
                    request_id="poison")
    culprit, _, _ = sup._bisect(["ghost", "poison"])
    assert culprit == "poison"
    eng.abort("poison")
    faults.clear()
    # no fault armed: other half clean, unstepped half stays suspect but
    # (being unsteppable) can never be positively attributed
    eng.add_request(prompts[1], max_new_tokens=4, temperature=0.0,
                    request_id="innocent")
    culprit, _, _ = sup._bisect(["ghost", "innocent"])
    assert culprit is None
    while eng.has_unfinished():
        eng.step()
    assert _idle(eng)


def test_supervisor_events_reach_the_trace(model):
    """Chaos runs are Perfetto-inspectable: fault fires, bisection
    probes, and the isolation verdict all land on the supervisor
    track."""
    prompts = _prompts((5, 9, 7), seed=12)
    eng = _engine(model, trace=True)
    sup = EngineSupervisor(eng)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison"},
    ]))
    _submit_all(eng, prompts, poison_index=0)
    _run(sup)
    names = {e["name"] for e in eng.tracer.chrome_trace()["traceEvents"]}
    assert {"fault[step_raise]", "step_failed", "bisect_probe",
            "poison_isolated"} <= names
    assert _idle(eng)


def test_poison_window_counts_distinct_sources(model):
    """The sliding poison-isolation window (the fleet router's sick-chip
    signal): every bisection attribution records its request SOURCE —
    the tenant, "-" when untenanted — and `poison_stats` reports both
    the isolation count and the DISTINCT source count. Serial poison
    from one tenant (or one untenanted client minting request ids) stays
    ONE source; isolations across tenants accumulate sources."""
    prompts = _prompts((5, 7, 9, 6), seed=20)
    eng = _engine(model)
    sup = EngineSupervisor(eng)
    assert sup.poison_stats() == {"window_s": 60.0,
                                  "isolated_in_window": 0,
                                  "distinct_sources": 0}
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": f"p{i}"} for i in range(3)]))
    # two isolations from tenant "mallory", one untenanted, one "acme":
    # 3 distinct sources total (mallory, -, acme) over 4 isolations
    plan = [("mallory", "p0"), ("mallory", "p1"), (None, "p2")]
    for i, (tenant, rid) in enumerate(plan):
        eng.add_request(prompts[i], max_new_tokens=4, temperature=0.0,
                        request_id=rid, tenant=tenant)
        _run(sup)
        stats = sup.poison_stats()
        assert stats["isolated_in_window"] == i + 1
    assert sup.poison_stats()["distinct_sources"] == 2   # mallory + "-"
    faults.clear()
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "p3"}]))
    eng.add_request(prompts[3], max_new_tokens=4, temperature=0.0,
                    request_id="p3", tenant="acme")
    _run(sup)
    stats = sup.poison_stats()
    assert stats == {"window_s": 60.0, "isolated_in_window": 4,
                     "distinct_sources": 3}
    # the gauges track the stats (refreshed by poison_stats itself)
    assert eng.metrics.gauges["poison_isolated_in_window"] == 4
    assert eng.metrics.gauges["poison_distinct_sources"] == 3
    assert _idle(eng)


def test_poison_window_slides(model):
    """Events age out of the window: with a tiny window, earlier
    isolations stop counting and the gauges decay on the next read."""
    prompts = _prompts((5, 7), seed=21)
    eng = _engine(model)
    sup = EngineSupervisor(eng, poison_window_s=0.2)
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": f"p{i}"} for i in range(2)]))
    eng.add_request(prompts[0], max_new_tokens=4, temperature=0.0,
                    request_id="p0", tenant="a")
    _run(sup)
    assert sup.poison_stats()["isolated_in_window"] == 1
    time.sleep(0.25)
    stats = sup.poison_stats()
    assert stats["isolated_in_window"] == 0
    assert stats["distinct_sources"] == 0
    assert eng.metrics.gauges["poison_distinct_sources"] == 0
    eng.add_request(prompts[1], max_new_tokens=4, temperature=0.0,
                    request_id="p1", tenant="b")
    _run(sup)
    stats = sup.poison_stats()
    assert stats == {"window_s": 0.2, "isolated_in_window": 1,
                     "distinct_sources": 1}
    assert _idle(eng)
