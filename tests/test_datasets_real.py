"""Real-format dataset ingestion: each loader parses a tiny real-format file
written by the test (VERDICT r3 item 5 — interface parity AND data parity).

Reference formats matched: MNIST IDX (vision/datasets/mnist.py), CIFAR
pickle-in-tar (cifar.py), image folder decode (folder.py), WAV audio
(audio/backends)."""
import gzip
import os
import pickle
import struct
import tarfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import image as V


# ---------------------------------------------------------------------------
# image codecs
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("channels", [1, 2, 3, 4])
def test_png_roundtrip(tmp_path, channels):
    rs = np.random.RandomState(channels)
    img = rs.randint(0, 256, (13, 17, channels), dtype=np.uint8)
    p = str(tmp_path / "x.png")
    V.image_save(p, img)
    back = V.image_load(p)
    np.testing.assert_array_equal(back, img)


def test_png_decodes_all_filter_types(tmp_path):
    """A zlib stream using filters 1-4 (written by hand) must decode to the
    same pixels as the filter-0 encoding."""
    import zlib

    rs = np.random.RandomState(0)
    img = rs.randint(0, 256, (4, 8, 3), dtype=np.uint8)
    stride, bpp = 8 * 3, 3
    rows = []
    for y, ftype in enumerate([1, 2, 3, 4]):
        line = img[y].reshape(-1).astype(np.int32)
        prev = img[y - 1].reshape(-1).astype(np.int32) if y else np.zeros(stride, np.int32)
        enc = np.zeros(stride, np.int32)
        for i in range(stride):
            a = line[i - bpp] if i >= bpp else 0
            b = prev[i]
            c = prev[i - bpp] if i >= bpp else 0
            if ftype == 1:
                pred = a
            elif ftype == 2:
                pred = b
            elif ftype == 3:
                pred = (a + b) >> 1
            else:
                p = a + b - c
                pa, pb, pc = abs(p - a), abs(p - b), abs(p - c)
                pred = a if (pa <= pb and pa <= pc) else (b if pb <= pc else c)
            enc[i] = (line[i] - pred) & 0xFF
        rows.append(bytes([ftype]) + bytes(enc.astype(np.uint8)))
    raw = b"".join(rows)

    def chunk(ctype, body):
        return (struct.pack(">I", len(body)) + ctype + body
                + struct.pack(">I", zlib.crc32(ctype + body) & 0xFFFFFFFF))

    data = (b"\x89PNG\r\n\x1a\n"
            + chunk(b"IHDR", struct.pack(">IIBBBBB", 8, 4, 8, 2, 0, 0, 0))
            + chunk(b"IDAT", zlib.compress(raw))
            + chunk(b"IEND", b""))
    np.testing.assert_array_equal(V.decode_png(data), img)


def test_ppm_binary_and_ascii(tmp_path):
    img = np.arange(2 * 3 * 3, dtype=np.uint8).reshape(2, 3, 3)
    p6 = str(tmp_path / "x.ppm")
    V.image_save(p6, img)
    np.testing.assert_array_equal(V.image_load(p6), img)
    # ascii P3 with a comment line
    body = " ".join(str(v) for v in img.reshape(-1))
    p3 = tmp_path / "y.ppm"
    p3.write_bytes(f"P3\n# comment\n3 2\n255\n{body}\n".encode())
    np.testing.assert_array_equal(V.image_load(str(p3)), img)


def test_bmp_24bit(tmp_path):
    img = np.random.RandomState(0).randint(0, 256, (5, 3, 3), dtype=np.uint8)
    h, w = img.shape[:2]
    stride = (w * 3 + 3) & ~3
    rows = b""
    for y in range(h - 1, -1, -1):  # bottom-up
        row = img[y, :, ::-1].tobytes()  # RGB -> BGR
        rows += row + b"\x00" * (stride - len(row))
    header = (b"BM" + struct.pack("<IHHI", 54 + len(rows), 0, 0, 54)
              + struct.pack("<IiiHHIIiiII", 40, w, h, 1, 24, 0, len(rows), 0, 0, 0, 0))
    p = tmp_path / "x.bmp"
    p.write_bytes(header + rows)
    np.testing.assert_array_equal(V.image_load(str(p)), img)


# ---------------------------------------------------------------------------
# MNIST idx
# ---------------------------------------------------------------------------

def _write_idx(tmp_path, n=6, gz=False):
    rs = np.random.RandomState(1)
    images = rs.randint(0, 256, (n, 28, 28), dtype=np.uint8)
    labels = rs.randint(0, 10, n).astype(np.uint8)
    op = gzip.open if gz else open
    ip = str(tmp_path / ("img.idx3-ubyte" + (".gz" if gz else "")))
    lp = str(tmp_path / ("lab.idx1-ubyte" + (".gz" if gz else "")))
    with op(ip, "wb") as f:
        f.write(struct.pack(">IIII", 2051, n, 28, 28) + images.tobytes())
    with op(lp, "wb") as f:
        f.write(struct.pack(">II", 2049, n) + labels.tobytes())
    return ip, lp, images, labels


@pytest.mark.parametrize("gz", [False, True])
def test_mnist_idx(tmp_path, gz):
    ip, lp, images, labels = _write_idx(tmp_path, gz=gz)
    ds = paddle.vision.datasets.MNIST(image_path=ip, label_path=lp)
    assert ds.real and len(ds) == 6
    img0, y0 = ds[0]
    np.testing.assert_allclose(img0[0], images[0] / 255.0, rtol=1e-6)
    assert int(y0[0]) == int(labels[0])


def test_mnist_synthetic_fallback_warns():
    with pytest.warns(UserWarning, match="SYNTHETIC"):
        ds = paddle.vision.datasets.MNIST()
    assert not ds.real and len(ds) > 0


# ---------------------------------------------------------------------------
# CIFAR tar.gz pickle
# ---------------------------------------------------------------------------

def _write_cifar(tmp_path, members, label_key, n=4):
    rs = np.random.RandomState(2)
    path = str(tmp_path / "cifar.tar.gz")
    all_data = {}
    with tarfile.open(path, "w:gz") as tf:
        import io

        for m in members:
            data = rs.randint(0, 256, (n, 3072), dtype=np.uint8)
            labels = rs.randint(0, 10, n).tolist()
            all_data[m] = (data, labels)
            blob = pickle.dumps({b"data": data, label_key: labels})
            info = tarfile.TarInfo(f"cifar-batches-py/{m}")
            info.size = len(blob)
            tf.addfile(info, io.BytesIO(blob))
    return path, all_data


def test_cifar10_pickle_tar(tmp_path):
    path, truth = _write_cifar(tmp_path, ["data_batch_1", "data_batch_2", "test_batch"], b"labels")
    train = paddle.vision.datasets.Cifar10(data_file=path, mode="train")
    test = paddle.vision.datasets.Cifar10(data_file=path, mode="test")
    assert train.real and len(train) == 8 and len(test) == 4
    img0, y0 = train[0]
    np.testing.assert_allclose(
        img0, truth["data_batch_1"][0][0].reshape(3, 32, 32) / 255.0, rtol=1e-6
    )
    assert int(y0[0]) == truth["data_batch_1"][1][0]


def test_cifar100_pickle_tar(tmp_path):
    path, truth = _write_cifar(tmp_path, ["train", "test"], b"fine_labels")
    ds = paddle.vision.datasets.Cifar100(data_file=path, mode="test")
    assert ds.real and len(ds) == 4
    _, y0 = ds[0]
    assert int(y0[0]) == truth["test"][1][0]


def test_cifar_synthetic_fallback_warns():
    with pytest.warns(UserWarning, match="SYNTHETIC"):
        ds = paddle.vision.datasets.Cifar10()
    assert not ds.real


# ---------------------------------------------------------------------------
# DatasetFolder with real image decode
# ---------------------------------------------------------------------------

def test_dataset_folder_mixed_formats(tmp_path):
    rs = np.random.RandomState(3)
    for cls in ("cat", "dog"):
        os.makedirs(tmp_path / cls)
    a = rs.randint(0, 256, (8, 8, 3), dtype=np.uint8)
    b = rs.randint(0, 256, (8, 8, 3), dtype=np.uint8)
    V.image_save(str(tmp_path / "cat" / "a.png"), a)
    V.image_save(str(tmp_path / "dog" / "b.ppm"), b)
    np.save(str(tmp_path / "dog" / "c.npy"), b)
    ds = paddle.vision.datasets.DatasetFolder(str(tmp_path))
    assert len(ds) == 3
    assert ds.class_to_idx == {"cat": 0, "dog": 1}
    img, target = ds[0]
    np.testing.assert_array_equal(img, a)
    assert target == 0
    img_b, target_b = ds[1]
    np.testing.assert_array_equal(img_b, b)
    assert target_b == 1


# ---------------------------------------------------------------------------
# WAV audio
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("bits", [8, 16, 32])
def test_wav_roundtrip(tmp_path, bits):
    rs = np.random.RandomState(bits)
    wav = np.clip(rs.randn(2, 400) * 0.3, -1, 1).astype(np.float32)
    p = str(tmp_path / "x.wav")
    paddle.audio.save(p, wav, 16000, bits_per_sample=bits)
    back, sr = paddle.audio.load(p)
    assert sr == 16000 and back.shape == wav.shape
    # 32-bit tolerance is float32 mantissa rounding of near-2^31 ints
    tol = {8: 2e-2, 16: 1e-4, 32: 1e-6}[bits]
    np.testing.assert_allclose(back, wav, atol=tol)


def test_tess_reads_wav_dir(tmp_path):
    t = np.arange(16000) / 16000.0
    for i, emotion in enumerate(["angry", "happy", "sad", "neutral"]):
        wav = np.sin(2 * np.pi * 200 * (i + 1) * t).astype(np.float32)
        paddle.audio.save(str(tmp_path / f"OAF_word_{emotion}.wav"), wav[None], 16000)
    ds = paddle.audio.datasets.TESS(mode="train", split=1.0, archive_path=str(tmp_path))
    assert len(ds) == 4
    wave0, label0 = ds[0]
    assert wave0.shape == (16000,)
    assert int(label0) == 0  # "angry" sorts first and maps to label_list[0]


def test_esc50_filename_labels(tmp_path):
    wav = np.zeros((1, 800), np.float32)
    paddle.audio.save(str(tmp_path / "1-100032-A-14.wav"), wav, 16000)
    paddle.audio.save(str(tmp_path / "1-100038-A-7.wav"), wav, 16000)
    ds = paddle.audio.datasets.ESC50(mode="train", split=1.0, archive_path=str(tmp_path))
    labels = sorted(int(ds[i][1]) for i in range(len(ds)))
    assert labels == [7, 14]


def test_dataloader_shuffle_deterministic_under_seed():
    """paddle.seed must reach the shuffle stream even though samplers
    iterate on the DataLoader's PREFETCH THREAD (r4 review: a thread-local
    host generator silently broke this)."""
    import numpy as np

    import paddle_tpu as paddle
    import paddle_tpu.io as io

    class DS:
        def __len__(self):
            return 32

        def __getitem__(self, i):
            return np.asarray([i], np.int64)

    def epoch():
        paddle.seed(123)
        loader = io.DataLoader(DS(), batch_size=8, shuffle=True)
        return [tuple(np.asarray(b).reshape(-1).tolist()) for b in loader]

    a = epoch()
    b = epoch()
    assert a == b, (a, b)
    # and it IS shuffled
    flat = [x for t in a for x in t]
    assert flat != sorted(flat)
