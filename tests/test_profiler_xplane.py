"""Cross-stack trace analysis tool (profiler/xplane.py): capture a real
jax.profiler trace and read op summaries back without TF/TensorBoard."""
import glob
import os
import tempfile

import numpy as np
import pytest


@pytest.mark.slow
def test_summarize_roundtrip():
    import io as _io

    import jax
    import jax.numpy as jnp

    from paddle_tpu.profiler import xplane

    with tempfile.TemporaryDirectory() as td:
        @jax.jit
        def f(x):
            return jnp.tanh(x @ x.T).sum()

        x = jnp.asarray(np.random.RandomState(0).rand(256, 256).astype(np.float32))
        f(x).block_until_ready()
        with jax.profiler.trace(td):
            for _ in range(3):
                r = f(x)
            r.block_until_ready()
        files = xplane.find_xplane_files(td)
        assert files, os.listdir(td)
        # CPU captures carry host planes; device_only=False must see ops
        summary = xplane.summarize(td, device_only=False)
        assert summary, "no planes parsed"
        total = sum(e["total_ms"] for e in summary.values())
        assert total > 0
        assert any(e["by_category"] for e in summary.values())
        buf = _io.StringIO()
        xplane.print_summary(td, device_only=False, file=buf)
        assert "busy" in buf.getvalue()


def test_interval_union_stats_empty_is_zeroed():
    """An empty interval list (metrics scraped before the first engine
    step) must yield a zeroed stats record, not IndexError (flagged in the
    serving-frontend issue: /metrics can fire before any step lands)."""
    from paddle_tpu.profiler import xplane

    st = xplane.interval_union_stats([])
    assert st == {"span_ms": 0.0, "busy_ms": 0.0, "idle_ms": 0.0,
                  "utilization": 0.0, "n_ops": 0, "top_gaps": []}
    # and the shape still renders through the shared printer
    import io

    buf = io.StringIO()
    xplane.print_schedule_analysis({"empty-plane": st}, file=buf)
    assert "empty-plane" in buf.getvalue()


def test_schedule_analysis_math():
    """Executor-schedule statistics (reference executor_statistics.cc):
    exact busy/idle/gap math on a hand-built device capture."""
    from paddle_tpu.profiler import xplane
    from paddle_tpu.profiler._xplane import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    plane.event_metadata[1].id = 1
    plane.event_metadata[1].name = "matmul.1"
    plane.event_metadata[2].id = 2
    plane.event_metadata[2].name = "fusion.2"
    plane.event_metadata[3].id = 3
    plane.event_metadata[3].name = "allreduce.3"
    line = plane.lines.add()
    line.name = "XLA Ops"
    line.timestamp_ns = 0
    # [0,10ms] matmul, [10,12] fusion (back to back), GAP 8ms, [20,25] ar
    for mid, off_ms, dur_ms in ((1, 0, 10), (2, 10, 2), (3, 20, 5)):
        ev = line.events.add()
        ev.metadata_id = mid
        ev.offset_ps = int(off_ms * 1e9)
        ev.duration_ps = int(dur_ms * 1e9)

    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "cap.xplane.pb")
        with open(path, "wb") as f:
            f.write(xs.SerializeToString())
        st = xplane.schedule_analysis(path)
    s = st["/device:TPU:0"]
    assert s["span_ms"] == 25.0
    assert s["busy_ms"] == 17.0
    assert s["idle_ms"] == 8.0
    assert abs(s["utilization"] - 17.0 / 25.0) < 1e-9
    assert s["top_gaps"][0]["gap_ms"] == 8.0
    assert s["top_gaps"][0]["after_op"] == "fusion.2"
    assert s["top_gaps"][0]["before_op"] == "allreduce.3"


def _device_capture(offset_events, clock_base_ns=0):
    """Minimal one-plane capture with [offset_ms, duration_ms] events."""
    from paddle_tpu.profiler._xplane import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/device:TPU:0"
    plane.event_metadata[1].id = 1
    plane.event_metadata[1].name = "op.1"
    line = plane.lines.add()
    line.name = "XLA Ops"
    line.timestamp_ns = clock_base_ns
    for off_ms, dur_ms in offset_events:
        ev = line.events.add()
        ev.metadata_id = 1
        ev.offset_ps = int(off_ms * 1e9)
        ev.duration_ps = int(dur_ms * 1e9)
    return xs


def test_schedule_analysis_reports_per_capture():
    """Two capture files with the SAME plane name but unrelated clock bases
    must be reported per-capture, NOT unioned into one timeline whose
    inter-capture dead time shows up as a giant idle gap."""
    from paddle_tpu.profiler import xplane

    with tempfile.TemporaryDirectory() as td:
        # capture A: 10ms busy starting at t=0; capture B: 10ms busy whose
        # clock base is 100 SECONDS later (a separate trace session)
        for name, xs in (
            ("a.xplane.pb", _device_capture([(0, 10)], clock_base_ns=0)),
            ("b.xplane.pb", _device_capture([(0, 10)],
                                            clock_base_ns=int(100e9))),
        ):
            with open(os.path.join(td, name), "wb") as f:
                f.write(xs.SerializeToString())
        st = xplane.schedule_analysis(td)
        assert len(st) == 2, st.keys()  # one entry per capture
        for s in st.values():
            # each capture is 100% busy over its own 10ms span — the old
            # union view reported ~100s span with a ~100s idle gap
            assert s["span_ms"] == 10.0
            assert s["busy_ms"] == 10.0
            assert s["idle_ms"] == 0.0
            assert not s["top_gaps"]


@pytest.mark.slow  # tier-1 headroom (PR 19): heaviest always-on case; tier-2 covers it
def test_real_capture_schema_canary():
    """VERDICT residual risk: schema drift in jax's xplane output would
    pass CI (the math tests build captures by hand) and fail in the
    field. Record a REAL `jax.profiler` capture of a tiny jitted loop and
    assert every structural property the tool chain depends on, straight
    off the serialized ``.xplane.pb``:

    - the logdir contains exactly the capture file `find_xplane_files`
      globs for;
    - the vendored minimal proto parses it: planes carry lines, lines
      carry events, and every event's ``metadata_id`` resolves through
      ``event_metadata`` to a non-empty name with a positive duration
      (the exact fields `summarize`/`schedule_analysis` read);
    - the jitted loop is VISIBLE: an op named after our function reaches
      `summarize`'s op table, so event->metadata name resolution works on
      real data, not just hand-built messages;
    - `schedule_analysis` fed the ``.pb`` path (not the dir) yields a
      plane with at least as many ops as the loop ran steps, a positive
      span, and a sane utilization.
    """
    import jax
    import jax.numpy as jnp

    from paddle_tpu.profiler import xplane
    from paddle_tpu.profiler._xplane import xplane_pb2

    steps = 5
    with tempfile.TemporaryDirectory() as td:
        @jax.jit
        def tiny_loop_step(x):
            return jnp.tanh(x @ x.T).sum()

        x = jnp.ones((128, 128))
        tiny_loop_step(x).block_until_ready()  # compile outside the trace
        with jax.profiler.trace(td):
            acc = jnp.float32(0.0)
            for _ in range(steps):
                acc = acc + tiny_loop_step(x)
            acc.block_until_ready()

        files = xplane.find_xplane_files(td)
        assert len(files) == 1, os.listdir(td)
        pb = files[0]
        assert pb.endswith(".xplane.pb")

        xs = xplane_pb2.XSpace()
        with open(pb, "rb") as f:
            xs.ParseFromString(f.read())
        event_planes = [p for p in xs.planes
                        if any(line.events for line in p.lines)]
        assert event_planes, [p.name for p in xs.planes]
        n_resolved = 0
        total_dur_ps = 0
        for plane in event_planes:
            em = plane.event_metadata
            for line in plane.lines:
                for ev in line.events:
                    assert ev.metadata_id in em, (plane.name, line.name)
                    assert em[ev.metadata_id].name, ev.metadata_id
                    total_dur_ps += ev.duration_ps
                    n_resolved += 1
        assert n_resolved >= steps
        # durations must carry real time — a schema change that zeroes
        # duration_ps would make every busy/utilization stat silently 0
        assert total_dur_ps > 0

        meta_names = [em[mid].name for plane in event_planes
                      for em in (plane.event_metadata,) for mid in em]
        assert any("tiny_loop_step" in n for n in meta_names)
        # ... and the same op flows through summarize's name resolution
        # (top= wide enough that a fast op is not cut by busy-time rank)
        summary = xplane.summarize(pb, device_only=False, top=100000)
        ops = [name for entry in summary.values()
               for name, _ in entry["by_op"]]
        assert any("tiny_loop_step" in name for name in ops)

        st = xplane.schedule_analysis(pb)
        assert st, "no planes analyzed from the pb file"
        best = max(st.values(), key=lambda s: s["n_ops"])
        assert best["n_ops"] >= steps
        assert best["span_ms"] > 0
        assert 0 < best["utilization"] <= 1.0


def test_schedule_analysis_on_real_cpu_capture():
    """CPU captures have no device plane: the host fallback still yields a
    utilization view."""
    import io as _io

    import jax
    import jax.numpy as jnp

    from paddle_tpu.profiler import xplane

    with tempfile.TemporaryDirectory() as td:
        f = jax.jit(lambda a: jnp.tanh(a @ a.T).sum())
        x = jnp.ones((256, 256))
        f(x).block_until_ready()
        with jax.profiler.trace(td):
            for _ in range(3):
                r = f(x)
            r.block_until_ready()
        st = xplane.schedule_analysis(td)
        assert st, "no planes analyzed"
        s = next(iter(st.values()))
        assert s["span_ms"] > 0 and 0 < s["utilization"] <= 1.0
        buf = _io.StringIO()
        xplane.print_schedule_analysis(td, file=buf)
        assert "util" in buf.getvalue()


# -- serving-trace <-> device-capture join (observability issue) ------------

def _annotated_capture(step_spans):
    """Capture whose host plane carries `paddle_tpu.step <id>` annotation
    events at [offset_ms, dur_ms] — what a jax.profiler trace of a
    tracing-enabled serve contains."""
    from paddle_tpu.profiler._xplane import xplane_pb2

    xs = xplane_pb2.XSpace()
    plane = xs.planes.add()
    plane.name = "/host:CPU"
    line = plane.lines.add()
    line.name = "python"
    line.timestamp_ns = 0
    for mid, (sid, off_ms, dur_ms) in enumerate(step_spans, start=1):
        plane.event_metadata[mid].id = mid
        plane.event_metadata[mid].name = f"paddle_tpu.step {sid}"
        ev = line.events.add()
        ev.metadata_id = mid
        ev.offset_ps = int(off_ms * 1e9)
        ev.duration_ps = int(dur_ms * 1e9)
    return xs


def test_engine_step_spans_and_join():
    """`engine_step_spans` maps annotation events to step ids;
    `join_engine_steps` lines them up with the serving trace's host step
    spans, leaving capture fields None where the capture has no data."""
    from paddle_tpu.profiler import xplane

    xs = _annotated_capture([(0, 0.0, 2.0), (1, 3.0, 1.5)])
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "cap.xplane.pb")
        with open(path, "wb") as f:
            f.write(xs.SerializeToString())
        spans = xplane.engine_step_spans(path)
        assert set(spans) == {0, 1}
        assert spans[0]["dur_us"] == pytest.approx(2000.0)
        assert spans[1]["start_us"] == pytest.approx(3000.0)
        assert spans[1]["plane"] == "/host:CPU"

        chrome = {"traceEvents": [
            {"name": "step[decode]", "ph": "X", "pid": 1, "tid": 0,
             "ts": 100.0, "dur": 1900.0, "args": {"step": 0,
                                                  "kind": "decode"}},
            {"name": "step[mixed]", "ph": "X", "pid": 1, "tid": 0,
             "ts": 5000.0, "dur": 800.0, "args": {"step": 7,
                                                  "kind": "mixed"}},
            # phase children and request spans must NOT join
            {"name": "dispatch", "ph": "X", "pid": 1, "tid": 0,
             "ts": 150.0, "dur": 100.0, "args": {"step": 0}},
        ]}
        rows = xplane.join_engine_steps(chrome, path)
    assert [r["step"] for r in rows] == [0, 7]
    assert rows[0]["kind"] == "decode"
    assert rows[0]["capture_dur_us"] == pytest.approx(2000.0)
    assert rows[0]["capture_plane"] == "/host:CPU"
    assert rows[1]["capture_dur_us"] is None  # step 7 not captured


def test_join_on_real_traced_serve():
    """End to end: a tracing-enabled engine served under
    `jax.profiler.trace` stamps its step ids into the capture, and the
    join recovers device/host rows for the steps the capture covered."""
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.profiler import xplane
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, attn_impl="xla",
                    dropout=0.0)
    model = GPT(cfg)
    model.eval()
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                       trace=1.0)
    rs = np.random.RandomState(0)
    # compile outside the capture so the trace records steady-state steps
    engine.generate([rs.randint(0, 128, (9,)).tolist()], max_new_tokens=2)
    with tempfile.TemporaryDirectory() as td:
        with jax.profiler.trace(td):
            engine.generate([rs.randint(0, 128, (7,)).tolist(),
                             rs.randint(0, 128, (12,)).tolist()],
                            max_new_tokens=4)
        spans = xplane.engine_step_spans(td)
        assert spans, "no step annotations reached the capture"
        rows = xplane.join_engine_steps(engine.tracer.chrome_trace(), td)
    joined = [r for r in rows if r["capture_dur_us"] is not None]
    assert joined, "no host step span joined to the capture"
    for r in joined:
        assert r["step"] in spans
        assert r["capture_dur_us"] > 0
        # the annotation wraps only the dispatch, so it can never exceed
        # the full host step span by more than measurement jitter
        assert r["host_dur_us"] > 0
