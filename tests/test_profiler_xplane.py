"""Cross-stack trace analysis tool (profiler/xplane.py): capture a real
jax.profiler trace and read op summaries back without TF/TensorBoard."""
import glob
import os
import tempfile

import numpy as np


def test_summarize_roundtrip():
    import io as _io

    import jax
    import jax.numpy as jnp

    from paddle_tpu.profiler import xplane

    with tempfile.TemporaryDirectory() as td:
        @jax.jit
        def f(x):
            return jnp.tanh(x @ x.T).sum()

        x = jnp.asarray(np.random.RandomState(0).rand(256, 256).astype(np.float32))
        f(x).block_until_ready()
        with jax.profiler.trace(td):
            for _ in range(3):
                r = f(x)
            r.block_until_ready()
        files = xplane.find_xplane_files(td)
        assert files, os.listdir(td)
        # CPU captures carry host planes; device_only=False must see ops
        summary = xplane.summarize(td, device_only=False)
        assert summary, "no planes parsed"
        total = sum(e["total_ms"] for e in summary.values())
        assert total > 0
        assert any(e["by_category"] for e in summary.values())
        buf = _io.StringIO()
        xplane.print_summary(td, device_only=False, file=buf)
        assert "busy" in buf.getvalue()
