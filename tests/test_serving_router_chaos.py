"""Router chaos: injected replica faults driven through the fleet.

THE acceptance criterion lives here: with one of 3 replicas killed
mid-wave, every request not in flight on the dead replica completes
token-identical to an unrouted reference serve, zero-token in-flight
requests retry elsewhere successfully, and mid-stream victims get exactly
ONE structured terminal error (`RoutedStream.terminal_events == 1`).
Plus: a watchdog-stuck replica is ejected while hung and re-admitted
after a half-open probe passes (factory restart — PR 9 unhealthy is
sticky), and the poison-rate satellite — a replica whose isolations span
distinct tenants is ejected as a sick chip while one adversarial tenant
can never trip it. The randomized drain-under-load soak is ``slow``.
"""
import asyncio
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import witness as lock_witness
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    LLMEngine,
    ReplicaRouter,
    faults,
)
from paddle_tpu.serving.faults import FaultPlan


@pytest.fixture(autouse=True, scope="module")
def _lock_order_witness():
    """PADDLE_TPU_LOCK_WITNESS=1: witness every lock the fleet builds in
    this module and assert acquisition-order acyclicity + static-model
    coverage at teardown (see tests/test_serving_chaos.py twin)."""
    if not lock_witness.enabled_from_env():
        yield None
        return
    w = lock_witness.install()
    try:
        yield w
    finally:
        lock_witness.uninstall()
    w.check_acyclic()
    gaps = lock_witness.cross_check(w)
    assert gaps == [], "\n".join(gaps)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _disarm():
    yield
    plan = faults.active()
    if plan is not None:
        plan.release_hangs()
    faults.clear()


@pytest.fixture(scope="module")
def ref_engine(model):
    return LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)


def _prompt(seed, n=10):
    return np.random.RandomState(seed).randint(0, 128, (n,)).tolist()


def _replica(model, **kw):
    return AsyncLLMEngine(
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64),
        max_waiting=8, **kw)


def _homed_prompt(router, home, seed0, n=12):
    seed = seed0
    while True:
        seed += 1
        p = _prompt(seed, n)
        if router.home_replica(p) == home:
            return p


def test_replica_thread_die_mid_wave(model, ref_engine):
    """Kill one of 3 replicas mid-wave: the dead replica's running
    requests fail with exactly one structured error each, its queued
    zero-token requests replay elsewhere and complete token-identical,
    everyone else is untouched, and the replica is ejected.

    The kill is PINNED to one replica (its supervisor's next step
    raises, escaping the engine loop — the exact thread_die/crash-
    epilogue path) and gated on THAT replica's engine-side state: the
    old global thread_die(times=1) raced cross-replica skew — the gate
    waited for the slowest replica while the eventual victim ran 24+
    steps ahead, finished its first pair, and deleted the zero-token
    replay (or the mid-stream victims) the test exists to exercise.
    Death lands before the victim's next step, so its running pair can
    never retire and its queued pair can never start — both outcome
    classes are guaranteed whatever the host scheduler does."""
    async def main():
        replicas = [_replica(model) for _ in range(3)]
        # warm every replica BEFORE the wave (the watchdog-test idiom):
        # first-step XLA compile is a slow step that widens skew
        for r in replicas:
            r.engine.generate([[0]], max_new_tokens=2, temperature=0.0)
        router = ReplicaRouter(replicas,
                               sweep_interval_s=0.02,
                               probe_interval_s=60.0)
        await router.start()
        # 4 prompts homed to each replica: with max_batch=2, two run
        # mid-stream and two wait queued (zero tokens) at kill time
        buckets = {r.name: [] for r in router.replicas}
        seed = 0
        while any(len(v) < 4 for v in buckets.values()):
            seed += 1
            p = _prompt(seed)
            h = router.home_replica(p)
            if len(buckets[h]) < 4:
                buckets[h].append(p)
        prompts = [p for i in range(4)
                   for p in (buckets["r0"][i], buckets["r1"][i],
                             buckets["r2"][i])]
        refs = ref_engine.generate(prompts, max_new_tokens=24,
                                   temperature=0.0)
        streams = [await router.submit(p, max_new_tokens=24,
                                       temperature=0.0) for p in prompts]
        victim = next(r for r in router.replicas if r.name == "r1")
        victim_streams = [s for s in streams if s.replica == "r1"]

        def victim_arranged():
            # ENGINE-side truth only (output_ids grows on the engine
            # thread): two rows emitting, two still at zero — the
            # loop-side token counts lag dispatch and raced under load
            started = sum(1 for s in victim_streams
                          if len(s.req.output_ids) >= 1)
            zero = sum(1 for s in victim_streams
                       if len(s.req.output_ids) == 0)
            return started >= 2 and zero >= 2

        t0 = time.monotonic()
        while not victim_arranged():
            assert time.monotonic() - t0 < 30, "victim never arranged"
            await asyncio.sleep(0.005)
        # pinned kill: the victim's next supervised step raises OUTSIDE
        # the supervisor's own isolation (frontend calls sup.step()
        # un-tried), escaping _run_engine_loop into the crash epilogue —
        # the same path the global thread_die fault takes
        def die():
            raise faults.FaultInjected("thread_die (pinned to r1)")

        victim.engine._sup.step = die
        results = await asyncio.wait_for(
            asyncio.gather(*[s.collect() for s in streams]), 60.0)
        dead = [r for r in router.replicas
                if r.engine.healthz_state()[0] == "engine_dead"]
        # let the sweep observe the death too (the forwarding error path
        # usually ejects first; either path must leave it ejected)
        t0 = time.monotonic()
        while dead and dead[0].state != "ejected":
            assert time.monotonic() - t0 < 10
            await asyncio.sleep(0.02)
        c = dict(router.metrics.counters)
        states = {r.name: r.state for r in router.replicas}
        await router.shutdown()
        return streams, results, refs, dead, c, states

    streams, results, refs, dead, c, states = asyncio.run(main())
    assert len(dead) == 1                       # exactly one replica died
    dead_name = dead[0].name
    assert states[dead_name] == "ejected"
    assert sum(1 for s in states.values() if s == "active") == 2
    n_ok = n_err = 0
    for s, (toks, reason), ref in zip(streams, results, refs):
        assert s.terminal_events == 1, (s.request_id, s.terminal_events)
        if reason == "length":
            assert toks == ref                  # token-identical survivor
            n_ok += 1
        else:
            # mid-stream victim: structured terminal error, tokens were
            # already delivered, never replayed
            assert reason == "error" and s.error and s.n_tokens > 0
            assert s.replays == 0
            n_err += 1
    # 8 untouched + 2 zero-token replays completed; 2 mid-stream victims
    assert n_ok == 10 and n_err == 2
    assert c["router_replays"] == 2
    assert c["router_midstream_errors"] == 2
    assert c["router_ejections"] == 1
    # the replayed pair must be the dead replica's queued requests, now
    # finished on a DIFFERENT replica
    replayed = [s for s in streams if s.replays]
    assert len(replayed) == 2
    assert all(s.replica != dead_name and s.finish_reason == "length"
               for s in replayed)


def test_watchdog_stuck_replica_ejected_then_readmitted(model, ref_engine):
    """A hung step trips the replica's watchdog: the router ejects it
    while it is STILL hung (innocents on the healthy replica keep
    serving, the hung replica's zero-token victim replays), then the
    half-open probe restarts it through the factory and re-admits it."""
    def mk():
        eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
        # warm: compile mixed+decode BEFORE arming a 0.3s watchdog — the
        # first-step XLA compile is a legitimately slow step, not a hang
        eng.generate([list(range(1, 10))], max_new_tokens=2,
                     temperature=0.0)
        return AsyncLLMEngine(eng, max_waiting=8,
                              watchdog_step_timeout_s=0.3,
                              hard_stop_timeout_s=2.0)

    async def main():
        router = ReplicaRouter([mk(), mk()], factory=lambda i: mk(),
                               sweep_interval_s=0.02, probe_interval_s=0.2,
                               probe_timeout_s=15.0)
        await router.start()
        plan = faults.install(FaultPlan([
            {"point": "step_hang", "request_id": "hangme", "times": 1}]))
        p = _prompt(50)
        hang_st = await router.submit(p, max_new_tokens=8, temperature=0.0,
                                      request_id="hangme")
        victim_name = hang_st.replica
        other = [r for r in router.replicas if r.name != victim_name][0]
        p2 = _homed_prompt(router, other.name, seed0=100)
        inno = await router.submit(p2, max_new_tokens=6, temperature=0.0)
        toks_h, reason_h = await asyncio.wait_for(hang_st.collect(), 30.0)
        toks_i, reason_i = await asyncio.wait_for(inno.collect(), 30.0)
        victim = [r for r in router.replicas if r.name == victim_name][0]
        # ejected while the step is STILL hung (hang released only below)
        t0 = time.monotonic()
        while victim.state not in ("ejected", "probing"):
            assert time.monotonic() - t0 < 10, victim.state
            await asyncio.sleep(0.02)
        stuck_eject_state = victim.state
        plan.release_hangs()
        t0 = time.monotonic()
        while victim.state != "active" or victim.restarts < 1:
            assert time.monotonic() - t0 < 60, (victim.state,
                                                victim.restarts)
            await asyncio.sleep(0.05)
        faults.clear()
        # the re-admitted (restarted) replica serves again
        post = await router.generate(
            _homed_prompt(router, victim_name, seed0=200),
            max_new_tokens=3, temperature=0.0)
        c = dict(router.metrics.counters)
        await router.shutdown()
        return (hang_st, toks_h, reason_h, toks_i, reason_i, p, p2,
                stuck_eject_state, victim, post, c, other.name)

    (hang_st, toks_h, reason_h, toks_i, reason_i, p, p2,
     stuck_eject_state, victim, post, c, other_name) = asyncio.run(main())
    # the hung request had zero tokens -> replayed on the healthy
    # replica, token-identical to an unrouted serve
    assert reason_h == "length" and hang_st.replays == 1
    assert hang_st.replica == other_name
    assert toks_h == ref_engine.generate([p], max_new_tokens=8,
                                         temperature=0.0)[0]
    # the innocent on the healthy replica was untouched
    assert reason_i == "length"
    assert toks_i == ref_engine.generate([p2], max_new_tokens=6,
                                         temperature=0.0)[0]
    assert stuck_eject_state in ("ejected", "probing")
    assert victim.restarts == 1
    assert post[1] == "length"
    assert c["router_ejections"] == 1
    assert c["router_readmissions"] == 1
    assert c["router_restarts"] == 1


def test_poison_rate_ejects_sick_chip_not_adversarial_tenant(model):
    """The PR 9 known limit closed at the fleet level: serial poison
    isolations spanning DISTINCT tenants read as a sick chip and eject
    the replica; the same isolations from one tenant (an adversarial
    client) never do — each poison request is aborted alone, never
    replayed onto a second replica."""
    async def run(tenants):
        router = ReplicaRouter([_replica(model) for _ in range(2)],
                               sweep_interval_s=0.02, probe_interval_s=60.0,
                               poison_source_threshold=3)
        await router.start()
        shared = _prompt(300, n=8)           # one full block: one home
        home = router.home_replica(shared + [1])
        faults.install(FaultPlan([
            {"point": "step_raise", "request_id": f"poison{i}"}
            for i in range(len(tenants))]))
        for i, tenant in enumerate(tenants):
            st = await router.submit(
                shared + [i], max_new_tokens=4, temperature=0.0,
                request_id=f"poison{i}", tenant=tenant)
            assert st.replica == home
            toks, reason = await asyncio.wait_for(st.collect(), 30.0)
            # request-attributed failure: terminal error, no replay —
            # a poison request must never get a shot at a second replica
            assert reason == "error" and st.replays == 0
            assert st.terminal_events == 1
        await asyncio.sleep(0.3)             # several sweep passes
        victim = [r for r in router.replicas if r.name == home][0]
        state = victim.state
        reason = victim.eject_reason
        stats = victim.engine.supervisor.poison_stats()
        faults.clear()
        # the OTHER replica still serves either way
        ok = await router.generate(_prompt(400), max_new_tokens=3,
                                   temperature=0.0)
        await router.shutdown()
        return state, reason, stats, ok

    state, reason, stats, ok = asyncio.run(
        run(["tenant-a", "tenant-b", "tenant-c"]))
    assert state == "ejected" and reason.startswith("poison_rate:")
    assert stats["distinct_sources"] == 3
    assert ok[1] == "length"

    state, reason, stats, ok = asyncio.run(
        run(["mallory", "mallory", "mallory"]))
    assert state == "active" and reason is None     # one source: no eject
    assert stats["isolated_in_window"] == 3
    assert stats["distinct_sources"] == 1
    assert ok[1] == "length"


def test_poison_on_draining_replica_is_request_attributed(model):
    """Attribution regression: a poison isolation on a replica whose
    healthz reads "draining" is still the REQUEST's own failure — the
    replica must not be ejected and the poison must not be replayed
    onto a second replica."""
    async def main():
        router = ReplicaRouter([_replica(model) for _ in range(2)],
                               sweep_interval_s=0.02, probe_interval_s=60.0)
        await router.start()
        home_name = router.home_replica(_prompt(600))
        home = [r for r in router.replicas if r.name == home_name][0]
        innocent = await router.submit(_prompt(600), max_new_tokens=20,
                                       temperature=0.0)
        assert innocent.replica == home_name
        poison = await router.submit(
            _homed_prompt(router, home_name, seed0=700),
            max_new_tokens=20, temperature=0.0, request_id="latepoison")
        assert poison.replica == home_name
        # drain the replica replica-side, THEN arm the fault: the
        # isolation happens while its healthz reads "draining"
        home.engine.stop_admitting()
        assert home.engine.healthz_state()[0] == "draining"
        faults.install(FaultPlan([
            {"point": "step_raise", "request_id": "latepoison"}]))
        toks_p, reason_p = await asyncio.wait_for(poison.collect(), 30.0)
        toks_i, reason_i = await asyncio.wait_for(innocent.collect(), 30.0)
        await asyncio.sleep(0.2)               # several sweeps
        state = home.state
        c = dict(router.metrics.counters)
        faults.clear()
        home.engine.resume_admitting()
        await router.shutdown()
        return poison, reason_p, reason_i, state, c

    poison, reason_p, reason_i, state, c = asyncio.run(main())
    assert reason_p == "error" and poison.replays == 0
    assert poison.terminal_events == 1
    assert reason_i == "length"                # the innocent rode it out
    assert state == "draining"                 # routed around, NOT ejected
    assert c.get("router_ejections", 0) == 0
    assert c.get("router_replays", 0) == 0


def test_poison_ejected_replica_stays_out_until_window_clears(model):
    """Flap regression: a poison-ejected replica still reports healthz
    "ok", so the half-open probe must consult the SAME poison window —
    no re-admission while the evidence is fresh, re-admission once the
    sliding window drains."""
    def mk():
        return AsyncLLMEngine(
            LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64),
            max_waiting=8, poison_window_s=1.5)

    async def main():
        router = ReplicaRouter([mk(), mk()], sweep_interval_s=0.02,
                               probe_interval_s=0.05,
                               poison_source_threshold=2)
        await router.start()
        shared = _prompt(500, n=8)
        home = router.home_replica(shared + [1])
        faults.install(FaultPlan([
            {"point": "step_raise", "request_id": f"poison{i}"}
            for i in range(2)]))
        for i, tenant in enumerate(["ta", "tb"]):
            st = await router.submit(
                shared + [i], max_new_tokens=4, temperature=0.0,
                request_id=f"poison{i}", tenant=tenant)
            await asyncio.wait_for(st.collect(), 30.0)
        faults.clear()
        victim = [r for r in router.replicas if r.name == home][0]
        t0 = time.monotonic()
        while victim.state not in ("ejected", "probing"):
            assert time.monotonic() - t0 < 10
            await asyncio.sleep(0.02)
        # probes run every ~50ms but must NOT re-admit while the window
        # still holds the 2-source evidence
        await asyncio.sleep(0.5)
        held_out = victim.state in ("ejected", "probing")
        readmissions_during = router.metrics.counters.get(
            "router_readmissions", 0)
        # once the 1.5s window slides empty, a probe re-admits
        t0 = time.monotonic()
        while victim.state != "active":
            assert time.monotonic() - t0 < 30, victim.state
            await asyncio.sleep(0.05)
        post = await router.generate(shared + [9], max_new_tokens=3,
                                     temperature=0.0)
        c = dict(router.metrics.counters)
        await router.shutdown()
        return held_out, readmissions_during, post, c

    held_out, readmissions_during, post, c = asyncio.run(main())
    assert held_out and readmissions_during == 0
    assert post[1] == "length"
    assert c["router_readmissions"] == 1
    assert c["router_probes"] >= 2          # failed probes backed off first


@pytest.mark.slow
def test_soak_rolling_drain_with_restarts_under_load(model, ref_engine):
    """Soak: three rolling-drain passes WITH factory restarts while a
    continuous wave is in flight — zero failed requests, every survivor
    token-identical, the fleet ends active and idle."""
    def mk():
        return AsyncLLMEngine(
            LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64),
            max_waiting=16)

    async def main():
        router = ReplicaRouter([mk(), mk()], factory=lambda i: mk(),
                               sweep_interval_s=0.02)
        await router.start()
        failures = []
        for round_i in range(3):
            prompts = [_prompt(1000 + 10 * round_i + j, n=6 + j % 5)
                       for j in range(8)]
            refs = ref_engine.generate(prompts, max_new_tokens=8,
                                       temperature=0.0)
            streams = [await router.submit(p, max_new_tokens=8,
                                           temperature=0.0)
                       for p in prompts]
            drained = await router.rolling_drain()
            assert drained == ["r0", "r1"]
            for s, ref in zip(streams, refs):
                toks, reason = await asyncio.wait_for(s.collect(), 60.0)
                if reason != "length" or toks != ref:
                    failures.append((s.request_id, reason, toks, ref))
        c = dict(router.metrics.counters)
        states = [r.state for r in router.replicas]
        restarts = [r.restarts for r in router.replicas]
        for r in router.replicas:
            eng = r.engine.engine
            assert eng.pool._refcount == {}
            assert eng.pool.num_free == eng.pool.num_blocks - 1
        await router.shutdown()
        return failures, c, states, restarts

    failures, c, states, restarts = asyncio.run(main())
    assert failures == []                        # zero failed requests
    assert states == ["active", "active"]
    assert all(n == 3 for n in restarts)
    assert c["router_drains"] == 6
    assert c.get("router_requests_failed", 0) == 0
