"""OpTest: the systematic numpy-reference + numeric-gradient parity harness.

Reference parity: /root/reference/python/paddle/fluid/tests/unittests/
op_test.py:326 — declare an op + numpy inputs + a numpy reference; the
harness checks forward outputs against the reference and gradients by
central-difference numeric differentiation against the autograd tape.
Tolerance exemptions live in op_test_whitelist.py (reference
white_list/op_accuracy_white_list.py).
"""
from __future__ import annotations

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor


class OpCase:
    """One enrolled op.

    op: the paddle_tpu function under test (Tensor -> Tensor/list).
    make_inputs: rng -> tuple of numpy arrays (positional op inputs).
    ref: numpy reference taking the same positional numpy inputs.
    kwargs: extra keyword args passed to op AND ref (ref may ignore).
    grad: check gradients for float inputs (central difference vs tape).
    grad_idx: which input positions get grad-checked (default: all float).
    rtol/atol: forward tolerances; gtol: gradient tolerance.
    ref_raw: if True, ref receives kwargs too.
    """

    def __init__(self, name, op, make_inputs, ref, kwargs=None, grad=True,
                 grad_idx=None, rtol=1e-5, atol=1e-6, gtol=2e-3, ref_kwargs=False):
        self.name = name
        self.op = op
        self.make_inputs = make_inputs
        self.ref = ref
        self.kwargs = kwargs or {}
        self.grad = grad
        self.grad_idx = grad_idx
        self.rtol = rtol
        self.atol = atol
        self.gtol = gtol
        self.ref_kwargs = ref_kwargs

    def __repr__(self):
        return f"OpCase({self.name})"


def _to_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _run_op(case, np_inputs, requires_grad=False):
    tensors = []
    for a in np_inputs:
        t = paddle.to_tensor(a)
        if requires_grad and np.issubdtype(a.dtype, np.floating):
            t.stop_gradient = False
        tensors.append(t)
    outs = _to_list(case.op(*tensors, **case.kwargs))
    outs = [o for o in outs if isinstance(o, Tensor)]
    return tensors, outs


def check_output(case, seed=0):
    rs = np.random.RandomState(seed)
    np_inputs = tuple(np.asarray(a) for a in case.make_inputs(rs))
    _, outs = _run_op(case, np_inputs)
    if case.ref_kwargs:
        ref_out = case.ref(*np_inputs, **case.kwargs)
    else:
        ref_out = case.ref(*np_inputs)
    ref_outs = _to_list(ref_out)
    assert len(outs) == len(ref_outs), (
        f"{case.name}: op returned {len(outs)} outputs, reference {len(ref_outs)}"
    )
    for i, (o, r) in enumerate(zip(outs, ref_outs)):
        got = np.asarray(o.numpy())
        want = np.asarray(r)
        assert got.shape == want.shape, (
            f"{case.name} out[{i}]: shape {got.shape} != ref {want.shape}"
        )
        if np.issubdtype(want.dtype, np.floating) or np.issubdtype(
            want.dtype, np.complexfloating
        ):
            np.testing.assert_allclose(
                got, want, rtol=case.rtol, atol=case.atol,
                err_msg=f"{case.name} out[{i}]",
            )
        else:
            np.testing.assert_array_equal(got, want, err_msg=f"{case.name} out[{i}]")


def _loss_np(case, np_inputs, projs):
    """Scalar projection of op outputs, computed by running the REAL op —
    the numeric-diff target (matches reference OpTest's numeric grad)."""
    _, outs = _run_op(case, np_inputs)
    total = 0.0
    for o, p in zip(outs, projs):
        total += float(np.sum(np.asarray(o.numpy(), np.float64) * p))
    return total


def check_grad(case, seed=0, eps=1e-3):
    rs = np.random.RandomState(seed + 1)
    np_inputs = tuple(
        np.asarray(a, np.float64).astype(a.dtype) for a in case.make_inputs(rs)
    )
    # promote float inputs to float64? tape runs the op in its native dtype;
    # use float32 inputs as declared, numeric diff in float64 arithmetic.
    tensors, outs = _run_op(case, np_inputs, requires_grad=True)
    projs = [rs.uniform(-1, 1, size=np.asarray(o.numpy()).shape) for o in outs]

    # analytic: tape backward of sum(out * proj)
    loss = None
    for o, p in zip(outs, projs):
        term = (o * paddle.to_tensor(p.astype(np.asarray(o.numpy()).dtype))).sum()
        loss = term if loss is None else loss + term
    loss.backward()

    idxs = case.grad_idx
    if idxs is None:
        idxs = [
            i for i, a in enumerate(np_inputs)
            if np.issubdtype(np.asarray(a).dtype, np.floating)
        ]
    for i in idxs:
        t = tensors[i]
        assert t.grad is not None, f"{case.name}: no grad reached input {i}"
        analytic = np.asarray(t.grad.numpy(), np.float64)
        a = np_inputs[i]
        numeric = np.zeros(a.shape, np.float64)
        flat = a.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            step = eps * max(1.0, abs(float(orig)))
            plus = list(np_inputs)
            minus = list(np_inputs)
            ap = a.copy().reshape(-1)
            ap[j] = orig + step
            plus[i] = ap.reshape(a.shape).astype(a.dtype)
            am = a.copy().reshape(-1)
            am[j] = orig - step
            minus[i] = am.reshape(a.shape).astype(a.dtype)
            numeric.reshape(-1)[j] = (
                _loss_np(case, tuple(plus), projs)
                - _loss_np(case, tuple(minus), projs)
            ) / (2 * step)
        denom = max(np.abs(numeric).max(), np.abs(analytic).max(), 1.0)
        np.testing.assert_allclose(
            analytic / denom, numeric / denom, rtol=case.gtol, atol=case.gtol,
            err_msg=f"{case.name} grad wrt input {i}",
        )
