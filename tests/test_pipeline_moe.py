"""Pipeline parallelism + MoE expert parallelism tests."""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed.mesh import init_mesh, set_mesh


def teardown_module():
    set_mesh(None)


def _shard_map():
    from paddle_tpu.parallel._compat import shard_map

    return shard_map


def test_gpipe_matches_sequential():
    """Pipelined stacked-MLP must equal running stages sequentially."""
    from paddle_tpu.parallel.pipeline import gpipe, stack_stage_params

    mesh = init_mesh({"pp": 4})
    rs = np.random.RandomState(0)
    H = 8
    stage_params = [
        {"w": jnp.asarray(rs.rand(H, H).astype(np.float32) * 0.3)} for _ in range(4)
    ]
    stacked = stack_stage_params(stage_params)

    def stage_fn(p, x):
        return jnp.tanh(x @ p["w"])

    mbs = jnp.asarray(rs.rand(3, 2, H).astype(np.float32))  # [M, mb, H]
    out = gpipe(stage_fn, stacked, mbs, mesh, axis="pp")

    ref = mbs
    for p in stage_params:
        ref = jnp.tanh(ref @ p["w"])
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_gpipe_gradients_flow():
    from paddle_tpu.parallel.pipeline import gpipe, stack_stage_params

    mesh = init_mesh({"pp": 2})
    rs = np.random.RandomState(0)
    stacked = stack_stage_params(
        [{"w": jnp.asarray(rs.rand(4, 4).astype(np.float32) * 0.3)} for _ in range(2)]
    )
    mbs = jnp.asarray(rs.rand(2, 2, 4).astype(np.float32))

    def loss(params):
        out = gpipe(lambda p, x: jnp.tanh(x @ p["w"]), params, mbs, mesh, axis="pp")
        return jnp.sum(out**2)

    g = jax.grad(loss)(stacked)
    gnorms = np.asarray(jnp.linalg.norm(g["w"], axis=(1, 2)))
    assert (gnorms > 0).all(), gnorms  # every stage received gradient


def test_pipelined_gpt_trains_and_matches():
    from paddle_tpu.models.gpt import GPTConfig
    from paddle_tpu.models.gpt_pipeline import make_pipelined_gpt

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2, max_seq_len=32)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 128, (4, 32)))
    labels = jnp.asarray(rs.randint(0, 128, (4, 32)))

    res = {}
    for degrees in ({"pp": 1}, {"pp": 2, "dp": 2}):
        mesh = init_mesh(degrees)
        params, step = make_pipelined_gpt(cfg, mesh, n_microbatches=2)
        ls = []
        for _ in range(3):
            loss, params = step(params, ids, labels, jnp.float32(0.01))
            ls.append(float(np.asarray(loss)))
        res[str(degrees)] = ls
    vals = list(res.values())
    assert vals[0][-1] < vals[0][0]  # learning
    assert np.allclose(vals[0], vals[1], atol=1e-4), res  # pp == no-pp


def test_moe_eager_forward_backward():
    from paddle_tpu.distributed.moe import MoELayer

    set_mesh(None)
    paddle.seed(0)
    moe = MoELayer(16, 32, num_experts=4)
    x = paddle.randn([2, 8, 16])
    x.stop_gradient = False
    y = moe(x)
    assert y.shape == [2, 8, 16]
    y.sum().backward()
    assert moe.w1.grad is not None
    assert moe.gate.gate.grad is not None
    assert x.grad is not None


def test_moe_alltoall_matches_dense():
    from paddle_tpu.distributed.moe import _dense_dispatch, moe_alltoall_block

    mesh = init_mesh({"mp": 4})
    H, F, E, T = 16, 32, 4, 64
    rs = np.random.RandomState(0)
    xa = jnp.asarray(rs.rand(T, H).astype(np.float32))
    gw = jnp.asarray(rs.rand(H, E).astype(np.float32) * 0.1)
    w1 = jnp.asarray(rs.rand(E, H, F).astype(np.float32) * 0.1)
    b1 = jnp.zeros((E, F))
    w2 = jnp.asarray(rs.rand(E, F, H).astype(np.float32) * 0.1)
    b2 = jnp.zeros((E, H))

    cap = int(np.ceil(1.25 * T / E))
    gates = jax.nn.softmax(xa @ gw, -1)
    disp, comb = _dense_dispatch(xa, gates, cap)
    h = jax.nn.gelu(jnp.einsum("ech,ehf->ecf", disp, w1) + b1[:, None])
    eout = jnp.einsum("ecf,efh->ech", h, w2) + b2[:, None]
    ref = jnp.einsum("tec,ech->th", comb, eout)

    fn = _shard_map()(
        lambda x_, gw_, w1_, b1_, w2_, b2_: moe_alltoall_block(
            x_, gw_, w1_, b1_, w2_, b2_, mesh, "mp"
        ),
        mesh=mesh,
        in_specs=(P(), P(), P("mp"), P("mp"), P("mp"), P("mp")),
        out_specs=P(),
        check_vma=False,
    )
    out = jax.jit(fn)(xa, gw, w1, b1, w2, b2)
    assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_moe_capacity_drops_overflow():
    """Tokens beyond expert capacity must be dropped (zero contribution)."""
    from paddle_tpu.distributed.moe import _dense_dispatch

    T, E, cap = 8, 2, 2
    x = jnp.ones((T, 4))
    gates = jnp.tile(jnp.asarray([[0.9, 0.1]]), (T, 1))  # all route to expert 0
    disp, comb = _dense_dispatch(x, gates, cap)
    # only `cap` tokens dispatched to expert 0
    assert float(jnp.sum(jnp.abs(disp[0]))) > 0
    assert float(jnp.sum(comb)) <= cap * 0.9 + 1e-6
