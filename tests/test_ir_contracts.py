"""Tier-1 CI gate: the hlolint IR contracts hold on the compiled programs.

Lowers the serving engine's unified ragged step program at every width
bucket (w1/w4/w8 on the harness config) at tp=1 and tp=2 on the
8-fake-device host mesh plus the spmd train step — all on the smallest
GPT that still exercises tp sharding — and checks:

- zero contract violations on main (collective budget, donation
  aliasing, host-sync hygiene, program-shape baseline);
- the SEEDED regressions trip: a deliberately qkv-major (pre-PR-10)
  fused-QKV layout blows the tp=2 all-gather budget, and ungated
  ``donate_argnums`` on the cpu host-platform mesh blows the donation
  contract — both with messages naming the contract and the offending
  HLO facts;
- the HLO-text parsing schema canary: a trivial jitted psum on the fake
  mesh must parse to the expected op names, so a jax lowering-format
  drift fails HERE with a pointed message instead of letting every
  contract pass vacuously;
- the CLI: --ir without jax exits 2, --select/--ignore span both layers.
"""
import dataclasses
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import contracts, ir
from paddle_tpu.serving.sharded import serving_collective_budget

_build_s = []


@pytest.fixture(scope="module")
def artifacts():
    paddle.seed(0)
    t0 = time.perf_counter()
    arts = ir.default_artifacts()
    _build_s.append(time.perf_counter() - t0)
    return arts


# ---------------------------------------------------------------------------
# main is clean


def test_main_is_contract_clean(artifacts):
    violations = contracts.evaluate(artifacts)
    assert violations == [], (
        "IR contract violations (if a budget moved legitimately, rerun "
        "`python -m paddle_tpu.analysis --ir --update-baseline` and "
        "commit ir_baseline.json with the change that moved it):\n"
        + "\n".join(v.format() for v in violations))


def test_program_set_covers_the_registry(artifacts):
    from paddle_tpu.analysis.ir import build_serving_engine, tiny_gpt_config
    from paddle_tpu.models.gpt import GPT

    eng = build_serving_engine(GPT(tiny_gpt_config()), 1)
    names = {a.name for a in artifacts}
    want = {f"serve/tp{tp}/{name}"
            for tp in (1, 2) for name in eng.step_program_shapes()}
    want |= {f"serve/tp{tp}/{name}"
             for tp in (1, 2) for name in eng.swap_program_shapes()}
    # the int8 end-to-end family: w1 decode + the 4-array swap pair
    want |= {f"serve_int8/tp{tp}/w1" for tp in (1, 2)}
    want |= {f"serve_int8/tp{tp}/{name}"
             for tp in (1, 2) for name in eng.swap_program_shapes()}
    # the LoRA family: w1 decode with 2 adapter slots gathered in-step
    want |= {f"serve_lora/tp{tp}/w1" for tp in (1, 2)}
    # the train/* family: legacy dp2 x mp2, the locked zs2-legacy
    # 'before', and the explicit weight-update matrix on dp4
    train_names = {"train/dp2_mp2", "train/dp2_mp2/zs2-legacy",
                   "train/dp4/zs0", "train/dp4/zs2", "train/dp4/zs3",
                   "train/dp4/zs2_gm2", "train/dp4/zs2_q8"}
    want |= train_names
    # one artifact per ragged width bucket plus the host-tier swap pair
    # (x2 for the int8 family's w1 + swaps, +2 for serve_lora's w1) —
    # the engine helpers are the ONE place the program-count contract
    # lives
    assert len(want) == (2 * eng.expected_program_count()
                         + 4 * len(eng.swap_program_shapes()) + 2 + 2
                         + len(train_names))
    assert names == want, names


def test_gate_stays_under_budget(artifacts):
    # the whole lower+compile pass must stay cheap enough for tier-1;
    # budget raised 45s -> 95s with the PR 19 train/* family (7 train
    # programs at ~6s each lock the explicit ZeRO collective shapes —
    # paid for by slow-marking heavier always-on tests the same PR)
    assert _build_s[0] < 95.0, (
        f"hlolint program set took {_build_s[0]:.1f}s to lower+compile "
        "(budget 95s) — shrink the tiny config or trim the registry")


def test_tp2_collectives_match_the_layout_budget(artifacts):
    by_name = {a.name: a for a in artifacts}
    tp2 = by_name["serve/tp2/w1"]
    assert tp2.collectives == serving_collective_budget(
        ir.tiny_gpt_config(), 2)
    # 2 output projections per layer + the vocab-parallel embedding psum
    assert tp2.collectives["all-reduce"] == 2 * 2 + 1
    # exactly ONE all-gather: the sampler-boundary logit materialization
    assert tp2.collectives["all-gather"] == 1
    for name in ("w4", "w8"):
        assert by_name[f"serve/tp2/{name}"].collectives == tp2.collectives
    for name in ("w1", "w4", "w8"):
        assert not any(by_name[f"serve/tp1/{name}"].collectives.values())


def test_int8_tp2_collectives_match_the_quantized_budget(artifacts):
    """EQuARX per-op gating, locked by IR001: with both RowParallel
    projections quantized, each f32 all-reduce becomes an int8-payload
    all-gather + f32-scalar all-gather pair — 2L quantized ops leave
    exactly ONE f32 all-reduce (the vocab-parallel embedding psum) and
    2*2*L+1 all-gathers (incl. the sampler boundary)."""
    by_name = {a.name: a for a in artifacts}
    q = by_name["serve_int8/tp2/w1"]
    assert q.collectives == serving_collective_budget(
        ir.tiny_gpt_config(), 2, quant_collectives=("attn_proj",
                                                    "ffn_fc2"))
    assert q.collectives["all-reduce"] == 1
    assert q.collectives["all-gather"] == 2 * 2 * 2 + 1
    # single-chip int8: no collectives at all, like the f32 family
    assert not any(by_name["serve_int8/tp1/w1"].collectives.values())


def test_lora_family_adds_zero_collectives(artifacts):
    """The serve_lora IR001 pin: the in-step adapter gather must add NO
    collectives at any tp degree — A tables replicate, B tables shard on
    the already-tp-sharded output axis, and the per-row gather + two
    rank-r matmuls are chip-local. The budget is therefore the SAME
    arithmetic `serving_collective_budget` as the base family; a LoRA
    refactor that starts re-gathering adapter shards (or all-reducing
    the delta separately from the base projection) busts IR001 here."""
    by_name = {a.name: a for a in artifacts}
    for tp in (1, 2):
        base = by_name[f"serve/tp{tp}/w1"]
        lora = by_name[f"serve_lora/tp{tp}/w1"]
        assert lora.collectives == base.collectives, (tp, lora.collectives)
        # the adapter gather is REAL work, not a no-op: IR004 locks the
        # flops/bytes delta via serve_lora's own baseline entries
        assert lora.facts["flops"] > base.facts["flops"], tp
        assert (lora.facts["bytes_accessed"]
                > base.facts["bytes_accessed"]), tp
    assert not any(by_name["serve_lora/tp1/w1"].collectives.values())
    assert by_name["serve_lora/tp2/w1"].collectives == (
        serving_collective_budget(ir.tiny_gpt_config(), 2))


def test_int8_step_reads_fewer_bytes(artifacts):
    """The perf claim behind the int8 arena, checked on XLA's own cost
    model: the quantized decode step accesses fewer bytes than the f32
    program at the same (B, W) — the attention working set quarters and
    the scale sidecar must not eat the win."""
    by_name = {a.name: a for a in artifacts}
    for tp in (1, 2):
        f32 = by_name[f"serve/tp{tp}/w1"].facts["bytes_accessed"]
        q = by_name[f"serve_int8/tp{tp}/w1"].facts["bytes_accessed"]
        assert q < f32, (tp, q, f32)
    # and the host-tier swap copies move ~4x fewer bytes per block
    for tp in (1, 2):
        f32 = by_name[f"serve/tp{tp}/swap_out"].facts["bytes_accessed"]
        q = by_name[f"serve_int8/tp{tp}/swap_out"].facts["bytes_accessed"]
        assert q < 0.5 * f32, (tp, q, f32)


def test_donation_aliases_match_the_gate(artifacts):
    """tp=1 donates unconditionally: the arena inputs must actually
    alias. tp=2 on the cpu host platform is gated OFF: nothing may
    alias (the PR 3 miscompile is outputs aliasing freed inputs)."""
    for a in artifacts:
        if not a.name.startswith("serve/tp1/"):
            continue
        don = a.expected["donation"]
        if a.kind == "swap_out":
            # the gather's arena inputs stay live: NOTHING may alias
            assert don["expected"] is False
            assert a.aliases == [], (a.name, a.aliases)
            continue
        assert don["expected"] is True
        aliased = {al.param_number for al in a.aliases}
        assert set(don["param_indices"]) <= aliased, (a.name, a.aliases)
        # and the aliased outputs are the updated arenas, not the tokens
        outs = {al.output_index[0] for al in a.aliases}
        assert outs == set(don["output_indices"]), (a.name, a.aliases)
    for a in artifacts:
        if a.name.startswith("serve/tp2/") or a.kind == "train":
            assert a.expected["donation"]["expected"] is False
            assert a.aliases == [], (a.name, a.aliases)


# ---------------------------------------------------------------------------
# seeded regressions: the two incidents the checker exists to catch


def _qkv_major_split(qkv, b, s, num_heads, head_dim):
    """The pre-PR-10 layout: all Q heads first. A contiguous tp shard of
    the fused 3h axis is then NOT a head group, so XLA must re-gather
    the sharded axis inside every layer."""
    from paddle_tpu.ops import manipulation as M

    qkv = M.reshape(qkv, [b, s, 3, num_heads, head_dim])
    q = M.squeeze(M.slice(qkv, [2], [0], [1]), 2)
    k = M.squeeze(M.slice(qkv, [2], [1], [2]), 2)
    v = M.squeeze(M.slice(qkv, [2], [2], [3]), 2)
    return q, k, v


def test_qkv_major_layout_trips_the_all_gather_budget(monkeypatch):
    from paddle_tpu.models import gpt as gpt_mod

    monkeypatch.setattr(gpt_mod, "_split_fused_qkv", _qkv_major_split)
    arts = ir.serving_artifacts(tp_degrees=(2,), kinds=["w1"])
    (art,) = arts
    assert art.collectives["all-gather"] > 1, art.collectives
    violations = contracts.evaluate(arts, select=["IR001"])
    assert violations, "qkv-major regroup must blow the collective budget"
    msg = violations[0].format()
    assert "IR001" in msg and "collective-budget" in msg
    assert "all-gather" in msg
    # the message names the offending HLO ops so the diff author sees
    # WHERE the re-gather got inserted
    assert "offending HLO ops" in msg and "all-gather" in msg, msg


def test_ungated_donation_trips_the_donation_contract(monkeypatch):
    from paddle_tpu.parallel import spmd

    monkeypatch.setattr(spmd, "mesh_donate_argnums",
                        lambda argnums: tuple(argnums))
    arts = ir.serving_artifacts(tp_degrees=(2,), kinds=["w1"])
    (art,) = arts
    assert art.aliases, "ungated donation should alias on the host mesh"
    violations = contracts.evaluate(arts, select=["IR002"])
    assert violations, "ungated sharded donation must trip IR002"
    msg = violations[0].format()
    assert "IR002" in msg and "donation-verified" in msg
    assert "input_output_alias" in msg and "param" in msg, msg


def test_silently_disabled_equarx_gate_trips_the_quantized_budget(
        monkeypatch):
    """The int8 family's IR001 is a REGRESSION tripwire, not just a
    description: if the per-op quantization hook stops firing (here:
    `_serving_row_parallel` patched back to a plain layer call — the
    shape of a refactor that loses the gate), the engine still REPORTS
    quantized collectives, the budget still expects the all-gather
    pairs, and the now-f32 program must fail the contract instead of
    silently serving unquantized."""
    from paddle_tpu.models import gpt as gpt_mod

    monkeypatch.setattr(gpt_mod, "_serving_row_parallel",
                        lambda layer, x, op_name, cache: layer(x))
    arts = ir.serving_artifacts(tp_degrees=(2,), kinds=["w1"],
                                kv_dtype="int8", quant_allreduce=True,
                                prefix="serve_int8")
    (art,) = arts
    # the broken gate falls back to plain psum all-reduces
    assert art.collectives["all-reduce"] > 1, art.collectives
    violations = contracts.evaluate(arts, select=["IR001"])
    assert violations, "a disabled EQuARX gate must blow the budget"
    msg = violations[0].format()
    assert "IR001" in msg and "collective-budget" in msg, msg


def test_hoisted_adapter_gather_trips_host_sync_hygiene(monkeypatch):
    """The serve_lora seeded regression: an adapter gather hoisted out
    of the compiled step onto the host (here: `gather_adapter_rows`
    patched to a `jax.pure_callback` row lookup — the shape of a
    refactor that 'simplifies' the per-row gather into a host-side
    table read) reintroduces a per-step device→host round trip. The
    callback's custom-call lands at its use site, upstream of the
    LM-head matmul, so IR003's whole-program hygiene flags it (IR005's
    sampler-tail check is the backstop had it landed after the head);
    the message must name the callback target so the diff author sees
    WHAT synced."""
    import jax

    from paddle_tpu.models import lora as lora_mod

    def hoisted_gather(tables, slots):
        if not tables:
            return None
        out = {}
        for name, (A, B) in tables.items():
            out[name] = tuple(
                jax.pure_callback(
                    lambda t, s: np.asarray(t)[np.asarray(s)],
                    jax.ShapeDtypeStruct(
                        (slots.shape[0],) + tab.shape[1:], tab.dtype),
                    tab, slots, vmap_method="sequential")
                for tab in (A, B))
        return out

    monkeypatch.setattr(lora_mod, "gather_adapter_rows", hoisted_gather)
    arts = ir.serving_artifacts(tp_degrees=(1,), kinds=["w1"],
                                lora_slots=2, prefix="serve_lora")
    (art,) = arts
    assert any(op.custom_call_target == "xla_python_cpu_callback"
               for op in art.ops
               if op.opcode.startswith("custom-call")), art.name
    violations = contracts.evaluate(arts, select=["IR003", "IR005"])
    assert violations, "a host-hoisted adapter gather must trip hygiene"
    msg = violations[0].format()
    assert "IR003" in msg and "host-sync-hygiene" in msg, msg
    assert "xla_python_cpu_callback" in msg, msg


# ---------------------------------------------------------------------------
# cheap contract-unit checks (hand-built artifacts, no lowering)


def _fake_artifact(**kw):
    base = dict(name="serve/tp2/decode", kind="decode", tp_degree=2,
                backend="cpu", hlo_text="", ops=[], aliases=[],
                facts={}, expected={})
    base.update(kw)
    return ir.ProgramArtifact(**base)


def test_host_sync_hygiene_contract_flags_unsanctioned_custom_call():
    op = ir.HloOp(opcode="custom-call", result_type="f32[2]", line=7,
                  op_name="jit(step)/jit(main)/pure_callback",
                  custom_call_target="xla_python_cpu_callback",
                  text="custom-call(...)")
    art = _fake_artifact(ops=[op])
    violations = contracts.evaluate([art], select=["IR003"], baseline={})
    assert len(violations) == 1
    assert "xla_python_cpu_callback" in violations[0].message
    # whitelisted targets (the Pallas kernel, SPMD plumbing) pass
    ok = ir.HloOp(opcode="custom-call", result_type="f32[2]", line=7,
                  op_name="x", custom_call_target="tpu_custom_call",
                  text="custom-call(...)")
    assert contracts.evaluate([_fake_artifact(ops=[ok])],
                              select=["IR003"], baseline={}) == []


def test_sampler_fused_contract_flags_host_call_after_lm_head():
    """IR005: a host custom-call BETWEEN attention/LM-head and token
    emission (a callback-based sampler, say) trips the contract; the
    same call before the last matmul — or in a program with no sampler
    region (train) — does not."""
    def mm(line):
        return ir.HloOp(opcode="dot-general", result_type="f32[2,2]",
                        line=line, op_name="jit(step)/dot_general",
                        custom_call_target=None, text="dot-general(...)")

    def cb(line):
        return ir.HloOp(opcode="custom-call", result_type="s32[2]",
                        line=line,
                        op_name="jit(step)/jit(main)/pure_callback",
                        custom_call_target="xla_python_cpu_callback",
                        text="custom-call(...)")

    sampler_tail_call = _fake_artifact(
        ops=[mm(1), mm(2), cb(3)], expected={"sampler_region": True})
    violations = contracts.evaluate([sampler_tail_call], select=["IR005"],
                                    baseline={})
    assert len(violations) == 1
    msg = violations[0].format()
    assert "IR005" in msg and "sampler-fused" in msg
    assert "between attention and token emission" in msg
    # the same call BEFORE the last matmul is attention-side plumbing,
    # not a sampler host sync (IR003's whitelist governs it)
    pre = _fake_artifact(ops=[mm(1), cb(2), mm(3)],
                         expected={"sampler_region": True})
    assert contracts.evaluate([pre], select=["IR005"], baseline={}) == []
    # GSPMD annotation calls in the tail are tolerated
    ann = ir.HloOp(opcode="custom-call", result_type="f32[2]", line=3,
                   op_name="x", custom_call_target="Sharding",
                   text="custom-call(...)")
    tol = _fake_artifact(ops=[mm(1), mm(2), ann],
                         expected={"sampler_region": True})
    assert contracts.evaluate([tol], select=["IR005"], baseline={}) == []
    # programs without a sampler region (train) skip the contract
    train = _fake_artifact(ops=[mm(1), cb(2)], expected={})
    assert contracts.evaluate([train], select=["IR005"], baseline={}) == []


def test_donation_contract_flags_wrong_output_alias():
    """Aliasing SOMEWHERE is not enough: a donated arena routed to the
    wrong output (in-place reuse of the sampled-tokens buffer, say) must
    trip IR002 even though the param number appears in the alias map."""
    don = {"expected": True, "param_indices": (10, 11),
           "output_indices": (2, 3), "what": "KV arena (k, v)"}
    right = [ir.Alias(output_index=(2,), param_number=10, kind="must-alias"),
             ir.Alias(output_index=(3,), param_number=11, kind="must-alias")]
    art = _fake_artifact(aliases=right, expected={"donation": don})
    assert contracts.evaluate([art], select=["IR002"], baseline={}) == []
    wrong = [ir.Alias(output_index=(0,), param_number=10, kind="must-alias"),
             ir.Alias(output_index=(3,), param_number=11, kind="must-alias")]
    art = _fake_artifact(aliases=wrong, expected={"donation": don})
    violations = contracts.evaluate([art], select=["IR002"], baseline={})
    assert len(violations) == 1
    msg = violations[0].message
    assert "parameter 10" in msg and "output 0" in msg and "2" in msg


def test_baseline_contract_flags_drift_and_missing_programs(artifacts):
    art = artifacts[0]
    drifted = dataclasses.replace(
        art, facts={k: v * 3 for k, v in art.facts.items()})
    violations = contracts.evaluate([drifted], select=["IR004"])
    assert violations and "drifted" in violations[0].message
    unknown = dataclasses.replace(art, name="serve/tp2/nonesuch")
    violations = contracts.evaluate([unknown], select=["IR004"])
    assert violations and "no recorded baseline" in violations[0].message
    # a missing/unreadable baseline FILE must not silently disable the
    # contract (a wheel without the package-data entry would otherwise be
    # a permanent false green) — it reports every program as unrecorded
    violations = contracts.evaluate([art], select=["IR004"], baseline={})
    assert violations and "no recorded baseline" in violations[0].message


# ---------------------------------------------------------------------------
# schema canary: HLO-text parsing vs jax lowering-format drift


def test_hlo_parser_schema_canary():
    """Lower a trivial jitted psum on the fake mesh and assert the
    parser extracts the expected op names — if jax/XLA ever change the
    compiled-HLO text format, THIS fails with a pointed message instead
    of every contract passing vacuously on empty parses."""
    import jax
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.asarray(jax.devices()[:2]), ("tp",))
    fn = jax.jit(shard_map(lambda x: jax.lax.psum(x, "tp"), mesh=mesh,
                           in_specs=P("tp"), out_specs=P()))
    comp = fn.lower(jax.ShapeDtypeStruct((8,), jnp.float32)).compile()
    text = comp.as_text()
    ops = ir.parse_hlo_ops(text)
    drift = ("jax lowering-format drift: analysis/ir.py's HLO-text "
             "parser no longer extracts %s from a trivial jitted psum — "
             "fix the parser or every IR contract passes vacuously")
    assert ops, drift % "any instructions"
    counts = ir.collective_counts(ops)
    assert counts["all-reduce"] >= 1, drift % "the psum's all-reduce"
    ar = next(o for o in ops if ir._base_opcode(o.opcode) == "all-reduce")
    assert ar.result_type.startswith("f32"), drift % "result types"
    assert any(o.op_name for o in ops), drift % "op_name metadata"

    donated = jax.jit(lambda a: a * 2.0, donate_argnums=(0,))
    dcomp = donated.lower(
        jax.ShapeDtypeStruct((128,), jnp.float32)).compile()
    aliases = ir.parse_input_output_aliases(dcomp.as_text())
    assert [a.param_number for a in aliases] == [0], (
        drift % "the input_output_alias map")
    facts = ir.extract_facts(dcomp)
    assert facts.get("flops", 0) > 0, drift % "cost_analysis flops"
    assert facts.get("peak_bytes", 0) > 0, drift % "memory_analysis sizes"


# ---------------------------------------------------------------------------
# CLI: both layers behind one command


def test_cli_ir_without_jax_exits_2(capsys, monkeypatch):
    from paddle_tpu.analysis import cli

    def broken_import():
        raise ImportError("No module named 'jax'")

    monkeypatch.setattr(cli, "_import_jax", broken_import)
    assert cli.main(["--ir"]) == 2
    err = capsys.readouterr().err
    assert "jax" in err and "--ir" in err
    # the AST-only path stays stdlib-pure and fully functional
    monkeypatch.undo()
    assert cli.main(["--update-baseline"]) == 2  # requires --ir
    capsys.readouterr()
    # a contract-only --select without --ir must be a usage error, not a
    # run of NEITHER layer that exits 0 (a false green in a CI job that
    # dropped the flag)
    assert cli.main(["--select", "IR001"]) == 2
    assert "--ir" in capsys.readouterr().err
    # same class: a typo'd id prefix must not silently run neither layer
    assert cli.main(["--select", "JK001"]) == 2
    assert "JK001" in capsys.readouterr().err
    assert cli.main(["--ignore", "XX999"]) == 2
    assert "XX999" in capsys.readouterr().err
    # and a correctly-prefixed but NONEXISTENT id (IR01 typo of IR001)
    # must not select zero contracts and exit 0 — validate against the
    # catalog, not the prefix
    assert cli.main(["--select", "IR01"]) == 2
    assert "IR01" in capsys.readouterr().err
    assert cli.main(["--ignore", "JL999"]) == 2
    assert "JL999" in capsys.readouterr().err
    # a typo'd explicit path must exit 2 even when an IR-only --select
    # skips the AST sweep that would have read it — not silently pass
    # having checked nothing at that path (returns before any lowering,
    # so this costs no compile time)
    assert cli.main(["--ir", "--select", "IR001",
                     "/no/such/paddle_tpu_path"]) == 2
    assert "no such path" in capsys.readouterr().err


def test_cli_harness_errors_exit_2_but_program_failures_propagate(
        capsys, monkeypatch):
    """Only usage-shaped harness failures (IRHarnessError, OSError) map
    to exit 2; a genuine lowering/compile failure of a registered
    program — jax's XlaRuntimeError is also a RuntimeError subclass —
    must propagate with its traceback instead of masquerading as a
    misconfigured invocation a CI wrapper might skip."""
    from paddle_tpu.analysis import cli

    def harness_broken(args, ir_select, ir_ignore, record_only=False):
        raise ir.IRHarnessError("backend has 1 device")

    monkeypatch.setattr(cli, "_run_ir", harness_broken)
    assert cli.main(["--ir", "--select", "IR001"]) == 2
    assert "1 device" in capsys.readouterr().err

    class FakeXlaRuntimeError(RuntimeError):
        pass

    def program_broken(args, ir_select, ir_ignore, record_only=False):
        raise FakeXlaRuntimeError("INTERNAL: program failed to compile")

    monkeypatch.setattr(cli, "_run_ir", program_broken)
    with pytest.raises(FakeXlaRuntimeError):
        cli.main(["--ir", "--select", "IR001"])


def test_cli_select_and_ignore_span_both_layers(capsys, monkeypatch,
                                                artifacts):
    from paddle_tpu.analysis import cli

    # reuse the module fixture's artifacts so the CLI test costs no
    # second lowering pass
    monkeypatch.setattr(ir, "default_artifacts", lambda: artifacts)
    assert cli.main(["--ir", "--select", "IR001,IR002,IR003", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ir"]["summary"]["programs"] == len(artifacts)
    assert doc["ir"]["summary"]["violations"] == 0
    # an IR-only select skips the AST sweep (0 files linted)
    assert doc["summary"]["files"] == 0
    # per-program facts + collectives ride on the JSON line
    names = {p["name"] for p in doc["ir"]["programs"]}
    assert "serve/tp2/w1" in names
    p = next(p for p in doc["ir"]["programs"]
             if p["name"] == "serve/tp2/w1")
    assert p["collectives"]["all-reduce"] == 5
    assert {"flops", "bytes_accessed", "peak_bytes"} <= set(p["facts"])
    # ignoring every contract leaves the IR layer green trivially
    assert cli.main(["--ir", "--ignore",
                     "IR001,IR002,IR003,IR004,IR005"]) == 0
    capsys.readouterr()
    # a JL-only select skips the IR layer even with --ir: no "ir" key
    assert cli.main(["--ir", "--select", "JL008", "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert "ir" not in doc
    assert cli.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "IR001" in out and "JL008" in out


def test_cli_update_baseline_respects_jl_only_select(capsys, monkeypatch,
                                                     artifacts, tmp_path):
    """--update-baseline forced the IR layer on so the artifacts exist to
    record from, but a JL-only --select still means "skip this layer's
    CHECKS": the baseline is written and no contract evaluates (an IR004
    drift between the old and new baseline must not flip the exit)."""
    from paddle_tpu.analysis import cli

    monkeypatch.setattr(ir, "default_artifacts", lambda: artifacts)
    path = tmp_path / "ir_baseline.json"
    monkeypatch.setattr(contracts, "BASELINE_PATH", str(path))
    assert cli.main(["--ir", "--update-baseline", "--select", "JL008",
                     "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ir"]["summary"]["violations"] == 0
    recorded = json.loads(path.read_text())
    assert set(recorded["programs"]) == {a.name for a in artifacts}
