"""AdaRound learned rounding (reference static/quantization/adaround.py:113).

The acceptance criterion mirrors the paper/reference: on the layer's own
calibration data, learned rounding reconstructs the float layer's outputs at
LOWER error than round-to-nearest.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.quantization import PTQ, QuantConfig, QuantedLinear
from paddle_tpu.quantization.adaround import adaround_linear


def test_adaround_beats_nearest_on_linear():
    paddle.seed(0)
    rs = np.random.RandomState(0)
    lin = nn.Linear(32, 16)
    sub = QuantedLinear(lin)
    xs = [rs.rand(64, 32).astype(np.float32) for _ in range(4)]

    w = np.asarray(lin.weight._array, np.float32)
    b = np.asarray(lin.bias._array, np.float32)
    w_qmax = 127.0
    scales = np.maximum(np.abs(w).max(axis=0), 1e-8)

    q_learned, _ = adaround_linear(sub, xs, w_qmax, iters=250)
    q_nearest = np.clip(np.round(w / scales[None] * w_qmax), -w_qmax, w_qmax)

    # learned grid stays on the integer lattice, within +-1 of nearest
    assert np.all(np.abs(q_learned - np.round(q_learned)) < 1e-5)
    assert np.abs(q_learned - q_nearest).max() <= 1.0 + 1e-5

    def out_err(q):
        wq = q * scales[None] / w_qmax
        errs = [
            np.mean((x @ wq + b - (x @ w + b)) ** 2) for x in xs
        ]
        return float(np.mean(errs))

    e_learned = out_err(q_learned)
    e_nearest = out_err(q_nearest)
    assert e_learned < e_nearest, (e_learned, e_nearest)


@pytest.mark.slow
def test_ptq_adaround_end_to_end_lenet():
    from paddle_tpu.vision.models import LeNet

    paddle.seed(3)
    rs = np.random.RandomState(0)
    X = rs.rand(32, 1, 28, 28).astype(np.float32)
    calib = [paddle.to_tensor(X[i * 8 : (i + 1) * 8]) for i in range(4)]

    def build_quanted():
        paddle.seed(3)
        net = LeNet()
        ptq = PTQ(QuantConfig())
        ptq.quantize(net)
        for b in calib:
            net(b)
        return net, ptq

    paddle.seed(3)
    ref = LeNet()
    ref_logits = np.asarray(ref(paddle.to_tensor(X))._array)

    net_n, ptq_n = build_quanted()
    nearest = ptq_n.convert(net_n)
    near_logits = np.asarray(nearest(paddle.to_tensor(X))._array)

    net_a, ptq_a = build_quanted()
    ada = ptq_a.convert(net_a, round_type="adaround", calib_data=calib,
                        adaround_iters=150)
    ada_logits = np.asarray(ada(paddle.to_tensor(X))._array)

    e_near = float(np.mean((near_logits - ref_logits) ** 2))
    e_ada = float(np.mean((ada_logits - ref_logits) ** 2))
    # per-layer reconstruction is the adaround objective; end to end it must
    # at least not regress (and typically improves)
    assert e_ada <= e_near * 1.05, (e_ada, e_near)
    # and stays a faithful int8 model
    denom = max(np.abs(ref_logits).max(), 1.0)
    assert np.abs(ada_logits - ref_logits).max() / denom < 0.2


def test_adaround_requires_calib_data():
    net = nn.Sequential(nn.Linear(4, 4))
    ptq = PTQ(QuantConfig())
    ptq.quantize(net)
    with pytest.raises(ValueError, match="calib_data"):
        ptq.convert(net, round_type="adaround")


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
