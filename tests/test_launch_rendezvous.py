"""Launcher multi-node rendezvous (VERDICT r3 item 8): two 'nodes' (local
launch processes) must resolve ranks, the peer endpoint table and the per-job
RPC authkey through the rank-0 TCPStore WITHOUT any pre-set rank/endpoint env.

Reference: launch/controllers/master.py:65 (HTTP master), :177 (etcd).

The master port is picked dynamically per attempt (the old fixed 29780
collided with unrelated listeners under concurrent bench load — the PR 14
flake), and a collision-shaped failure retries on a fresh port instead of
failing the run: the property under test is the rendezvous protocol, not
this host's port map.
"""
import json
import os
import socket
import subprocess
import sys
import tempfile

import numpy as np

# rendezvous (main.py _RDZV_PORT_OFFSET): the TCPStore listens at
# master_port + 5, and per-rank trainer endpoints at master_port + 100+r
# — the whole window must be free, not just the coordinator port
_PORT_SPAN = (0, 5, 100, 101)


def _free_master_port():
    """A master port whose rendezvous-derived port window is currently
    free. Best-effort (another process may grab one between probe and
    bind) — the caller retries with a fresh pick on failure."""
    for _ in range(64):
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        if base + 101 > 65535:
            continue
        try:
            for off in _PORT_SPAN[1:]:
                with socket.socket() as probe:
                    probe.bind(("127.0.0.1", base + off))
            return base
        except OSError:
            continue
    raise RuntimeError("no free rendezvous port window found")


def _run_rendezvous_once(td, script, env, port):
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--master", f"127.0.0.1:{port}", "--nnodes", "2",
             "--log_dir", os.path.join(td, f"log{i}"), "--", script],
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out))
    return outs


def test_two_nodes_rendezvous_without_preset_env():
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "probe.py")
        with open(script, "w") as f:
            f.write(
                "import os, json\n"
                "print('PROBE ' + json.dumps({\n"
                "    'rank': os.environ.get('PADDLE_TRAINER_ID'),\n"
                "    'eps': os.environ.get('PADDLE_TRAINER_ENDPOINTS'),\n"
                "    'key': os.environ.get('PADDLE_RPC_AUTHKEY'),\n"
                "}))\n"
            )
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PADDLE_", "NODE_RANK"))}
        env["JAX_PLATFORMS"] = "cpu"
        # sitecustomize ignores JAX_PLATFORMS; the package-level override is
        # what actually keeps launch children off the (possibly dead) tunnel
        env["PADDLE_TPU_PLATFORM"] = "cpu"
        outs = None
        for attempt in range(3):
            run_dir = os.path.join(td, f"try{attempt}")
            os.makedirs(run_dir)
            outs = _run_rendezvous_once(
                run_dir, script, env, _free_master_port())
            if all(rc == 0 for rc, _ in outs):
                td_run = run_dir
                break
            # a lost port race looks like a nonzero exit with a
            # connect/bind complaint — retry on a fresh window; any
            # OTHER failure is the protocol breaking and must surface
            combined = "\n".join(out for _, out in outs).lower()
            if not any(s in combined for s in
                       ("address already in use", "connection refused",
                        "timed out", "timeout")):
                break
        for rc, out in outs:
            assert rc == 0, out[-2000:]

        probes = []
        for i in range(2):
            log_root = os.path.join(td_run, f"log{i}")
            text = ""
            for fn in os.listdir(log_root):
                with open(os.path.join(log_root, fn)) as f:
                    text += f.read()
            line = [l for l in text.splitlines() if l.startswith("PROBE ")]
            assert line, text
            probes.append(json.loads(line[0][len("PROBE "):]))
        ranks = sorted(p["rank"] for p in probes)
        assert ranks == ["0", "1"], probes
        # both resolved the SAME two-entry endpoint table and authkey
        assert probes[0]["eps"] == probes[1]["eps"]
        assert len(probes[0]["eps"].split(",")) == 2
        assert probes[0]["key"] == probes[1]["key"]
        assert len(probes[0]["key"]) == 32
