"""Launcher multi-node rendezvous (VERDICT r3 item 8): two 'nodes' (local
launch processes) must resolve ranks, the peer endpoint table and the per-job
RPC authkey through the rank-0 TCPStore WITHOUT any pre-set rank/endpoint env.

Reference: launch/controllers/master.py:65 (HTTP master), :177 (etcd).
"""
import os
import subprocess
import sys
import tempfile

import numpy as np


def test_two_nodes_rendezvous_without_preset_env():
    with tempfile.TemporaryDirectory() as td:
        script = os.path.join(td, "probe.py")
        with open(script, "w") as f:
            f.write(
                "import os, json\n"
                "print('PROBE ' + json.dumps({\n"
                "    'rank': os.environ.get('PADDLE_TRAINER_ID'),\n"
                "    'eps': os.environ.get('PADDLE_TRAINER_ENDPOINTS'),\n"
                "    'key': os.environ.get('PADDLE_RPC_AUTHKEY'),\n"
                "}))\n"
            )
        env = {k: v for k, v in os.environ.items()
               if not k.startswith(("PADDLE_", "NODE_RANK"))}
        env["JAX_PLATFORMS"] = "cpu"
        # sitecustomize ignores JAX_PLATFORMS; the package-level override is
        # what actually keeps launch children off the (possibly dead) tunnel
        env["PADDLE_TPU_PLATFORM"] = "cpu"
        procs = [
            subprocess.Popen(
                [sys.executable, "-m", "paddle_tpu.distributed.launch",
                 "--master", "127.0.0.1:29780", "--nnodes", "2",
                 "--log_dir", os.path.join(td, f"log{i}"), "--", script],
                env=env, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            )
            for i in range(2)
        ]
        outs = []
        for p in procs:
            out, _ = p.communicate(timeout=180)
            assert p.returncode == 0, out[-2000:]
            outs.append(out)
        import json

        probes = []
        for i in range(2):
            log_root = os.path.join(td, f"log{i}")
            text = ""
            for fn in os.listdir(log_root):
                with open(os.path.join(log_root, fn)) as f:
                    text += f.read()
            line = [l for l in text.splitlines() if l.startswith("PROBE ")]
            assert line, text
            probes.append(json.loads(line[0][len("PROBE "):]))
        ranks = sorted(p["rank"] for p in probes)
        assert ranks == ["0", "1"], probes
        # both resolved the SAME two-entry endpoint table and authkey
        assert probes[0]["eps"] == probes[1]["eps"]
        assert len(probes[0]["eps"].split(",")) == 2
        assert probes[0]["key"] == probes[1]["key"]
        assert len(probes[0]["key"]) == 32
