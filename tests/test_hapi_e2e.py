"""End-to-end hapi Model tests (the reference's north-star config 1:
LeNet/MNIST via Model.fit — BASELINE.json)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.metric import Accuracy
from paddle_tpu.vision.datasets import MNIST
from paddle_tpu.vision.models import LeNet


def _fit_lenet(epochs=3, compiled=True):
    paddle.seed(0)
    net = LeNet()
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy(), compiled=compiled)
    train = MNIST(mode="train")
    train.n = 256
    model.fit(train, epochs=epochs, batch_size=64, verbose=0)
    test = MNIST(mode="test")
    test.n = 128
    return model, model.evaluate(test, batch_size=64, verbose=0)


def test_lenet_mnist_convergence():
    model, res = _fit_lenet(epochs=4)
    assert res["acc"] > 0.9, res
    assert res["loss"] < 0.5


def test_eager_adapter_matches():
    model, res = _fit_lenet(epochs=2, compiled=False)
    # mechanism test (tape path), not a convergence benchmark: 2 epochs on
    # 256 samples must beat chance (0.1) clearly
    assert res["acc"] > 0.45, res


def test_train_batch_api():
    net = LeNet()
    model = paddle.Model(net)
    opt = paddle.optimizer.SGD(learning_rate=0.01, parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss())
    x = np.random.rand(8, 1, 28, 28).astype(np.float32)
    y = np.random.randint(0, 10, (8, 1))
    loss1 = model.train_batch([x], [y])
    loss2 = model.train_batch([x], [y])
    assert loss2[0] < loss1[0]  # learning on a fixed batch


def test_predict():
    net = LeNet()
    model = paddle.Model(net)
    model.prepare()
    test = MNIST(mode="test")
    test.n = 32
    out = model.predict(test, batch_size=16, verbose=0)
    assert len(out) == 1
    assert out[0][0].shape == (16, 10)


def test_save_load(tmp_path):
    model, res = _fit_lenet(epochs=1)
    path = str(tmp_path / "ck" / "model")
    model.save(path)
    assert os.path.exists(path + ".pdparams")

    net2 = LeNet()
    model2 = paddle.Model(net2)
    opt2 = paddle.optimizer.Adam(parameters=net2.parameters())
    model2.prepare(opt2, nn.CrossEntropyLoss(), Accuracy())
    model2.load(path)
    for (k1, v1), (k2, v2) in zip(
        model.network.state_dict().items(), net2.state_dict().items()
    ):
        assert np.allclose(v1.numpy(), v2.numpy(), atol=1e-6)


def test_save_resume_matches_uninterrupted_trajectory(tmp_path):
    """Resume parity: fit -> save -> load -> fit must equal the uninterrupted
    run exactly, including Adam moments (reference optimizer state round-trip,
    python/paddle/hapi/model.py:1732 + optimizer.state_dict)."""

    def make():
        paddle.seed(7)
        net = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
        model = paddle.Model(net)
        opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
        model.prepare(opt, nn.MSELoss())
        return model

    rs = np.random.RandomState(0)
    xs = [rs.rand(8, 4).astype(np.float32) for _ in range(6)]
    ys = [rs.rand(8, 2).astype(np.float32) for _ in range(6)]

    # uninterrupted: 6 steps
    m_ref = make()
    ref_losses = [m_ref.train_batch([x], [y])[0] for x, y in zip(xs, ys)]

    # interrupted: 3 steps, save, fresh model+optimizer, load, 3 more steps
    m1 = make()
    for x, y in zip(xs[:3], ys[:3]):
        m1.train_batch([x], [y])
    path = str(tmp_path / "resume" / "ck")
    m1.save(path)

    m2 = make()
    m2.load(path)
    resumed = [m2.train_batch([x], [y])[0] for x, y in zip(xs[3:], ys[3:])]
    for a, b in zip(resumed, ref_losses[3:]):
        assert np.allclose(a, b, rtol=1e-5, atol=1e-7), (resumed, ref_losses[3:])

    # the saved .pdopt must contain real (non-empty) slots after compiled training
    opt_sd = paddle.load(path + ".pdopt")
    slot_keys = [k for k in opt_sd if not k.startswith("@") and k != "LR_Scheduler"]
    assert slot_keys, "optimizer state_dict is empty after compiled training"
    moment1 = [k for k in slot_keys if "moment1" in k]
    assert moment1
    assert any(np.abs(opt_sd[k].numpy()).max() > 0 for k in moment1)


def test_paddle_save_load_tensors(tmp_path):
    obj = {"a": paddle.to_tensor(np.random.rand(3, 3).astype(np.float32)), "b": [1, 2]}
    p = str(tmp_path / "obj.pdt")
    paddle.save(obj, p)
    back = paddle.load(p)
    assert np.allclose(back["a"].numpy(), obj["a"].numpy())
    assert back["b"] == [1, 2]


def test_bf16_save_load(tmp_path):
    t = paddle.to_tensor(np.random.rand(4).astype(np.float32)).astype("bfloat16")
    p = str(tmp_path / "bf16.pdt")
    paddle.save({"t": t}, p)
    back = paddle.load(p)
    assert np.dtype(back["t"].dtype).name == "bfloat16"


def test_callbacks_early_stopping():
    from paddle_tpu.hapi.callbacks import EarlyStopping

    paddle.seed(0)
    net = LeNet()
    model = paddle.Model(net)
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=net.parameters())
    model.prepare(opt, nn.CrossEntropyLoss(), Accuracy())
    train = MNIST(mode="train")
    train.n = 128
    es = EarlyStopping(monitor="acc", mode="max", patience=0)
    model.fit(train, eval_data=train, epochs=3, batch_size=64, verbose=0, callbacks=[es])
    # just ensure it ran and the flag machinery works
    assert isinstance(model.stop_training, bool)


def test_summary():
    from paddle_tpu.hapi.summary import summary

    info = summary(LeNet())
    assert info["total_params"] > 40000


def test_model_fit_static_mode_matches_dynamic():
    """hapi StaticGraphAdapter (VERDICT r3 item 10): Model.prepare under
    paddle.enable_static() drives a captured Program; the fit loss
    trajectory must match dynamic mode exactly."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet

    rs = np.random.RandomState(0)
    X = rs.rand(64, 1, 28, 28).astype(np.float32)
    Y = rs.randint(0, 10, (64, 1))

    def run(static):
        paddle.seed(0)
        net = LeNet()
        model = paddle.Model(net)
        if static:
            paddle.enable_static()
        try:
            model.prepare(
                paddle.optimizer.Adam(learning_rate=1e-3,
                                      parameters=net.parameters()),
                nn.CrossEntropyLoss(),
            )
            assert (model._static_adapter is not None) == static
            losses = []
            for ep in range(2):
                for i in range(0, 64, 32):
                    out = model.train_batch(
                        [paddle.to_tensor(X[i:i + 32])],
                        [paddle.to_tensor(Y[i:i + 32])],
                    )
                    loss = out[0] if not isinstance(out, tuple) else out[0][0]
                    losses.append(float(np.asarray(loss)))
        finally:
            if static:
                paddle.disable_static()
        return losses

    dyn = run(False)
    st = run(True)
    np.testing.assert_allclose(st, dyn, rtol=2e-4, err_msg=f"{(st, dyn)}")
    assert st[-1] < st[0]
