"""Static graph: Program capture, Executor.run, control flow (VERDICT
round-2 items 6/7; reference fluid/framework.py Program, executor.py:1394,
static/nn/control_flow.py:401)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static
from paddle_tpu.static.nn import case, cond, switch_case, while_loop


class TestProgramExecutor:
    def test_capture_and_run(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 4], "float32")
            w = paddle.to_tensor(np.ones((4, 3), np.float32) * 2.0)
            y = paddle.matmul(x, w)
            z = (y + 1.0).sum()
        exe = static.Executor()
        out = exe.run(prog, feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[y, z])
        assert np.allclose(out[0], 8.0)
        assert abs(float(out[1]) - 54.0) < 1e-5

    def test_param_update_without_recompile(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [2, 2], "float32")
            w = paddle.to_tensor(np.eye(2, dtype=np.float32))
            y = paddle.matmul(x, w)
        exe = static.Executor()
        feed = {"x": np.ones((2, 2), np.float32)}
        out1 = exe.run(prog, feed=feed, fetch_list=[y])
        w.set_value(np.eye(2, dtype=np.float32) * 3.0)
        out2 = exe.run(prog, feed=feed, fetch_list=[y])
        assert np.allclose(out2[0], out1[0] * 3.0)
        assert len(exe._cache) == 1  # same executable, new weight argument

    def test_feed_shape_recompiles(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [None, 3], "float32")
            y = x * 2.0
        exe = static.Executor()
        o1 = exe.run(prog, feed={"x": np.ones((1, 3), np.float32)}, fetch_list=[y])
        o2 = exe.run(prog, feed={"x": np.ones((5, 3), np.float32)}, fetch_list=[y])
        assert o1[0].shape == (1, 3) and o2[0].shape == (5, 3)

    def test_fetch_outside_program_raises(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [1], "float32")
            _ = x + 1.0
        stray = paddle.to_tensor(np.ones(1, np.float32)) * 2  # outside guard
        with pytest.raises(ValueError, match="not produced"):
            static.Executor().run(prog, feed={"x": np.ones(1, np.float32)}, fetch_list=[stray])

    def test_default_main_program_guard(self):
        before = static.default_main_program().num_ops()
        with static.program_guard(static.default_main_program()):
            x = static.data("dmp_x", [1], "float32")
            y = x + 1.0
        assert static.default_main_program().num_ops() > before
        out = static.Executor().run(
            feed={"dmp_x": np.array([41.0], np.float32)}, fetch_list=[y]
        )
        assert np.allclose(out[0], 42.0)


class TestCond:
    def test_value_and_grad_through_taken_branch(self):
        a = paddle.to_tensor(np.array(3.0, np.float32), stop_gradient=False)
        b = paddle.to_tensor(np.array(5.0, np.float32), stop_gradient=False)
        r = cond(a < b, lambda: a * 2, lambda: b * 3)
        assert float(r.numpy()) == 6.0
        r.backward()
        assert float(a.grad.numpy()) == 2.0
        assert b.grad is None or float(b.grad.numpy()) == 0.0

    def test_false_branch(self):
        a = paddle.to_tensor(np.array(7.0, np.float32))
        b = paddle.to_tensor(np.array(5.0, np.float32))
        r = cond(a < b, lambda: a * 2, lambda: b * 3)
        assert float(r.numpy()) == 15.0

    def test_nested_structure(self):
        a = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        outs = cond(
            paddle.to_tensor(True),
            lambda: [a, a + 1],
            lambda: [a * 0, a * 0],
        )
        assert np.allclose(outs[0].numpy(), [1, 2])
        assert np.allclose(outs[1].numpy(), [2, 3])

    def test_mismatched_branches_raise(self):
        a = paddle.to_tensor(np.ones(2, np.float32))
        with pytest.raises(ValueError, match="structure|shape"):
            cond(paddle.to_tensor(True), lambda: [a], lambda: [a, a])

    def test_case_and_switch_case(self):
        a = paddle.to_tensor(np.array(1.0, np.float32))
        b = paddle.to_tensor(np.array(2.0, np.float32))
        r = case(
            [(paddle.to_tensor(False), lambda: a), (paddle.to_tensor(True), lambda: b)],
            default=lambda: a * 0,
        )
        assert float(r.numpy()) == 2.0
        r = switch_case(paddle.to_tensor(np.int32(0)), [lambda: a, lambda: b])
        assert float(r.numpy()) == 1.0
        r = switch_case(
            paddle.to_tensor(np.int32(9)), {0: (lambda: a), 1: (lambda: b)},
            default=lambda: a + b,
        )
        assert float(r.numpy()) == 3.0


class TestWhileLoop:
    def test_counts(self):
        i = paddle.to_tensor(np.array(0, np.int32))
        s = paddle.to_tensor(np.array(0.0, np.float32))
        iv, sv = while_loop(lambda i, s: i < 10, lambda i, s: [i + 1, s + 2.0], [i, s])
        assert int(iv.numpy()) == 10
        assert float(sv.numpy()) == 20.0

    def test_data_dependent_trip_count(self):
        n = paddle.to_tensor(np.array(7, np.int32))
        i = paddle.to_tensor(np.array(0, np.int32))
        v = paddle.to_tensor(np.array(1.0, np.float32))
        _, vv = while_loop(lambda i, v: i < n, lambda i, v: [i + 1, v * 2.0], [i, v])
        assert float(vv.numpy()) == 2.0**7

    def test_under_program_capture(self):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [1], "float32")
            i = paddle.to_tensor(np.array(0, np.int32))
            iv, xv = while_loop(lambda i, v: i < 5, lambda i, v: [i + 1, v * 2.0], [i, x])
        out = static.Executor().run(
            prog, feed={"x": np.array([1.5], np.float32)}, fetch_list=[xv]
        )
        assert np.allclose(out[0], 1.5 * 32)

    def test_under_to_static(self):
        from paddle_tpu import jit

        @jit.to_static
        def f(x):
            i = paddle.to_tensor(np.array(0, np.int32))
            _, out = while_loop(
                lambda i, v: i < 3, lambda i, v: [i + 1, v + v], [i, x]
            )
            return out

        x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
        assert np.allclose(f(x).numpy(), [8.0, 16.0])

    def test_body_structure_mismatch_raises(self):
        i = paddle.to_tensor(np.array(0, np.int32))
        with pytest.raises(ValueError, match="body returned"):
            while_loop(lambda i: i < 3, lambda i: [i + 1, i], [i])


def test_capture_ignores_traced_interior_ops():
    """jit-traced calls inside program_guard must not poison the op log with
    tracer arrays (functional_call interiors are part of their own op)."""
    from paddle_tpu import nn

    net = nn.Linear(4, 2)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [2, 4], "float32")
        y = x * 2.0
        # a compiled-path call mid-capture (runs under trace_mode inside jit)
        from paddle_tpu import jit as pjit

        traced = pjit.to_static(lambda t: t + 1)
        _ = traced(paddle.to_tensor(np.ones((1, 4), np.float32)))
        z = y + 1.0
    out = static.Executor().run(
        prog, feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[z]
    )
    assert np.allclose(out[0], 3.0)


def test_cond_under_to_static_grad():
    """cond inside a traced function differentiates through the select."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core import autograd
    from paddle_tpu.core.tensor import Tensor

    def loss(arr):
        with autograd.trace_mode():
            t = Tensor._from_op(arr)
            r = cond(t.sum() > 0, lambda: (t * 2).sum(), lambda: (t * 3).sum())
        return r._array if isinstance(r, Tensor) else r

    g_pos = jax.grad(loss)(jnp.array([1.0, 1.0]))
    g_neg = jax.grad(loss)(jnp.array([-1.0, -1.0]))
    assert np.allclose(np.asarray(g_pos), 2.0)
    assert np.allclose(np.asarray(g_neg), 3.0)


def test_save_load_inference_model_roundtrip(tmp_path):
    """static.save_inference_model bakes the feed->fetch slice + current
    weights into a StableHLO artifact; load_inference_model returns the
    reference [program, feed_names, fetch_targets] triple that Executor.run
    executes in a fresh-graph world (reference static/io.py:442)."""
    import os

    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import nn, static

    paddle.seed(0)
    net = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
    net.eval()
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [3, 4], "float32")
        y = net(x)
    exe = static.Executor()
    rs = np.random.RandomState(0)
    xv = rs.rand(3, 4).astype(np.float32)
    want = exe.run(prog, feed={"x": xv}, fetch_list=[y])[0]

    prefix = str(tmp_path / "inf")
    out_path = static.save_inference_model(prefix, [x], [y], exe, program=prog)
    assert os.path.exists(out_path)

    # weights changing AFTER save must not affect the baked artifact
    for p in net.parameters():
        p.set_value(np.zeros_like(p.numpy()))

    loaded, feed_names, fetch_targets = static.load_inference_model(prefix, exe)
    assert feed_names == ["x"]
    got = exe.run(loaded, feed={"x": xv}, fetch_list=fetch_targets)[0]
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_save_inference_model_refuses_baked_placeholder(tmp_path):
    """A placeholder reaching the fetch but missing from feed_vars must be
    refused (it would bake in as capture-time zeros — silent wrong output)."""
    import numpy as np
    import pytest

    import paddle_tpu as paddle
    from paddle_tpu import static

    paddle.seed(0)
    prog = static.Program()
    with static.program_guard(prog):
        a = static.data("a", [2, 3], "float32")
        b = static.data("b", [2, 3], "float32")
        y = a + b
    with pytest.raises(ValueError, match="baked"):
        static.save_inference_model(str(tmp_path / "m"), [a], [y], program=prog)
