"""ServingServer: the HTTP/SSE surface over AsyncLLMEngine.

Everything runs in-process over loopback (127.0.0.1, ephemeral ports — no
egress) on the tier-1 CPU invocation. `test_server_smoke_streamed` is the
always-on fast path: boot, stream one greedy completion token-for-token
equal to the engine reference, scrape /healthz + /metrics, drain cleanly.
The long mixed-traffic soak is `-m slow`.
"""
import asyncio
import json
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import LLMEngine, ServingServer


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def _idle(engine):
    return engine.pool.num_free == engine.pool.num_blocks - 1


async def _wait_for(cond, timeout=30.0, msg="condition"):
    t0 = time.monotonic()
    while not cond():
        if time.monotonic() - t0 > timeout:
            raise AssertionError(f"timed out waiting for {msg}")
        await asyncio.sleep(0.01)


async def _http(port, method, path, obj=None, trailer=b""):
    """One loopback HTTP exchange; returns (status, body_bytes).
    `trailer` sends stray bytes after the body (some clients emit a
    trailing CRLF) — they must be drained, never read as a disconnect."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    data = json.dumps(obj).encode() if obj is not None else b""
    writer.write(
        (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
         f"Content-Type: application/json\r\n"
         f"Content-Length: {len(data)}\r\n\r\n").encode() + data + trailer
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), body


def _sse_tokens(body):
    """Parse an SSE body -> (token list, final finish_reason, saw_done)."""
    toks, reason, done = [], None, False
    for line in body.decode().splitlines():
        if not line.startswith("data: "):
            continue
        payload = line[len("data: "):]
        if payload == "[DONE]":
            done = True
            continue
        chunk = json.loads(payload)
        choice = chunk["choices"][0]
        toks.extend(choice["token_ids"])
        if choice["finish_reason"] is not None:
            reason = choice["finish_reason"]
    return toks, reason, done


async def _start_server(model, **kw):
    engine = LLMEngine(model, block_size=8, max_batch=kw.pop("max_batch", 4),
                       max_seq_len=64)
    server = ServingServer(engine, host="127.0.0.1", port=0, **kw)
    await server.start()
    return engine, server


def test_server_smoke_streamed(model):
    """Always-on smoke: boot in-process, stream one greedy completion
    (token-for-token the engine reference), check /healthz, drain."""
    (p,) = _prompts((7,), seed=1)
    ref = _reference(model, p, 6)

    async def main():
        engine, server = await _start_server(model)
        status, body = await _http(
            server.port, "POST", "/v1/completions",
            {"prompt": p, "max_tokens": 6, "stream": True},
            trailer=b"\r\n",  # stray client bytes must not read as hangup
        )
        hstatus, hbody = await _http(server.port, "GET", "/healthz")
        await server.shutdown(drain=True)
        return engine, server, status, body, hstatus, json.loads(hbody)

    engine, server, status, body, hstatus, health = asyncio.run(main())
    assert status == 200
    toks, reason, done = _sse_tokens(body)
    assert toks == ref
    assert reason == "length" and done
    assert hstatus == 200 and health["status"] == "ok"
    assert _idle(engine)
    assert not server.engine._thread.is_alive()  # drained, no hung tasks


def test_server_non_streaming_and_metrics(model):
    (p,) = _prompts((9,), seed=2)
    ref = _reference(model, p, 5)

    async def main():
        engine, server = await _start_server(model)
        status, body = await _http(
            server.port, "POST", "/v1/completions",
            {"prompt": p, "max_tokens": 5},
        )
        mstatus, metrics = await _http(server.port, "GET", "/metrics")
        bstatus, _ = await _http(server.port, "POST", "/v1/completions",
                                 {"prompt": "not token ids"})
        tstatus, _ = await _http(server.port, "POST", "/v1/completions",
                                 {"prompt": p, "timeout_s": "soon"})
        nstatus, _ = await _http(server.port, "GET", "/nope")
        await server.shutdown()
        return engine, status, json.loads(body), mstatus, metrics.decode(), \
            bstatus, tstatus, nstatus

    engine, status, out, mstatus, metrics, bstatus, tstatus, nstatus = \
        asyncio.run(main())
    assert status == 200
    assert out["object"] == "text_completion"
    assert out["choices"][0]["token_ids"] == ref
    assert out["choices"][0]["finish_reason"] == "length"
    assert out["usage"] == {"prompt_tokens": 9, "completion_tokens": 5,
                            "total_tokens": 14}
    assert mstatus == 200
    assert "paddle_tpu_serving_requests_added_total 1" in metrics
    assert "paddle_tpu_serving_generated_tokens_total 5" in metrics
    assert 'quantile="0.95"' in metrics  # step-latency summaries
    assert bstatus == 400 and tstatus == 400 and nstatus == 404
    assert engine.metrics.counters["requests_added"] == 1  # no slot leaked
    assert _idle(engine)


def test_server_full_wait_queue_yields_429(model):
    """Admission control over HTTP: with one lane and no wait queue, a
    second request is rejected 429 while the first is in flight — never
    queued unboundedly."""
    p1, p2 = _prompts((4, 5), seed=3)

    async def main():
        engine, server = await _start_server(model, max_batch=1,
                                             max_waiting=0)
        # occupy the single slot straight through the frontend; the HTTP
        # request below races nothing — admission is synchronous
        st = server.engine.submit(p1, max_new_tokens=40, temperature=0.0)
        status, body = await _http(
            server.port, "POST", "/v1/completions",
            {"prompt": p2, "max_tokens": 2},
        )
        await st.collect()
        # slot free again: same request now admitted
        status2, _ = await _http(server.port, "POST", "/v1/completions",
                                 {"prompt": p2, "max_tokens": 2})
        await server.shutdown()
        return engine, status, json.loads(body), status2

    engine, status, body, status2 = asyncio.run(main())
    assert status == 429
    assert body["error"]["type"] == "overloaded"
    assert status2 == 200
    assert engine.metrics.counters["requests_rejected"] == 1
    assert _idle(engine)


def test_server_client_disconnect_aborts_and_frees(model):
    """A client that drops its SSE connection mid-stream: the request is
    aborted, its KV blocks return to the pool, and the server keeps
    serving (next request is token-exact)."""
    p1, p2 = _prompts((6, 8), seed=4)
    ref2 = _reference(model, p2, 4)

    async def main():
        engine, server = await _start_server(model)
        reader, writer = await asyncio.open_connection(
            "127.0.0.1", server.port)
        data = json.dumps({"prompt": p1, "max_tokens": 56,
                           "stream": True}).encode()
        writer.write(
            (f"POST /v1/completions HTTP/1.1\r\nHost: t\r\n"
             f"Content-Type: application/json\r\n"
             f"Content-Length: {len(data)}\r\n\r\n").encode() + data
        )
        await writer.drain()
        # wait for the first SSE chunk, then vanish
        while True:
            line = await reader.readline()
            if line.startswith(b"data: "):
                break
        writer.close()
        try:
            await writer.wait_closed()
        except (ConnectionError, OSError):
            pass
        await _wait_for(
            lambda: engine.metrics.counters["requests_cancelled"] >= 1
            and _idle(engine),
            msg="disconnect abort + block reclamation",
        )
        status, body = await _http(server.port, "POST", "/v1/completions",
                                   {"prompt": p2, "max_tokens": 4})
        await server.shutdown(drain=True)
        return engine, status, json.loads(body)

    engine, status, out = asyncio.run(main())
    assert status == 200
    assert out["choices"][0]["token_ids"] == ref2
    assert engine.metrics.counters["client_disconnects"] >= 1
    assert _idle(engine)


def test_server_draining_rejects_with_503(model):
    """While draining, /v1/completions yields 503 and /healthz reports
    draining — in-flight work still completes."""
    (p,) = _prompts((5,), seed=5)

    async def main():
        engine, server = await _start_server(model)
        st = server.engine.submit(p, max_new_tokens=10, temperature=0.0)
        # LB drain pattern: admissions close and /healthz flips to 503
        # while the listener stays up and in-flight work continues
        server.begin_drain()
        status, body = await _http(server.port, "POST", "/v1/completions",
                                   {"prompt": p, "max_tokens": 2})
        hstatus, hbody = await _http(server.port, "GET", "/healthz")
        toks, reason = await st.collect()
        await server.shutdown(drain=True)
        return status, json.loads(body), hstatus, json.loads(hbody), reason

    status, body, hstatus, health, reason = asyncio.run(main())
    assert status == 503 and body["error"]["type"] == "draining"
    assert hstatus == 503 and health["status"] == "draining"
    assert reason == "length"  # the in-flight request finished the drain


def test_server_sampling_and_spec_knobs_passthrough(model):
    """/v1/completions passes top_k/top_p and the speculative-decoding
    overrides through to the engine: top_k=1 at high temperature is
    greedy-exact, a spec-enabled server still serves token-exact greedy
    completions, and the spec series reaches /metrics. The prompt is
    repetitive so the n-gram drafter proposes FULL windows — the ragged
    width gate drops short lone drafts by design."""
    p = [5, 9, 11, 4] * 3
    ref = _reference(model, p, 8)

    async def main():
        engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64,
                           spec_decoding=True, num_spec_tokens=3)
        server = ServingServer(engine, host="127.0.0.1", port=0)
        await server.start()
        status, body = await _http(
            server.port, "POST", "/v1/completions",
            {"prompt": p, "max_tokens": 8, "temperature": 3.0, "top_k": 1,
             "top_p": 0.9},
        )
        # per-request opt-out rides the same body
        ostatus, obody = await _http(
            server.port, "POST", "/v1/completions",
            {"prompt": p, "max_tokens": 8, "spec_decoding": False},
        )
        bstatus, _ = await _http(server.port, "POST", "/v1/completions",
                                 {"prompt": p, "top_p": "hot"})
        mstatus, metrics = await _http(server.port, "GET", "/metrics")
        await server.shutdown(drain=True)
        return (engine, status, json.loads(body), ostatus, json.loads(obody),
                bstatus, mstatus, metrics.decode())

    engine, status, out, ostatus, oout, bstatus, mstatus, metrics = \
        asyncio.run(main())
    assert status == 200
    assert out["choices"][0]["token_ids"] == ref  # top_k=1 == greedy
    assert ostatus == 200
    assert oout["choices"][0]["token_ids"] == ref
    assert bstatus == 400
    assert mstatus == 200
    assert "paddle_tpu_serving_spec_proposed_tokens_total" in metrics
    # drafts may ride mixed steps under the unified ragged program, so
    # the drafted-rows counter (not verify-kind steps) is the signal
    assert "paddle_tpu_serving_spec_drafted_rows_total" in metrics
    assert _idle(engine)


@pytest.mark.slow
def test_server_soak_mixed_traffic(model):
    """Soak: waves of streamed/non-streamed/cancelled/timed-out requests
    with a tiny stream queue (forced backpressure). Afterwards the pool is
    at its idle free count, nothing is in flight, and survivors are
    token-exact."""
    prompts = _prompts((4, 6, 9, 12, 5, 7, 10, 8), seed=6)
    refs = {i: _reference(model, p, 8) for i, p in enumerate(prompts)}

    async def main():
        engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
        server = ServingServer(engine, host="127.0.0.1", port=0,
                               stream_queue_size=2, max_waiting=32)
        await server.start()
        exact = 0

        async def slow_consumer(p, i):
            # forced backpressure: read nothing until the request is done
            st = server.engine.submit(p, max_new_tokens=8, temperature=0.0)
            await asyncio.wait_for(st.done.wait(), 60.0)
            return await st.collect()

        for wave in range(3):
            tasks = [slow_consumer(prompts[0], 0)]
            for i, p in enumerate(prompts):
                if i % 4 == 3:
                    tasks.append(server.engine.submit(
                        p, max_new_tokens=8, temperature=0.0,
                        timeout_s=0.001 if wave % 2 else 30.0,
                    ).collect())
                else:
                    tasks.append(_http(
                        server.port, "POST", "/v1/completions",
                        {"prompt": p, "max_tokens": 8,
                         "stream": i % 2 == 0},
                    ))
            results = await asyncio.gather(*tasks)
            toks, reason = results[0]  # the starved consumer catches up
            assert toks == refs[0] and reason == "length"
            exact += 1
            for i, r in enumerate(results[1:]):
                if i % 4 == 3:
                    toks, reason = r
                    if reason == "length":
                        assert toks == refs[i]
                        exact += 1
                else:
                    status, body = r
                    assert status == 200
                    if i % 2 == 0:
                        toks, reason, done = _sse_tokens(body)
                        assert done and reason == "length"
                    else:
                        toks = json.loads(body)["choices"][0]["token_ids"]
                    assert toks == refs[i]
                    exact += 1
        await server.shutdown(drain=True)
        return engine, exact

    engine, exact = asyncio.run(main())
    assert exact >= 18  # every non-timeout request was token-exact
    assert engine.metrics.counters["backpressure_drops"] >= 1
    assert _idle(engine)
    assert engine._requests == {}


def test_server_debug_trace_and_healthz_pool(model):
    """Observability surface: /healthz carries the pool saturation gauges
    (no /metrics scrape needed), and /debug/trace serves the Perfetto
    trace when the engine traces — 404 with a hint when it does not."""
    (p,) = _prompts((9,), seed=7)

    async def main():
        # tracing OFF (default engine): /debug/trace is a guided 404
        engine_off, server_off = await _start_server(model)
        t_status, t_body = await _http(server_off.port, "GET", "/debug/trace")
        await server_off.shutdown(drain=True)

        # tracing ON: serve one request, then export
        engine = LLMEngine(model, block_size=8, max_batch=4,
                           max_seq_len=64, trace=1.0)
        server = ServingServer(engine, host="127.0.0.1", port=0)
        await server.start()
        status, _ = await _http(server.port, "POST", "/v1/completions",
                                {"prompt": p, "max_tokens": 4})
        d_status, d_body = await _http(server.port, "GET", "/debug/trace")
        h_status, h_body = await _http(server.port, "GET", "/healthz")
        await server.shutdown(drain=True)
        return (t_status, t_body, status, d_status, json.loads(d_body),
                h_status, json.loads(h_body), engine)

    (t_status, t_body, status, d_status, trace, h_status, health,
     engine) = asyncio.run(main())
    assert t_status == 404 and b"PADDLE_TPU_TRACE" in t_body
    assert status == 200 and d_status == 200
    names = {e["name"] for e in trace["traceEvents"]}
    assert {"request", "ttft", "queued", "decode"} <= names
    assert any(n.startswith("step[") for n in names)
    assert trace["otherData"]["producer"] == "paddle_tpu.serving.trace"
    # healthz saturation gauges: pool tiers + queue depths, all idle now
    assert h_status == 200
    pool = health["pool"]
    assert pool["blocks_total"] == engine.pool.num_blocks - 1
    assert pool["blocks_truly_free"] + pool["blocks_cached_free"] \
        == pool["blocks_total"]
    assert pool["blocks_allocated"] == 0
    assert pool["requests_running"] == 0 and pool["requests_waiting"] == 0
    assert _idle(engine)


def test_server_per_request_trace_flag(model):
    """A request body's "trace": true forces itself into a sampled trace
    (sample fraction 0 of the stream would otherwise skip everyone)."""
    p1, p2 = _prompts((6, 8), seed=8)

    async def main():
        engine = LLMEngine(model, block_size=8, max_batch=4,
                           max_seq_len=64, trace=0.0001)
        server = ServingServer(engine, host="127.0.0.1", port=0)
        await server.start()
        s1, _ = await _http(server.port, "POST", "/v1/completions",
                            {"prompt": p1, "max_tokens": 3})
        s2, _ = await _http(server.port, "POST", "/v1/completions",
                            {"prompt": p2, "max_tokens": 3, "trace": True})
        d_status, d_body = await _http(server.port, "GET", "/debug/trace")
        await server.shutdown(drain=True)
        return s1, s2, d_status, json.loads(d_body)

    s1, s2, d_status, trace = asyncio.run(main())
    assert s1 == s2 == 200 and d_status == 200
    closed = [e for e in trace["traceEvents"] if e["name"] == "request"]
    assert len(closed) == 1              # only the forced request traced
    assert closed[0]["args"]["output_tokens"] == 3


def test_metrics_exposes_pool_saturation_gauges(model):
    """Observability satellite: the /healthz pool split (truly-free vs
    cached-free vs allocated blocks, running/waiting) must ALSO land on
    Prometheus /metrics — with HELP/TYPE — so dashboards never scrape a
    non-Prometheus endpoint. The gauges refresh at scrape time and agree
    with /healthz's live numbers on an idle engine."""
    async def run():
        engine, server = await _start_server(model)
        try:
            await server.engine.submit(
                _prompts((9,))[0], max_new_tokens=4).collect()
            mstatus, mbody = await _http(server.port, "GET", "/metrics")
            hstatus, hbody = await _http(server.port, "GET", "/healthz")
            return engine, mstatus, mbody.decode(), json.loads(hbody)
        finally:
            await server.shutdown()

    engine, mstatus, metrics, health = asyncio.run(run())
    assert mstatus == 200
    gauges = {}
    for line in metrics.splitlines():
        if line.startswith("paddle_tpu_serving_pool_"):
            name, val = line.rsplit(" ", 1)
            gauges[name] = float(val)
    # kv_dtype is the one non-numeric pool stat: /healthz carries the
    # string, /metrics carries it on the `kv` info family, not a gauge
    assert health["pool"]["kv_dtype"] == "float32"
    want = {f"paddle_tpu_serving_pool_{k}": float(v)
            for k, v in health["pool"].items() if not isinstance(v, str)}
    assert gauges == want                      # same live numbers
    assert gauges["paddle_tpu_serving_pool_blocks_total"] > 0
    assert gauges["paddle_tpu_serving_pool_blocks_allocated"] == 0  # idle
    for fam in ("pool_blocks_truly_free", "pool_blocks_cached_free",
                "pool_requests_running", "pool_requests_waiting"):
        assert f"# HELP paddle_tpu_serving_{fam} " in metrics
        assert f"# TYPE paddle_tpu_serving_{fam} gauge" in metrics
