"""SLO ledger (serving/slo.py) + fault flight recorder (serving/postmortem.py).

Acceptance criteria from the observability issue:

- the ledger invariant: per-request phase durations sum to end-to-end
  wall time (float tolerance) across preempt/abort/fault interleavings,
  including preempted and fault-recovered requests (chaos harness
  reused from tests/test_serving_chaos.py);
- per-class rollups (p95 TTFT, TPOT, deadline attainment) exposed on
  /debug/slo and /metrics agree on the same traffic;
- exposition-spec conformance for the labeled histograms: ordered `le`
  buckets ending +Inf, `_count`/`_sum` consistent, label values escaped
  — locked by a /metrics parse test;
- each PR 9 fault class (poison isolation, watchdog trip, nonfinite
  row, thread death) produces exactly ONE valid postmortem bundle
  (valid JSON + Perfetto-loadable trace) and bundles prune to the cap;
- everything off by default: no ledger, no recorder, no slo_* series.

Fast deterministic variants run in tier-1; the randomized soak is
``slow``.
"""
import asyncio
import json
import os
import re

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    LLMEngine,
    ServingServer,
    faults,
)
from paddle_tpu.serving.faults import FaultPlan
from paddle_tpu.serving.slo import PHASES


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


@pytest.fixture(autouse=True)
def _disarm():
    yield
    plan = faults.active()
    if plan is not None:
        plan.release_hangs()
    faults.clear()


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(model, **kw)


def _assert_sums(req, abs_ms=0.05):
    """THE ledger invariant: the phase decomposition sums to e2e."""
    s = req.slo_summary
    assert s is not None, req.request_id
    assert set(s["phases_ms"]) == set(PHASES)
    assert sum(s["phases_ms"].values()) == pytest.approx(
        s["e2e_s"] * 1e3, abs=abs_ms), (req.request_id, s)
    return s


async def _http(port, method, path, obj=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    data = json.dumps(obj).encode() if obj is not None else b""
    writer.write(
        (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
         f"Content-Type: application/json\r\n"
         f"Content-Length: {len(data)}\r\n\r\n").encode() + data
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.decode("latin1").split("\r\n")[0].split(" ")[1]), body


# -- Prometheus exposition parsing (the conformance lock) --------------------

_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{.*\})? (\S+)$")
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(s):
    out, i = [], 0
    while i < len(s):
        if s[i] == "\\" and i + 1 < len(s):
            out.append({"n": "\n", '"': '"', "\\": "\\"}
                       .get(s[i + 1], "\\" + s[i + 1]))
            i += 2
        else:
            out.append(s[i])
            i += 1
    return "".join(out)


def _parse_prom(text):
    """(types, samples): every non-comment line must parse — an escaping
    bug anywhere invalidates the whole scrape, which is the point."""
    types, samples = {}, []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split(" ", 3)
            types[name] = kind
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        assert m is not None, f"unparseable sample line: {line!r}"
        labels = {}
        if m.group(2):
            body = m.group(2)[1:-1]
            # the label body must be fully consumed by valid pairs
            rebuilt = ",".join(f'{k}="{v}"'
                               for k, v in _LABEL_RE.findall(body))
            assert rebuilt == body, f"bad label body: {body!r}"
            labels = {k: _unescape(v) for k, v in _LABEL_RE.findall(body)}
        samples.append((m.group(1), labels, float(m.group(3))))
    return types, samples


def _histogram_series(samples, family):
    """{labelkey: {"buckets": [(le, cum)], "sum": x, "count": n}} for one
    histogram family, le rows in exposition order."""
    out = {}
    for name, labels, value in samples:
        if not name.startswith(family):
            continue
        key = tuple(sorted((k, v) for k, v in labels.items() if k != "le"))
        s = out.setdefault(key, {"buckets": [], "sum": None, "count": None})
        if name == family + "_bucket":
            s["buckets"].append((labels["le"], value))
        elif name == family + "_sum":
            s["sum"] = value
        elif name == family + "_count":
            s["count"] = value
    return out


def _check_histogram_conformance(types, samples, family):
    assert types[family] == "histogram"
    series = _histogram_series(samples, family)
    assert series, family
    for key, s in series.items():
        les = [le for le, _ in s["buckets"]]
        assert les[-1] == "+Inf", (family, key, les)
        bounds = [float(le) for le in les[:-1]]
        assert bounds == sorted(bounds), (family, key)
        cums = [v for _, v in s["buckets"]]
        assert cums == sorted(cums), (family, key)   # cumulative
        assert s["count"] == cums[-1], (family, key)
        assert s["sum"] is not None
    return series


# -- off by default -----------------------------------------------------------


def test_everything_off_by_default(model, monkeypatch):
    monkeypatch.delenv("PADDLE_TPU_SLO", raising=False)
    monkeypatch.delenv("PADDLE_TPU_POSTMORTEM_DIR", raising=False)
    engine = _engine(model)
    assert engine.slo is None and engine.recorder is None
    assert engine.scheduler.slo is None
    engine.generate(_prompts((5,), seed=1), max_new_tokens=2)
    req_probe = engine.add_request(_prompts((4,), seed=2)[0],
                                   max_new_tokens=1)
    assert engine.get_request(req_probe).phase is None   # clock never ran
    assert "slo_" not in engine.metrics.prometheus_text()
    assert "postmortem" not in engine.metrics.prometheus_text()


def test_label_values_truncated():
    from paddle_tpu.serving.scheduler import Request

    req = Request([1, 2, 3], tenant="t" * 500, priority="p" * 500)
    assert req.tenant == "t" * 64          # multi-MB tenant strings must
    assert req.priority == "p" * 64        # not become metrics state


# -- decomposition invariant: happy path + preemption churn ------------------


def test_decomposition_sums_and_preemption_attribution(model):
    # pool sized so the younger of two long requests must be preempted
    engine = _engine(model, max_batch=2, num_blocks=5, slo=True)
    rids = [
        engine.add_request(_prompts((24,), seed=3)[0], max_new_tokens=8,
                           tenant="acme", priority="hi", deadline_s=60.0),
        engine.add_request(_prompts((24,), seed=4)[0], max_new_tokens=8,
                           tenant="free", priority="lo"),
    ]
    reqs = [engine.get_request(r) for r in rids]
    while engine.has_unfinished():
        engine.step()
    for req in reqs:
        s = _assert_sums(req)
        assert s["reason"] == "finished"
        assert all(v >= 0.0 for v in s["phases_ms"].values())
        assert s["phases_ms"]["decode_compute"] > 0.0
        assert s["ttft_s"] > 0.0 and s["tpot_s"] > 0.0
    assert reqs[1].preemptions >= 1
    assert reqs[1].slo_summary["phases_ms"]["preempted"] > 0.0
    assert reqs[0].slo_summary["deadline"] == "met"
    assert reqs[1].slo_summary["deadline"] is None     # no deadline set
    roll = engine.slo.rollup()
    by_class = {(c["tenant"], c["priority"]): c for c in roll["classes"]}
    acme = by_class[("acme", "hi")]
    assert acme["requests"] == 1 and acme["deadline"]["attainment"] == 1.0
    free = by_class[("free", "lo")]
    assert free["preemptions"] >= 1 and free["preemption_share"] > 0.0
    assert roll["total"]["requests"] == 2
    # rollup phase totals are the per-request decompositions, summed
    assert roll["total"]["phases_ms"]["preempted"] == pytest.approx(
        sum(r.slo_summary["phases_ms"]["preempted"] for r in reqs),
        abs=0.01)


def test_abort_and_queued_only_requests_close_cleanly(model):
    engine = _engine(model, max_batch=1, slo=True)
    run = engine.add_request(_prompts((6,), seed=5)[0], max_new_tokens=4)
    parked = engine.add_request(_prompts((6,), seed=6)[0], max_new_tokens=4,
                                deadline_s=30.0)
    run_req, parked_req = engine.get_request(run), engine.get_request(parked)
    engine.step()
    engine.abort(parked)                   # dies waiting: queued only
    while engine.has_unfinished():
        engine.step()
    s = _assert_sums(parked_req)
    assert s["reason"] == "aborted" and s["deadline"] == "aborted"
    assert s["phases_ms"]["queued"] > 0.0
    assert s["phases_ms"]["decode_compute"] == 0.0
    _assert_sums(run_req)


# -- /debug/slo vs /metrics on the same traffic ------------------------------


def test_debug_slo_and_metrics_agree_and_conform(model):
    engine = _engine(model, slo=True)
    weird = 'we"ird\\ten\nant'             # must survive label escaping

    async def main():
        server = await ServingServer(engine, port=0, max_waiting=8).start()
        jobs = []
        for i, (tenant, prio) in enumerate(
                [("acme", "hi")] * 3 + [("free", "lo")] * 2 + [(weird, "x")]):
            jobs.append(_http(
                server.port, "POST", "/v1/completions",
                {"prompt": _prompts((5 + i,), seed=7 + i)[0],
                 "max_tokens": 4, "tenant": tenant, "priority": prio,
                 "timeout_s": 30.0}))
        results = await asyncio.gather(*jobs)
        s1, slo_body = await _http(server.port, "GET", "/debug/slo")
        s2, met_body = await _http(server.port, "GET", "/metrics")
        s3, _ = await _http(server.port, "GET", "/debug/postmortem")
        await server.shutdown(drain=True)
        return results, (s1, slo_body), (s2, met_body), s3

    results, (s1, slo_body), (s2, met_body), s3 = asyncio.run(main())
    assert all(status == 200 for status, _ in results)
    assert s1 == 200 and s2 == 200
    assert s3 == 404                       # recorder off on this engine
    roll = json.loads(slo_body)
    by_class = {(c["tenant"], c["priority"]): c for c in roll["classes"]}
    assert by_class[("acme", "hi")]["requests"] == 3
    assert by_class[(weird, "x")]["requests"] == 1
    types, samples = _parse_prom(met_body.decode())
    pre = "paddle_tpu_serving_"
    for fam in ("slo_e2e_seconds", "slo_ttft_seconds", "slo_tpot_seconds"):
        series = _check_histogram_conformance(types, samples, pre + fam)
        if fam == "slo_e2e_seconds":
            e2e_series = series
    # per-class agreement between the JSON rollup and the scrape
    for (tenant, prio), entry in by_class.items():
        key = tuple(sorted({"tenant": tenant, "priority": prio}.items()))
        s = e2e_series[key]
        n = entry["e2e_ms"]["count"]
        assert s["count"] == n == entry["requests"]
        # nearest-rank p95 must land in a bucket consistent with the
        # histogram's cumulative counts: strictly fewer than `rank`
        # observations below its bucket, at least `rank` at/above it
        p95_s = entry["e2e_ms"]["p95"] / 1e3
        rank = -(-95 * n // 100)
        below = 0.0
        for le, cum in s["buckets"]:
            if le != "+Inf" and float(le) < p95_s:
                below = cum
        assert below < rank
        at_or_above = [cum for le, cum in s["buckets"]
                       if le == "+Inf" or float(le) >= p95_s]
        assert at_or_above and at_or_above[0] >= rank
    # labeled counters agree too (all six finished within deadline)
    met = {tuple(sorted(lbl.items())): v for name, lbl, v in samples
           if name == pre + "slo_deadline_met_total"}
    for (tenant, prio), entry in by_class.items():
        key = tuple(sorted({"tenant": tenant, "priority": prio}.items()))
        assert met[key] == entry["deadline"]["met"] == entry["requests"]
        assert entry["deadline"]["attainment"] == 1.0
    # the weird tenant's label value round-trips exactly
    assert any(lbl.get("tenant") == weird for _, lbl, _ in samples)


# -- deadline verdicts through the frontend ----------------------------------


def test_frontend_timeout_is_missed_deadline(model):
    faults.install(FaultPlan([{"point": "slow_step_ms", "ms": 30}]))
    engine = _engine(model, slo=True)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        slow = fe.submit(_prompts((5,), seed=20)[0], max_new_tokens=48,
                         temperature=0.0, timeout_s=0.15, tenant="t")
        ok = fe.submit(_prompts((5,), seed=21)[0], max_new_tokens=3,
                       temperature=0.0, timeout_s=30.0, tenant="t")
        r_slow = await asyncio.wait_for(slow.collect(), 30.0)
        r_ok = await asyncio.wait_for(ok.collect(), 30.0)
        await fe.shutdown(drain=True, timeout_s=10.0)
        return (slow.req, r_slow), (ok.req, r_ok)

    (req_slow, (_, reason_slow)), (req_ok, (_, reason_ok)) = asyncio.run(
        main())
    assert reason_slow == "timeout" and reason_ok == "length"
    assert _assert_sums(req_slow)["deadline"] == "missed"
    assert _assert_sums(req_ok)["deadline"] == "met"
    roll = engine.slo.rollup()["total"]
    assert roll["deadline"]["met"] == 1
    assert roll["deadline"]["missed"] == 1
    assert roll["deadline"]["attainment"] == 0.5


# -- chaos: invariant + one bundle per fault class ---------------------------


def test_poison_isolation_ledger_and_bundle(model, tmp_path):
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "poison", "exc": "DeviceBoom"},
    ]))
    engine = _engine(model, postmortem_dir=str(tmp_path))
    assert engine.slo is not None          # the recorder implies a ledger

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        streams = []
        for i, p in enumerate(_prompts((5, 9, 13), seed=22)):
            rid = "poison" if i == 1 else f"r{i}"
            streams.append(fe.submit(p, max_new_tokens=6, temperature=0.0,
                                     request_id=rid))
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 30.0)
        await fe.shutdown(drain=True, timeout_s=10.0)
        return streams, results

    streams, results = asyncio.run(main())
    assert results[1][1] == "error"
    assert results[0][1] == results[2][1] == "length"
    for st in streams:
        _assert_sums(st.req)
    # the culprit's decomposition shows failure-boundary time
    assert streams[1].req.slo_summary["phases_ms"]["stalled"] > 0.0
    bundles = engine.recorder.list_bundles()
    assert [b["event"] for b in bundles] == ["poison_isolated"]
    assert bundles[0]["victim"] == "poison"
    bd = json.load(open(os.path.join(str(tmp_path), bundles[0]["name"],
                                     "bundle.json")))
    assert bd["victim"]["request_id"] == "poison"
    assert bd["fault_plan"]["fired"]       # the chaos run self-describes
    assert set(bd["victim"]["phases_ms"]) == set(PHASES)
    assert bd["metrics"]["counters"]["poison_requests_isolated"] == 1


def test_nonfinite_row_bundle_exactly_once(model, tmp_path):
    faults.install(FaultPlan([
        {"point": "step_nonfinite_logits", "request_id": "poison",
         "times": 1},
    ]))
    engine = _engine(model, postmortem_dir=str(tmp_path))
    engine.add_request(_prompts((5,), seed=23)[0], max_new_tokens=4,
                       request_id="poison")
    engine.add_request(_prompts((7,), seed=24)[0], max_new_tokens=4,
                       request_id="ok")
    # grab refs now: the abort releases the poison's engine record
    poison, ok = engine.get_request("poison"), engine.get_request("ok")
    while engine.has_unfinished():
        engine.step()
    assert [b["event"] for b in engine.recorder.list_bundles()] \
        == ["nonfinite_row"]
    assert ok.slo_summary["reason"] == "finished"
    _assert_sums(poison)


def test_watchdog_trip_stalled_and_bundle(model, tmp_path):
    plan = faults.install(FaultPlan([
        {"point": "step_hang", "at_step": 1, "timeout_s": 60.0},
    ]))
    engine = _engine(model, trace=True, postmortem_dir=str(tmp_path))

    async def main():
        fe = await AsyncLLMEngine(
            engine, max_waiting=8,
            watchdog_step_timeout_s=0.2, watchdog_poll_s=0.05,
        ).start()
        streams = [fe.submit(p, max_new_tokens=4, temperature=0.0,
                             request_id=f"r{i}")
                   for i, p in enumerate(_prompts((5, 9), seed=25))]
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 15.0)
        plan.release_hangs()
        await fe.shutdown(drain=True, timeout_s=10.0)
        return streams, results

    streams, results = asyncio.run(main())
    for _, reason in results:
        assert reason == "error"
    bundles = engine.recorder.list_bundles()
    assert [b["event"] for b in bundles] == ["watchdog_trip"]
    name = bundles[0]["name"]
    bd = json.load(open(os.path.join(str(tmp_path), name, "bundle.json")))
    assert bd["health"]["reason"] == "step_stuck"
    # Perfetto-loadable trace rode along (tracing was on)
    tr = json.load(open(os.path.join(str(tmp_path), name, "trace.json")))
    assert isinstance(tr["traceEvents"], list) and tr["traceEvents"]
    # the hung step's victims: wall time attributed to `stalled`, and
    # the invariant survives the watchdog/abort interleaving
    for st in streams:
        s = _assert_sums(st.req)
        assert s["phases_ms"]["stalled"] > 0.0


def test_thread_death_bundle(model, tmp_path):
    engine = _engine(model, postmortem_dir=str(tmp_path))

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=8).start()
        streams = [fe.submit(p, max_new_tokens=40, temperature=0.0,
                             request_id=f"r{i}")
                   for i, p in enumerate(_prompts((5, 9), seed=26))]
        await asyncio.sleep(0.05)
        faults.install(FaultPlan([{"point": "thread_die"}]))
        results = await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 10.0)
        await asyncio.wait_for(fe.shutdown(drain=False), 10.0)
        return streams, results

    streams, results = asyncio.run(main())
    for _, reason in results:
        assert reason == "error"
    bundles = engine.recorder.list_bundles()
    assert [b["event"] for b in bundles] == ["engine_thread_died"]
    for st in streams:                     # aborted by the crash epilogue
        _assert_sums(st.req)


# -- pruning + manifests -----------------------------------------------------


def test_bundles_prune_to_cap(model, tmp_path):
    engine = _engine(model, postmortem_dir=str(tmp_path), postmortem_keep=3)
    for i in range(5):
        path = engine.recorder.record("watchdog_trip", detail=f"drill {i}")
        assert path is not None
    bundles = engine.recorder.list_bundles()
    assert len(bundles) == 3
    assert [b["seq"] for b in bundles] == [2, 3, 4]   # oldest pruned
    assert engine.metrics.counters["postmortem_bundles"] == 5
    for b in bundles:
        assert "bundle.json" in b["files"]


# -- randomized soak ---------------------------------------------------------


@pytest.mark.slow
def test_chaos_soak_ledger_invariant(model):
    """Seeded random faults over a mixed multi-tenant wave: every
    request's decomposition sums to its e2e whatever interleaving ran,
    and class request counts add up."""
    rs = np.random.RandomState(41)
    prompts = [rs.randint(0, 128, (int(n),)).tolist()
               for n in rs.randint(3, 40, size=24)]
    faults.install(FaultPlan([
        {"point": "step_raise", "probability": 0.05, "seed": 1},
        {"point": "alloc_fail", "probability": 0.05, "seed": 2},
        {"point": "step_nonfinite_logits", "probability": 0.01, "seed": 3},
        {"point": "slow_step_ms", "probability": 0.1, "seed": 4, "ms": 2},
    ]))
    engine = _engine(model, slo=True)

    async def main():
        fe = await AsyncLLMEngine(engine, max_waiting=32,
                                  max_step_retries=4).start()
        streams = [fe.submit(p, max_new_tokens=int(rs.randint(1, 12)),
                             temperature=0.0, request_id=f"s{i}",
                             tenant=f"t{i % 3}", priority=str(i % 2),
                             timeout_s=60.0)
                   for i, p in enumerate(prompts)]
        await asyncio.wait_for(
            asyncio.gather(*(s.collect() for s in streams)), 120.0)
        await fe.shutdown(drain=True, timeout_s=30.0)
        return streams

    streams = asyncio.run(main())
    for st in streams:
        _assert_sums(st.req)
    roll = engine.slo.rollup()
    assert roll["total"]["requests"] == len(prompts)
    assert sum(c["requests"] for c in roll["classes"]) == len(prompts)
