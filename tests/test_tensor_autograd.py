"""Tensor + eager autograd tests.

Modeled on the reference OpTest pattern (unittests/op_test.py:326): numpy
reference forward + gradient check against jax.grad ground truth.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle


def test_to_tensor_roundtrip():
    a = np.random.rand(3, 4).astype(np.float32)
    t = paddle.to_tensor(a)
    assert t.shape == [3, 4]
    assert np.allclose(t.numpy(), a)


def test_default_float32():
    t = paddle.to_tensor([1.5, 2.5])
    assert np.dtype(t.dtype) == np.float32


def test_arithmetic_and_broadcast():
    x = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = paddle.to_tensor(np.ones((3,), np.float32))
    z = x + y * 2 - 1
    assert np.allclose(z.numpy(), x.numpy() + 1)
    assert np.allclose((x / 2).numpy(), x.numpy() / 2)
    assert np.allclose((x ** 2).numpy(), x.numpy() ** 2)


def test_matmul_grad_matches_jax():
    a = np.random.rand(4, 5).astype(np.float32)
    b = np.random.rand(5, 6).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    w = paddle.to_tensor(b, stop_gradient=False)
    loss = paddle.matmul(x, w).sum()
    loss.backward()
    ga, gb = jax.grad(lambda p, q: jnp.sum(p @ q), (0, 1))(a, b)
    assert np.allclose(x.grad.numpy(), ga, atol=1e-5)
    assert np.allclose(w.grad.numpy(), gb, atol=1e-5)


def test_grad_accumulation_fanout():
    x = paddle.to_tensor(np.array([2.0], np.float32), stop_gradient=False)
    y = x * 3 + x * 4  # two uses of x
    y.backward()
    assert np.allclose(x.grad.numpy(), [7.0])


def test_chained_ops_grad():
    a = np.random.rand(8).astype(np.float32) + 0.1
    x = paddle.to_tensor(a, stop_gradient=False)
    y = (x.log() * x.sqrt()).sum()
    y.backward()
    g = jax.grad(lambda v: jnp.sum(jnp.log(v) * jnp.sqrt(v)))(a)
    assert np.allclose(x.grad.numpy(), g, atol=1e-5)


def test_stop_gradient_blocks():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = paddle.to_tensor(np.ones(3, np.float32))  # stop_gradient=True
    z = (x * y).sum()
    z.backward()
    assert x.grad is not None
    assert y.grad is None


def test_no_grad_context():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    with paddle.no_grad():
        y = (x * 2).sum()
    assert y._node is None


def test_backward_twice_raises():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_retain_graph():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    y = (x * x).sum()
    y.backward(retain_graph=True)
    y.backward()
    assert np.allclose(x.grad.numpy(), 4 * np.ones(3))


def test_detach_and_clone():
    x = paddle.to_tensor(np.ones(3, np.float32), stop_gradient=False)
    d = x.detach()
    assert d.stop_gradient
    c = x.clone()
    s = (c * 2).sum()
    s.backward()
    assert np.allclose(x.grad.numpy(), 2 * np.ones(3))


def test_getitem_setitem():
    x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
    assert np.allclose(x[1].numpy(), np.arange(4, 8))
    x[0, 0] = 100.0
    assert x.numpy()[0, 0] == 100.0


def test_getitem_grad():
    a = np.random.rand(4, 4).astype(np.float32)
    x = paddle.to_tensor(a, stop_gradient=False)
    y = x[1:3].sum()
    y.backward()
    expected = np.zeros((4, 4), np.float32)
    expected[1:3] = 1
    assert np.allclose(x.grad.numpy(), expected)


def test_paddle_grad_api():
    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = x * x
    (g,) = paddle.grad(y, x)
    assert np.allclose(g.numpy(), [6.0])


def test_reductions_match_numpy():
    a = np.random.rand(3, 5).astype(np.float32)
    x = paddle.to_tensor(a)
    assert np.allclose(x.sum(axis=1).numpy(), a.sum(1), atol=1e-6)
    assert np.allclose(x.mean().numpy(), a.mean(), atol=1e-6)
    assert np.allclose(x.max(axis=0).numpy(), a.max(0))
    assert np.allclose(x.std().numpy(), a.std(ddof=1), atol=1e-5)
    assert np.allclose(x.logsumexp().numpy(), np.log(np.exp(a).sum()), atol=1e-5)


def test_manipulation_ops():
    a = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    x = paddle.to_tensor(a)
    assert paddle.reshape(x, [6, 4]).shape == [6, 4]
    assert paddle.transpose(x, [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.flatten(x, 1).shape == [2, 12]
    parts = paddle.split(x, 3, axis=1)
    assert len(parts) == 3 and parts[0].shape == [2, 1, 4]
    assert paddle.concat(parts, axis=1).shape == [2, 3, 4]
    assert paddle.stack([x, x]).shape == [2, 2, 3, 4]
    assert paddle.squeeze(parts[0], 1).shape == [2, 4]
    assert paddle.unsqueeze(x, 0).shape == [1, 2, 3, 4]


def test_gather_scatter():
    x = paddle.to_tensor(np.arange(10, dtype=np.float32))
    idx = paddle.to_tensor(np.array([1, 3, 5]))
    assert np.allclose(paddle.gather(x, idx).numpy(), [1, 3, 5])
    y = paddle.scatter(x, idx, paddle.to_tensor(np.zeros(3, np.float32)))
    assert y.numpy()[1] == 0 and y.numpy()[3] == 0


def test_where_topk_sort():
    a = np.random.rand(4, 6).astype(np.float32)
    x = paddle.to_tensor(a)
    v, i = paddle.topk(x, 2, axis=1)
    ref = np.sort(a, 1)[:, ::-1][:, :2]
    assert np.allclose(v.numpy(), ref)
    w = paddle.where(x > 0.5, x, paddle.zeros_like(x))
    assert np.allclose(w.numpy(), np.where(a > 0.5, a, 0))


def test_einsum():
    a = np.random.rand(2, 3).astype(np.float32)
    b = np.random.rand(3, 4).astype(np.float32)
    out = paddle.einsum("ij,jk->ik", paddle.to_tensor(a), paddle.to_tensor(b))
    assert np.allclose(out.numpy(), a @ b, atol=1e-5)


def test_linalg_suite():
    a = np.random.rand(4, 4).astype(np.float32)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    x = paddle.to_tensor(spd)
    c = paddle.cholesky(x)
    assert np.allclose((c @ c.t()).numpy(), spd, atol=1e-3)
    assert np.allclose(paddle.inverse(x).numpy(), np.linalg.inv(spd), atol=1e-3)
    assert abs(paddle.det(x).item() - np.linalg.det(spd)) / abs(np.linalg.det(spd)) < 1e-3


def test_cast_astype():
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    # int64 maps to int32 on TPU (x64 disabled) — integer semantics preserved
    assert np.dtype(x.astype("int64").dtype).kind == "i"
    assert np.dtype(x.astype("bfloat16").dtype).name == "bfloat16"
    assert np.dtype(x.astype("float16").dtype) == np.float16


def test_creation_ops():
    assert paddle.zeros([2, 3]).shape == [2, 3]
    assert paddle.ones([4]).numpy().sum() == 4
    assert paddle.full([2], 7).numpy().tolist() == [7, 7]
    assert paddle.arange(5).numpy().tolist() == [0, 1, 2, 3, 4]
    assert paddle.linspace(0, 1, 5).shape == [5]
    assert paddle.eye(3).numpy().trace() == 3
    assert paddle.randn([3, 3]).shape == [3, 3]
    assert paddle.randperm(10).numpy().sum() == 45
    paddle.seed(42)
    a = paddle.rand([4]).numpy()
    paddle.seed(42)
    b = paddle.rand([4]).numpy()
    assert np.allclose(a, b)


def test_logic_ops():
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    y = paddle.to_tensor(np.array([1.0, 5.0, 3.0], np.float32))
    assert (x == y).numpy().tolist() == [True, False, True]
    assert paddle.allclose(x, x).item()
    assert not paddle.equal_all(x, y).item()
