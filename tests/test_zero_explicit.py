"""Explicit ZeRO weight-update path (parallel/spmd.py, arXiv:2004.13336).

The parity matrix the PR 19 acceptance bar names: loss trajectories for
zero_stage {0, 2, 3} x gradient_merge {1, k} x remat {on, off} must agree
BIT-IDENTICALLY with the stage-0 GSPMD reference on the fake 8-device CPU
mesh (greedy-deterministic f32 — dropout 0, one key), int8 quantized
gradients sit behind a tolerance gate (the PR 17 AdaRound-NLL-gate
discipline), per-chip optimizer-state sharding is asserted on the PLACED
arrays, and a seeded trip test proves a silently-disabled reduce-scatter
busts the IR001 train budget — the regression hlolint exists to catch.
"""
import numpy as np
import pytest

import paddle_tpu as paddle

DP = 4
STEPS = 4


def _mesh():
    from paddle_tpu.distributed.mesh import init_mesh

    return init_mesh({"dp": DP})


def teardown_module():
    from paddle_tpu.distributed.mesh import set_mesh

    set_mesh(None)


def _batch():
    rs = np.random.RandomState(0)
    return (rs.randint(0, 64, (8, 16), dtype=np.int32),
            rs.randint(0, 64, (8, 16), dtype=np.int32))


def _run(zero_stage, gm=1, remat=False, quant=False, steps=STEPS,
         optimizer="AdamW", **kw):
    """Train `steps` steps; returns (losses, step, params, opt_state)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.analysis.ir import tiny_gpt_config
    from paddle_tpu.models.gpt import GPT, gpt_loss_fn
    from paddle_tpu.parallel.spmd import make_sharded_train_step

    mesh = _mesh()
    paddle.seed(0)
    model = GPT(tiny_gpt_config())
    opt = getattr(paddle.optimizer, optimizer)(
        learning_rate=0.01, parameters=model.parameters())
    step = make_sharded_train_step(
        model, gpt_loss_fn, opt, mesh, zero_stage=zero_stage,
        gradient_merge_k=gm, remat=remat, quant_grads=quant, **kw)
    params, buffers, opt_state = step.init_state()
    ids, labels = _batch()
    batch = step.shard_batch(ids, labels)
    key = jax.random.PRNGKey(0)
    losses = []
    for _ in range(steps):
        loss, params, buffers, opt_state = step(
            params, buffers, opt_state, jnp.float32(0.01), key, *batch)
        losses.append(float(np.asarray(loss)))
    return losses, step, params, opt_state


# one stage-0 GSPMD reference trajectory per (gm, remat) cell, computed
# lazily and shared across the matrix (4 compiles instead of 8)
_REFS = {}


def _reference(gm, remat):
    key = (gm, remat)
    if key not in _REFS:
        _REFS[key] = _run(0, gm=gm, remat=remat)
    return _REFS[key]


@pytest.mark.parametrize("remat", [False, True])
@pytest.mark.parametrize("gm", [1, 2])
@pytest.mark.parametrize("zs", [2, 3])
def test_explicit_path_matches_stage0_bit_identical(zs, gm, remat):
    """The acceptance-bar parity gate: the explicit reduce-scatter +
    shard-local update + gather-updated-shards program replays the
    stage-0 GSPMD loss trajectory BIT-identically (deterministic f32),
    across gradient-merge and remat."""
    ref, _, _, _ = _reference(gm, remat)
    got, step, _, _ = _run(zs, gm=gm, remat=remat)
    assert step.explicit_update, "pure-dp zs>=2 must take the explicit path"
    assert got == ref, (zs, gm, remat, got, ref)


def test_quantized_grads_within_tolerance_and_converging():
    """int8 gradient reduce-scatter (EQuARX wire format) is opt-in and
    tolerance-gated, PR 17 AdaRound-gate style: the trajectory must track
    the f32 reference closely AND actually descend — a quantizer bug that
    zeroed or saturated gradients would stall the loss and trip this even
    inside the tolerance band."""
    ref, _, _, _ = _reference(1, False)
    got, step, _, _ = _run(2, quant=True)
    assert step.quant_grads
    drift = max(abs(a - b) for a, b in zip(got, ref))
    assert drift < 0.02, (drift, got, ref)
    assert got[-1] < got[0] - 0.5, got


@pytest.mark.parametrize("opt", ["Lamb", "Lars"])
@pytest.mark.parametrize("zs", [2, 3])
def test_trust_ratio_optimizers_on_explicit_path(zs, opt):
    """ROADMAP 5(b): Lars/Lamb per-tensor trust ratios on the explicit
    shard-local update. Each norm is a psum of shard-local partial
    squared sums (`optimizer.optimizers.sharded_norms`), so the 1/dp
    flat shards see FULL-tensor norms: the trajectory tracks the
    stage-0 GSPMD reference to reduction-order noise (Lars lands bit-
    identical; Lamb's moment normalization amplifies 1-ulp sum-order
    differences) and actually descends."""
    ref, _, _, _ = _run(0, optimizer=opt)
    got, step, _, _ = _run(zs, optimizer=opt)
    assert step.explicit_update
    drift = max(abs(a - b) for a, b in zip(got, ref))
    assert drift < 1e-5, (drift, got, ref)
    assert got[-1] < got[0], got


def test_optimizer_state_shards_dp_fold_on_placed_arrays():
    """The placed init_state arrays, not specs: every param-shaped AdamW
    slot holds 1/dp of its elements per chip, scalars replicate, and the
    per-chip byte total drops ~dp-fold vs the stage-0 replicated state."""
    from jax.sharding import PartitionSpec as P

    from paddle_tpu.parallel.spmd import per_chip_opt_state_bytes

    _, _, _, state0 = _reference(1, False)
    _, step, params, state2 = _run(2)
    for name, slots in state2.items():
        for slot, arr in slots.items():
            shard = arr.addressable_shards[0]
            if arr.ndim == 0:       # beta pows replicate
                assert shard.data.size == arr.size, (name, slot)
            else:                   # flat [n_pad] leaves, 1/dp per chip
                assert arr.sharding.spec == P("dp"), (name, slot)
                assert shard.data.size * DP == arr.size, (name, slot)
    b0 = per_chip_opt_state_bytes(state0)
    b2 = per_chip_opt_state_bytes(state2)
    # padding + replicated scalars keep it shy of exactly dp-fold
    assert b2 * (DP - 1) < b0, (b0, b2)


def test_stage3_params_stay_sharded_and_gather_round_trips():
    """Stage 3: params live as padded-flat dp-sharded leaves (1/dp per
    chip, never re-materialized), and gather_params reconstructs natural
    shapes that track the stage-0 reference."""
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    _, _, p0, _ = _reference(1, False)
    _, step, p3, _ = _run(3)
    for k, v in p3.items():
        assert v.ndim == 1 and v.sharding.spec == P("dp"), k
        assert v.addressable_shards[0].data.size * DP == v.size, k
    nat = step.gather_params(p3)
    for k in p0:
        assert nat[k].shape == p0[k].shape, k
        # losses are bit-identical; params agree to reduction-order noise
        # (Adam normalizes near-zero grads, amplifying 1-ulp sum-order
        # differences between all-reduce and reduce-scatter)
        np.testing.assert_allclose(np.asarray(nat[k]), np.asarray(p0[k]),
                                   atol=1e-2, rtol=0)


def test_explicit_path_guards():
    """Misconfigurations fail loudly at construction: quant_grads off the
    explicit path, explicit_update on a dp x mp mesh, grad_clip and
    per-tensor-reduction optimizers without the sharded-norm bridge
    (DGC's top-k) on the shard-local update — Lars/Lamb are ADMITTED
    now (their norms psum via `sharded_norms`)."""
    from paddle_tpu.analysis.ir import tiny_gpt_config
    from paddle_tpu.distributed.mesh import init_mesh
    from paddle_tpu.models.gpt import GPT, gpt_loss_fn
    from paddle_tpu.parallel.spmd import make_sharded_train_step

    paddle.seed(0)
    model = GPT(tiny_gpt_config())
    mesh = _mesh()
    mk = lambda opt, **kw: make_sharded_train_step(
        model, gpt_loss_fn, opt, mesh, **kw)
    sgd = lambda **kw: paddle.optimizer.SGD(
        learning_rate=0.1, parameters=model.parameters(), **kw)
    with pytest.raises(ValueError, match="quant_grads"):
        mk(sgd(), zero_stage=0, quant_grads=True)
    with pytest.raises(ValueError, match="grad_clip"):
        mk(sgd(grad_clip=paddle.nn.ClipGradByGlobalNorm(1.0)), zero_stage=2)
    from paddle_tpu.optimizer.optimizers import DGCMomentum

    with pytest.raises(ValueError, match="per-tensor"):
        mk(DGCMomentum(learning_rate=0.01,
                       parameters=model.parameters()),
           zero_stage=2)
    # Lamb/Lars declare _sharded_norm_ready: construction succeeds and
    # takes the explicit path (trajectory parity is its own test)
    assert mk(paddle.optimizer.Lamb(
        learning_rate=0.01, parameters=model.parameters()),
        zero_stage=2).explicit_update
    with pytest.raises(ValueError, match="pure-dp"):
        make_sharded_train_step(
            model, gpt_loss_fn, sgd(), init_mesh({"dp": 2, "mp": 2}),
            zero_stage=2, explicit_update=True)
    # dp x mp at zero_stage>=2 silently keeps the GSPMD path (the legacy
    # 'sharding'-axis meshes in test_distributed_spmd.py rely on this)
    step = make_sharded_train_step(
        model, gpt_loss_fn, sgd(), init_mesh({"dp": 2, "mp": 2}),
        zero_stage=2)
    assert not step.explicit_update


def test_disabled_reduce_scatter_trips_ir001_train_budget(monkeypatch):
    """The seeded hlolint regression: if the explicit path's
    reduce-scatter silently degrades to a full-size all-reduce (here:
    `jax.lax.psum_scatter` monkeypatched to psum + local slice — same
    numerics, wrong collective), the train/* IR001 budget must bust on
    BOTH counts: surplus all-reduce AND missing reduce-scatter."""
    import jax

    from paddle_tpu.analysis import contracts
    from paddle_tpu.analysis.ir import train_artifact

    real_axis_index = jax.lax.axis_index

    def fake_psum_scatter(x, axis_name, *, scatter_dimension=0, tiled=True):
        full = jax.lax.psum(x, axis_name)
        shard = x.shape[scatter_dimension] // DP
        return jax.lax.dynamic_slice_in_dim(
            full, real_axis_index(axis_name) * shard, shard,
            axis=scatter_dimension)

    monkeypatch.setattr(jax.lax, "psum_scatter", fake_psum_scatter)
    art = train_artifact({"dp": DP}, zero_stage=2, optimizer="AdamW",
                         name="train/dp4/zs2")
    assert art.collectives["reduce-scatter"] == 0, art.collectives
    assert art.collectives["all-reduce"] > 1, art.collectives
    violations = contracts.evaluate([art], select=["IR001"])
    msgs = "\n".join(v.format() for v in violations)
    assert "reduce-scatter" in msgs and "all-reduce" in msgs, msgs
