"""Eager collective semantics.

Two layers (VERDICT round-1 item 2):
- single-process unit tests of the stacked-collective math on the forced
  8-device CPU mesh (each row of the stacked array simulates one rank);
- a real 2-process test via subprocess + jax.distributed (Gloo), mirroring
  the reference's test_collective_api_base.py Popen pattern.
"""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.collective import ReduceOp, stacked_collective

HERE = os.path.dirname(os.path.abspath(__file__))


def _rank_mesh(n):
    return Mesh(np.asarray(jax.devices()[:n]), ("rank",))


def _stacked(vals):
    """Simulate n ranks' local values as one rank-sharded stacked array."""
    arr = np.stack(vals)
    mesh = _rank_mesh(arr.shape[0])
    spec = P("rank", *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec)), list(mesh.devices.flat)


class TestStackedCollectiveMath:
    def setup_method(self):
        self.vals = [np.arange(6, dtype=np.float32).reshape(2, 3) + 10 * r for r in range(4)]

    def test_all_reduce_ops(self):
        stacked, devs = _stacked(self.vals)
        for op, ref in [
            (ReduceOp.SUM, sum(self.vals)),
            (ReduceOp.MAX, np.max(self.vals, axis=0)),
            (ReduceOp.MIN, np.min(self.vals, axis=0)),
            (ReduceOp.PROD, np.prod(np.stack(self.vals), axis=0)),
            (ReduceOp.AVG, np.mean(self.vals, axis=0)),
        ]:
            out = stacked_collective("reduce", stacked, devs, op)
            np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)
            assert out.sharding.is_fully_replicated

    def test_all_gather_replicates_stack(self):
        stacked, devs = _stacked(self.vals)
        out = stacked_collective("gather", stacked, devs)
        np.testing.assert_allclose(np.asarray(out), np.stack(self.vals))
        assert out.sharding.is_fully_replicated

    def test_broadcast_selects_src_row(self):
        stacked, devs = _stacked(self.vals)
        out = stacked_collective("select", stacked, devs, 2)
        np.testing.assert_allclose(np.asarray(out), self.vals[2])

    def test_alltoall_transposes(self):
        # rank-major matrix of per-destination payloads
        mat = [np.stack([v + 100 * d for d, v in enumerate(self.vals)]) + 1000 * r
               for r in range(4)]
        stacked, devs = _stacked(mat)
        out = np.asarray(stacked_collective("transpose", stacked, devs))
        for r in range(4):
            for p in range(4):
                np.testing.assert_allclose(out[r, p], mat[p][r])

    def test_shard_rows_keeps_rows_on_rank_devices(self):
        # reduce_scatter-shaped input: (nranks, nranks, payload)
        vals = [np.arange(12, dtype=np.float32).reshape(4, 3) + 10 * r for r in range(4)]
        stacked, devs = _stacked(vals)
        out = stacked_collective("reduce", stacked, devs, ReduceOp.SUM, shard_rows=True)
        assert not out.sharding.is_fully_replicated
        full = sum(vals)
        for shard in out.addressable_shards:
            r = devs.index(shard.device)
            np.testing.assert_allclose(np.asarray(shard.data)[0], full[r], rtol=1e-6)

    def test_compiled_program_contains_collective(self):
        stacked, devs = _stacked(self.vals)
        mesh = _rank_mesh(4)
        lowered = jax.jit(
            lambda x: jnp.sum(x, axis=0), out_shardings=NamedSharding(mesh, P())
        ).lower(stacked)
        hlo = lowered.compile().as_text()
        assert "all-reduce" in hlo or "all-gather" in hlo, hlo[:500]


class TestSingleProcessSemantics:
    def test_single_rank_all_reduce_identity(self):
        t = paddle.to_tensor(np.arange(4, dtype=np.float32))
        out = dist.all_reduce(t)
        np.testing.assert_allclose(out.numpy(), np.arange(4, dtype=np.float32))

    def test_single_rank_all_gather(self):
        lst = []
        dist.all_gather(lst, paddle.to_tensor(np.ones(3, dtype=np.float32)))
        assert len(lst) == 1
        np.testing.assert_allclose(lst[0].numpy(), np.ones(3))

    def test_new_group_registry(self):
        g = dist.new_group([0])
        assert g.nranks == 1 and g.rank == 0 and g.is_member()
        from paddle_tpu.distributed.collective import get_group

        assert get_group(g.id) is g

    def test_new_group_rejects_unknown_rank(self):
        with pytest.raises(ValueError):
            dist.new_group([0, 99])

    def test_send_to_self_raises(self):
        with pytest.raises(ValueError):
            dist.send(paddle.to_tensor(np.ones(2)), dst=jax.process_index())


@pytest.mark.slow
def test_two_process_collectives():
    """Real cross-process collectives over jax.distributed + Gloo."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = os.path.join(HERE, "_collective_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), "2", str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"COLLECTIVE_OK rank={r}" in out, out
        assert f"P2P_TIMEOUT_OK rank={r}" in out, out
