"""Automatic prefix caching: ref-counted KV block reuse with COW + LRU.

Acceptance criteria from the prefix-caching issue:

- serving the same prompt list twice (second pass warm) yields
  token-identical output to a cold-cache serve, with
  ``prefix_cache_hit_tokens > 0`` on the warm pass;
- `copy_blocks` backs a real copy-on-write path (src immutable after the
  copy, dst independently writable);
- after ANY interleaving of cache hits, COW appends, preemptions, and
  aborts, every block's refcount is 0 in the free/cached tiers and
  ``num_free`` returns to the idle count (the churn sweep is `slow`; a
  smoke variant stays in tier-1).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import BlockPool, LLMEngine, chain_block_hashes
from paddle_tpu.serving.metrics import ServingMetrics
from paddle_tpu.serving.scheduler import Request, Scheduler


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0, shared=0):
    rs = np.random.RandomState(seed)
    prefix = rs.randint(0, 128, (shared,)).tolist()
    return [prefix + rs.randint(0, 128, (n - shared,)).tolist()
            for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def _pool(num_blocks=16, block_size=4, metrics=None):
    return BlockPool(num_blocks=num_blocks, num_layers=1,
                     block_size=block_size, num_heads=1, head_dim=4,
                     metrics=metrics)


def assert_pool_idle(pool):
    """Every block is at refcount 0 in the free or cached tier, the two
    hash maps are exact inverses, and num_free is back to the idle count."""
    assert pool._refcount == {}
    assert pool.num_free == pool.num_blocks - 1
    assert {h: b for b, h in pool._block_hash.items()} == pool._hash_index
    for b in pool._cached:
        assert b in pool._block_hash
    tiers = set(pool._free) | set(pool._cached)
    assert len(tiers) == pool.num_blocks - 1 and 0 not in tiers


# -- hashing ---------------------------------------------------------------

def test_chain_block_hashes_commit_to_whole_prefix():
    a = chain_block_hashes([1, 2, 3, 4, 5, 6, 7, 8, 9], 4)
    b = chain_block_hashes([1, 2, 3, 4, 5, 6, 7, 8], 4)
    assert len(a) == 2 and a == b  # partial tail block hashes nothing
    # divergence in block 0 changes EVERY downstream hash (chained)
    c = chain_block_hashes([9, 2, 3, 4, 5, 6, 7, 8], 4)
    assert c[0] != a[0] and c[1] != a[1]
    # same block-0, divergent block-1
    d = chain_block_hashes([1, 2, 3, 4, 9, 6, 7, 8], 4)
    assert d[0] == a[0] and d[1] != a[1]
    assert chain_block_hashes([1, 2, 3], 4) == []


# -- pool tiers ------------------------------------------------------------

def test_release_publishes_to_cached_tier_and_matches():
    pool = _pool()
    hashes = chain_block_hashes(list(range(8)), 4)
    blocks = pool.allocate(2)
    assert pool.num_free == 13
    pool.release(blocks, hashes)
    # cached-free: both tiers count as free, blocks matchable
    assert pool.num_free == 15 and pool.num_cached_blocks == 2
    hit = pool.match_prefix(hashes)
    assert hit == blocks and pool.refcount(hit[0]) == 1
    assert pool.num_free == 13 and pool.num_cached_blocks == 0
    # a second request shares the SAME pinned blocks (live sharing)
    hit2 = pool.match_prefix(hashes)
    assert hit2 == blocks and pool.refcount(hit[0]) == 2
    pool.release(hit, hashes)
    pool.release(hit2, hashes)
    assert_pool_idle(pool)


def test_match_stops_at_first_miss():
    pool = _pool()
    hashes = chain_block_hashes(list(range(12)), 4)
    blocks = pool.allocate(3)
    pool.release(blocks, hashes[:2])  # block 2 never published
    assert pool.match_prefix(hashes) == blocks[:2]
    other = chain_block_hashes(list(range(50, 62)), 4)
    assert pool.match_prefix(other) == []  # miss at block 0 pins nothing
    pool.release(blocks[:2])


def test_allocate_prefers_truly_free_and_evicts_lru():
    metrics = ServingMetrics()
    pool = _pool(num_blocks=6, metrics=metrics)  # 5 usable
    h1 = chain_block_hashes([1] * 4, 4)
    h2 = chain_block_hashes([2] * 4, 4)
    b1 = pool.allocate(1)
    b2 = pool.allocate(1)
    pool.release(b1, h1)  # cached first -> LRU-oldest
    pool.release(b2, h2)
    # 3 truly free + 2 cached; allocating 3 must not touch the cache
    assert pool.allocate(3) is not None
    assert pool.num_cached_blocks == 2 and pool.evictions == 0
    # 4th allocation evicts the LRU entry (b1), keeping b2 matchable
    got = pool.allocate(1)
    assert got == b1 and pool.evictions == 1
    assert metrics.counters["prefix_cache_evictions"] == 1
    assert pool.match_prefix(h1) == [] and pool.match_prefix(h2) == b2
    # b2's pin took the last free block: the pool is truly dry now
    assert pool.allocate(1) is None


def test_match_refreshes_lru_position():
    pool = _pool(num_blocks=6)
    h1 = chain_block_hashes([1] * 4, 4)
    h2 = chain_block_hashes([2] * 4, 4)
    b1 = pool.allocate(1)
    b2 = pool.allocate(1)
    pool.release(b1, h1)
    pool.release(b2, h2)
    # touch b1: match + release moves it to the MRU end
    pool.release(pool.match_prefix(h1), h1)
    pool.allocate(3)          # drain truly-free
    evicted = pool.allocate(1)  # evicts the LRU entry — now b2
    assert evicted == b2
    assert pool.match_prefix(h1) == b1 and pool.match_prefix(h2) == []


def test_refcount_underflow_and_null_guards():
    pool = _pool()
    blocks = pool.allocate(2)
    pool.release(blocks)
    with pytest.raises(ValueError, match="double free"):
        pool.release([blocks[0]])
    with pytest.raises(ValueError, match="null"):
        pool.release([0])
    # shared block: each holder releases exactly once, the third raises
    h = chain_block_hashes([7] * 4, 4)
    b = pool.allocate(1)
    pool.release(b, h)
    pool.match_prefix(h)
    pool.match_prefix(h)
    pool.release(b)
    pool.release(b, h)
    with pytest.raises(ValueError, match="double free"):
        pool.release(b)
    assert pool.match_prefix(h) == b  # still cached after the guard fired
    pool.release(b, h)


def test_duplicate_content_release_frees_truly():
    pool = _pool()
    h = chain_block_hashes([3] * 4, 4)
    b1 = pool.allocate(1)
    b2 = pool.allocate(1)
    pool.release(b1, h)       # b1 owns the hash
    pool.release(b2, h)       # duplicate content -> truly free, no alias
    assert pool.num_cached_blocks == 1
    assert pool.match_prefix(h) == b1
    pool.release(b1, h)
    assert_pool_idle(pool)


def test_hashless_release_of_published_block_drops_index_entry():
    pool = _pool()
    h = chain_block_hashes([5] * 4, 4)
    b = pool.allocate(1)
    pool.release(b, h)
    pinned = pool.match_prefix(h)
    pool.release(pinned)  # e.g. tail block partially rewritten: no hash
    assert pool.match_prefix(h) == []  # never hands out a freed block
    assert_pool_idle(pool)


# -- copy-on-write ---------------------------------------------------------

def test_copy_blocks_src_immutable_dst_independent():
    import jax.numpy as jnp

    pool = _pool(num_blocks=8)
    (src,) = pool.allocate(1)
    pool.k = pool.k.at[:, :, src].set(3.0)
    pool.v = pool.v.at[:, :, src].set(4.0)
    (dst,) = pool.allocate(1)
    pool.copy_blocks([src], [dst])
    np.testing.assert_array_equal(np.asarray(pool.k[:, :, dst]), 3.0)
    np.testing.assert_array_equal(np.asarray(pool.v[:, :, dst]), 4.0)
    # dst independently writable: src keeps its bits
    pool.k = pool.k.at[:, :, dst, 0].set(9.0)
    np.testing.assert_array_equal(np.asarray(pool.k[:, :, src]), 3.0)
    # src immutable from dst's perspective too
    pool.k = pool.k.at[:, :, src].set(5.0)
    assert float(jnp.max(pool.k[:, :, dst])) == 9.0
    pool.release([src, dst])


def test_scheduler_cow_on_shared_tail_block():
    """Two requests pin the SAME fully-cached prompt: each one's first
    step feeds the last prompt token, whose scatter targets the shared
    tail block — the first writer gets a private copy (content preserved),
    the second finds the block private again and writes in place."""
    metrics = ServingMetrics()
    pool = _pool(num_blocks=16, metrics=metrics)
    prompt = list(range(8))
    hashes = chain_block_hashes(prompt, 4)
    blocks = pool.allocate(2)
    pool.k = pool.k.at[:, :, blocks[1]].set(7.0)  # recognizable content
    pool.release(blocks, hashes)

    sched = Scheduler(pool, max_batch=2, token_budget=8, prefill_chunk=8,
                      metrics=metrics)
    r1 = Request(prompt, max_new_tokens=4)
    r2 = Request(prompt, max_new_tokens=4)
    r1.block_hashes = list(hashes)
    r2.block_hashes = list(hashes)
    sched.add(r1)
    sched.add(r2)
    rows = sched.schedule()
    # both matched 2 blocks, capped at num_tokens-1 -> one pending token
    assert [(w.req, w.start, w.count, w.emit) for w in rows] == [
        (r1, 7, 1, True), (r2, 7, 1, True)
    ]
    # hit tokens count MATCHED blocks (2 x 8), not the num_tokens-1 cap —
    # a fully-cached prompt is a 100% hit
    assert metrics.counters["prefix_cache_hit_tokens"] == 16
    assert metrics.counters["prefix_cache_cow_copies"] == 1
    # r1 (planned first) copied; r2 kept the original, now private to it
    assert r1.blocks[0] == r2.blocks[0] == blocks[0]  # full block: shared
    assert r1.blocks[1] != blocks[1] and r2.blocks[1] == blocks[1]
    np.testing.assert_array_equal(
        np.asarray(pool.k[:, :, r1.blocks[1]]), 7.0)  # content came along
    for r in rows:
        r.req.num_cached += r.count
    sched.finish(r1)
    sched.finish(r2)
    assert_pool_idle(pool)


def test_scheduler_hit_skips_budget_and_starts_at_first_uncached():
    """A 12-token prompt with its first 8 tokens cached prefills ONLY the
    remaining 4 under a 4-token budget — the whole prompt would need 3
    steps cold, and the cached tokens are never charged to the budget."""
    pool = _pool(num_blocks=32)
    prompt = list(range(12))
    hashes = chain_block_hashes(prompt, 4)
    blocks = pool.allocate(3)
    pool.release(blocks, hashes[:2])  # only blocks 0,1 published
    sched = Scheduler(pool, max_batch=2, token_budget=4, prefill_chunk=4)
    req = Request(prompt, max_new_tokens=2)
    req.block_hashes = list(hashes)
    sched.add(req)
    (row,) = sched.schedule()
    assert (row.start, row.count, row.emit) == (8, 4, True)
    assert req.num_cached == 8 and req.blocks[:2] == blocks[:2]


def test_early_abort_of_fully_cached_request_keeps_index_intact():
    """Review regression: aborting (or preempting) a fully-cached request
    BEFORE its first step must republish ALL matched blocks — num_cached
    is capped below the last block boundary, but that block's content is
    still valid, and dropping its index entry would decay hot shared
    prefixes under deadline/disconnect abort load."""
    pool = _pool(num_blocks=16)
    prompt = list(range(8))
    hashes = chain_block_hashes(prompt, 4)
    blocks = pool.allocate(2)
    pool.release(blocks, hashes)
    sched = Scheduler(pool, max_batch=2, token_budget=8, prefill_chunk=8)
    req = Request(prompt, max_new_tokens=4)
    req.block_hashes = list(hashes)
    sched.add(req)
    sched.schedule()  # match pins both blocks, num_cached capped at 7
    assert req.num_cached == 7 and req.num_matched_blocks == 2
    sched.abort(req)  # before any step ran
    assert pool.match_prefix(hashes) == blocks  # BOTH still matchable
    pool.release(blocks, hashes)
    assert_pool_idle(pool)


def test_preempted_victim_repins_its_own_published_blocks():
    """Preemption releases blocks WITH their hashes: if they survive in
    the cached tier until re-admission, the replay pins them instead of
    recomputing the prompt."""
    pool = _pool(num_blocks=32)
    prompt = list(range(8))
    sched = Scheduler(pool, max_batch=2, token_budget=8, prefill_chunk=8)
    req = Request(prompt, max_new_tokens=4)
    req.block_hashes = chain_block_hashes(prompt, 4)
    sched.add(req)
    (row,) = sched.schedule()
    assert (row.start, row.count) == (0, 8)  # cold: nothing published yet
    req.num_cached += row.count
    held = list(req.blocks)
    sched._preempt(req)
    assert pool.num_cached_blocks == 2  # both full blocks published
    (row,) = sched.schedule()
    # replay starts at the capped hit (7 of 8 tokens), reusing the blocks
    assert (row.start, row.count) == (7, 1)
    assert req.blocks[0] == held[0]
    sched.finish(req)
    assert_pool_idle(pool)


# -- engine end-to-end -----------------------------------------------------

def test_warm_serve_token_identical_with_hits(model):
    """THE acceptance test: same batch served twice through one engine —
    the warm pass is token-for-token identical, reports hit tokens, and
    still compiles nothing new; a cache-disabled engine agrees."""
    prompts = _prompts((21, 25, 29), seed=3, shared=18)
    prompts.append(_prompts((16,), seed=4)[0])  # fully-cached-prompt edge
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    assert engine.prefix_cache
    cold = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    hits_cold = engine.metrics.counters.get("prefix_cache_hit_tokens", 0)
    warm = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    hits_warm = engine.metrics.counters["prefix_cache_hit_tokens"] - hits_cold
    assert warm == cold
    assert hits_warm > 0
    assert engine.metrics.counters["jit_traces"] == 2
    assert engine.metrics.gauges["prefix_cache_hit_rate"] > 0
    off = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64,
                    prefix_cache=False)
    assert off.generate(prompts, max_new_tokens=6, temperature=0.0) == cold
    for p, o in zip(prompts, cold):
        assert o == _reference(model, p, 6)
    assert_pool_idle(engine.pool)


def test_cache_hit_serve_matches_reference_mid_traffic(model):
    """Warm requests joining COLD traffic mid-decode stay exact: a shared
    prefix is published by an early finisher while a longer stranger is
    still decoding, then a warm request rides the same steps."""
    p_shared = _prompts((14,), seed=5)[0]
    p_other = _prompts((9,), seed=6)[0]
    engine = LLMEngine(model, block_size=4, max_batch=4, max_seq_len=64)
    r1 = engine.add_request(p_shared, max_new_tokens=4, temperature=0.0)
    r2 = engine.add_request(p_other, max_new_tokens=12, temperature=0.0)
    while not engine.get_request(r1).finished:
        engine.step()
    # r1 finished -> its prefix published; r2 still decoding
    r3 = engine.add_request(p_shared + [5, 9], max_new_tokens=4,
                            temperature=0.0)
    hits0 = engine.metrics.counters.get("prefix_cache_hit_tokens", 0)
    while engine.has_unfinished():
        engine.step()
    assert engine.metrics.counters["prefix_cache_hit_tokens"] > hits0
    assert engine.get_request(r1).output_ids == _reference(model, p_shared, 4)
    assert engine.get_request(r2).output_ids == _reference(model, p_other, 12)
    assert engine.get_request(r3).output_ids == _reference(
        model, p_shared + [5, 9], 4)
    assert_pool_idle(engine.pool)


def test_prefix_cache_disable_flag_and_env(model, monkeypatch):
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                       prefix_cache=False)
    prompts = _prompts((17, 19), seed=7, shared=16)
    out1 = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
    out2 = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
    assert out1 == out2
    assert "prefix_cache_hit_tokens" not in engine.metrics.counters
    assert "prefix_cache_lookup_tokens" not in engine.metrics.counters
    assert engine.pool.num_cached_blocks == 0
    # env kill switch drives the default; explicit ctor arg wins over it
    monkeypatch.setenv("PADDLE_TPU_PREFIX_CACHE", "0")
    assert not LLMEngine(model, block_size=8, max_batch=2,
                         max_seq_len=64).prefix_cache
    assert LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                     prefix_cache=True).prefix_cache
    monkeypatch.setenv("PADDLE_TPU_PREFIX_CACHE", "1")
    assert LLMEngine(model, block_size=8, max_batch=2,
                     max_seq_len=64).prefix_cache


# -- pool-invariant churn sweep (issue satellite) --------------------------

def _churn(model, rounds, seed):
    """Interleave cache hits, COW appends, preemptions, evictions, and
    aborts through a deliberately tiny pool, checking exactness for every
    surviving request and the pool invariant after every round."""
    rs = np.random.RandomState(seed)
    engine = LLMEngine(model, block_size=4, num_blocks=10, max_batch=3,
                       max_seq_len=64, prefill_chunk=8)
    idle_free = engine.pool.num_blocks - 1
    prefixes = [rs.randint(0, 128, (8,)).tolist() for _ in range(3)]
    for rnd in range(rounds):
        reqs = []
        for i in range(rs.randint(2, 5)):
            # tail 0 = the prompt IS a published prefix: the fully-cached
            # match caps at num_tokens-1 and appends through COW
            p = (prefixes[rs.randint(len(prefixes))]
                 + rs.randint(0, 128, (rs.randint(0, 9),)).tolist())
            reqs.append(engine.add_request(
                p, max_new_tokens=int(rs.randint(2, 8)), temperature=0.0))
        doomed = set(rs.choice(reqs, size=len(reqs) // 3, replace=False)
                     .tolist()) if len(reqs) >= 3 else set()
        steps = 0
        while engine.has_unfinished():
            engine.step()
            steps += 1
            if steps == 2:
                for rid in doomed:
                    engine.abort(rid)
        for rid in reqs:
            if rid in doomed:
                continue
            req = engine.get_request(rid)
            prompt = req.prompt_ids
            assert req.output_ids == _reference(
                model, prompt, req.max_new_tokens), f"round {rnd}"
            engine.release(rid)
        # every round ends idle: refcounts all zero, num_free restored
        assert engine.pool.num_free == idle_free, f"round {rnd}"
        assert_pool_idle(engine.pool)
    c = engine.metrics.counters
    # the sweep must actually exercise the mechanisms it claims to
    assert c.get("prefix_cache_hit_tokens", 0) > 0
    return c


def test_cache_churn_smoke(model):
    """Always-on tier-1 smoke: few rounds, same invariant checks."""
    c = _churn(model, rounds=3, seed=0)
    assert c.get("requests_aborted", 0) > 0


@pytest.mark.slow
def test_cache_churn_soak(model):
    """Soak-style sweep across more rounds and seeds (slow tier): enough
    churn that hits, COW, evictions, aborts, AND preemptions all fire."""
    merged = {}
    for seed in (1, 2):
        c = _churn(model, rounds=8, seed=seed)
        for k, v in c.items():
            merged[k] = merged.get(k, 0) + v
    assert merged.get("preemptions", 0) > 0
    assert merged.get("prefix_cache_evictions", 0) > 0
    assert merged.get("prefix_cache_cow_copies", 0) > 0
    assert merged.get("requests_aborted", 0) > 0
