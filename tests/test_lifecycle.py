"""Replica lifecycle (serving/lifecycle.py) + the warm guarantee.

The state machine is the contract the router and autoscaler program
against: cold → loading → warm → serving ⇄ draining → stopped, every
edge validated (`LifecycleError` on an illegal jump), exactly one
terminal stamp, and the state surfaced on `/healthz`, `/metrics`
(`lifecycle_state` gauge), and the router's replica snapshots — so the
half-open probe can DEFER instead of firing a trial request into a
still-compiling replica. `warm` is a guarantee, not a label:
`LLMEngine(warmup=True)` compiles every width bucket via a synthetic
warmup wave, so the first served request after `start()` /
`resume_admitting()` / a factory restart runs with ZERO retraces
(the `jit_traces` sentinel).
"""
import asyncio
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    LifecycleError,
    LLMEngine,
    ReplicaLifecycle,
    ReplicaRouter,
    ServingMetrics,
)
from paddle_tpu.serving.lifecycle import LEGAL, STATES
from paddle_tpu.serving.router import ACTIVE, EJECTED


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(model, **kw)


# -- the state machine, exhaustively ------------------------------------------


def test_every_edge_of_the_matrix():
    for src in STATES:
        for dst in STATES:
            lc = ReplicaLifecycle()
            lc._state = src          # jump straight to the source state
            if dst == src:
                assert lc.to(dst) is False   # same-state no-op
                assert lc.state == src
            elif dst in LEGAL[src]:
                assert lc.to(dst, "edge test") is True
                assert lc.state == dst
            else:
                with pytest.raises(LifecycleError, match=f"{src} -> {dst}"):
                    lc.to(dst)
                assert lc.state == src       # failed jump changes nothing


def test_terminal_is_terminal():
    lc = ReplicaLifecycle()
    lc.to("stopped", "crash before load")
    assert lc.terminal
    assert lc.to("stopped") is False         # idempotent stamp
    for dst in ("cold", "loading", "warm", "serving", "draining"):
        with pytest.raises(LifecycleError):
            lc.to(dst)


def test_history_and_transitions():
    lc = ReplicaLifecycle()
    for s in ("loading", "warm", "serving", "draining", "serving",
              "draining", "stopped"):
        lc.to(s, f"to {s}")
    assert lc.transitions() == [
        ("cold", "loading"), ("loading", "warm"), ("warm", "serving"),
        ("serving", "draining"), ("draining", "serving"),
        ("serving", "draining"), ("draining", "stopped")]
    snap = lc.snapshot()
    assert snap["state"] == "stopped"
    assert snap["history"][-1]["state"] == "stopped"
    # every recorded edge is legal and exactly one terminal stamp exists
    assert all(b in LEGAL[a] for a, b in lc.transitions())
    assert sum(1 for _, b in lc.transitions() if b == "stopped") == 1


def test_gauge_tracks_state():
    m = ServingMetrics()
    lc = ReplicaLifecycle(metrics=m)
    assert m.gauges["lifecycle_state"] == STATES.index("cold")
    lc.to("loading")
    lc.to("warm")
    assert m.gauges["lifecycle_state"] == STATES.index("warm")


# -- engine + frontend integration --------------------------------------------


def test_engine_lifecycle_through_serve_and_drain(model):
    eng = _engine(model)
    assert eng.lifecycle.state == "warm"     # built + weights placed
    assert eng.lifecycle.transitions() == [("cold", "loading"),
                                           ("loading", "warm")]

    async def run():
        fe = AsyncLLMEngine(eng)
        await fe.start()
        assert fe.lifecycle_state() == "serving"
        fe.stop_admitting()
        assert fe.lifecycle_state() == "draining"
        fe.resume_admitting()
        assert fe.lifecycle_state() == "serving"
        out, reason = await fe.submit([1, 2, 3], max_new_tokens=2,
                                      temperature=0.0).collect()
        assert reason in ("length", "stop") and len(out) == 2
        await fe.shutdown()
        assert fe.lifecycle_state() == "stopped"
        snap = fe.lifecycle_snapshot()
        assert snap["state"] == "stopped"
        return fe

    fe = asyncio.run(run())
    tr = eng.lifecycle.transitions()
    assert all(b in LEGAL[a] for a, b in tr)
    assert sum(1 for _, b in tr if b == "stopped") == 1
    # the /healthz surface carries the word
    state, _ = fe.healthz_state()
    assert state in ("draining", "engine_dead")


def test_warmup_compiles_every_bucket_zero_retraces_on_serve(model):
    eng = _engine(model, warmup=True)
    expected = eng.expected_program_count()
    assert eng.metrics.counters["jit_traces"] == expected
    assert eng.lifecycle.warmed and eng.lifecycle.programs_compiled == expected
    assert eng.metrics.gauges["warmup_programs"] == expected
    # warmup leaves no residue: no live requests, pool fully idle
    assert not eng.has_unfinished()
    assert eng.pool._refcount == {}

    async def serve():
        fe = AsyncLLMEngine(eng)
        await fe.start()
        fe.stop_admitting()
        fe.resume_admitting()   # the satellite: warm survives re-admission
        out, reason = await fe.submit(
            list(np.random.RandomState(7).randint(0, 128, (9,))),
            max_new_tokens=3, temperature=0.0).collect()
        assert reason in ("length", "stop") and len(out) == 3
        await fe.shutdown()

    asyncio.run(serve())
    # THE warm guarantee: the first served wave retraced NOTHING
    assert eng.metrics.counters["jit_traces"] == expected


def test_warmup_reaches_the_drafted_spec_bucket(model):
    eng = _engine(model, warmup=True, spec_decoding=True, num_spec_tokens=3)
    expected = eng.expected_program_count()
    assert expected == len(eng.width_buckets)
    assert eng.metrics.counters["jit_traces"] == expected
    # every bucket's program exists under the unified (B, W) keying
    assert {w for _, w in eng._step_fns} == set(eng.width_buckets)


def test_factory_restart_starts_warm(model):
    """The autoscaler/router birth path: a factory-built warmed engine's
    FIRST served request after start() retraces nothing."""
    eng = _engine(model, warmup=True)
    traced = eng.metrics.counters["jit_traces"]

    async def run():
        fe = AsyncLLMEngine(eng)
        await fe.start()
        out, _ = await fe.submit([5, 6, 7, 8], max_new_tokens=2,
                                 temperature=0.0).collect()
        await fe.shutdown()
        return out

    assert len(asyncio.run(run())) == 2
    assert eng.metrics.counters["jit_traces"] == traced


# -- the router consults lifecycle --------------------------------------------


def test_probe_defers_on_mid_birth_replica(model):
    """An ejected replica whose engine is still cold/loading/warm gets
    its probe DEFERRED (rescheduled, no failure counted) — never a trial
    request into a still-compiling engine."""

    async def run():
        router = ReplicaRouter(
            [AsyncLLMEngine(_engine(model)) for _ in range(2)],
            sweep_interval_s=3600.0)
        await router.start()
        victim = router.replicas[1]
        victim.state = EJECTED
        victim.next_probe_at = 0.0
        for fake in ("cold", "loading", "warm"):
            victim.engine.lifecycle_state = lambda s=fake: s
            await router._probe(victim)
            assert victim.state == EJECTED           # still out, no flap
            assert victim.next_probe_at > time.monotonic()
            victim.next_probe_at = 0.0
        assert router.metrics.counters["router_probe_deferrals"] == 3
        assert victim.probe_failures == 0    # deferral is not a failure
        # lifecycle rides the routing table snapshot
        snap = router.snapshot()
        assert all("lifecycle" in r for r in snap["replicas"])
        # a replica that reached `serving` probes normally and re-enters
        del victim.engine.lifecycle_state            # restore the real one
        await router._probe(victim)
        assert victim.state == ACTIVE
        await router.shutdown()

    asyncio.run(run())
