"""Abort/cancellation path: scheduler removal + KV block reclamation.

The serving-frontend issue's edge cases: abort a QUEUED request (never
admitted), abort a request MID-PREFILL-CHUNK (blocks allocated, no token
emitted yet), and abort a PREEMPTED request awaiting re-admission — in
every case the blocks return to the pool, `schedule()` never emits a row
for the aborted request again, and surviving requests still produce
token-exact greedy output.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import BlockPool, LLMEngine
from paddle_tpu.serving.scheduler import Request, Scheduler


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def _pool():
    return BlockPool(num_blocks=16, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)


def test_abort_queued_request_never_scheduled():
    """Abort before admission: the request leaves the waiting queue and no
    schedule() call ever emits a row for it."""
    pool = _pool()
    sched = Scheduler(pool, max_batch=1, token_budget=8, prefill_chunk=8)
    r1 = Request([1] * 4, max_new_tokens=4)
    r2 = Request([2] * 4, max_new_tokens=4)  # stuck behind r1 (one lane)
    sched.add(r1)
    sched.add(r2)
    rows = sched.schedule()
    assert [w.req for w in rows] == [r1]
    sched.abort(r2)
    assert r2.finished and r2.aborted and r2 not in sched.waiting
    r1.num_cached += rows[0].count
    for _ in range(4):  # r2 must never surface even as lanes free up
        assert all(w.req is not r2 for w in sched.schedule())
    sched.finish(r1)
    assert sched.schedule() == [] and not sched.has_unfinished()
    assert pool.num_free == pool.num_blocks - 1


def test_abort_mid_prefill_chunk_frees_blocks():
    """Abort between two prefill chunks: allocated blocks go back to the
    pool and the half-written KV is never walked again."""
    pool = _pool()
    sched = Scheduler(pool, max_batch=2, token_budget=4, prefill_chunk=4)
    req = Request([1] * 10, max_new_tokens=4)  # 3 chunks of <=4
    sched.add(req)
    (row,) = sched.schedule()
    req.num_cached += row.count
    assert req.blocks and pool.num_free < pool.num_blocks - 1
    sched.abort(req)
    assert not req.blocks and req.num_cached == 0
    assert pool.num_free == pool.num_blocks - 1
    assert sched.schedule() == [] and not sched.has_unfinished()


def test_abort_preempted_request_awaiting_readmission():
    """A preempted request sits at the FRONT of the waiting queue holding
    no blocks; abort must pull it out so re-admission can never replay it."""
    pool = _pool()
    sched = Scheduler(pool, max_batch=2, token_budget=8, prefill_chunk=8)
    r1 = Request([1] * 8, max_new_tokens=4)
    sched.add(r1)
    (row,) = sched.schedule()
    r1.num_cached += row.count
    sched._preempt(r1)
    assert r1 in sched.waiting and not r1.blocks
    sched.abort(r1)
    assert r1 not in sched.waiting and r1.aborted
    assert sched.schedule() == [] and not sched.has_unfinished()
    assert pool.num_free == pool.num_blocks - 1


def test_abort_is_idempotent_and_terminal():
    pool = _pool()
    sched = Scheduler(pool, max_batch=1, token_budget=8, prefill_chunk=8)
    req = Request([1] * 4, max_new_tokens=4)
    sched.add(req)
    sched.schedule()
    sched.abort(req)
    sched.abort(req)  # no double free, no error
    assert pool.num_free == pool.num_blocks - 1
    done = Request([1] * 4, max_new_tokens=4)
    sched.add(done)
    sched.schedule()
    sched.finish(done)
    sched.abort(done)  # aborting a finished request is a no-op
    assert done.state == "finished"


def test_block_pool_double_free_raises():
    pool = _pool()
    blocks = pool.allocate(2)
    pool.free(blocks)
    with pytest.raises(ValueError, match="double free"):
        pool.free([blocks[0]])
    with pytest.raises(ValueError, match="double free"):
        pool.free([pool.num_blocks - 1] if pool.num_blocks - 1 not in blocks
                  else [blocks[1]])


def test_engine_abort_mid_decode_survivors_exact(model):
    """LLMEngine.abort mid-serve: the aborted request's blocks return to
    the pool, its record is released, and the surviving requests' greedy
    streams stay token-for-token exact."""
    p_kill, p_keep = _prompts((9, 7), seed=3)
    engine = LLMEngine(model, block_size=4, max_batch=4, max_seq_len=64,
                       prefill_chunk=4)
    rid_kill = engine.add_request(p_kill, max_new_tokens=12, temperature=0.0)
    rid_keep = engine.add_request(p_keep, max_new_tokens=12, temperature=0.0)
    while len(engine.get_request(rid_kill).output_ids) < 3:
        engine.step()
    assert engine.abort(rid_kill) is True
    assert engine.abort(rid_kill) is False  # already gone
    assert engine.abort("nope") is False
    assert engine.metrics.counters["requests_aborted"] == 1
    streamed = []
    while engine.has_unfinished():
        for out in engine.step():
            assert out.request_id == rid_keep  # never re-emitted
            streamed.append(out.token)
    ref = _reference(model, p_keep, 12)
    assert engine.get_request(rid_keep).output_ids == ref
    assert streamed == ref[len(ref) - len(streamed):]
    engine.release(rid_keep)
    assert engine.pool.num_free == engine.pool.num_blocks - 1
    assert engine._requests == {}


def test_abort_shared_prefix_blocks_pool_invariant(model):
    """Abort requests that hold PINNED cache-hit blocks (refcount > 1 with
    a sibling): each abort drops exactly one reference, the survivor's
    stream stays exact, and once everything finishes the pool is idle —
    every refcount zero, num_free back to the idle count (cached-free
    blocks count as free)."""
    from tests.test_prefix_cache import assert_pool_idle

    p_shared = _prompts((14,), seed=5)[0]
    engine = LLMEngine(model, block_size=4, max_batch=4, max_seq_len=64)
    # publish the prefix, then pin it from two warm requests
    engine.generate([p_shared], max_new_tokens=2, temperature=0.0)
    assert engine.pool.num_cached_blocks > 0
    r1 = engine.add_request(p_shared + [3], max_new_tokens=8, temperature=0.0)
    r2 = engine.add_request(p_shared + [9], max_new_tokens=8, temperature=0.0)
    engine.step()
    assert engine.metrics.counters["prefix_cache_hit_tokens"] >= 24
    shared_block = engine.get_request(r1).blocks[0]
    assert engine.get_request(r2).blocks[0] == shared_block
    assert engine.pool.refcount(shared_block) == 2
    assert engine.abort(r1) is True           # one ref down, sibling lives
    assert engine.pool.refcount(shared_block) == 1
    while engine.has_unfinished():
        engine.step()
    assert engine.get_request(r2).output_ids == _reference(
        model, p_shared + [9], 8)
    engine.release(r2)
    assert engine.pool.num_free == engine.pool.num_blocks - 1
    assert_pool_idle(engine.pool)


def test_engine_abort_queued_and_preempted(model):
    """Abort across states through the engine API: one request still
    queued (tiny pool keeps it out), one preempted; pool returns to idle
    and the survivor completes exactly."""
    prompts = _prompts((6, 7, 9), seed=1)
    engine = LLMEngine(model, block_size=4, num_blocks=10, max_batch=4,
                       max_seq_len=64)
    rids = [engine.add_request(p, max_new_tokens=10, temperature=0.0)
            for p in prompts]
    engine.step()
    # drive until somebody gets preempted (pool of 9 usable blocks forces it)
    for _ in range(30):
        if engine.metrics.counters["preemptions"] >= 1:
            break
        engine.step()
    assert engine.metrics.counters["preemptions"] >= 1
    # abort everything except the first request, whatever state it's in
    for rid in rids[1:]:
        engine.abort(rid)
    while engine.has_unfinished():
        for out in engine.step():
            assert out.request_id == rids[0]
    assert engine.get_request(rids[0]).output_ids == _reference(
        model, prompts[0], 10)
    engine.release(rids[0])
    assert engine.pool.num_free == engine.pool.num_blocks - 1
