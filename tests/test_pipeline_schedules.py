"""1F1B + interleaved pipeline schedules (VERDICT round-1 item 3).

Reference parity: meta_parallel/pipeline_parallel.py:117 (1F1B) and :461
(interleaved virtual stages). Checks: numerical equality with non-pipelined
execution, bounded activation memory vs GPipe, cross-mesh/schedule GPT
trajectory equality, and the user-facing PipelineLayer/fleet dispatch.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.mesh import init_mesh
from paddle_tpu.parallel.pipeline import (
    interleaved_one_f_one_b,
    one_f_one_b,
    stack_interleaved_params,
    stack_stage_params,
)

M, MB, D = 6, 4, 8


def _mlp_stages(n, seed=0):
    rs = np.random.RandomState(seed)
    return [
        {"w": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3),
         "b": jnp.asarray(rs.randn(D).astype(np.float32) * 0.1)}
        for _ in range(n)
    ]


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _loss_fn(y, lab):
    return jnp.mean((y - lab) ** 2)


def _data(seed=0):
    rs = np.random.RandomState(seed)
    return (jnp.asarray(rs.randn(M, MB, D).astype(np.float32)),
            jnp.asarray(rs.randn(M, MB, D).astype(np.float32)))


def _ref_loss_and_grads(stages, x, labs):
    def ref(stages_list):
        tot = 0.0
        for i in range(M):
            h = x[i]
            for p in stages_list:
                h = _stage_fn(p, h)
            tot = tot + _loss_fn(h, labs[i])
        return tot / M

    return jax.value_and_grad(ref)(stages)


class Test1F1B:
    def test_matches_sequential_pp4_dp2(self):
        mesh = init_mesh({"pp": 4, "dp": 2})
        stages = _mlp_stages(4)
        x, labs = _data()
        loss, grads = one_f_one_b(
            _stage_fn, _loss_fn, stack_stage_params(stages), x, labs, mesh,
            io_spec=P(None, "dp"), label_spec=P(None, "dp"), reduce_axes=("dp",),
        )
        rl, rg = _ref_loss_and_grads(stages, x, labs)
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        rgs = stack_stage_params(rg)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(rgs[k]), rtol=1e-4, atol=1e-6
            )

    def test_head_and_input_grads(self):
        """Fused head grads + d(loss)/d(inputs) against jax.grad of the same
        composite (head = extra linear layer folded into the last stage)."""
        mesh = init_mesh({"pp": 2})
        stages = _mlp_stages(2)
        rs = np.random.RandomState(3)
        head = {"wh": jnp.asarray(rs.randn(D, D).astype(np.float32) * 0.3)}
        x, labs = _data(3)

        def head_loss(h, y, lab):
            return _loss_fn(y @ h["wh"], lab)

        loss, grads, hgrads, dmbs = one_f_one_b(
            _stage_fn, head_loss, stack_stage_params(stages), x, labs, mesh,
            head_params=head, return_input_grads=True,
        )

        def ref(stages_list, h, xx):
            tot = 0.0
            for i in range(M):
                hh = xx[i]
                for p in stages_list:
                    hh = _stage_fn(p, hh)
                tot = tot + _loss_fn(hh @ h["wh"], labs[i])
            return tot / M

        rl, (rg, rh, rx) = jax.value_and_grad(ref, argnums=(0, 1, 2))(stages, head, x)
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(hgrads["wh"]), np.asarray(rh["wh"]), rtol=1e-4, atol=1e-6
        )
        np.testing.assert_allclose(np.asarray(dmbs), np.asarray(rx), rtol=1e-4, atol=1e-6)

    def test_interleaved_matches_sequential(self):
        mesh = init_mesh({"pp": 2, "dp": 2})
        vstages = _mlp_stages(4, seed=1)  # V=2 chunks x P=2 devices
        x, labs = _data(1)
        loss, grads = interleaved_one_f_one_b(
            _stage_fn, _loss_fn, stack_interleaved_params(vstages, 2), x, labs,
            mesh, n_chunks=2, io_spec=P(None, "dp"), label_spec=P(None, "dp"),
            reduce_axes=("dp",),
        )
        rl, rg = _ref_loss_and_grads(vstages, x, labs)
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        rgs = stack_interleaved_params(rg, 2)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(rgs[k]), rtol=1e-4, atol=1e-6
            )

    def test_interleaved_microbatches_not_multiple_of_stages(self):
        """M % P != 0 must still schedule every backward (the scan length
        accounts for the partial final block)."""
        mesh = init_mesh({"pp": 2})
        vstages = _mlp_stages(4, seed=2)  # V=2 x P=2
        x_all, labs_all = _data(3)
        m = 5  # odd vs P=2
        x, labs = x_all[:m], labs_all[:m]
        loss, grads = interleaved_one_f_one_b(
            _stage_fn, _loss_fn, stack_interleaved_params(vstages, 2), x, labs,
            mesh, n_chunks=2,
        )

        def ref(stages_list):
            tot = 0.0
            for i in range(m):
                h = x[i]
                for p in stages_list:
                    h = _stage_fn(p, h)
                tot = tot + _loss_fn(h, labs[i])
            return tot / m

        rl, rg = jax.value_and_grad(ref)(vstages)
        np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
        rgs = stack_interleaved_params(rg, 2)
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(grads[k]), np.asarray(rgs[k]), rtol=1e-4, atol=1e-6
            )


class TestGPTSchedules:
    def _train(self, degrees, sched, steps=3):
        from paddle_tpu.models.gpt import GPTConfig
        from paddle_tpu.models.gpt_pipeline import make_pipelined_gpt

        rs = np.random.RandomState(0)
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=4,
                        num_heads=2, max_seq_len=32)
        ids = jnp.asarray(rs.randint(0, 128, (8, 32)))
        labs = jnp.asarray(rs.randint(0, 128, (8, 32)))
        mesh = init_mesh(degrees)
        params, step = make_pipelined_gpt(cfg, mesh, n_microbatches=4, schedule=sched)
        p, ls = params, []
        for _ in range(steps):
            loss, p = step(p, ids, labs, jnp.float32(1e-1))
            ls.append(float(loss))
        return ls

    @pytest.mark.slow
    def test_cross_mesh_and_schedule_trajectories_agree(self):
        base = self._train({"pp": 2}, "gpipe")
        np.testing.assert_allclose(self._train({"pp": 2}, "1f1b"), base, rtol=3e-4)
        np.testing.assert_allclose(
            self._train({"pp": 2, "mp": 2, "dp": 2}, "1f1b"), base, rtol=3e-4
        )
        np.testing.assert_allclose(
            self._train({"pp": 2, "mp": 2, "dp": 2}, "gpipe"), base, rtol=3e-4
        )

    def test_1f1b_activation_memory_bounded(self):
        """At M=32 microbatches GPipe's scan stacks every tick's output while
        1F1B holds a 2P-slot ring buffer — compiled temp memory must differ
        by a wide margin (reference pipeline_parallel.py:117 motivation)."""
        from paddle_tpu.models.gpt import GPTConfig
        from paddle_tpu.models.gpt_pipeline import make_pipelined_gpt

        cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=4,
                        num_heads=2, max_seq_len=64)
        mesh = init_mesh({"pp": 4})
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, 128, (64, 64)))
        labs = jnp.asarray(rs.randint(0, 128, (64, 64)))
        temps = {}
        for sched in ("gpipe", "1f1b"):
            params, step = make_pipelined_gpt(cfg, mesh, 32, schedule=sched)
            ma = step.lower(params, ids, labs, jnp.float32(1e-3)).compile().memory_analysis()
            if ma is None:
                pytest.skip("memory_analysis unavailable on this backend")
            temps[sched] = ma.temp_size_in_bytes
        assert temps["1f1b"] * 4 < temps["gpipe"], temps


class _Block(nn.Layer):
    def __init__(self, h):
        super().__init__()
        self.fc = nn.Linear(h, h)

    def forward(self, x):
        return paddle.tanh(self.fc(x))


class TestPipelineLayerDispatch:
    def _build(self, seed):
        from paddle_tpu.distributed.fleet.meta_parallel import LayerDesc, PipelineLayer

        paddle.seed(seed)
        descs = (
            [LayerDesc(nn.Linear, 8, 16)]
            + [LayerDesc(_Block, 16) for _ in range(4)]
            + [LayerDesc(nn.Linear, 16, 4)]
        )
        return PipelineLayer(layers=descs, num_stages=4, loss_fn=nn.MSELoss())

    def test_fleet_pp_dispatches_compiled_1f1b(self):
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import DistributedStrategy

        strategy = DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 4, "sharding_degree": 2,
        }
        strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
        fleet.init(is_collective=True, strategy=strategy)

        rs = np.random.RandomState(0)
        X = paddle.to_tensor(rs.randn(8, 8).astype(np.float32))
        Y = paddle.to_tensor(rs.randn(8, 4).astype(np.float32))

        def run(force_fallback):
            m = self._build(7)
            opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
            wrapped = fleet.fleet.distributed_model(m)
            opt = fleet.fleet.distributed_optimizer(opt)
            if force_fallback:
                wrapped._pipe = False
            losses = [
                float(np.asarray(wrapped.train_batch((X, Y), opt)._array))
                for _ in range(4)
            ]
            return wrapped, losses

        piped, t1 = run(False)
        assert piped._pipe, "PipelineParallel did not build the compiled 1F1B path"
        _, t2 = run(True)
        np.testing.assert_allclose(t1, t2, rtol=2e-4)
        assert t1[-1] < t1[0]  # actually training

    def test_fleet_pp_global_norm_clip(self):
        """Global-norm clipping under pp>1 must span ALL stages' grads
        (VERDICT round-2 item 8): skew one stage's weights so its grads
        dominate the global norm, then compiled-1F1B and the degree-1
        sequential fallback must produce identical clipped trajectories."""
        from paddle_tpu.distributed import fleet
        from paddle_tpu.distributed.fleet import DistributedStrategy
        from paddle_tpu.nn import ClipGradByGlobalNorm

        strategy = DistributedStrategy()
        strategy.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 4, "sharding_degree": 2,
        }
        strategy.pipeline_configs = {"accumulate_steps": 4, "micro_batch_size": 2}
        fleet.init(is_collective=True, strategy=strategy)

        rs = np.random.RandomState(1)
        X = paddle.to_tensor(rs.randn(8, 8).astype(np.float32))
        Y = paddle.to_tensor(rs.randn(8, 4).astype(np.float32) * 5)

        def run(force_fallback):
            m = self._build(13)
            # skew: inflate the LAST trunk stage's weights so its grads
            # dwarf the others — a per-stage-only norm would clip wrongly
            trunk = [l for l in m._funcs if isinstance(l, nn.Linear)]
            big = trunk[-2]
            big.weight.set_value(np.asarray(big.weight.numpy()) * 20.0)
            opt = paddle.optimizer.SGD(
                learning_rate=0.05, parameters=m.parameters(),
                grad_clip=ClipGradByGlobalNorm(0.5),
            )
            wrapped = fleet.fleet.distributed_model(m)
            opt = fleet.fleet.distributed_optimizer(opt)
            if force_fallback:
                wrapped._pipe = False
            return [
                float(np.asarray(wrapped.train_batch((X, Y), opt)._array))
                for _ in range(4)
            ]

        t1 = run(False)
        t2 = run(True)
        np.testing.assert_allclose(t1, t2, rtol=2e-4)
        assert np.isfinite(t1).all()


def test_fleet_distributed_scaler():
    """fleet.distributed_scaler wraps GradScaler and unwraps the hybrid
    optimizer for step/minimize (reference hybrid_parallel_gradscaler)."""
    import numpy as _np

    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy

    strategy = DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                               "sharding_degree": 1}
    fleet.init(is_collective=True, strategy=strategy)
    w = paddle.Parameter(_np.array([2.0], _np.float32))
    opt = fleet.fleet.distributed_optimizer(
        paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    )
    scaler = fleet.fleet.distributed_scaler(
        paddle.amp.GradScaler(init_loss_scaling=4.0, use_dynamic_loss_scaling=False)
    )
    loss = (w * 3.0).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    assert abs(float(w.numpy()[0]) - (2.0 - 0.1 * 3.0)) < 1e-6

    # documented unscale_ -> clip -> step pattern through the hybrid wrapper
    # must unscale exactly ONCE (per-optimizer state keys one identity)
    opt.clear_grad()
    loss = (w * 3.0).sum()
    scaler.scale(loss).backward()
    scaler.unscale_(opt)
    before = float(w.numpy()[0])
    scaler.step(opt)
    scaler.update()
    assert abs(float(w.numpy()[0]) - (before - 0.1 * 3.0)) < 1e-6
