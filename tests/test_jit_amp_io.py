"""jit.to_static, amp, DataLoader, PyLayer, recompute tests."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


def test_to_static_function():
    calls = []

    @paddle.jit.to_static
    def f(x, y):
        calls.append(1)
        return x * 2 + y

    a = paddle.to_tensor(np.ones(4, np.float32))
    b = paddle.to_tensor(np.ones(4, np.float32))
    out1 = f(a, b)
    out2 = f(a, b)
    assert np.allclose(out1.numpy(), 3.0)
    assert np.allclose(out2.numpy(), 3.0)
    assert len(calls) == 1  # traced once, cached executable reused


def test_to_static_layer():
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    x = paddle.randn([3, 4])
    ref = net(x).numpy()
    snet = paddle.jit.to_static(net)
    out = snet(x)
    assert np.allclose(out.numpy(), ref, atol=1e-5)


def test_dataloader_basics():
    from paddle_tpu.io import DataLoader, TensorDataset

    xs = paddle.randn([20, 3])
    ys = paddle.arange(20)
    ds = TensorDataset([xs, ys])
    loader = DataLoader(ds, batch_size=6, shuffle=False, drop_last=False)
    batches = list(loader)
    assert len(batches) == 4
    x0, y0 = batches[0]
    assert x0.shape == [6, 3]
    assert y0.numpy().tolist() == [0, 1, 2, 3, 4, 5]


def test_dataloader_shuffle_and_drop():
    from paddle_tpu.io import DataLoader, TensorDataset

    ds = TensorDataset([paddle.arange(10)])
    loader = DataLoader(ds, batch_size=3, shuffle=True, drop_last=True)
    batches = list(loader)
    assert len(batches) == 3


def test_distributed_batch_sampler():
    from paddle_tpu.io import DistributedBatchSampler, TensorDataset

    ds = TensorDataset([paddle.arange(10)])
    s0 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
    s1 = DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
    i0 = [i for b in s0 for i in b]
    i1 = [i for b in s1 for i in b]
    assert len(i0) == len(i1) == 5
    assert set(i0) | set(i1) == set(range(10))


def test_amp_autocast_flags():
    from paddle_tpu.amp.auto_cast import amp_state

    assert not amp_state().enabled
    with paddle.amp.auto_cast():
        assert amp_state().enabled
        assert amp_state().dtype == "bfloat16"
    assert not amp_state().enabled


def test_grad_scaler_noop_flow():
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    scaler = paddle.amp.GradScaler(enable=False)
    loss = (w * 2.0).sum()
    scaled = scaler.scale(loss)
    scaled.backward()
    scaler.step(opt)
    assert abs(w.numpy()[0] - 0.8) < 1e-6


def test_grad_scaler_dynamic():
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0, incr_every_n_steps=1)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    loss = (w * 1.0).sum()
    scaler.scale(loss).backward()
    scaler.step(opt)
    scaler.update()
    assert scaler._scale == 8.0  # grew after a good step


def test_grad_scaler_single_unscale_with_clip():
    # documented pattern: unscale_ -> clip -> step must divide by scale ONCE
    scaler = paddle.amp.GradScaler(init_loss_scaling=4.0, use_dynamic_loss_scaling=False)
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w])
    loss = (w * 2.0).sum()  # dL/dw = 2
    scaler.scale(loss).backward()  # grad = 8
    scaler.unscale_(opt)  # grad = 2 (back to true)
    scaler.step(opt)  # must NOT unscale again
    scaler.update()
    assert abs(w.numpy()[0] - (1.0 - 0.1 * 2.0)) < 1e-6

    # double unscale_ raises
    loss2 = (w * 2.0).sum()
    scaler.scale(loss2).backward()
    scaler.unscale_(opt)
    import pytest

    with pytest.raises(RuntimeError):
        scaler.unscale_(opt)
    scaler.step(opt)
    with pytest.raises(RuntimeError):
        scaler.step(opt)
    scaler.update()  # resets state machine
    opt.clear_grad()


def test_grad_scaler_two_optimizers_independent_inf():
    """One optimizer's inf grads must not be erased by another's clean
    unscale_: opt1 skips its step, opt2 still steps, the scale backs off."""
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0)
    w1 = paddle.Parameter(np.array([1.0], np.float32))
    w2 = paddle.Parameter(np.array([1.0], np.float32))
    opt1 = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w1])
    opt2 = paddle.optimizer.SGD(learning_rate=0.1, parameters=[w2])
    import jax.numpy as jnp

    w1._grad = jnp.asarray(np.array([np.inf], np.float32))
    w2._grad = jnp.asarray(np.array([2.0], np.float32))
    scaler.unscale_(opt1)
    scaler.unscale_(opt2)  # clean — must not clear opt1's inf
    scaler.step(opt1)
    scaler.step(opt2)
    scaler.update()
    assert w1.numpy()[0] == 1.0  # skipped
    assert abs(w2.numpy()[0] - (1.0 - 0.1 * 1.0)) < 1e-6  # grad 2/scale 2
    assert scaler._scale == 1.0  # backed off from 2.0


def test_pylayer():
    from paddle_tpu.autograd import PyLayer

    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, grad):
            return grad * 2

    x = paddle.to_tensor(np.array([3.0], np.float32), stop_gradient=False)
    y = Double.apply(x)
    y.backward()
    assert np.allclose(y.numpy(), [6.0])
    assert np.allclose(x.grad.numpy(), [2.0])


def test_recompute():
    from paddle_tpu.distributed.fleet.utils import recompute

    lin = nn.Linear(4, 4)
    x = paddle.randn([2, 4])
    x.stop_gradient = False
    y = recompute(lin, x).sum()
    y.backward()
    assert lin.weight.grad is not None
    assert x.grad is not None


def test_jacobian_hessian():
    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32), stop_gradient=False)
    jac = paddle.autograd.jacobian(lambda v: (v * v).sum(), x)
    assert np.allclose(jac.numpy(), [2.0, 4.0])
    hes = paddle.autograd.hessian(lambda v: (v * v).sum(), x)
    assert np.allclose(hes.numpy(), 2 * np.eye(2))


def test_sdpa_matches_manual():
    q = paddle.randn([1, 4, 2, 8])
    out = F.scaled_dot_product_attention(q, q, q)
    qn = q.numpy().transpose(0, 2, 1, 3)  # b h s d
    s = (qn @ qn.transpose(0, 1, 3, 2)) / np.sqrt(8)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    ref = (p @ qn).transpose(0, 2, 1, 3)
    assert np.allclose(out.numpy(), ref, atol=1e-4)


def test_sdpa_causal_grad():
    q = paddle.randn([1, 4, 2, 8])
    q.stop_gradient = False
    out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
    out.sum().backward()
    assert q.grad is not None


def test_flash_attention_pallas_interpret():
    """Run the actual Pallas kernel in interpret mode on CPU."""
    import os

    os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
    try:
        from paddle_tpu.ops.pallas.flash_attention import (
            _attention_xla,
            flash_attention_array,
        )
        import jax.numpy as jnp

        q = np.random.rand(1, 128, 2, 16).astype(np.float32)
        out = flash_attention_array(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q), causal=True)
        ref = _attention_xla(jnp.asarray(q), jnp.asarray(q), jnp.asarray(q), causal=True)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=2e-3)
    finally:
        del os.environ["PADDLE_TPU_PALLAS_INTERPRET"]


@pytest.mark.slow
def test_flash_attention_mask_grad_matches_xla():
    """Pallas path must differentiate an additive mask (e.g. a trainable
    relative-position bias) identically to the XLA fallback."""
    import os

    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas.flash_attention import (
        _attention_xla,
        flash_attention_array,
    )

    rs = np.random.RandomState(3)
    q = jnp.asarray(rs.rand(2, 128, 2, 16).astype(np.float32))
    k = jnp.asarray(rs.rand(2, 128, 2, 16).astype(np.float32))
    v = jnp.asarray(rs.rand(2, 128, 2, 16).astype(np.float32))

    q1, k1, v1 = q[:1], k[:1], v[:1]  # batch-1: a (1,H) mask reaches the
    # kernel un-broadcast (mask_b=1, mask_h=H)
    cases = [
        (q, k, v, (1, 1, 128, 128)),
        (q, k, v, (2, 2, 128, 128)),
        (q, k, v, (2, 1, 128, 128)),
        (q, k, v, (1, 2, 128, 128)),
        (q1, k1, v1, (1, 2, 128, 128)),
    ]
    for qq, kk, vv, mshape in cases:
        mask = jnp.asarray(rs.randn(*mshape).astype(np.float32) * 0.5)

        def loss_pallas(m):
            os.environ["PADDLE_TPU_PALLAS_INTERPRET"] = "1"
            try:
                return flash_attention_array(qq, kk, vv, mask=m).sum()
            finally:
                del os.environ["PADDLE_TPU_PALLAS_INTERPRET"]

        def loss_xla(m):
            return _attention_xla(qq, kk, vv, mask=m).sum()

        g_pallas = jax.grad(loss_pallas)(mask)
        g_xla = jax.grad(loss_xla)(mask)
        assert g_pallas.shape == mask.shape
        assert np.abs(np.asarray(g_xla)).max() > 1e-4  # non-trivial gradient
        assert np.allclose(np.asarray(g_pallas), np.asarray(g_xla), atol=2e-3), mshape
