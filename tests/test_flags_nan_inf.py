"""FLAGS_check_nan_inf guard (VERDICT round-2 item 9; reference hooks every
op output — framework/operator.cc:1666, nan_inf_utils_detail.cc:177)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


@pytest.fixture
def nan_flag():
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    yield
    paddle.set_flags({"FLAGS_check_nan_inf": False})


def test_eager_poisoned_weight_names_layer(nan_flag):
    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    net[0].weight.set_value(np.full((4, 8), np.nan, np.float32))
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    with pytest.raises(RuntimeError, match="non-finite .*Linear"):
        net(x)


def test_eager_inf_detected(nan_flag):
    net = nn.Linear(4, 4)
    net.weight.set_value(np.full((4, 4), np.inf, np.float32))
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    with pytest.raises(RuntimeError, match="inf"):
        net(x)


def test_compiled_step_guard(nan_flag):
    """Under jit the guard compiles in via debug callback (CPU backend
    supports host callbacks; on restricted backends the eager guard is the
    supported mode)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.core.functional import functional_call, state_dict_arrays

    net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    params, bufs = state_dict_arrays(net)
    poisoned = {
        k: (jnp.full_like(v, jnp.nan) if "0.weight" in k else v)
        for k, v in params.items()
    }
    x = jnp.ones((2, 4), jnp.float32)
    f = jax.jit(lambda p, x: functional_call(net, p, bufs, (x,))[0])
    with pytest.raises(Exception, match="non-finite|nan_inf|callback"):
        np.asarray(f(poisoned, x))


def test_clean_forward_unaffected(nan_flag):
    net = nn.Linear(4, 4)
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    y = net(x)
    assert np.isfinite(y.numpy()).all()


def test_flag_off_no_check():
    net = nn.Linear(4, 4)
    net.weight.set_value(np.full((4, 4), np.nan, np.float32))
    x = paddle.to_tensor(np.ones((1, 4), np.float32))
    y = net(x)  # no raise
    assert np.isnan(y.numpy()).all()


class _MultiOut(nn.Layer):
    """Layer with a structured output: only one leaf is poisoned."""

    def forward(self, x):
        return x, {"aux": x + 1.0, "bad": x * float("nan")}


def test_failure_names_first_nonfinite_leaf_path(nan_flag):
    """Observability-issue satellite: the report must NAME the offending
    leaf (pytree path inside the layer's output), not just say
    'non-finite detected' — for multi-output layers that is the
    difference between a lead and a grep."""
    x = paddle.to_tensor(np.ones((2, 3), np.float32))
    with pytest.raises(RuntimeError) as ei:
        _MultiOut()(x)
    msg = str(ei.value)
    assert "_MultiOut" in msg
    assert "[1]['bad']" in msg            # the pytree path of the bad leaf
    assert "'aux'" not in msg             # the clean leaves are not blamed


def test_failure_names_first_bad_index(nan_flag):
    """... and the first non-finite ELEMENT's index, localizing a
    poisoned row/channel."""
    net = nn.Linear(4, 4)
    net.weight.set_value(np.zeros((4, 4), np.float32))
    b = np.zeros((4,), np.float32)
    b[3] = np.inf                         # one poisoned output channel
    net.bias.set_value(b)
    x = paddle.to_tensor(np.zeros((2, 4), np.float32))
    with pytest.raises(RuntimeError, match=r"first at index \[0, 3\]"):
        net(x)
