"""Cost model (VERDICT r4 missing #8).

Reference: /root/reference/python/paddle/cost_model/ (per-op program costs
feeding the auto-parallel planner) and pipeline-stage balancing. TPU-native:
XLA's compile-time cost_analysis is the estimator — abstract (ShapeDtypeStruct)
lowering, no device execution.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static
from paddle_tpu.cost_model import (
    CostModel,
    balanced_partition,
    estimate_cost,
    layer_cost,
    segment_layers_by_cost,
)


def test_estimate_cost_matmul_flops():
    import jax.numpy as jnp

    cd = estimate_cost(
        lambda a, b: a @ b,
        np.zeros((256, 512), np.float32), np.zeros((512, 128), np.float32),
    )
    # 2*M*K*N flops
    assert cd.flops == pytest.approx(2 * 256 * 512 * 128, rel=0.01)
    assert cd.bytes_accessed > 0
    assert cd.time_us > 0


def test_layer_cost_scales_with_width():
    paddle.seed(0)
    small = layer_cost(nn.Linear(64, 64), np.zeros((32, 64), np.float32))
    big = layer_cost(nn.Linear(64, 512), np.zeros((32, 64), np.float32))
    assert big.flops > 4 * small.flops


def test_profile_measure_program():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [64, 128], "float32")
        net = nn.Linear(128, 256)
        y = net(x)
        z = nn.functional.relu(y)
    cm = CostModel()
    costs = cm.profile_measure(prog)
    assert len(costs) == prog.num_ops()
    # the linear dominates: 2*64*128*256 flops
    flops = [c.flops for c in costs]
    assert max(flops) == pytest.approx(2 * 64 * 128 * 256, rel=0.05)
    total = cm.program_cost(prog)
    assert total.flops == pytest.approx(sum(flops))


def test_balanced_partition_minimizes_max():
    # one heavy layer; uniform split would pair it with others
    costs = [10.0, 1.0, 1.0, 1.0]
    bounds = balanced_partition(costs, 2)
    assert bounds[0] == 0 and bounds[-1] == 4
    cut = bounds[1]
    assert cut == 1  # heavy layer isolated
    # degenerate cases
    assert balanced_partition([1.0] * 4, 2)[1] == 2


def test_pipeline_layer_cost_segmentation():
    from paddle_tpu.distributed.fleet.meta_parallel.pp_layers import (
        LayerDesc,
        PipelineLayer,
    )

    paddle.seed(0)
    descs = [
        LayerDesc(nn.Linear, 64, 512),   # heavy
        LayerDesc(nn.Linear, 512, 16),   # medium
        LayerDesc(nn.Linear, 16, 16),    # tiny
        LayerDesc(nn.Linear, 16, 16),    # tiny
    ]
    pl = PipelineLayer(
        descs, num_stages=2, seg_method="cost",
        seg_sample_input=np.zeros((32, 64), np.float32),
    )
    assert pl.seg_cost_us is not None and len(pl.seg_cost_us) == 4
    # the heavy first layer gets its own stage; uniform would split 2/2
    assert pl.segment_parts == [0, 1, 4] or pl.segment_parts == [0, 2, 4]
    # with these sizes the heavy layer dominates -> must be isolated
    assert pl.segment_parts[1] <= 2
    # sanity: the costs really are decreasing
    assert pl.seg_cost_us[0] > pl.seg_cost_us[2]

    with pytest.raises(ValueError, match="seg_sample_input"):
        PipelineLayer(descs, num_stages=2, seg_method="cost")


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
