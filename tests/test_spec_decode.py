"""Speculative decoding: prompt-lookup drafting + batched verification.

Acceptance criteria from the spec-decoding issue:

- with GREEDY sampling, speculative output is token-for-token identical to
  non-speculative output for the same requests, prefix caching on AND off,
  across mixed continuous-batching traffic;
- after any interleaving of accepts, full rejections, preemptions, and
  aborts the pool returns to its idle free-block count with all refcounts
  zero (the churn-sweep pattern from tests/test_prefix_cache.py);
- the compiled-program count stays bounded by the engine's ragged
  width buckets (`expected_program_count()`) regardless of request mix;
- acceptance-rate metrics are wired: `spec_proposed_tokens` /
  `spec_accepted_tokens` counters, `spec_acceptance_rate` /
  `spec_mean_accepted_len` / `tokens_per_step` gauges, snapshot and
  Prometheus exposition.

Acceptance-sensitive paths use oracle/adversarial drafters (a drafter that
proposes the model's true continuation, or deliberate garbage) so the
tests pin behavior at 100% and 0% acceptance independent of what the
random tiny model happens to emit; the NgramDrafter itself is unit-tested
on host.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import LLMEngine, NgramDrafter
from paddle_tpu.serving.spec import apply_top_k_top_p


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0, shared=0):
    rs = np.random.RandomState(seed)
    prefix = rs.randint(0, 128, (shared,)).tolist()
    return [prefix + rs.randint(0, 128, (n - shared,)).tolist()
            for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def assert_pool_idle(pool):
    assert pool._refcount == {}
    assert pool.num_free == pool.num_blocks - 1
    assert {h: b for b, h in pool._block_hash.items()} == pool._hash_index


class OracleDrafter(NgramDrafter):
    """Drafts the model's TRUE greedy continuation (precomputed per
    prompt): every drafted token verifies, pinning the accept path at
    100% acceptance regardless of the model's own repetitiveness."""

    def __init__(self, continuations, num_spec_tokens=4):
        super().__init__(num_spec_tokens=num_spec_tokens)
        self._cont = continuations  # prompt tuple -> full greedy output

    def propose(self, all_ids, max_tokens=None):
        cap = self.num_spec_tokens
        if max_tokens is not None:
            cap = min(cap, int(max_tokens))
        for p, out in self._cont.items():
            if tuple(all_ids[:len(p)]) == p:
                done = len(all_ids) - len(p)
                if all_ids[len(p):] != out[:done]:
                    return []  # a sampled/diverged path: oracle blind
                return out[done:done + cap]
        return []


class GarbageDrafter(NgramDrafter):
    """Adversarial drafter: always proposes out-of-distribution tokens
    (vocab - 1 - last_token mod vocab style), so greedy verification
    rejects EVERY draft — output must still be exact and every reserved
    block must roll back."""

    def propose(self, all_ids, max_tokens=None):
        cap = self.num_spec_tokens
        if max_tokens is not None:
            cap = min(cap, int(max_tokens))
        return [(all_ids[-1] + 1 + i) % 127 for i in range(cap)]


# -- drafter units (host only, no model) -----------------------------------

def test_ngram_drafter_match_and_no_match():
    d = NgramDrafter(num_spec_tokens=4, max_ngram=3, min_ngram=1)
    # suffix [7, 8] occurred earlier, followed by 9, 10, 11
    assert d.propose([7, 8, 9, 10, 11, 3, 7, 8]) == [9, 10, 11, 3]
    # cap respected
    assert d.propose([7, 8, 9, 10, 11, 3, 7, 8], 2) == [9, 10]
    # no earlier occurrence of any suffix n-gram
    assert d.propose([1, 2, 3, 4, 5]) == []
    # the most recent match with a FULL draft window wins (i=0 here); the
    # nearer match at i=2 could only supply a truncated draft
    assert d.propose([5, 1, 5, 2, 9, 5]) == [1, 5, 2, 9]
    # with a smaller cap the nearer match has the full window and wins
    assert d.propose([5, 1, 5, 2, 9, 5], 3) == [2, 9, 5]


def test_ngram_drafter_prefers_longer_ngrams():
    d = NgramDrafter(num_spec_tokens=3, max_ngram=3, min_ngram=1)
    # trigram [1,2,3] matched at the start beats the more recent unigram 3
    assert d.propose([1, 2, 3, 7, 7, 3, 4, 1, 2, 3]) == [7, 7, 3]


def test_ngram_drafter_short_history_and_caps():
    d = NgramDrafter(num_spec_tokens=4)
    assert d.propose([5]) == []          # nothing before the suffix
    assert d.propose([5, 5]) == [5]      # 1-token history match
    assert d.propose([5, 5], 0) == []    # zero cap: no draft
    assert d.propose([], 4) == []
    with pytest.raises(ValueError):
        NgramDrafter(num_spec_tokens=0)
    with pytest.raises(ValueError):
        NgramDrafter(min_ngram=0)


def test_ngram_drafter_proposal_includes_overlap():
    d = NgramDrafter(num_spec_tokens=6, max_ngram=2)
    # periodic history: the most recent earlier [1, 2] sits right before
    # the suffix, and its continuation reads INTO the suffix region (the
    # draft may propose tokens the sequence just emitted — that is the
    # whole trick on cyclic output)
    assert d.propose([1, 2, 1, 2, 1, 2]) == [1, 2]
    assert d.propose([3, 1, 2, 1, 2, 1]) == [2, 1]


# -- top-k / top-p processing ----------------------------------------------

def test_apply_top_k_top_p_masks_support():
    import jax.numpy as jnp

    lg = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 4.0],
                      [4.0, 3.0, 2.0, 1.0, 0.0]], jnp.float32)
    # top_k=2 keeps the two largest per row
    out = apply_top_k_top_p(lg, jnp.asarray([2, 2]), jnp.asarray([1.0, 1.0]))
    assert np.isfinite(np.asarray(out)).tolist() == [
        [False, False, False, True, True], [True, True, False, False, False]]
    # top_k=0 / top_p=1.0 are no-ops
    out = apply_top_k_top_p(lg, jnp.asarray([0, 0]), jnp.asarray([1.0, 1.0]))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(lg))
    # tiny top_p keeps only the argmax (nucleus of one)
    out = apply_top_k_top_p(lg, jnp.asarray([0, 0]),
                            jnp.asarray([1e-4, 1e-4]))
    finite = np.isfinite(np.asarray(out))
    assert finite.sum() == 2 and finite[0, 4] and finite[1, 0]
    # top_k and top_p compose (k first, then nucleus over the survivors)
    out = apply_top_k_top_p(lg, jnp.asarray([3, 3]),
                            jnp.asarray([0.5, 0.5]))
    assert np.isfinite(np.asarray(out)).sum(axis=1).max() <= 3
    # top_p just under 1.0: float32 cumsum may never reach p — the cut
    # must keep (nearly) everything, NOT collapse to the argmax
    flat = jnp.zeros((1, 50000), jnp.float32)  # uniform: worst cumsum case
    out = apply_top_k_top_p(flat, jnp.asarray([0]),
                            jnp.asarray([0.9999999]))
    assert np.isfinite(np.asarray(out)).sum() == 50000


def test_engine_sampler_top_k_top_p_restrict_support(model):
    """Sampled serving tokens stay inside the top-k support: with top_k=1
    sampling at any temperature IS greedy (the only surviving token is the
    argmax), so the output must equal the greedy reference."""
    prompts = _prompts((6, 11), seed=3)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    outs = engine.generate(prompts, max_new_tokens=6, temperature=1.5,
                           top_k=1)
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 6)
    # a tiny nucleus behaves the same way (top-1 always survives top-p)
    outs = engine.generate(prompts, max_new_tokens=6, temperature=1.5,
                           top_p=1e-6)
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 6)
    with pytest.raises(ValueError):
        engine.add_request(prompts[0], top_p=1.5)
    with pytest.raises(ValueError):
        engine.add_request(prompts[0], top_k=-3)


def test_verify_rejection_sampling_respects_top_k(model):
    """Spec-on sampling with top_k=1 must also equal greedy: the verify
    step's rejection test and residual/bonus samples all draw from the
    SAME top-k/top-p-processed distribution as the decode sampler."""
    prompts = _prompts((7, 12), seed=9)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                       spec_decoding=True, num_spec_tokens=3)
    outs = engine.generate(prompts, max_new_tokens=8, temperature=2.0,
                           top_k=1)
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 8)
    assert_pool_idle(engine.pool)


# -- greedy parity ---------------------------------------------------------

@pytest.mark.slow  # tier-1 headroom (PR 19): heaviest always-on case; tier-2 covers it
def test_spec_greedy_parity_mixed_batch(model):
    """THE acceptance test: the same overlapping request mix served by a
    spec-enabled engine and a plain engine is token-for-token identical,
    with prefix caching on AND off, and the spec engine stays inside its
    `expected_program_count()` width buckets."""
    prompts = _prompts((5, 9, 21, 13), seed=1, shared=4)
    for prefix_cache in (True, False):
        base = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64,
                         prefix_cache=prefix_cache)
        want = base.generate(prompts, max_new_tokens=10, temperature=0.0)
        eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64,
                        prefix_cache=prefix_cache, spec_decoding=True,
                        num_spec_tokens=4)
        got = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
        assert got == want, f"prefix_cache={prefix_cache}"
        got2 = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
        assert got2 == want  # warm pass (cache hits + spec) still exact
        traces = eng.metrics.counters["jit_traces"]
        assert traces <= eng.expected_program_count() == 3, traces
        assert eng.metrics.counters["verify_steps"] > 0
        assert_pool_idle(eng.pool)
    for p, o in zip(prompts, want):
        assert o == _reference(model, p, 10)


def test_spec_oracle_drafter_accepts_everything(model):
    """With a drafter proposing the model's true continuation, every
    drafted token is accepted (rate 1.0), decode finishes in ~1/(k+1) of
    the steps, and the output is exact."""
    prompts = _prompts((6, 9), seed=2)
    refs = {tuple(p): _reference(model, p, 12) for p in prompts}
    base = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    base.generate(prompts, max_new_tokens=12, temperature=0.0)
    base_steps = (base.metrics.counters["decode_steps"]
                  + base.metrics.counters["mixed_steps"])

    eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                    spec_decoding=True, num_spec_tokens=4)
    eng.scheduler.drafter = OracleDrafter(refs, num_spec_tokens=4)
    outs = eng.generate(prompts, max_new_tokens=12, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o == refs[tuple(p)]
    c = eng.metrics.counters
    assert c["spec_accepted_tokens"] == c["spec_proposed_tokens"] > 0
    assert eng.metrics.gauges["spec_acceptance_rate"] == 1.0
    spec_steps = (c["decode_steps"] + c["mixed_steps"] + c["verify_steps"])
    assert spec_steps < base_steps  # fewer invocations for the same tokens
    assert eng.metrics.gauges["tokens_per_step"] > 1.0
    assert_pool_idle(eng.pool)


def test_spec_full_rejection_is_exact_and_rolls_back(model):
    """An adversarial drafter whose every candidate is rejected: outputs
    stay exact (the stop-slot token is the model's own), acceptance is
    0.0, and every speculative block reservation rolls back — the pool
    ends idle with zero refcounts."""
    prompts = _prompts((6, 10), seed=4)
    eng = LLMEngine(model, block_size=4, max_batch=2, max_seq_len=64,
                    spec_decoding=True, num_spec_tokens=4)
    eng.scheduler.drafter = GarbageDrafter(num_spec_tokens=4)
    outs = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 10)
    c = eng.metrics.counters
    assert c["spec_proposed_tokens"] > 0
    assert c["spec_accepted_tokens"] == 0
    assert eng.metrics.gauges["spec_acceptance_rate"] == 0.0
    assert eng.metrics.counters["verify_steps"] > 0
    assert_pool_idle(eng.pool)


def test_spec_mid_verify_abort_block_accounting(model):
    """Abort a request immediately after a verify step that reserved and
    partially rolled back speculative blocks (and one mid-prefill), while
    another spec request keeps decoding exactly."""
    p1, p2 = _prompts((9, 30), seed=5)
    eng = LLMEngine(model, block_size=4, max_batch=2, max_seq_len=64,
                    prefill_chunk=8, spec_decoding=True, num_spec_tokens=4)
    eng.scheduler.drafter = GarbageDrafter(num_spec_tokens=4)
    r1 = eng.add_request(p1, max_new_tokens=12, temperature=0.0)
    eng.step()            # p1 prefill
    eng.step()            # first decode/verify round for p1
    r2 = eng.add_request(p2, max_new_tokens=12, temperature=0.0)
    eng.step()            # p2 mid-prefill, p1 verifying
    assert eng.abort(r2)  # abort mid-prefill
    eng.step()            # p1 verify right after the abort
    assert eng.abort(r1)  # abort right after a verify (spec tail live)
    assert not eng.has_unfinished()
    assert_pool_idle(eng.pool)
    # a fresh request serves exactly after the churn
    (out,) = eng.generate([p1], max_new_tokens=6, temperature=0.0)
    assert out == _reference(model, p1, 6)
    assert_pool_idle(eng.pool)


def test_spec_eos_inside_accepted_run(model):
    """When eos lands inside the accepted run, emission truncates at eos
    and the request finishes — trailing accepted drafts are discarded."""
    (p,) = _prompts((7,), seed=6)
    ref = _reference(model, p, 12)
    eos = ref[2]  # force a stop mid-run
    base = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    (want,) = base.generate([p], max_new_tokens=12, temperature=0.0,
                            eos_token_id=eos)
    eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                    spec_decoding=True, num_spec_tokens=4)
    eng.scheduler.drafter = OracleDrafter({tuple(p): ref}, num_spec_tokens=4)
    (got,) = eng.generate([p], max_new_tokens=12, temperature=0.0,
                          eos_token_id=eos)
    assert got == want == ref[:ref.index(eos) + 1]
    assert_pool_idle(eng.pool)


# -- knobs -----------------------------------------------------------------

def test_spec_env_gate_and_per_request_optout(model, monkeypatch):
    assert not LLMEngine(model, block_size=8, max_batch=2,
                         max_seq_len=64).spec_decoding  # default OFF
    monkeypatch.setenv("PADDLE_TPU_SPEC_DECODE", "1")
    assert LLMEngine(model, block_size=8, max_batch=2,
                     max_seq_len=64).spec_decoding
    monkeypatch.setenv("PADDLE_TPU_SPEC_DECODE", "0")
    assert not LLMEngine(model, block_size=8, max_batch=2,
                         max_seq_len=64).spec_decoding
    # explicit ctor arg beats the env
    assert LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                     spec_decoding=True).spec_decoding
    monkeypatch.delenv("PADDLE_TPU_SPEC_DECODE")

    # per-request opt-out on a spec engine: no drafts for that request
    prompts = _prompts((6, 8), seed=7)
    eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                    spec_decoding=True, num_spec_tokens=4)
    eng.scheduler.drafter = GarbageDrafter(num_spec_tokens=4)
    outs = eng.generate(prompts, max_new_tokens=6, temperature=0.0,
                        spec_decoding=False)
    assert eng.metrics.counters.get("spec_proposed_tokens", 0) == 0
    assert eng.metrics.counters.get("verify_steps", 0) == 0
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 6)
    # per-request num_spec_tokens caps (never raises) the draft length
    eng.generate(prompts, max_new_tokens=6, temperature=0.0,
                 num_spec_tokens=1)
    assert eng.metrics.counters["spec_drafted_rows"] == \
        eng.metrics.counters["spec_proposed_tokens"]


def test_spec_metrics_flow_to_snapshot_and_prometheus(model):
    prompts = _prompts((6, 9), seed=8)
    eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                    spec_decoding=True, num_spec_tokens=3)
    refs = {tuple(p): _reference(model, p, 8) for p in prompts}
    eng.scheduler.drafter = OracleDrafter(refs, num_spec_tokens=3)
    eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    snap = eng.metrics.snapshot()
    assert snap["counters"]["spec_proposed_tokens"] > 0
    assert snap["gauges"]["spec_acceptance_rate"] == 1.0
    assert snap["gauges"]["tokens_per_step"] > 1.0
    assert "verify_step" in snap["latency"]
    text = eng.metrics.prometheus_text()
    assert "paddle_tpu_serving_spec_accepted_tokens_total" in text
    assert "paddle_tpu_serving_spec_acceptance_rate" in text
    assert "paddle_tpu_serving_verify_step_seconds_count" in text


# -- churn sweep (pool-invariant soak) -------------------------------------

def _churn(model, rounds, seed, drafter=None):
    """Interleave spec accepts/rejections, prefix-cache hits, preemptions,
    and aborts through a deliberately tiny pool; exactness for every
    surviving request and the idle-pool invariant after every round."""
    rs = np.random.RandomState(seed)
    engine = LLMEngine(model, block_size=4, num_blocks=10, max_batch=3,
                       max_seq_len=64, prefill_chunk=8, spec_decoding=True,
                       num_spec_tokens=3)
    if drafter is not None:
        engine.scheduler.drafter = drafter
    idle_free = engine.pool.num_blocks - 1
    prefixes = [rs.randint(0, 128, (8,)).tolist() for _ in range(3)]
    for rnd in range(rounds):
        reqs = []
        for _ in range(rs.randint(2, 5)):
            p = (prefixes[rs.randint(len(prefixes))]
                 + rs.randint(0, 128, (rs.randint(0, 9),)).tolist())
            reqs.append(engine.add_request(
                p, max_new_tokens=int(rs.randint(2, 8)), temperature=0.0))
        doomed = set(rs.choice(reqs, size=len(reqs) // 3, replace=False)
                     .tolist()) if len(reqs) >= 3 else set()
        steps = 0
        while engine.has_unfinished():
            engine.step()
            steps += 1
            if steps == 2:
                for rid in doomed:
                    engine.abort(rid)
        for rid in reqs:
            if rid in doomed:
                continue
            req = engine.get_request(rid)
            assert req.output_ids == _reference(
                model, req.prompt_ids, req.max_new_tokens), f"round {rnd}"
            engine.release(rid)
        assert engine.pool.num_free == idle_free, f"round {rnd}"
        assert engine.pool._refcount == {}, f"round {rnd}"
    return engine.metrics.counters


def test_spec_churn_smoke(model):
    """Always-on tier-1 smoke: n-gram drafting + spec verify under abort
    churn in a tiny pool, every output exact, pool idle every round.
    Drafted rows may ride mixed steps now (ragged widths), so the
    exercised-speculation signal is drafted rows, not verify-kind
    steps."""
    c = _churn(model, rounds=3, seed=0)
    assert c.get("spec_drafted_rows", 0) > 0
    assert c.get("spec_proposed_tokens", 0) > 0
    assert c.get("requests_aborted", 0) > 0


@pytest.mark.slow
def test_spec_churn_soak(model):
    """Soak across seeds and drafters (real n-gram AND always-reject):
    enough churn that accepts, full rejections, preemptions, evictions,
    and aborts all fire with speculation on."""
    merged = {}
    for seed, drafter in ((1, None), (2, None),
                          (3, GarbageDrafter(num_spec_tokens=3))):
        c = _churn(model, rounds=8, seed=seed, drafter=drafter)
        for k, v in c.items():
            merged[k] = merged.get(k, 0) + v
    assert merged.get("spec_proposed_tokens", 0) > 0
    assert merged.get("spec_accepted_tokens", 0) > 0
    assert merged.get("preemptions", 0) > 0
    assert merged.get("requests_aborted", 0) > 0


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
