"""paddle_tpu.serving: continuous-batching engine over the paged KV cache.

Acceptance criteria from the serving issues: paged-cache generation matches
sequential `GPT.generate` greedy outputs token-for-token while serving
overlapping requests of different prompt lengths; requests admitted
mid-decode join the running batch; preemption under a tiny pool frees and
recomputes correctly; and the whole workload — any prompt lengths, chunked
prefill mixed with decode — compiles exactly TWO programs, watched by the
engine's `jit_traces` counter, which increments inside the traced step body
(trace time only). Chunked-prefill edge cases live in
test_serving_chunked.py; Pallas-kernel/fallback parity in
test_paged_attention_kernel.py.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import BlockPool, LLMEngine
from paddle_tpu.serving.scheduler import Request, Scheduler


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def test_paged_matches_generate_greedy_overlapping(model):
    """>= 3 overlapping requests with different prompt lengths produce
    greedy outputs identical to sequential GPT.generate, with at most one
    compile per (prefill bucket, decode) shape."""
    prompts = _prompts((5, 9, 13))
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    outs = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 6)
    # all three prompts share the chunk-width bucket + the decode bucket
    # (the one-place program-count contract: engine.expected_program_count)
    assert engine.expected_program_count() == 2
    assert engine.metrics.counters["jit_traces"] == 2
    assert engine.pool.num_free == engine.pool.num_blocks - 1  # all freed


def test_mixed_lengths_compile_two_programs(model):
    """Chunked prefill retired the per-bucket programs: prompts of ANY
    length share one (max_batch, prefill_chunk) instance of the unified
    ragged step plus its (max_batch, 1) decode-width instance —
    re-serving different lengths adds zero traces."""
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    prompts = _prompts((4, 20), seed=1)
    outs = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 4)
    assert (engine.metrics.counters["jit_traces"]
            == engine.expected_program_count() == 2)
    engine.generate(_prompts((7, 30, 44), seed=2), max_new_tokens=4,
                    temperature=0.0)
    assert engine.metrics.counters["jit_traces"] == 2  # no recompiles


def test_width_bucket_collision_dedups_programs(model):
    """The program table is keyed by (batch, width) only — when the spec
    width coincides with the chunk width, the old per-kind model's third
    program simply does not exist: FEWER compiled programs, same
    tokens."""
    prompts = _prompts((5, 9, 13), seed=6)
    base = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    want = base.generate(prompts, max_new_tokens=8, temperature=0.0)
    eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64,
                    prefill_chunk=4, spec_decoding=True, num_spec_tokens=3)
    assert eng.width_buckets == [1, 4]         # 1 + num_spec == chunk
    assert eng.expected_program_count() == 2   # was 3 kinds pre-unification
    got = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
    assert got == want
    assert eng.metrics.counters["jit_traces"] <= 2


def test_width_buckets_knob(model, monkeypatch):
    """`width_buckets` (and PADDLE_TPU_WIDTH_BUCKETS) add intermediate
    ragged widths: a short prefill rides the smallest covering bucket
    instead of full chunk width, tokens unchanged."""
    prompts = _prompts((5, 30), seed=8)
    base = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    want = base.generate(prompts, max_new_tokens=4, temperature=0.0)
    eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                    width_buckets=[8])
    assert eng.width_buckets == [1, 8, 64]
    assert eng.expected_program_count() == 3
    (o1,) = eng.generate([prompts[0]], max_new_tokens=4, temperature=0.0)
    assert o1 == want[0]
    # the 5-token prefill fit the w8 bucket — chunk width never compiled
    assert set(eng._step_fns) == {(2, 1), (2, 8)}
    (o2,) = eng.generate([prompts[1]], max_new_tokens=4, temperature=0.0)
    assert o2 == want[1]
    assert set(eng._step_fns) == {(2, 1), (2, 8), (2, 64)}
    # env spelling + validation
    monkeypatch.setenv("PADDLE_TPU_WIDTH_BUCKETS", "8,32")
    env_eng = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    assert env_eng.width_buckets == [1, 8, 32, 64]
    monkeypatch.delenv("PADDLE_TPU_WIDTH_BUCKETS")
    with pytest.raises(ValueError, match="width_buckets"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                  width_buckets=[0])


def test_one_host_sync_per_step(model):
    """THE host-sync contract: every step — mixed, decode, spec verify —
    reads back exactly ONE packed device array, so the `host_syncs`
    counter equals the step count after any wave."""
    eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64,
                    prefill_chunk=8, spec_decoding=True, num_spec_tokens=3)
    eng.generate(_prompts((5, 21, 9), seed=9) + [[7, 3] * 8],
                 max_new_tokens=8, temperature=0.0)
    c = eng.metrics.counters
    steps = (c.get("mixed_steps", 0) + c.get("decode_steps", 0)
             + c.get("verify_steps", 0))
    assert steps > 0
    assert c["host_syncs"] == steps


def test_long_prompt_prefills_in_chunks(model):
    """A prompt longer than prefill_chunk streams into the arena a chunk at
    a time — several mixed steps before the first token — and still matches
    the sequential reference exactly (chunk boundaries change no math)."""
    (p,) = _prompts((29,), seed=7)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                       prefill_chunk=8)
    (out,) = engine.generate([p], max_new_tokens=5, temperature=0.0)
    assert out == _reference(model, p, 5)
    # 29 tokens at chunk 8 -> 4 mixed steps (the last emits token 1)
    assert engine.metrics.counters["mixed_steps"] == 4
    assert engine.metrics.counters["jit_traces"] == 2


def test_staggered_add_request_mid_decode(model):
    """A request added while another is mid-decode joins the running batch
    (continuous batching) and both finish with exact greedy outputs."""
    p1, p2 = _prompts((6, 11), seed=3)
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    r1 = engine.add_request(p1, max_new_tokens=8, temperature=0.0)
    # run prefill + a few decode steps for r1 alone
    for _ in range(4):
        engine.step()
    assert len(engine.get_request(r1).output_ids) == 4
    r2 = engine.add_request(p2, max_new_tokens=8, temperature=0.0)
    saw_joint_decode = False
    while engine.has_unfinished():
        engine.step()
        if engine.metrics.gauges.get("num_running", 0) >= 2:
            saw_joint_decode = True
    assert saw_joint_decode  # r2 decoded alongside r1, not after it
    assert engine.get_request(r1).output_ids == _reference(model, p1, 8)
    assert engine.get_request(r2).output_ids == _reference(model, p2, 8)


def test_preemption_frees_and_recomputes(model):
    """A pool too small for three full sequences preempts by recompute:
    blocks are freed, the victim re-prefills prompt+generated, and greedy
    outputs still match the sequential reference exactly."""
    prompts = _prompts((6, 7, 9), seed=1)
    engine = LLMEngine(model, block_size=4, num_blocks=10, max_batch=4,
                       max_seq_len=64)
    outs = engine.generate(prompts, max_new_tokens=10, temperature=0.0)
    assert engine.metrics.counters["preemptions"] >= 1
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 10)
    assert engine.pool.num_free == engine.pool.num_blocks - 1


def test_stream_yields_tokens_incrementally(model):
    (p,) = _prompts((8,), seed=4)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    toks = []
    for out in engine.stream(p, max_new_tokens=5, temperature=0.0):
        toks.append(out.token)
        last_finished = out.finished
    assert toks == _reference(model, p, 5)
    assert last_finished


def test_eos_and_temperature_sampling(model):
    (p,) = _prompts((6,), seed=5)
    ref = _reference(model, p, 8)
    eos = ref[2]
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    (out,) = engine.generate([p], max_new_tokens=8, temperature=0.0,
                             eos_token_id=eos)
    # stops right after the FIRST occurrence of eos (tiny models repeat)
    assert out == ref[: ref.index(eos) + 1]
    # sampled path: legal tokens, full length, engine survives temp > 0
    (sampled,) = engine.generate([p], max_new_tokens=8, temperature=0.8)
    assert len(sampled) == 8
    assert all(0 <= t < 128 for t in sampled)


def test_request_validation(model):
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.add_request(list(range(60)), max_new_tokens=10)
    with pytest.raises(ValueError, match="empty"):
        engine.add_request([], max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.add_request([1, 2], max_new_tokens=0)
    # a request whose worst-case KV need exceeds the whole pool is rejected
    # at ADMISSION — otherwise it becomes the oldest running sequence and
    # the scheduler's no-livelock error kills the whole serve mid-flight
    small = LLMEngine(model, block_size=4, num_blocks=4, max_batch=2,
                      max_seq_len=64)
    with pytest.raises(ValueError, match="KV blocks"):
        small.add_request(list(range(1, 20)), max_new_tokens=4)
    small.add_request([1, 2, 3], max_new_tokens=4)  # fits: 2 of 3 blocks
    with pytest.raises(ValueError, match="token_budget"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                  token_budget=0)
    # chunking removed the bucketed engine's token-budget admission limit:
    # a prompt (or post-preempt recompute) larger than the budget streams
    # through in chunks instead of being rejected
    tight = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                      token_budget=8)
    p = _prompts((20,), seed=11)[0]
    (out,) = tight.generate([p], max_new_tokens=6, temperature=0.0)
    assert out == _reference(model, p, 6)
    assert tight.metrics.counters["mixed_steps"] >= 3  # 20 tokens / chunk 8


def test_generate_and_stream_release_requests(model):
    """generate/stream evict finished requests from the engine's registry —
    a long-running engine must not retain every prompt forever."""
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    engine.generate(_prompts((5, 9), seed=8), max_new_tokens=3)
    for _ in engine.stream(_prompts((6,), seed=9)[0], max_new_tokens=3):
        pass
    assert engine._requests == {}
    # manually-driven requests stay until released; unfinished can't release
    rid = engine.add_request(_prompts((5,), seed=10)[0], max_new_tokens=4)
    with pytest.raises(ValueError, match="release"):
        engine.release(rid)
    while engine.has_unfinished():
        engine.step()
    engine.release(rid)
    assert engine._requests == {}


def test_metrics_schedule_view_and_snapshot(model):
    """Metrics export in the shape xplane.print_schedule_analysis consumes
    and as a flat JSON snapshot for bench.py."""
    import io
    import json

    from paddle_tpu.profiler import xplane

    (p,) = _prompts((6,), seed=6)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    engine.generate([p], max_new_tokens=4, temperature=0.0)
    snap = engine.metrics.snapshot()
    json.dumps(snap)  # JSON-able end to end
    assert snap["counters"]["generated_tokens"] == 4
    assert "decode_step" in snap["latency"]
    assert "ttft" in snap["latency"]  # time-to-first-token, for bench
    assert snap["latency"]["ttft"]["p95_ms"] >= snap["latency"]["ttft"]["p50_ms"]
    view = engine.metrics.schedule_view()
    st = view["serving-engine"]
    assert st["span_ms"] > 0 and 0 < st["utilization"] <= 1.0
    assert st["n_ops"] == snap["counters"]["mixed_steps"] + snap[
        "counters"]["decode_steps"]
    buf = io.StringIO()
    xplane.print_schedule_analysis(view, file=buf)
    assert "util" in buf.getvalue()


def test_block_pool_alloc_free_copy():
    import jax.numpy as jnp

    pool = BlockPool(num_blocks=6, num_layers=2, block_size=4, num_heads=2,
                     head_dim=8)
    # head-major arena: [layers, heads, blocks, block_size, head_dim]
    assert pool.k.shape == (2, 2, 6, 4, 8)
    assert pool.num_free == 5  # block 0 reserved as null
    a = pool.allocate(3)
    assert a is not None and 0 not in a
    assert pool.allocate(3) is None  # only 2 left
    pool.k = pool.k.at[:, :, a[0]].set(1.0)
    b = pool.allocate(1)
    pool.copy_blocks([a[0]], [b[0]])
    assert float(jnp.sum(pool.k[:, :, b[0]])) == float(
        jnp.sum(pool.k[:, :, a[0]]))
    pool.free(a + b)
    assert pool.num_free == 5
    with pytest.raises(ValueError, match="null"):
        pool.free([0])


def test_scheduler_fcfs_mixed_rows_and_token_budget():
    """One mixed plan per step: FCFS lane admission, decode rows always
    ride, prefill chunks split under the per-step token budget."""
    pool = BlockPool(num_blocks=64, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)
    sched = Scheduler(pool, max_batch=2, token_budget=6, prefill_chunk=6)
    r1 = Request([1] * 10, max_new_tokens=4)
    r2 = Request([1] * 4, max_new_tokens=4)
    r3 = Request([1] * 4, max_new_tokens=4)
    for r in (r1, r2, r3):
        sched.add(r)
    # max_batch=2 lanes: r1 gets a full 6-token chunk, r2 (FCFS next) gets
    # nothing this step (budget spent); r3 waits for a lane
    rows = sched.schedule()
    assert [(w.req, w.start, w.count, w.emit) for w in rows] == [
        (r1, 0, 6, False)
    ]
    assert r2.state == "running" and r3.state == "waiting"
    r1.num_cached += 6
    # next step: r1's last 4 prompt tokens (emits) + r2's full 4-token
    # prompt would exceed budget 6 -> r2 gets the 2 remaining tokens
    rows = sched.schedule()
    assert [(w.req, w.count, w.emit) for w in rows] == [
        (r1, 4, True), (r2, 2, False)
    ]
    for w in rows:
        w.req.num_cached += w.count
    r1.output_ids.append(7)  # r1's first token emitted -> decode row next
    # mixed step: r1 decodes (never budget-gated) while r2 finishes prefill
    rows = sched.schedule()
    assert [(w.req, w.count, w.emit) for w in rows] == [
        (r1, 1, True), (r2, 2, True)
    ]
    for w in rows:
        w.req.num_cached += w.count
    sched.finish(r1)
    sched.finish(r2)
    # freed lanes: r3 admitted FCFS, prompt fits one chunk
    rows = sched.schedule()
    assert [(w.req, w.count, w.emit) for w in rows] == [(r3, 4, True)]


def test_scheduler_admission_exactly_at_token_budget():
    """Chunk packing fills the budget exactly: three rows' chunks sum to
    token_budget with the tail row truncated, never overshooting."""
    pool = BlockPool(num_blocks=64, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)
    sched = Scheduler(pool, max_batch=4, token_budget=12, prefill_chunk=5)
    reqs = [Request([1] * n, max_new_tokens=2) for n in (5, 5, 9, 8)]
    for r in reqs:
        sched.add(r)
    rows = sched.schedule()
    assert [(w.req, w.count) for w in rows] == [
        (reqs[0], 5), (reqs[1], 5), (reqs[2], 2)  # 5+5+2 == budget 12
    ]
    assert sum(w.count for w in rows) == 12
    for w in rows:
        w.req.num_cached += w.count
        if w.emit:
            w.req.output_ids.append(3)
    # next step: the two finished-prefill rows decode (not budget-gated)
    # while the mid-prompt rows take chunk-capped budget shares
    rows = sched.schedule()
    assert [(w.req, w.count, w.emit) for w in rows] == [
        (reqs[0], 1, True), (reqs[1], 1, True),
        (reqs[2], 5, False), (reqs[3], 5, False),
    ]


def test_scheduler_pool_too_small_fails_loudly():
    """The oldest sequence failing to grow with no younger victims is a
    config error, not a livelock."""
    pool = BlockPool(num_blocks=3, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)
    sched = Scheduler(pool, max_batch=2, token_budget=64, prefill_chunk=64)
    sched.add(Request([1] * 12, max_new_tokens=1))  # needs 3 blocks, pool has 2
    with pytest.raises(ValueError, match="KV blocks"):
        sched.schedule()


def test_recompile_sentinel_zero_retraces_steady_state(model):
    """The program-count contract, locked from the sentinel's side via
    the one shared helper: the compiled table never exceeds
    `expected_program_count()` (one program per ragged width bucket),
    and after a warmup wave an arbitrary steady-state serve (varied
    prompt lengths, sampling knobs, cache hits) runs with ZERO further
    XLA traces — `jit_traces` stays equal to the compiled-program count,
    the `jit_retraces` gauge stays 0, and the sentinel never warns."""
    import warnings

    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                       spec_decoding=True, num_spec_tokens=3)
    # the default spec engine buckets: decode, 1 + num_spec, chunk
    assert engine.expected_program_count() == 3
    with warnings.catch_warnings():
        warnings.simplefilter("error")       # any sentinel warning fails
        # warmup: a repetitive prompt drives mixed, decode, AND (via the
        # pure-decode width gate) spec-bucket steps
        engine.generate([[7] * 24], max_new_tokens=12)
        assert len(engine._step_fns) <= engine.expected_program_count()
        warm = engine.metrics.counters["jit_traces"]
        assert warm == len(engine._step_fns)  # one trace per program, ever
        rs = np.random.RandomState(1)
        for round_ in range(3):
            prompts = [rs.randint(0, 128, (n,)).tolist()
                       for n in (5, 17, 9)]
            engine.generate(prompts[:2], max_new_tokens=8)
            engine.generate([prompts[2]], max_new_tokens=4,
                            temperature=0.8, top_k=5)
    assert len(engine._step_fns) <= engine.expected_program_count()
    assert (engine.metrics.counters["jit_traces"]
            == len(engine._step_fns))        # 0 retraces, ever
    assert engine.metrics.gauges["jit_retraces"] == 0


def test_recompile_sentinel_warns_on_surplus_trace(model):
    """A trace beyond one-per-program is exactly what the sentinel must
    catch: simulate one (the counter is the engine's own trace-time
    signal) and the next step warns once, sets the gauge, and never
    spams."""
    import warnings

    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    engine.generate(_prompts((9,)), max_new_tokens=2)
    engine.metrics.inc("jit_traces")         # a phantom re-trace
    with pytest.warns(RuntimeWarning, match="recompile sentinel"):
        engine.generate(_prompts((7,), seed=1), max_new_tokens=2)
    assert engine.metrics.gauges["jit_retraces"] == 1
    with warnings.catch_warnings():          # warns once, never spams
        warnings.simplefilter("error")
        engine.generate(_prompts((5,), seed=2), max_new_tokens=2)
