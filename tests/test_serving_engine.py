"""paddle_tpu.serving: continuous-batching engine over the paged KV cache.

Acceptance criteria from the serving issue: paged-cache generation matches
sequential `GPT.generate` greedy outputs token-for-token while serving
overlapping requests of different prompt lengths; requests admitted
mid-decode join the running batch; preemption under a tiny pool frees and
recomputes correctly; and the whole workload compiles at most once per
(prefill bucket, decode) shape — watched by the engine's `jit_traces`
counter, which increments inside the traced step body (trace time only).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import BlockPool, LLMEngine
from paddle_tpu.serving.scheduler import Request, Scheduler


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def test_paged_matches_generate_greedy_overlapping(model):
    """>= 3 overlapping requests with different prompt lengths produce
    greedy outputs identical to sequential GPT.generate, with at most one
    compile per (prefill bucket, decode) shape."""
    prompts = _prompts((5, 9, 13))
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    outs = engine.generate(prompts, max_new_tokens=6, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 6)
    # all three prompts share the 16-bucket -> 1 prefill + 1 decode program
    assert engine.metrics.counters["jit_traces"] == 2
    assert engine.pool.num_free == engine.pool.num_blocks - 1  # all freed


def test_distinct_buckets_compile_once_each(model):
    """Prompt lengths spanning two buckets compile two prefill programs and
    ONE decode program — re-serving the same shapes adds zero traces."""
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    prompts = _prompts((4, 20), seed=1)  # buckets 16 and 32
    engine.generate(prompts, max_new_tokens=4, temperature=0.0)
    assert engine.metrics.counters["jit_traces"] == 3
    engine.generate(_prompts((7, 30), seed=2), max_new_tokens=4,
                    temperature=0.0)
    assert engine.metrics.counters["jit_traces"] == 3  # no recompiles


def test_staggered_add_request_mid_decode(model):
    """A request added while another is mid-decode joins the running batch
    (continuous batching) and both finish with exact greedy outputs."""
    p1, p2 = _prompts((6, 11), seed=3)
    engine = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64)
    r1 = engine.add_request(p1, max_new_tokens=8, temperature=0.0)
    # run prefill + a few decode steps for r1 alone
    for _ in range(4):
        engine.step()
    assert len(engine.get_request(r1).output_ids) == 4
    r2 = engine.add_request(p2, max_new_tokens=8, temperature=0.0)
    saw_joint_decode = False
    while engine.has_unfinished():
        engine.step()
        if engine.metrics.gauges.get("num_running", 0) >= 2:
            saw_joint_decode = True
    assert saw_joint_decode  # r2 decoded alongside r1, not after it
    assert engine.get_request(r1).output_ids == _reference(model, p1, 8)
    assert engine.get_request(r2).output_ids == _reference(model, p2, 8)


def test_preemption_frees_and_recomputes(model):
    """A pool too small for three full sequences preempts by recompute:
    blocks are freed, the victim re-prefills prompt+generated, and greedy
    outputs still match the sequential reference exactly."""
    prompts = _prompts((6, 7, 9), seed=1)
    engine = LLMEngine(model, block_size=4, num_blocks=10, max_batch=4,
                       max_seq_len=64)
    outs = engine.generate(prompts, max_new_tokens=10, temperature=0.0)
    assert engine.metrics.counters["preemptions"] >= 1
    for p, o in zip(prompts, outs):
        assert o == _reference(model, p, 10)
    assert engine.pool.num_free == engine.pool.num_blocks - 1


def test_stream_yields_tokens_incrementally(model):
    (p,) = _prompts((8,), seed=4)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    toks = []
    for out in engine.stream(p, max_new_tokens=5, temperature=0.0):
        toks.append(out.token)
        last_finished = out.finished
    assert toks == _reference(model, p, 5)
    assert last_finished


def test_eos_and_temperature_sampling(model):
    (p,) = _prompts((6,), seed=5)
    ref = _reference(model, p, 8)
    eos = ref[2]
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    (out,) = engine.generate([p], max_new_tokens=8, temperature=0.0,
                             eos_token_id=eos)
    # stops right after the FIRST occurrence of eos (tiny models repeat)
    assert out == ref[: ref.index(eos) + 1]
    # sampled path: legal tokens, full length, engine survives temp > 0
    (sampled,) = engine.generate([p], max_new_tokens=8, temperature=0.8)
    assert len(sampled) == 8
    assert all(0 <= t < 128 for t in sampled)


def test_request_validation(model):
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    with pytest.raises(ValueError, match="max_seq_len"):
        engine.add_request(list(range(60)), max_new_tokens=10)
    with pytest.raises(ValueError, match="empty"):
        engine.add_request([], max_new_tokens=4)
    with pytest.raises(ValueError, match="max_new_tokens"):
        engine.add_request([1, 2], max_new_tokens=0)
    # worst-case recompute prefill (prompt + max_new - 1 after a preempt)
    # must fit the token budget, or a preemption could wedge the queue
    tight = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                      token_budget=16)
    with pytest.raises(ValueError, match="token budget"):
        tight.add_request(list(range(10)), max_new_tokens=10)  # worst 19 -> 32
    tight.add_request(list(range(10)), max_new_tokens=7)  # worst 16: fits


def test_generate_and_stream_release_requests(model):
    """generate/stream evict finished requests from the engine's registry —
    a long-running engine must not retain every prompt forever."""
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    engine.generate(_prompts((5, 9), seed=8), max_new_tokens=3)
    for _ in engine.stream(_prompts((6,), seed=9)[0], max_new_tokens=3):
        pass
    assert engine._requests == {}
    # manually-driven requests stay until released; unfinished can't release
    rid = engine.add_request(_prompts((5,), seed=10)[0], max_new_tokens=4)
    with pytest.raises(ValueError, match="release"):
        engine.release(rid)
    while engine.has_unfinished():
        engine.step()
    engine.release(rid)
    assert engine._requests == {}


def test_metrics_schedule_view_and_snapshot(model):
    """Metrics export in the shape xplane.print_schedule_analysis consumes
    and as a flat JSON snapshot for bench.py."""
    import io
    import json

    from paddle_tpu.profiler import xplane

    (p,) = _prompts((6,), seed=6)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64)
    engine.generate([p], max_new_tokens=4, temperature=0.0)
    snap = engine.metrics.snapshot()
    json.dumps(snap)  # JSON-able end to end
    assert snap["counters"]["generated_tokens"] == 4
    assert "decode_step" in snap["latency"]
    view = engine.metrics.schedule_view()
    st = view["serving-engine"]
    assert st["span_ms"] > 0 and 0 < st["utilization"] <= 1.0
    assert st["n_ops"] == snap["counters"]["prefill_steps"] + snap[
        "counters"]["decode_steps"]
    buf = io.StringIO()
    xplane.print_schedule_analysis(view, file=buf)
    assert "util" in buf.getvalue()


def test_block_pool_alloc_free_copy():
    import jax.numpy as jnp

    pool = BlockPool(num_blocks=6, num_layers=2, block_size=4, num_heads=2,
                     head_dim=8)
    assert pool.num_free == 5  # block 0 reserved as null
    a = pool.allocate(3)
    assert a is not None and 0 not in a
    assert pool.allocate(3) is None  # only 2 left
    pool.k = pool.k.at[a[0]].set(1.0)
    b = pool.allocate(1)
    pool.copy_blocks([a[0]], [b[0]])
    assert float(jnp.sum(pool.k[b[0]])) == float(jnp.sum(pool.k[a[0]]))
    pool.free(a + b)
    assert pool.num_free == 5
    with pytest.raises(ValueError, match="null"):
        pool.free([0])


def test_scheduler_fcfs_and_token_budget():
    """Admission is FCFS and respects the token budget; decode has priority
    between admissions."""
    pool = BlockPool(num_blocks=64, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)
    sched = Scheduler(pool, max_batch=2, token_budget=16, prefill_interval=2)
    bucket = lambda n: 16 if n <= 16 else 32
    r1 = Request([1] * 4, max_new_tokens=4)
    r2 = Request([1] * 4, max_new_tokens=4)
    r3 = Request([1] * 4, max_new_tokens=4)
    for r in (r1, r2, r3):
        sched.add(r)
    kind, picked = sched.schedule(bucket)
    assert kind == "prefill" and picked[0] is r1
    r1.num_cached = 4
    # decode-priority: r2 must wait prefill_interval decode steps
    kind, _ = sched.schedule(bucket)
    assert kind == "decode"
    r1.num_cached += 1
    kind, _ = sched.schedule(bucket)
    assert kind == "decode"
    r1.num_cached += 1
    kind, picked = sched.schedule(bucket)
    assert kind == "prefill" and picked[0] is r2  # FCFS order
    r2.num_cached = 4
    # max_batch=2: r3 cannot be admitted while r1, r2 run
    for _ in range(4):
        kind, _ = sched.schedule(bucket)
        assert kind == "decode"
        for r in (r1, r2):
            r.num_cached += 1
    sched.finish(r1)
    sched.finish(r2)
    kind, picked = sched.schedule(bucket)
    assert kind == "prefill" and picked[0] is r3
    # over-budget head blocks with nothing running -> loud error
    sched2 = Scheduler(pool, max_batch=2, token_budget=8, prefill_interval=1)
    sched2.add(Request([1] * 12, max_new_tokens=1))
    with pytest.raises(ValueError, match="token budget"):
        sched2.schedule(bucket)
