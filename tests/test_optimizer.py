"""Optimizer + LR scheduler tests (numeric update rules vs manual refs)."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _quad_problem(opt_cls, steps=50, **kw):
    paddle.seed(0)
    w = paddle.Parameter(np.array([5.0], np.float32))
    opt = opt_cls(parameters=[w], **kw)
    for _ in range(steps):
        loss = (w * w).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return float(w.numpy()[0])


def test_sgd_converges():
    assert abs(_quad_problem(optimizer.SGD, learning_rate=0.1)) < 0.1


def test_sgd_exact_step():
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = optimizer.SGD(learning_rate=0.5, parameters=[w])
    (w * 3.0).sum().backward()
    opt.step()
    assert abs(w.numpy()[0] - (1.0 - 0.5 * 3.0)) < 1e-6


def test_momentum_converges():
    assert abs(_quad_problem(optimizer.Momentum, learning_rate=0.05, momentum=0.9, steps=80)) < 0.2


def test_adam_bias_correction_first_step():
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = optimizer.Adam(learning_rate=0.1, parameters=[w])
    (w * 2.0).sum().backward()
    opt.step()
    # first adam step moves by ~lr regardless of grad scale
    assert abs(w.numpy()[0] - 0.9) < 1e-3


def test_adam_converges():
    assert abs(_quad_problem(optimizer.Adam, learning_rate=0.2, steps=100)) < 0.1


def test_adamw_decoupled_decay():
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = optimizer.AdamW(learning_rate=0.1, parameters=[w], weight_decay=0.5)
    w._grad = None
    (w * 0.0).sum().backward()
    opt.step()
    # grad is 0 -> update is pure decay: w -= lr*wd*w
    assert abs(w.numpy()[0] - (1.0 - 0.1 * 0.5)) < 1e-4


def test_all_optimizers_step():
    for cls, kw in [
        (optimizer.Adamax, {}),
        (optimizer.Adagrad, {"learning_rate": 0.1}),
        (optimizer.Adadelta, {}),
        (optimizer.RMSProp, {"learning_rate": 0.01}),
        (optimizer.Lamb, {}),
    ]:
        w = paddle.Parameter(np.ones(3, np.float32))
        opt = cls(parameters=[w], **kw)
        (w * w).sum().backward()
        opt.step()
        assert np.abs(w.numpy() - 1.0).max() > 1e-7, cls.__name__


def test_grad_clip_in_optimizer():
    w = paddle.Parameter(np.array([1.0], np.float32))
    opt = optimizer.SGD(
        learning_rate=1.0, parameters=[w], grad_clip=nn.ClipGradByGlobalNorm(0.1)
    )
    (w * 100.0).sum().backward()
    opt.step()
    assert abs(w.numpy()[0] - 0.9) < 1e-4  # clipped grad = 0.1


def test_weight_decay_coupled():
    w = paddle.Parameter(np.array([2.0], np.float32))
    opt = optimizer.SGD(learning_rate=0.1, parameters=[w], weight_decay=0.5)
    (w * 0.0).sum().backward()
    opt.step()
    assert abs(w.numpy()[0] - (2.0 - 0.1 * 0.5 * 2.0)) < 1e-5


def test_optimizer_state_dict_roundtrip():
    w = paddle.Parameter(np.ones(3, np.float32), name="w0")
    opt = optimizer.Adam(parameters=[w])
    (w * w).sum().backward()
    opt.step()
    sd = opt.state_dict()
    w2 = paddle.Parameter(np.ones(3, np.float32), name="w0")
    opt2 = optimizer.Adam(parameters=[w2])
    opt2.set_state_dict(sd)
    st = opt2._get_state(w2)
    ref = opt._get_state(w)
    assert np.allclose(np.asarray(st["moment1"]), np.asarray(ref["moment1"]))


def test_lr_schedulers():
    lr = optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    vals = []
    for _ in range(5):
        vals.append(lr())
        lr.step()
    assert np.allclose(vals[:2], 0.1) and np.allclose(vals[2:4], 0.05)

    cos = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
    assert abs(cos() - 1.0) < 1e-6

    warm = optimizer.lr.LinearWarmup(0.1, warmup_steps=10, start_lr=0.0, end_lr=0.1)
    v0 = warm()
    for _ in range(10):
        warm.step()
    assert v0 < 0.02 and abs(warm() - 0.1) < 1e-6

    pw = optimizer.lr.PiecewiseDecay([3, 6], [0.1, 0.01, 0.001])
    for i in range(8):
        expected = 0.1 if i < 3 else (0.01 if i < 6 else 0.001)
        assert abs(pw() - expected) < 1e-9
        pw.step()


def test_scheduler_in_optimizer():
    w = paddle.Parameter(np.array([1.0], np.float32))
    sched = optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.1)
    opt = optimizer.SGD(learning_rate=sched, parameters=[w])
    assert abs(opt.get_lr() - 0.1) < 1e-9
    sched.step()
    assert abs(opt.get_lr() - 0.01) < 1e-9
