"""Predictor shape buckets beyond batch + GSPMD-sharded serving
(VERDICT r4 item 8 / Missing #6, #7).

Reference capabilities covered: TRT dynamic-shape profiles
(analysis_predictor.h:95) -> per-axis bucketing with padding + out-slicing;
DistModel sharded inference (fleet_executor/dist_model.cc) -> the predictor
compiled over a jax.sharding.Mesh with GSPMD param/input placement.
"""
import numpy as np
import pytest

import jax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import inference, nn


class TokenTagger(nn.Layer):
    """Per-position model: padding positions don't influence real ones, so
    sliced bucketed outputs must equal direct outputs exactly."""

    def __init__(self, vocab=128, dim=16, classes=4):
        super().__init__()
        self.emb = nn.Embedding(vocab, dim)
        self.fc = nn.Linear(dim, classes)

    def forward(self, ids):
        return self.fc(self.emb(ids))


def _tagger_config():
    paddle.seed(0)
    cfg = inference.Config()
    cfg.set_model_factory(TokenTagger)
    return cfg


def test_seq_bucketing_bounds_compile_count():
    cfg = _tagger_config()
    cfg.set_batch_buckets([4])
    cfg.set_shape_buckets({1: [16, 32, 64]})
    pred = inference.create_predictor(cfg)
    rs = np.random.RandomState(0)
    direct = inference.create_predictor(_tagger_config())
    # serve 12 different sequence lengths
    for n, s in [(2, 5), (4, 16), (3, 17), (1, 30), (4, 33), (2, 64),
                 (3, 7), (4, 40), (1, 12), (2, 22), (3, 50), (4, 64)]:
        ids = rs.randint(0, 128, (n, s)).astype(np.int32)
        (out,) = pred.run([ids])
        assert out.shape == (n, s, 4)
        (ref,) = direct.run([ids])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)
    # bounded compile count: 1 batch bucket x 3 seq buckets >= what we used
    assert len(pred._compiled) <= 3, len(pred._compiled)


def test_bucket_overflow_is_loud():
    cfg = _tagger_config()
    cfg.set_shape_buckets({1: [16]})
    pred = inference.create_predictor(cfg)
    with pytest.raises(ValueError, match="exceeds largest bucket"):
        pred.run([np.zeros((1, 32), np.int32)])


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(16, 64)
        self.fc2 = nn.Linear(64, 8)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _mlp_config():
    paddle.seed(1)
    cfg = inference.Config()
    cfg.set_model_factory(MLP)
    return cfg


def test_sharded_predictor_dp_matches_single_device():
    """Batch-sharded (dp) serving over the virtual 8-device mesh equals the
    unsharded predictor bit-for-bit on the same weights."""
    devs = np.array(jax.devices()[:8]).reshape(8)
    mesh = Mesh(devs, ("dp",))
    rs = np.random.RandomState(0)
    x = rs.rand(16, 16).astype(np.float32)

    ref = inference.create_predictor(_mlp_config()).run([x])[0]

    cfg = _mlp_config()
    cfg.set_device_mesh(mesh, input_spec=P("dp"))
    pred = inference.create_predictor(cfg)
    out = pred.run([x])[0]
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-7)
    # params really live on the mesh
    p = next(iter(pred._params.values()))
    assert len(p.sharding.device_set) == 8


def test_sharded_predictor_tensor_parallel_matches():
    """Column-parallel fc1 / row-parallel fc2 over an mp axis (Megatron
    layout) — GSPMD inserts the collectives; outputs equal unsharded."""
    devs = np.array(jax.devices()[:8]).reshape(2, 4)
    mesh = Mesh(devs, ("dp", "mp"))

    def param_spec(name, arr):
        if name == "fc1.weight":  # [in, out] column-split
            return P(None, "mp")
        if name == "fc2.weight":  # [in, out] row-split
            return P("mp", None)
        return P()

    rs = np.random.RandomState(1)
    x = rs.rand(8, 16).astype(np.float32)
    ref = inference.create_predictor(_mlp_config()).run([x])[0]

    cfg = _mlp_config()
    cfg.set_device_mesh(mesh, input_spec=P("dp"), param_spec_fn=param_spec)
    pred = inference.create_predictor(cfg)
    out = pred.run([x])[0]
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_mesh_with_artifact_is_refused(tmp_path):
    from paddle_tpu import jit
    from paddle_tpu.static import InputSpec

    paddle.seed(2)
    net = MLP()
    net.eval()
    path = str(tmp_path / "mlp" / "model")
    jit.save(net, path, input_spec=[InputSpec([None, 16], "float32")])
    cfg = inference.Config(model_path=path)
    cfg.set_device_mesh(Mesh(np.array(jax.devices()[:8]), ("dp",)), input_spec=P("dp"))
    with pytest.raises(ValueError, match="sharded serving"):
        inference.create_predictor(cfg)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))
