"""Tier-1 CI gate: the whole paddle_tpu tree must be jaxlint-clean.

Every finding is either fixed or carries an inline
``# jaxlint: disable=JLxxx -- reason`` waiver; reintroducing any of the
historical bug patterns (zero-copy asarray into donated state, ungated
donate_argnums, repr cache keys, ...) turns this test red.
"""
import os
import time

from paddle_tpu.analysis import lint_paths, lint_source

PKG_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "paddle_tpu")


def _sweep():
    return lint_paths([PKG_DIR], rel_to=os.path.dirname(PKG_DIR))


def test_codebase_is_lint_clean():
    t0 = time.perf_counter()
    rep = _sweep()
    elapsed = time.perf_counter() - t0
    assert rep.errors == [], rep.errors
    assert rep.unsuppressed == [], (
        "jaxlint findings (fix them or add a justified "
        "'# jaxlint: disable=JLxxx -- reason' waiver):\n"
        + "\n".join(f.format() for f in rep.unsuppressed))
    # the gate must stay cheap enough to run in tier-1 forever
    assert elapsed < 10.0, f"lint sweep took {elapsed:.1f}s (budget 10s)"


def test_every_waiver_carries_a_justification():
    rep = _sweep()
    undocumented = [f for f in rep.suppressed if not f.justification]
    assert undocumented == [], (
        "suppressions without a ' -- reason' justification:\n"
        + "\n".join(f.format() for f in undocumented))


def test_gate_trips_on_reseeded_historical_bugs():
    """Seeding any one postmortemed pattern must produce a finding — the
    exact regression the gate exists to catch."""
    seeded = {
        # PR 1 heap corruption: zero-copy asarray into donated state
        "JL001": """
import jax.numpy as jnp
class Tensor:
    def set_value(self, value):
        self._array = jnp.asarray(value)
""",
        # PR 3 constant-baking: repr-keyed compiled-callable cache
        "JL002": """
import jax
def _key(args):
    key = []
    key.append(repr(args[0]))
    return tuple(key)
""",
        # PR 3 mesh miscompile: donation without the backend gate
        "JL004": """
import jax
def build(step):
    return jax.jit(step, donate_argnums=(0, 2))
""",
        # PR 10 round-3 OOM class: eager materialize, then place
        "JL008": """
import jax
import jax.numpy as jnp
def build_arena(shape, sharding):
    return jax.device_put(jnp.zeros(shape, jnp.float32), sharding)
""",
        # PR 6 ring-buffer race: guarded deque iterated outside the lock
        "JL005": """
import threading
class Tracer:
    def __init__(self):
        self.events = []
        self._lock = threading.Lock()
    def push(self, ev):
        with self._lock:
            self.events.append(ev)
    def chrome_trace(self):
        return list(self.events)
""",
        # the deadlock class JL009 exists for: an AB/BA lock-order
        # inversion between two subsystems (hand-built seed — the tree
        # itself must stay cycle-free)
        "JL009": """
import threading
class Ledger:
    def __init__(self):
        self._lock = threading.Lock()
        self._families = threading.Lock()
    def finalize(self):
        with self._lock:
            with self._families:
                pass
    def scrape(self):
        with self._families:
            with self._lock:
                pass
""",
        # PR 13 functional_call race shape: one thread swaps the shared
        # layer's arrays while another reads them, no common guard
        "JL010": """
import threading
class SwappedLayer:
    def __init__(self):
        self._array = None
        self._thread = threading.Thread(target=self._trace_loop)
    def _trace_loop(self):
        saved = self._array
        self._array = saved
    def swap_state(self, arr):
        prev = self._array
        self._array = arr
        return prev
""",
        # the JL007 blind spot JL011 closes: the blocking join is one
        # helper below the async def
        "JL011": """
import threading
class Frontend:
    def __init__(self):
        self._thread = threading.Thread(target=self._loop)
    def _loop(self):
        pass
    def _join_engine(self):
        self._thread.join(timeout=5.0)
    async def shutdown(self):
        self._join_engine()
""",
    }
    for rule_id, src in seeded.items():
        rep = lint_source(src, path=f"seeded_{rule_id}.py")
        assert [f.rule for f in rep.unsuppressed] == [rule_id], (
            rule_id, [f.format() for f in rep.findings])
