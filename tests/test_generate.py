"""GPT autoregressive generation with KV cache (the reference's
fused_multi_transformer decode role, TPU-native: fixed-size caches, one
compiled prefill + one compiled per-token step)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.functional import functional_call, state_dict_arrays
from paddle_tpu.models.gpt import GPT, GPTConfig


@pytest.fixture
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _ids(b=2, s=8):
    return paddle.to_tensor(
        np.random.RandomState(0).randint(0, 128, (b, s)).astype(np.int64)
    )


def test_prefill_matches_full_forward(model):
    ids = _ids()
    full = model(ids).numpy()
    params, bufs = state_dict_arrays(model)
    caches = model.init_caches(2, 16)
    (lg, _), _ = functional_call(
        model, params, bufs, args=(ids._array,),
        kwargs={"caches": caches, "pos_offset": 0}, training=False,
    )
    np.testing.assert_allclose(np.asarray(lg), full, atol=1e-4)


def test_incremental_step_matches_full_forward(model):
    ids = _ids()
    params, bufs = state_dict_arrays(model)
    caches = model.init_caches(2, 16)
    (_, caches), _ = functional_call(
        model, params, bufs, args=(ids._array,),
        kwargs={"caches": caches, "pos_offset": 0}, training=False,
    )
    nxt = np.array([[5], [7]], np.int64)
    full2 = model(
        paddle.to_tensor(np.concatenate([ids.numpy(), nxt], 1))
    ).numpy()
    (lg2, _), _ = functional_call(
        model, params, bufs, args=(nxt,),
        kwargs={"caches": caches, "pos_offset": 8}, training=False,
    )
    np.testing.assert_allclose(np.asarray(lg2)[:, 0], full2[:, 8], atol=1e-4)


def test_generate_greedy_deterministic(model):
    ids = _ids()
    out = model.generate(ids, max_new_tokens=6, temperature=0.0)
    assert out.shape == [2, 14]
    assert np.array_equal(out.numpy()[:, :8], ids.numpy())  # prompt kept
    out2 = model.generate(ids, max_new_tokens=6, temperature=0.0)
    assert np.array_equal(out.numpy(), out2.numpy())


def test_generate_greedy_matches_nocache_argmax(model):
    """Greedy decode with the cache must equal naive re-forward argmax."""
    ids = _ids(b=1, s=4)
    out = model.generate(ids, max_new_tokens=4, temperature=0.0).numpy()[0]
    seq = ids.numpy()[0].tolist()
    for _ in range(4):
        logits = model(paddle.to_tensor(np.asarray([seq], np.int64))).numpy()
        seq.append(int(np.argmax(logits[0, -1])))
    assert out.tolist() == seq


def test_generate_sampling_and_eos(model):
    ids = _ids()
    out = model.generate(ids, max_new_tokens=4, temperature=0.8, top_k=10, seed=3)
    assert out.shape == [2, 12]
    assert (out.numpy() < 128).all() and (out.numpy() >= 0).all()
    # eos early stop: pick the first greedily generated token as "eos"
    g = model.generate(ids, max_new_tokens=6, temperature=0.0)
    eos = int(g.numpy()[0, 8])
    out_eos = model.generate(ids, max_new_tokens=6, temperature=0.0,
                             eos_token_id=eos)
    assert out_eos.shape[1] <= g.shape[1]


def test_generate_length_guard(model):
    with pytest.raises(ValueError, match="max_seq_len"):
        model.generate(_ids(s=60), max_new_tokens=10)


def test_generate_zero_tokens_and_bf16(model):
    ids = _ids()
    out = model.generate(ids, max_new_tokens=0)
    assert out.shape == [2, 8]  # prompt unchanged, nothing sampled

    model.to(dtype="bfloat16")
    out = model.generate(ids, max_new_tokens=3, temperature=0.0)
    assert out.shape == [2, 11]  # bf16 caches follow the param dtype


def test_generate_reuses_compiled_steps(model):
    ids = _ids()
    model.generate(ids, max_new_tokens=2, temperature=0.0)
    fns = dict(model._decode_fns)
    model.generate(ids, max_new_tokens=2, temperature=0.0)
    assert dict(model._decode_fns) == fns  # same executables, no re-jit
