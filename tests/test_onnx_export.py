"""paddle.onnx.export emits REAL ONNX ModelProto (vendored schema) and the
round-trip importer reproduces the model's numerics exactly (no onnx wheel
or runtime ships in-image, so load() is the verification vehicle).

Reference: python/paddle/onnx/export.py:22 (delegates to paddle2onnx)."""
import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.static import InputSpec


def _roundtrip(net, x, spec_shape):
    from paddle_tpu import onnx as ponnx

    net.eval()
    with tempfile.TemporaryDirectory() as td:
        p = ponnx.export(net, os.path.join(td, "m"),
                         input_spec=[InputSpec(spec_shape, "float32")])
        assert p.endswith(".onnx") and os.path.getsize(p) > 0
        assert os.path.exists(p + ".stablehlo.mlir")
        f = ponnx.load(p)
        got = np.asarray(f(np.asarray(x)))
    want = net(paddle.to_tensor(x)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_mlp_roundtrip():
    paddle.seed(0)
    net = nn.Sequential(
        nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 16), nn.GELU(),
        nn.LayerNorm(16), nn.Linear(16, 4), nn.Softmax(),
    )
    x = np.random.RandomState(0).rand(3, 8).astype(np.float32)
    _roundtrip(net, x, [None, 8])


def test_lenet_style_conv_roundtrip():
    paddle.seed(0)
    net = nn.Sequential(
        nn.Conv2D(1, 4, 3, padding=1), nn.ReLU(), nn.MaxPool2D(2, 2),
        nn.Conv2D(4, 8, 3), nn.BatchNorm2D(8), nn.AvgPool2D(2, 2),
        nn.Flatten(), nn.Linear(8 * 6 * 6, 10),
    )
    # burn in some BN stats so eval-form BN is non-trivial
    net.train()
    for _ in range(2):
        net(paddle.to_tensor(np.random.RandomState(1).rand(4, 1, 28, 28).astype(np.float32)))
    x = np.random.RandomState(2).rand(2, 1, 28, 28).astype(np.float32)
    _roundtrip(net, x, [None, 1, 28, 28])


def test_unsupported_layer_raises_clearly():
    from paddle_tpu import onnx as ponnx

    net = nn.Sequential(nn.Linear(4, 4), nn.LSTM(4, 4))
    with tempfile.TemporaryDirectory() as td:
        with pytest.raises(NotImplementedError, match="LSTM"):
            ponnx.export(net, os.path.join(td, "m"),
                         input_spec=[InputSpec([None, 4], "float32")])


def test_avgpool_padding_and_asymmetric_conv_pad_roundtrip():
    """The two review-flagged conventions: exclusive average pooling with
    padding, and paddle's [hb, he, wb, we] conv padding mapping to ONNX
    [hb, wb, he, we]."""
    paddle.seed(0)
    net = nn.Sequential(
        nn.Conv2D(1, 2, 3, padding=[1, 0, 2, 0]),  # top=1 bottom=0 left=2 right=0
        nn.AvgPool2D(2, 2, padding=1),
        nn.Flatten(),
    )
    x = np.random.RandomState(0).rand(2, 1, 9, 9).astype(np.float32)
    _roundtrip(net, x, [None, 1, 9, 9])


def test_gpt_flagship_onnx_roundtrip(tmp_path):
    """The flagship GPT exports to a real ONNX graph (VERDICT r4 weak #8)
    and the verifying importer reproduces the live model's logits."""
    import numpy as np

    import paddle_tpu as paddle
    from paddle_tpu import onnx
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=16, attn_impl="xla", dropout=0.0)
    model = GPT(cfg)
    model.eval()
    path = onnx.export(
        model, str(tmp_path / "gpt"),
        input_spec=[InputSpec([None, 16], "int64")],
    )
    rs = np.random.RandomState(0)
    ids = rs.randint(0, 128, (2, 16)).astype(np.int64)
    ref = np.asarray(model(paddle.to_tensor(ids))._array)
    run = onnx.load(path)
    got = np.asarray(run(ids))
    assert got.shape == ref.shape == (2, 16, 128)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-5)


def test_gpt_onnx_dynamic_seq_refused(tmp_path):
    import pytest as _pytest

    import paddle_tpu as paddle
    from paddle_tpu import onnx
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.static import InputSpec

    paddle.seed(0)
    model = GPT(GPTConfig(vocab_size=64, hidden_size=16, num_layers=1,
                          num_heads=2, max_seq_len=8, attn_impl="xla"))
    with _pytest.raises(NotImplementedError, match="shape buckets"):
        onnx.export(model, str(tmp_path / "g"),
                    input_spec=[InputSpec([None, None], "int64")])
