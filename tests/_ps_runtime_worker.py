"""Worker for the 2-process PS runtime test (reference TheOnePSRuntime
deployment shape: one PSERVER process hosting tables, one TRAINER process
training an embedding model whose rows live on the server).

Usage: python _ps_runtime_worker.py <role> <port>
"""
import os
import sys

ROLE = sys.argv[1]
PORT = sys.argv[2]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddle_tpu.distributed.ps import PSRoleMaker, PSRuntime, distributed_lookup_table

role = PSRoleMaker(role=ROLE, server_num=1, trainer_num=1, index=0)
rt = PSRuntime(role, master_endpoint=f"127.0.0.1:{PORT}")

if ROLE == "PSERVER":
    rt.run_server(block=True)  # returns after the trainer's stop_worker
    print("SERVER DONE", flush=True)
    sys.exit(0)

# ---- trainer --------------------------------------------------------------
import paddle_tpu as paddle
from paddle_tpu import nn


class RecModel(nn.Layer):
    """Dense tower over a REMOTE embedding (lives on the PS)."""

    def __init__(self):
        super().__init__()
        self.emb = nn.Embedding(64, 8)
        self.emb.remote = True  # rows served by the parameter server
        self.fc = nn.Linear(8, 1)

    def forward(self, ids):
        x = distributed_lookup_table(rt, self.emb._ps_table, ids)
        return self.fc(x.mean(axis=1))


paddle.seed(0)
model = RecModel()
rt.init_worker(model, lr=0.5)
assert model.emb._ps_table == "emb.emb"

opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=model.fc.parameters())
rs = np.random.RandomState(0)
ids = paddle.to_tensor(rs.randint(0, 64, (16, 5)).astype(np.int64))
target = paddle.to_tensor(np.ones((16, 1), np.float32))

client = rt.client_for("emb.emb")
rows_before = np.asarray(client.pull_sparse("emb.emb", np.arange(64)))

losses = []
for _ in range(15):
    pred = model(ids)
    loss = ((pred - target) ** 2).mean()
    loss.backward()   # backward PUSHES row grads to the server table
    opt.step()
    opt.clear_grad()
    losses.append(float(np.asarray(loss._array)))

rows_after = np.asarray(client.pull_sparse("emb.emb", np.arange(64)))
assert losses[-1] < 0.5 * losses[0], losses
# the server-side table actually trained (rows moved for the touched ids)
touched = np.unique(np.asarray(ids._array))
delta = np.abs(rows_after[touched] - rows_before[touched]).max()
assert delta > 1e-4, delta
print("TRAINER LOSSES", losses[0], losses[-1], "DELTA", float(delta), flush=True)
rt.stop_worker()
print("TRAINER DONE", flush=True)
