"""SLO-driven autoscaler (serving/autoscale.py): control logic, the
spawn/retire actuation paths, the HTTP surface, and the soak.

Fast tier: `decide()`/`signals()` driven synchronously (streaks,
cooldown, hysteresis, min/max clamps, busy-guard) over a stub router,
plus one real scale-up → scale-down round trip with manually forced
signals (spawn through the factory, spawn-TTFT measured, retire drains
and stamps exactly one terminal lifecycle state) and the
``/debug/autoscale`` endpoint. The `slow` soak is the ISSUE acceptance:
a ramping mixed-tenant wave drives the REAL timer loop to scale 1 → 2
under queue pressure and back down when idle — zero failed requests,
`router_migrated_blocks > 0` on the scale-down (the zero-rewarm
handoff), every replica's lifecycle transitions monotone over the legal
edges, exactly one terminal state each.
"""
import asyncio
import json
import time
import types

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    AsyncLLMEngine,
    AutoScaler,
    LLMEngine,
    ReplicaRouter,
    RouterServer,
)
from paddle_tpu.serving.lifecycle import LEGAL
from paddle_tpu.serving.router import ACTIVE


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _engine(model, **kw):
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 2)
    kw.setdefault("max_seq_len", 64)
    return LLMEngine(model, **kw)


# -- pure control logic over a stub router ------------------------------------


class _StubRouter:
    def __init__(self, n=1, wait=0.0):
        self.wait = wait
        self.factory = lambda i: None
        self.replicas = []
        for i in range(n):
            eng = types.SimpleNamespace(
                engine=types.SimpleNamespace(slo=None, tracer=None),
                inflight=0)
            self.replicas.append(types.SimpleNamespace(
                state=ACTIVE, name=f"r{i}", engine=eng, index=i))

    def _predicted_wait(self, _r):
        return self.wait


def _scaler(router, **kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 2)
    kw.setdefault("up_streak", 2)
    kw.setdefault("down_streak", 3)
    kw.setdefault("cooldown_s", 0.0)
    return AutoScaler(router, **kw)


def test_factory_is_required():
    r = _StubRouter()
    r.factory = None
    with pytest.raises(ValueError, match="factory"):
        AutoScaler(r)


def test_wait_pressure_scales_up_after_streak():
    sc = _scaler(_StubRouter(n=1, wait=1.0), wait_high_s=0.5)
    a1, r1, _ = sc.decide(time.monotonic())
    assert a1 is None and r1 == "steady"         # streak 1 of 2
    a2, r2, sig = sc.decide(time.monotonic())
    assert a2 == "up" and "predicted wait" in r2
    assert sig["min_wait_s"] == 1.0


def test_attainment_pressure_scales_up():
    sc = _scaler(_StubRouter(n=1), up_streak=1, target_attainment=0.99)
    sc.signals = lambda: {"active": 1, "replicas": 1,
                          "worst_attainment": 0.5, "window_events": 10,
                          "min_wait_s": 0.0, "max_wait_s": 0.0,
                          "inflight": 0}
    action, reason, _ = sc.decide(time.monotonic())
    assert action == "up" and "attainment 0.5" in reason


def test_max_replicas_clamps_scale_up():
    sc = _scaler(_StubRouter(n=2, wait=1.0), up_streak=1, wait_high_s=0.5,
                 max_replicas=2)
    action, _, _ = sc.decide(time.monotonic())
    assert action is None


def test_idle_scales_down_after_streak_and_min_clamps():
    sc = _scaler(_StubRouter(n=2, wait=0.0), down_streak=3)
    for _ in range(2):
        assert sc.decide(time.monotonic())[0] is None
    action, reason, _ = sc.decide(time.monotonic())
    assert action == "down" and "idle" in reason
    # at the floor the same idle signal never retires the last replica
    sc2 = _scaler(_StubRouter(n=1, wait=0.0), down_streak=1)
    assert sc2.decide(time.monotonic())[0] is None


def test_cooldown_and_busy_block_decisions():
    sc = _scaler(_StubRouter(n=1, wait=1.0), up_streak=1, wait_high_s=0.5,
                 cooldown_s=60.0)
    sc._cooldown_until = time.monotonic() + 60.0
    action, reason, _ = sc.decide(time.monotonic())
    assert action is None and reason == "cooldown"
    sc._cooldown_until = 0.0
    sc._busy = True
    action, reason, _ = sc.decide(time.monotonic())
    assert action is None and reason == "scale op in flight"
    sc._busy = False
    assert sc.decide(time.monotonic())[0] == "up"


def test_pressure_resets_the_idle_streak():
    sc = _scaler(_StubRouter(n=2, wait=0.0), down_streak=2)
    assert sc.decide(time.monotonic())[0] is None    # idle streak 1
    sc.router.wait = 1.0                             # pressure interleaves
    sc.decide(time.monotonic())
    sc.router.wait = 0.0
    assert sc.decide(time.monotonic())[0] is None    # idle streak restarts
    assert sc.decide(time.monotonic())[0] == "down"


# -- actuation round trip + HTTP surface --------------------------------------


def test_scale_up_then_down_round_trip(model):
    """Forced signals drive one full spawn → retire cycle through the
    real router: the spawned replica serves (TTFT measured), the retired
    one drains to exactly one terminal lifecycle state."""
    born = []

    def factory(i):
        fe = AsyncLLMEngine(_engine(model, warmup=True))
        born.append(fe)
        return fe

    async def run():
        router = ReplicaRouter([factory(0)], factory=factory,
                               sweep_interval_s=3600.0)
        sc = AutoScaler(router, factory=factory, min_replicas=1,
                        max_replicas=2, up_streak=1, down_streak=1,
                        cooldown_s=0.0)
        server = RouterServer(router, port=0, autoscaler=sc)
        await server.start()       # starts the timer loop...
        await sc.stop()            # ...which this test drives by hand
        pressure = {"active": 1, "replicas": 1, "worst_attainment": None,
                    "window_events": 0, "min_wait_s": 9.9, "max_wait_s": 9.9,
                    "inflight": 0}
        sc.signals = lambda: dict(pressure, active=len(router.replicas),
                                  replicas=len(router.replicas))
        await sc.tick()
        assert len(router.replicas) == 2
        assert sc.metrics.counters["autoscale_ups"] == 1
        up = sc.decisions[-1]
        assert up["action"] == "up" and up["spawn_ttft_s"] is not None
        assert router.replicas[1].engine.lifecycle_state() == "serving"
        # the spawned replica serves real traffic
        st = await router.submit([1, 2, 3, 4], max_new_tokens=2,
                                 temperature=0.0)
        toks, reason = await st.collect()
        assert reason in ("length", "stop") and len(toks) == 2

        # /debug/autoscale surfaces knobs + the decision log
        code, body = await _http(server.port, "GET", "/debug/autoscale")
        snap = json.loads(body)
        assert code == 200 and snap["replicas"] == 2
        assert snap["decisions"][-1]["action"] == "up"
        # autoscale series ride the router scrape
        code, body = await _http(server.port, "GET", "/metrics")
        assert code == 200 and b"autoscale_replicas 2" in body

        pressure.update(min_wait_s=0.0, max_wait_s=0.0)
        sc._cooldown_until = 0.0
        await sc.tick()
        assert len(router.replicas) == 1
        assert sc.metrics.counters["autoscale_downs"] == 1
        assert sc.decisions[-1]["action"] == "down"
        retired = born[1]
        assert retired.lifecycle_state() == "stopped"
        tr = retired.engine.lifecycle.transitions()
        assert all(b in LEGAL[a] for a, b in tr)
        assert sum(1 for _, b in tr if b == "stopped") == 1
        await server.shutdown()

    asyncio.run(run())


def test_autoscale_endpoint_404_when_off(model):
    async def run():
        router = ReplicaRouter([AsyncLLMEngine(_engine(model))],
                               sweep_interval_s=3600.0)
        server = RouterServer(router, port=0)
        await server.start()
        code, body = await _http(server.port, "GET", "/debug/autoscale")
        assert code == 404 and b"autoscale-max" in body
        await server.shutdown()

    asyncio.run(run())


async def _http(port, method, path, obj=None):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    data = json.dumps(obj).encode() if obj is not None else b""
    writer.write(
        (f"{method} {path} HTTP/1.1\r\nHost: t\r\n"
         f"Content-Type: application/json\r\n"
         f"Content-Length: {len(data)}\r\n\r\n").encode() + data
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    try:
        await writer.wait_closed()
    except (ConnectionError, OSError):
        pass
    head, _, body = raw.partition(b"\r\n\r\n")
    return int(head.split(b" ")[1]), body


# -- the soak -----------------------------------------------------------------


@pytest.mark.slow
def test_autoscale_soak_ramp_up_and_down(model):
    """The ISSUE acceptance: a ramping mixed-tenant wave through the
    REAL timer loop. Queue pressure on one max_batch=2 replica spawns a
    second through the factory (warmup birth path); going idle retires
    it with the KV-tier migration handoff. Zero failed requests, zero
    rewarm lost (`router_migrated_blocks > 0`), monotone lifecycles,
    exactly one terminal state per replica."""
    born = []

    def factory(i):
        fe = AsyncLLMEngine(_engine(model, warmup=True, slo=True,
                                    host_kv_blocks=16))
        born.append(fe)
        return fe

    rs = np.random.RandomState(0)
    chat_prefix = rs.randint(0, 128, (16,)).tolist()   # 2 full blocks

    async def run():
        # least-loaded spread (no affinity): BOTH replicas must serve —
        # and therefore cache — shared-prefix traffic, so the scale-down
        # migration provably carries blocks (affinity would home every
        # chat request onto one replica and leave the other cold)
        router = ReplicaRouter([factory(0)], factory=factory,
                               sweep_interval_s=0.05, affinity=False)
        await router.start()
        sc = AutoScaler(router, factory=factory, min_replicas=1,
                        max_replicas=2, interval_s=0.05, cooldown_s=0.3,
                        up_streak=1, down_streak=5, wait_high_s=0.02,
                        wait_low_s=0.0, min_window_events=2)
        await sc.start()
        outs = []

        async def fire(prompt, tenant, n=4):
            st = await router.submit(prompt, max_new_tokens=n,
                                     temperature=0.0, tenant=tenant,
                                     deadline_s=120.0)
            outs.append(await st.collect())

        # ramp: mixed-tenant burst waves until the loop spawns replica 2
        deadline = time.monotonic() + 120.0
        while len(router.replicas) < 2 and time.monotonic() < deadline:
            wave = []
            for k in range(6):
                prompt = (chat_prefix + [k] if k % 2 == 0
                          else rs.randint(0, 128, (12,)).tolist())
                wave.append(fire(prompt, "chat" if k % 2 == 0 else "batch"))
            await asyncio.gather(*wave)
        assert len(router.replicas) == 2, "ramp never tripped a scale-up"
        assert len(born) == 2
        up = next(d for d in sc.decisions if d["action"] == "up")
        assert up["spawn_ttft_s"] is not None
        # keep the 2-replica fleet busy so BOTH replicas cache blocks
        await asyncio.gather(*[fire(chat_prefix + [90 + k], "chat")
                               for k in range(10)])

        # go idle: the loop drains replica 2 (down_streak * interval +
        # cooldown + drain); migration must carry its cached blocks over
        deadline = time.monotonic() + 120.0
        while len(router.replicas) > 1 and time.monotonic() < deadline:
            await asyncio.sleep(0.1)
        assert len(router.replicas) == 1, "idle never tripped a scale-down"
        assert sc.metrics.counters["autoscale_downs"] == 1
        assert router.metrics.counters.get("router_migrated_blocks", 0) > 0

        # post-scale-down traffic still serves (zero-rewarm survivors)
        await fire(chat_prefix + [99], "chat")
        await sc.stop()
        await router.shutdown()
        return outs

    outs = asyncio.run(run())
    assert outs and all(r in ("length", "stop") for _, r in outs), (
        "soak dropped requests: "
        f"{[r for _, r in outs if r not in ('length', 'stop')]}")
    for fe in born:
        tr = fe.engine.lifecycle.transitions()
        assert all(b in LEGAL[a] for a, b in tr), tr
        assert sum(1 for _, b in tr if b == "stopped") == 1
        assert fe.lifecycle_state() == "stopped"
