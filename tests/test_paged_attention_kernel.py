"""Ragged paged-attention kernel vs. the XLA gather fallback.

The Pallas kernel (ops/pallas/paged_attention.py) runs in `interpret=True`
mode on CPU against the padded-gather reference across ragged cases: mixed
decode/prefill rows, chunks crossing block boundaries, a partially filled
last block (whose stale tail the positional mask must discard), and
null-block table padding. A small smoke subset always runs; the full sweep
is marked `slow` so tier-1 stays inside its timeout.

Also covers the shared backend gate (`ops/pallas/_backend.py`) env knobs.
"""
import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu.ops.pallas._backend import interpret_mode, use_pallas
from paddle_tpu.ops.pallas.paged_attention import (
    paged_attention_xla,
    ragged_paged_attention,
)

TOL = 1e-3  # issue acceptance: kernel matches fallback to >= 1e-3


def _case(lengths_counts, *, block_size, num_heads=2, head_dim=16,
          num_layers=2, layer=1, seed=0):
    """Build a random arena + ragged batch. `lengths_counts` is a list of
    (total_tokens, chunk_count): each row's query chunk is the LAST `count`
    positions of its `total` tokens (count == total -> fresh prefill;
    count == 1 -> decode row)."""
    rs = np.random.RandomState(seed)
    B = len(lengths_counts)
    blocks_per = [
        max(1, -(-total // block_size)) for total, _ in lengths_counts
    ]
    num_blocks = 1 + sum(blocks_per)  # block 0 = null
    max_blocks = max(blocks_per) + 1  # leave table padding to exercise
    # garbage EVERYWHERE (incl. the null block and each partially filled
    # last block's tail): correctness must come from masking, not zeros
    k = rs.randn(num_layers, num_heads, num_blocks, block_size,
                 head_dim).astype(np.float32)
    v = rs.randn(num_layers, num_heads, num_blocks, block_size,
                 head_dim).astype(np.float32)
    tables = np.zeros((B, max_blocks), np.int32)
    nxt = 1
    for i, nb in enumerate(blocks_per):
        tables[i, :nb] = np.arange(nxt, nxt + nb)
        nxt += nb
    S = max(c for _, c in lengths_counts)
    q = rs.randn(B, S, num_heads, head_dim).astype(np.float32)
    qpos = np.zeros((B, S), np.int32)
    q_start = np.zeros(B, np.int32)
    kv_live = np.ones(B, np.int32)
    for i, (total, count) in enumerate(lengths_counts):
        start = total - count
        qpos[i, :count] = np.arange(start, total)
        q_start[i] = start
        kv_live[i] = (total - 1) // block_size + 1
    return (jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), layer,
            jnp.asarray(tables), jnp.asarray(qpos), jnp.asarray(q_start),
            jnp.asarray(kv_live))


def _check(lengths_counts, **kw):
    q, k, v, layer, tables, qpos, q_start, kv_live = _case(
        lengths_counts, **kw)
    out_k = np.asarray(ragged_paged_attention(
        q, k, v, layer, tables, q_start, kv_live, interpret=True))
    out_r = np.asarray(paged_attention_xla(q, k, v, layer, tables, qpos))
    for i, (_, count) in enumerate(lengths_counts):
        err = np.abs(out_k[i, :count] - out_r[i, :count]).max()
        assert err < TOL, f"row {i} (count {count}): max err {err}"
        assert np.isfinite(out_k[i, :count]).all()


def test_kernel_matches_fallback_smoke():
    """Always-on subset: one mixed batch with a decode row, a fresh prefill
    chunk, and a boundary-crossing chunk over a partially filled block."""
    _check([(18, 1), (5, 5), (13, 7)], block_size=8)


def test_kernel_single_row_partial_last_block():
    """A lone decode row whose last block is partially filled: the stale
    tail beyond qpos must not leak into the softmax."""
    _check([(9, 1)], block_size=8)


def _check_ragged_q(lengths_counts, pad_to, **kw):
    """Like _check but with per-row ragged QUERY lengths (`q_lens`): the
    step width pads to `pad_to` and every row declares its own live
    count — the unified step program's shape (a decode row inside a wide
    launch). Live outputs must match the reference; dead q tiles may
    hold garbage."""
    q, k, v, layer, tables, qpos, q_start, kv_live = _case(
        lengths_counts, **kw)
    B, S = q.shape[:2]
    assert pad_to >= S
    qw = jnp.zeros((B, pad_to) + q.shape[2:], q.dtype).at[:, :S].set(q)
    q_lens = jnp.asarray([c for _, c in lengths_counts], jnp.int32)
    out_k = np.asarray(ragged_paged_attention(
        qw, k, v, layer, tables, q_start, kv_live, q_lens=q_lens,
        interpret=True))
    out_r = np.asarray(paged_attention_xla(q, k, v, layer, tables, qpos))
    for i, (_, count) in enumerate(lengths_counts):
        err = np.abs(out_k[i, :count] - out_r[i, :count]).max()
        assert err < TOL, f"row {i} (count {count}): max err {err}"
        assert np.isfinite(out_k[i, :count]).all()


def test_kernel_ragged_query_lengths_smoke():
    """Per-row ragged q: a decode row, a short chunk, and a full-width
    chunk share one 16-wide launch (qt=8, two query tiles — the decode
    row computes only tile 0); live rows match the reference exactly."""
    _check_ragged_q([(18, 1), (5, 5), (16, 16)], pad_to=16, block_size=8)


def test_kernel_ragged_query_decode_in_wide_launch():
    """The dominant unified-program case: width-1 decode rows riding a
    wide (verify/chunk) program width — q_lens=1 everywhere, padding
    tiles dead."""
    _check_ragged_q([(9, 1), (23, 1)], pad_to=8, block_size=8)


@pytest.mark.slow
@pytest.mark.parametrize("block_size", [4, 8, 16])
@pytest.mark.parametrize("lengths_counts", [
    [(1, 1)],                                  # minimal decode
    [(16, 16)],                                # exact block multiple prefill
    [(17, 17)],                                # one past a block boundary
    [(31, 15), (32, 1), (3, 3), (20, 4)],      # ragged mixed batch
    [(8, 1), (8, 8), (24, 12), (5, 2)],        # decode + chunks, shared S
])
def test_kernel_matches_fallback_sweep(block_size, lengths_counts):
    """Interpret-mode sweep over ragged lengths x block sizes (slow: the
    Pallas interpreter runs one grid step at a time)."""
    _check(lengths_counts, block_size=block_size, seed=hash(
        (block_size, tuple(lengths_counts))) % 2**31)


@pytest.mark.slow
def test_kernel_bfloat16_tolerance():
    q, k, v, layer, tables, qpos, q_start, kv_live = _case(
        [(18, 1), (13, 7)], block_size=8)
    qb, kb, vb = (x.astype(jnp.bfloat16) for x in (q, k, v))
    out_k = np.asarray(ragged_paged_attention(
        qb, kb, vb, layer, tables, q_start, kv_live, interpret=True)
    ).astype(np.float32)
    out_r = np.asarray(paged_attention_xla(
        qb, kb, vb, layer, tables, qpos)).astype(np.float32)
    for i, count in enumerate((1, 7)):
        err = np.abs(out_k[i, :count] - out_r[i, :count]).max()
        assert err < 2e-2, f"row {i}: bf16 max err {err}"


def test_backend_gate_env_overrides(monkeypatch):
    """DISABLE beats FORCE beats platform; FORCE turns on interpret mode."""
    monkeypatch.delenv("PADDLE_TPU_DISABLE_PALLAS", raising=False)
    monkeypatch.delenv("PADDLE_TPU_FORCE_PALLAS_INTERPRET", raising=False)
    monkeypatch.delenv("PADDLE_TPU_PALLAS_INTERPRET", raising=False)
    assert use_pallas() is False  # CPU backend, no opt-in
    assert interpret_mode() is False
    monkeypatch.setenv("PADDLE_TPU_FORCE_PALLAS_INTERPRET", "1")
    assert use_pallas() is True
    assert interpret_mode() is True
    monkeypatch.setenv("PADDLE_TPU_DISABLE_PALLAS", "1")
    assert use_pallas() is False  # DISABLE wins
    monkeypatch.delenv("PADDLE_TPU_DISABLE_PALLAS")
    monkeypatch.delenv("PADDLE_TPU_FORCE_PALLAS_INTERPRET")
    monkeypatch.setenv("PADDLE_TPU_PALLAS_INTERPRET", "1")
    assert use_pallas() is True  # legacy knob still opts in
    assert interpret_mode() is True


def test_flash_attention_shares_backend_gate():
    """The flash kernel's gate is the hoisted shared one, not a copy."""
    from paddle_tpu.ops.pallas import flash_attention

    assert flash_attention._use_pallas is use_pallas


@pytest.mark.slow
def test_engine_greedy_identical_through_interpreted_kernel(monkeypatch):
    """End to end: LLMEngine with PADDLE_TPU_FORCE_PALLAS_INTERPRET serves
    greedy outputs token-identical to sequential GPT.generate — the kernel
    slots into the jitted mixed step without changing argmax decisions."""
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPT, GPTConfig
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=32, attn_impl="xla",
                    dropout=0.0)
    m = GPT(cfg)
    m.eval()
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, 128, (n,)).tolist() for n in (5, 11)]

    def ref(p, n):
        ids = paddle.to_tensor(np.asarray([p], np.int64))
        out = m.generate(ids, max_new_tokens=n, temperature=0.0)
        return out.numpy()[0, len(p):].tolist()

    monkeypatch.setenv("PADDLE_TPU_FORCE_PALLAS_INTERPRET", "1")
    engine = LLMEngine(m, block_size=8, max_batch=2, max_seq_len=32,
                       prefill_chunk=8)
    outs = engine.generate(prompts, max_new_tokens=4, temperature=0.0)
    for p, o in zip(prompts, outs):
        assert o == ref(p, 4)
    assert engine.metrics.counters["jit_traces"] == 2
