"""static.append_backward / static.gradients (VERDICT r4 item 5).

Reference: /root/reference/python/paddle/fluid/backward.py:1826 — the static
autodiff API that lets raw static-graph users build training programs
without hapi. Here the backward is one recorded op (jax.vjp of the program
replay) and optimizer.minimize under capture appends update ops with
state-write registrations, so Executor.run IS a train step.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, static


def test_gradients_wrt_feed():
    """d(mean(x^2 + 3x))/dx = (2x + 3)/n fetched via Executor.run."""
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [4, 5], "float32")
        y = (x * x + 3.0 * x).mean()
        (gx,) = static.gradients(y, x)
    exe = static.Executor()
    xv = np.random.RandomState(0).rand(4, 5).astype(np.float32)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[y, gx])
    np.testing.assert_allclose(out[1], (2 * xv + 3) / xv.size, rtol=1e-5)


def test_gradients_with_target_gradients():
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [3], "float32")
        y = x * x
        ct = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
        (gx,) = static.gradients(y, x, target_gradients=[ct])
    exe = static.Executor()
    xv = np.array([1.0, 1.0, 1.0], np.float32)
    out = exe.run(prog, feed={"x": xv}, fetch_list=[gx])
    np.testing.assert_allclose(out[0], 2 * xv * np.array([1, 2, 3]), rtol=1e-6)


def test_append_backward_finds_parameters():
    paddle.seed(0)
    net = nn.Linear(8, 4)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [16, 8], "float32")
        loss = net(x).mean()
        pgs = static.append_backward(loss)
    names = {id(p) for p, _ in pgs}
    assert id(net.weight) in names and id(net.bias) in names
    exe = static.Executor()
    xv = np.ones((16, 8), np.float32)
    grads = exe.run(prog, feed={"x": xv}, fetch_list=[g for _, g in pgs])
    # d mean(xW+b) / d b = 1/4 per output unit
    bias_grad = grads[[id(p) for p, _ in pgs].index(id(net.bias))]
    np.testing.assert_allclose(bias_grad, 0.25 * np.ones(4), rtol=1e-5)


def _raw_static_train(opt_factory, steps=60):
    """A raw static training loop — no hapi anywhere: capture forward + loss,
    minimize() appends backward + update ops, then Executor.run per batch."""
    paddle.seed(3)
    rs = np.random.RandomState(0)
    # learnable 2-layer net on a linearly separable toy problem
    net = nn.Sequential(nn.Linear(10, 32), nn.ReLU(), nn.Linear(32, 3))
    W = rs.rand(10, 3).astype(np.float32)
    X = rs.rand(512, 10).astype(np.float32)
    Y = (X @ W).argmax(1)[:, None].astype(np.int64)

    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [64, 10], "float32")
        y = static.data("y", [64, 1], "int64")
        loss = nn.CrossEntropyLoss()(net(x), paddle.to_tensor(y) if False else y)
        opt = opt_factory(net.parameters())
        _, pgs = opt.minimize(loss)
    exe = static.Executor()
    losses = []
    for step in range(steps):
        i = (step * 64) % 512
        out = exe.run(
            prog, feed={"x": X[i : i + 64], "y": Y[i : i + 64]}, fetch_list=[loss]
        )
        losses.append(float(out[0]))
    return losses, net, opt


def test_raw_static_training_converges_sgd():
    losses, net, _ = _raw_static_train(
        lambda ps: paddle.optimizer.SGD(learning_rate=0.5, parameters=ps)
    )
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    # params actually moved (state writes persisted into the layer)
    assert float(np.abs(np.asarray(net[0].weight._array)).max()) > 0


def test_raw_static_training_converges_adam_with_slots():
    losses, net, opt = _raw_static_train(
        lambda ps: paddle.optimizer.Adam(learning_rate=0.01, parameters=ps)
    )
    assert losses[-1] < 0.5 * losses[0], (losses[0], losses[-1])
    # Adam moments persisted across runs (non-zero after training) and are
    # visible through state_dict for checkpointing
    sd = opt.state_dict()
    m1 = [v for k, v in sd.items() if k.endswith("_moment1")]
    assert m1 and any(float(np.abs(np.asarray(t._array)).max()) > 0 for t in m1)
    # beta1_pow advanced: 0.9^steps, not the fresh 0.9
    b1p = [v for k, v in sd.items() if k.endswith("_beta1_pow")]
    assert b1p and float(np.asarray(b1p[0]._array)) < 0.9**10


def test_static_training_matches_eager():
    """The raw static loop and an eager loop with identical data and init
    produce the same loss trajectory (same math, whole-program compiled)."""
    rs = np.random.RandomState(7)
    X = rs.rand(128, 6).astype(np.float32)
    Y = rs.randint(0, 2, (128, 1)).astype(np.int64)

    def eager():
        paddle.seed(1)
        net = nn.Linear(6, 2)
        opt = paddle.optimizer.SGD(learning_rate=0.2, parameters=net.parameters())
        losses = []
        for s in range(20):
            i = (s * 32) % 128
            loss = nn.CrossEntropyLoss()(
                net(paddle.to_tensor(X[i : i + 32])), paddle.to_tensor(Y[i : i + 32])
            )
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._array)))
        return losses

    def static_run():
        paddle.seed(1)
        net = nn.Linear(6, 2)
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data("x", [32, 6], "float32")
            y = static.data("y", [32, 1], "int64")
            loss = nn.CrossEntropyLoss()(net(x), y)
            opt = paddle.optimizer.SGD(learning_rate=0.2, parameters=net.parameters())
            opt.minimize(loss)
        exe = static.Executor()
        losses = []
        for s in range(20):
            i = (s * 32) % 128
            out = exe.run(
                prog, feed={"x": X[i : i + 32], "y": Y[i : i + 32]},
                fetch_list=[loss],
            )
            losses.append(float(out[0]))
        return losses

    np.testing.assert_allclose(static_run(), eager(), rtol=2e-4, atol=1e-6)


if __name__ == "__main__":
    import sys

    sys.exit(pytest.main([__file__, "-x", "-q"]))


def test_executor_train_from_dataset(tmp_path):
    """The reference's dataset-trainer entry (fluid Executor.train_from_dataset
    over an InMemoryDataset): slot files -> fleet dataset -> per-batch
    Executor.run with minimize-appended update ops -> loss drops."""
    from paddle_tpu.io.fleet_dataset import InMemoryDataset

    rs = np.random.RandomState(0)
    w_true = np.array([1.5, -2.0, 0.5], np.float32)
    # slot-text file: x (3 floats) then label (1 float) per line
    lines, xs_raw, ys_raw = [], [], []
    for _ in range(256):
        x = rs.rand(3).astype(np.float32)
        yv = float(x @ w_true)
        xs_raw.append(x)
        ys_raw.append([yv])
        # paddle slot-text: "<count> <values...>" per declared slot
        lines.append("3 " + " ".join(f"{v:.6f}" for v in x) + f" 1 {yv:.6f}")
    f = tmp_path / "part-000"
    f.write_text("\n".join(lines) + "\n")

    paddle.seed(0)
    net = nn.Linear(3, 1)
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", [32, 3], "float32")
        y = static.data("y", [32, 1], "float32")
        loss = ((net(x) - y) ** 2).mean()
        opt = paddle.optimizer.SGD(learning_rate=0.2, parameters=net.parameters())
        opt.minimize(loss)

    ds = InMemoryDataset()
    ds.init(batch_size=32, use_var=[x, y])
    ds.set_filelist([str(f)])
    ds.set_drop_last(True)
    ds.load_into_memory()

    exe = static.Executor()
    xb = np.stack(xs_raw[:32]).astype(np.float32)
    yb = np.asarray(ys_raw[:32], np.float32)
    first = float(exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])[0])
    for _ in range(15):  # epochs over the dataset
        ds.local_shuffle()
        exe.train_from_dataset(prog, ds, fetch_list=[loss], print_period=10**9)
    final = float(exe.run(prog, feed={"x": xb, "y": yb}, fetch_list=[loss])[0])
    assert final < 0.05 * first, (first, final)
    w = np.asarray(net.weight._array).ravel()
    np.testing.assert_allclose(w, w_true, atol=0.4)
