"""Detection vertical: ops parity (matrix_nms / generate_proposals /
distribute_fpn_proposals / box_coder vs straightforward numpy references of
the reference-op semantics), the PP-YOLOE-class model, and the inference
predictor end-to-end with shape buckets.

Reference: /root/reference/paddle/fluid/operators/detection/*.cc (semantics),
python/paddle/vision/ops.py (API shapes).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


# ---------------------------------------------------------------------------
# numpy references (reimplement semantics, not the reference code)
# ---------------------------------------------------------------------------

def _np_iou(a, b):
    lt = np.maximum(a[:2], b[:2])
    rb = np.minimum(a[2:], b[2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[0] * wh[1]
    ar = lambda x: (x[2] - x[0]) * (x[3] - x[1])
    return inter / max(ar(a) + ar(b) - inter, 1e-10)


def _np_matrix_nms_class(boxes, scores, score_thr, post_thr, top_k, gaussian, sigma):
    """Decay NMS for one class, sorted-descending semantics."""
    idx = np.argsort(-scores)
    idx = [i for i in idx if scores[i] > score_thr][:top_k]
    out = []
    for r, i in enumerate(idx):
        decay = 1.0
        for rj in range(r):
            j = idx[rj]
            iou_ij = _np_iou(boxes[i], boxes[j])
            comp_j = max(
                (_np_iou(boxes[j], boxes[idx[rl]]) for rl in range(rj)), default=0.0
            )
            if gaussian:
                # reference kernel formula: exp((max_iou^2 - iou^2) * sigma)
                decay = min(decay, np.exp((comp_j**2 - iou_ij**2) * sigma))
            else:
                decay = min(decay, (1 - iou_ij) / max(1 - comp_j, 1e-10))
        ds = scores[i] * decay
        if ds > post_thr:
            out.append((i, ds))
    return out


class TestMatrixNMS:
    def test_matches_numpy_reference(self):
        rs = np.random.RandomState(0)
        M, C = 24, 3
        boxes = rs.rand(M, 4).astype(np.float32) * 50
        boxes[:, 2:] = boxes[:, :2] + 5 + rs.rand(M, 2).astype(np.float32) * 40
        scores = rs.rand(C, M).astype(np.float32)
        for gaussian in (False, True):
            out, idx, num = vops.matrix_nms(
                boxes[None], scores[None], 0.15, 0.25, 16, 32,
                use_gaussian=gaussian, gaussian_sigma=2.0,
                background_label=0, return_index=True,
            )
            got = np.asarray(out.numpy())
            n = int(num.numpy()[0])
            expect = []
            for c in range(1, C):  # class 0 = background, excluded
                for i, ds in _np_matrix_nms_class(
                    boxes, scores[c], 0.15, 0.25, 16, gaussian, 2.0
                ):
                    expect.append((c, ds, i))
            expect.sort(key=lambda t: -t[1])
            expect = expect[:32]
            assert n == len(expect), (n, len(expect))
            for r, (c, ds, i) in enumerate(expect):
                assert int(got[r, 0]) == c
                assert abs(got[r, 1] - ds) < 1e-4
                np.testing.assert_allclose(got[r, 2:], boxes[i], rtol=1e-5)
                assert int(idx.numpy()[r]) == i

    def test_padding_is_marked(self):
        boxes = np.array([[0, 0, 10, 10.0]], np.float32)
        scores = np.array([[0.9], [0.8]], np.float32)
        out, num = vops.matrix_nms(boxes[None], scores[None], 0.5, 0.5, 10, 8,
                                   background_label=-1)
        assert int(num.numpy()[0]) == 2
        got = np.asarray(out.numpy())
        assert (got[2:, 0] == -1).all()  # pad rows carry label -1


class TestGreedyNMS:
    def test_matches_host_nms(self):
        import jax.numpy as jnp

        rs = np.random.RandomState(1)
        n = 30
        boxes = rs.rand(n, 4).astype(np.float32) * 60
        boxes[:, 2:] = boxes[:, :2] + 4 + rs.rand(n, 2).astype(np.float32) * 30
        scores = rs.rand(n).astype(np.float32)
        keep, num = vops.nms_padded_array(
            jnp.asarray(boxes), jnp.asarray(scores), 0.4, n
        )
        ref = np.asarray(vops.nms(boxes, 0.4, scores=scores).numpy())
        got = np.asarray(keep)[: int(num)]
        np.testing.assert_array_equal(got, ref)


class TestBoxCoder:
    def test_encode_decode_roundtrip(self):
        rs = np.random.RandomState(2)
        P_, T_ = 5, 7
        priors = rs.rand(P_, 4).astype(np.float32) * 50
        priors[:, 2:] = priors[:, :2] + 10 + rs.rand(P_, 2).astype(np.float32) * 20
        targets = rs.rand(T_, 4).astype(np.float32) * 50
        targets[:, 2:] = targets[:, :2] + 10 + rs.rand(T_, 2).astype(np.float32) * 20
        enc = vops.box_coder(priors, None, targets, "encode_center_size")
        dec = vops.box_coder(priors, None, enc.numpy(), "decode_center_size")
        d = np.asarray(dec.numpy())  # [T,P,4]; diagonal-free: every prior decodes
        for t in range(T_):
            for p in range(P_):
                np.testing.assert_allclose(d[t, p], targets[t], rtol=1e-4, atol=1e-3)


class TestGenerateProposals:
    def _anchors(self, H, W, A, stride=8):
        a = np.zeros((H, W, A, 4), np.float32)
        for y in range(H):
            for x in range(W):
                for k in range(A):
                    cs = stride * (k + 1)
                    a[y, x, k] = [x * stride - cs / 2, y * stride - cs / 2,
                                  x * stride + cs / 2, y * stride + cs / 2]
        return a

    def test_invariants(self):
        rs = np.random.RandomState(3)
        N, A, H, W = 2, 3, 8, 8
        scores = rs.rand(N, A, H, W).astype(np.float32)
        deltas = (rs.rand(N, 4 * A, H, W).astype(np.float32) - 0.5) * 0.3
        anchors = self._anchors(H, W, A)
        var = np.ones_like(anchors) * 0.5
        img = np.array([[64, 64], [48, 56]], np.float32)
        rois, nums = vops.generate_proposals(
            scores, deltas, img, anchors, var,
            pre_nms_top_n=60, post_nms_top_n=12, nms_thresh=0.5, min_size=2.0,
        )
        r = np.asarray(rois.numpy()).reshape(N, 12, 4)
        ns = np.asarray(nums.numpy())
        for i in range(N):
            k = int(ns[i])
            assert 0 < k <= 12
            valid = r[i, :k]
            # clipped to the per-image size
            assert (valid[:, 0] >= 0).all() and (valid[:, 2] <= img[i, 1]).all()
            assert (valid[:, 1] >= 0).all() and (valid[:, 3] <= img[i, 0]).all()
            # min-size respected
            assert ((valid[:, 2] - valid[:, 0]) >= 2.0 - 1e-4).all()
            # pairwise IoU below the NMS threshold
            for a_ in range(k):
                for b_ in range(a_ + 1, k):
                    assert _np_iou(valid[a_], valid[b_]) <= 0.5 + 1e-5
            # padding rows are zero
            assert (r[i, k:] == 0).all()


class TestDistributeFPN:
    def test_levels_and_restore(self):
        rs = np.random.RandomState(4)
        R = 20
        rois = rs.rand(R, 4).astype(np.float32) * 80
        sizes = np.array([16, 32, 64, 128, 256] * 4, np.float32)[:R]
        rois[:, 2] = rois[:, 0] + sizes
        rois[:, 3] = rois[:, 1] + sizes
        multi, restore, nums = vops.distribute_fpn_proposals(rois, 2, 5, 4, 224)
        ns = np.asarray(nums.numpy())
        assert ns.sum() == R
        # expected level from the reference formula
        areas = sizes * sizes
        lvl = np.clip(
            np.floor(np.log2(np.sqrt(areas) / 224 + 1e-8)) + 4, 2, 5
        ).astype(int)
        for li in range(4):
            level_rois = np.asarray(multi[li].numpy())[: ns[li]]
            mine = rois[lvl == li + 2]
            np.testing.assert_allclose(level_rois, mine, rtol=1e-6)
        # restore index maps the level-concat back to input order
        concat = np.concatenate(
            [np.asarray(multi[li].numpy())[: ns[li]] for li in range(4)]
        )
        ri = np.asarray(restore.numpy())[:, 0]
        np.testing.assert_allclose(concat[ri], rois, rtol=1e-6)


class TestPPYOLOE:
    @pytest.mark.slow  # tier-1 headroom (PR 19): heaviest always-on case; tier-2 covers it
    def test_predict_shapes_and_validity(self):
        from paddle_tpu.vision.models import ppyoloe_s

        paddle.seed(0)
        m = ppyoloe_s(num_classes=4)
        m.eval()
        x = np.random.RandomState(0).rand(2, 3, 64, 64).astype(np.float32)
        out, nums = m.predict(x, keep_top_k=10)
        o = np.asarray(out.numpy()).reshape(2, 10, 6)
        ns = np.asarray(nums.numpy())
        assert ns.shape == (2,)
        for i in range(2):
            valid = o[i, : ns[i]]
            if len(valid):
                assert (valid[:, 2] >= 0).all() and (valid[:, 4] <= 64).all()
                assert (valid[:, 1] >= 0).all() and (valid[:, 1] <= 1).all()

    @pytest.mark.slow
    def test_simple_loss_trains(self):
        from paddle_tpu.vision.models import ppyoloe_s

        paddle.seed(0)
        m = ppyoloe_s(num_classes=3)
        opt = paddle.optimizer.Adam(learning_rate=5e-4, parameters=m.parameters())
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(rs.rand(2, 3, 64, 64).astype(np.float32))
        gt_boxes = paddle.to_tensor(
            np.array([[[8, 8, 24, 24]], [[30, 30, 50, 50]]], np.float32)
        )
        gt_labels = paddle.to_tensor(np.array([[1], [2]]))
        losses = []
        for _ in range(3):
            cls, reg = m(x)
            loss = m.simple_loss(cls, reg, gt_boxes, gt_labels)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0], losses


class TestPredictorDetection:
    def test_shape_buckets_e2e(self):
        """The BASELINE-config-4 capability: variable batch through the
        bucket-AOT predictor on a real detection model."""
        from paddle_tpu.inference import Config, create_predictor
        from paddle_tpu.vision.models import ppyoloe_s

        paddle.seed(0)

        built = {}

        def factory():
            m = ppyoloe_s(num_classes=4)
            m.eval()
            built["m"] = m
            return m

        cfg = Config()
        cfg.set_model_factory(factory)
        cfg.set_batch_buckets([2, 4])
        pred = create_predictor(cfg)
        rs = np.random.RandomState(0)
        for n in (1, 2, 3):
            outs = pred.run([rs.rand(n, 3, 64, 64).astype(np.float32)])
            # raw head outputs, truncated back to the real batch
            assert all(np.asarray(o).shape[0] == n for o in outs)
        # only two buckets -> at most two compiled signatures
        assert len(pred._compiled) <= 2


def test_box_coder_2d_decode_pairs_rows():
    """[T,4] deltas decode row t against prior t (not prior 0)."""
    rs = np.random.RandomState(5)
    n = 6
    priors = rs.rand(n, 4).astype(np.float32) * 50
    priors[:, 2:] = priors[:, :2] + 10 + rs.rand(n, 2).astype(np.float32) * 20
    targets = rs.rand(n, 4).astype(np.float32) * 50
    targets[:, 2:] = targets[:, :2] + 10 + rs.rand(n, 2).astype(np.float32) * 20
    enc = np.asarray(
        vops.box_coder(priors, None, targets, "encode_center_size").numpy()
    )
    deltas = enc[np.arange(n), np.arange(n)]  # row t encoded vs prior t
    dec = np.asarray(
        vops.box_coder(priors, None, deltas, "decode_center_size").numpy()
    )
    np.testing.assert_allclose(dec, targets, rtol=1e-4, atol=1e-3)


def test_distribute_fpn_respects_rois_num():
    """Pad rows (index >= rois_num) route to NO level and restore maps them
    past the valid rows (padded-capacity contract)."""
    rois = np.array(
        [[0, 0, 16, 16], [0, 0, 600, 600], [0, 0, 0, 0], [0, 0, 0, 0]],
        np.float32,
    )
    multi, restore, nums = vops.distribute_fpn_proposals(
        rois, 2, 5, 4, 224, rois_num=np.array([2], np.int32)
    )
    ns = np.asarray(nums.numpy())
    assert ns.sum() == 2  # pads counted nowhere
    assert ns[0] == 1 and ns[-1] == 1  # small -> level 2, big -> level 5
    ri = np.asarray(restore.numpy())[:, 0]
    assert set(ri[2:]) == {2, 3}  # pad rows sit past the valid rows
