"""Runtime lock-order witness (analysis/witness.py): unit mechanics plus
THE tier-1 end-to-end check — a witnessed chaos serve must be
acquisition-order-acyclic, the observed graph must be covered by the
static JL009 model (observed-but-unmodeled edges are a parser-gap
canary, the hlolint discipline), and a witnessed serve must be
token-identical to an unwitnessed one.

The full chaos/router-chaos suites run witnessed when
``PADDLE_TPU_LOCK_WITNESS=1`` (module fixtures there); this file keeps a
compact always-on variant inside the tier-1 budget.
"""
import asyncio
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.analysis import witness
from paddle_tpu.analysis.witness import LockOrderViolation

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))


@pytest.fixture(autouse=True)
def _always_uninstall():
    yield
    witness.uninstall()


def _install_here():
    """Witness locks constructed from THIS file (the default filter only
    wraps paddle_tpu construction sites)."""
    return witness.install(package_root=TESTS_DIR)


# -- unit: bookkeeping --------------------------------------------------------


def test_held_set_bookkeeping_and_consistent_order_is_clean():
    w = _install_here()
    a = threading.Lock()
    b = threading.Lock()
    with a:
        assert len(w.held_now()) == 1
        with b:
            assert len(w.held_now()) == 2
    assert w.held_now() == []
    with a:
        with b:
            pass
    w.check_acyclic()   # A->B twice: one edge, no cycle
    g = w.observed_graph()
    assert len(g["nodes"]) == 2
    assert len(g["edges"]) == 1
    assert g["edges"][0]["count"] == 2


def test_rlock_reentrancy_records_no_self_edge():
    w = _install_here()
    r = threading.RLock()
    with r:
        with r:
            assert len(w.held_now()) == 2
        assert len(w.held_now()) == 1
    assert w.held_now() == []
    assert w.observed_graph()["edges"] == []
    w.check_acyclic()


def test_ab_ba_cycle_detected_naming_both_sites():
    w = _install_here()
    a = threading.Lock()
    b = threading.Lock()
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    with pytest.raises(LockOrderViolation) as ei:
        w.check_acyclic()
    msg = str(ei.value)
    # both acquisition paths named, with this file's sites and stacks
    assert msg.count("test_lock_witness.py") >= 4
    assert "acquisition stack" in msg


def test_three_lock_cycle_detected():
    w = _install_here()
    # three distinct construction SITES: the node identity is the ctor
    # site, so a comprehension would fold all three into one node
    locks = [threading.Lock(),
             threading.Lock(),
             threading.Lock()]
    for i in range(3):
        with locks[i]:
            with locks[(i + 1) % 3]:
                pass
    with pytest.raises(LockOrderViolation):
        w.check_acyclic()


def test_cross_thread_union_graph_catches_split_cycle():
    """Each thread's own order is locally consistent; the cycle only
    exists in the UNION graph — exactly the deadlock shape."""
    w = _install_here()
    a = threading.Lock()
    b = threading.Lock()

    def t1():
        with a:
            with b:
                pass

    def t2():
        with b:
            with a:
                pass

    th1 = threading.Thread(target=t1)
    th1.start()
    th1.join()
    th2 = threading.Thread(target=t2)
    th2.start()
    th2.join()
    with pytest.raises(LockOrderViolation):
        w.check_acyclic()


# -- unit: gating + identity --------------------------------------------------


def test_disabled_is_byte_identical_factories():
    """Without install, the factories are the stdlib originals; install
    patches, uninstall restores — and locks built while uninstalled are
    raw (no wrapper in the acquire path at all)."""
    orig_lock, orig_rlock = threading.Lock, threading.RLock
    assert witness.active() is None
    w = _install_here()
    assert getattr(threading.Lock, "__self__", None) is w
    witness.uninstall()
    assert threading.Lock is orig_lock
    assert threading.RLock is orig_rlock
    assert type(threading.Lock()) is type(orig_lock())


def test_site_filter_leaves_foreign_locks_raw():
    """Locks constructed outside the package root (stdlib: queue.Queue's
    mutex) stay raw — the witness never taxes or renames them."""
    import queue

    w = witness.install()   # real package root: tests/ is outside it
    q = queue.Queue()
    mine = threading.Lock()
    assert not isinstance(q.mutex, witness._WitnessedLock)
    assert not isinstance(mine, witness._WitnessedLock)
    assert w.observed_graph()["nodes"] == []


def test_env_gate():
    try:
        for v, want in (("", False), ("0", False), ("off", False),
                        ("1", True), ("true", True)):
            os.environ["PADDLE_TPU_LOCK_WITNESS"] = v
            assert witness.enabled_from_env() is want
    finally:
        # a mid-loop assertion must not leak the gate into the rest of
        # the session (it would silently witness every later chaos run)
        os.environ.pop("PADDLE_TPU_LOCK_WITNESS", None)


def test_nested_install_keeps_outer_witness_alive():
    """An inner install/uninstall pair (witnessed() inside an already-
    witnessed module) must not tear down the outer witness, and a
    nested install with a conflicting filter must raise instead of
    silently mis-attributing."""
    outer = _install_here()
    with witness.witnessed() as inner:
        assert inner is outer
    assert witness.active() is outer          # outer survives the pair
    lock = threading.Lock()
    assert isinstance(lock, witness._WitnessedLock)
    with pytest.raises(RuntimeError):
        witness.install(package_root="/somewhere/else")
    witness.uninstall()
    assert witness.active() is None


def test_overhead_bound():
    """The wrapper must stay cheap enough for witnessed chaos runs to
    fit the tier-1 margin: 20k uncontended acquire/release pairs well
    under a (very generous) wall bound."""
    _install_here()
    lock = threading.Lock()
    t0 = time.perf_counter()
    for _ in range(20000):
        with lock:
            pass
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"witnessed acquire overhead too high: {elapsed:.3f}s"


# -- observed-vs-static cross-check ------------------------------------------


def test_cross_check_flags_unmodeled_edge_and_lock():
    """The parser-gap canary mechanics: an observed edge the static
    JL009 graph does not model (here: a REVERSED ledger->metrics edge,
    and a lock constructed at an unmodeled site) must come back as
    gaps."""
    from paddle_tpu.analysis.core import Module, iter_python_files
    from paddle_tpu.analysis.threadgraph import Program

    pkg = os.path.join(os.path.dirname(TESTS_DIR), "paddle_tpu")
    mods = []
    for p in iter_python_files([pkg]):
        try:
            with open(p, encoding="utf-8") as f:
                mods.append(Module(p, f.read(),
                                   display_path=os.path.relpath(
                                       p, os.path.dirname(pkg))))
        except (OSError, SyntaxError, ValueError):
            continue
    prog = Program(mods)
    nodes = prog.lock_nodes()
    slo_site = nodes["SLOLedger._lock"]["sites"][0]
    met_site = nodes["ServingMetrics._families_lock"]["sites"][0]
    to_abs = lambda s: (os.path.join(os.path.dirname(pkg), s[0]), s[1])  # noqa: E731

    w = witness.Witness()
    # one cross_check call (it reparses the tree, ~3s) covering all
    # three behaviors: the modeled direction produces NO gap, the
    # reversed edge is an unmodeled-edge gap, and a construction site
    # the parser never saw is an unmodeled-lock gap
    w.nodes[to_abs(slo_site)] = "Lock"
    w.nodes[to_abs(met_site)] = "Lock"
    fake = (os.path.join(pkg, "serving", "engine.py"), 99999)
    w.nodes[fake] = "Lock"
    w.edges[(to_abs(slo_site), to_abs(met_site))] = witness._Edge(
        to_abs(slo_site), to_abs(met_site), ("x", 1), ("y", 2), "")
    w.edges[(to_abs(met_site), to_abs(slo_site))] = witness._Edge(
        to_abs(met_site), to_abs(slo_site), ("x", 1), ("y", 2), "")
    gaps = witness.cross_check(w)
    assert len(gaps) == 2, gaps
    assert any("unmodeled lock" in g and "engine.py:99999" in g
               for g in gaps)
    assert any("observed-but-unmodeled edge" in g
               and "ServingMetrics._families_lock -> SLOLedger._lock"
               in g for g in gaps)


# -- end-to-end: witnessed chaos serve ---------------------------------------


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    from paddle_tpu.models.gpt import GPT, GPTConfig

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, attn_impl="xla",
                    dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def test_witnessed_chaos_serve_acyclic_covered_and_token_identical(model):
    """THE acceptance path in one compact serve: poison isolation +
    watchdog-armed engine with SLO ledger, tracer, and a mid-serve
    scrape from the loop thread, all under the witness. The observed
    graph must be acyclic, non-trivial (the ledger->metrics edge fires),
    fully covered by the static JL009 model, and the tokens must match
    an unwitnessed reference serve."""
    from paddle_tpu.serving import AsyncLLMEngine, LLMEngine, faults
    from paddle_tpu.serving.faults import FaultPlan

    prompts = _prompts((5, 9, 13), seed=7)

    def build():
        return LLMEngine(model, block_size=8, max_batch=4, max_seq_len=64,
                         trace=True, slo=True)

    # unwitnessed reference first (fixture uninstalls between tests)
    ref = build().generate(prompts, max_new_tokens=6, temperature=0.0)

    w = witness.install()
    try:
        faults.install(FaultPlan([
            {"point": "step_raise", "request_id": "poison",
             "exc": "DeviceBoom"},
        ]))
        engine = build()

        async def main():
            fe = await AsyncLLMEngine(
                engine, max_waiting=8,
                watchdog_step_timeout_s=30.0).start()
            streams = []
            for i, p in enumerate(prompts):
                rid = "poison" if i == 1 else f"r{i}"
                streams.append(fe.submit(
                    p, max_new_tokens=6, temperature=0.0, request_id=rid,
                    tenant="t0"))
            # mid-serve scrape from the LOOP thread: trace export + SLO
            # rollup both take their locks concurrently with the engine
            await asyncio.sleep(0.02)
            engine.tracer.chrome_trace()
            engine.slo.rollup()
            results = await asyncio.wait_for(
                asyncio.gather(*(s.collect() for s in streams)), 30.0)
            await fe.shutdown(drain=True, timeout_s=10.0)
            return results

        results = asyncio.run(main())
    finally:
        plan = faults.active()
        if plan is not None:
            plan.release_hangs()
        faults.clear()
        witness.uninstall()

    toks, reasons = zip(*results)
    assert reasons[1] == "error"                  # poison isolated
    assert list(toks[0]) == ref[0]                # innocents identical to
    assert list(toks[2]) == ref[2]                # the unwitnessed serve
    w.check_acyclic()
    g = w.observed_graph()
    assert g["nodes"], "no paddle_tpu lock was witnessed"
    assert any(e["held_ctor"].endswith("slo.py:122") or
               "slo.py" in e["held_ctor"] and "metrics.py" in
               e["acquired_ctor"] for e in g["edges"]), (
        "expected the SLOLedger->ServingMetrics edge in the observed "
        "graph", g["edges"])
    gaps = witness.cross_check(w)
    assert gaps == [], "\n".join(gaps)
