"""Fused chunked linear+CE head (ops/fused_ce.py) — exactness vs the unfused
logsumexp CE, for the op and for the GPT labels= forward path it powers.

Reference parity: softmax_with_cross_entropy fusion
(/root/reference/paddle/phi/kernels/gpu/cross_entropy_kernel.cu), extended
TPU-side to fold the tied unembedding matmul into the chunk scan.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.ops.fused_ce import fused_linear_cross_entropy


def _ref(x, w, labels):
    lg = jax.lax.dot_general(
        x, w, (((2,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    lse = jax.scipy.special.logsumexp(lg, -1)
    picked = jnp.take_along_axis(lg, labels[..., None].astype(jnp.int32), -1)[..., 0]
    return jnp.mean(lse - picked)


@pytest.mark.parametrize("n_chunks", [1, 2, 4, None, 0])
def test_fused_ce_matches_unfused(n_chunks):
    rs = np.random.RandomState(0)
    B, S, H, V = 2, 32, 16, 64
    x = jnp.asarray(rs.randn(B, S, H).astype(np.float32))
    w = jnp.asarray(rs.randn(V, H).astype(np.float32) * 0.1)
    labels = jnp.asarray(rs.randint(0, V, (B, S)))

    v, g = jax.value_and_grad(
        lambda x, w: fused_linear_cross_entropy(x, w, labels, n_chunks), (0, 1)
    )(x, w)
    rv, rg = jax.value_and_grad(lambda x, w: _ref(x, w, labels), (0, 1))(x, w)
    assert np.allclose(float(v), float(rv), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(rg[0]), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(rg[1]), rtol=1e-5, atol=1e-6)


def test_fused_ce_odd_seq_under_jit():
    rs = np.random.RandomState(1)
    B, S, H, V = 3, 30, 8, 32  # S=30: chunk fit must back off to a divisor
    x = jnp.asarray(rs.randn(B, S, H).astype(np.float32))
    w = jnp.asarray(rs.randn(V, H).astype(np.float32) * 0.1)
    labels = jnp.asarray(rs.randint(0, V, (B, S)))
    v = jax.jit(lambda x, w: fused_linear_cross_entropy(x, w, labels, 4))(x, w)
    assert np.allclose(float(v), float(_ref(x, w, labels)), rtol=1e-6)


def test_gpt_labels_path_matches_logits_path():
    """GPT.forward(ids, labels=) (fused head, the bench train path) must give
    the same loss AND parameter grads as gpt_loss_fn over the logits path —
    including the weight-tied wte grad, which gets contributions from both
    the embedding lookup and the unembed matmul."""
    from paddle_tpu.core.functional import functional_call, state_dict_arrays
    from paddle_tpu.models.gpt import gpt_tiny, gpt_loss_fn

    paddle.seed(0)
    m = gpt_tiny()
    params, buffers = state_dict_arrays(m)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 1024, (2, 64)).astype(np.int32))
    labels = jnp.asarray(rs.randint(0, 1024, (2, 64)).astype(np.int32))

    def loss_fused(p):
        out, _ = functional_call(
            m, p, buffers, args=(ids,), kwargs={"labels": labels}, training=False
        )
        return out

    def loss_ref(p):
        out, _ = functional_call(m, p, buffers, args=(ids,), training=False)
        return gpt_loss_fn(out, labels)

    vf, gf = jax.value_and_grad(loss_fused)(params)
    vr, gr = jax.value_and_grad(loss_ref)(params)
    assert np.allclose(float(vf), float(vr), rtol=1e-5)
    for k in gf:
        np.testing.assert_allclose(
            np.asarray(gf[k]), np.asarray(gr[k]), rtol=1e-4, atol=1e-5, err_msg=k
        )


def test_gpt_labels_path_eager_backward():
    from paddle_tpu.models.gpt import gpt_tiny

    paddle.seed(0)
    m = gpt_tiny()
    opt = paddle.optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
    rs = np.random.RandomState(0)
    ids = paddle.to_tensor(rs.randint(0, 1024, (4, 64)))
    labels = paddle.to_tensor(rs.randint(0, 1024, (4, 64)))
    losses = []
    for _ in range(4):
        loss = m(ids, labels=labels)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0], losses
