"""Tensor-parallel serving (serving/sharded.py) on the 8-fake-device CPU
mesh: the acceptance bar is TOKEN parity — a tp-sharded engine serving a
mixed wave (chunked prefill + decode + speculative drafts + prefix-cache
hits) emits greedy output token-for-token identical to the single-chip
engine, still compiles at most one program per ragged width bucket
(`expected_program_count`) with 0 steady-state retraces,
and keeps every host-side invariant (refcounts drain, pool returns to
idle). Always-on: the tp=2 smoke plus unit/capacity/topology-surface
checks; the tp=4/8 sweep, preemption interleaving, and the shard_map'd
Pallas-interpret kernel path are ``-m slow``.
"""
import asyncio
import json

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import (
    EngineSupervisor,
    LLMEngine,
    ServingMesh,
    ServingServer,
    as_serving_mesh,
    build_serving_mesh,
    faults,
    kv_capacity_blocks,
    serving_param_specs,
)
from paddle_tpu.serving.faults import FaultPlan
from paddle_tpu.serving.scheduler import Request


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=96, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _wave_prompts(seed=0):
    """The acceptance-criterion mixed wave: two prompts sharing a cached
    prefix, one prompt longer than the prefill chunk, one with a
    repetitive suffix the n-gram drafter hits."""
    rs = np.random.RandomState(seed)
    shared = rs.randint(0, 128, (24,)).tolist()
    motif = [7, 11, 13]
    return shared, [
        shared + rs.randint(0, 128, (4,)).tolist(),
        shared + rs.randint(0, 128, (6,)).tolist(),
        rs.randint(0, 128, (40,)).tolist(),             # > prefill_chunk
        rs.randint(0, 128, (5,)).tolist() + motif * 4,  # drafter fodder
    ]


def _serve_wave(model, mesh, **kw):
    """Warm the prefix cache with the shared prefix, then serve the wave
    with speculative decoding on; returns (engine, outputs)."""
    shared, prompts = _wave_prompts()
    eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8, mesh=mesh, spec_decoding=True,
                    num_spec_tokens=3, **kw)
    eng.generate([shared], max_new_tokens=2, temperature=0.0)
    outs = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
    return eng, outs


@pytest.fixture(scope="module")
def ref_wave(model):
    """Single-chip reference serve of the mixed wave (the parity anchor
    for every sharded run in this file). mesh=1, not None: this fixture
    is module-scoped, so it builds BEFORE the function-scoped _no_env_tp
    guard — only the explicit single-chip request ignores PADDLE_TPU_TP
    regardless of fixture ordering."""
    eng, outs = _serve_wave(model, mesh=1)
    return eng, outs


def _idle(engine):
    assert engine.pool._refcount == {}
    return engine.pool.num_free == engine.pool.num_blocks - 1


@pytest.fixture(autouse=True)
def _no_env_tp(monkeypatch):
    """A PADDLE_TPU_TP left in the developer's env must not shard this
    file's single-chip reference engines and make parity vacuous."""
    monkeypatch.delenv("PADDLE_TPU_TP", raising=False)


# ---------------------------------------------------------------------------
# units: mesh construction, param specs, capacity formula
# ---------------------------------------------------------------------------

def test_build_serving_mesh_validation():
    import jax

    with pytest.raises(ValueError, match="tp_degree >= 2"):
        build_serving_mesh(1)
    with pytest.raises(ValueError, match="devices"):
        build_serving_mesh(4096)
    sm = build_serving_mesh(2)
    assert sm.tp_degree == 2 and sm.device_count == 2
    assert sm.backend == jax.devices()[0].platform
    # coercions: int, Mesh, ServingMesh, None
    assert as_serving_mesh(None) is None
    assert as_serving_mesh(sm) is sm
    assert as_serving_mesh(2).tp_degree == 2
    assert as_serving_mesh(sm.mesh).tp_degree == 2
    from jax.sharding import Mesh

    # a degree-1 mesh is an explicit single-chip request in every form,
    # not a sharded engine that disabled donation for nothing
    one = Mesh(np.asarray(jax.devices()[:1]), ("tp",))
    assert as_serving_mesh(one) is None
    assert as_serving_mesh(ServingMesh(one)) is None
    with pytest.raises(ValueError, match="'tp' axis"):
        as_serving_mesh(Mesh(np.asarray(jax.devices()[:2]), ("dp",)))


def test_serving_param_specs_layout(model):
    """The documented tp layout: attention heads / FFN columns / vocab
    rows on 'tp' (the model's own mp sharding_axes renamed), norms and
    position embeddings replicated."""
    from jax.sharding import PartitionSpec as P

    sm = build_serving_mesh(2)
    specs = serving_param_specs(model, sm)
    assert specs["wte.weight"] == P("tp", None)
    assert specs["blocks.0.attn.qkv.weight"] == P(None, "tp")
    assert specs["blocks.0.attn.qkv.bias"] == P("tp")
    assert specs["blocks.0.attn.proj.weight"] == P("tp", None)
    assert specs["blocks.0.fc1.weight"] == P(None, "tp")
    assert specs["blocks.0.fc2.weight"] == P("tp", None)
    assert specs["blocks.0.ln1.weight"] == P()
    assert specs["wpe.weight"] == P()
    # RowParallel bias is the post-psum add — replicated
    assert specs["blocks.0.attn.proj.bias"] == P()


def test_validate_model_divisibility(model):
    # heads=4: tp=8 cannot shard them — one loud error at construction
    with pytest.raises(ValueError, match="num_heads"):
        LLMEngine(model, mesh=8)


def test_kv_capacity_blocks_per_shard():
    """Same per-chip byte budget buys tp x the blocks of the naive
    logical-head-count formula: under tp each shard stores heads/tp per
    block (the satellite fix — admission bounds must speak per-shard)."""
    kw = dict(kv_bytes=1 << 20, num_layers=2, num_heads=8, block_size=16,
              head_dim=32, dtype_itemsize=4)
    one = kv_capacity_blocks(**kw, tp_degree=1)
    four = kv_capacity_blocks(**kw, tp_degree=4)
    assert four == 4 * one
    assert one == (1 << 20) // (2 * 2 * 8 * 16 * 32 * 4)


def test_kv_hbm_bytes_admission_per_shard(model):
    """The same per-chip byte budget serves at tp=4 what tp=1 cannot
    hold: the single-chip engine fails LOUDLY at construction (budget
    named, not per-request 4xxes), the tp=4 engine gets 4x the blocks
    and admits a max-length request; num_blocks + kv_hbm_bytes together
    is a loud config error."""
    per_block = 2 * model.cfg.num_layers * model.cfg.num_heads * 8 * 16 * 4
    # a max-len (96-token) sequence worst-cases at blocks_for(95) = 12
    # blocks; 12-block budget is one short of 12 + the null block
    budget = 12 * per_block
    with pytest.raises(ValueError, match="kv_hbm_bytes"):
        LLMEngine(model, block_size=8, max_batch=2, max_seq_len=96,
                  kv_hbm_bytes=budget)
    # the gate mirrors validate EXACTLY: 13 blocks (12 usable) admits a
    # max-length request, so construction must accept it too
    e13 = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=96,
                    kv_hbm_bytes=13 * per_block)
    assert e13.validate(Request([1] * 46, max_new_tokens=50)) == 12
    e4 = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=96,
                   mesh=4, kv_hbm_bytes=budget)
    assert e4.pool.num_blocks == 4 * 12       # same budget, 4x the blocks
    long_req = Request([1] * 40, max_new_tokens=50)       # 90 tokens
    assert e4.validate(long_req) == e4.pool.blocks_for(89)
    with pytest.raises(ValueError, match="not both"):
        LLMEngine(model, block_size=8, num_blocks=64,
                  kv_hbm_bytes=budget)


def test_explicit_tp1_beats_env(model, monkeypatch):
    """mesh=1 (and --tp-degree 1) is an EXPLICIT single-chip request: it
    must win over a PADDLE_TPU_TP env default; the env applies only when
    mesh is unset."""
    monkeypatch.setenv("PADDLE_TPU_TP", "2")
    assert LLMEngine(model, block_size=8, mesh=1)._smesh is None
    eng = LLMEngine(model, block_size=8)
    assert eng._smesh is not None and eng._smesh.tp_degree == 2
    assert as_serving_mesh(1) is None


# ---------------------------------------------------------------------------
# the acceptance test: tp=2 mixed-wave token parity
# ---------------------------------------------------------------------------

def test_tp2_mixed_wave_token_parity(model, ref_wave):
    """tp=2 serve of the full mixed wave (prefill chunks + decode + spec
    drafts + prefix-cache hits) is greedy token-identical to single-chip,
    compiles at most one mesh-aware program per ragged width bucket
    (`expected_program_count`, the one-place program contract) with 0
    steady-state retraces, and drains the pool to idle."""
    ref_eng, ref_outs = ref_wave
    eng, outs = _serve_wave(model, mesh=2)
    assert outs == ref_outs
    # program-count contract + recompile sentinel: the table is keyed by
    # (batch, width) only, never outgrows the bucket set, and every
    # compiled program traced exactly once
    assert set(eng._step_fns) <= {(eng.max_batch, w)
                                  for w in eng.width_buckets}
    assert len(eng._step_fns) <= eng.expected_program_count()
    assert (int(eng.metrics.counters["jit_traces"])
            == len(eng._step_fns))
    assert eng.metrics.gauges.get("jit_retraces", 0) == 0
    # the wave really exercised cache + spec on BOTH engines identically
    for m in (eng.metrics, ref_eng.metrics):
        assert m.counters.get("prefix_cache_hit_tokens", 0) > 0
        assert m.counters.get("spec_accepted_tokens", 0) > 0
    assert (eng.metrics.counters["prefix_cache_hit_tokens"]
            == ref_eng.metrics.counters["prefix_cache_hit_tokens"])
    assert (eng.metrics.counters["spec_accepted_tokens"]
            == ref_eng.metrics.counters["spec_accepted_tokens"])
    assert _idle(eng)


def test_tp2_temperature_sampling_bit_identical(model):
    """The PR 10 known limit, closed: with sampling compiled into the
    step on rows pinned REPLICATED at the program boundary, a tp=2
    temperature>0 serve draws the same tokens as single-chip from the
    same PRNG key — bit-identical, not merely same-distribution. The
    per-step key sequence is host-side and scheduling is deterministic,
    so every categorical/rejection draw sees the same (replicated) rows
    and the same key on both engines."""
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, 128, (n,)).tolist() for n in (6, 11, 17)]
    kw = dict(block_size=8, max_batch=3, max_seq_len=96, prefill_chunk=8,
              seed=123)
    ref = LLMEngine(model, mesh=1, **kw)
    want = ref.generate(prompts, max_new_tokens=12, temperature=0.9,
                        top_k=20, top_p=0.95)
    eng = LLMEngine(model, mesh=2, **kw)
    got = eng.generate(prompts, max_new_tokens=12, temperature=0.9,
                       top_k=20, top_p=0.95)
    assert got == want
    assert _idle(eng) and _idle(ref)


def test_tp2_arena_and_param_placement(model, ref_wave):
    """The sharded engine's device state carries the documented layout:
    arenas head-sharded over tp, column/row-parallel weights on their
    axes (checked on the placed jax.Arrays, not just the spec table)."""
    from jax.sharding import PartitionSpec as P

    eng, _ = _serve_wave(model, mesh=2)
    assert eng.pool.k.sharding.spec == P(None, "tp")
    assert eng.pool.v.sharding.spec == P(None, "tp")
    assert eng._params["blocks.0.attn.qkv.weight"].sharding.spec == P(None, "tp")
    assert eng._params["blocks.1.fc2.weight"].sharding.spec == P("tp", None)
    # per-shard bytes: each of the 2 chips holds half the arena
    shard = next(iter(eng.pool.k.addressable_shards))
    assert shard.data.shape[1] == model.cfg.num_heads // 2
    assert eng.mesh_info() == {"tp_degree": 2, "device_count": 2,
                               "backend": "cpu", "kv_dtype": "float32"}


# ---------------------------------------------------------------------------
# supervision / fault injection keep working against the sharded engine
# ---------------------------------------------------------------------------

@pytest.fixture(autouse=True)
def _disarm():
    yield
    plan = faults.active()
    if plan is not None:
        plan.release_hangs()
    faults.clear()


def test_supervisor_poison_isolation_tp2(model, ref_wave):
    """PR 9's bisection isolation, unchanged against a tp=2 engine: a
    step_raise pinned to one request aborts exactly that request; every
    other request's tokens match the no-fault sharded (== single-chip)
    reference; pool drains to idle."""
    _, ref_outs = ref_wave
    _, prompts = _wave_prompts()
    by_ref = {}
    for i, o in enumerate(ref_outs):
        by_ref[f"r{i}"] = o
    shared, _ = _wave_prompts()
    eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8, mesh=2, spec_decoding=True,
                    num_spec_tokens=3)
    eng.generate([shared], max_new_tokens=2, temperature=0.0)
    for i, p in enumerate(prompts):
        eng.add_request(p, max_new_tokens=10, request_id=f"r{i}")
    faults.install(FaultPlan([
        {"point": "step_raise", "request_id": "r2", "exc": "ShardBoom"},
    ]))
    sup = EngineSupervisor(eng)
    outs, failures = [], []
    steps = 0
    while eng.has_unfinished():
        o, f = sup.step()
        outs += o
        failures += f
        steps += 1
        assert steps < 300, "supervised sharded serve did not converge"
    assert [rid for rid, _ in failures] == ["r2"]
    assert "ShardBoom" in failures[0][1]
    got = {}
    for o in outs:
        got.setdefault(o.request_id, []).append(o.token)
    for rid in ("r0", "r1", "r3"):
        assert got[rid] == by_ref[rid]
    assert _idle(eng)


# ---------------------------------------------------------------------------
# /healthz and /metrics expose the mesh topology, and they agree
# ---------------------------------------------------------------------------

def _prom_gauge(text, name):
    for line in text.splitlines():
        if line.startswith(f"paddle_tpu_serving_{name} "):
            return float(line.split()[-1])
    raise AssertionError(f"gauge {name} not in /metrics")


def test_mesh_gauges_healthz_metrics_agree(model):
    """mesh_tp_degree / mesh_device_count gauges and the mesh_info
    backend label on /metrics must agree with /healthz's mesh object —
    a sharded replica's shape is visible on both surfaces."""
    async def main():
        engine = LLMEngine(model, block_size=8, max_batch=2,
                           max_seq_len=96, mesh=2)
        server = ServingServer(engine, host="127.0.0.1", port=0)
        await server.start()
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       server.port)
        writer.write(b"GET /healthz HTTP/1.1\r\nHost: t\r\n\r\n")
        await writer.drain()
        raw = await reader.read()
        writer.close()
        health = json.loads(raw.partition(b"\r\n\r\n")[2])
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       server.port)
        writer.write(b"GET /metrics HTTP/1.1\r\nHost: t\r\n\r\n")
        await writer.drain()
        raw = await reader.read()
        writer.close()
        metrics_text = raw.partition(b"\r\n\r\n")[2].decode()
        await server.shutdown(drain=True)
        return health, metrics_text

    health, text = asyncio.run(main())
    mesh = health["mesh"]
    assert mesh["tp_degree"] == 2 and mesh["device_count"] == 2
    assert mesh["backend"] == "cpu"
    assert _prom_gauge(text, "mesh_tp_degree") == mesh["tp_degree"]
    assert _prom_gauge(text, "mesh_device_count") == mesh["device_count"]
    assert (f'paddle_tpu_serving_mesh_info{{backend="{mesh["backend"]}"}} 1'
            in text)


def test_single_chip_reports_degree_one(model, ref_wave):
    ref_eng, _ = ref_wave
    info = ref_eng.mesh_info()
    assert info["tp_degree"] == 1 and info["device_count"] == 1
    assert ref_eng.metrics.gauges["mesh_tp_degree"] == 1


# ---------------------------------------------------------------------------
# slow: tp=4/8 sweep, preemption interleaving, shard_map'd Pallas kernel
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_tp4_tp8_parity_sweep():
    """Wider meshes: an 8-head model served at tp=4 and tp=8 stays token-
    identical to its single-chip serve."""
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=64, num_layers=2,
                    num_heads=8, max_seq_len=64, attn_impl="xla",
                    dropout=0.0)
    m = GPT(cfg)
    m.eval()
    rs = np.random.RandomState(3)
    prompts = [rs.randint(0, 128, (n,)).tolist() for n in (5, 17, 9)]
    ref = LLMEngine(m, block_size=8, max_batch=4, max_seq_len=64,
                    prefill_chunk=8)
    ref_outs = ref.generate(prompts, max_new_tokens=8, temperature=0.0)
    for tp in (4, 8):
        eng = LLMEngine(m, block_size=8, max_batch=4, max_seq_len=64,
                        prefill_chunk=8, mesh=tp)
        outs = eng.generate(prompts, max_new_tokens=8, temperature=0.0)
        assert outs == ref_outs, f"tp={tp} diverged"
        assert eng.mesh_info()["tp_degree"] == tp
        assert _idle(eng)


@pytest.mark.slow
def test_tp2_preemption_interleave_parity(model):
    """A pool small enough to force preemption-by-recompute, with prefix
    caching and spec decoding live: any interleaving of admissions,
    preemptions, cache hits, and verify steps stays token-identical to
    the single-chip engine under the same pressure, and refcounts drain."""
    shared, prompts = _wave_prompts(seed=5)
    kw = dict(block_size=8, max_batch=3, max_seq_len=96, prefill_chunk=8,
              num_blocks=30, spec_decoding=True, num_spec_tokens=3)
    ref = LLMEngine(model, **kw)
    ref.generate([shared], max_new_tokens=2, temperature=0.0)
    ref_outs = ref.generate(prompts, max_new_tokens=10, temperature=0.0)
    eng = LLMEngine(model, mesh=2, **kw)
    eng.generate([shared], max_new_tokens=2, temperature=0.0)
    outs = eng.generate(prompts, max_new_tokens=10, temperature=0.0)
    assert outs == ref_outs
    assert (eng.metrics.counters.get("preemptions", 0)
            == ref.metrics.counters.get("preemptions", 0))
    assert _idle(eng) and _idle(ref)


@pytest.mark.slow
def test_shard_map_pallas_interpret_parity(model, monkeypatch):
    """The per-shard Pallas dispatch (shard_map over the head axis):
    forced interpret mode exercises the kernel path on CPU; a tp=2 serve
    through it matches the XLA-fallback single-chip serve token-for-
    token. (On a real TPU the same dispatch runs the compiled kernel.)"""
    _, prompts = _wave_prompts(seed=9)
    ref = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8)
    ref_outs = ref.generate(prompts[:2], max_new_tokens=6, temperature=0.0)
    monkeypatch.setenv("PADDLE_TPU_FORCE_PALLAS_INTERPRET", "1")
    eng = LLMEngine(model, block_size=8, max_batch=4, max_seq_len=96,
                    prefill_chunk=8, mesh=2)
    outs = eng.generate(prompts[:2], max_new_tokens=6, temperature=0.0)
    assert outs == ref_outs
    assert _idle(eng)
