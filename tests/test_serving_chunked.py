"""Chunked-prefill scheduler/engine edge cases.

Satellites from the ragged-paged-attention issue: admission exactly at the
token budget lives in test_serving_engine.py; here: preemption of a
half-prefilled / half-decoded request (recompute must replay already-emitted
chunks WITHOUT re-emitting their tokens), zero-waiting-queue mixed steps,
and chunk accounting across replays.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models.gpt import GPT, GPTConfig
from paddle_tpu.serving import BlockPool, LLMEngine
from paddle_tpu.serving.scheduler import Request, Scheduler


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2, num_heads=2,
                    max_seq_len=64, attn_impl="xla", dropout=0.0)
    m = GPT(cfg)
    m.eval()
    return m


def _prompts(lengths, seed=0):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, 128, (n,)).tolist() for n in lengths]


def _reference(model, prompt, n):
    ids = paddle.to_tensor(np.asarray([prompt], np.int64))
    out = model.generate(ids, max_new_tokens=n, temperature=0.0)
    return out.numpy()[0, len(prompt):].tolist()


def test_replay_of_preempted_request_does_not_reemit():
    """A preempted request with emitted tokens replays prompt+outputs in
    chunks: every replay row is emit=False until the chunk that reaches the
    last pending position — which samples the NEXT token, not a repeat."""
    pool = BlockPool(num_blocks=64, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)
    sched = Scheduler(pool, max_batch=2, token_budget=4, prefill_chunk=4)
    req = Request([1] * 9, max_new_tokens=8)
    sched.add(req)
    # prefill 9 tokens in chunks of 4: emit only on the last
    emits = []
    for _ in range(3):
        (row,) = sched.schedule()
        emits.append(row.emit)
        req.num_cached += row.count
    assert emits == [False, False, True]
    req.output_ids.extend([5, 6])  # two tokens emitted (engine would do it)
    req.num_cached = req.num_tokens - 1  # decode steady state
    sched._preempt(req)
    assert req.num_cached == 0 and not req.blocks
    # replay: 9 + 2 = 11 pending tokens -> chunks 4, 4, 3; only the chunk
    # reaching position 10 (the last emitted token, fed back in) emits — and
    # what it samples is output token #3, never a re-emission of 5 or 6
    emits, counts = [], []
    while req.num_pending > 1:
        (row,) = sched.schedule()
        assert row.start == req.num_cached
        emits.append(row.emit)
        counts.append(row.count)
        req.num_cached += row.count
    assert counts == [4, 4, 3]
    assert emits == [False, False, True]
    assert req.preemptions == 1 and req.output_ids == [5, 6]


def test_engine_preempts_mid_serve_token_streams_exact(model):
    """Step-by-step streams under preemption pressure: every request's
    emitted token sequence equals its final output_ids equals the
    sequential reference — replays never duplicate or drop a token."""
    prompts = _prompts((6, 7, 9), seed=1)
    engine = LLMEngine(model, block_size=4, num_blocks=10, max_batch=4,
                       max_seq_len=64, prefill_chunk=4)
    rids = [engine.add_request(p, max_new_tokens=10, temperature=0.0)
            for p in prompts]
    streams = {rid: [] for rid in rids}
    while engine.has_unfinished():
        for out in engine.step():
            streams[out.request_id].append(out.token)
    assert engine.metrics.counters["preemptions"] >= 1
    for rid, p in zip(rids, prompts):
        ref = _reference(model, p, 10)
        assert streams[rid] == ref
        assert engine.get_request(rid).output_ids == ref
    assert engine.pool.num_free == engine.pool.num_blocks - 1


def test_zero_waiting_queue_mixed_steps(model):
    """With the waiting queue empty, a long prompt keeps chunking WHILE the
    other lane decodes — mixed steps with num_waiting == 0, and the decode
    lane emits a token in every one of them."""
    p_short, p_long = _prompts((4, 40), seed=2)
    engine = LLMEngine(model, block_size=8, max_batch=2, max_seq_len=64,
                       prefill_chunk=8)
    r1 = engine.add_request(p_short, max_new_tokens=12, temperature=0.0)
    engine.step()  # admit + prefill r1 (emits its first token)
    r2 = engine.add_request(p_long, max_new_tokens=4, temperature=0.0)
    mixed_with_empty_queue = 0
    decode_progress = []
    while engine.get_request(r2).num_pending > 1 or not engine.get_request(
            r2).output_ids:
        n1 = len(engine.get_request(r1).output_ids)
        engine.step()
        if (engine.metrics.gauges["num_waiting"] == 0
                and len(engine.get_request(r1).output_ids) == n1 + 1):
            mixed_with_empty_queue += 1
            decode_progress.append(True)
    # 40-token prompt at chunk 8 -> 5 chunk steps, all riding with r1's
    # decode rows after admission emptied the queue
    assert mixed_with_empty_queue >= 4
    while engine.has_unfinished():
        engine.step()
    assert engine.get_request(r1).output_ids == _reference(model, p_short, 12)
    assert engine.get_request(r2).output_ids == _reference(model, p_long, 4)


def test_preemption_priority_is_arrival_order_not_list_position():
    """A preempted-and-readmitted request sits at the END of the running
    list but keeps its arrival age: an arrival-younger sequence must defer
    rather than victimize it, while the arrival-oldest may still reclaim
    from the true youngest."""
    pool = BlockPool(num_blocks=5, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)  # 4 usable
    sched = Scheduler(pool, max_batch=3, token_budget=12, prefill_chunk=4)
    r1, r2, r3 = (Request([1] * 4, max_new_tokens=8) for _ in range(3))
    for r in (r1, r2, r3):
        sched.add(r)
    rows = sched.schedule()  # one block each, 1 free
    assert [w.req for w in rows] == [r1, r2, r3]
    for w in rows:
        w.req.num_cached += w.count
    # simulate r2 having been preempted + re-admitted: list-youngest now,
    # but still arrival-older than r3
    sched.running.remove(r2)
    sched.running.append(r2)
    # r3 wants 3 blocks: takes the free one, then the pool is dry — r2 (the
    # list-tail) is NOT fair game, and r3 has no arrival-younger victim
    assert sched._grow(r3, 3) is False
    assert r2.blocks and r2.preemptions == 0
    # the arrival-oldest r1 reclaims from the arrival-youngest holder (r3)
    assert sched._grow(r1, 3) is True
    assert r3.preemptions == 1 and r3.state == "waiting"
    assert r2.preemptions == 0


def test_scheduler_defers_younger_prefill_when_pool_dry():
    """FCFS block priority: when the pool is dry, a younger mid-prefill row
    defers (no self-thrash) while an older sequence keeps its blocks and
    advances."""
    pool = BlockPool(num_blocks=5, num_layers=1, block_size=4, num_heads=1,
                     head_dim=4)  # 4 usable blocks
    sched = Scheduler(pool, max_batch=2, token_budget=32, prefill_chunk=8)
    r1 = Request([1] * 12, max_new_tokens=8)   # 3 blocks at 12 tokens
    r2 = Request([1] * 8, max_new_tokens=8)
    sched.add(r1)
    sched.add(r2)
    rows = sched.schedule()  # r1 chunk 8 (2 blocks) + r2 chunk 8 (2 blocks)
    assert [(w.req, w.count) for w in rows] == [(r1, 8), (r2, 8)]
    for w in rows:
        w.req.num_cached += w.count
    # r1's last chunk needs a 3rd block; pool is dry -> r2 (younger, holds
    # blocks) is preempted, r1 proceeds, r2 replays later
    rows = sched.schedule()
    assert [(w.req, w.count, w.emit) for w in rows] == [(r1, 4, True)]
    assert r2.state == "waiting" and r2.num_cached == 0
    assert r2.preemptions == 1
