"""Worker for the 2-process RPC + PS test (run via subprocess).

Usage: python _rpc_worker.py <rank> <nranks> <port>
rank 0 hosts the PS tables; rank 1 drives pulls/pushes over RPC.
"""
import os
import sys

RANK = int(sys.argv[1])
NRANKS = int(sys.argv[2])
PORT = sys.argv[3]

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.ps import PSClient

rpc.init_rpc(
    f"worker{RANK}", rank=RANK, world_size=NRANKS,
    master_endpoint=f"127.0.0.1:{PORT}",
)
infos = rpc.get_all_worker_infos()
assert [w.name for w in infos] == [f"worker{r}" for r in range(NRANKS)], infos

# plain RPC: remote computation on the other worker
peer = f"worker{(RANK + 1) % NRANKS}"
out = rpc.rpc_sync(peer, pow, args=(2, 10))
assert out == 1024, out
fut = rpc.rpc_async(peer, sorted, args=([3, 1, 2],))
assert fut.result(timeout=30) == [1, 2, 3]

# remote errors propagate
try:
    rpc.rpc_sync(peer, int, args=("not-a-number",))
    raise AssertionError("remote exception did not propagate")
except ValueError:
    pass

# PS: rank 0 hosts, rank 1 is the trainer
if RANK == 1:
    client = PSClient(server="worker0")
    client.create_sparse_table("emb", dim=4, lr=0.5)
    ids = np.array([3, 7, 3])
    rows0 = client.pull_sparse("emb", ids)
    assert rows0.shape == (3, 4)
    np.testing.assert_array_equal(rows0[0], rows0[2])  # same id, same row
    # push a known gradient twice for id 3 (accumulated server-side)
    client.push_sparse("emb", np.array([3]), np.ones((1, 4), np.float32))
    rows1 = client.pull_sparse("emb", np.array([3]))
    np.testing.assert_allclose(rows1[0], rows0[0] - 0.5, atol=1e-6)

    client.create_dense_table("w", shape=(2, 2), lr=0.1,
                              init=np.ones((2, 2), np.float32))
    client.push_dense("w", np.full((2, 2), 2.0, np.float32))
    np.testing.assert_allclose(client.pull_dense("w"), 0.8)
    assert client.table_size("emb") == 2

# both sides must stay alive until all RPC traffic is done
import time

marker = os.environ["RPC_TEST_DIR"] + f"/done_{RANK}"
open(marker, "w").write("1")
deadline = time.time() + 60
while time.time() < deadline:
    if all(
        os.path.exists(os.environ["RPC_TEST_DIR"] + f"/done_{r}")
        for r in range(NRANKS)
    ):
        break
    time.sleep(0.05)
rpc.shutdown()
print(f"RPC_OK rank={RANK}", flush=True)
