"""auto_parallel front-end: ProcessMesh + shard_tensor + Engine (VERDICT
round-2 item 6; reference auto_parallel/engine.py:57, interface.py:28,
process_mesh.py:45). Runs on the forced 8-device CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed.auto_parallel import Engine, ProcessMesh, shard_tensor


def _mlp(seed=0):
    paddle.seed(seed)
    return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))


class TestProcessMesh:
    def test_shape_and_jax_mesh(self):
        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["dp", "mp"])
        assert pm.shape == [2, 4]
        assert pm.dim_names == ["dp", "mp"]
        assert pm.process_ids == list(range(8))
        assert dict(pm.jax_mesh.shape) == {"dp": 2, "mp": 4}

    def test_1d(self):
        pm = ProcessMesh(list(range(8)), dim_names=["x"])
        assert pm.ndim == 1 and pm.shape == [8]

    def test_bad_dim_names(self):
        with pytest.raises(ValueError, match="dim_names"):
            ProcessMesh([[0, 1], [2, 3]], dim_names=["x"])


class TestShardTensor:
    def test_annotates_and_places(self):
        pm = ProcessMesh([[0, 1], [2, 3]], dim_names=["dp", "mp"])
        w = paddle.Parameter(np.ones((8, 4), np.float32))
        shard_tensor(w, pm, [None, "mp"])
        assert w.sharding_axes == (None, "mp")
        shardings = {s for s in [w._array.sharding]}
        assert len(shardings) == 1  # placed with a concrete sharding

    def test_rejects_indivisible(self):
        pm = ProcessMesh(list(range(8)), dim_names=["mp"])
        w = paddle.Parameter(np.ones((6, 4), np.float32))
        with pytest.raises(ValueError, match="divisible"):
            shard_tensor(w, pm, ["mp", None])

    def test_rejects_unknown_dim(self):
        pm = ProcessMesh(list(range(8)), dim_names=["mp"])
        w = paddle.Parameter(np.ones((8, 4), np.float32))
        with pytest.raises(ValueError, match="unknown mesh dim"):
            shard_tensor(w, pm, ["pp", None])


class TestEngine:
    def _data(self, n=32):
        rs = np.random.RandomState(0)
        return (rs.rand(n, 8).astype(np.float32), rs.rand(n, 8).astype(np.float32))

    def _run_engine(self, annotate, steps=4, bs=8):
        net = _mlp()
        pm = ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]], dim_names=["dp", "mp"])
        if annotate:
            # Megatron column/row split of the two Linears over mp
            shard_tensor(net[0].weight, pm, [None, "mp"])
            shard_tensor(net[0].bias, pm, ["mp"])
            shard_tensor(net[2].weight, pm, ["mp", None])
        else:
            # mesh only; all params replicated
            shard_tensor(net[0].weight, pm, [None, None])
        opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
        eng = Engine(net, nn.MSELoss(), opt)
        xs, ys = self._data(steps * bs)

        class DS(paddle.io.Dataset):
            def __len__(self):
                return len(xs)

            def __getitem__(self, i):
                return xs[i], ys[i]

        hist = eng.fit(DS(), epochs=1, batch_size=bs)
        return hist["loss"], eng, net

    def _run_reference(self, steps=4, bs=8):
        """Hand-specced make_sharded_train_step trajectory (the VERDICT
        equivalence bar)."""
        from paddle_tpu.core import rng
        from paddle_tpu.core.functional import tree_to_tensors
        from paddle_tpu.parallel.spmd import make_sharded_train_step
        from jax.sharding import Mesh

        net = _mlp()
        net[0].weight.sharding_axes = (None, "mp")
        net[0].bias.sharding_axes = ("mp",)
        net[2].weight.sharding_axes = ("mp", None)
        opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
        mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "mp"))
        loss_layer = nn.MSELoss()

        def loss_fn(out_arrays, labels):
            from paddle_tpu.core import autograd
            from paddle_tpu.core.tensor import Tensor

            outs = tree_to_tensors(out_arrays)
            if not isinstance(outs, (list, tuple)):
                outs = [outs]
            with autograd.trace_mode():
                lv = loss_layer(*outs, Tensor._from_op(labels))
            return jnp.mean(lv._array)

        step = make_sharded_train_step(net, loss_fn, opt, mesh, batch_specs=(P("dp"), P("dp")))
        params, buffers, opt_state = step.init_state()
        xs, ys = self._data(steps * bs)
        losses = []
        for i in range(steps):
            xa, ya = step.shard_batch(xs[i * bs:(i + 1) * bs], ys[i * bs:(i + 1) * bs])
            lr = jnp.asarray(1e-2, jnp.float32)
            loss, params, buffers, opt_state = step(
                params, buffers, opt_state, lr, rng.next_key(), xa, ya
            )
            losses.append(float(np.asarray(loss)))
        return losses

    def test_engine_dp_mp_matches_hand_specced_step(self):
        ref = self._run_reference()
        eng_losses, _, _ = self._run_engine(annotate=True)
        assert len(eng_losses) == len(ref)
        np.testing.assert_allclose(eng_losses, ref, rtol=1e-5, atol=1e-7)

    def test_engine_trains_and_state_flows_back(self):
        losses, eng, net = self._run_engine(annotate=False, steps=6)
        assert losses[-1] < losses[0]  # learning
        # eager model got the trained weights back
        ev = eng.evaluate(None, steps=0)  # no data: just exercises the path
        w = np.asarray(net[0].weight.numpy())
        assert np.isfinite(w).all()
        # optimizer accumulators synced (Model.save-style flows work)
        sd = eng.optimizer.state_dict()
        assert any("moment1" in k for k in sd)

    def test_engine_save_load_roundtrip(self, tmp_path):
        losses, eng, net = self._run_engine(annotate=True, steps=2)
        path = str(tmp_path / "ap" / "ck")
        import os

        os.makedirs(os.path.dirname(path), exist_ok=True)
        eng.save(path)
        net2 = _mlp(seed=3)
        opt2 = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net2.parameters())
        eng2 = Engine(net2, nn.MSELoss(), opt2)
        eng2.load(path)
        for (k1, v1), (k2, v2) in zip(
            net.state_dict().items(), net2.state_dict().items()
        ):
            np.testing.assert_allclose(np.asarray(v1.numpy()), np.asarray(v2.numpy()))
