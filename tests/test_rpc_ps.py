"""RPC + PS-lite (VERDICT round-2 item 10; reference distributed/rpc/rpc.py
and ps/service/ps_client.h + the_one_ps.py)."""
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

from paddle_tpu.distributed.ps import DenseTable, PSClient, SparseTable


class TestTablesLocal:
    def test_dense_pull_push(self):
        t = DenseTable((2, 3), lr=0.1, init=np.ones((2, 3), np.float32))
        np.testing.assert_allclose(t.pull(), 1.0)
        t.push(np.full((2, 3), 2.0))
        np.testing.assert_allclose(t.pull(), 0.8)

    def test_sparse_lazy_rows_and_sgd(self):
        t = SparseTable(dim=4, lr=0.5, seed=0)
        rows = t.pull([5, 9, 5])
        assert rows.shape == (3, 4)
        np.testing.assert_array_equal(rows[0], rows[2])
        t.push([5], np.ones((1, 4), np.float32))
        np.testing.assert_allclose(t.pull([5])[0], rows[0] - 0.5, atol=1e-6)
        assert t.size() == 2

    def test_sparse_adagrad(self):
        t = SparseTable(dim=2, lr=1.0, optimizer="adagrad", seed=1)
        r0 = t.pull([0])[0].copy()
        t.push([0], np.full((1, 2), 2.0, np.float32))
        # adagrad step: lr * g / (sqrt(g^2) + eps) ~= 1.0
        np.testing.assert_allclose(t.pull([0])[0], r0 - 1.0, atol=1e-4)

    def test_save_load_roundtrip(self):
        t = SparseTable(dim=3, seed=2)
        t.pull([1, 2, 3])
        dump = t.save()
        t2 = SparseTable(dim=3, seed=99)
        t2.load(dump)
        np.testing.assert_array_equal(t.pull([2]), t2.pull([2]))

    def test_ps_client_local_mode(self):
        c = PSClient(server=None)
        c.create_sparse_table("local_emb", dim=2, lr=0.1)
        rows = c.pull_sparse("local_emb", np.array([1, 2]))
        assert rows.shape == (2, 2)
        c.push_sparse("local_emb", np.array([1]), np.ones((1, 2), np.float32))
        assert c.table_size("local_emb") == 2


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def test_two_process_rpc_and_ps(tmp_path):
    """Real 2-process RPC: rendezvous, remote calls, error propagation, and
    a PS server/trainer split (the reference's multi-process test pattern,
    test_dist_base.py)."""
    port = _free_port()
    env = dict(os.environ)
    env["RPC_TEST_DIR"] = str(tmp_path)
    workers = []
    here = os.path.dirname(os.path.abspath(__file__))
    for rank in range(2):
        workers.append(
            subprocess.Popen(
                [sys.executable, os.path.join(here, "_rpc_worker.py"),
                 str(rank), "2", str(port)],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True, env=env,
            )
        )
    outs = []
    for w in workers:
        try:
            out, _ = w.communicate(timeout=180)
        except subprocess.TimeoutExpired:
            w.kill()
            out, _ = w.communicate()
        outs.append(out)
    for rank, (w, out) in enumerate(zip(workers, outs)):
        assert w.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RPC_OK rank={rank}" in out, out


def test_sparse_table_capacity_and_shrink():
    """Eviction/growth policy (r3 verdict missing #8 note): LRU capacity cap
    + reference-style Shrink by access count."""
    import numpy as np

    from paddle_tpu.distributed.ps import SparseTable

    t = SparseTable(dim=4, lr=0.1, max_rows=4, seed=0)
    t.pull([0, 1, 2, 3])
    assert t.size() == 4 and t.evictions == 0
    t.pull([0])           # 0 becomes most-recent
    t.pull([4, 5])        # evicts LRU rows 1, 2
    assert t.size() == 4 and t.evictions == 2
    assert 0 in t.rows and 1 not in t.rows and 2 not in t.rows

    # evicted id re-initializes (fresh row), survivors keep training state
    r0_before = t.rows[0].copy()
    t.push([0], np.ones((1, 4), np.float32))
    assert not np.allclose(t.rows[0], r0_before)

    # shrink drops cold rows only
    t2 = SparseTable(dim=4)
    t2.pull([10, 11, 12])
    t2.pull([10, 10])     # 10 is hot
    dropped = t2.shrink(threshold=2)
    assert dropped == 2 and t2.size() == 1 and 10 in t2.rows
    # access counters reset after shrink
    assert t2.shrink(threshold=1) == 1  # 10 now cold again


def test_ps_runtime_deployment():
    """TheOnePSRuntime shape (reference the_one_ps.py:1031): a PSERVER
    process hosts tables, a TRAINER process auto-creates them from a model,
    trains through distributed_lookup_table (backward pushes row grads),
    and stop_worker shuts the server down."""
    import subprocess
    import sys

    port = _free_port()
    env = dict(os.environ)
    env["PADDLE_TPU_PLATFORM"] = "cpu"
    script = os.path.join(os.path.dirname(__file__), "_ps_runtime_worker.py")
    procs = [
        subprocess.Popen(
            [sys.executable, script, role, str(port)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for role in ("PSERVER", "TRAINER")
    ]
    try:
        # TRAINER first: if it dies before stop_worker, the server would
        # block forever — failing fast here surfaces the real error
        trainer_out, _ = procs[1].communicate(timeout=240)
        assert procs[1].returncode == 0, trainer_out[-2000:]
        server_out, _ = procs[0].communicate(timeout=60)
        assert procs[0].returncode == 0, server_out[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    assert "SERVER DONE" in server_out, server_out[-500:]
    assert "TRAINER DONE" in trainer_out, trainer_out[-500:]
