"""Sharded distributed checkpoint with re-shard on load (VERDICT round-2
item 3; reference incubate/distributed/utils/io/dist_save.py,
auto_parallel/dist_saver.py). 8-device CPU mesh."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.core import rng
from paddle_tpu.distributed.checkpoint import (
    load_sharded_model,
    load_state,
    save_sharded_model,
    save_state,
)
from paddle_tpu.parallel.spmd import make_sharded_train_step


def _mesh(**axes):
    names = tuple(axes)
    sizes = tuple(axes[n] for n in names)
    devs = np.asarray(jax.devices()[: int(np.prod(sizes))]).reshape(sizes)
    return Mesh(devs, names)


def test_save_load_reshard_values():
    """Arrays saved sharded over one mesh reassemble exactly, and re-shard
    onto a different mesh shape on load."""
    m1 = _mesh(dp=2, mp=4)
    rs = np.random.RandomState(0)
    a = rs.rand(8, 16).astype(np.float32)
    b = rs.rand(12,).astype(np.float32)
    state = {
        "w": jax.device_put(a, NamedSharding(m1, P("dp", "mp"))),
        "nested": {"v": jax.device_put(b, NamedSharding(m1, P(None)))},
    }
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        save_state(state, d)
        # plain host load
        back = load_state(d)
        np.testing.assert_array_equal(back["w"], a)
        np.testing.assert_array_equal(back["nested"]["v"], b)
        # re-shard onto a DIFFERENT mesh shape
        m2 = _mesh(dp=8)
        back2 = load_state(d, shardings={"w": NamedSharding(m2, P("dp")),
                                         "nested/v": NamedSharding(m2, P())})
        np.testing.assert_array_equal(np.asarray(back2["w"]), a)
        assert back2["w"].sharding.spec == P("dp")


def test_missing_shard_file_is_loud():
    import os
    import tempfile

    m1 = _mesh(dp=2, mp=4)
    a = np.arange(32, dtype=np.float32).reshape(8, 4)
    state = {"w": jax.device_put(a, NamedSharding(m1, P("dp")))}
    with tempfile.TemporaryDirectory() as d:
        save_state(state, d)
        # corrupt: rewrite npz without one shard key
        import json

        with open(os.path.join(d, "index.json")) as f:
            idx = json.load(f)
        victim = idx["arrays"]["w"]["shards"][0]
        data = dict(np.load(os.path.join(d, victim["file"])))
        del data[victim["key"]]
        np.savez(os.path.join(d, victim["file"]), **data)
        with pytest.raises(KeyError):
            load_state(d)


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(8, 32)
        self.fc2 = nn.Linear(32, 8)

    def forward(self, x):
        return self.fc2(nn.functional.relu(self.fc1(x)))


def _loss_fn(out_arrays, labels):
    from paddle_tpu.core import autograd
    from paddle_tpu.core.functional import tree_to_tensors
    from paddle_tpu.core.tensor import Tensor

    outs = tree_to_tensors(out_arrays)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    with autograd.trace_mode():
        lv = nn.MSELoss()(*outs, Tensor._from_op(labels))
    return jnp.mean(lv._array)


def _train(step, state, xs, ys, n, bs):
    params, buffers, opt_state = state
    losses = []
    for i in range(n):
        xa, ya = step.shard_batch(xs[i * bs:(i + 1) * bs], ys[i * bs:(i + 1) * bs])
        loss, params, buffers, opt_state = step(
            params, buffers, opt_state, jnp.asarray(1e-2, jnp.float32),
            rng.next_key(), xa, ya,
        )
        losses.append(float(np.asarray(loss)))
    return losses, (params, buffers, opt_state)


def test_resume_on_different_mesh_matches_trajectory(tmp_path):
    """Train ZeRO-sharded on mesh {dp:2, sharding:2, mp:2}; save; reload
    re-sharded onto {dp:4, mp:2}; the continued trajectory equals the
    uninterrupted one (same data, same steps)."""
    rs = np.random.RandomState(7)
    bs, steps = 8, 6
    xs = rs.rand(bs * steps, 8).astype(np.float32)
    ys = rs.rand(bs * steps, 8).astype(np.float32)

    def build(mesh, zero, seed=5):
        paddle.seed(seed)
        rng.seed(123)
        net = _MLP()
        net.fc1.weight.sharding_axes = (None, "mp")
        net.fc2.weight.sharding_axes = ("mp", None)
        opt = paddle.optimizer.Adam(learning_rate=1e-2, parameters=net.parameters())
        step = make_sharded_train_step(net, _loss_fn, opt, mesh,
                                       batch_specs=(P("dp"), P("dp")),
                                       zero_stage=zero)
        return net, opt, step

    # uninterrupted on mesh B for all steps (the target trajectory)
    mesh_b = _mesh(dp=4, mp=2)
    net_u, _, step_u = build(mesh_b, zero=0)
    ref_losses, _ = _train(step_u, step_u.init_state(), xs, ys, steps, bs)

    # phase 1: ZeRO-1 on mesh A {dp:2, sharding:2, mp:2} for half the steps
    mesh_a = _mesh(dp=2, sharding=2, mp=2)
    net_a, opt_a, step_a = build(mesh_a, zero=1)
    rng.seed(123)
    half = steps // 2
    losses_a, state_a = _train(step_a, step_a.init_state(), xs, ys, half, bs)
    np.testing.assert_allclose(losses_a, ref_losses[:half], rtol=1e-4, atol=1e-6)

    params_a, buffers_a, opt_state_a = state_a
    ckpt = str(tmp_path / "dist_ck")
    save_state({"params": params_a, "buffers": buffers_a, "opt": opt_state_a}, ckpt)

    # phase 2: fresh model on mesh B, re-sharded load, continue
    net_b, opt_b, step_b = build(mesh_b, zero=0, seed=9)  # different init
    state = load_state(ckpt)
    params_b, buffers_b, opt_b_state = step_b.init_state()
    # re-shard loaded values with mesh-B placements from init_state templates
    params_b = {k: jax.device_put(np.asarray(state["params"][k]), v.sharding)
                for k, v in params_b.items()}
    buffers_b = {k: jax.device_put(np.asarray(state["buffers"][k]), v.sharding)
                 for k, v in buffers_b.items()}
    opt_b_state = {
        k: {s: jax.device_put(np.asarray(state["opt"][k][s]), a.sharding)
            for s, a in slots.items()}
        for k, slots in opt_b_state.items()
    }
    losses_b, _ = _train(
        step_b, (params_b, buffers_b, opt_b_state),
        xs[half * bs:], ys[half * bs:], steps - half, bs,
    )
    np.testing.assert_allclose(losses_b, ref_losses[half:], rtol=1e-4, atol=1e-6)


def test_save_load_sharded_model_wrappers(tmp_path):
    paddle.seed(0)
    net = _MLP()
    opt = paddle.optimizer.Adam(parameters=net.parameters())
    # give the optimizer some state
    out = net(paddle.to_tensor(np.ones((4, 8), np.float32)))
    out.sum().backward()
    opt.step()
    ckpt = str(tmp_path / "model_ck")
    save_sharded_model(net, opt, ckpt)

    paddle.seed(3)
    net2 = _MLP()
    opt2 = paddle.optimizer.Adam(parameters=net2.parameters())
    load_sharded_model(net2, opt2, ckpt)
    for (k1, v1), (k2, v2) in zip(net.state_dict().items(), net2.state_dict().items()):
        np.testing.assert_array_equal(np.asarray(v1.numpy()), np.asarray(v2.numpy()))
    # optimizer slots restored
    sd2 = opt2.state_dict()
    assert any("moment1" in k for k in sd2)
