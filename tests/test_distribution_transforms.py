"""Distribution transforms + KL registry (reference distribution/transform.py
and kl.py register_kl)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


def _grid():
    return paddle.to_tensor(np.linspace(-2, 2, 9).astype(np.float32))


class TestTransforms:
    @pytest.mark.parametrize("t,domain", [
        (D.AffineTransform(1.0, 2.5), None),
        (D.ExpTransform(), None),
        (D.SigmoidTransform(), None),
        (D.TanhTransform(), None),
        (D.SoftplusTransform(), None),
        (D.PowerTransform(2.0), "pos"),
        (D.ChainTransform([D.AffineTransform(0.5, 1.5), D.ExpTransform()]), None),
    ])
    def test_inverse_roundtrip_and_jacobian(self, t, domain):
        x = _grid() if domain is None else paddle.to_tensor(
            np.linspace(0.2, 2.0, 9).astype(np.float32)
        )
        y = t.forward(x)
        back = t.inverse(y)
        np.testing.assert_allclose(back.numpy(), x.numpy(), rtol=1e-4, atol=1e-5)
        # numeric check of the log-det-jacobian: d forward / dx
        eps = 1e-3
        xp = paddle.to_tensor(x.numpy() + eps)
        xm = paddle.to_tensor(x.numpy() - eps)
        dydx = (t.forward(xp).numpy() - t.forward(xm).numpy()) / (2 * eps)
        np.testing.assert_allclose(
            t.forward_log_det_jacobian(x).numpy(),
            np.log(np.abs(dydx)),
            rtol=5e-3, atol=5e-3,
        )
        # inverse_log_det_jacobian = -forward at the preimage
        np.testing.assert_allclose(
            t.inverse_log_det_jacobian(y).numpy(),
            -t.forward_log_det_jacobian(x).numpy(),
            rtol=1e-4, atol=1e-5,
        )

    def test_reshape_and_independent(self):
        t = D.ReshapeTransform((4,), (2, 2))
        x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(2, 4))
        y = t.forward(x)
        assert y.shape == [2, 2, 2]
        np.testing.assert_array_equal(t.inverse(y).numpy(), x.numpy())

        it = D.IndependentTransform(D.ExpTransform(), 1)
        x2 = paddle.to_tensor(np.ones((3, 4), np.float32))
        ld = it.forward_log_det_jacobian(x2)
        assert ld.shape == [3]  # summed over the event dim
        np.testing.assert_allclose(ld.numpy(), 4.0)


class TestTransformedDistribution:
    def test_lognormal_via_exp_transform(self):
        """TransformedDistribution(Normal, Exp) must equal LogNormal."""
        paddle.seed(0)
        base = D.Normal(0.3, 0.8)
        td = D.TransformedDistribution(base, [D.ExpTransform()])
        ref = D.LogNormal(0.3, 0.8)
        v = paddle.to_tensor(np.array([0.5, 1.0, 2.5], np.float32))
        np.testing.assert_allclose(
            td.log_prob(v).numpy(), ref.log_prob(v).numpy(), rtol=1e-5
        )
        s = td.sample([1000])
        assert (s.numpy() > 0).all()

    def test_affine_of_normal_is_normal(self):
        base = D.Normal(0.0, 1.0)
        td = D.TransformedDistribution(base, [D.AffineTransform(2.0, 3.0)])
        ref = D.Normal(2.0, 3.0)
        v = paddle.to_tensor(np.array([-1.0, 2.0, 5.0], np.float32))
        np.testing.assert_allclose(
            td.log_prob(v).numpy(), ref.log_prob(v).numpy(), rtol=1e-5
        )


def _mc_kl(p, q, n=200_000):
    paddle.seed(42)
    x = p.sample([n])
    return float(np.mean(p.log_prob(x).numpy() - q.log_prob(x).numpy()))


class TestKLRegistry:
    @pytest.mark.parametrize("p,q", [
        (lambda: D.Normal(0.0, 1.0), lambda: D.Normal(1.0, 2.0)),
        (lambda: D.Exponential(2.0), lambda: D.Exponential(0.7)),
        (lambda: D.Laplace(0.0, 1.0), lambda: D.Laplace(0.5, 2.0)),
        (lambda: D.Gamma(2.0, 3.0), lambda: D.Gamma(3.0, 2.0)),
        (lambda: D.Beta(2.0, 3.0), lambda: D.Beta(4.0, 2.0)),
        (lambda: D.Dirichlet(np.array([1.0, 2.0, 3.0], np.float32)),
         lambda: D.Dirichlet(np.array([2.0, 2.0, 2.0], np.float32))),
        (lambda: D.LogNormal(0.0, 0.5), lambda: D.LogNormal(0.3, 0.8)),
    ])
    def test_closed_form_matches_monte_carlo(self, p, q):
        pd, qd = p(), q()
        kl = float(np.asarray(D.kl_divergence(pd, qd).numpy()))
        mc = _mc_kl(pd, qd)
        assert kl >= -1e-4
        assert abs(kl - mc) < max(0.05, 0.1 * abs(mc)), (kl, mc)

    def test_uniform_uniform(self):
        kl = D.kl_divergence(D.Uniform(0.0, 1.0), D.Uniform(0.0, 2.0))
        np.testing.assert_allclose(float(kl.numpy()), np.log(2.0), rtol=1e-6)
        kl_inf = D.kl_divergence(D.Uniform(0.0, 3.0), D.Uniform(0.0, 2.0))
        assert np.isinf(float(kl_inf.numpy()))

    def test_register_kl_custom_pair(self):
        class MyDist(D.Normal):
            pass

        @D.register_kl(MyDist, MyDist)
        def _kl_my(p, q):
            return paddle.to_tensor(np.float32(7.0))

        # most specific rule wins over the Normal/Normal rule
        assert float(D.kl_divergence(MyDist(0.0, 1.0), MyDist(0.0, 1.0)).numpy()) == 7.0
        # subclass falls back to the base rule against a plain Normal
        v = D.kl_divergence(MyDist(0.0, 1.0), D.Normal(1.0, 1.0))
        np.testing.assert_allclose(float(v.numpy()), 0.5, rtol=1e-5)

    def test_unregistered_pair_raises(self):
        with pytest.raises(NotImplementedError, match="register_kl"):
            D.kl_divergence(D.Normal(0.0, 1.0), D.Gamma(1.0, 1.0))
