// Native data-feed core: index shuffling + bounded batch ring buffer +
// multi-threaded collate.
//
// Reference parity: the C++ BufferedReader double-buffer prefetch
// (paddle/fluid/operators/reader/buffered_reader.h:48) and the DataFeed
// batch assembly (paddle/fluid/framework/data_feed.cc) in /root/reference.
// TPU adaptation: the device side of prefetch is jax.device_put in Python;
// this module supplies the host-side hot loops — epoch shuffling, bounded
// producer/consumer queue, and parallel memcpy collate of fixed-size
// samples into a batch buffer — through a C ABI for ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <thread>
#include <vector>

namespace {

struct RingBuffer {
  std::deque<std::vector<uint8_t>> slots;
  size_t capacity;
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::atomic<bool> closed{false};
};

struct CollatePool {
  int n_threads;
};

}  // namespace

extern "C" {

// ---- shuffling ------------------------------------------------------------

void df_shuffle_indices(int64_t* indices, int64_t n, uint64_t seed) {
  std::mt19937_64 rng(seed);
  for (int64_t i = n - 1; i > 0; --i) {
    uint64_t j = rng() % static_cast<uint64_t>(i + 1);
    std::swap(indices[i], indices[j]);
  }
}

void df_iota(int64_t* indices, int64_t n) {
  for (int64_t i = 0; i < n; ++i) indices[i] = i;
}

// ---- bounded batch queue --------------------------------------------------

void* df_queue_new(int64_t capacity) {
  auto* rb = new RingBuffer();
  rb->capacity = static_cast<size_t>(capacity);
  return rb;
}

// Returns 0 on success, -1 if closed.
int df_queue_push(void* h, const uint8_t* data, int64_t nbytes) {
  auto* rb = static_cast<RingBuffer*>(h);
  std::unique_lock<std::mutex> lk(rb->mu);
  rb->cv_push.wait(lk, [&] { return rb->closed.load() || rb->slots.size() < rb->capacity; });
  if (rb->closed.load()) return -1;
  rb->slots.emplace_back(data, data + nbytes);
  rb->cv_pop.notify_one();
  return 0;
}

// Returns bytes written, 0 if queue closed+drained, -2 if cap too small.
int64_t df_queue_pop(void* h, uint8_t* out, int64_t cap) {
  auto* rb = static_cast<RingBuffer*>(h);
  std::unique_lock<std::mutex> lk(rb->mu);
  rb->cv_pop.wait(lk, [&] { return rb->closed.load() || !rb->slots.empty(); });
  if (rb->slots.empty()) return 0;
  auto& front = rb->slots.front();
  if (static_cast<int64_t>(front.size()) > cap) return -2;
  std::memcpy(out, front.data(), front.size());
  int64_t n = static_cast<int64_t>(front.size());
  rb->slots.pop_front();
  rb->cv_push.notify_one();
  return n;
}

int64_t df_queue_size(void* h) {
  auto* rb = static_cast<RingBuffer*>(h);
  std::lock_guard<std::mutex> lk(rb->mu);
  return static_cast<int64_t>(rb->slots.size());
}

void df_queue_close(void* h) {
  auto* rb = static_cast<RingBuffer*>(h);
  rb->closed.store(true);
  rb->cv_push.notify_all();
  rb->cv_pop.notify_all();
}

void df_queue_free(void* h) { delete static_cast<RingBuffer*>(h); }

// ---- parallel collate -----------------------------------------------------

// Gathers `n` samples of `sample_bytes` each from `base + idx*sample_bytes`
// into `dst`, using up to `n_threads` threads. The memcpy-bound inner loop
// of batch assembly.
void df_gather_collate(uint8_t* dst, const uint8_t* base, const int64_t* idx,
                       int64_t n, int64_t sample_bytes, int n_threads) {
  if (n_threads < 1) n_threads = 1;
  if (n_threads == 1 || n < n_threads * 4) {
    for (int64_t i = 0; i < n; ++i)
      std::memcpy(dst + i * sample_bytes, base + idx[i] * sample_bytes,
                  static_cast<size_t>(sample_bytes));
    return;
  }
  std::vector<std::thread> workers;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; ++t) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    workers.emplace_back([=] {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(dst + i * sample_bytes, base + idx[i] * sample_bytes,
                    static_cast<size_t>(sample_bytes));
    });
  }
  for (auto& w : workers) w.join();
}

// ---- normalize + cast fused (uint8 HWC -> float CHW) ----------------------

void df_u8_to_f32_normalize(float* dst, const uint8_t* src, int64_t n,
                            float scale, float shift) {
  for (int64_t i = 0; i < n; ++i) dst[i] = src[i] * scale + shift;
}

}  // extern "C"
